"""Pod-scale streaming: gang-sharded ingest, merged drift, psum learner.

Three pieces compose the single-device out-of-core stack (ingest.py,
learner.py, drift.py) into the parallel-and-stream regime of ROADMAP
item 3 — a dataset no single device could hold trains continuously
across an elastic gang:

  * `ShardedRowBlockStore` partitions pushed row blocks round-robin
    across shards, the placement pinned at push (`push_index % shards`).
    The caller's `LGBM_DatasetPushRows*` surface is unchanged — sharding
    is internal placement, not an API. Bin mappers are fitted from exact
    per-shard quantile sketches merged across ranks in RANK order
    (drift.merge_ranked) after one small allgather, so the cut points
    reflect the GLOBAL prefix distribution bit-identically no matter
    which shard saw which rows: the merged multiset is reconstructed
    into a surrogate prefix (sorted values scattered back to the true
    nonzero-row positions) and fed through the SAME Dataset._fit_layout
    a one-shot build runs, reproducing mappers AND the EFB group lists
    byte-for-byte whenever the sketches stay exact (k covers the prefix,
    the default here) and bin_sample_rows <= bin_construct_sample_cnt.
  * `PodDriftMonitor` fans DriftMonitor out per shard and merges the
    shard sketches + bin-occupancy windows across ranks at every drift
    check (both are mergeable by construction), so alarm decisions and
    the generation-fenced bin refresh are byte-identical across the
    gang. `reshard()` keeps retired shards' accumulations — only the
    MERGED state is observable, so shrink-to-fit resume stays exact.
  * `ShardedStreamedTreeLearner` shards the device block cache across
    the gang (`block % shards`), giving the fleet D x the single-device
    LGBM_TPU_HBM_BUDGET of resident bins, and merges quantized per-leaf
    histograms with the same psum-over-"data" reduction the resident
    data-parallel learner uses — int32 accumulation makes the merge
    exact under any summation order, so training is bit-identical to the
    single-device streamed learner at matched data order. Float (plain /
    bagged) histograms keep the parent's canonical chunk-order fold
    unchanged: a float psum would reassociate partial sums, and the
    sharding only moves block PLACEMENT, never the numeric sequence.
"""
from __future__ import annotations

import io as _io
from time import perf_counter
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..io.dataset import Dataset as CoreDataset
from ..parallel.mesh import data_mesh
from ..utils.compat import shard_map
from ..utils.log import Log
from ..utils.timer import global_timer
from .. import telemetry
from .drift import DriftMonitor, QuantileSketch, merge_ranked
from .ingest import RowBlockStore
from .learner import StreamedTreeLearner, _BlockCache


# --------------------------------------------------------- gang transport

def _gang_world() -> int:
    try:
        return int(jax.process_count())
    except Exception:  # noqa: BLE001 - backend not initialized yet
        return 1


def _allgather_bytes(payload: bytes) -> List[bytes]:
    """Gather one opaque byte payload from every process, in rank order.

    Single-process returns [payload] without touching the backend. The
    multi-process path pads every rank's payload to the gathered max
    length (allgather needs equal shapes) and prefixes the true length.
    """
    world = _gang_world()
    if world <= 1:
        return [payload]
    from jax.experimental import multihost_utils

    # graftlint: disable=collective-order -- process_count() is uniform across the gang: every rank takes the same arm together, and both allgathers below run unconditionally on that arm in the same order
    length = np.array([len(payload)], dtype=np.int64)
    lengths = np.asarray(multihost_utils.process_allgather(length)).reshape(-1)
    max_len = int(lengths.max())
    buf = np.zeros(max_len, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = np.asarray(multihost_utils.process_allgather(buf))
    gathered = gathered.reshape(world, max_len)
    return [gathered[r, : int(lengths[r])].tobytes() for r in range(world)]


def _pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    out = _io.BytesIO()
    np.savez(out, **arrays)
    return out.getvalue()


def _unpack_arrays(payload: bytes) -> Dict[str, np.ndarray]:
    with np.load(_io.BytesIO(payload), allow_pickle=False) as z:
        return {k: np.asarray(z[k]) for k in z.files}


def _sketch_to_arrays(sk: QuantileSketch, prefix: str,
                      arrays: Dict[str, np.ndarray]) -> None:
    arrays[prefix + "meta"] = np.array(
        [sk.k, sk.nonzero_n, sk.zero_n, sk.nan_n, sk._parity, len(sk.levels)],
        dtype=np.int64)
    for i, lv in enumerate(sk.levels):
        arrays[f"{prefix}lv{i}"] = np.asarray(lv, dtype=np.float64)


def _sketch_from_arrays(prefix: str, arrays: Dict[str, np.ndarray]
                        ) -> Optional[QuantileSketch]:
    meta = arrays.get(prefix + "meta")
    if meta is None:
        return None
    k, nonzero_n, zero_n, nan_n, parity, n_levels = (int(v) for v in meta)
    sk = QuantileSketch(k)
    sk.levels = [np.asarray(arrays[f"{prefix}lv{i}"], dtype=np.float64)
                 for i in range(n_levels)]
    sk.nonzero_n, sk.zero_n, sk.nan_n = nonzero_n, zero_n, nan_n
    sk._parity = parity
    return sk


# ------------------------------------------------------------- pod drift

class PodDriftMonitor(DriftMonitor):
    """DriftMonitor fanned out per shard with rank-ordered gang merges.

    Blocks route to per-shard child monitors in lockstep with the
    store's round-robin placement; the pod keeps the check cadence.
    At each check (and each refit) the shard sketches fold through
    drift.merge_ranked and the shard occupancy windows sum in rank
    order, so the merged state — and every alarm / refreshed cut point
    derived from it — is a pure function of the pushed stream,
    byte-identical across ranks and across reruns.
    """

    def __init__(self, proto: DriftMonitor, num_shards: int) -> None:
        super().__init__(proto.config, sorted(proto.categorical),
                         threshold=proto.threshold,
                         check_rows=proto.check_rows,
                         sketch_k=proto.sketch_k)
        self.num_shards = max(1, int(num_shards))
        # children never self-check: the pod owns the cadence
        self._children = [
            DriftMonitor(proto.config, sorted(proto.categorical),
                         threshold=proto.threshold, check_rows=2 ** 62,
                         sketch_k=proto.sketch_k)
            for _ in range(self.num_shards)]
        self._push_i = 0
        self._merged_dirty = True

    # ------------------------------------------------------------ routing

    def observe(self, block: np.ndarray, layout) -> None:
        child = self._children[self._push_i % self.num_shards]
        self._push_i += 1
        child.observe(block, layout)
        self._merged_dirty = True
        if layout is not None:
            self._layout = layout
            self._rows_since_check += block.shape[0]
            if self._rows_since_check >= self.check_rows:
                self._merge_shards()
                self._check()

    def set_reference(self, layout, prefix: np.ndarray) -> None:
        super().set_reference(layout, prefix)
        for child in self._children:
            # the (global) ref content is inert in children — their
            # _check never runs — but its keys define which features the
            # child's _cur occupancy window accumulates
            child.set_reference(layout, prefix)

    def after_refresh(self, layout) -> None:
        self._merge_shards()
        super().after_refresh(layout)
        for child in self._children:
            child.after_refresh(layout)

    def refit_mapper(self, j: int, mapper):
        self._merge_shards()
        nm = super().refit_mapper(j, mapper)
        if j < len(self.sketches) and self.sketches[j] is not None \
                and self.sketches[j].nonzero_n == 0:
            # super() discarded a corrupt merged sketch; drop the shard
            # copies too or the garbage re-merges at the next check
            for child in self._children:
                if j < len(child.sketches) and child.sketches[j] is not None \
                        and not child.sketches[j].healthy():
                    child.sketches[j] = QuantileSketch(self.sketch_k)
        return nm

    def reshard(self, num_shards: int) -> None:
        """Shrink-to-fit: future blocks route over the surviving shard
        count; retired children keep their accumulations (only the
        rank-ordered MERGE is observable, so history stays exact)."""
        self.num_shards = max(1, int(num_shards))
        while len(self._children) < self.num_shards:
            ref = self._children[0]
            self._children.append(
                DriftMonitor(ref.config, sorted(ref.categorical),
                             threshold=ref.threshold, check_rows=2 ** 62,
                             sketch_k=ref.sketch_k))
        self._merged_dirty = True

    # -------------------------------------------------------------- merge

    def _shard_payload(self, rank: int) -> bytes:
        child = self._children[rank]
        arrays: Dict[str, np.ndarray] = {"rank": np.array([rank])}
        for j, sk in enumerate(child.sketches):
            if sk is not None:
                _sketch_to_arrays(sk, f"sk{j}_", arrays)
        for j, cur in child._cur.items():
            arrays[f"cur{j}"] = np.asarray(cur, dtype=np.float64)
        return _pack_arrays(arrays)

    def _merge_shards(self) -> None:
        """Fold the shard sketches and occupancy windows into the pod's
        own state, in rank order. Multi-process, rank r is authoritative
        for shard r and one allgather rebuilds the full set everywhere;
        single-process the 'gather' is a local walk over the children."""
        if not self._merged_dirty:
            return
        world = _gang_world()
        t0 = perf_counter()
        if world > 1:
            my = int(jax.process_index())
            payloads = _allgather_bytes(
                self._shard_payload(my % self.num_shards))
        else:
            payloads = [self._shard_payload(r)
                        for r in range(self.num_shards)]
        shards = [_unpack_arrays(p) for p in payloads]
        n_feat = max((len(c.sketches) for c in self._children), default=0)
        merged: List[Optional[QuantileSketch]] = []
        for j in range(n_feat):
            pairs = []
            for arrays in shards:
                sk = _sketch_from_arrays(f"sk{j}_", arrays)
                if sk is not None:
                    pairs.append((int(arrays["rank"][0]), sk))
            merged.append(merge_ranked(pairs) if pairs else None)
        self.sketches = merged
        for j in list(self._cur):
            acc = np.zeros_like(self._cur[j])
            for arrays in shards:  # rank order: payloads land rank-sorted
                cur = arrays.get(f"cur{j}")
                if cur is not None:
                    acc += cur
            self._cur[j] = acc
        self._merged_dirty = False
        global_timer.set_count("stream_sketch_merge_us",
                               int((perf_counter() - t0) * 1e6))
        global_timer.add_count("stream_sketch_merges", 1)


# ---------------------------------------------------------- sharded store

class ShardedRowBlockStore(RowBlockStore):
    """RowBlockStore with round-robin block placement across a gang.

    The push surface (and therefore LGBM_DatasetPushRows* C-API parity)
    is byte-identical to the base store: every block is binned into the
    same global plane in push order, so finalize() snapshots are
    indistinguishable from the single-shard build. What sharding adds:

      * placement pinned at push (`push_index % num_shards`) with
        per-shard row watermarks (`shard_rows`),
      * a bin-layout fit from rank-merged exact sketches instead of the
        raw prefix (see module docstring for the equality argument),
      * the PodDriftMonitor gang merge for drift + bin refresh,
      * `reshard()` for shrink-to-fit resume after a lost worker.
    """

    def __init__(self, *args, num_shards: Optional[int] = None,
                 **kwargs) -> None:
        super().__init__(*args, **kwargs)
        self._num_shards_req = num_shards
        self._num_shards: Optional[int] = None
        self._block_owner: List[int] = []
        self._block_nrows: List[int] = []
        if self._drift is not None:
            self._drift = PodDriftMonitor(self._drift, self.num_shards)

    @property
    def num_shards(self) -> int:
        if self._num_shards is None:
            if self._num_shards_req is not None:
                self._num_shards = max(1, int(self._num_shards_req))
            elif _gang_world() > 1:
                self._num_shards = _gang_world()
            else:
                self._num_shards = int(
                    data_mesh(self.config.num_machines).devices.size)
        return self._num_shards

    # ------------------------------------------------------------- push

    def push_rows(self, data, label=None, weight=None):
        block_rows = (np.asarray(data).shape[0]
                      if np.asarray(data).ndim == 2 else 1)
        with self._lock:
            self._block_owner.append(len(self._block_owner)
                                     % self.num_shards)
            self._block_nrows.append(int(block_rows))
        return super().push_rows(data, label=label, weight=weight)

    def shard_rows(self, rank: int) -> int:
        """Per-shard row watermark: rows pushed into shard `rank` so far
        (same monotone semantics the continuous trainer pins globally)."""
        with self._lock:
            return sum(n for o, n in zip(self._block_owner,
                                         self._block_nrows) if o == rank)

    def reshard(self, num_shards: int) -> None:
        """Re-shard after the gang shrank: surviving ranks re-take the
        pinned placements round-robin over the new world. The plane and
        merged drift state are placement-independent, so a resumed refit
        stays byte-identical."""
        with self._lock:
            self._num_shards = max(1, int(num_shards))
            self._num_shards_req = self._num_shards
            self._block_owner = [i % self._num_shards
                                 for i in range(len(self._block_owner))]
            if isinstance(self._drift, PodDriftMonitor):
                self._drift.reshard(self._num_shards)
        Log.info("streaming: re-sharded block store over %d shards",
                 self._num_shards)

    # -------------------------------------------------------------- fit

    def _fit_and_drain(self) -> None:
        """Sketch-merged global layout fit. Called under self._lock.

        Each shard folds its owned prefix blocks into one exact sketch
        per feature (k = 2 * bin_sample_rows: level 0 never compacts, so
        the sketch IS the multiset) plus the nonzero-position mask; one
        allgather + rank-ordered merge rebuilds the global multiset, and
        a surrogate prefix (sorted values scattered to the true mask
        positions) flows through the stock Dataset._fit_layout — cut
        points AND EFB bundles match the one-shot fit byte-for-byte,
        independent of which shard saw which rows.
        """
        n_prefix = min(self.bin_sample_rows,
                       sum(b.shape[0] for b in self._raw_blocks))
        f = int(self.n_features)
        world = _gang_world()
        shard_ranks = ([int(jax.process_index()) % self.num_shards]
                       if world > 1 else list(range(self.num_shards)))
        with global_timer.scope("stream_fit_layout"):
            local = {r: self._shard_fit_payload(r, n_prefix, f)
                     for r in shard_ranks}
            if world > 1:
                payloads = _allgather_bytes(local[shard_ranks[0]])
            else:
                payloads = [local[r] for r in range(self.num_shards)]
            t0 = perf_counter()
            surrogate = self._merge_fit_payloads(payloads, n_prefix, f)
            global_timer.set_count("stream_sketch_merge_us",
                                   int((perf_counter() - t0) * 1e6))
            global_timer.add_count("stream_sketch_merges", 1)
            layout = CoreDataset(self.config)
            group_lists = layout._fit_layout(surrogate,
                                             self.categorical_feature)
            layout._make_groups(group_lists)
        self._layout = layout
        self._group_lists = group_lists
        if self._drift is not None:
            # surrogate carries the identical per-feature marginals, so
            # the occupancy baseline matches the raw-prefix reference
            self._drift.set_reference(layout, surrogate)
        for blk in self._raw_blocks:
            self._bin_blocks.append(
                np.ascontiguousarray(layout._bin_rows(blk)))
        self._raw_blocks = []
        self._buffered = 0
        if telemetry.enabled():
            telemetry.emit("stream_layout_fitted",
                           sample_rows=int(n_prefix),
                           num_groups=len(layout.groups),
                           num_shards=self.num_shards)

    def _shard_fit_payload(self, rank: int, n_prefix: int, f: int) -> bytes:
        """Pack shard `rank`'s view of the prefix: exact per-feature
        sketches over its owned rows plus the (nonzero|NaN) mask and the
        global row offsets those rows came from."""
        k_exact = max(8, 2 * n_prefix)
        sketches = [QuantileSketch(k_exact) for _ in range(f)]
        seg_starts: List[int] = []
        seg_lens: List[int] = []
        masks: List[np.ndarray] = []
        row0 = 0
        for i, blk in enumerate(self._raw_blocks):
            take = min(blk.shape[0], n_prefix - row0)
            if take > 0 and self._block_owner[i] == rank:
                part = blk[:take]
                for j in range(f):
                    sketches[j].update(part[:, j])
                masks.append((part != 0) | np.isnan(part))
                seg_starts.append(row0)
                seg_lens.append(take)
            row0 += blk.shape[0]
            if row0 >= n_prefix:
                break
        arrays: Dict[str, np.ndarray] = {
            "rank": np.array([rank]),
            "seg_starts": np.asarray(seg_starts, dtype=np.int64),
            "seg_lens": np.asarray(seg_lens, dtype=np.int64),
            "mask": (np.concatenate(masks, axis=0) if masks
                     else np.zeros((0, f), dtype=bool)),
        }
        for j in range(f):
            _sketch_to_arrays(sketches[j], f"sk{j}_", arrays)
        return _pack_arrays(arrays)

    @staticmethod
    def _merge_fit_payloads(payloads: List[bytes], n_prefix: int,
                            f: int) -> np.ndarray:
        """Rank-ordered merge of the gathered shard payloads into the
        surrogate prefix matrix Dataset._fit_layout consumes."""
        shards = sorted((_unpack_arrays(p) for p in payloads),
                        key=lambda a: int(a["rank"][0]))
        mask = np.zeros((n_prefix, f), dtype=bool)
        for arrays in shards:
            local0 = 0
            for start, length in zip(arrays["seg_starts"],
                                     arrays["seg_lens"]):
                mask[start:start + length] = \
                    arrays["mask"][local0:local0 + length]
                local0 += length
        surrogate = np.zeros((n_prefix, f), dtype=np.float64)
        for j in range(f):
            sk = merge_ranked([(int(a["rank"][0]),
                                _sketch_from_arrays(f"sk{j}_", a))
                               for a in shards
                               if a.get(f"sk{j}_meta") is not None])
            pos = np.flatnonzero(mask[:, j])
            vals, wts = sk.weighted()
            expanded = np.sort(np.repeat(vals, wts.astype(np.int64)))
            if len(expanded) != sk.nonzero_n:
                # compacted sketch (prefix outgrew k): rank-uniform
                # resample — approximate, like the reference's sampled fit
                expanded = np.sort(sk.quantile_sample(sk.nonzero_n))
            n_fill = min(len(expanded), len(pos))
            surrogate[pos[:n_fill], j] = expanded[:n_fill]
            if len(pos) > n_fill:  # remaining masked rows were NaN
                surrogate[pos[n_fill:], j] = np.nan
        return surrogate


# --------------------------------------------------------- sharded cache

class _ShardedBlockCache:
    """_BlockCache surface routed over per-rank sub-caches.

    Block b lives on rank `b % num_shards`; every rank's cache gets the
    full per-device LGBM_TPU_HBM_BUDGET, so the gang holds num_shards x
    the single-device resident working set — the 'dataset no single
    device could hold' leg. Values are untouched (the sub-caches slice
    the same plane), so every consumer of get()/prefetch() sees the
    exact arrays the single cache would serve.
    """

    def __init__(self, plane: np.ndarray, block_rows: int, capacity: int,
                 upload_dtype, num_shards: int) -> None:
        self.plane = plane
        self.block_rows = int(block_rows)
        self.num_rows = int(plane.shape[1])
        self.n_blocks = max(1, -(-self.num_rows // self.block_rows))
        self.num_shards = max(1, int(num_shards))
        self.capacity = max(1, int(capacity)) * self.num_shards
        self.upload_dtype = upload_dtype
        self._shards = [
            _BlockCache(plane, block_rows, capacity, upload_dtype)
            for _ in range(self.num_shards)]

    def owner(self, b: int) -> int:
        return int(b) % self.num_shards

    def block_range(self, b: int):
        lo = b * self.block_rows
        return lo, min(self.num_rows, lo + self.block_rows)

    def prefetch(self, b: int) -> None:
        self._shards[self.owner(b)].prefetch(b)

    def get(self, b: int):
        return self._shards[self.owner(b)].get(b)

    @property
    def upload_s(self) -> float:
        return sum(s.upload_s for s in self._shards)


# -------------------------------------------------------- sharded learner

class ShardedStreamedTreeLearner(StreamedTreeLearner):
    """StreamedTreeLearner whose block cache and quantized histogram
    reduction span the data mesh.

    Float (plain / bagged) training inherits the parent's canonical
    chunk-order fold untouched — sharding moves block placement and
    caching, never the floating-point summation sequence — so those
    paths are trivially bit-identical to the single-device streamed
    learner for ANY shard count, including after a shrink. Quantized
    training computes one per-rank partial histogram over each rank's
    owned blocks and merges them with the same psum-over-"data" the
    resident data-parallel learner uses: int32 accumulation is exact
    under any order, so the merged histogram equals the canonical fold
    bit-for-bit (the test_sharded_device.py precedent). The per-wave
    wire cost is one [G, B, 3] int32 histogram per rank — independent
    of N — recorded as stream_ici_bytes_per_wave.
    """

    def __init__(self, config, dataset, budget_bytes=None,
                 block_rows=None) -> None:
        self.mesh = data_mesh(config.num_machines)
        self.num_shards = int(self.mesh.devices.size)
        self._psum_hist = None
        super().__init__(config, dataset, budget_bytes=budget_bytes,
                         block_rows=block_rows)

    def _device_bins(self, dataset) -> None:
        super()._device_bins(dataset)
        base = self._cache
        if self.num_shards > 1:
            self._cache = _ShardedBlockCache(
                base.plane, base.block_rows, base.capacity,
                base.upload_dtype, self.num_shards)
            global_timer.set_count(
                "stream_resident_blocks",
                min(self._cache.capacity, self._cache.n_blocks))
        global_timer.set_count("stream_shards", self.num_shards)
        return None

    def _make_psum_hist(self):
        if self._psum_hist is None:
            from jax.sharding import PartitionSpec as P

            self._psum_hist = jax.jit(shard_map(
                lambda h: jax.lax.psum(h[0], "data"),
                mesh=self.mesh, in_specs=P("data"), out_specs=P(),
                check_vma=False))
        return self._psum_hist

    def _leaf_hist(self, leaf: int):
        if not (self.quantized and self.num_shards > 1) \
                or _gang_world() > 1:
            # float paths keep the parent's canonical fold (a float psum
            # would reassociate partial sums); a multi-process gang also
            # folds canonically — its local [D, ...] partial stack is not
            # globally addressable, and the canonical order is already
            # the bit-identity baseline
            return super()._leaf_hist(leaf)
        idx = np.asarray(self.partition.indices(leaf))
        vi = idx[idx < self.num_data].astype(np.int64)
        mode = self._ragged_mode()
        num_bins = self.group_bin_padded
        G = len(self.dataset.groups)
        owner = (vi // self._cache.block_rows) % self.num_shards
        zeros = jnp.zeros((G, num_bins, 3), dtype=jnp.int32)
        parts = []
        for r in range(self.num_shards):
            sub = vi[owner == r]
            if sub.size == 0:
                parts.append(zeros)
            elif mode is not None:
                parts.append(self._ragged_over_indices(
                    sub, interpret=mode == "interpret"))
            else:
                parts.append(self._hist_over_indices(sub))
        merged = self._make_psum_hist()(jnp.stack(parts))
        global_timer.set_count("stream_ici_bytes_per_wave",
                               G * num_bins * 3 * 4)
        global_timer.set_count("device_ici_bytes_per_wave",
                               G * num_bins * 3 * 4)
        return merged
