"""Structured telemetry: event stream, Chrome-trace export, watchers.

The reference's observability story is a timer table printed at exit under
-DUSE_TIMETAG (include/LightGBM/utils/common.h:979-1063). A TPU-native stack
needs machine-readable, per-iteration data because XLA adds failure modes the
reference never had — shape-driven recompile churn, HBM high-water blowups,
host<->device sync stalls — and "bench before/after" needs more than one
end-of-run text dump. This module is the event bus:

  * In-process aggregator — always on while a session is active: every event
    type counted, every `global_timer.scope` span captured via `span_hook`.
  * JSONL file sink — one self-describing object per line in
    `<dir>/events.jsonl`, written with checkpoint.py's atomic
    temp+fsync+os.replace writer so a crash never leaves a torn file.
  * Chrome trace-event exporter — `<dir>/trace.json` loadable in Perfetto /
    chrome://tracing: B/E span pairs on per-phase tracks (one tid per timer
    label), "C" counter tracks for per-device HBM samples.

Two watchers with no reference counterpart:

  * Recompile watcher — a logging.Handler on jax's pxla logger (enabled via
    `jax_log_compiles`) counting jit cache misses per (function, input
    shapes); warns once per function past a churn threshold. The hook is
    logging-only: it cannot change compilation or numerics.
  * HBM gauge — samples `device.memory_stats()` per device, tracks the
    high-water mark, publishes `hbm_high_water_bytes` and per-device "C"
    trace counter events. Degrades to a no-op where the backend reports no
    memory stats (CPU).

Enable with the `telemetry_dir` param, $LGBM_TPU_TELEMETRY, or the CLI;
`start(None)` runs an aggregate-only session (no files — bench.py uses this
to read compile/HBM figures without touching disk). Emission is a single
module-global None-check when no session is active, so the disabled path
costs <1% (asserted by tests/test_telemetry.py) and changes no model output.
Hot-path call sites must guard `emit()` behind `telemetry.enabled()` —
enforced by graftlint R9 (telemetry-hygiene).

Offline analysis: tools/teldiff.py summarizes one run or diffs two.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import time
from collections import Counter
from typing import Any, Dict, Iterator, List, Optional, Tuple

from .utils.log import Log
from .utils.timer import global_timer

ENV_VAR = "LGBM_TPU_TELEMETRY"
EVENTS_FILE = "events.jsonl"
TRACE_FILE = "trace.json"
# rewrite the JSONL sink every this-many events (plus once at close); the
# whole-file atomic rewrite keeps the on-disk stream crash-consistent
FLUSH_EVERY = 256
# warn when one jitted function compiles this many times in a session (low
# enough to catch per-iteration churn, high enough to pass over the normal
# warm-up of generic helpers like convert_element_type)
RECOMPILE_WARN_THRESHOLD = 8
_PXLA_LOGGER = "jax._src.interpreters.pxla"

_session: Optional["TelemetrySession"] = None

# --- kernel-compile classification -----------------------------------------
# Pallas/Mosaic kernel wrappers register their jitted entry names here at
# import; the recompile watcher splits their cache misses into the separate
# `kernel_compiles` counter so kernel-flag experiments (LGBM_TPU_GH_BF16,
# LGBM_TPU_COMPACT_ALIAS change kernel signatures, hence kernel compiles)
# show their compile cost apart from ordinary XLA jit churn. The substring
# markers back up the registry for names we never saw registered.
_KERNEL_FN_MARKERS = ("pallas", "mosaic")
_kernel_fns: set = set()


def register_kernel_fn(name: str) -> None:
    """Mark a jitted entry point as a Pallas/Mosaic kernel wrapper (called
    at import time by ops/hist_pallas.py and friends)."""
    _kernel_fns.add(str(name))


def is_kernel_fn(fn: str) -> bool:
    if fn in _kernel_fns:
        return True
    low = fn.lower()
    return any(m in low for m in _KERNEL_FN_MARKERS)


def enabled() -> bool:
    """True while a session is recording. Hot paths MUST check this before
    building event payloads (graftlint R9)."""
    return _session is not None


def session() -> Optional["TelemetrySession"]:
    return _session


def emit(ev: str, **fields: Any) -> None:
    """Record one structured event; single None-check no-op when disabled."""
    s = _session
    if s is not None:
        s.emit(ev, **fields)


def sample_hbm() -> int:
    """Sample per-device memory stats into the active session (no-op when
    disabled or when the backend reports none). Returns the high-water."""
    s = _session
    return s.hbm.sample() if s is not None else 0


def signals() -> Dict[str, int]:
    """Cheap watcher snapshot for adaptive consumers — the serving circuit
    breaker polls this between batches to detect compile churn and HBM
    pressure without owning the watchers. Ints read from the active
    session (zeros when no session is recording): total jit cache misses
    seen by the recompile watcher, the Pallas/Mosaic-kernel subset of
    those, and the per-device HBM high-water. exposition.py renders the
    same snapshot as Prometheus text."""
    s = _session
    if s is None:
        return {"compiles": 0, "kernel_compiles": 0,
                "hbm_high_water_bytes": 0}
    return s.signal_snapshot()


def resolve_dir(params: Optional[Dict[str, Any]]) -> str:
    """Output dir from the `telemetry_dir` param, else $LGBM_TPU_TELEMETRY."""
    return str((params or {}).get("telemetry_dir") or ""
               ) or os.environ.get(ENV_VAR, "")


def start(out_dir: Optional[str], **kwargs: Any) -> "TelemetrySession":
    """Begin a session. `out_dir=None` -> aggregate-only (no files). At most
    one session is active per process; a second start() keeps the first."""
    global _session
    if _session is not None:
        Log.warning("Telemetry session already active; keeping it")
        return _session
    _session = TelemetrySession(out_dir, **kwargs)
    return _session


def stop() -> Optional[Dict[str, Any]]:
    """Close the active session (flush sinks, restore hooks); returns its
    summary dict, or None if no session was active."""
    global _session
    s, _session = _session, None
    return s.close() if s is not None else None


@contextlib.contextmanager
def capture(out_dir: Optional[str], **kwargs: Any
            ) -> Iterator["TelemetrySession"]:
    """Session as a context manager (closes even when the body raises)."""
    s = start(out_dir, **kwargs)
    try:
        yield s
    finally:
        if _session is s:
            stop()


class _RecompileWatcher(logging.Handler):
    """Counts jit cache misses per (function, input shapes) by listening to
    jax's `jax_log_compiles` log line; warns once per function on churn.

    The pxla logger emits "Compiling <fn> with global shapes and types
    [...]. Argument mapping: ..." per cache miss — the only public hook that
    carries function identity (jax._src.monitoring events do not)."""

    def __init__(self, sess: "TelemetrySession") -> None:
        super().__init__(level=logging.DEBUG)
        self._sess = sess
        self.per_key: Counter = Counter()  # (fn, shapes) -> compiles
        self.per_fn: Counter = Counter()
        self.kernel_total = 0  # Pallas/Mosaic subset of the per_fn total
        self._warned: set = set()
        self._logger = logging.getLogger(_PXLA_LOGGER)
        self._dispatch_logger = logging.getLogger("jax._src.dispatch")
        self._prev_flag: Optional[bool] = None
        self._prev_propagate = True
        self._prev_dispatch_level = logging.NOTSET

    def install(self) -> None:
        try:
            import jax
            self._prev_flag = bool(jax.config.jax_log_compiles)
            jax.config.update("jax_log_compiles", True)
        except Exception:  # pragma: no cover - jax unavailable/changed
            self._prev_flag = None
        # the flag makes jax log compile chatter at WARNING; keep it out of
        # the user's stderr (handlers on the logger itself still fire with
        # propagate off) — both settings restored at uninstall
        self._prev_propagate = self._logger.propagate
        self._logger.propagate = False
        self._prev_dispatch_level = self._dispatch_logger.level
        self._dispatch_logger.setLevel(logging.ERROR)
        self._logger.addHandler(self)

    def uninstall(self) -> None:
        self._logger.removeHandler(self)
        self._logger.propagate = self._prev_propagate
        self._dispatch_logger.setLevel(self._prev_dispatch_level)
        if self._prev_flag is not None:
            try:
                import jax
                jax.config.update("jax_log_compiles", self._prev_flag)
            except Exception:  # pragma: no cover
                pass

    def emit(self, record: logging.LogRecord) -> None:  # logging.Handler API
        try:
            msg = record.getMessage()
        except Exception:  # pragma: no cover - malformed record
            return
        if not msg.startswith("Compiling "):
            return
        head, _, rest = msg[len("Compiling "):].partition(
            " with global shapes and types ")
        fn = head.strip() or "<unknown>"
        shapes = rest.split(". Argument mapping", 1)[0].strip()
        self.per_key[(fn, shapes)] += 1
        self.per_fn[fn] += 1
        global_timer.add_count("jit_compiles", 1)
        kernel = is_kernel_fn(fn)
        if kernel:
            self.kernel_total += 1
            global_timer.add_count("kernel_compiles", 1)
        self._sess.emit("compile", fn=fn, shapes=shapes[:400],
                        n_for_fn=self.per_fn[fn], kernel=kernel)
        if (self.per_fn[fn] >= self._sess.recompile_warn
                and fn not in self._warned):
            self._warned.add(fn)
            n_shapes = sum(1 for k in self.per_key if k[0] == fn)
            Log.warning(
                "Recompile churn: %r compiled %d times (%d distinct input "
                "shapes) — shape-unstable inputs defeat the jit cache; pad "
                "to stable buckets", fn, self.per_fn[fn], n_shapes)

    @property
    def total(self) -> int:
        return int(sum(self.per_fn.values()))


class _HbmGauge:
    """Per-device memory high-water from `device.memory_stats()`.

    `devices` is injectable for tests (fakes with a memory_stats() method);
    defaults to jax.local_devices(). Backends without stats (CPU) -> 0."""

    def __init__(self, sess: "TelemetrySession", devices=None) -> None:
        self._sess = sess
        self._devices = devices
        self.high_water: Dict[str, int] = {}

    def _device_list(self):
        if self._devices is not None:
            return self._devices
        try:
            import jax
            return jax.local_devices()
        except Exception:  # pragma: no cover - jax unavailable
            return []

    def sample(self) -> int:
        for d in self._device_list():
            stats_fn = getattr(d, "memory_stats", None)
            if stats_fn is None:
                continue
            try:
                stats = stats_fn()
            except Exception:  # backend without stats support
                stats = None
            if not stats:
                continue
            used = int(stats.get("peak_bytes_in_use",
                                 stats.get("bytes_in_use", 0)) or 0)
            name = str(d)
            if used > self.high_water.get(name, -1):
                self.high_water[name] = used
            self._sess.counter_sample(f"hbm:{name}", used)
        top = max(self.high_water.values(), default=0)
        if top:
            global_timer.set_count("hbm_high_water_bytes", top)
        return top


class TelemetrySession:
    """One recording window: event list + aggregate counts + timer spans,
    flushed to JSONL + Chrome trace at close when `out_dir` is set."""

    def __init__(self, out_dir: Optional[str] = None, label: str = "train",
                 flush_every: int = FLUSH_EVERY,
                 recompile_warn: int = RECOMPILE_WARN_THRESHOLD,
                 devices=None, watch_compiles: bool = True) -> None:
        self.out_dir = out_dir or None
        self.label = label
        self.flush_every = max(1, int(flush_every))
        self.recompile_warn = int(recompile_warn)
        self.t0 = time.perf_counter()
        self.events: List[Dict[str, Any]] = []
        self.aggregate: Counter = Counter()  # event type -> count
        self.spans: List[Tuple[str, float, float]] = []  # (label, t0, t1) rel
        self._counter_samples: List[Tuple[str, float, int]] = []
        self._counters0 = dict(global_timer.counters)
        self._closed = False
        self._summary: Dict[str, Any] = {}
        if self.out_dir:
            os.makedirs(self.out_dir, exist_ok=True)
        # force timer scopes on for the session (they feed the trace) and
        # chain any pre-existing hook; both restored at close
        self._prev_timer_enabled = global_timer.enabled
        self._prev_span_hook = global_timer.span_hook
        global_timer.enabled = True
        global_timer.span_hook = self._on_span
        self.hbm = _HbmGauge(self, devices)
        self.recompiles = _RecompileWatcher(self) if watch_compiles else None
        if self.recompiles is not None:
            self.recompiles.install()
        self.emit("session_start", label=label, wall_time=time.time(),
                  timer_epoch=global_timer.epoch, pid=os.getpid())

    def _now(self) -> float:
        return time.perf_counter() - self.t0

    def emit(self, ev: str, **fields: Any) -> None:
        rec: Dict[str, Any] = {"ev": ev, "t": round(self._now(), 6)}
        rec.update(fields)
        self.events.append(rec)
        self.aggregate[ev] += 1
        if self.out_dir and len(self.events) % self.flush_every == 0:
            self._flush_jsonl()

    def _on_span(self, label: str, start: float, end: float) -> None:
        self.spans.append((label, start - self.t0, end - self.t0))
        if self._prev_span_hook is not None:
            self._prev_span_hook(label, start, end)

    def add_span(self, label: str, start: float, end: float) -> None:
        """Record an externally-timed span (perf_counter seconds) — the
        tracing module feeds finished request/iteration stage spans here
        so the Chrome-trace export is one unified timeline. Clamped at
        the session start so a span opened pre-session can't produce a
        negative trace timestamp."""
        t0 = max(0.0, start - self.t0)
        t1 = max(t0, end - self.t0)
        self.spans.append((label, t0, t1))

    def counter_sample(self, name: str, value: int) -> None:
        """Timestamped gauge sample (becomes a "C" counter trace track)."""
        self._counter_samples.append((name, self._now(), int(value)))

    def counter_deltas(self) -> Dict[str, int]:
        """Session-scoped view of global_timer counters: accumulators as
        the delta since session start (counters are process-cumulative —
        see timer.py), gauges at their absolute level."""
        out: Dict[str, int] = {}
        for k, v in global_timer.counters.items():
            if k in global_timer.gauges:
                out[k] = int(v)
            else:
                d = int(v) - int(self._counters0.get(k, 0))
                if d:
                    out[k] = d
        return out

    def signal_snapshot(self) -> Dict[str, int]:
        """This session's watcher figures (the signals() payload) — callable
        even after stop() has already detached the module global, so the
        close-time metrics.prom snapshot reports the session's real totals
        instead of the no-session zeros."""
        return {
            "compiles": (self.recompiles.total
                         if self.recompiles is not None else 0),
            "kernel_compiles": (self.recompiles.kernel_total
                                if self.recompiles is not None else 0),
            "hbm_high_water_bytes": max(self.hbm.high_water.values(),
                                        default=0),
        }

    def close(self) -> Dict[str, Any]:
        if self._closed:
            return self._summary
        self._closed = True
        self.hbm.sample()
        summary: Dict[str, Any] = {
            "label": self.label,
            "duration_s": round(self._now(), 6),
            "events": {k: int(v) for k, v in sorted(self.aggregate.items())},
            "n_spans": len(self.spans),
            "compile_count": (self.recompiles.total
                              if self.recompiles is not None else 0),
            "kernel_compile_count": (self.recompiles.kernel_total
                                     if self.recompiles is not None else 0),
            "hbm_high_water_bytes": max(self.hbm.high_water.values(),
                                        default=0),
            "timer_totals": {k: round(global_timer.totals[k], 6)
                             for k in sorted(global_timer.totals)},
            "timer_counts": {k: int(global_timer.counts[k])
                             for k in sorted(global_timer.counts)},
            "counters": dict(sorted(self.counter_deltas().items())),
        }
        self.emit("session_end", **summary)
        if self.recompiles is not None:
            self.recompiles.uninstall()
        global_timer.span_hook = self._prev_span_hook
        global_timer.enabled = self._prev_timer_enabled
        if self.out_dir:
            self._flush_jsonl()
            self._write_trace()
            Log.info("Telemetry written to %s (%d events, %d spans)",
                     self.out_dir, len(self.events), len(self.spans))
        self._summary = summary
        return summary

    # --- sinks -----------------------------------------------------------
    def _flush_jsonl(self) -> None:
        # lazy: checkpoint.py imports this module at top level for event
        # emission, so the reverse import must happen at call time
        from .checkpoint import atomic_write_text
        text = "".join(json.dumps(e, sort_keys=True, default=_jsonable) + "\n"
                       for e in self.events)
        atomic_write_text(os.path.join(self.out_dir, EVENTS_FILE), text)
        # same cadence: a Prometheus textfile snapshot of the live counter
        # namespace, so a node-exporter collector scrapes a running train
        # exactly like the serving /metrics endpoint (exposition.py)
        try:
            from .exposition import SNAPSHOT_FILE, write_snapshot
            write_snapshot(os.path.join(self.out_dir, SNAPSHOT_FILE),
                           signals=self.signal_snapshot())
        except Exception:  # a scrape failure must never kill a train
            pass

    def _write_trace(self) -> None:
        from .checkpoint import atomic_write_text
        trace = build_chrome_trace(self.spans, self._counter_samples,
                                   label=self.label)
        atomic_write_text(os.path.join(self.out_dir, TRACE_FILE),
                          json.dumps(trace, default=_jsonable))


def _jsonable(obj: Any) -> Any:
    """JSON fallback for numpy/jax scalars and arrays in event payloads."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if fn is not None:
            try:
                return fn()
            except Exception:
                pass
    return str(obj)


def build_chrome_trace(spans: List[Tuple[str, float, float]],
                       counter_samples: List[Tuple[str, float, int]],
                       label: str = "train") -> Dict[str, Any]:
    """Trace-event JSON: B/E pairs on one track (tid) per span label —
    labels never self-nest, so per-label tracks need no nesting bookkeeping
    — plus "C" counter events per gauge name. ts is µs from session start;
    the list is sorted ts-ascending with E-before-B at ties so Perfetto's
    importer never sees a child close after its parent."""
    labels = sorted({s[0] for s in spans})
    tid_of = {lbl: i + 1 for i, lbl in enumerate(labels)}
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": 0, "tid": 0,
        "args": {"name": f"lightgbm_tpu:{label}"},
    }]
    for lbl, tid in tid_of.items():
        events.append({"name": "thread_name", "ph": "M", "pid": 0,
                       "tid": tid, "args": {"name": lbl}})
    timed: List[Tuple[int, int, int, Dict[str, Any]]] = []
    for lbl, t0, t1 in spans:
        b = int(round(t0 * 1e6))
        e = max(int(round(t1 * 1e6)), b)
        dur = e - b
        tid = tid_of[lbl]
        # sort key: ts, then E(0) before B(1); longer spans open first and
        # close last at identical timestamps so nesting stays well-formed
        timed.append((b, 1, -dur, {"name": lbl, "ph": "B", "pid": 0,
                                   "tid": tid, "ts": b}))
        timed.append((e, 0, dur, {"name": lbl, "ph": "E", "pid": 0,
                                  "tid": tid, "ts": e}))
    for name, t, value in counter_samples:
        ts = int(round(t * 1e6))
        timed.append((ts, 2, 0, {"name": name, "ph": "C", "pid": 0, "tid": 0,
                                 "ts": ts, "args": {"bytes": value}}))
    timed.sort(key=lambda x: x[:3])
    events.extend(ev for _, _, _, ev in timed)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
