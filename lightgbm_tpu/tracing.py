"""Request-path tracing + always-on flight recorder.

Two instruments that answer two questions nothing else in the stack can:

* **Where do the 25× go?** ROADMAP item 4: serving moves 81k rows/s where
  direct predict moves 2.0M, and until now the path had no per-request
  decomposition. Every request now carries a `Span` with monotonic stage
  marks (`parse`, `queue_wait`, `assembly`, `device`, `d2h`, `serialize`;
  shed requests end in a terminal `shed` stage), trace context rides the
  W3C ``traceparent`` header end to end, and per-stage log-bucketed
  streaming histograms aggregate into p50/p99 gauges surfaced on
  ``/statz``, ``/metrics`` and the bench ledger.

* **What happened just before it broke?** The `FlightRecorder` is an
  always-on bounded ring buffer — O(1) locked append, fixed memory cap,
  no I/O on the hot path, works with ``telemetry_dir`` unset — holding
  the most recent events, finished spans, and counter snapshots. It is
  dumped atomically (checkpoint writers) on breaker→OPEN, health
  rollback, fault-injection firing, unhandled exceptions in
  ``engine.train`` / the batcher worker, and on demand via
  ``GET /debug/flight``; ``tools/flightview.py`` renders a dump.

Design constraints (enforced by tests + graftlint R9 scope):

* ``note()`` is the one sanctioned unguarded hot-path emit in the tree:
  it must stay O(1) and allocation-bounded (one tuple + one small dict
  per call, ring slots preallocated by index arithmetic, no growth).
* Everything is stdlib: ids from ``os.urandom``, time from
  ``time.perf_counter`` (same basis as telemetry sessions, so finished
  spans feed straight into the unified Chrome-trace export).
* ``LGBM_TPU_FLIGHT=0`` compiles the recorder out (every entry point
  early-returns); numerical results are bit-identical either way.
  ``LGBM_TPU_FLIGHT_DIR`` pins the dump directory; otherwise dumps land
  in the active telemetry session dir, or stay in memory
  (``last_dump()``) when neither exists.
"""
from __future__ import annotations

import math
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .utils.timer import global_timer

# --------------------------------------------------------------------------
# W3C trace context (stdlib traceparent parse/generate)
# --------------------------------------------------------------------------

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def new_trace_id() -> str:
    return os.urandom(16).hex()


def new_span_id() -> str:
    return os.urandom(8).hex()


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``traceparent`` -> (trace_id, parent_span_id), or None when the
    header is absent/malformed (caller starts a fresh trace — the W3C
    "restart" behaviour, never an error)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, parent_id = m.group(1), m.group(2), m.group(3)
    if version == "ff":  # forbidden version
        return None
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return trace_id, parent_id


def format_traceparent(trace_id: str, span_id: str,
                       flags: str = "01") -> str:
    return f"00-{trace_id}-{span_id}-{flags}"


# --------------------------------------------------------------------------
# log-bucketed streaming histograms -> p50/p99 stage gauges
# --------------------------------------------------------------------------

_HIST_BASE_S = 1e-6     # bucket 0 upper bound: 1 microsecond
_HIST_GROWTH = 1.25     # geometric bucket growth
_HIST_BUCKETS = 96      # 1.25**96 * 1µs ≈ 2e3 s — covers any sane stage
_LOG_GROWTH = math.log(_HIST_GROWTH)


class StageHistogram:
    """Fixed-size log-bucketed histogram: O(1) record, bounded memory,
    quantiles read from bucket upper bounds (conservative — a reported
    p99 is an upper bound on the true p99 within one bucket width)."""

    __slots__ = ("counts", "n", "total_s")

    def __init__(self) -> None:
        self.counts = [0] * _HIST_BUCKETS
        self.n = 0
        self.total_s = 0.0

    def record(self, duration_s: float) -> None:
        if duration_s < 0.0:
            duration_s = 0.0
        if duration_s <= _HIST_BASE_S:
            idx = 0
        else:
            idx = min(_HIST_BUCKETS - 1,
                      1 + int(math.log(duration_s / _HIST_BASE_S)
                              / _LOG_GROWTH))
        self.counts[idx] += 1
        self.n += 1
        self.total_s += duration_s

    def quantile_s(self, q: float) -> float:
        """Nearest-rank quantile as the matched bucket's upper bound."""
        if self.n == 0:
            return 0.0
        rank = max(1, min(self.n, int(math.ceil(q * self.n))))
        seen = 0
        for idx, c in enumerate(self.counts):
            seen += c
            if seen >= rank:
                return _HIST_BASE_S * (_HIST_GROWTH ** idx)
        return _HIST_BASE_S * (_HIST_GROWTH ** (_HIST_BUCKETS - 1))


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

class Span:
    """One traced unit of work with ordered, accumulating stage marks.

    Stages are durations, not timestamps: ``add_stage`` accumulates under
    the same name (a chunked dispatch adds ``device`` once per chunk), and
    the Chrome-trace export lays stages out contiguously from ``t0``.
    ``finish`` is idempotent — whichever side reaches it first (the HTTP
    handler's ``finally`` or the batcher shedding the request) records the
    span exactly once.
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "t0", "t1",
                 "stages", "terminal", "links", "attrs", "record_stats",
                 "_finished")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 record_stats: bool = True) -> None:
        self.name = name
        self.trace_id = trace_id or new_trace_id()
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.t0 = time.perf_counter()
        self.t1: Optional[float] = None
        self.stages: Dict[str, float] = {}
        self.terminal: Optional[str] = None
        self.links: List[str] = []
        self.attrs: Dict[str, Any] = {}
        self.record_stats = record_stats
        self._finished = False

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)

    def add_stage(self, stage: str, duration_s: float) -> None:
        if self._finished:
            return
        self.stages[stage] = self.stages.get(stage, 0.0) + float(duration_s)

    def link(self, span_id: str) -> None:
        self.links.append(span_id)

    def finish(self, terminal: Optional[str] = None) -> None:
        if self._finished:
            return
        self._finished = True
        self.t1 = time.perf_counter()
        if terminal is not None:
            self.terminal = terminal
        _finish_span(self)


def start_span(name: str, traceparent: Optional[str] = None,
               parent: Optional[Span] = None,
               record_stats: bool = True) -> Span:
    """New span; inbound ``traceparent`` (honored when well-formed) or a
    parent span supplies trace ancestry, else a fresh trace starts."""
    trace_id = parent_id = None
    if parent is not None:
        trace_id, parent_id = parent.trace_id, parent.span_id
    else:
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_id = parsed
    return Span(name, trace_id=trace_id, parent_id=parent_id,
                record_stats=record_stats)


# --------------------------------------------------------------------------
# flight recorder (always-on bounded ring buffer)
# --------------------------------------------------------------------------

DEFAULT_CAPACITY = 2048
# one write per reason per interval: postmortems want the FIRST dump after
# an incident, not a dump per firing while a fault storm is in progress
DUMP_MIN_INTERVAL_S = 1.0

DUMP_FORMAT = "lgbm-flight"
DUMP_VERSION = 1

_enabled = os.environ.get("LGBM_TPU_FLIGHT", "1").lower() not in (
    "0", "off", "false", "no")


def enabled() -> bool:
    return _enabled


def set_enabled(on: bool) -> None:
    """Test hook: flips the compile-out switch at runtime (the env var
    ``LGBM_TPU_FLIGHT=0`` sets the process-wide default)."""
    global _enabled
    _enabled = bool(on)


class FlightRecorder:
    """Bounded ring of (seq, t, kind, fields) records.

    Append is a lock + index arithmetic + one slot store: O(1), no
    allocation beyond the record itself, no I/O ever. `snapshot()` walks
    the ring in sequence order; `dropped` counts evicted records so a
    dump states exactly how much history it lost."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self.capacity = max(16, int(capacity))
        self._slots: List[Optional[Tuple[int, float, str, Dict[str, Any]]]] \
            = [None] * self.capacity
        self._seq = 0
        self._lock = threading.Lock()

    def note(self, kind: str, fields: Dict[str, Any]) -> None:
        with self._lock:
            seq = self._seq
            self._seq = seq + 1
            self._slots[seq % self.capacity] = (
                seq, time.perf_counter(), kind, fields)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            slots = [s for s in self._slots if s is not None]
        slots.sort(key=lambda s: s[0])
        out = []
        for seq, t, kind, fields in slots:
            rec = {"seq": seq, "t": round(t, 6), "kind": kind}
            rec.update(fields)
            out.append(rec)
        return out

    @property
    def total(self) -> int:
        with self._lock:
            return self._seq

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._seq - self.capacity)

    def reset(self) -> None:
        with self._lock:
            self._slots = [None] * self.capacity
            self._seq = 0


_recorder = FlightRecorder(
    int(os.environ.get("LGBM_TPU_FLIGHT_CAP", DEFAULT_CAPACITY)))
_stats_lock = threading.Lock()
_stage_stats: Dict[Tuple[str, str], StageHistogram] = {}
_last_dump: Optional[Dict[str, Any]] = None
_last_dump_path: Optional[str] = None
_last_dump_ts: Dict[str, float] = {}


def recorder() -> FlightRecorder:
    return _recorder


def note(kind: str, **fields: Any) -> None:
    """The always-on recorder append — the sanctioned unguarded hot-path
    emit (graftlint R9 scopes this file): O(1), allocation-bounded, no
    I/O. Callers pass cheap already-computed scalars only."""
    if not _enabled:
        return
    _recorder.note(kind, fields)


def _finish_span(span: Span) -> None:
    if not _enabled:
        return
    if span.record_stats and span.stages:
        with _stats_lock:
            for stage, dur in span.stages.items():
                hist = _stage_stats.get((span.name, stage))
                if hist is None:
                    hist = _stage_stats[(span.name, stage)] = StageHistogram()
                hist.record(dur)
    rec: Dict[str, Any] = {
        "name": span.name,
        "trace_id": span.trace_id,
        "span_id": span.span_id,
        "t0": round(span.t0, 6),
        "t1": round(span.t1 or span.t0, 6),
        "stages_ms": {k: round(v * 1000.0, 4)
                      for k, v in span.stages.items()},
    }
    if span.parent_id:
        rec["parent_id"] = span.parent_id
    if span.terminal:
        rec["terminal"] = span.terminal
    if span.links:
        rec["links"] = list(span.links)
    if span.attrs:
        rec["attrs"] = dict(span.attrs)
    _recorder.note("span", rec)
    # unified trace: finished spans land in the active telemetry session
    # so build_chrome_trace exports serving + training in one timeline
    from . import telemetry
    if telemetry.enabled():
        sess = telemetry.session()
        if sess is not None:
            t = span.t0
            for stage, dur in span.stages.items():
                sess.add_span(f"{span.name}.{stage}", t, t + dur)
                t += dur


# --------------------------------------------------------------------------
# stage quantiles (for /statz, /metrics, bench)
# --------------------------------------------------------------------------

def stage_summary(span_name: str) -> Dict[str, Dict[str, float]]:
    """{stage: {count, p50_ms, p99_ms, total_ms}} for one span family."""
    out: Dict[str, Dict[str, float]] = {}
    with _stats_lock:
        items = [(k[1], h) for k, h in _stage_stats.items()
                 if k[0] == span_name]
    for stage, hist in sorted(items):
        out[stage] = {
            "count": hist.n,
            "p50_ms": round(hist.quantile_s(0.50) * 1000.0, 4),
            "p99_ms": round(hist.quantile_s(0.99) * 1000.0, 4),
            "total_ms": round(hist.total_s * 1000.0, 4),
        }
    return out


def quantile_gauges() -> Dict[str, float]:
    """Flat gauge map for the exposition renderer:
    ``<span>_stage_<stage>_p50_ms`` / ``..._p99_ms``."""
    out: Dict[str, float] = {}
    with _stats_lock:
        items = sorted(_stage_stats.items())
    for (name, stage), hist in items:
        if hist.n == 0:
            continue
        out[f"{name}_stage_{stage}_p50_ms"] = round(
            hist.quantile_s(0.50) * 1000.0, 4)
        out[f"{name}_stage_{stage}_p99_ms"] = round(
            hist.quantile_s(0.99) * 1000.0, 4)
    return out


def reset_stats() -> None:
    with _stats_lock:
        _stage_stats.clear()


# --------------------------------------------------------------------------
# flight dumps
# --------------------------------------------------------------------------

def resolve_flight_dir() -> Optional[str]:
    """Dump directory: ``LGBM_TPU_FLIGHT_DIR`` env, else the active
    telemetry session's out_dir, else None (in-memory dump only)."""
    env = os.environ.get("LGBM_TPU_FLIGHT_DIR")
    if env:
        return env
    from . import telemetry
    sess = telemetry.session()
    if sess is not None and sess.out_dir:
        return sess.out_dir
    return None


def build_dump(reason: str,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The postmortem document: recent ring contents + counter snapshot +
    stage quantiles. Pure in-memory assembly — writing is dump_flight's
    job."""
    from . import telemetry

    with _stats_lock:
        span_names = sorted({k[0] for k in _stage_stats})
    dump: Dict[str, Any] = {
        "format": DUMP_FORMAT,
        "version": DUMP_VERSION,
        "reason": reason,
        "wall_time": time.time(),
        "pid": os.getpid(),
        "capacity": _recorder.capacity,
        "total_records": _recorder.total,
        "dropped": _recorder.dropped,
        "telemetry_enabled": telemetry.enabled(),
        "events": _recorder.snapshot(),
        "counters": {k: int(v) for k, v in
                     sorted(global_timer.counters.items())},
        "gauges": sorted(global_timer.gauges),
        "stage_summary": {name: stage_summary(name)
                          for name in span_names},
    }
    if extra:
        dump["extra"] = extra
    return dump


def dump_flight(reason: str, extra: Optional[Dict[str, Any]] = None,
                force: bool = False) -> Optional[str]:
    """Dump the recorder for a postmortem. Returns the written path (or
    None when rate-limited, disabled, or no directory resolves — the
    in-memory copy is still retrievable via ``last_dump()``). Never
    raises: a failing postmortem write must not take down serving."""
    global _last_dump, _last_dump_path
    if not _enabled:
        return None
    now = time.monotonic()
    if not force:
        last = _last_dump_ts.get(reason)
        if last is not None and now - last < DUMP_MIN_INTERVAL_S:
            return None
    _last_dump_ts[reason] = now
    try:
        dump = build_dump(reason, extra)
    except Exception:  # pragma: no cover - assembly must never propagate
        return None
    _last_dump = dump
    global_timer.add_count("flight_dumps", 1)
    out_dir = resolve_flight_dir()
    if not out_dir:
        _last_dump_path = None
        return None
    try:
        import json

        from .checkpoint import atomic_write_text

        os.makedirs(out_dir, exist_ok=True)
        # latest-per-reason filename keeps the on-disk footprint bounded
        # under a fault storm; the ring inside each dump carries the
        # history of the preceding firings anyway
        safe = re.sub(r"[^a-zA-Z0-9_.-]", "_", reason)
        path = os.path.join(out_dir, f"flight-{safe}.json")
        atomic_write_text(path, json.dumps(dump, indent=1, sort_keys=True))
        _last_dump_path = path
        return path
    except Exception:  # pragma: no cover - best-effort postmortem I/O
        _last_dump_path = None
        return None


def last_dump() -> Optional[Dict[str, Any]]:
    return _last_dump


def last_dump_path() -> Optional[str]:
    return _last_dump_path


def reset() -> None:
    """Test hook: fresh recorder ring + stage stats + dump rate-limits."""
    global _last_dump, _last_dump_path
    _recorder.reset()
    reset_stats()
    _last_dump = None
    _last_dump_path = None
    _last_dump_ts.clear()
