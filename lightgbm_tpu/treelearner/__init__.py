from .serial import SerialTreeLearner, create_tree_learner

__all__ = ["SerialTreeLearner", "create_tree_learner"]
