"""Cost-Effective Gradient Boosting gain penalties.

Counterpart of CostEfficientGradientBoosting
(src/treelearner/cost_effective_gradient_boosting.hpp:23-174): per-candidate
split the gain is reduced by

    cegb_tradeoff * cegb_penalty_split * num_data_in_leaf
  + cegb_tradeoff * cegb_penalty_feature_coupled[f]   (first use of f only)
  + cegb_tradeoff * sum_{rows in leaf not yet seen by f} penalty_lazy[f]

The penalty is materialized here as a per-leaf [F] vector fed to the split
scan (ops/split.py per_feature_best), instead of the reference's per-
(leaf,feature) SplitInfo cache: when a coupled feature is first used, the
serial learner simply re-runs the (cached-histogram) scans for the live
frontier — the refund the reference applies by patching stored SplitInfos.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ..config import Config
from ..utils.log import Log


class CEGB:
    @staticmethod
    def enabled(config: Config) -> bool:
        # the reference's IsEnable also triggers on cegb_tradeoff != 1 alone,
        # but with every penalty zero that is a pure no-op — only an actual
        # penalty justifies leaving the fast device paths
        return (config.cegb_penalty_split > 0.0
                or bool(config.cegb_penalty_feature_coupled)
                or bool(config.cegb_penalty_feature_lazy))

    def __init__(self, config: Config, dataset) -> None:
        self.tradeoff = float(config.cegb_tradeoff)
        self.penalty_split = float(config.cegb_penalty_split)
        used: List[int] = dataset.used_features
        self.F = len(used)
        self.num_data = dataset.num_data

        def per_used(values: List[float], name: str) -> Optional[np.ndarray]:
            if not values:
                return None
            if len(values) != dataset.num_total_features:
                Log.fatal("%s should be the same size as feature number.", name)
            return np.asarray([values[f] for f in used], dtype=np.float64)

        self.coupled = per_used(config.cegb_penalty_feature_coupled,
                                "cegb_penalty_feature_coupled")
        self.lazy = per_used(config.cegb_penalty_feature_lazy,
                             "cegb_penalty_feature_lazy")
        self.used_in_split = np.zeros(self.F, dtype=bool)
        # per-(feature, row) "feature already computed for this row" marks,
        # bit-packed like the reference's Common::EmptyBitset (N/8 bytes per
        # feature instead of N bools)
        self.seen_bits: Optional[np.ndarray] = (
            np.zeros((self.F, (self.num_data + 7) // 8), dtype=np.uint8)
            if self.lazy is not None else None)

    @property
    def needs_rows(self) -> bool:
        return self.lazy is not None

    def penalty_vector(self, leaf_count: float,
                       leaf_rows: Optional[np.ndarray]) -> np.ndarray:
        """[F] gain penalty for one leaf's split scan (DeltaGain)."""
        vec = np.full(self.F, self.tradeoff * self.penalty_split * leaf_count,
                      dtype=np.float64)
        if self.coupled is not None:
            vec += np.where(self.used_in_split, 0.0,
                            self.tradeoff * self.coupled)
        if self.lazy is not None and leaf_rows is not None and len(leaf_rows):
            byte_idx = leaf_rows >> 3
            bit = (leaf_rows & 7).astype(np.uint8)
            seen = (self.seen_bits[:, byte_idx] >> bit) & 1  # [F, R]
            unseen = len(leaf_rows) - seen.sum(axis=1)
            vec += self.tradeoff * self.lazy * unseen
        return vec.astype(np.float32)

    def on_split_applied(self, dense_f: int,
                         leaf_rows: Optional[np.ndarray]) -> bool:
        """Record a committed split on dense feature dense_f over leaf_rows.
        Returns True when a coupled penalty was just lifted (the caller must
        refresh pending frontier scans — UpdateLeafBestSplits)."""
        newly = (self.coupled is not None
                 and not self.used_in_split[dense_f]
                 and self.coupled[dense_f] > 0)
        self.used_in_split[dense_f] = True
        if self.seen_bits is not None and leaf_rows is not None:
            np.bitwise_or.at(self.seen_bits[dense_f], leaf_rows >> 3,
                             np.uint8(1) << (leaf_rows & 7).astype(np.uint8))
        return bool(newly)
