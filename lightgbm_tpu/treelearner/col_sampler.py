"""Column sampling + interaction constraints.

Counterpart of src/treelearner/col_sampler.hpp: feature_fraction picks a
random feature subset per tree, feature_fraction_bynode re-samples per node,
and interaction_constraints restrict a node's candidate features to
constraint groups containing every feature already used on its path.
Produces dense-feature boolean masks consumed by the vectorized split scan.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Set

import numpy as np

from ..config import Config


def parse_interaction_constraints(text: str) -> List[Set[int]]:
    """Parse "[0,1,2],[2,3]" (real feature indices) into sets."""
    text = text.strip()
    if not text:
        return []
    groups: List[Set[int]] = []
    for chunk in text.replace(" ", "").strip("[]").split("],["):
        if chunk:
            groups.append({int(x) for x in chunk.split(",") if x != ""})
    return groups


class ColSampler:
    def __init__(self, config: Config, real_features: Sequence[int]) -> None:
        self.fraction = config.feature_fraction
        self.fraction_bynode = config.feature_fraction_bynode
        self.rng = np.random.RandomState(config.feature_fraction_seed)
        self.real_features = list(real_features)  # dense idx -> real idx
        self.num_features = len(real_features)
        self.constraints = parse_interaction_constraints(
            config.interaction_constraints)
        self._tree_mask = np.ones(self.num_features, dtype=bool)

    @property
    def active(self) -> bool:
        return (self.fraction < 1.0 or self.fraction_bynode < 1.0
                or bool(self.constraints))

    def _sample(self, base: np.ndarray, fraction: float) -> np.ndarray:
        candidates = np.nonzero(base)[0]
        k = max(1, int(round(len(candidates) * fraction)))
        chosen = self.rng.choice(candidates, k, replace=False)
        mask = np.zeros(self.num_features, dtype=bool)
        mask[chosen] = True
        return mask

    def reset_by_tree(self) -> np.ndarray:
        """Per-tree feature subset (ResetByTree)."""
        if self.fraction < 1.0:
            self._tree_mask = self._sample(
                np.ones(self.num_features, dtype=bool), self.fraction)
        else:
            self._tree_mask = np.ones(self.num_features, dtype=bool)
        return self._tree_mask

    def get_by_node(self, features_in_path: Optional[Set[int]]) -> np.ndarray:
        """Per-node mask (GetByNode): bynode re-sampling on top of the tree
        subset, intersected with the interaction-constraint closure of the
        path's features (real indices)."""
        mask = self._tree_mask
        if self.constraints:
            allowed: Set[int] = set()
            path = features_in_path or set()
            for group in self.constraints:
                if path <= group:
                    allowed |= group
            cmask = np.array([rf in allowed for rf in self.real_features])
            mask = mask & cmask
        if self.fraction_bynode < 1.0 and mask.any():
            mask = self._sample(mask, self.fraction_bynode)
        return mask
