"""Whole-tree-on-device learner: one XLA dispatch per tree.

The host-driven SerialTreeLearner pays per-split dispatch latency (3 calls +
2 blocking scalar pulls), which dominates wall-clock on a remote-attached
TPU. This learner instead grows the ENTIRE tree inside a single jitted
function: a `lax.while_loop` over speculative WAVES carrying the data in a
LEAF-CONTIGUOUS permutation:

    bins_p     [Gp,Np]      bin columns, rows permuted leaf-contiguously
    row_p      [Np,CH+2]    f32 payload: gh channels + perm + leaf id
    start/cnt  [L+1]        per-leaf (start, count) row ranges
    pool       [L+1,G,B,CH] per-leaf histograms (subtraction trick)
    leaf_best  [L+1,R]      per-leaf packed best-split records
    depth      [L+1]        per-leaf depth
    rec_store  [L,R+4]      the split log the host replays into a Tree

Per wave: top-K frontier leaves by gain -> stable 2-way partition of every
selected leaf's range (ops/compact_pallas.py) -> ragged rows-in-leaf
histogram of ONLY the smaller children (ops/hist_pallas.py ragged tiles,
K*CH channels) -> larger children by histogram subtraction from the pool ->
2K split scans -> an on-device replay that commits splits in exact
best-first order until the argmax needs a leaf whose children were not
precomputed. All shapes are static; the only host traffic per TREE is the
split log + final leaf ids (recovered in original row order by one
sort_key_val over the carried permutation).

Design notes:
  * Histogram work per tree is O(rows in selected leaves) ~ <= ~4N, not
    O(N * waves): the wave partitions FIRST (safe even for leaves the
    replay later declines — an internally reordered range is still one
    contiguous range), then histograms only the smaller-child subranges.
  * Row routing (which leaf owns a row, split decision fields, commit
    application) is position-range compares and masked [N,K]@[K,F]
    matmuls — TPU gathers serialize, compares and matmuls vectorize.
  * The wave replay keeps the reference's leaf-wise semantics bit-exact
    (tree.h best-first; growth stops when the best gain <= 0; masked no-op
    steps write to dump rows so the loop body stays branch-free).
  * The histogram pool this design needs (subtraction trick) is updated
    OUTSIDE the replay fori_loop in one vectorized masked write — per-step
    dynamic pool writes inside the loop defeat XLA's in-place analysis.

Counterpart of SerialTreeLearner::Train + CUDASingleGPUTreeLearner::Train
+ CUDADataPartition::SplitInner (serial_tree_learner.cpp:182,
cuda_single_gpu_tree_learner.cpp:169-360, cuda_data_partition.cu).
"""
from __future__ import annotations

import os
from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.sample_strategy import DeviceBag
from ..models.tree import Tree
from ..ops.histogram import build_histogram
from ..ops.partition import bucket_size
from ..ops.split import (SPLIT_FIELDS, ScanMeta, SplitInfo, find_best_split,
                         fix_feature_hist, gather_feature_hist_raw,
                         per_feature_best, reduce_best_record)
from .. import perfmodel, telemetry
from ..utils import sanitize
from ..utils.compat import shard_map
from ..utils.log import Log
from ..utils.timer import global_timer
from .serial import SerialTreeLearner, _leaf_output_host

REC = len(SPLIT_FIELDS)
# rec_store row: [leaf, parent_output, depth, valid] + SPLIT_FIELDS
STORE = REC + 4

# gain-adaptive wave-width thresholds: commit rate (committed splits /
# speculated splits) below which K steps one rung down, above which it
# steps back up toward the LGBM_TPU_WAVE ceiling
_WAVE_SHRINK_RATE = 0.5
_WAVE_GROW_RATE = 0.9


class FeatureTables(NamedTuple):
    """Per-dense-feature decision fields for device-side partitioning."""

    group: jax.Array  # [F] int32 group row in the bin matrix
    lo: jax.Array  # [F] int32 EFB group-bin range
    hi: jax.Array  # [F] int32
    default_bin: jax.Array  # [F] int32
    nbins: jax.Array  # [F] int32
    missing_type: jax.Array  # [F] int32
    is_efb: jax.Array  # [F] bool


def _feature_tables(dataset, used_features) -> FeatureTables:
    F = len(used_features)
    group = np.zeros(F, dtype=np.int32)
    lo = np.zeros(F, dtype=np.int32)
    hi = np.zeros(F, dtype=np.int32)
    db = np.zeros(F, dtype=np.int32)
    nb = np.zeros(F, dtype=np.int32)
    mt = np.zeros(F, dtype=np.int32)
    ie = np.zeros(F, dtype=bool)
    for k, f in enumerate(used_features):
        m = dataset.mappers[f]
        gi, mi = dataset.feature_to_group[f]
        fg = dataset.groups[gi]
        l, h, _ = fg.feature_bin_range(mi)
        group[k], lo[k], hi[k] = gi, l, h
        db[k], nb[k], mt[k] = m.default_bin, m.num_bin, m.missing_type
        ie[k] = fg.is_multi
    return FeatureTables(*(jnp.asarray(a, dtype=a.dtype)
                           for a in (group, lo, hi, db, nb, mt, ie)))


from ..common import MISSING_NAN, MISSING_ZERO  # noqa: E402


def _decide_go_left(gb, thresh, default_left, missing_type, default_bin,
                    nbins, efb_lo, efb_hi, is_efb):
    """NumericalDecisionInner on raw group bins with traced scalar fields
    (the per-node twin of ops.partition.split_decision_bins)."""
    gb = gb.astype(jnp.int32)
    in_range = (gb >= efb_lo) & (gb < efb_hi)
    shifted = gb - efb_lo
    natural = shifted + (shifted >= default_bin).astype(jnp.int32)
    fbin = jnp.where(is_efb, jnp.where(in_range, natural, default_bin), gb)
    is_missing = jnp.where(
        missing_type == MISSING_NAN, fbin == nbins - 1,
        jnp.where(missing_type == MISSING_ZERO, fbin == default_bin, False))
    return jnp.where(is_missing, default_left, fbin <= thresh)


class ShardMeta(NamedTuple):
    """Split-scan metadata for the ICI-sharded growers. Layout depends on
    the comm mode (see make_sharded_grow_fn):

    * mode="data" — gather tables span the FULL padded feature axis
      replicated (every device gathers all features from its local group
      histogram before the psum_scatter hands it a feature block); `scan`
      holds only this device's feature block.
    * mode="voting" — everything spans the FULL padded feature axis
      replicated: local scans nominate over all features and only elected
      slices are reduced.
    * mode="feature" — everything holds only this device's feature block
      (tables arrive feature-sharded; rows are replicated)."""

    gather_index: jax.Array  # [F_pad | f_local, Bmax] int32
    valid_slot: jax.Array  # [F_pad | f_local, Bmax] bool
    scan: ScanMeta  # matching [F_pad | f_local] feature block


# graftlint: disable=untimed-hot-func -- traced only inside the jitted grow_tree_on_device / make_sharded_grow_fn wrappers; every call site runs under the timed tree_device scope
def _grow_impl(bins: jax.Array, gh: jax.Array, leaf_id0: jax.Array,
               meta, tables: FeatureTables, params: jax.Array,
               feature_mask: jax.Array, scale_vec: Optional[jax.Array], *,
               num_leaves: int, num_bins: int, max_depth: int,
               quantized: bool, batch: int, bagged: bool,
               sharded: bool, narrow: bool, mode: str = "data",
               top_k: int = 0, exact_check: bool = False,
               skew: Optional[Tuple[jax.Array, jax.Array]] = None):
    """Shared wave-loop body of the single-device and ICI-sharded growers.

    sharded=False: `meta` is a FeatureMeta and everything is local — the
    body of the public `grow_tree_on_device`.

    sharded=True runs inside a `jax.shard_map` over the "data" mesh axis
    (see make_sharded_grow_fn); `meta` is a ShardMeta and `mode` picks the
    comm scheme:

    mode="data" — bins/gh/leaf_id0 are this device's leaf-contiguous row
    shard, and per wave the ONLY cross-device traffic — all of it
    O(K*F*Bmax*CH), independent of the row count — is
      * a psum of the K per-shard left counts, so the smaller/larger-child
        choice and the subtraction pool key off GLOBAL row counts
        (SyncUpGlobalBestSplit semantics, parallel_tree_learner.h:209);
      * ONE psum_scatter merging the [K, F_pad, Bmax, CH] RAW smaller-child
        feature histograms into per-device feature blocks (int16 when
        `narrow` — the reference's int16 histogram reduction);
      * an all_gather of the [2K, F_pad, REC] per-feature best records
        before the replicated argmax.
    Partition, ragged histograms, and the leaf-id relabel stay 100% local
    (the CUDADataPartition-style local design); the best-first replay
    consumes only replicated values, so every device commits the identical
    tree. The histogram pool turns feature-major ([L+1, f_local, Bmax, CH]
    raw reduced blocks) and is paired with replicated raw leaf totals +
    global leaf counts so subtraction works on already-reduced data.

    mode="voting" — rows sharded like "data", but the histogram pool keeps
    the LOCAL group layout and the full reduction is replaced by PV-Tree
    two-phase voting (voting_parallel_tree_learner.cpp, arxiv 1611.01276):
    each device scans its local feature histograms, nominates its top-k
    features per candidate leaf, one tiny all_gather of the nomination ids
    elects the global top-2k by vote count (deterministic and replicated),
    and ONLY the elected features' raw histogram slices cross the wire via
    a gathered psum before a replicated rescan commits a true global
    argmax over the candidate set. Per-wave ICI volume is
    O(K*(D*k + 2k*Bmax*CH)) — independent of F. The K smaller children
    are nominated/elected/reduced BEFORE the pool subtraction produces the
    K larger children (double-buffered dispatch): the first slice psum is
    in flight while the subtraction runs, which is what the
    `device_ici_overlap_pct` gauge prices. `exact_check` additionally runs
    the full reduction each scan and counts elected-vs-exact best-feature
    disagreements (the `voting_miss_total` counter, returned as a sixth
    output); `skew` is the vote_skew fault hook — (rank, wave) traced
    scalars, -1 to disarm.

    mode="feature" — rows REPLICATED (feature_parallel_tree_learner.cpp):
    every device builds the full local histogram and partitions
    identically; only the split scan is feature-sharded (meta holds this
    device's block) and the single collective per scan is the [2K, D, REC]
    best-record all_gather — O(2K*REC), independent of rows AND features.
    """
    L = num_leaves
    G, N = bins.shape
    CH = gh.shape[1]
    K = max(1, min(batch, L))
    voting = sharded and mode == "voting"
    feature_par = sharded and mode == "feature"
    data_par = sharded and mode == "data"
    # "data" and "voting" shard the rows; "feature" replicates them and
    # shards only the scan
    row_sharded = sharded and not feature_par
    min_data, min_hess = params[2], params[3]
    neg_inf = jnp.float32(-jnp.inf)
    from ..ops.compact_pallas import (COMPACT_TILE, compact_rows,
                                      range_partition_dst)
    from ..ops.hist_pallas import (DEFAULT_TILE_ROWS, active_tile_table,
                                   hist_force_f32,
                                   pallas_histogram_slots_ragged)
    from ..ops.histogram import _use_pallas

    # pad rows ONCE to a common multiple of the histogram and compaction
    # tiles; padded rows carry leaf_id -1 and zero gh and (like bagged-out
    # rows) sit after every leaf range, contributing nothing anywhere
    unit = max(DEFAULT_TILE_ROWS, COMPACT_TILE)
    assert unit % COMPACT_TILE == 0 and unit % DEFAULT_TILE_ROWS == 0
    Np = -(-N // unit) * unit
    if Np != N:
        bins = jnp.pad(bins, ((0, 0), (0, Np - N)), constant_values=0)
        gh = jnp.pad(gh, ((0, Np - N), (0, 0)))
        leaf_id0 = jnp.pad(leaf_id0, (0, Np - N), constant_values=-1)
    # 8-bit planes (uint8 bins, every group <= 256 bins) are carried
    # UNWIDENED through the wave loop — 4x less HBM traffic on the dominant
    # [Gp, Np] array, single-limb compaction transport. Mosaic tiles 8-bit
    # as (32, 128), so the group dim pads to 32 instead of 8. Wider planes
    # (uint16 groups, or the LGBM_TPU_BINS_I32 escape hatch upstream)
    # widen to int32 here as before.
    plane8 = bins.dtype.itemsize == 1
    Gp = -(-G // 32) * 32 if plane8 else -(-G // 8) * 8
    bins_p = bins if plane8 else bins.astype(jnp.int32)
    if Gp != G:
        bins_p = jnp.pad(bins_p, ((0, Gp - G), (0, 0)), constant_values=0)
    T_hist = Np // DEFAULT_TILE_ROWS
    # Pallas kernels on TPU backends; the XLA fallback (CPU tests) shares
    # the forward-map/range logic and differs only in kernel dispatch.
    # LGBM_TPU_PALLAS_INTERPRET=1 runs the TPU kernel path in interpret
    # mode — CPU-runnable end-to-end coverage of the ragged machinery.
    interp = os.environ.get("LGBM_TPU_PALLAS_INTERPRET", "").lower() in (
        "1", "true", "on")
    use_kernels = (_use_pallas() or interp) and os.environ.get(
        "LGBM_TPU_HIST_SLOTS", "1").lower() not in ("0", "false", "off")
    pool_dtype = jnp.int32 if quantized else jnp.float32
    pos = jnp.arange(Np, dtype=jnp.int32)

    # leaf-contiguous payload: gh channels + original position + leaf id,
    # all exact in f32 (positions < 2**24, ids < 2**8; quantized int8 gh
    # values are exact too) and moved bit-exactly by the compaction kernel.
    # LGBM_TPU_GH_BF16=1 (opt-in, float path only): gh rides as bf16 PAIRS
    # bitcast into f32 payload columns — half the gh carry bytes. The
    # packed bits survive compaction unchanged (the kernel moves f32 limbs
    # exactly) and are unpacked per histogram pass; bit-identity with the
    # f32 path is NOT guaranteed (the learner warns once).
    pack_bf16 = (not quantized) and os.environ.get(
        "LGBM_TPU_GH_BF16", "").lower() in ("1", "true", "on")
    if pack_bf16:
        CHp = CH + (CH % 2)
        ghb = gh.astype(jnp.float32).astype(jnp.bfloat16)
        if CHp != CH:
            ghb = jnp.pad(ghb, ((0, 0), (0, CHp - CH)))
        gh_cols = jax.lax.bitcast_convert_type(
            ghb.reshape(Np, CHp // 2, 2), jnp.float32)  # [Np, CHp//2]
        n_gh = CHp // 2
    else:
        gh_cols = gh.astype(jnp.float32)
        n_gh = CH
    row_p = jnp.concatenate([
        gh_cols, pos.astype(jnp.float32)[:, None],
        leaf_id0.astype(jnp.float32)[:, None]], axis=1)  # [Np, n_gh+2]
    POS_COL = n_gh
    LEAF_COL = n_gh + 1

    def payload_gh(row_c):
        """gh channels of a payload slice as f32 [rows, CH] (unpacks the
        bf16 pairs when the narrow carry is on)."""
        if not pack_bf16:
            return row_c[:, :CH]
        pairs = jax.lax.bitcast_convert_type(row_c[:, :n_gh], jnp.bfloat16)
        return pairs.reshape(row_c.shape[0], 2 * n_gh)[:, :CH].astype(
            jnp.float32)

    def scan_hist(hist):
        if quantized:
            return hist.astype(jnp.float32) * scale_vec
        return hist

    def hist_totals(hist):
        if quantized:
            return hist[0].sum(axis=0).astype(jnp.float32) * scale_vec
        return hist[0].sum(axis=0)

    def guard(rec, cnt, sum_h, depth):
        """BeforeFindBestSplit gates (serial_tree_learner.cpp:343)."""
        ok = (cnt >= 2 * min_data) & (sum_h >= 2 * min_hess)
        if max_depth > 0:
            ok &= depth < max_depth
        return rec.at[0].set(jnp.where(ok, rec[0], neg_inf))

    def ranged_hist(bins_c, row_c, slot, n_slots, starts, ends, valid):
        """[G, B, n_slots*CH] histogram of the rows inside the given
        leaf-contiguous ranges (slot must be the dump value outside).
        bins_c/row_c passed explicitly: inside the wave loop they are the
        CARRY arrays, not the pre-loop closure values."""
        ghc = payload_gh(row_c)
        if use_kernels:
            tiles, nact = active_tile_table(starts, ends, valid, T_hist,
                                            DEFAULT_TILE_ROWS)
            h = pallas_histogram_slots_ragged(
                bins_c, ghc, slot, tiles, nact, num_bins,
                n_slots, quantized=quantized, f32=hist_force_f32(),
                interpret=interp)
            return h[:G]
        # XLA fallback: flat slot-expanded build over the full row set
        col_slot = jnp.arange(n_slots * CH, dtype=jnp.int32) // CH
        ghK = jnp.where(slot[:, None] == col_slot[None, :],
                        jnp.tile(ghc, (1, n_slots)), 0.0)
        h = build_histogram(bins_c[:G], ghK, num_bins)
        return h.astype(pool_dtype)  # quantized: exact ints below 2**24

    if data_par:
        gidx, vslot, sm = meta.gather_index, meta.valid_slot, meta.scan
        F_pad, Bmax = gidx.shape
        f_local = sm.default_bin.shape[0]
        shard_off = (jax.lax.axis_index("data") * f_local).astype(
            jnp.float32)

        def raw_blocks(hists_k):
            """[k, G, B, CH] raw local group hists -> [k, f_local, Bmax, CH]
            RAW per-device feature blocks via ONE psum_scatter over the
            padded feature axis — the wave's dominant ICI transfer
            (K*F_pad*Bmax*CH values, int16 when `narrow`). The gather is a
            pure selection, so it commutes bit-exactly with the reduction;
            EFB reconstruction and scaling happen AFTER, on reduced blocks
            with global totals, matching the single-device op order."""
            fh = jax.vmap(
                lambda h: gather_feature_hist_raw(h, gidx, vslot))(hists_k)
            if narrow:
                fh = fh.astype(jnp.int16)
            blk = jax.lax.psum_scatter(fh, "data", scatter_dimension=1,
                                       tiled=True)
            return blk.astype(pool_dtype)

        def scan_blocks(blk_raw, tot_raw, depths):
            """[k, f_local, Bmax, CH] raw reduced blocks + [k, CH] raw
            GLOBAL totals -> [k, REC] guarded globally-best records:
            scale -> EFB fix -> local per-feature scan -> all_gather +
            argmax (SyncUpGlobalBestSplit) — the sharded twin of
            find_best_split over the same values."""
            if quantized:
                blk = blk_raw.astype(jnp.float32) * scale_vec
                tot = tot_raw.astype(jnp.float32) * scale_vec[None, :]
            else:
                blk, tot = blk_raw, tot_raw
            blk = jax.vmap(
                lambda b, t: fix_feature_hist(b, t, sm.efb_omitted,
                                              sm.default_bin))(blk, tot)
            recs = jax.vmap(
                lambda b, t: per_feature_best(b, t, sm, params,
                                              feature_mask))(blk, tot)
            feat = recs[:, :, 1]
            recs = recs.at[:, :, 1].set(
                jnp.where(feat >= 0, feat + shard_off, -1.0))
            recs = jax.lax.all_gather(recs, "data", axis=1, tiled=True)
            best = jax.vmap(reduce_best_record)(recs)
            return jax.vmap(guard)(best, tot[:, 2], tot[:, 1], depths)

    if voting:
        gidx, vslot, sm_full = meta.gather_index, meta.valid_slot, meta.scan
        F_pad, Bmax = gidx.shape
        k_local = max(1, min(top_k, F_pad))
        k_global = max(1, min(2 * top_k, F_pad))

        def _scaled(a):
            if quantized:
                return a.astype(jnp.float32) * scale_vec
            return a

        def _fix_scan(fh, tot):
            """Scaled feature hists + matching totals -> [*, F_pad, REC]
            per-feature records (EFB fix commutes with the reduction, so
            fixing local hists with local totals and reduced hists with
            global totals yields consistent values)."""
            fh = jax.vmap(lambda b, t: fix_feature_hist(
                b, t, sm_full.efb_omitted, sm_full.default_bin))(fh, tot)
            return jax.vmap(lambda b, t: per_feature_best(
                b, t, sm_full, params, feature_mask))(fh, tot)

        def vote_scan(hists_k, tot_raw, depths, wave_no):
            """[k, G, B, CH] raw LOCAL group hists + [k, CH] raw GLOBAL
            totals -> ([k, REC] guarded globally-best records over the
            ELECTED candidate set, disagreement count).

            PV-Tree two-phase voting: local full-F scan -> top-k
            nomination -> all_gather + vote count -> replicated top-2k
            election (jax.lax.top_k ties break to the LOWER index and the
            elected set is sorted, so top_k >= F elects arange(F) and the
            rescan is bit-identical to a full scan) -> psum of ONLY the
            elected raw slices -> replicated rescan."""
            kk = hists_k.shape[0]
            fh_raw = jax.vmap(lambda h: gather_feature_hist_raw(
                h, gidx, vslot))(hists_k)  # [k, F_pad, Bmax, CH] raw local
            loc_tot_raw = hists_k[:, 0].sum(axis=1)  # [k, CH] raw local
            local_recs = _fix_scan(_scaled(fh_raw), _scaled(loc_tot_raw))
            # phase 1 (LocalVoting): nominate the local top-k by local gain
            _, nom = jax.lax.top_k(local_recs[:, :, 0], k_local)  # [k, kl]
            if skew is not None:
                # vote_skew@R:K fault: this rank's nominations are garbage
                # at the armed wave (highest feature ids — the padded/inert
                # tail), modelling a worker whose local scan is corrupted
                hit = ((jax.lax.axis_index("data") == skew[0])
                       & (wave_no == skew[1]))
                garbage = (F_pad - 1 - jnp.arange(k_local, dtype=nom.dtype)
                           ) % F_pad
                nom = jnp.where(hit, jnp.broadcast_to(garbage[None, :],
                                                      nom.shape), nom)
            votes = jax.lax.all_gather(nom, "data", axis=1,
                                       tiled=True)  # [k, D*kl]
            counts = jax.vmap(lambda v: jnp.zeros(
                (F_pad,), jnp.int32).at[v].add(1))(votes)
            # phase 2 (GlobalVoting): elect the top-2k by vote count —
            # replicated inputs, deterministic ties, ascending elected ids
            _, selected = jax.lax.top_k(counts, k_global)  # [k, kg]
            selected = jnp.sort(selected, axis=1)
            sel_raw = jnp.take_along_axis(
                fh_raw, selected[:, :, None, None], axis=1)
            if narrow:
                sel_raw = sel_raw.astype(jnp.int16)
            sel_red = jax.lax.psum(sel_raw, "data").astype(pool_dtype)
            tot = _scaled(tot_raw)

            def rescan(blk, idx, t):
                m = jax.tree_util.tree_map(lambda a: a[idx], sm_full)
                blk = fix_feature_hist(blk, t, m.efb_omitted, m.default_bin)
                recs = per_feature_best(blk, t, m, params,
                                        feature_mask[idx])
                feat = recs[:, 1]
                gid = idx[jnp.maximum(feat.astype(jnp.int32), 0)].astype(
                    jnp.float32)
                recs = recs.at[:, 1].set(jnp.where(feat >= 0, gid, -1.0))
                return reduce_best_record(recs)

            best = jax.vmap(rescan)(_scaled(sel_red), selected, tot)
            best = jax.vmap(guard)(best, tot[:, 2], tot[:, 1], depths)
            if not exact_check:
                return best, jnp.int32(0)
            # LGBM_TPU_VOTING_EXACT_CHECK=1: also run the full reduction
            # the vote avoided and count best-feature disagreements (the
            # documented approximation: the exact best can be un-nominated)
            full_raw = fh_raw.astype(jnp.int16) if narrow else fh_raw
            full = jax.lax.psum(full_raw, "data").astype(pool_dtype)
            frecs = _fix_scan(_scaled(full),
                              jnp.broadcast_to(tot, (kk, CH)))
            fbest = jax.vmap(reduce_best_record)(frecs)
            fbest = jax.vmap(guard)(fbest, tot[:, 2], tot[:, 1], depths)
            miss = jnp.sum(((fbest[:, 0] > 0)
                            & (fbest[:, 1] != best[:, 1])).astype(jnp.int32))
            return best, miss

    if feature_par:
        gidx, vslot, sm = meta.gather_index, meta.valid_slot, meta.scan
        f_local = sm.default_bin.shape[0]
        shard_off = (jax.lax.axis_index("data") * f_local).astype(
            jnp.float32)

        def feature_scan(hists_k, tots, depths):
            """[k, G, B, CH] replicated raw group hists + [k, CH] scaled
            totals -> [k, REC] guarded best records: every device gathers
            and scans its OWN feature block of the full local histogram;
            the only cross-device traffic is the [k, D, REC] best-record
            all_gather (FeatureParallelTreeLearner semantics)."""
            fh = jax.vmap(lambda h: gather_feature_hist_raw(
                scan_hist(h), gidx, vslot))(hists_k)
            fh = jax.vmap(lambda b, t: fix_feature_hist(
                b, t, sm.efb_omitted, sm.default_bin))(fh, tots)
            recs = jax.vmap(lambda b, t: per_feature_best(
                b, t, sm, params, feature_mask))(fh, tots)
            feat = recs[:, :, 1]
            recs = recs.at[:, :, 1].set(
                jnp.where(feat >= 0, feat + shard_off, -1.0))
            best = jax.vmap(reduce_best_record)(recs)  # [k, REC] local
            allr = jax.lax.all_gather(best[:, None], "data", axis=1,
                                      tiled=True)  # [k, D, REC]
            best = jax.vmap(reduce_best_record)(allr)
            return jax.vmap(guard)(best, tots[:, 2], tots[:, 1], depths)

    # --- initial compaction: in-bag rows to the front, root = [0, n_in)
    if bagged:
        in_bag = leaf_id0 == 0
        n_in = in_bag.sum().astype(jnp.int32)
        dst0, _ = range_partition_dst(
            in_bag, jnp.ones((Np, 1), bool), jnp.zeros(1, jnp.int32),
            jnp.full(1, Np, jnp.int32), jnp.ones(1, bool))
        bins_p, row_p = compact_rows(
            bins_p, row_p, dst0, [in_bag, ~in_bag],
            jnp.ones(Np, bool), tile=COMPACT_TILE,
            use_pallas=use_kernels, interpret=interp)
    elif row_sharded:
        # the learner's global row padding trails the real rows, so every
        # shard's real rows are already contiguous from 0 — count, don't
        # compact
        n_in = (leaf_id0 == 0).sum().astype(jnp.int32)
    else:
        n_in = jnp.int32(N)

    start = jnp.zeros(L + 1, jnp.int32)
    count = jnp.zeros(L + 1, jnp.int32).at[0].set(n_in)

    # --- root histogram through the ragged slots kernel (satellite: the
    # thin-CH masked dot cost ~183 ms/tree; this path is O(n_in) and warm)
    root_hist = ranged_hist(
        bins_p, row_p, jnp.where(pos < n_in, 0, 1), 1,
        jnp.zeros(1, jnp.int32), n_in[None], jnp.ones(1, bool))
    hist_rows = n_in  # instrumentation: rows histogrammed this tree

    depth = jnp.zeros(L + 1, jnp.int32)
    leaf_best = jnp.full((L + 1, REC), neg_inf, jnp.float32)
    if data_par:
        root_tot_raw = jax.lax.psum(root_hist[0].sum(axis=0), "data")
        n_in_g = jax.lax.psum(n_in, "data")
        pool = jnp.zeros((L + 1, f_local, Bmax, CH), pool_dtype).at[0].set(
            raw_blocks(root_hist[None])[0])
        tpool = jnp.zeros((L + 1, CH), pool_dtype).at[0].set(root_tot_raw)
        count_g = jnp.zeros(L + 1, jnp.int32).at[0].set(n_in_g)
        root_rec = scan_blocks(pool[0][None], root_tot_raw[None],
                               jnp.zeros(1, jnp.int32))[0]
    elif voting:
        # the pool keeps the LOCAL raw group layout — no feature-blocked
        # histogram crosses the wire until the vote elects its slice
        root_tot_raw = jax.lax.psum(root_hist[0].sum(axis=0), "data")
        n_in_g = jax.lax.psum(n_in, "data")
        pool = jnp.zeros((L + 1, G, num_bins, CH), pool_dtype).at[0].set(
            root_hist)
        tpool = jnp.zeros((L + 1, CH), pool_dtype).at[0].set(root_tot_raw)
        count_g = jnp.zeros(L + 1, jnp.int32).at[0].set(n_in_g)
        root_rec, root_miss = vote_scan(
            root_hist[None].astype(pool_dtype), root_tot_raw[None],
            jnp.zeros(1, jnp.int32), jnp.int32(0))
        root_rec = root_rec[0]
    else:
        root_tot = hist_totals(root_hist)
        pool = jnp.zeros((L + 1, G, num_bins, CH), pool_dtype).at[0].set(
            root_hist)
        if feature_par:
            root_rec = feature_scan(root_hist[None].astype(pool_dtype),
                                    root_tot[None],
                                    jnp.zeros(1, jnp.int32))[0]
        else:
            root_rec = guard(find_best_split(scan_hist(root_hist), root_tot,
                                             meta, params, feature_mask),
                             root_tot[2], root_tot[1], jnp.int32(0))
    leaf_best = leaf_best.at[0].set(root_rec)
    # one extra dump row at the end for masked-out replay writes
    rec_store = jnp.zeros((max(L - 1, 1) + 1, STORE), jnp.float32)

    l1, l2, max_delta = params[0], params[1], params[5]

    def wave(carry):
        if voting:
            (bins_p, row_p, start, count, depth, leaf_best, rec_store, pool,
             n_cur, t, hist_rows, tpool, count_g, miss, n_waves) = carry
        elif data_par:
            (bins_p, row_p, start, count, depth, leaf_best, rec_store, pool,
             n_cur, t, hist_rows, tpool, count_g, n_waves) = carry
        else:
            (bins_p, row_p, start, count, depth, leaf_best, rec_store, pool,
             n_cur, t, hist_rows, n_waves) = carry
        n_waves = n_waves + 1  # wave-efficiency telemetry (finalize())
        gains = leaf_best[:L, 0]
        sel_gain, sel = jax.lax.top_k(gains, K)  # [K] distinct leaves
        sel = sel.astype(jnp.int32)
        sel_ok = sel_gain > 0

        # --- per-selected-leaf split fields
        recs_sel = leaf_best[sel]  # [K, REC]
        f_k = jnp.maximum(recs_sel[:, 1].astype(jnp.int32), 0)
        thresh_k = recs_sel[:, 2].astype(jnp.int32)
        defl_k = recs_sel[:, 3] > 0.5
        s_k = jnp.take(start, sel)
        c_k = jnp.take(count, sel)
        e_k = s_k + c_k

        # --- per-row ownership by POSITION RANGE (leaf-contiguous layout).
        # The [N, K] compare stays VECTORIZED on the VPU; a [L+1]-table
        # gather formulation measured ~20% slower end to end (TPU gathers
        # serialize, elementwise compares do not).
        match = ((pos[:, None] >= s_k[None, :])
                 & (pos[:, None] < e_k[None, :]) & sel_ok[None, :])  # [N, K]
        kvalid = match.any(axis=1)

        # per-row split fields as ONE masked [N,K]@[K,F] matmul over the
        # match matrix — vectorized VPU/MXU work; jnp.take gathers here
        # measured far slower (TPU gathers serialize), and separate
        # per-field matvecs would re-read the [N, K] matrix from HBM many
        # times. Field values are small ints, exact in f32. HIGHEST
        # precision: default TPU matmul rounds operands to bf16 (8 mantissa
        # bits), which would corrupt integer fields > 256 — group ids, new
        # leaf ids, bin offsets, row positions.
        matchf = match.astype(jnp.float32)

        def rows_of(per_k_fields):  # [K, F] -> [N, F]
            return jax.lax.dot(matchf, per_k_fields.astype(jnp.float32),
                               precision=jax.lax.Precision.HIGHEST)

        fields = jnp.stack([
            tables.group[f_k], thresh_k, defl_k.astype(jnp.int32),
            tables.missing_type[f_k], tables.default_bin[f_k],
            tables.nbins[f_k], tables.lo[f_k], tables.hi[f_k],
            tables.is_efb[f_k].astype(jnp.int32),
        ], axis=1)  # [K, 9]
        rowsF = rows_of(fields)  # [N, 9]
        ri = rowsF.astype(jnp.int32)
        grp_row = ri[:, 0]
        # bins[grp_row[n], n] without a gather: compare-select over the G
        # group rows (G*N elementwise beats an N-sized row-varying gather)
        gb_row = jnp.sum(
            jnp.where(jnp.arange(Gp, dtype=jnp.int32)[:, None] == grp_row[None, :], bins_p,
                      0), axis=0, dtype=jnp.int32)
        go_left = _decide_go_left(
            gb_row, ri[:, 1], rowsF[:, 2] > 0.5, ri[:, 3], ri[:, 4],
            ri[:, 5], ri[:, 6], ri[:, 7], rowsF[:, 8] > 0.5)

        # --- stable partition of EVERY selected range (speculative: an
        # uncommitted leaf's range is merely reordered, still contiguous)
        dst, nl_k = range_partition_dst(go_left, match, s_k, c_k, sel_ok)
        cmasks = ([match[:, k] & go_left for k in range(K)]
                  + [match[:, k] & ~go_left for k in range(K)])
        bins_p, row_p = compact_rows(
            bins_p, row_p, dst, cmasks, kvalid, tile=COMPACT_TILE,
            use_pallas=use_kernels, interpret=interp)

        # --- ragged histogram of ONLY the smaller children; tie -> left,
        # matching the serial learner's _apply_split choice
        nr_k = c_k - nl_k
        if row_sharded:
            # smaller/larger child by GLOBAL row counts (psum of the
            # per-shard left counts — SyncUpGlobalBestSplit semantics):
            # every device histograms its LOCAL rows of the globally
            # smaller child, whatever their local count
            nl_g = jax.lax.psum(nl_k, "data")
            c_g = jnp.take(count_g, sel)
            nr_g = c_g - nl_g
            left_small = nl_g <= nr_g
            sc_k = jnp.where(left_small, nl_k, nr_k)
        else:
            left_small = nl_k <= nr_k
            sc_k = jnp.minimum(nl_k, nr_k)
        ss_k = jnp.where(left_small, s_k, s_k + nl_k)
        se_k = ss_k + sc_k
        inS = ((pos[:, None] >= ss_k[None, :])
               & (pos[:, None] < se_k[None, :]) & sel_ok[None, :])
        slotS = jnp.where(inS.any(axis=1),
                          jnp.argmax(inS, axis=1).astype(jnp.int32), K)
        hist_rows = hist_rows + jnp.sum(jnp.where(sel_ok, sc_k, 0))
        histS = ranged_hist(bins_p, row_p, slotS, K, ss_k, se_k,
                            sel_ok & (sc_k > 0))
        histS_k = jnp.moveaxis(
            histS.reshape(G, num_bins, K, CH), 2, 0)  # [K, G, B, CH]
        child_depth = depth[sel] + 1  # [K]
        depth2 = jnp.repeat(child_depth, 2)  # [2K]
        if data_par:
            # global raw totals of the smaller children, then ONE
            # psum_scatter merges the raw gathered feature hists into this
            # device's reduced block; subtraction happens on reduced data
            totS_raw = jax.lax.psum(histS_k[:, 0].sum(axis=1), "data")
            blkS = raw_blocks(histS_k)  # [K, f_local, Bmax, CH]
            pool_sel = jnp.take(pool, sel, axis=0)
            tp_sel = jnp.take(tpool, sel, axis=0)  # [K, CH]
            histL = jnp.where(left_small[:, None, None, None], blkS,
                              pool_sel - blkS)
            histR = pool_sel - histL  # subtract_histogram, on blocks
            totL_raw = jnp.where(left_small[:, None], totS_raw,
                                 tp_sel - totS_raw)
            totR_raw = tp_sel - totL_raw
            hists = jnp.stack([histL, histR], axis=1).reshape(
                2 * K, f_local, Bmax, CH)
            tot2_raw = jnp.stack([totL_raw, totR_raw], axis=1).reshape(
                2 * K, CH)
            totals = tot2_raw
            if quantized:
                totals = totals.astype(jnp.float32) * scale_vec[None, :]
            recs2 = scan_blocks(hists, tot2_raw, depth2)
        elif voting:
            # double-buffered dispatch: elect + reduce the SMALLER children
            # first, so their nomination gather and elected-slice psum are
            # in flight while the larger-child subtraction runs on local
            # data — the overlapped half of the wave's ICI traffic
            # (device_ici_overlap_pct)
            totS_raw = jax.lax.psum(histS_k[:, 0].sum(axis=1), "data")
            histSblk = histS_k.astype(pool_dtype)
            recsS, missS = vote_scan(histSblk, totS_raw, child_depth,
                                     n_waves)
            pool_sel = jnp.take(pool, sel, axis=0)  # [K, G, B, CH] local
            tp_sel = jnp.take(tpool, sel, axis=0)  # [K, CH] global raw
            histB = pool_sel - histSblk  # the bigger sibling, local raw
            totB_raw = tp_sel - totS_raw
            recsB, missB = vote_scan(histB, totB_raw, child_depth, n_waves)
            miss = miss + missS + missB
            histL = jnp.where(left_small[:, None, None, None], histSblk,
                              histB)
            histR = pool_sel - histL
            totL_raw = jnp.where(left_small[:, None], totS_raw, totB_raw)
            totR_raw = tp_sel - totL_raw
            recsL = jnp.where(left_small[:, None], recsS, recsB)
            recsR = jnp.where(left_small[:, None], recsB, recsS)
            recs2 = jnp.stack([recsL, recsR], axis=1).reshape(2 * K, REC)
            tot2_raw = jnp.stack([totL_raw, totR_raw], axis=1).reshape(
                2 * K, CH)
            totals = tot2_raw
            if quantized:
                totals = totals.astype(jnp.float32) * scale_vec[None, :]
        else:
            pool_sel = jnp.take(pool, sel, axis=0)  # [K, G, B, CH]
            histL = jnp.where(left_small[:, None, None, None], histS_k,
                              pool_sel - histS_k)
            histR = pool_sel - histL  # subtract_histogram, vectorized
            hists = jnp.stack([histL, histR], axis=1).reshape(
                2 * K, G, num_bins, CH)
            totals = hists[:, 0].sum(axis=1)  # bins-summed -> [2K, CH]
            if quantized:
                totals = totals.astype(jnp.float32) * scale_vec[None, :]
            if feature_par:
                recs2 = feature_scan(hists, totals, depth2)
            else:
                recs2 = jax.vmap(
                    lambda h, tot: find_best_split(scan_hist(h), tot, meta,
                                                   params, feature_mask))(
                    hists, totals)
                recs2 = jax.vmap(guard)(recs2, totals[:, 2], totals[:, 1],
                                        depth2)

        # --- exact best-first replay over the precomputed set
        def replay_step(_, rp):
            (leaf_best, depth, rec_store, n_cur, t, committed, newids,
             active) = rp
            cur = leaf_best[:L, 0]
            b = jnp.argmax(cur).astype(jnp.int32)
            brec = leaf_best[b]
            eq = (sel == b) & sel_ok
            pos = jnp.argmax(eq).astype(jnp.int32)
            # ~committed[pos]: a left child reuses its parent's leaf id; its
            # slot holds the PARENT's children — never commit it twice.
            # t < L-1: the leaf budget binds mid-wave too.
            can = (active & (brec[0] > 0) & eq.any() & ~committed[pos]
                   & (t < L - 1))

            new_leaf = n_cur
            lrec = recs2[2 * pos]
            rrec = recs2[2 * pos + 1]
            ltot = totals[2 * pos]
            rtot = totals[2 * pos + 1]
            ptot = ltot + rtot
            pnum = -jnp.sign(ptot[0]) * jnp.maximum(jnp.abs(ptot[0]) - l1,
                                                    0.0)
            pout = pnum / jnp.maximum(ptot[1] + l2, 1e-15)
            pout = jnp.where(max_delta > 0,
                             jnp.clip(pout, -max_delta, max_delta), pout)
            nd = depth[b] + 1

            wb = jnp.where(can, b, L)
            wn = jnp.where(can, new_leaf, L)
            depth = depth.at[wb].set(nd).at[wn].set(nd)
            leaf_best = leaf_best.at[wb].set(lrec).at[wn].set(rrec)
            leaf_best = leaf_best.at[L].set(jnp.full(REC, neg_inf,
                                                     dtype=jnp.float32))
            row = jnp.concatenate([
                jnp.stack([b.astype(jnp.float32), pout,
                           nd.astype(jnp.float32),
                           jnp.where(can, 1.0, 0.0)]), brec])
            wt = jnp.where(can, t, rec_store.shape[0] - 1)
            rec_store = rec_store.at[wt].set(row)
            committed = committed.at[jnp.where(can, pos, K)].set(True)
            newids = newids.at[jnp.where(can, pos, K)].set(new_leaf)
            inc = jnp.where(can, 1, 0).astype(jnp.int32)
            return (leaf_best, depth, rec_store, n_cur + inc, t + inc,
                    committed, newids, active & can)

        rp0 = (leaf_best, depth, rec_store, n_cur, t,
               jnp.zeros(K + 1, bool), jnp.zeros(K + 1, jnp.int32),
               jnp.bool_(True))
        (leaf_best, depth, rec_store, n_cur, t, committed, newids,
         _) = jax.lax.fori_loop(0, K, replay_step, rp0)

        # --- commit side effects, all OUTSIDE the replay fori_loop (the
        # heavy [K, G, B, CH] pool writes and [N]-row updates run once per
        # wave, vectorized over the committed mask, not once per replay
        # step). Uncommitted leaves keep their old (start, count, pool)
        # entries — their ranges were only reordered internally.
        wbK = jnp.where(committed[:K], sel, L)       # parent keeps left
        wnK = jnp.where(committed[:K], newids[:K], L)  # new leaf = right
        pool = pool.at[wbK].set(histL).at[wnK].set(histR)
        mid_k = s_k + nl_k
        start = start.at[wnK].set(mid_k)
        count = count.at[wnK].set(nr_k).at[wbK].set(nl_k)
        if row_sharded:
            # replicated raw totals + GLOBAL counts ride with the pool so
            # later subtractions stay reduction-free
            tpool = tpool.at[wbK].set(totL_raw).at[wnK].set(totR_raw)
            count_g = count_g.at[wnK].set(nr_g).at[wbK].set(nl_g)

        # per-row leaf relabel via the same stacked masked matmul (position
        # >= split midpoint <=> right child, thanks to the partition)
        post = jnp.stack([committed[:K].astype(jnp.int32), newids[:K],
                          mid_k], axis=1)  # [K, 3]
        rowsP = rows_of(post)  # [N, 3]
        com_row = kvalid & (rowsP[:, 0] > 0.5)
        is_right = com_row & (pos >= rowsP[:, 2].astype(jnp.int32))
        leafcol = jnp.where(is_right, rowsP[:, 1], row_p[:, LEAF_COL])
        row_p = row_p.at[:, LEAF_COL].set(leafcol)
        if voting:
            return (bins_p, row_p, start, count, depth, leaf_best,
                    rec_store, pool, n_cur, t, hist_rows, tpool, count_g,
                    miss, n_waves)
        if data_par:
            return (bins_p, row_p, start, count, depth, leaf_best,
                    rec_store, pool, n_cur, t, hist_rows, tpool, count_g,
                    n_waves)
        return (bins_p, row_p, start, count, depth, leaf_best, rec_store,
                pool, n_cur, t, hist_rows, n_waves)

    def cond(carry):
        leaf_best, t = carry[5], carry[9]
        return (t < L - 1) & (jnp.max(leaf_best[:L, 0]) > 0)

    carry = (bins_p, row_p, start, count, depth, leaf_best, rec_store, pool,
             jnp.int32(1), jnp.int32(0), hist_rows)
    if row_sharded:
        carry = carry + (tpool, count_g)
    if voting:
        carry = carry + (root_miss,)
    carry = carry + (jnp.int32(0),)  # n_waves, last so indices above hold
    if L > 1:
        carry = jax.lax.while_loop(cond, wave, carry)
    row_p, rec_store, n_cur, hist_rows = carry[1], carry[6], carry[8], \
        carry[10]
    n_waves = carry[-1]
    if row_sharded:
        hist_rows = jax.lax.psum(hist_rows, "data")
    # undo the permutation without a TPU scatter: sort leaf ids by the
    # original-position column (both exact small ints in f32)
    _, leaf_sorted = jax.lax.sort_key_val(
        row_p[:, POS_COL].astype(jnp.int32),
        row_p[:, LEAF_COL].astype(jnp.int32))
    if voting:
        return (rec_store[:-1], leaf_sorted[:N], n_cur, hist_rows, n_waves,
                carry[13])
    return rec_store[:-1], leaf_sorted[:N], n_cur, hist_rows, n_waves


# bins/gh/leaf_id0 are donated: each is a fresh per-tree buffer (the
# learner COPIES bins_dev before the call) consumed by the wave loop, so
# XLA reuses their allocations for the loop carries instead of double
# buffering the two largest arrays. CPU backends ignore donation (warning
# suppressed by Python's default dedup filter).
# graftlint: disable=R11 -- this entry traces _grow_impl with the STATIC arg sharded=False, so every `if sharded:` collective is pruned from this trace; the sharded trace exists only inside make_sharded_grow_fn's shard_map, and test_sharded_device.py locks both paths bit-identical
@partial(jax.jit,
         static_argnames=("num_leaves", "num_bins", "max_depth", "quantized",
                          "batch", "bagged"),
         donate_argnums=(0, 1, 2))
def grow_tree_on_device(bins: jax.Array, gh: jax.Array, leaf_id0: jax.Array,
                        meta, tables: FeatureTables, params: jax.Array,
                        feature_mask: jax.Array,
                        num_leaves: int, num_bins: int, max_depth: int,
                        quantized: bool = False,
                        scale_vec: Optional[jax.Array] = None,
                        batch: int = 16, bagged: bool = False):
    """Grow one leaf-wise tree fully on device, K splits per histogram pass.

    bins [G, N], gh [N, 3] (bagged-out rows must have zero gh),
    leaf_id0 [N] (0 for in-bag rows, -1 otherwise; pass bagged=True when
    any row is bagged out so the initial compaction runs).
    quantized: gh is int8 (g_int, h_int, 1); histogram values stay exact
    ints (int32 pool) and re-enter float space via scale_vec at scan time —
    the on-device twin of the serial learner's quantized path.

    Rows-in-leaf waves over a leaf-contiguous permutation: each WAVE takes
    the top-K frontier leaves by gain, PARTITIONS each selected range into
    left|right in place (stable; safe even if the replay later declines the
    split — the range stays contiguous), histograms ONLY the smaller-child
    subranges via ragged tiles (K*CH channels), derives the larger children
    from the histogram pool by subtraction, then an on-device replay
    commits splits in exact best-first order until the global argmax falls
    outside the precomputed set (a child created this wave) — then the next
    wave recomputes. Semantics are EXACTLY the reference's leaf-wise
    best-first growth (serial_tree_learner.cpp:182): only histogram and
    partition WORK is speculative, never split decisions. Histogrammed rows
    per tree: N (root) + sum over waves of the selected smaller-child rows
    — <= ~4N in practice vs O(N * waves) for full-N masked waves.
    Returns (rec_store [L-1, STORE], leaf_id [N] in ORIGINAL row order,
    num_leaves_final, hist_rows — rows histogrammed, the perf counter,
    n_waves — while_loop trips, for the committed-vs-speculated telemetry).
    """
    return _grow_impl(bins, gh, leaf_id0, meta, tables, params, feature_mask,
                      scale_vec, num_leaves=num_leaves, num_bins=num_bins,
                      max_depth=max_depth, quantized=quantized, batch=batch,
                      bagged=bagged, sharded=False, narrow=False)


# graftlint: disable=untimed-hot-func -- builder only defines the shard_map/jit closure; real cost is lazy trace+compile inside the timed tree_device scope every caller runs under
def make_sharded_grow_fn(mesh, *, num_leaves: int, num_bins: int,
                         max_depth: int, quantized: bool, batch: int,
                         bagged: bool, narrow: bool = False,
                         mode: str = "data", top_k: int = 0,
                         exact_check: bool = False):
    """jit(shard_map) whole-tree grower over the "data" mesh axis: one
    dispatch per tree across every device.

    Three modes (the tree_learner config knob):

    mode="data" — rows sharded, scan feature-sharded by ONE psum_scatter.
    Call signature of the returned fn (all arrays GLOBAL, rows padded by
    the caller to a per-shard multiple of the wave tile unit so each
    device's shard needs no further padding):

        fn(bins [G, Np], gh [Np, CH], leaf_id0 [Np],
           gather_index [F_pad, Bmax], valid_slot [F_pad, Bmax],
           scan_meta (ScanMeta over [F_pad], feature-sharded),
           tables, params, feature_mask [F_pad], scale_vec [CH])

    bins/gh/leaf_id0/feature_mask arrive row-/feature-sharded on "data";
    gather tables, decision tables, params and scale_vec replicated.

    mode="voting" — rows sharded like "data", but gather tables, scan_meta
    and feature_mask arrive REPLICATED over the FULL padded feature axis
    (every device scans all features locally; only elected slices are
    reduced — PV-Tree, `top_k` nominations per shard). Two extra trailing
    scalar args (skew_rank, skew_wave — int32, -1 disarmed) drive the
    vote_skew fault hook, and the returned tuple gains a trailing
    replicated `miss` count (non-zero only when exact_check=True).

    mode="feature" — bins/gh/leaf_id0 arrive REPLICATED (and unpadded:
    the internal padding handles them exactly like the single-device
    path) while gather tables, scan_meta and feature_mask arrive
    feature-sharded; the only collective is the best-record all_gather.

    scale_vec must be a real array even when quantized=False (pass ones —
    it is ignored). Categorical splits are not supported here (the factory
    routes categorical configs to the host-driven learners). Returns the
    same (rec_store, leaf_id [Np] global original order, n_cur, hist_rows,
    n_waves) as grow_tree_on_device; rec_store/n_cur/hist_rows/n_waves are
    replicated.
    """
    from jax.sharding import PartitionSpec as P

    if mode == "voting":
        def body(bins, gh, leaf_id0, gather_index, valid_slot, scan_meta,
                 tables, params, feature_mask, scale_vec, skew_rank,
                 skew_wave):
            meta = ShardMeta(gather_index, valid_slot, scan_meta)
            return _grow_impl(bins, gh, leaf_id0, meta, tables, params,
                              feature_mask,
                              scale_vec if quantized else None,
                              num_leaves=num_leaves, num_bins=num_bins,
                              max_depth=max_depth, quantized=quantized,
                              batch=batch, bagged=bagged, sharded=True,
                              narrow=narrow, mode="voting", top_k=top_k,
                              exact_check=exact_check,
                              skew=(skew_rank, skew_wave))

        return jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(None, "data"), P("data"), P("data"), P(), P(),
                      P(), P(), P(), P(), P(), P(), P()),
            out_specs=(P(), P("data"), P(), P(), P(), P()),
            check_vma=False), donate_argnums=(0, 1, 2))

    if mode == "feature":
        def body(bins, gh, leaf_id0, gather_index, valid_slot, scan_meta,
                 tables, params, feature_mask, scale_vec):
            meta = ShardMeta(gather_index, valid_slot, scan_meta)
            return _grow_impl(bins, gh, leaf_id0, meta, tables, params,
                              feature_mask,
                              scale_vec if quantized else None,
                              num_leaves=num_leaves, num_bins=num_bins,
                              max_depth=max_depth, quantized=quantized,
                              batch=batch, bagged=bagged, sharded=True,
                              narrow=False, mode="feature")

        # no donation: the replicated row arrays arrive unpadded, so their
        # buffers never match the padded loop carries anyway (donating
        # them only buys a "not usable" warning)
        return jax.jit(shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), P(), P("data"), P("data"), P("data"),
                      P(), P(), P("data"), P()),
            out_specs=(P(), P(), P(), P(), P()),
            check_vma=False))

    def body(bins, gh, leaf_id0, gather_index, valid_slot, scan_meta,
             tables, params, feature_mask, scale_vec):
        meta = ShardMeta(gather_index, valid_slot, scan_meta)
        return _grow_impl(bins, gh, leaf_id0, meta, tables, params,
                          feature_mask,
                          scale_vec if quantized else None,
                          num_leaves=num_leaves, num_bins=num_bins,
                          max_depth=max_depth, quantized=quantized,
                          batch=batch, bagged=bagged, sharded=True,
                          narrow=narrow)

    return jax.jit(shard_map(
        body, mesh=mesh,
        in_specs=(P(None, "data"), P("data"), P("data"), P(), P(),
                  P("data"), P(), P(), P("data"), P()),
        out_specs=(P(), P("data"), P(), P(), P()),
        check_vma=False), donate_argnums=(0, 1, 2))


class DevicePartition:
    """Partition view over the final leaf-id vector (indices()/count()
    surface shared with ops.partition.RowPartition, plus the vectorized
    leaf_ids_dev fast path for score updates)."""

    def __init__(self, leaf_ids_dev: jax.Array, counts: Dict[int, int]) -> None:
        self._ids_dev = leaf_ids_dev
        self._ids: Optional[np.ndarray] = None
        self._order: Optional[np.ndarray] = None
        self._sorted: Optional[np.ndarray] = None
        self.counts = counts

    def leaf_ids_dev(self) -> jax.Array:
        return self._ids_dev

    @property
    def ids_host(self) -> np.ndarray:
        if self._ids is None:
            self._ids = np.asarray(self._ids_dev)
        return self._ids

    def count(self, leaf: int) -> int:
        return self.counts.get(leaf, 0)

    def indices(self, leaf: int) -> np.ndarray:
        # one stable argsort amortized over every leaf query (the old
        # per-leaf np.nonzero scan was O(N) PER LEAF under the serial
        # fallbacks and quantized leaf renewal). Stable sort keeps equal
        # ids in ascending position order, so each slice is bit-identical
        # to the nonzero scan's output.
        if self._order is None:
            ids = self.ids_host
            self._order = np.argsort(ids, kind="stable").astype(np.int32)
            self._sorted = ids[self._order]
        lo = np.searchsorted(self._sorted, leaf, side="left")
        hi = np.searchsorted(self._sorted, leaf, side="right")
        return self._order[lo:hi]


class _PendingTree(NamedTuple):
    """In-flight tree: dispatched on device, split log not yet replayed.

    `tree` is the (still empty) host Tree that finalize() fills IN PLACE —
    the async pipeline in models/gbdt.py appends it to the model list
    before the replay happens, so predictions through the model see the
    grown tree as soon as finalize() returns."""

    tree: Tree
    rec_store: jax.Array
    leaf_id: jax.Array
    hist_rows: jax.Array
    n_waves: jax.Array
    n_bag: int
    wave_k: int = 0  # wave width this tree was dispatched with


class DeviceTreeLearner(SerialTreeLearner):
    """Serial learner running the whole tree in one dispatch.

    train() splits into train_async() (dispatch + start the device->host
    copy of the split log, non-blocking) and finalize() (block on the log,
    replay it into the Tree, install the partition). The GBDT async
    pipeline overlaps tree t's device growth with the host replay of tree
    t-1 by holding the _PendingTree across iterations; the plain train()
    path chains the two immediately and is bit-identical."""

    def __init__(self, config, dataset) -> None:
        super().__init__(config, dataset)
        self.tables = _feature_tables(dataset, dataset.used_features)
        self._row_arange = np.arange(self.num_data, dtype=np.int32)
        # speculative-wave width: 2*K*3 histogram channels per pass.
        # 21 -> 126 channels (one 128-lane M-tile on the MXU); raise for
        # deeper amortization, lower if speculation hit-rate drops.
        self.wave = int(os.environ.get("LGBM_TPU_WAVE", "21"))
        # gain-adaptive wave width: `wave` is the ceiling, `wave_k` the
        # width actually dispatched; _record_wave_efficiency moves it one
        # power-of-two rung per tree from the observed commit rate
        # (LGBM_TPU_ADAPTIVE_WAVE=0 pins K to the ceiling). Rungs reuse
        # ops.partition.bucket_size so `batch` — a static jit arg of
        # grow_tree_on_device — takes at most ~log2(wave) distinct values
        # per run instead of recompiling on every width change.
        self._wave_cap = max(1, min(self.wave, int(config.num_leaves)))
        self._adaptive_wave = os.environ.get(
            "LGBM_TPU_ADAPTIVE_WAVE", "1").lower() not in (
                "0", "false", "off")
        self.wave_k = self._wave_cap
        self._gh_bf16 = (not self.quantized) and os.environ.get(
            "LGBM_TPU_GH_BF16", "").lower() in ("1", "true", "on")
        if os.environ.get("LGBM_TPU_GH_BF16", "").lower() in (
                "1", "true", "on"):
            if self.quantized:
                Log.warning("LGBM_TPU_GH_BF16=1 is ignored with "
                            "use_quantized_grad (the int8 payload is "
                            "already narrow)")
            else:
                Log.warning(
                    "LGBM_TPU_GH_BF16=1: gh wave-carry payload packed as "
                    "bf16 — bit-identity with the f32 path is NOT "
                    "guaranteed (bf16 keeps 8 mantissa bits)")

    def snapshot_state(self) -> dict:
        st = super().snapshot_state()
        st["bins_dtype"] = str(self.bins_dev.dtype)
        return st

    def restore_snapshot_state(self, st: dict) -> None:
        want = st.get("bins_dtype")
        if want is not None and want != str(self.bins_dev.dtype):
            Log.fatal("Checkpoint was captured with a %s bin plane but the "
                      "resume run built %s (LGBM_TPU_BINS_I32 mismatch?) — "
                      "histogram accumulation order would differ, breaking "
                      "bit-identical resume", want, self.bins_dev.dtype)
        super().restore_snapshot_state(st)

    def _payload_cols(self) -> int:
        """Payload columns of the wave carry: gh channels (bf16-packed in
        pairs when opted in) + position + leaf id."""
        n_gh = 2 if self._gh_bf16 else 3
        return n_gh + 2

    def _record_carry_bytes(self) -> None:
        """Gauges for the analytic bandwidth model (docs/PERF_NOTES.md,
        executable form in perfmodel.py): HBM bytes of the per-wave loop
        carry, bytes the ragged histogram kernel streams per row, and the
        gain-scan read volume per wave — perfmodel.attribution() reads
        these back to attribute the fused `tree_device` wall."""
        from .. import perfmodel
        from ..ops.compact_pallas import COMPACT_TILE
        from ..ops.hist_pallas import DEFAULT_TILE_ROWS
        unit = max(DEFAULT_TILE_ROWS, COMPACT_TILE)
        G = self.bins_dev.shape[0]
        plane_b = self.bins_dev.dtype.itemsize
        plane_b = plane_b if plane_b == 1 else 4
        global_timer.set_count(
            "device_carry_bytes_per_wave",
            perfmodel.carry_bytes_per_wave(
                self.num_data, G, plane_b, unit,
                payload_cols=self._payload_cols()))
        global_timer.set_count(
            "device_hist_bytes_per_row",
            perfmodel.hist_bytes_per_row(G, plane_b))
        # the replay scan sweeps the [K, G, Bpad, CH] pool block and writes
        # the [2K, G, REC] best-record store; the pool is 4-byte in both the
        # float and quantized (int32) regimes
        from ..ops import scan_pallas
        global_timer.set_count(
            "device_scan_bytes_per_wave",
            perfmodel.scan_bytes_per_wave(self.wave_k, G,
                                          self.group_bin_padded,
                                          fused=scan_pallas.use_scan_pallas()))

    def train(self, gh_ext: jax.Array,
              bag_indices: Optional[np.ndarray] = None) -> Tree:
        return self.finalize(self.train_async(gh_ext, bag_indices))

    def train_async(self, gh_ext: jax.Array,
                    bag_indices: Optional[np.ndarray] = None) -> _PendingTree:
        cfg = self.config
        num_leaves = cfg.num_leaves
        if self.quantized:
            gh_ext = self._prepare_gh(gh_ext)  # int8 rows + scales
        gh = gh_ext[:-1]
        if isinstance(bag_indices, DeviceBag):
            # device-resident bag (GOSS): the mask never touches the host —
            # same where() ops as the host-index branch below, so the masked
            # gh and leaf seeds are bit-identical for an identical bag
            mask = bag_indices.mask
            leaf_id0 = jnp.where(mask, 0, -1).astype(jnp.int32)
            gh = jnp.where(mask[:, None], gh, jnp.zeros((), gh.dtype))
            n_bag = bag_indices.n_bag
        elif bag_indices is not None:
            in_bag = np.zeros(self.num_data, dtype=bool)
            in_bag[np.asarray(bag_indices, dtype=np.int64)] = True
            leaf_id0 = jnp.asarray(np.where(in_bag, 0, -1), dtype=jnp.int32)
            gh = jnp.where(jnp.asarray(in_bag, dtype=jnp.bool_)[:, None], gh,
                           jnp.zeros((), gh.dtype))
            n_bag = len(bag_indices)
        else:
            leaf_id0 = jnp.zeros(self.num_data, dtype=jnp.int32)
            n_bag = self.num_data

        if self.col_sampler.active:
            fmask = jnp.asarray(self.col_sampler.reset_by_tree(),
                                dtype=jnp.bool_)
        else:
            fmask = jnp.ones(len(self.meta.real_feature), dtype=bool)
        self._record_carry_bytes()
        grow = sanitize.guard(
            grow_tree_on_device, (0, 1, 2),
            "grow_tree_on_device (treelearner/device.py train_async)")
        if telemetry.enabled():
            # one-time dispatch capture: perfmodel AOT-relowers this exact
            # signature for cost_analysis() (dict-check no-op afterwards)
            perfmodel.note_dispatch(
                "grow_fused", grow_tree_on_device,
                self.bins_dev, gh, leaf_id0, self.meta, self.tables,
                self.params_dev, fmask, num_leaves, self.group_bin_padded,
                cfg.max_depth, quantized=self.quantized,
                scale_vec=self._scale_vec, batch=self.wave_k,
                bagged=bag_indices is not None)
        with global_timer.scope("tree_device"):
            # bins_dev is COPIED per tree: grow_tree_on_device donates its
            # first three args (gh and leaf_id0 are already fresh buffers)
            rec_store, leaf_id, _, hist_rows, n_waves = grow(
                jnp.copy(self.bins_dev), gh, leaf_id0, self.meta,
                self.tables, self.params_dev, fmask, num_leaves,
                self.group_bin_padded,
                cfg.max_depth, quantized=self.quantized,
                scale_vec=self._scale_vec, batch=self.wave_k,
                bagged=bag_indices is not None)
        # start the device->host copies without blocking; finalize() (maybe
        # a full iteration later, under the async pipeline) pays no wait if
        # the transfer already landed
        for arr in (rec_store, leaf_id, hist_rows, n_waves):
            start = getattr(arr, "copy_to_host_async", None)
            if start is not None:
                start()
        return _PendingTree(Tree(num_leaves), rec_store, leaf_id, hist_rows,
                            n_waves, n_bag, wave_k=self.wave_k)

    def finalize(self, pending: _PendingTree) -> Tree:
        cfg = self.config
        tree = pending.tree
        with global_timer.scope("tree_replay"):
            rec_np = np.asarray(pending.rec_store)  # the one blocking pull
        leaf_id = pending.leaf_id
        self.last_hist_rows = int(pending.hist_rows)
        global_timer.add_count("device_hist_rows", self.last_hist_rows)

        counts: Dict[int, int] = {0: int(pending.n_bag)}
        for t in range(rec_np.shape[0]):
            row = rec_np[t]
            if row[3] < 0.5:  # valid flag: growth stopped here
                break
            leaf = int(row[0])
            split = SplitInfo.from_packed(row[4:])
            dense_f = split.feature
            real_f = self.meta.real_feature[dense_f]
            mapper = self.dataset.mappers[real_f]
            tree.split(
                leaf=leaf, feature_inner=dense_f, real_feature=real_f,
                threshold_bin=split.threshold_bin,
                threshold_double=mapper.bin_to_value(split.threshold_bin),
                default_left=split.default_left,
                missing_type=mapper.missing_type, gain=split.gain,
                left_value=split.left_output, right_value=split.right_output,
                left_count=split.left_count, right_count=split.right_count,
                left_weight=split.left_sum_h, right_weight=split.right_sum_h,
                parent_value=float(row[1]))
            counts[leaf] = split.left_count
            counts[tree.num_leaves - 1] = split.right_count

        self._record_wave_efficiency(pending, tree)
        self.partition = DevicePartition(leaf_id, counts)
        if tree.num_leaves == 1:
            tree.as_constant_tree(0.0)
        elif self.quantized and cfg.quant_train_renew_leaf:
            self._renew_quantized_leaves_device(tree, leaf_id)
        return tree

    def _record_wave_efficiency(self, pending: _PendingTree,
                                tree: Tree) -> None:
        """Committed-vs-speculated wave accounting + the gain-adaptive
        wave-width controller: each wave partitions + histograms K candidate
        splits but the replay commits only as many as stay globally
        best-first — the measured ratio drives the next tree's K
        (ROADMAP item 1; split decisions are K-invariant, so only the
        amount of speculative work changes, never the model)."""
        from .. import telemetry, tracing
        n_waves = int(pending.n_waves)
        wave_k = pending.wave_k or self.wave_k
        committed = tree.num_leaves - 1
        speculated = n_waves * wave_k
        commit_rate = committed / speculated if speculated else 1.0
        global_timer.add_count("device_waves", n_waves)
        global_timer.add_count("wave_splits_committed", committed)
        global_timer.add_count("wave_splits_speculated", speculated)
        # flight-recorder mirror: plain already-computed ints, O(1), no
        # sync — a postmortem sees the last trees' wave shape even with
        # telemetry off
        tracing.note("tree_wave", waves=n_waves, committed=committed,
                     speculated=speculated)
        if telemetry.enabled():
            telemetry.emit(
                "tree_wave", waves=n_waves, wave_width=wave_k,
                committed=committed, speculated=speculated,
                efficiency=round(commit_rate, 4) if speculated else 1.0,
                hist_rows=self.last_hist_rows,
                ici_bytes_per_wave=int(global_timer.counters.get(
                    "device_ici_bytes_per_wave", 0)),
                carry_bytes_per_wave=int(global_timer.counters.get(
                    "device_carry_bytes_per_wave", 0)))
        new_k = self._next_wave_k(commit_rate)
        if telemetry.enabled() and new_k != self.wave_k:
            telemetry.emit("wave_ctl", wave_k=new_k, prev_k=self.wave_k,
                           wave_commit_rate=round(commit_rate, 4))
        self.wave_k = new_k
        global_timer.set_count("wave_k", self.wave_k)

    def _next_wave_k(self, commit_rate: float) -> int:
        """One power-of-two rung per tree: commit rate under 50% means the
        replay declined half the partition+histogram work a wave paid for —
        halve K; above 90% speculation is nearly free — grow back toward the
        ceiling. Rungs come from ops.partition.bucket_size, so the static
        `batch` jit arg takes at most ~log2(wave) distinct values per run
        (pinned by the recompile-watcher test in test_device_learner.py)."""
        if not self._adaptive_wave:
            return self.wave_k
        k = self.wave_k
        if commit_rate < _WAVE_SHRINK_RATE and k > 1:
            return min(bucket_size(max(1, k // 2), minimum=1),
                       self._wave_cap)
        if commit_rate > _WAVE_GROW_RATE and k < self._wave_cap:
            return min(bucket_size(k + 1, minimum=1), self._wave_cap)
        return k

    def _renew_quantized_leaves_device(self, tree: Tree,
                                       leaf_id: jax.Array) -> None:
        """True-gradient leaf renewal in ONE scatter-add dispatch over the
        on-device leaf-id vector (no per-leaf host scans; no frontier bounds
        here — the factory routes monotone configs to the host learner)."""
        cfg = self.config
        L = tree.num_leaves
        ghf = self._gh_float[:-1, :2]
        ids = jnp.where(leaf_id >= 0, leaf_id, L)  # bagged-out -> dump row
        sums = np.asarray(
            jnp.zeros((L + 1, 2), jnp.float32).at[ids].add(ghf))
        for leaf in range(L):
            out = _leaf_output_host(float(sums[leaf, 0]),
                                    float(sums[leaf, 1]),
                                    cfg.lambda_l1, cfg.lambda_l2,
                                    cfg.max_delta_step)
            tree.set_leaf_output(leaf, out)
