"""Whole-tree-on-device learner: one XLA dispatch per tree.

The host-driven SerialTreeLearner pays per-split dispatch latency (3 calls +
2 blocking scalar pulls), which dominates wall-clock on a remote-attached
TPU. This learner instead grows the ENTIRE tree inside a single jitted
function: a `lax.while_loop` over speculative WAVES carrying

    leaf_id    [N]          per-row leaf assignment (bagged-out rows = -1)
    leaf_best  [L+1,R]      per-leaf packed best-split records
    depth      [L+1]        per-leaf depth
    rec_store  [L,R+4]      the split log the host replays into a Tree

Per wave: top-K frontier leaves by gain -> BOTH children's histograms for
all K in ONE 2*K*3-channel masked full-N one-hot MXU contraction (Pallas,
ops/hist_pallas.py) -> 2K split scans -> an on-device replay that commits
splits in exact best-first order until the argmax needs a leaf whose
children were not precomputed (see grow_tree_on_device's docstring). All
shapes are static; the only host traffic per TREE is the split log + final
leaf ids.

Design notes, each measured on hardware:
  * No histogram pool, no subtraction trick: with full-N masked histograms
    a child costs the same either way, and a [L+1, G, B, 3] pool carried
    through the loop defeats XLA's in-place buffer analysis once a Pallas
    call sits in the body (~10 ms/split of copies).
  * Row routing (which leaf/slot owns a row, split decision fields, commit
    application) is all compares and masked [N,K]@[K,F] matmuls — TPU
    gathers serialize, elementwise compares and matmuls vectorize.
  * The wave replay keeps the reference's leaf-wise semantics bit-exact
    (tree.h best-first; growth stops when the best gain <= 0; masked no-op
    steps write to dump rows so the loop body stays branch-free).

Counterpart of SerialTreeLearner::Train + CUDASingleGPUTreeLearner::Train
(serial_tree_learner.cpp:182, cuda_single_gpu_tree_learner.cpp:169-360).
"""
from __future__ import annotations

import os
from functools import partial
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.tree import Tree
from ..ops.histogram import build_histogram
from ..ops.split import SPLIT_FIELDS, SplitInfo, find_best_split
from ..utils.log import Log
from ..utils.timer import global_timer
from .serial import SerialTreeLearner, _leaf_output_host

REC = len(SPLIT_FIELDS)
# rec_store row: [leaf, parent_output, depth, valid] + SPLIT_FIELDS
STORE = REC + 4


class FeatureTables(NamedTuple):
    """Per-dense-feature decision fields for device-side partitioning."""

    group: jax.Array  # [F] int32 group row in the bin matrix
    lo: jax.Array  # [F] int32 EFB group-bin range
    hi: jax.Array  # [F] int32
    default_bin: jax.Array  # [F] int32
    nbins: jax.Array  # [F] int32
    missing_type: jax.Array  # [F] int32
    is_efb: jax.Array  # [F] bool


def _feature_tables(dataset, used_features) -> FeatureTables:
    F = len(used_features)
    group = np.zeros(F, dtype=np.int32)
    lo = np.zeros(F, dtype=np.int32)
    hi = np.zeros(F, dtype=np.int32)
    db = np.zeros(F, dtype=np.int32)
    nb = np.zeros(F, dtype=np.int32)
    mt = np.zeros(F, dtype=np.int32)
    ie = np.zeros(F, dtype=bool)
    for k, f in enumerate(used_features):
        m = dataset.mappers[f]
        gi, mi = dataset.feature_to_group[f]
        fg = dataset.groups[gi]
        l, h, _ = fg.feature_bin_range(mi)
        group[k], lo[k], hi[k] = gi, l, h
        db[k], nb[k], mt[k] = m.default_bin, m.num_bin, m.missing_type
        ie[k] = fg.is_multi
    return FeatureTables(*(jnp.asarray(a) for a in (group, lo, hi, db, nb,
                                                    mt, ie)))


from ..common import MISSING_NAN, MISSING_ZERO  # noqa: E402


def _decide_go_left(gb, thresh, default_left, missing_type, default_bin,
                    nbins, efb_lo, efb_hi, is_efb):
    """NumericalDecisionInner on raw group bins with traced scalar fields
    (the per-node twin of ops.partition.split_decision_bins)."""
    gb = gb.astype(jnp.int32)
    in_range = (gb >= efb_lo) & (gb < efb_hi)
    shifted = gb - efb_lo
    natural = shifted + (shifted >= default_bin).astype(jnp.int32)
    fbin = jnp.where(is_efb, jnp.where(in_range, natural, default_bin), gb)
    is_missing = jnp.where(
        missing_type == MISSING_NAN, fbin == nbins - 1,
        jnp.where(missing_type == MISSING_ZERO, fbin == default_bin, False))
    return jnp.where(is_missing, default_left, fbin <= thresh)


@partial(jax.jit,
         static_argnames=("num_leaves", "num_bins", "max_depth", "quantized",
                          "batch"))
def grow_tree_on_device(bins: jax.Array, gh: jax.Array, leaf_id0: jax.Array,
                        meta, tables: FeatureTables, params: jax.Array,
                        feature_mask: jax.Array,
                        num_leaves: int, num_bins: int, max_depth: int,
                        quantized: bool = False,
                        scale_vec: Optional[jax.Array] = None,
                        batch: int = 16):
    """Grow one leaf-wise tree fully on device, K splits per histogram pass.

    bins [G, N], gh [N, 3] (bagged-out rows must have zero gh),
    leaf_id0 [N] (0 for in-bag rows, -1 otherwise).
    quantized: gh is int8 (g_int, h_int, 1); histograms accumulate exact
    int32 on the MXU and re-enter float space via scale_vec at scan time —
    the on-device twin of the serial learner's quantized path.

    Frontier-batched speculative histograms: each WAVE takes the top-K
    frontier leaves by gain, computes BOTH children's histograms for all of
    them in ONE full-N contraction with 2*K*3 gh channels, then an on-device
    replay commits splits in exact best-first order until the global argmax
    falls outside the precomputed set (a child created this wave) — then the
    next wave recomputes. Semantics are EXACTLY the reference's leaf-wise
    best-first growth (serial_tree_learner.cpp:182): only histogram WORK is
    speculative, never split decisions. The win: the [TN, B] one-hot — the
    dominant VPU/VMEM cost of a full-N histogram — is built once per K
    splits instead of once per split, and K*6 output channels fill the MXU
    lane dim that a single split's 6 channels leave 95% idle.
    Returns (rec_store [L-1, STORE], leaf_id [N], num_leaves_final).
    """
    L = num_leaves
    G, N = bins.shape
    CH = gh.shape[1]
    K = max(1, min(batch, L))
    min_data, min_hess = params[2], params[3]
    neg_inf = jnp.float32(-jnp.inf)
    gh_dtype = jnp.int8 if quantized else jnp.float32
    zero_gh = jnp.zeros((), gh_dtype)
    from ..ops.hist_pallas import DEFAULT_TILE_ROWS, hist_force_f32
    from ..ops.histogram import _use_pallas

    # pad rows ONCE to the histogram tile size so the per-wave kernel pads
    # (a [N, 2K*CH] copy each) vanish; padded rows carry leaf_id -1 and
    # zero gh, contributing nothing anywhere
    Np = -(-N // DEFAULT_TILE_ROWS) * DEFAULT_TILE_ROWS
    if Np != N:
        bins = jnp.pad(bins, ((0, 0), (0, Np - N)), constant_values=0)
        gh = jnp.pad(gh, ((0, Np - N), (0, 0)))
        leaf_id0 = jnp.pad(leaf_id0, (0, Np - N), constant_values=-1)
    # in-kernel slot expansion is the default on TPU (the XLA-side [N, 2K*CH]
    # materialization profiled at ~18 ms/wave); LGBM_TPU_HIST_SLOTS=0 opts out
    slots_kernel = _use_pallas() and os.environ.get(
        "LGBM_TPU_HIST_SLOTS", "1").lower() not in ("0", "false", "off")

    def masked_hist(mask):
        ghm = jnp.where(mask[:, None], gh, zero_gh)
        return build_histogram(bins, ghm, num_bins,
                               compute_dtype=gh_dtype)

    def scan_hist(hist):
        if quantized:
            return hist.astype(jnp.float32) * scale_vec
        return hist

    def hist_totals(hist):
        if quantized:
            return hist[0].sum(axis=0).astype(jnp.float32) * scale_vec
        return hist[0].sum(axis=0)

    def guard(rec, cnt, sum_h, depth):
        """BeforeFindBestSplit gates (serial_tree_learner.cpp:343)."""
        ok = (cnt >= 2 * min_data) & (sum_h >= 2 * min_hess)
        if max_depth > 0:
            ok &= depth < max_depth
        return rec.at[0].set(jnp.where(ok, rec[0], neg_inf))

    root_mask = leaf_id0 == 0
    root_hist = masked_hist(root_mask)
    root_tot = hist_totals(root_hist)

    depth = jnp.zeros(L + 1, jnp.int32)
    leaf_best = jnp.full((L + 1, REC), neg_inf, jnp.float32)
    root_rec = guard(find_best_split(scan_hist(root_hist), root_tot, meta,
                                     params, feature_mask),
                     root_tot[2], root_tot[1], jnp.int32(0))
    leaf_best = leaf_best.at[0].set(root_rec)
    # one extra dump row at the end for masked-out replay writes
    rec_store = jnp.zeros((max(L - 1, 1) + 1, STORE), jnp.float32)

    l1, l2, max_delta = params[0], params[1], params[5]

    def wave(carry):
        leaf_id, depth, leaf_best, rec_store, n_cur, t = carry
        gains = leaf_best[:L, 0]
        sel_gain, sel = jax.lax.top_k(gains, K)  # [K] distinct leaves
        sel = sel.astype(jnp.int32)
        sel_ok = sel_gain > 0

        # --- per-selected-leaf split fields
        recs_sel = leaf_best[sel]  # [K, REC]
        f_k = jnp.maximum(recs_sel[:, 1].astype(jnp.int32), 0)
        thresh_k = recs_sel[:, 2].astype(jnp.int32)
        defl_k = recs_sel[:, 3] > 0.5

        # --- per-row wave slot: which selected leaf (if any) owns this row.
        # The [N, K] compare stays VECTORIZED on the VPU; a [L+1]-table
        # gather formulation measured ~20% slower end to end (TPU gathers
        # serialize, elementwise compares do not).
        match = (leaf_id[:, None] == sel[None, :]) & sel_ok[None, :]  # [N, K]
        kvalid = match.any(axis=1)
        kidx = jnp.argmax(match, axis=1).astype(jnp.int32)  # [N], junk if !kvalid

        # per-row split fields as ONE masked [N,K]@[K,9] matmul over the
        # match matrix — vectorized VPU/MXU work; jnp.take gathers here
        # measured far slower (TPU gathers serialize), and separate
        # per-field matvecs would re-read the [N, K] matrix from HBM nine
        # times. Field values are small ints, exact in f32. HIGHEST
        # precision: default TPU matmul rounds operands to bf16 (8 mantissa
        # bits), which would corrupt integer fields > 256 — group ids, new
        # leaf ids, bin offsets.
        matchf = match.astype(jnp.float32)

        def rows_of(per_k_fields):  # [K, F] -> [N, F]
            return jax.lax.dot(matchf, per_k_fields.astype(jnp.float32),
                               precision=jax.lax.Precision.HIGHEST)

        fields = jnp.stack([
            tables.group[f_k], thresh_k, defl_k.astype(jnp.int32),
            tables.missing_type[f_k], tables.default_bin[f_k],
            tables.nbins[f_k], tables.lo[f_k], tables.hi[f_k],
            tables.is_efb[f_k].astype(jnp.int32),
        ], axis=1)  # [K, 9]
        rowsF = rows_of(fields)  # [N, 9]
        ri = rowsF.astype(jnp.int32)
        grp_row = ri[:, 0]
        # bins[grp_row[n], n] without a gather: compare-select over the G
        # group rows (G*N elementwise beats an N-sized row-varying gather)
        gb_row = jnp.sum(
            jnp.where(jnp.arange(G)[:, None] == grp_row[None, :], bins, 0),
            axis=0, dtype=jnp.int32)
        go_left = _decide_go_left(
            gb_row, ri[:, 1], rowsF[:, 2] > 0.5, ri[:, 3], ri[:, 4],
            ri[:, 5], ri[:, 6], ri[:, 7], rowsF[:, 8] > 0.5)

        # --- one histogram pass: channel block 2k+0 = left of sel[k],
        #     2k+1 = right; rows outside the selection hit the dump slot
        slot2 = jnp.where(kvalid, kidx * 2 + (1 - go_left.astype(jnp.int32)),
                          2 * K)  # [N] in [0, 2K]
        if slots_kernel:
            # in-kernel slot expansion: no [N, 2K*CH] HBM matrix (the XLA
            # materialization profiled at ~18 ms/wave at 1M rows)
            from ..ops.hist_pallas import pallas_histogram_slots

            histK = pallas_histogram_slots(
                bins.astype(jnp.int32), gh, slot2, num_bins, 2 * K,
                quantized=quantized, f32=hist_force_f32())
        else:
            # flat 2D build: column c belongs to slot c//CH, channel c%CH
            # (profiled: the 3D broadcast+reshape fused badly, and a bf16
            # output made the fusion 2x SLOWER — keep operand dtype)
            col_slot = jnp.arange(2 * K * CH) // CH  # [2K*CH]
            ghK = jnp.where(slot2[:, None] == col_slot[None, :],
                            jnp.tile(gh, (1, 2 * K)), zero_gh)
            histK = build_histogram(bins, ghK, num_bins,
                                    compute_dtype=gh_dtype)  # [G, B, 2K*CH]
        hists = histK.reshape(G, num_bins, 2 * K, CH)
        hists = jnp.moveaxis(hists, 2, 0)  # [2K, G, B, CH]
        totals = hists[:, 0].sum(axis=1)  # [2K, B, CH] bins-summed -> [2K, CH]
        if quantized:
            totals = totals.astype(jnp.float32) * scale_vec[None, :]
        child_depth = depth[sel] + 1  # [K]
        depth2 = jnp.repeat(child_depth, 2)  # [2K]
        recs2 = jax.vmap(
            lambda h, tot: find_best_split(scan_hist(h), tot, meta, params,
                                           feature_mask))(hists, totals)
        recs2 = jax.vmap(guard)(recs2, totals[:, 2], totals[:, 1], depth2)

        # --- exact best-first replay over the precomputed set
        def replay_step(_, rp):
            (leaf_best, depth, rec_store, n_cur, t, committed, newids,
             active) = rp
            cur = leaf_best[:L, 0]
            b = jnp.argmax(cur).astype(jnp.int32)
            brec = leaf_best[b]
            eq = (sel == b) & sel_ok
            pos = jnp.argmax(eq).astype(jnp.int32)
            # ~committed[pos]: a left child reuses its parent's leaf id; its
            # slot holds the PARENT's children — never commit it twice.
            # t < L-1: the leaf budget binds mid-wave too.
            can = (active & (brec[0] > 0) & eq.any() & ~committed[pos]
                   & (t < L - 1))

            new_leaf = n_cur
            lrec = recs2[2 * pos]
            rrec = recs2[2 * pos + 1]
            ltot = totals[2 * pos]
            rtot = totals[2 * pos + 1]
            ptot = ltot + rtot
            pnum = -jnp.sign(ptot[0]) * jnp.maximum(jnp.abs(ptot[0]) - l1,
                                                    0.0)
            pout = pnum / jnp.maximum(ptot[1] + l2, 1e-15)
            pout = jnp.where(max_delta > 0,
                             jnp.clip(pout, -max_delta, max_delta), pout)
            nd = depth[b] + 1

            wb = jnp.where(can, b, L)
            wn = jnp.where(can, new_leaf, L)
            depth = depth.at[wb].set(nd).at[wn].set(nd)
            leaf_best = leaf_best.at[wb].set(lrec).at[wn].set(rrec)
            leaf_best = leaf_best.at[L].set(jnp.full(REC, neg_inf))
            row = jnp.concatenate([
                jnp.stack([b.astype(jnp.float32), pout,
                           nd.astype(jnp.float32),
                           jnp.where(can, 1.0, 0.0)]), brec])
            wt = jnp.where(can, t, rec_store.shape[0] - 1)
            rec_store = rec_store.at[wt].set(row)
            committed = committed.at[jnp.where(can, pos, K)].set(True)
            newids = newids.at[jnp.where(can, pos, K)].set(new_leaf)
            inc = jnp.where(can, 1, 0).astype(jnp.int32)
            return (leaf_best, depth, rec_store, n_cur + inc, t + inc,
                    committed, newids, active & can)

        rp0 = (leaf_best, depth, rec_store, n_cur, t,
               jnp.zeros(K + 1, bool), jnp.zeros(K + 1, jnp.int32),
               jnp.bool_(True))
        (leaf_best, depth, rec_store, n_cur, t, committed, newids,
         _) = jax.lax.fori_loop(0, K, replay_step, rp0)

        # --- apply all committed partitions in one vectorized pass
        # (one stacked masked matmul again, not [K]-table gathers)
        post = jnp.stack([committed[:K].astype(jnp.int32), newids[:K]],
                         axis=1)  # [K, 2]
        rowsP = rows_of(post)  # [N, 2]
        com_row = kvalid & (rowsP[:, 0] > 0.5)
        rid_row = rowsP[:, 1].astype(jnp.int32)
        leaf_id = jnp.where(com_row & ~go_left, rid_row, leaf_id)
        return leaf_id, depth, leaf_best, rec_store, n_cur, t

    def cond(carry):
        _, _, leaf_best, _, _, t = carry
        return (t < L - 1) & (jnp.max(leaf_best[:L, 0]) > 0)

    carry = (leaf_id0, depth, leaf_best, rec_store, jnp.int32(1),
             jnp.int32(0))
    if L > 1:
        carry = jax.lax.while_loop(cond, wave, carry)
    leaf_id, _, _, rec_store, n_cur, _ = carry
    return rec_store[:-1], leaf_id[:N], n_cur


class DevicePartition:
    """Partition view over the final leaf-id vector (indices()/count()
    surface shared with ops.partition.RowPartition, plus the vectorized
    leaf_ids_dev fast path for score updates)."""

    def __init__(self, leaf_ids_dev: jax.Array, counts: Dict[int, int]) -> None:
        self._ids_dev = leaf_ids_dev
        self._ids: Optional[np.ndarray] = None
        self.counts = counts

    def leaf_ids_dev(self) -> jax.Array:
        return self._ids_dev

    @property
    def ids_host(self) -> np.ndarray:
        if self._ids is None:
            self._ids = np.asarray(self._ids_dev)
        return self._ids

    def count(self, leaf: int) -> int:
        return self.counts.get(leaf, 0)

    def indices(self, leaf: int) -> np.ndarray:
        return np.nonzero(self.ids_host == leaf)[0].astype(np.int32)


class DeviceTreeLearner(SerialTreeLearner):
    """Serial learner running the whole tree in one dispatch."""

    def __init__(self, config, dataset) -> None:
        super().__init__(config, dataset)
        self.tables = _feature_tables(dataset, dataset.used_features)
        self._row_arange = np.arange(self.num_data, dtype=np.int32)
        # speculative-wave width: 2*K*3 histogram channels per pass.
        # 21 -> 126 channels (one 128-lane M-tile on the MXU); raise for
        # deeper amortization, lower if speculation hit-rate drops.
        self.wave = int(os.environ.get("LGBM_TPU_WAVE", "21"))

    def train(self, gh_ext: jax.Array,
              bag_indices: Optional[np.ndarray] = None) -> Tree:
        cfg = self.config
        num_leaves = cfg.num_leaves
        tree = Tree(num_leaves)
        if self.quantized:
            gh_ext = self._prepare_gh(gh_ext)  # int8 rows + scales
        gh = gh_ext[:-1]
        if bag_indices is not None:
            in_bag = np.zeros(self.num_data, dtype=bool)
            in_bag[np.asarray(bag_indices, dtype=np.int64)] = True
            leaf_id0 = jnp.asarray(np.where(in_bag, 0, -1).astype(np.int32))
            gh = jnp.where(jnp.asarray(in_bag)[:, None], gh,
                           jnp.zeros((), gh.dtype))
        else:
            leaf_id0 = jnp.zeros(self.num_data, dtype=jnp.int32)

        if self.col_sampler.active:
            fmask = jnp.asarray(self.col_sampler.reset_by_tree())
        else:
            fmask = jnp.ones(len(self.meta.real_feature), dtype=bool)
        with global_timer.scope("tree_device"):
            rec_store, leaf_id, _ = grow_tree_on_device(
                self.bins_dev, gh, leaf_id0, self.meta, self.tables,
                self.params_dev, fmask, num_leaves, self.group_bin_padded,
                cfg.max_depth, quantized=self.quantized,
                scale_vec=self._scale_vec, batch=self.wave)
            rec_np = np.asarray(rec_store)  # the one transfer per tree

        counts: Dict[int, int] = {0: int(self.num_data if bag_indices is None
                                         else len(bag_indices))}
        for t in range(rec_np.shape[0]):
            row = rec_np[t]
            if row[3] < 0.5:  # valid flag: growth stopped here
                break
            leaf = int(row[0])
            split = SplitInfo.from_packed(row[4:])
            dense_f = split.feature
            real_f = self.meta.real_feature[dense_f]
            mapper = self.dataset.mappers[real_f]
            tree.split(
                leaf=leaf, feature_inner=dense_f, real_feature=real_f,
                threshold_bin=split.threshold_bin,
                threshold_double=mapper.bin_to_value(split.threshold_bin),
                default_left=split.default_left,
                missing_type=mapper.missing_type, gain=split.gain,
                left_value=split.left_output, right_value=split.right_output,
                left_count=split.left_count, right_count=split.right_count,
                left_weight=split.left_sum_h, right_weight=split.right_sum_h,
                parent_value=float(row[1]))
            counts[leaf] = split.left_count
            counts[tree.num_leaves - 1] = split.right_count

        self.partition = DevicePartition(leaf_id, counts)
        if tree.num_leaves == 1:
            tree.as_constant_tree(0.0)
        elif self.quantized and cfg.quant_train_renew_leaf:
            self._renew_quantized_leaves_device(tree, leaf_id)
        return tree

    def _renew_quantized_leaves_device(self, tree: Tree,
                                       leaf_id: jax.Array) -> None:
        """True-gradient leaf renewal in ONE scatter-add dispatch over the
        on-device leaf-id vector (no per-leaf host scans; no frontier bounds
        here — the factory routes monotone configs to the host learner)."""
        cfg = self.config
        L = tree.num_leaves
        ghf = self._gh_float[:-1, :2]
        ids = jnp.where(leaf_id >= 0, leaf_id, L)  # bagged-out -> dump row
        sums = np.asarray(
            jnp.zeros((L + 1, 2), jnp.float32).at[ids].add(ghf))
        for leaf in range(L):
            out = _leaf_output_host(float(sums[leaf, 0]),
                                    float(sums[leaf, 1]),
                                    cfg.lambda_l1, cfg.lambda_l2,
                                    cfg.max_delta_step)
            tree.set_leaf_output(leaf, out)
