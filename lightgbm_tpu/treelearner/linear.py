"""Per-leaf linear model fitting for linear trees.

Counterpart of LinearTreeLearner::CalculateLinear
(src/treelearner/linear_tree_learner.cpp:180-392): after a tree is grown,
each leaf gets a linear model over the NUMERICAL features on its branch
path, solving the hessian-weighted ridge normal equations of Eq 3 in
de Vito (arXiv:1802.05640):

    coeffs = -(X^T H X + lambda I)^{-1} X^T g

where X is [rows-in-leaf, k+1] raw feature values with a ones column,
H = diag(hess), g = grad. Numerical-stability fallbacks mirror the
reference: rows with NaN in any leaf feature are dropped from the solve;
leaves with fewer usable rows than k+1 keep their constant output; the
solve uses a pseudo-inverse (the reference's fullPivLu), and coefficients
within kZeroThreshold of zero are pruned.

The host solves are tiny (num_leaves × (depth+1)² doubles); the heavy part
— per-row leaf membership and the X^T H X accumulation — is vectorized
numpy over each leaf's row set.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..common import K_ZERO_THRESHOLD
from ..models.tree import Tree
from ..utils.timer import global_timer


def fit_leaf_linear_models(tree: Tree, dataset, raw: np.ndarray,
                           partition, grad: np.ndarray, hess: np.ndarray,
                           linear_lambda: float,
                           is_first_tree: bool) -> None:
    """Fit per-leaf linear models in place on `tree`.

    raw:  [N, F_total] raw feature matrix (training data)
    partition: the tree learner's partition (per-leaf row index sets)
    grad/hess: [N] float gradients/hessians
    """
    global_timer.add_count("linear_leaf_fits", tree.num_leaves)
    tree.is_linear = True
    if tree.leaf_const is None:
        tree.leaf_const = np.zeros(tree.max_leaves, dtype=np.float64)
        tree.leaf_coeff = [[] for _ in range(tree.max_leaves)]
        tree.leaf_features = [[] for _ in range(tree.max_leaves)]
        tree.leaf_features_inner = [[] for _ in range(tree.max_leaves)]

    n_leaves = tree.num_leaves
    if is_first_tree:
        for leaf in range(n_leaves):
            tree.leaf_const[leaf] = tree.leaf_value[leaf]
            tree.leaf_coeff[leaf] = []
            tree.leaf_features[leaf] = []
            tree.leaf_features_inner[leaf] = []
        return

    num_data = raw.shape[0]
    grad = np.asarray(grad, dtype=np.float64)
    hess = np.asarray(hess, dtype=np.float64)

    for leaf in range(n_leaves):
        # numerical features on the branch path, sorted + deduped
        # (linear_tree_learner.cpp:208-232)
        feats: List[int] = sorted({
            f for f in (tree.branch_features[leaf]
                        if tree.track_branch_features else [])
            if dataset.mappers[f].bin_type == 0})
        rows = np.asarray(partition.indices(leaf))
        rows = rows[rows < num_data]
        tree.leaf_features[leaf] = []
        tree.leaf_features_inner[leaf] = []
        tree.leaf_coeff[leaf] = []
        tree.leaf_const[leaf] = tree.leaf_value[leaf]
        k = len(feats)
        if k == 0 or len(rows) == 0:
            continue
        Xl = np.asarray(raw[np.ix_(rows, feats)], dtype=np.float64)
        good = ~np.isnan(Xl).any(axis=1)
        if int(good.sum()) < k + 1:  # too few usable rows: constant leaf
            continue
        Xl = Xl[good]
        g = grad[rows][good]
        h = hess[rows][good]
        A = np.concatenate([Xl, np.ones((Xl.shape[0], 1))], axis=1)
        XTHX = A.T @ (A * h[:, None])
        XTHX[np.arange(k), np.arange(k)] += linear_lambda
        XTg = A.T @ g
        try:
            coeffs = -np.linalg.solve(XTHX, XTg)
            if not np.isfinite(coeffs).all():
                raise np.linalg.LinAlgError
        except np.linalg.LinAlgError:
            coeffs = -np.linalg.pinv(XTHX) @ XTg
        if not np.isfinite(coeffs).all():
            continue  # keep the constant leaf
        kept_feats = []
        kept_coeffs = []
        for i, f in enumerate(feats):
            if abs(coeffs[i]) > K_ZERO_THRESHOLD:
                kept_feats.append(int(f))
                kept_coeffs.append(float(coeffs[i]))
        tree.leaf_features[leaf] = kept_feats
        tree.leaf_features_inner[leaf] = list(kept_feats)
        tree.leaf_coeff[leaf] = kept_coeffs
        tree.leaf_const[leaf] = float(coeffs[k])


