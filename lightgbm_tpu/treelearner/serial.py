"""Leaf-wise (best-first) tree learner on TPU.

Counterpart of SerialTreeLearner (src/treelearner/serial_tree_learner.cpp:182+)
with the execution structure of the CUDA single-GPU learner
(src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:169-360): the leaf-wise
loop runs on host, each step dispatching three fused device computations —

  1. leaf histogram           (ops/histogram.py — one-hot MXU contraction)
  2. best-split search        (ops/split.py — cumsum + masked argmax)
  3. partition update         (ops/partition.py — stable-sort compaction)

with the histogram-subtraction trick (larger child = parent − smaller,
feature_histogram.hpp:99) and one device→host sync per split (the packed
best-split record), exactly the CUDA learner's sync budget.

Histograms are cached per leaf (the HistogramPool analog — device arrays held
by the frontier map; LRU capping arrives with histogram_pool_size support).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..models.tree import Tree
from ..ops.histogram import build_histogram_rows, subtract_histogram
from ..ops.partition import RowPartition
from ..ops.split import (FeatureMeta, SplitInfo, bins_to_bitset,
                         derive_cat_left_bins, find_best_split,
                         make_feature_meta)
from .col_sampler import ColSampler
from ..utils.log import Log
from ..utils.timer import global_timer


@dataclass
class _LeafState:
    hist: Optional[jax.Array]  # [G, B, 3] leaf histogram
    totals: Tuple[float, float, float]  # (sum_g, sum_h, count)
    split: Optional[SplitInfo]
    depth: int
    features_in_path: frozenset = frozenset()  # real indices (interaction constraints)


class SerialTreeLearner:
    def __init__(self, config: Config, dataset: Dataset) -> None:
        self.config = config
        self.dataset = dataset
        self.num_data = dataset.num_data
        # device-resident bin matrix (the CUDARowData analog)
        self.bins_dev = self._device_bins(dataset)
        self.group_bin_padded = int(max(dataset.group_bin_counts().max(), 2))
        self.meta: FeatureMeta = make_feature_meta(dataset, self.group_bin_padded)
        self.params_dev = jnp.asarray([
            config.lambda_l1, config.lambda_l2,
            float(config.min_data_in_leaf), config.min_sum_hessian_in_leaf,
            config.min_gain_to_split, config.max_delta_step,
            float(config.max_cat_to_onehot), float(config.max_cat_threshold),
            config.cat_l2, config.cat_smooth,
            float(config.min_data_per_group),
        ], dtype=jnp.float32)
        self.partition: Optional[RowPartition] = None
        self.col_sampler = ColSampler(config, self.meta.real_feature)
        self._tree_feature_mask: Optional[jax.Array] = None

    # ------------------------------------------------------------------ train

    def train(self, gh_ext: jax.Array,
              bag_indices: Optional[np.ndarray] = None) -> Tree:
        """Grow one tree from extended gradients gh_ext [N+1, 3]
        (zero sentinel row at N)."""
        cfg = self.config
        num_leaves = cfg.num_leaves
        tree = Tree(num_leaves)
        self._begin_tree(gh_ext, bag_indices)

        frontier: Dict[int, _LeafState] = {}
        with global_timer.scope("hist_root"):
            root_hist = self._leaf_hist(0)
        root_totals = self._root_totals(root_hist)
        frontier[0] = _LeafState(root_hist, root_totals, None, depth=0)
        self._find_split(frontier, 0)

        for _ in range(num_leaves - 1):
            best_leaf, best = None, None
            for leaf, state in frontier.items():
                if state.split is not None and state.split.valid:
                    if best is None or state.split.gain > best.gain:
                        best_leaf, best = leaf, state.split
            if best_leaf is None:
                Log.debug("No further splits with positive gain, best gain: -inf")
                break
            self._apply_split(tree, frontier, best_leaf, best)
            if tree.num_leaves >= num_leaves:
                break

        # leaf outputs: already set by _apply_split; root-only tree handled
        if tree.num_leaves == 1:
            tree.as_constant_tree(0.0)
        self._last_frontier = frontier
        return tree

    # ------------------------------------------------ device-execution hooks
    # The parallel learners (parallel/learners.py) subclass and override
    # these hooks; the leaf-wise control flow above is shared.

    def _device_bins(self, dataset: Dataset) -> jax.Array:
        return jnp.asarray(dataset.bins)

    def _begin_tree(self, gh_ext: jax.Array,
                    bag_indices: Optional[np.ndarray]) -> None:
        self._gh = gh_ext
        partition = RowPartition(self.num_data)
        if bag_indices is not None:
            partition.set_used_indices(bag_indices)
        self.partition = partition
        if self.col_sampler.active:
            self._tree_feature_mask = jnp.asarray(
                self.col_sampler.reset_by_tree())
        else:
            self._tree_feature_mask = None

    def _leaf_hist(self, leaf: int) -> jax.Array:
        return build_histogram_rows(
            self.bins_dev, self._gh, self.partition.indices(leaf),
            self.group_bin_padded)

    def _root_totals(self, root_hist: jax.Array) -> Tuple[float, float, float]:
        # any group's bins partition all rows, so group 0's bin-sum = totals
        return tuple(float(x) for x in np.asarray(root_hist[0].sum(axis=0)))

    def _node_feature_mask(self, state: "_LeafState") -> Optional[jax.Array]:
        cs = self.col_sampler
        if not cs.active:
            return None
        if cs.fraction_bynode < 1.0 or cs.constraints:
            return jnp.asarray(cs.get_by_node(set(state.features_in_path)))
        return self._tree_feature_mask

    def _search_split(self, state: "_LeafState") -> SplitInfo:
        rec = find_best_split(
            state.hist, jnp.asarray(state.totals, dtype=jnp.float32),
            self.meta, self.params_dev, self._node_feature_mask(state))
        return SplitInfo.from_packed(np.asarray(rec))

    def _partition_split(self, leaf: int, new_leaf: int, gi: int,
                         decision: jax.Array,
                         cat_mask: Optional[jax.Array] = None
                         ) -> Tuple[int, int]:
        return self.partition.split(leaf, new_leaf, self.bins_dev[gi],
                                    decision, cat_mask)

    # --------------------------------------------------------------- internal

    def _max_depth_ok(self, depth: int) -> bool:
        return self.config.max_depth <= 0 or depth < self.config.max_depth

    def _find_split(self, frontier: Dict[int, _LeafState], leaf: int) -> None:
        state = frontier[leaf]
        cnt = state.totals[2]
        if (not self._max_depth_ok(state.depth)
                or cnt < 2 * self.config.min_data_in_leaf
                or state.totals[1] < 2 * self.config.min_sum_hessian_in_leaf):
            state.split = SplitInfo()
            return
        with global_timer.scope("find_best_split"):
            state.split = self._search_split(state)

    def _apply_split(self, tree: Tree, frontier: Dict[int, _LeafState],
                     leaf: int, split: SplitInfo) -> None:
        ds = self.dataset
        meta = self.meta
        dense_f = split.feature
        real_f = meta.real_feature[dense_f]
        mapper = ds.mappers[real_f]
        gi, mi = ds.feature_to_group[real_f]
        fg = ds.groups[gi]
        lo, hi, dbin = fg.feature_bin_range(mi)

        state = frontier[leaf]
        new_leaf = tree.num_leaves

        # 1. record the split in the tree (real-value threshold / bitset)
        parent_output = _leaf_output_host(
            state.totals[0], state.totals[1],
            self.config.lambda_l1, self.config.lambda_l2,
            self.config.max_delta_step)
        cat_mask = None
        if split.is_categorical:
            # categorical features are never EFB-bundled, so the feature's
            # histogram row IS the group's
            bin_stats = np.asarray(state.hist[gi])
            left_bins = derive_cat_left_bins(
                bin_stats, mapper.num_bin, split, self.config.cat_smooth)
            split.cat_bitset_bins = left_bins
            cat_values = [mapper.bin_2_categorical[b] for b in left_bins
                          if 0 <= b < len(mapper.bin_2_categorical)]
            tree.split_categorical(
                leaf=leaf, feature_inner=dense_f, real_feature=real_f,
                bin_bitset=bins_to_bitset(left_bins),
                value_bitset=bins_to_bitset(cat_values),
                missing_type=mapper.missing_type, gain=split.gain,
                left_value=split.left_output, right_value=split.right_output,
                left_count=split.left_count, right_count=split.right_count,
                left_weight=split.left_sum_h, right_weight=split.right_sum_h,
                parent_value=parent_output)
            mask = np.zeros(self.group_bin_padded, dtype=bool)
            mask[np.asarray(left_bins, dtype=np.int64)] = True
            cat_mask = jnp.asarray(mask)
        else:
            threshold_double = mapper.bin_to_value(split.threshold_bin)
            tree.split(leaf=leaf, feature_inner=dense_f, real_feature=real_f,
                       threshold_bin=split.threshold_bin,
                       threshold_double=threshold_double,
                       default_left=split.default_left,
                       missing_type=mapper.missing_type,
                       gain=split.gain,
                       left_value=split.left_output,
                       right_value=split.right_output,
                       left_count=split.left_count,
                       right_count=split.right_count,
                       left_weight=split.left_sum_h,
                       right_weight=split.right_sum_h,
                       parent_value=parent_output)

        # 2. partition rows (one host sync for the left count)
        decision = jnp.asarray([
            float(split.threshold_bin), 1.0 if split.default_left else 0.0,
            float(mapper.missing_type), float(mapper.default_bin),
            float(mapper.num_bin), float(lo), float(hi),
            1.0 if fg.is_multi else 0.0,
        ], dtype=jnp.float32)
        with global_timer.scope("partition"):
            left_cnt, right_cnt = self._partition_split(
                leaf, new_leaf, gi, decision, cat_mask)
        if left_cnt != split.left_count or right_cnt != split.right_count:
            Log.debug("Partition count mismatch at leaf %d: %d/%d vs %d/%d",
                      leaf, left_cnt, right_cnt, split.left_count, split.right_count)

        # 3. child histograms: construct the smaller, subtract for the larger
        parent_hist = state.hist
        left_totals = (split.left_sum_g, split.left_sum_h, float(left_cnt))
        right_totals = (split.right_sum_g, split.right_sum_h, float(right_cnt))
        with global_timer.scope("hist_children"):
            if left_cnt <= right_cnt:
                small, big = leaf, new_leaf
            else:
                small, big = new_leaf, leaf
            small_hist = self._leaf_hist(small)
            big_hist = subtract_histogram(parent_hist, small_hist)
        depth = state.depth + 1
        frontier[leaf] = _LeafState(
            small_hist if small == leaf else big_hist, left_totals, None, depth)
        frontier[new_leaf] = _LeafState(
            small_hist if small == new_leaf else big_hist, right_totals, None, depth)
        state.hist = None  # release parent histogram
        self._find_split(frontier, leaf)
        self._find_split(frontier, new_leaf)


def _leaf_output_host(sum_g: float, sum_h: float, l1: float, l2: float,
                      max_delta: float) -> float:
    num = -np.sign(sum_g) * max(abs(sum_g) - l1, 0.0)
    out = num / max(sum_h + l2, 1e-15)
    if max_delta > 0:
        out = float(np.clip(out, -max_delta, max_delta))
    return float(out)


def create_tree_learner(learner_type: str, device_type: str, config: Config,
                        dataset: Dataset):
    """Factory (tree_learner.cpp:17-57). Distributed learners (feature/data/
    voting) are built on the parallel backend in parallel/."""
    if learner_type in ("serial",):
        from .device import DeviceTreeLearner, pool_bytes, POOL_BYTE_LIMIT

        # The on-device whole-tree learner trades O(leaf) index gathers for
        # O(N) static-shape masked histograms — near-free on the MXU, slow on
        # the CPU backend — so it is selected on accelerators only (and when
        # its histogram pool fits); device_type=cpu forces the host-driven
        # learner regardless of the attached backend.
        try:
            on_accelerator = jax.default_backend() not in ("cpu",)
        except RuntimeError:
            on_accelerator = False
        has_cat = any(dataset.mappers[f].bin_type == 1
                      for f in dataset.used_features)
        # per-node feature masks need the host-driven loop for now
        needs_host = (config.feature_fraction_bynode < 1.0
                      or bool(config.interaction_constraints))
        if (device_type != "cpu" and on_accelerator and not has_cat
                and not needs_host
                and pool_bytes(
                    config.num_leaves, dataset.num_groups,
                    int(max(dataset.group_bin_counts().max(), 2))
                ) <= POOL_BYTE_LIMIT):
            return DeviceTreeLearner(config, dataset)
        return SerialTreeLearner(config, dataset)
    if learner_type in ("feature", "data", "voting"):
        from ..parallel.learners import create_parallel_learner

        return create_parallel_learner(learner_type, config, dataset)
    Log.fatal("Unknown tree learner type: %s", learner_type)
