"""Leaf-wise (best-first) tree learner on TPU.

Counterpart of SerialTreeLearner (src/treelearner/serial_tree_learner.cpp:182+)
with the execution structure of the CUDA single-GPU learner
(src/treelearner/cuda/cuda_single_gpu_tree_learner.cpp:169-360): the leaf-wise
loop runs on host, each step dispatching three fused device computations —

  1. leaf histogram           (ops/histogram.py — one-hot MXU contraction)
  2. best-split search        (ops/split.py — cumsum + masked argmax)
  3. partition update         (ops/partition.py — stable-sort compaction)

with the histogram-subtraction trick (larger child = parent − smaller,
feature_histogram.hpp:99) and one device→host sync per split (the packed
best-split record), exactly the CUDA learner's sync budget.

Histograms are cached per leaf (the HistogramPool analog — device arrays held
by the frontier map; LRU capping arrives with histogram_pool_size support).
"""
from __future__ import annotations

import os
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Config
from ..io.dataset import Dataset
from ..models.sample_strategy import host_bag_indices
from ..models.tree import Tree
from ..ops.histogram import build_histogram_rows, subtract_histogram
from ..ops.partition import RowPartition
from ..ops.quantize import discretize_gradients
from ..ops.split import (FeatureMeta, SplitInfo, bins_to_bitset,
                         derive_cat_left_bins, find_best_split,
                         make_feature_meta)
from .cegb import CEGB
from .col_sampler import ColSampler
from .. import perfmodel, telemetry
from ..utils.log import Log
from ..utils.timer import global_timer


@dataclass
class _LeafState:
    hist: Optional[jax.Array]  # [G, B, 3] leaf histogram
    totals: Tuple[float, float, float]  # (sum_g, sum_h, count)
    split: Optional[SplitInfo]
    depth: int
    features_in_path: frozenset = frozenset()  # real indices (interaction constraints)
    # basic-mode monotone output bounds inherited from ancestors
    # (monotone_constraints.hpp BasicLeafConstraints)
    bounds: Tuple[float, float] = (-np.inf, np.inf)


class SerialTreeLearner:
    def __init__(self, config: Config, dataset: Dataset) -> None:
        self.config = config
        self.dataset = dataset
        self.num_data = dataset.num_data
        # device-resident bin matrix (the CUDARowData analog)
        with global_timer.scope("learner_init"):
            self.bins_dev = self._device_bins(dataset)
        self.group_bin_padded = int(max(dataset.group_bin_counts().max(), 2))
        self.meta: FeatureMeta = make_feature_meta(dataset, self.group_bin_padded)
        self.params_dev = jnp.asarray([
            config.lambda_l1, config.lambda_l2,
            float(config.min_data_in_leaf), config.min_sum_hessian_in_leaf,
            config.min_gain_to_split, config.max_delta_step,
            float(config.max_cat_to_onehot), float(config.max_cat_threshold),
            config.cat_l2, config.cat_smooth,
            float(config.min_data_per_group),
        ], dtype=jnp.float32)
        self.partition: Optional[RowPartition] = None
        self.col_sampler = ColSampler(config, self.meta.real_feature)
        self._tree_feature_mask: Optional[jax.Array] = None
        # HistogramPool byte cap (feature_histogram.hpp:1367-1597): when
        # histogram_pool_size (MB) is set, at most `_pool_cap` leaf
        # histograms stay materialized; LRU-evicted ones recompute on demand
        self._pool_cap = 0
        if config.histogram_pool_size > 0:
            hist_bytes = (len(dataset.groups) * self.group_bin_padded * 3 * 4)
            self._pool_cap = max(
                2, int(config.histogram_pool_size * 1024 * 1024 / hist_bytes))
        self._hist_lru: "OrderedDict[int, bool]" = OrderedDict()
        self._has_mc = bool(dataset.monotone_constraints
                            and any(dataset.monotone_constraints))
        if self._has_mc and config.monotone_constraints_method not in (
                "basic",):
            Log.fatal("monotone_constraints_method=%s is not supported "
                      "(only 'basic')", config.monotone_constraints_method)
        self.cegb: Optional[CEGB] = (CEGB(config, dataset)
                                     if CEGB.enabled(config) else None)
        # quantized-gradient training (GradientDiscretizer analog)
        self.quantized = bool(config.use_quantized_grad)
        self._scale_vec: Optional[jax.Array] = None
        if self.quantized:
            self._quant_key = jax.random.PRNGKey(
                int(getattr(config, "data_random_seed", 1)))
        # forcedsplits_filename (SerialTreeLearner::ForceSplits,
        # serial_tree_learner.cpp:627+): nested {"feature","threshold",
        # "left","right"} JSON applied at the top of every tree
        self._forced_json = None
        if config.forcedsplits_filename:
            import json as _json

            try:
                with open(config.forcedsplits_filename) as fh:
                    self._forced_json = _json.load(fh)
            except OSError:
                Log.warning("Could not open forced splits file %s",
                            config.forcedsplits_filename)

    # ------------------------------------------------------------- checkpoint

    def snapshot_state(self) -> dict:
        """Learner state a bit-identical resume needs, split into ndarrays
        (stored raw in the checkpoint sidecar's npz) and scalars (stored in
        the JSON manifest): the column-sampler's MT19937 stream, the
        per-tree quantized-gradient PRNG key, and a structural fingerprint
        (num_data / padded bin count) that restore refuses to cross."""
        kind, keys, pos, has_gauss, cached = self.col_sampler.rng.get_state()
        st = {
            "rng_kind": kind,
            "colsampler_keys": np.asarray(keys, dtype=np.uint32),
            "colsampler_pos": int(pos),
            "colsampler_has_gauss": int(has_gauss),
            "colsampler_cached_gaussian": float(cached),
            "num_data": int(self.num_data),
            "group_bin_padded": int(self.group_bin_padded),
        }
        if self.quantized:
            st["quant_key"] = np.asarray(self._quant_key, dtype=np.uint32)
        return st

    def restore_snapshot_state(self, st: dict) -> None:
        if int(st.get("num_data", self.num_data)) != int(self.num_data) \
                or int(st.get("group_bin_padded", self.group_bin_padded)) \
                != int(self.group_bin_padded):
            Log.fatal("Checkpoint learner state was captured on a different "
                      "dataset shape (num_data=%s, group_bin_padded=%s vs "
                      "%d, %d) — refusing to resume",
                      st.get("num_data"), st.get("group_bin_padded"),
                      self.num_data, self.group_bin_padded)
        self.col_sampler.rng.set_state((
            str(st["rng_kind"]),
            np.asarray(st["colsampler_keys"], dtype=np.uint32),
            int(st["colsampler_pos"]),
            int(st["colsampler_has_gauss"]),
            float(st["colsampler_cached_gaussian"])))
        if self.quantized and "quant_key" in st:
            # plain asarray, NOT device_put: a fresh PRNGKey lives on the
            # default device, and bit-identity requires matching placement
            self._quant_key = jnp.asarray(
                np.asarray(st["quant_key"], dtype=np.uint32),
                dtype=jnp.uint32)

    # ------------------------------------------------------------------ train

    def train(self, gh_ext: jax.Array,
              bag_indices: Optional[np.ndarray] = None) -> Tree:
        """Grow one tree from extended gradients gh_ext [N+1, 3]
        (zero sentinel row at N)."""
        cfg = self.config
        num_leaves = cfg.num_leaves
        tree = Tree(num_leaves, track_branch_features=cfg.linear_tree,
                    is_linear=cfg.linear_tree)
        self._begin_tree(gh_ext, bag_indices)

        frontier: Dict[int, _LeafState] = {}
        with global_timer.scope("hist_root"):
            root_hist = self._leaf_hist(0)
        root_totals = self._root_totals(root_hist)
        frontier[0] = _LeafState(root_hist, root_totals, None, depth=0)
        if not self._force_splits(tree, frontier):
            self._find_split(frontier, 0)

        for _ in range(num_leaves - 1):
            best_leaf, best = None, None
            for leaf, state in frontier.items():
                if state.split is not None and state.split.valid:
                    if best is None or state.split.gain > best.gain:
                        best_leaf, best = leaf, state.split
            if best_leaf is None:
                Log.debug("No further splits with positive gain, best gain: -inf")
                break
            self._apply_split(tree, frontier, best_leaf, best)
            if tree.num_leaves >= num_leaves:
                break

        # leaf outputs: already set by _apply_split; root-only tree handled
        if tree.num_leaves == 1:
            tree.as_constant_tree(0.0)
        elif self.quantized and cfg.quant_train_renew_leaf:
            self._renew_quantized_leaves(tree, frontier)
        self._last_frontier = frontier
        return tree

    def _renew_quantized_leaves(self, tree: Tree,
                                frontier: Dict[int, _LeafState]) -> None:
        """Recompute leaf outputs from the TRUE float gradients, removing
        quantization error (GradientDiscretizer::RenewIntGradTreeOutput,
        gradient_discretizer.cpp:166-233). Unlike the reference (which renews
        unclamped), renewed outputs stay inside the leaf's monotone bounds so
        quantized training keeps the monotonicity guarantee."""
        cfg = self.config
        for leaf in range(tree.num_leaves):
            idx = jnp.asarray(np.asarray(self.partition.indices(leaf)),
                              dtype=jnp.int32)
            gh = jnp.take(self._gh_float, idx, axis=0).sum(axis=0)
            sums = np.asarray(gh)
            out = _leaf_output_host(float(sums[0]), float(sums[1]),
                                    cfg.lambda_l1, cfg.lambda_l2,
                                    cfg.max_delta_step)
            if self._has_mc and leaf in frontier:
                lo, hi = frontier[leaf].bounds
                out = float(np.clip(out, lo, hi))
            tree.set_leaf_output(leaf, out)

    # ------------------------------------------------ device-execution hooks
    # The parallel learners (parallel/learners.py) subclass and override
    # these hooks; the leaf-wise control flow above is shared.

    def _device_bins(self, dataset: Dataset) -> jax.Array:
        """Upload the bin matrix at its native width. uint8 planes (every
        group <= 256 bins, the common case) stay 8-bit end to end — the
        device learner carries and histograms them unwidened. The int32
        escape hatch: LGBM_TPU_BINS_I32=1 forces a wide plane; datasets
        with any group > 256 bins are uint16 host-side already and widen
        automatically downstream."""
        if (dataset.bins.dtype.itemsize == 1
                and os.environ.get("LGBM_TPU_BINS_I32", "") == "1"):
            return jnp.asarray(dataset.bins, dtype=jnp.int32)
        return jnp.asarray(dataset.bins, dtype=dataset.bins.dtype)

    def _prepare_gh(self, gh_ext: jax.Array) -> jax.Array:
        """Quantize the gradient pack when use_quantized_grad is on: int8
        (g, h, 1) rows + a zero sentinel; scales kept for the scan."""
        if not self.quantized:
            return gh_ext
        self._gh_float = gh_ext  # kept for leaf-output renewal
        self._quant_key, sub = jax.random.split(self._quant_key)
        g_int, h_int, gs, hs = discretize_gradients(
            gh_ext[:-1, 0], gh_ext[:-1, 1], sub,
            self.config.num_grad_quant_bins,
            self.config.stochastic_rounding)
        self._scale_vec = jnp.stack([gs, hs, jnp.float32(1.0)])
        ghq = jnp.stack([g_int, h_int, jnp.ones_like(g_int)], axis=1)
        return jnp.concatenate([ghq, jnp.zeros((1, 3), jnp.int8)], axis=0)

    def _hist_for_scan(self, hist: jax.Array) -> jax.Array:
        """Integer histograms re-enter float space via the quantization
        scales right before the split scan."""
        if not self.quantized:
            return hist
        scale = self._scale_vec
        # the distributed learners hand over mesh-committed histograms;
        # the per-tree scales come off the default device — replicate them
        # onto the same mesh once so the multiply has one device set
        if (isinstance(hist.sharding, jax.sharding.NamedSharding)
                and scale.sharding.device_set != hist.sharding.device_set):
            scale = jax.device_put(scale, jax.sharding.NamedSharding(
                hist.sharding.mesh, jax.sharding.PartitionSpec()))
            self._scale_vec = scale
        return hist.astype(jnp.float32) * scale

    def _begin_tree(self, gh_ext: jax.Array,
                    bag_indices: Optional[np.ndarray]) -> None:
        self._gh = self._prepare_gh(gh_ext)
        self._hist_lru.clear()
        partition = RowPartition(self.num_data)
        if bag_indices is not None:
            # a DeviceBag (device GOSS) materializes host indices here —
            # the host-driven learner's RowPartition is index-based anyway
            partition.set_used_indices(host_bag_indices(bag_indices))
        self.partition = partition
        if self.col_sampler.active:
            self._tree_feature_mask = jnp.asarray(
                self.col_sampler.reset_by_tree(), dtype=jnp.bool_)
        else:
            self._tree_feature_mask = None

    def _leaf_hist(self, leaf: int) -> jax.Array:
        return build_histogram_rows(
            self.bins_dev, self._gh, self.partition.indices(leaf),
            self.group_bin_padded,
            compute_dtype=jnp.int8 if self.quantized else jnp.float32)

    def _root_totals(self, root_hist: jax.Array) -> Tuple[float, float, float]:
        # any group's bins partition all rows, so group 0's bin-sum = totals
        return tuple(float(x) for x in np.asarray(
            self._hist_for_scan(root_hist)[0].sum(axis=0)))

    def _node_feature_mask(self, state: "_LeafState") -> Optional[jax.Array]:
        cs = self.col_sampler
        if not cs.active:
            return None
        if cs.fraction_bynode < 1.0 or cs.constraints:
            return jnp.asarray(cs.get_by_node(set(state.features_in_path)),
                               dtype=jnp.bool_)
        return self._tree_feature_mask

    def _search_split(self, state: "_LeafState", leaf: int) -> SplitInfo:
        args = (self._hist_for_scan(state.hist),
                jnp.asarray(state.totals, dtype=jnp.float32),
                self.meta, self.params_dev, self._node_feature_mask(state),
                self._constraint_of(state), self._penalty_of(state, leaf))
        if telemetry.enabled():
            # one-time capture of the gain-scan dispatch signature for
            # perfmodel's AOT cost_analysis (dict-check no-op afterwards)
            perfmodel.note_dispatch("scan", find_best_split, *args)
        rec = find_best_split(*args)
        return SplitInfo.from_packed(np.asarray(rec))

    def _constraint_of(self, state: "_LeafState") -> Optional[jax.Array]:
        if not self._has_mc:
            return None
        return jnp.asarray(state.bounds, dtype=jnp.float32)

    def _penalty_of(self, state: "_LeafState",
                    leaf: int) -> Optional[jax.Array]:
        if self.cegb is None:
            return None
        rows = self._leaf_rows(leaf) if self.cegb.needs_rows else None
        return jnp.asarray(
            self.cegb.penalty_vector(state.totals[2], rows),
            dtype=jnp.float32)

    def _leaf_rows(self, leaf: int) -> np.ndarray:
        """Actual (unpadded) row indices of a leaf, for CEGB lazy tracking."""
        rows = np.asarray(self.partition.indices(leaf))
        return rows[rows < self.num_data]

    def _partition_split(self, leaf: int, new_leaf: int, gi: int,
                         decision: jax.Array,
                         cat_mask: Optional[jax.Array] = None
                         ) -> Tuple[int, int]:
        return self.partition.split(leaf, new_leaf, self.bins_dev[gi],
                                    decision, cat_mask)

    def _cat_bin_stats(self, state: "_LeafState", gi: int,
                       dense_f: int) -> np.ndarray:
        """Aggregated histogram row of a winning categorical split's feature
        (categorical features are never EFB-bundled, so the feature's
        histogram row IS its group's). Scaled on device so the host bin-set
        re-derivation replays bit-identical f32 values to the scan."""
        return np.asarray(self._hist_for_scan(state.hist)[gi])

    def _feature_hist_row(self, state: "_LeafState",
                          dense_f: int) -> np.ndarray:
        """One feature's aggregated [Bmax, 3] histogram (forced splits).
        Overridden by the distributed learners, whose state.hist layouts
        differ from the serial group-major [G, Bpad, 3]."""
        from ..ops.split import gather_feature_hist

        return np.asarray(gather_feature_hist(
            self._hist_for_scan(state.hist), self.meta,
            jnp.asarray(state.totals, dtype=jnp.float32))[dense_f])

    # --------------------------------------------------------------- internal

    def _max_depth_ok(self, depth: int) -> bool:
        return self.config.max_depth <= 0 or depth < self.config.max_depth

    def _force_splits(self, tree: Tree, frontier: Dict[int, _LeafState]) -> int:
        """Apply the forced-splits JSON at the top of the tree
        (SerialTreeLearner::ForceSplits, serial_tree_learner.cpp:627+).
        Returns the number of applied splits."""
        if self._forced_json is None:
            return 0
        count = 0
        queue = [(self._forced_json, 0)]
        while queue and tree.num_leaves < self.config.num_leaves:
            jnode, leaf = queue.pop(0)
            split = self._forced_split_info(frontier[leaf], jnode)
            if split is None:
                continue
            new_leaf = tree.num_leaves
            self._apply_split(tree, frontier, leaf, split)
            count += 1
            if isinstance(jnode.get("left"), dict):
                queue.append((jnode["left"], leaf))
            if isinstance(jnode.get("right"), dict):
                queue.append((jnode["right"], new_leaf))
        return count

    # graftlint: disable=untimed-hot-func -- cold path: runs only when forcedsplits_filename is set
    def _forced_split_info(self, state: "_LeafState",
                           jnode) -> Optional[SplitInfo]:
        """Split stats for a forced (feature, threshold) pair, computed from
        the leaf histogram at the forced bin instead of the best-split scan."""
        try:
            real_f = int(jnode["feature"])
            thr = float(jnode["threshold"])
        except (KeyError, TypeError, ValueError):
            return None
        if real_f not in self.meta.real_feature:
            return None
        dense_f = self.meta.real_feature.index(real_f)
        mapper = self.dataset.mappers[real_f]
        if mapper.bin_type == 1:  # categorical forced splits unsupported
            Log.warning("Forced split on categorical feature %d ignored", real_f)
            return None
        fh = self._feature_hist_row(state, dense_f)
        tbin = int(mapper.value_to_bin(thr))
        nb = mapper.num_bin
        has_nan = mapper.missing_type == 2
        # keep at least one real bin right of the threshold; with NaN missing
        # the last bin is the NaN bin, which clamping also keeps on the right
        # (default_left=False)
        if tbin >= nb - (2 if has_nan else 1):
            tbin = nb - (3 if has_nan else 2)
        if tbin < 0:
            return None
        left = fh[: tbin + 1].sum(axis=0)
        tg, th_, tc = state.totals
        lg, lh, lc = float(left[0]), float(left[1]), float(left[2])
        rg, rh, rc = tg - lg, th_ - lh, tc - lc
        cfg = self.config
        if (lc < cfg.min_data_in_leaf or rc < cfg.min_data_in_leaf
                or lh < cfg.min_sum_hessian_in_leaf
                or rh < cfg.min_sum_hessian_in_leaf):
            return None
        lout = _leaf_output_host(lg, lh, cfg.lambda_l1, cfg.lambda_l2,
                                 cfg.max_delta_step)
        rout = _leaf_output_host(rg, rh, cfg.lambda_l1, cfg.lambda_l2,
                                 cfg.max_delta_step)

        def g(sg, sh, out):
            sgl = np.sign(sg) * max(abs(sg) - cfg.lambda_l1, 0.0)
            return -(2.0 * sgl * out + (sh + cfg.lambda_l2) * out * out)

        parent_out = _leaf_output_host(tg, th_, cfg.lambda_l1, cfg.lambda_l2,
                                       cfg.max_delta_step)
        gain = g(lg, lh, lout) + g(rg, rh, rout) - g(tg, th_, parent_out)
        return SplitInfo(gain=float(gain), feature=dense_f, threshold_bin=tbin,
                         default_left=False, left_sum_g=lg, left_sum_h=lh,
                         left_count=int(round(lc)), right_sum_g=rg,
                         right_sum_h=rh, right_count=int(round(rc)),
                         left_output=lout, right_output=rout)

    def _pool_touch(self, frontier: Dict[int, _LeafState], leaf: int) -> None:
        """Materialize an evicted leaf histogram and refresh its LRU slot,
        evicting the coldest leaves past the pool cap."""
        state = frontier[leaf]
        if state.hist is None:
            with global_timer.scope("hist_recompute"):
                state.hist = self._leaf_hist(leaf)
        if not self._pool_cap:
            return
        lru = self._hist_lru
        lru.pop(leaf, None)
        lru[leaf] = True
        while len(lru) > self._pool_cap:
            old, _ = lru.popitem(last=False)
            old_state = frontier.get(old)
            if old_state is not None and old_state.hist is not None:
                old_state.hist = None

    def _find_split(self, frontier: Dict[int, _LeafState], leaf: int) -> None:
        state = frontier[leaf]
        cnt = state.totals[2]
        if (not self._max_depth_ok(state.depth)
                or cnt < 2 * self.config.min_data_in_leaf
                or state.totals[1] < 2 * self.config.min_sum_hessian_in_leaf):
            state.split = SplitInfo()
            return
        self._pool_touch(frontier, leaf)
        with global_timer.scope("find_best_split"):
            state.split = self._search_split(state, leaf)

    def _apply_split(self, tree: Tree, frontier: Dict[int, _LeafState],
                     leaf: int, split: SplitInfo) -> None:
        ds = self.dataset
        meta = self.meta
        dense_f = split.feature
        real_f = meta.real_feature[dense_f]
        mapper = ds.mappers[real_f]
        gi, mi = ds.feature_to_group[real_f]
        fg = ds.groups[gi]
        lo, hi, dbin = fg.feature_bin_range(mi)

        state = frontier[leaf]
        new_leaf = tree.num_leaves
        self._pool_touch(frontier, leaf)  # parent hist needed for subtraction

        # 1. record the split in the tree (real-value threshold / bitset)
        parent_output = _leaf_output_host(
            state.totals[0], state.totals[1],
            self.config.lambda_l1, self.config.lambda_l2,
            self.config.max_delta_step)
        cat_mask = None
        if split.is_categorical:
            bin_stats = self._cat_bin_stats(state, gi, dense_f)
            left_bins = derive_cat_left_bins(
                bin_stats, mapper.num_bin, split, self.config.cat_smooth)
            split.cat_bitset_bins = left_bins
            cat_values = [mapper.bin_2_categorical[b] for b in left_bins
                          if 0 <= b < len(mapper.bin_2_categorical)]
            tree.split_categorical(
                leaf=leaf, feature_inner=dense_f, real_feature=real_f,
                bin_bitset=bins_to_bitset(left_bins),
                value_bitset=bins_to_bitset(cat_values),
                missing_type=mapper.missing_type, gain=split.gain,
                left_value=split.left_output, right_value=split.right_output,
                left_count=split.left_count, right_count=split.right_count,
                left_weight=split.left_sum_h, right_weight=split.right_sum_h,
                parent_value=parent_output)
            mask = np.zeros(self.group_bin_padded, dtype=bool)
            mask[np.asarray(left_bins, dtype=np.int64)] = True
            cat_mask = jnp.asarray(mask, dtype=jnp.bool_)
        else:
            threshold_double = mapper.bin_to_value(split.threshold_bin)
            tree.split(leaf=leaf, feature_inner=dense_f, real_feature=real_f,
                       threshold_bin=split.threshold_bin,
                       threshold_double=threshold_double,
                       default_left=split.default_left,
                       missing_type=mapper.missing_type,
                       gain=split.gain,
                       left_value=split.left_output,
                       right_value=split.right_output,
                       left_count=split.left_count,
                       right_count=split.right_count,
                       left_weight=split.left_sum_h,
                       right_weight=split.right_sum_h,
                       parent_value=parent_output)

        # 2. partition rows (one host sync for the left count)
        decision = jnp.asarray([
            float(split.threshold_bin), 1.0 if split.default_left else 0.0,
            float(mapper.missing_type), float(mapper.default_bin),
            float(mapper.num_bin), float(lo), float(hi),
            1.0 if fg.is_multi else 0.0,
        ], dtype=jnp.float32)
        with global_timer.scope("partition"):
            left_cnt, right_cnt = self._partition_split(
                leaf, new_leaf, gi, decision, cat_mask)
        if left_cnt != split.left_count or right_cnt != split.right_count:
            Log.debug("Partition count mismatch at leaf %d: %d/%d vs %d/%d",
                      leaf, left_cnt, right_cnt, split.left_count, split.right_count)

        # 3. child histograms: construct the smaller, subtract for the larger
        parent_hist = state.hist
        left_totals = (split.left_sum_g, split.left_sum_h, float(left_cnt))
        right_totals = (split.right_sum_g, split.right_sum_h, float(right_cnt))
        with global_timer.scope("hist_children"):
            if left_cnt <= right_cnt:
                small, big = leaf, new_leaf
            else:
                small, big = new_leaf, leaf
            small_hist = self._leaf_hist(small)
            big_hist = subtract_histogram(parent_hist, small_hist)
        depth = state.depth + 1
        child_path = state.features_in_path | {int(real_f)}
        # monotone bound propagation (BasicLeafConstraints::Update,
        # monotone_constraints.hpp:487-503): a numerical split on a monotone
        # feature pins the children's shared boundary at the output midpoint
        lbounds = rbounds = state.bounds
        if self._has_mc and not split.is_categorical:
            mono = (self.dataset.monotone_constraints[real_f]
                    if real_f < len(self.dataset.monotone_constraints) else 0)
            if mono != 0:
                lo, hi_b = state.bounds
                mid = (split.left_output + split.right_output) / 2.0
                if mono > 0:
                    lbounds = (lo, min(hi_b, mid))
                    rbounds = (max(lo, mid), hi_b)
                else:
                    lbounds = (max(lo, mid), hi_b)
                    rbounds = (lo, min(hi_b, mid))
        frontier[leaf] = _LeafState(
            small_hist if small == leaf else big_hist, left_totals, None, depth,
            child_path, lbounds)
        frontier[new_leaf] = _LeafState(
            small_hist if small == new_leaf else big_hist, right_totals, None,
            depth, child_path, rbounds)
        state.hist = None  # release parent histogram
        self._hist_lru.pop(leaf, None)
        self._pool_touch(frontier, leaf)
        self._pool_touch(frontier, new_leaf)
        refresh_frontier = False
        if self.cegb is not None:
            rows = None
            if self.cegb.needs_rows:
                rows = np.concatenate([self._leaf_rows(leaf),
                                       self._leaf_rows(new_leaf)])
            refresh_frontier = self.cegb.on_split_applied(dense_f, rows)
        self._find_split(frontier, leaf)
        self._find_split(frontier, new_leaf)
        if refresh_frontier:
            # a coupled feature penalty was just lifted: refresh the other
            # pending scans so their gains drop the stale coupled penalty
            # (UpdateLeafBestSplits, cost_effective_gradient_boosting.hpp:100)
            for lf in frontier:
                if lf not in (leaf, new_leaf):
                    self._find_split(frontier, lf)


def _leaf_output_host(sum_g: float, sum_h: float, l1: float, l2: float,
                      max_delta: float) -> float:
    num = -np.sign(sum_g) * max(abs(sum_g) - l1, 0.0)
    out = num / max(sum_h + l2, 1e-15)
    if max_delta > 0:
        out = float(np.clip(out, -max_delta, max_delta))
    return float(out)


def device_growth_applies(device_type: str, config: Config,
                          dataset: Dataset) -> bool:
    """Whether the on-device whole-tree wave learner can serve this config.

    The wave learner trades O(leaf) index gathers for O(N) static-shape
    masked histograms — near-free on the MXU, slow on the CPU backend — so
    it is selected on accelerators only; device_type=cpu forces the
    host-driven learner regardless of the attached backend (device_type
    defaults to "auto": see Config._post_process). Shared by the serial
    factory below and the data-parallel factory (parallel/learners.py),
    which stacks its sharded grower on the same device-growth conditions.
    """
    try:
        on_accelerator = jax.default_backend() not in ("cpu",)
    except RuntimeError:
        on_accelerator = False
    has_cat = any(dataset.mappers[f].bin_type == 1
                  for f in dataset.used_features)
    # per-node feature masks / per-leaf bounds and penalties need the
    # host-driven loop for now
    needs_host = (config.feature_fraction_bynode < 1.0
                  or bool(config.interaction_constraints)
                  or bool(dataset.monotone_constraints
                          and any(dataset.monotone_constraints))
                  or CEGB.enabled(config)
                  or config.linear_tree
                  or bool(config.forcedsplits_filename))
    return (device_type != "cpu" and on_accelerator and not has_cat
            and not needs_host)


def create_tree_learner(learner_type: str, device_type: str, config: Config,
                        dataset: Dataset):
    """Factory (tree_learner.cpp:17-57). Distributed learners (feature/data/
    voting) are built on the parallel backend in parallel/."""
    if learner_type in ("serial",):
        from .device import DeviceTreeLearner
        # out-of-core: an HBM budget (LGBM_TPU_HBM_BUDGET) means the plane
        # must NOT be uploaded whole — the streamed learner takes
        # precedence over device growth (streaming/learner.py)
        from ..streaming.learner import (StreamedTreeLearner,
                                         streaming_requested)

        if streaming_requested():
            return StreamedTreeLearner(config, dataset)
        if device_growth_applies(device_type, config, dataset):
            return DeviceTreeLearner(config, dataset)
        return SerialTreeLearner(config, dataset)
    if learner_type in ("feature", "data", "voting"):
        from ..parallel.learners import create_parallel_learner

        return create_parallel_learner(learner_type, config, dataset)
    Log.fatal("Unknown tree learner type: %s", learner_type)
