from .log import Log, register_log_callback
from .timer import global_timer, timed

__all__ = ["Log", "register_log_callback", "global_timer", "timed"]
