"""JAX version compatibility shims.

The distributed learners target the stable `jax.shard_map` API
(check_vma); older JAX releases ship it as
`jax.experimental.shard_map.shard_map` with the `check_rep` spelling of
the same flag. One wrapper, named `shard_map` so call sites (and the R7
collective-axis lint, which keys on the call name) read identically on
every version.
"""
from __future__ import annotations

import jax

try:
    _shard_map_impl = jax.shard_map  # stable API (jax >= 0.4.35-ish)
    _CHECK_KW = "check_vma"
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_impl

    _CHECK_KW = "check_rep"


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """`jax.shard_map` across JAX versions (check_vma == check_rep)."""
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **{_CHECK_KW: check_vma})
