"""Deterministic fault injection for the fault-tolerance test suite.

A FaultPlan is parsed from a comma-separated spec — either installed
programmatically (tests call ``install``/``clear``) or read once from the
``LGBM_TPU_FAULT`` environment variable (CLI runs) with the companion
``LGBM_TPU_FAULT_SEED`` controlling the poisoning RNG. Supported tokens:

    kill@K              raise InjectedFault at the START of iteration K
                        (the mid-train process-kill stand-in)
    nan_gh@K[:frac]     poison `frac` of the gradient/hessian rows with NaN
                        after iteration K's gradient pass (default 1%)
    ckpt_write_fail:N   the next N atomic writes raise OSError before the
                        temp file is created (transient disk failure — the
                        retry-with-backoff wrapper must absorb them)
    ckpt_corrupt        flip bytes in the middle of the next checkpoint
                        sidecar AFTER it is durably written
    ckpt_truncate       truncate the next model-text artifact to half its
                        size AFTER it is durably written

Serving faults (lightgbm_tpu/serving/, docs/SERVING.md) — the dispatch
counter counts device dispatches through the serving batcher, 1-based:

Distributed faults (lightgbm_tpu/parallel/elastic.py, docs/ROBUSTNESS.md
"Distributed fault domain") — ranks come from JAX_PROCESS_ID; the kill/hang
pair fires only on gang attempt 0 (``LGBM_TPU_GANG_ATTEMPT``), so an
elastic restart that resumes at the fault iteration does not re-die:

    worker_kill@R:K     rank R dies at the START of iteration K — a hard
                        os._exit under gang supervision (exit code 43,
                        modelling SIGKILL: no unwind, no atexit), a raised
                        InjectedFault otherwise
    worker_hang@R:K     rank R stops participating at iteration K but stays
                        alive: an interruptible spin that polls the elastic
                        watchdog — the WorkerLostError conversion path
    coord_loss@K        the coordinator (rank 0) dies at iteration K —
                        sugar for worker_kill@0:K
    slow_worker@R:ms    rank R sleeps `ms` milliseconds at the start of
                        every iteration (straggler; fires every attempt)
    vote_skew@R:K       device-voting MESH rank R nominates garbage
                        features at wave K (its PV-Tree ballot is
                        corrupted; ranks here are mesh axis positions, so
                        single-process fake-device meshes inject too).
                        LGBM_TPU_VOTING_EXACT_CHECK=1 surfaces it as
                        VotingDivergenceError with the measured election
                        divergence attached; under an elastic gang without
                        exact-check the detecting worker parks in the
                        interruptible watchdog spin instead — the same
                        WorkerLostError conversion path as worker_hang,
                        never a silent hang

    slow_predict@N[:secs]    every device dispatch from the Nth onward
                             sleeps `secs` (default 0.05) before running —
                             the slow-device stand-in that saturates the
                             admission queue in open-loop load tests
    predict_fail@N[:count]   dispatches N..N+count-1 raise InjectedFault
                             (default count 3) — trips the circuit breaker,
                             then lets it recover once the window passes
    model_corrupt_upload     garble the NEXT staged model upload before the
                             registry verifies it (one-shot) — the checksum
                             gate must reject it and keep the prior version

Drift / continuous-training faults (lightgbm_tpu/streaming/drift.py,
docs/STREAMING.md "Drift and generation safety"):

    drift_shift@K:F     every pushed row with absolute index >= K gets
                        feature F affinely shifted (x*3 + 10) out of the
                        fitted bin support — a planted covariate shift the
                        drift monitor must alarm on and a bin refresh must
                        re-resolve (fires continuously; emits once)
    bad_generation@G    the refit that would publish generation G has its
                        trained model poisoned in memory (leaf values
                        sign-flipped and scaled 1e6) AFTER training and checkpointing — a
                        genuinely bad candidate only the quality gate can
                        stop from reaching serving (one-shot)
    sketch_corrupt@K    plant non-finite garbage inside feature K's
                        quantile sketch — the next bin refresh must detect
                        it via the sketch health check and keep the
                        feature's current cut points (one-shot)

Every injection is one-shot (``kill@K`` fires once even if iteration K is
re-entered after a rollback) and seeded, so a failing fault test replays
exactly. All hooks are cheap no-ops when no plan is armed — the boosting
hot loop pays two dict lookups per iteration.
"""
from __future__ import annotations

import os
from typing import Optional

from .log import Log


class InjectedFault(RuntimeError):
    """Raised by the kill injection point; stands in for SIGKILL in tests
    (the checkpoint files on disk are all a real kill would leave)."""


class VotingDivergenceError(RuntimeError):
    """Raised by the voting exact-check harness when an armed vote_skew
    plan corrupted a PV-Tree ballot: the typed surface for election
    tampering (the message carries the measured committed-split
    divergence, which can legitimately be 0 — a single corrupted ballot is
    often outvoted — but a tampered election must never train on
    silently)."""


# exit code an injected worker_kill uses under gang supervision — distinct
# from real crash codes so the supervisor log names the injection
EXIT_INJECTED_KILL = 43


def _rank() -> int:
    try:
        return int(os.environ.get("JAX_PROCESS_ID", "0") or 0)
    except ValueError:
        return 0


def _gang_attempt() -> int:
    try:
        return int(os.environ.get("LGBM_TPU_GANG_ATTEMPT", "0") or 0)
    except ValueError:
        return 0


def _rank_iter(token: str, prefix: str, value=int):
    """Parse a ``prefix<rank>:<n>`` token; malformed specs are fatal (a
    typo'd chaos token silently arming nothing would fake a green run)."""
    body = token[len(prefix):]
    try:
        r, v = body.split(":", 1)
        return int(r), value(v)
    except ValueError:
        Log.fatal("Malformed fault token %r: expected %s<rank>:<n>",
                  token, prefix)


class FaultPlan:
    def __init__(self, spec: str = "", seed: int = 0) -> None:
        self.spec = spec or ""
        self.seed = int(seed)
        self.kill_at: Optional[int] = None
        self.nan_at: Optional[int] = None
        self.nan_frac = 0.01
        self.write_fails = 0
        self.corrupt_sidecar = False
        self.truncate_model = False
        self.worker_kill = None   # (rank, iteration)
        self.worker_hang = None   # (rank, iteration)
        self.slow_worker = None   # (rank, seconds)
        self.vote_skew = None     # (mesh rank, wave)
        self.slow_predict_at: Optional[int] = None
        self.slow_predict_s = 0.05
        self.fail_predict_at: Optional[int] = None
        self.fail_predict_count = 3
        self.corrupt_upload = False
        self.drift_shift = None       # (start_row, feature)
        self.bad_generation: Optional[int] = None
        self.sketch_corrupt: Optional[int] = None
        self._dispatch_no = 0  # serving device-dispatch counter (1-based)
        self._fired = set()
        for token in (t.strip() for t in self.spec.split(",")):
            if not token:
                continue
            if token.startswith("kill@"):
                self.kill_at = int(token[len("kill@"):])
            elif token.startswith("nan_gh@"):
                body = token[len("nan_gh@"):]
                if ":" in body:
                    it, frac = body.split(":", 1)
                    self.nan_at, self.nan_frac = int(it), float(frac)
                else:
                    self.nan_at = int(body)
            elif token.startswith("ckpt_write_fail:"):
                self.write_fails = int(token.split(":", 1)[1])
            elif token == "ckpt_corrupt":
                self.corrupt_sidecar = True
            elif token == "ckpt_truncate":
                self.truncate_model = True
            elif token.startswith("slow_predict@"):
                body = token[len("slow_predict@"):]
                if ":" in body:
                    at, secs = body.split(":", 1)
                    self.slow_predict_at, self.slow_predict_s = (
                        int(at), float(secs))
                else:
                    self.slow_predict_at = int(body)
            elif token.startswith("predict_fail@"):
                body = token[len("predict_fail@"):]
                if ":" in body:
                    at, cnt = body.split(":", 1)
                    self.fail_predict_at, self.fail_predict_count = (
                        int(at), int(cnt))
                else:
                    self.fail_predict_at = int(body)
            elif token == "model_corrupt_upload":
                self.corrupt_upload = True
            elif token.startswith("worker_kill@"):
                self.worker_kill = _rank_iter(token, "worker_kill@")
            elif token.startswith("worker_hang@"):
                self.worker_hang = _rank_iter(token, "worker_hang@")
            elif token.startswith("coord_loss@"):
                self.worker_kill = (0, int(token[len("coord_loss@"):]))
            elif token.startswith("slow_worker@"):
                r, ms = _rank_iter(token, "slow_worker@", value=float)
                self.slow_worker = (r, ms / 1e3)
            elif token.startswith("vote_skew@"):
                self.vote_skew = _rank_iter(token, "vote_skew@")
            elif token.startswith("drift_shift@"):
                self.drift_shift = _rank_iter(token, "drift_shift@")
            elif token.startswith("bad_generation@"):
                self.bad_generation = int(token[len("bad_generation@"):])
            elif token.startswith("sketch_corrupt@"):
                self.sketch_corrupt = int(token[len("sketch_corrupt@"):])
            else:
                Log.fatal("Unknown fault token %r in fault spec %r",
                          token, self.spec)

    def once(self, key: str) -> bool:
        if key in self._fired:
            return False
        self._fired.add(key)
        return True


_plan: Optional[FaultPlan] = None


def _get() -> FaultPlan:
    global _plan
    if _plan is None:
        _plan = FaultPlan(os.environ.get("LGBM_TPU_FAULT", ""),
                          int(os.environ.get("LGBM_TPU_FAULT_SEED", "0")))
    return _plan


def install(spec: str, seed: int = 0) -> FaultPlan:
    """Arm a fault plan programmatically (tests)."""
    global _plan
    _plan = FaultPlan(spec, seed)
    return _plan


def clear() -> None:
    """Disarm; the next hook re-reads the environment."""
    global _plan
    _plan = None


# ------------------------------------------------------------------- hooks

def check_kill(iteration: int) -> None:
    """Injection point at the start of GBDT.train_one_iter."""
    p = _get()
    if p.kill_at is not None and iteration == p.kill_at and p.once("kill"):
        _emit_fault("kill", iteration=iteration)
        raise InjectedFault(f"injected fault: kill at iteration {iteration}")


def check_distributed(iteration: int) -> None:
    """Injection point at the start of GBDT.train_one_iter, right after
    check_kill: the distributed fault family. Kill/hang are gated to gang
    attempt 0 — a relaunched gang resumes at the fault iteration and must
    not re-die — while the straggler fires every attempt."""
    p = _get()
    if p.worker_kill is None and p.worker_hang is None \
            and p.slow_worker is None:
        return
    rank = _rank()
    attempt0 = _gang_attempt() == 0
    if p.slow_worker is not None and rank == p.slow_worker[0]:
        import time

        _emit_fault("slow_worker", rank=rank, iteration=iteration,
                    seconds=p.slow_worker[1])
        time.sleep(p.slow_worker[1])
    if attempt0 and p.worker_kill is not None \
            and (rank, iteration) == p.worker_kill \
            and p.once("worker_kill"):
        _emit_fault("worker_kill", rank=rank, iteration=iteration)
        Log.warning("Fault injection: killing rank %d at iteration %d",
                    rank, iteration)
        if os.environ.get("LGBM_TPU_GANG"):
            # SIGKILL semantics: no unwind, no atexit, no flush
            os._exit(EXIT_INJECTED_KILL)
        raise InjectedFault(
            f"injected fault: worker {rank} killed at iteration {iteration}")
    if attempt0 and p.worker_hang is not None \
            and (rank, iteration) == p.worker_hang \
            and p.once("worker_hang"):
        _emit_fault("worker_hang", rank=rank, iteration=iteration)
        Log.warning("Fault injection: rank %d hanging at iteration %d "
                    "(interruptible spin)", rank, iteration)
        import time

        from ..parallel import elastic
        while True:
            time.sleep(0.01)
            rt = elastic.active()
            if rt is not None:
                rt.poll_raise()


def vote_skew_params():
    """(mesh_rank, wave) of an armed vote_skew plan, else None. The voting
    learner threads these into the grower as traced scalars; inside the
    vote the nomination row of mesh rank `mesh_rank` is replaced with
    garbage at wave `wave`. Ranks are mesh axis positions (not
    JAX_PROCESS_ID), so a single-process fake-device mesh injects too."""
    return _get().vote_skew


def check_vote_skew_surfaced(miss_total: int, exact_check: bool) -> None:
    """Post-tree hook in the voting learner's finalize: an armed vote_skew
    plan must surface as a TYPED error, never a hang or a silent quality
    loss. Exact-check mode is the detector harness — it aborts with
    VotingDivergenceError carrying the measured election divergence.
    Without exact-check, under an elastic gang, the detecting worker parks
    in the interruptible watchdog spin until the supervisor declares it
    lost — the same WorkerLostError conversion path as worker_hang. With
    neither armed the corruption only shifts split quality, which the
    exact-check counter exists to measure. One-shot, like every
    injection."""
    p = _get()
    if p.vote_skew is None or not p.once("vote_skew"):
        return
    rank, wave = p.vote_skew
    _emit_fault("vote_skew", rank=rank, wave=wave, miss=int(miss_total),
                exact_check=exact_check)
    if exact_check:
        raise VotingDivergenceError(
            f"injected fault: vote_skew@{rank}:{wave} corrupted a PV-Tree "
            f"ballot ({int(miss_total)} committed-split disagreement(s) "
            "counted by the exact check)")
    import time

    from ..parallel import elastic
    if elastic.active() is None:
        Log.warning("vote_skew@%d:%d armed without exact-check or an "
                    "elastic gang: corruption measured nowhere (arm "
                    "LGBM_TPU_VOTING_EXACT_CHECK=1 to count it)",
                    rank, wave)
        return
    Log.warning("Fault injection: vote_skew@%d:%d under an elastic gang — "
                "parking in the watchdog spin", rank, wave)
    while True:
        time.sleep(0.01)
        rt = elastic.active()
        if rt is not None:
            rt.poll_raise()


def maybe_poison_gh(grads, hesses, iteration: int):
    """Injection point after the gradient pass: NaN a seeded row subset of
    the gh wave. One-shot, so a rollback's recomputed gradients are clean."""
    p = _get()
    if p.nan_at is None or iteration != p.nan_at or not p.once("nan_gh"):
        return grads, hesses
    import numpy as np

    n = int(grads.shape[-1])
    k = max(1, int(round(p.nan_frac * n)))
    rng = np.random.RandomState(p.seed + iteration)
    idx = np.sort(rng.choice(n, k, replace=False)).astype(np.int32)
    Log.warning("Fault injection: poisoning %d/%d gradient rows with NaN "
                "at iteration %d", k, n, iteration)
    _emit_fault("nan_gh", iteration=iteration, rows=k)
    if grads.ndim == 1:
        return grads.at[idx].set(float("nan")), hesses.at[idx].set(float("nan"))
    return (grads.at[:, idx].set(float("nan")),
            hesses.at[:, idx].set(float("nan")))


def maybe_fail_write(path: str) -> None:
    """Injection point inside the atomic writer's retry loop, before the
    temp file exists — a transient host-side write failure."""
    p = _get()
    if p.write_fails > 0:
        p.write_fails -= 1
        _emit_fault("write_fail", path=path)
        raise OSError(f"injected fault: transient write failure for {path}")


def maybe_corrupt_artifact(path: str) -> None:
    """Injection point after an atomic write lands: corrupt the sidecar or
    truncate the model text, simulating on-disk damage the loader must
    detect (checksum / fail-fast parse) rather than crash on."""
    p = _get()
    is_sidecar = path.endswith(".ckpt")
    if p.corrupt_sidecar and is_sidecar and p.once("corrupt"):
        with open(path, "rb") as fh:
            data = bytearray(fh.read())
        mid = len(data) // 2
        for i in range(mid, min(mid + 16, len(data))):
            data[i] ^= 0xFF
        with open(path, "wb") as fh:  # graftlint: disable=non-atomic-write -- fault injection deliberately damages the artifact in place
            fh.write(bytes(data))
        Log.warning("Fault injection: corrupted checkpoint sidecar %s", path)
        _emit_fault("corrupt", path=path)
    elif p.truncate_model and not is_sidecar and p.once("truncate"):
        size = os.path.getsize(path)
        with open(path, "rb+") as fh:
            fh.truncate(size // 2)
        Log.warning("Fault injection: truncated %s to %d bytes",
                    path, size // 2)
        _emit_fault("truncate", path=path)


def on_serve_dispatch() -> None:
    """Injection point just before a serving device dispatch (one call per
    batch the micro-batcher sends to the device). Counts dispatches and
    applies the armed slow/fail serving faults in that order, so a single
    plan can model a device that is first slow and then dies."""
    p = _get()
    if p.slow_predict_at is None and p.fail_predict_at is None:
        return
    p._dispatch_no += 1
    no = p._dispatch_no
    if p.slow_predict_at is not None and no >= p.slow_predict_at:
        import time

        _emit_fault("slow_predict", dispatch=no, seconds=p.slow_predict_s)
        time.sleep(p.slow_predict_s)
    if p.fail_predict_at is not None and \
            p.fail_predict_at <= no < p.fail_predict_at + p.fail_predict_count:
        _emit_fault("predict_fail", dispatch=no)
        raise InjectedFault(
            f"injected fault: device dispatch {no} failed")


def maybe_corrupt_upload(text: str) -> str:
    """Injection point in the model registry's staged-load path: garble the
    upload BEFORE verification (one-shot). Digits flip too, so even a parse
    that survives the '#' noise cannot reproduce the original checksum."""
    p = _get()
    if not p.corrupt_upload or not p.once("corrupt_upload"):
        return text
    mid = len(text) // 2
    _emit_fault("corrupt_upload", bytes=64)
    Log.warning("Fault injection: corrupted staged model upload "
                "(%d chars garbled)", min(64, len(text) - mid))
    return text[:mid] + "#" * min(64, len(text) - mid) + text[mid + 64:]


def maybe_shift_block(block, start_row: int):
    """Injection point at the top of RowBlockStore.push_rows: apply the
    planted covariate shift to every row whose absolute index is at or
    past the armed threshold. Continuous (drift must persist across
    checks), but the telemetry record is one-shot."""
    p = _get()
    if p.drift_shift is None:
        return block
    at, feat = p.drift_shift
    end_row = start_row + block.shape[0]
    if end_row <= at or feat >= block.shape[1]:
        return block
    import numpy as np

    block = np.array(block, copy=True)  # graftlint: disable=jit-host-sync-xmod -- pushed blocks are host numpy already; the copy keeps the caller's array unshifted
    lo = max(0, at - start_row)
    block[lo:, feat] = block[lo:, feat] * 3.0 + 10.0
    if p.once("drift_shift"):
        Log.warning("Fault injection: shifting feature %d out of bin "
                    "support from row %d onward", feat, at)
        _emit_fault("drift_shift", feature=feat, start_row=at)
    return block


def maybe_poison_generation(booster, generation: int):
    """Injection point after a refit trains (and checkpoints) generation G:
    rebuild the booster from model text with every leaf value sign-flipped and scaled 1e6 —
    a genuinely broken candidate that only the publish quality gate stands
    between and live traffic. In-memory only: the on-disk checkpoint keeps
    the good model, so the retry after rejection republishes clean."""
    p = _get()
    if p.bad_generation is None or generation != p.bad_generation \
            or not p.once("bad_generation"):
        return booster
    import re

    from .. import basic

    Log.warning("Fault injection: poisoning the trained model for "
                "generation %d (leaf values sign-flipped and scaled 1e6)", generation)
    _emit_fault("bad_generation", generation=generation)
    txt = booster.model_to_string()
    poisoned = re.sub(
        r"^leaf_value=(.*)$",
        lambda m: "leaf_value=" + " ".join(
            repr(float(v) * -1e6) for v in m.group(1).split()),
        txt, flags=re.M)
    return basic.Booster(model_str=poisoned)


def sketch_corrupt_feature() -> Optional[int]:
    """Injection point in the drift monitor's scoring pass: returns the
    feature index whose sketch should be poisoned with non-finite garbage
    (one-shot), or None."""
    p = _get()
    if p.sketch_corrupt is None or not p.once("sketch_corrupt"):
        return None
    Log.warning("Fault injection: corrupting the quantile sketch for "
                "feature %d", p.sketch_corrupt)
    _emit_fault("sketch_corrupt", feature=p.sketch_corrupt)
    return p.sketch_corrupt


def _emit_fault(kind: str, **fields) -> None:
    """Record the injection in the telemetry stream AND the always-on
    flight recorder, then dump a postmortem (rate-limited per kind so a
    fault storm costs one write, not one per firing). Lazy imports: this
    module loads before the package's telemetry module in some paths."""
    from .. import telemetry, tracing
    telemetry.emit("fault", kind=kind, **fields)
    tracing.note("fault", fault=kind, **fields)
    tracing.dump_flight(f"fault_{kind}")
