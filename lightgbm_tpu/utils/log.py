"""Logging with levels + redirectable callback.

TPU-native counterpart of the reference's Log class
(include/LightGBM/utils/log.h:78-180): four levels (Fatal/Warning/Info/Debug),
a process-wide verbosity, and a registerable output callback (the reference
exposes this through LGBM_RegisterLogCallback, c_api.h:73).
"""
from __future__ import annotations

import sys
import threading
from typing import Callable, Optional

_state = threading.local()


class LightGBMError(Exception):
    """Raised by Log.fatal — mirrors the reference's LightGBMException."""


def _default_writer(msg: str) -> None:
    sys.stdout.write(msg)
    sys.stdout.flush()


_callback: Optional[Callable[[str], None]] = None
_verbosity = 1  # matches config `verbosity` default: <0 fatal, 0 warn, 1 info, >1 debug


def register_log_callback(cb: Optional[Callable[[str], None]]) -> None:
    global _callback
    _callback = cb


def set_verbosity(level: int) -> None:
    global _verbosity
    _verbosity = level


class Log:
    @staticmethod
    def _write(level_str: str, msg: str) -> None:
        out = f"[LightGBM-TPU] [{level_str}] {msg}\n"
        (_callback or _default_writer)(out)

    @staticmethod
    def debug(msg: str, *args) -> None:
        if _verbosity > 1:
            Log._write("Debug", msg % args if args else msg)

    @staticmethod
    def info(msg: str, *args) -> None:
        if _verbosity >= 1:
            Log._write("Info", msg % args if args else msg)

    @staticmethod
    def warning(msg: str, *args) -> None:
        if _verbosity >= 0:
            Log._write("Warning", msg % args if args else msg)

    @staticmethod
    def fatal(msg: str, *args) -> None:
        text = msg % args if args else msg
        Log._write("Fatal", text)
        raise LightGBMError(text)
