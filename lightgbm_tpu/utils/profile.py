"""Device-level profiling: jax.profiler trace capture around training.

The reference's tracing story is the CHECK/timer macros summarized at exit
(src/utils/common.h timers, Log::Info dumps); ours is two layers:

  * `global_timer` (utils/timer.py) — host-side scoped wall-clock sums,
    printed via `print_timer_summary()` like the reference's timer table.
  * THIS module — XLA device traces. `maybe_trace()` wraps a training run
    in `jax.profiler.trace` when LGBM_TPU_PROFILE=<dir> is set (or a dir is
    passed explicitly), producing a TensorBoard-loadable xplane profile of
    every kernel the run dispatched. Used by engine.train and the CLI, so

        LGBM_TPU_PROFILE=/tmp/prof python -m lightgbm_tpu.cli config=...

    captures the whole training run with zero code changes.
"""
from __future__ import annotations

import contextlib
import os
from typing import Optional

from .log import Log

ENV_VAR = "LGBM_TPU_PROFILE"
ENV_VAR_LEGACY = "LGBM_TPU_PROFILE_DIR"  # same job, older spelling


@contextlib.contextmanager
def maybe_trace(trace_dir: Optional[str] = None):
    """Trace into `trace_dir` (or $LGBM_TPU_PROFILE / $LGBM_TPU_PROFILE_DIR);
    no-op when unset."""
    target = (trace_dir or os.environ.get(ENV_VAR)
              or os.environ.get(ENV_VAR_LEGACY))
    if not target:
        yield
        return
    _check_writable(target)
    import jax

    Log.info("Profiling to %s (load with TensorBoard's profile plugin)",
             target)
    try:
        with jax.profiler.trace(target):
            yield
    finally:
        # the partial profile of a crashed run is often the most useful
        # artifact it leaves behind — always say where it landed
        Log.info("Profile written to %s", target)


def _check_writable(target: str) -> None:
    """Fail fast with a named invariant instead of the deep TraceMe/XLA
    traceback jax.profiler.trace raises mid-run on an unwritable target."""
    probe = target
    while probe and not os.path.exists(probe):
        parent = os.path.dirname(probe)
        if parent == probe:
            break
        probe = parent
    if os.path.isfile(target):
        Log.fatal("Profile target %s is a file, not a directory", target)
    if not probe or not os.access(probe, os.W_OK):
        Log.fatal("Profile target %s is not writable (nearest existing "
                  "ancestor: %s) — fix LGBM_TPU_PROFILE or the trace_dir "
                  "argument", target, probe or "<none>")


def annotate(name: str):
    """Named sub-span inside a capture (jax.profiler.TraceAnnotation), for
    marking phases (binning, tree N, eval) in the device timeline."""
    import jax

    return jax.profiler.TraceAnnotation(name)
