"""Runtime donation/sync sanitizer (LGBM_TPU_SANITIZE=1).

The dynamic counterpart of graftlint's static R1/R10 passes: where the
linter proves properties over the call graph, the sanitizer enforces them
on a real run —

* **Use-after-donation poisoning.** `guard(fn, donate, site)` wraps a
  dispatch whose jit donates buffer arguments. After the call, every
  donated `jax.Array` positional arg is deleted and registered; any later
  host access to that Python reference raises `UseAfterDonationError`
  naming the donation site, instead of silently reading a recycled buffer
  on TPU (on CPU, where XLA ignores donation, the bug would otherwise pass
  tests and only corrupt results on the accelerator).

* **Sync accounting.** Host-sync entry points on `jax.Array`
  (`item`/`tolist`/`block_until_ready`/`__bool__`/`__float__`/`__int__`)
  are counted per innermost `global_timer.scope` label (the timer keeps
  its label stack even with LGBM_TPU_TIMETAG off). Scopes listed in
  `SYNC_FREE` assert zero syncs: any counted sync while such a scope is
  open raises `SyncInScopeError` naming the scope and the sync kind.

Known gap: `np.asarray(arr)` reaches the host through the buffer protocol
without calling any patchable `jax.Array` method (patching `__array__` on
ArrayImpl does not intercept it), so asarray pulls are invisible to the
sync counter. They ARE covered by the poison pass — asarray on a deleted
array still goes through `_check_if_deleted` — and by graftlint R1
statically.

Everything here is inert unless enabled: `guard` returns its argument
unchanged and no class is patched, so the production path pays one
function call and an env lookup per tree dispatch.
"""
from __future__ import annotations

import os
from collections import defaultdict
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from .timer import global_timer


class UseAfterDonationError(RuntimeError):
    """A host access hit a buffer that was donated to an earlier dispatch."""


class SyncInScopeError(RuntimeError):
    """A device sync happened inside a scope declared sync-free."""


# scopes asserted to perform ZERO countable device syncs while open
SYNC_FREE = {"tree_device", "goss_device_select"}

_forced: Optional[bool] = None
_installed = False
_orig: Dict[str, Callable] = {}
# id(arr) -> (arr, site): strong refs keep id() stable for the run
_poisoned: Dict[int, Tuple[Any, str]] = {}
_sync_counts: Dict[str, Dict[str, int]] = defaultdict(
    lambda: defaultdict(int))


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return os.environ.get("LGBM_TPU_SANITIZE", "") not in ("", "0")


def enable() -> None:
    """Force-on regardless of the env var; installs the jax.Array patches."""
    global _forced
    _forced = True
    _install()


def disable() -> None:
    """Force-off regardless of the env var; patches stay installed but
    become pass-throughs (they consult `enabled()` per call)."""
    global _forced
    _forced = False


def clear_override() -> None:
    """Back to env-var-driven (undoes enable()/disable())."""
    global _forced
    _forced = None


def reset() -> None:
    """Drop the poison registry and sync counters (between test cases)."""
    _poisoned.clear()
    _sync_counts.clear()


def sync_counts() -> Dict[str, Dict[str, int]]:
    """Per-scope-label sync counts: {label: {kind: n}}."""
    return {label: dict(kinds) for label, kinds in _sync_counts.items()}


def _note_sync(kind: str) -> None:
    stack = global_timer.label_stack
    label = stack[-1] if stack else "<no-scope>"
    _sync_counts[label][kind] += 1
    bad = SYNC_FREE.intersection(stack)
    if bad:
        scope = sorted(bad)[0]
        raise SyncInScopeError(
            f"device sync ({kind}) inside the sync-free scope {scope!r}: "
            f"this region is asserted to stay on-device end to end — a "
            f"sync here serializes the async pipeline (see "
            f"docs/PERF_NOTES.md)")


def _install() -> None:
    """Patch jax.Array's concrete class once per process.

    The poison check rides `_check_if_deleted`, which every host-facing
    accessor (item, __array__, np.asarray, device_get, ...) calls first;
    the sync counters wrap the explicit sync entry points.
    """
    global _installed
    if _installed:
        return
    from jax._src.array import ArrayImpl

    _orig["_check_if_deleted"] = ArrayImpl._check_if_deleted

    def _checked(self):
        ent = _poisoned.get(id(self))
        if ent is not None:
            raise UseAfterDonationError(
                f"this array's buffer was donated to {ent[1]}; XLA reuses "
                f"donated buffers in place, so reading the old reference "
                f"returns garbage on TPU — copy before the dispatch or "
                f"read the dispatch's output instead")
        return _orig["_check_if_deleted"](self)

    ArrayImpl._check_if_deleted = _checked

    def _counted(name: str):
        orig = _orig[name]

        def wrapper(self, *args, **kwargs):
            if enabled():
                _note_sync(name)
            return orig(self, *args, **kwargs)

        wrapper.__name__ = name
        return wrapper

    for name in ("item", "tolist", "block_until_ready",
                 "__bool__", "__float__", "__int__"):
        _orig[name] = getattr(ArrayImpl, name)
        setattr(ArrayImpl, name, _counted(name))
    _installed = True


def guard(fn: Callable, donate: Sequence[int], site: str) -> Callable:
    """Wrap a donating dispatch so its donated args are poisoned after use.

    `donate` lists the POSITIONAL indices the jit donates (its
    donate_argnums); `site` names the dispatch for the eventual error.
    Identity when the sanitizer is off. Args that reappear in the output
    pytree (possible when XLA aliases through) are left alone.
    """
    if not enabled():
        return fn
    _install()
    import jax

    def wrapper(*args, **kwargs):
        out = fn(*args, **kwargs)
        out_ids = {id(leaf) for leaf in jax.tree_util.tree_leaves(out)}
        for i in donate:
            if i >= len(args):
                continue
            arr = args[i]
            if isinstance(arr, jax.Array) and id(arr) not in out_ids:
                # when the jit really donated (TPU, or CPU backends that
                # honor it) the buffer is ALREADY deleted — registering it
                # upgrades jax's generic "Array has been deleted" into an
                # error naming the donation site; on backends that ignore
                # donation, delete() poisons it ourselves (async-safe: the
                # runtime holds the buffer until in-flight consumers
                # finish)
                if not arr.is_deleted():
                    arr.delete()
                _poisoned[id(arr)] = (arr, site)
        return out

    return wrapper
