"""Runtime donation/sync sanitizer (LGBM_TPU_SANITIZE=1).

The dynamic counterpart of graftlint's static R1/R10 passes: where the
linter proves properties over the call graph, the sanitizer enforces them
on a real run —

* **Use-after-donation poisoning.** `guard(fn, donate, site)` wraps a
  dispatch whose jit donates buffer arguments. After the call, every
  donated `jax.Array` positional arg is deleted and registered; any later
  host access to that Python reference raises `UseAfterDonationError`
  naming the donation site, instead of silently reading a recycled buffer
  on TPU (on CPU, where XLA ignores donation, the bug would otherwise pass
  tests and only corrupt results on the accelerator).

* **Sync accounting.** Host-sync entry points on `jax.Array`
  (`item`/`tolist`/`block_until_ready`/`__bool__`/`__float__`/`__int__`)
  are counted per innermost `global_timer.scope` label (the timer keeps
  its label stack even with LGBM_TPU_TIMETAG off). Scopes listed in
  `SYNC_FREE` assert zero syncs: any counted sync while such a scope is
  open raises `SyncInScopeError` naming the scope and the sync kind.

* **Collective-order cross-check.** The dynamic oracle for graftlint
  R12: when enabled, `jax.lax.psum` / `psum_scatter` / `all_gather` are
  wrapped to record each (op, axis_name) the process TRACES, as a
  deterministic rolling CRC per step. `check_collective_order()` — called
  from the elastic heartbeat's existing sync slot and directly by tests —
  all-gathers the per-rank prefix fingerprints and raises a typed
  `CollectiveOrderError(rank, first_divergent_op)` naming the first op
  where this rank's sequence left the gang's. Trace-time recording is
  deliberate: it is sync-free (R12's sequences are trace properties), and
  a rank that traces a collective the others never trace is exactly the
  static rule's deadlock — caught here before the mesh hangs. A
  re-executed cached jit does not re-trace, so sequences are compared per
  distinct traced program, not per dispatch.

Known gap: `np.asarray(arr)` reaches the host through the buffer protocol
without calling any patchable `jax.Array` method (patching `__array__` on
ArrayImpl does not intercept it), so asarray pulls are invisible to the
sync counter. They ARE covered by the poison pass — asarray on a deleted
array still goes through `_check_if_deleted` — and by graftlint R1
statically.

Everything here is inert unless enabled: `guard` returns its argument
unchanged and no class is patched, so the production path pays one
function call and an env lookup per tree dispatch.
"""
from __future__ import annotations

import os
import zlib
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .timer import global_timer


class UseAfterDonationError(RuntimeError):
    """A host access hit a buffer that was donated to an earlier dispatch."""


class SyncInScopeError(RuntimeError):
    """A device sync happened inside a scope declared sync-free."""


class CollectiveOrderError(RuntimeError):
    """This rank's traced collective sequence diverged from the gang's.

    `rank` is the process that detected the divergence (the raiser),
    `first_divergent_op` names this rank's op at the first step where the
    prefix fingerprints disagree ("<none>" when this rank posted fewer
    collectives than the others)."""

    def __init__(self, message: str, rank: int = -1,
                 first_divergent_op: str = "") -> None:
        super().__init__(message)
        self.rank = int(rank)
        self.first_divergent_op = first_divergent_op


# scopes asserted to perform ZERO countable device syncs while open
SYNC_FREE = {"tree_device", "goss_device_select"}

_forced: Optional[bool] = None
_installed = False
_orig: Dict[str, Callable] = {}
# id(arr) -> (arr, site): strong refs keep id() stable for the run
_poisoned: Dict[int, Tuple[Any, str]] = {}
_sync_counts: Dict[str, Dict[str, int]] = defaultdict(
    lambda: defaultdict(int))
# traced collective sequence: (op, axis_repr) in trace order, plus the
# rolling CRC after each step (process-independent: zlib.crc32, no string
# hash salting)
_collective_seq: List[Tuple[str, str]] = []
_collective_crcs: List[int] = []
# prefix slots exchanged by check_collective_order: enough that real
# divergence (which appears at the first differing op) is always visible
_FP_SLOTS = 32

_COLLECTIVE_OPS = ("psum", "psum_scatter", "all_gather")


def enabled() -> bool:
    if _forced is not None:
        return _forced
    return os.environ.get("LGBM_TPU_SANITIZE", "") not in ("", "0")


def enable() -> None:
    """Force-on regardless of the env var; installs the jax.Array patches."""
    global _forced
    _forced = True
    _install()


def disable() -> None:
    """Force-off regardless of the env var; patches stay installed but
    become pass-throughs (they consult `enabled()` per call)."""
    global _forced
    _forced = False


def clear_override() -> None:
    """Back to env-var-driven (undoes enable()/disable())."""
    global _forced
    _forced = None


def reset() -> None:
    """Drop the poison registry, sync counters and collective sequence
    (between test cases)."""
    _poisoned.clear()
    _sync_counts.clear()
    _collective_seq.clear()
    _collective_crcs.clear()


def sync_counts() -> Dict[str, Dict[str, int]]:
    """Per-scope-label sync counts: {label: {kind: n}}."""
    return {label: dict(kinds) for label, kinds in _sync_counts.items()}


def _note_sync(kind: str) -> None:
    stack = global_timer.label_stack
    label = stack[-1] if stack else "<no-scope>"
    _sync_counts[label][kind] += 1
    bad = SYNC_FREE.intersection(stack)
    if bad:
        scope = sorted(bad)[0]
        raise SyncInScopeError(
            f"device sync ({kind}) inside the sync-free scope {scope!r}: "
            f"this region is asserted to stay on-device end to end — a "
            f"sync here serializes the async pipeline (see "
            f"docs/PERF_NOTES.md)")


def _install() -> None:
    """Patch jax.Array's concrete class once per process.

    The poison check rides `_check_if_deleted`, which every host-facing
    accessor (item, __array__, np.asarray, device_get, ...) calls first;
    the sync counters wrap the explicit sync entry points.
    """
    global _installed
    if _installed:
        return
    from jax._src.array import ArrayImpl

    _orig["_check_if_deleted"] = ArrayImpl._check_if_deleted

    def _checked(self):
        ent = _poisoned.get(id(self))
        if ent is not None:
            raise UseAfterDonationError(
                f"this array's buffer was donated to {ent[1]}; XLA reuses "
                f"donated buffers in place, so reading the old reference "
                f"returns garbage on TPU — copy before the dispatch or "
                f"read the dispatch's output instead")
        return _orig["_check_if_deleted"](self)

    ArrayImpl._check_if_deleted = _checked

    def _counted(name: str):
        orig = _orig[name]

        def wrapper(self, *args, **kwargs):
            if enabled():
                _note_sync(name)
            return orig(self, *args, **kwargs)

        wrapper.__name__ = name
        return wrapper

    for name in ("item", "tolist", "block_until_ready",
                 "__bool__", "__float__", "__int__"):
        _orig[name] = getattr(ArrayImpl, name)
        setattr(ArrayImpl, name, _counted(name))

    import jax

    def _probed(op: str):
        orig = _orig["lax." + op]

        def wrapper(x, axis_name=None, *args, **kwargs):
            if axis_name is None and "axis_name" in kwargs:
                axis_name = kwargs["axis_name"]
            if enabled():
                _note_collective(op, axis_name)
            if axis_name is None:
                return orig(x, *args, **kwargs)
            return orig(x, axis_name, *args, **kwargs)

        wrapper.__name__ = op
        return wrapper

    for op in _COLLECTIVE_OPS:
        _orig["lax." + op] = getattr(jax.lax, op)
        setattr(jax.lax, op, _probed(op))
    _installed = True


def _note_collective(op: str, axis_name: Any) -> None:
    """Record one traced collective: append (op, axis) and roll the CRC.
    Runs at TRACE time inside jit, which is host-side and sync-free."""
    axis = repr(axis_name)
    _collective_seq.append((op, axis))
    prev = _collective_crcs[-1] if _collective_crcs else 0
    step = ("%s@%s" % (op, axis)).encode("utf-8")
    _collective_crcs.append(zlib.crc32(step, prev) & 0xFFFFFFFF)


def collective_sequence() -> List[Tuple[str, str]]:
    """The (op, axis) pairs this process has traced, in order."""
    return list(_collective_seq)


def collective_fingerprint() -> Tuple[int, int]:
    """(count, rolling CRC of the full sequence) — cheap equality probe."""
    return (len(_collective_seq),
            _collective_crcs[-1] if _collective_crcs else 0)


def _fingerprint_vector() -> "Any":
    """[count, crc_1..crc_K]: the per-rank row exchanged by the check.
    Slot i holds the CRC of the first i+1 ops (0 when fewer were traced),
    so the first differing slot IS the first divergent op index."""
    import numpy as np

    vec = np.zeros((_FP_SLOTS + 1,), dtype=np.uint32)
    vec[0] = min(len(_collective_seq), np.iinfo(np.uint32).max)
    for i, crc in enumerate(_collective_crcs[:_FP_SLOTS]):
        vec[1 + i] = crc
    return vec


def check_collective_order(gather_fn: Optional[Callable] = None) -> None:
    """Cross-check the traced collective sequence against every rank.

    Rides the elastic heartbeat's sync slot (heartbeat_sync calls this
    when the sanitizer is on and the world is multi-process); tests call
    it directly. `gather_fn(vec) -> [world, len(vec)]` defaults to
    `multihost_utils.process_allgather` — inject a fake for single-process
    tests. No-op when disabled or when the gathered world is 1.

    Raises CollectiveOrderError(rank, first_divergent_op) on the first
    rank whose prefix fingerprints disagree with any other rank's.
    """
    if not enabled():
        return
    import numpy as np

    mine = _fingerprint_vector()
    if gather_fn is None:
        import jax
        from jax.experimental import multihost_utils

        if jax.process_count() <= 1:
            return
        rank = jax.process_index()
        rows = np.asarray(multihost_utils.process_allgather(mine))
    else:
        import jax

        rank = int(getattr(jax, "process_index", lambda: 0)())
        rows = np.asarray(gather_fn(mine))
    if rows.ndim != 2 or rows.shape[0] <= 1:
        return
    for other in range(rows.shape[0]):
        if np.array_equal(rows[other], mine):
            continue
        # first prefix slot (op index) where this rank and `other` split
        div = None
        for i in range(_FP_SLOTS):
            if rows[other][1 + i] != mine[1 + i]:
                div = i
                break
        if div is None:
            # prefixes agree through every slot: the counts differ
            div = min(int(mine[0]), int(rows[other][0]))
        if div < len(_collective_seq):
            op = "%s@%s" % _collective_seq[div]
        else:
            op = "<none: this rank traced %d collective(s), rank %d "\
                 "traced %d>" % (int(mine[0]), other, int(rows[other][0]))
        raise CollectiveOrderError(
            "collective order divergence: rank %d and rank %d traced "
            "different collective sequences, first divergent op #%d is "
            "%s on this rank — every rank must issue the same collectives "
            "in the same order or the mesh deadlocks (graftlint R12 is "
            "the static form of this check)" % (rank, other, div, op),
            rank=rank, first_divergent_op=op)


def guard(fn: Callable, donate: Sequence[int], site: str) -> Callable:
    """Wrap a donating dispatch so its donated args are poisoned after use.

    `donate` lists the POSITIONAL indices the jit donates (its
    donate_argnums); `site` names the dispatch for the eventual error.
    Identity when the sanitizer is off. Args that reappear in the output
    pytree (possible when XLA aliases through) are left alone.
    """
    if not enabled():
        return fn
    _install()
    import jax

    def wrapper(*args, **kwargs):
        out = fn(*args, **kwargs)
        out_ids = {id(leaf) for leaf in jax.tree_util.tree_leaves(out)}
        for i in donate:
            if i >= len(args):
                continue
            arr = args[i]
            if isinstance(arr, jax.Array) and id(arr) not in out_ids:
                # when the jit really donated (TPU, or CPU backends that
                # honor it) the buffer is ALREADY deleted — registering it
                # upgrades jax's generic "Array has been deleted" into an
                # error naming the donation site; on backends that ignore
                # donation, delete() poisons it ourselves (async-safe: the
                # runtime holds the buffer until in-flight consumers
                # finish)
                if not arr.is_deleted():
                    arr.delete()
                _poisoned[id(arr)] = (arr, site)
        return out

    return wrapper
