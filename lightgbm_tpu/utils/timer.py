"""Per-label accumulating wall-clock timer.

Counterpart of the reference's Common::Timer/FunctionTimer RAII scopes
(include/LightGBM/utils/common.h:979-1063) that feed `global_timer`, printed
at exit under -DUSE_TIMETAG. Here: a context-manager / decorator that
accumulates per-label seconds, plus jax.profiler trace annotation so the same
labels appear in TPU traces.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Iterator


class GlobalTimer:
    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.counters: Dict[str, int] = defaultdict(int)
        self.enabled = bool(os.environ.get("LGBM_TPU_TIMETAG"))

    @contextlib.contextmanager
    def scope(self, label: str) -> Iterator[None]:
        if not self.enabled:
            yield
            return
        try:
            import jax.profiler

            ctx = jax.profiler.TraceAnnotation(label)
        except Exception:  # pragma: no cover - profiler unavailable
            ctx = contextlib.nullcontext()
        start = time.perf_counter()
        with ctx:
            yield
        self.totals[label] += time.perf_counter() - start
        self.counts[label] += 1

    def add_count(self, label: str, n: int) -> None:
        """Accumulate a work counter (rows histogrammed, bytes moved, ...).

        Always on, unlike the wall-clock scopes: counters are cheap ints
        and the perf tests assert on them (e.g. `device_hist_rows` proving
        the rows-in-leaf wave path is O(selected rows), not O(N * waves)).
        """
        self.counters[label] += int(n)

    def set_count(self, label: str, n: int) -> None:
        """Set a gauge counter (a level, not an accumulation): idempotent,
        so per-tree code can re-publish a static figure — e.g. the device
        learner's `device_carry_bytes_per_wave` — without inflating it."""
        self.counters[label] = int(n)

    def report(self) -> str:
        lines = ["LightGBM-TPU timer summary:"]
        for label in sorted(self.totals, key=self.totals.get, reverse=True):
            lines.append(f"  {label}: {self.totals[label]:.3f}s ({self.counts[label]} calls)")
        for label in sorted(self.counters):
            lines.append(f"  {label}: {self.counters[label]}")
        return "\n".join(lines)

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()
        self.counters.clear()


global_timer = GlobalTimer()


def timed(label: str):
    """Decorator form of global_timer.scope."""

    def deco(fn):
        def wrapper(*args, **kwargs):
            with global_timer.scope(label):
                return fn(*args, **kwargs)

        wrapper.__name__ = getattr(fn, "__name__", "timed")
        return wrapper

    return deco
