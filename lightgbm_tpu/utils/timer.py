"""Per-label accumulating wall-clock timer.

Counterpart of the reference's Common::Timer/FunctionTimer RAII scopes
(include/LightGBM/utils/common.h:979-1063) that feed `global_timer`, printed
at exit under -DUSE_TIMETAG. Here: a context-manager / decorator that
accumulates per-label seconds, plus jax.profiler trace annotation so the same
labels appear in TPU traces.

The timer doubles as the span source for the structured telemetry stack
(lightgbm_tpu/telemetry.py): a session installs `span_hook`, every closed
scope reports (label, start, end) to it, and the Chrome-trace exporter turns
those into B/E span events. `new_epoch()` gives each engine.train() call a
fresh accumulation window so back-to-back runs in one process stop
conflating totals (counters survive — perf tests read them after train).
"""
from __future__ import annotations

import contextlib
import functools
import os
import time
from collections import defaultdict
from typing import Callable, Dict, Iterator, List, Optional


class GlobalTimer:
    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.counters: Dict[str, int] = defaultdict(int)
        # labels published via set_count (levels, not accumulations) — lets
        # telemetry report gauges absolute and accumulators as deltas
        self.gauges: set = set()
        self.enabled = bool(os.environ.get("LGBM_TPU_TIMETAG"))
        self.epoch = 0
        # telemetry sink: called as span_hook(label, t0, t1) on every closed
        # scope (perf_counter seconds). None when no session is recording.
        self.span_hook: Optional[Callable[[str, float, float], None]] = None
        # always-maintained stack of open scope labels (a list push/pop is
        # nanoseconds): the sanitizer attributes counted device syncs to
        # the innermost scope even when wall-clock timing is off, so
        # sync-free assertions (utils/sanitize.py) work without TIMETAG.
        self.label_stack: List[str] = []

    @contextlib.contextmanager
    def scope(self, label: str) -> Iterator[None]:
        if not self.enabled:
            self.label_stack.append(label)
            try:
                yield
            finally:
                self.label_stack.pop()
            return
        try:
            import jax.profiler

            ctx = jax.profiler.TraceAnnotation(label)
        except Exception:  # pragma: no cover - profiler unavailable
            ctx = contextlib.nullcontext()
        start = time.perf_counter()
        self.label_stack.append(label)
        try:
            with ctx:
                yield
        finally:
            self.label_stack.pop()
        end = time.perf_counter()
        self.totals[label] += end - start
        self.counts[label] += 1
        if self.span_hook is not None:
            self.span_hook(label, start, end)

    def add_count(self, label: str, n: int) -> None:
        """Accumulate a work counter (rows histogrammed, bytes moved, ...).

        Always on, unlike the wall-clock scopes: counters are cheap ints
        and the perf tests assert on them (e.g. `device_hist_rows` proving
        the rows-in-leaf wave path is O(selected rows), not O(N * waves)).
        """
        self.counters[label] += int(n)

    def set_count(self, label: str, n: int) -> None:
        """Set a gauge counter (a level, not an accumulation): idempotent,
        so per-tree code can re-publish a static figure — e.g. the device
        learner's `device_carry_bytes_per_wave` — without inflating it."""
        self.counters[label] = int(n)
        self.gauges.add(label)

    def report(self) -> str:
        lines = ["LightGBM-TPU timer summary:"]
        # deterministic: totals descending, equal totals tie-broken by label
        for label in sorted(self.totals, key=lambda k: (-self.totals[k], k)):
            lines.append(f"  {label}: {self.totals[label]:.3f}s ({self.counts[label]} calls)")
        for label in sorted(self.counters):
            lines.append(f"  {label}: {self.counters[label]}")
        return "\n".join(lines)

    def reset(self) -> None:
        self.totals.clear()
        self.counts.clear()
        self.counters.clear()

    def new_epoch(self) -> int:
        """Start a fresh per-run accumulation window: wall-clock totals and
        call counts reset; work counters SURVIVE (bench.py and the learner
        perf tests read them after training returns). Returns the new epoch
        id so telemetry records can name the run they belong to."""
        self.totals.clear()
        self.counts.clear()
        self.epoch += 1
        return self.epoch


global_timer = GlobalTimer()


def timed(label: str):
    """Decorator form of global_timer.scope."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with global_timer.scope(label):
                return fn(*args, **kwargs)

        return wrapper

    return deco
