"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Mirrors the reference's DistributedMockup strategy (tests/distributed/
_test_distributed.py) of exercising the real collective path on one machine:
here `xla_force_host_platform_device_count=8` gives 8 XLA CPU devices so
shard_map/pjit collective code paths run exactly as they would across a TPU
slice.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# The gain-adaptive wave controller (default on) walks wave_k down a
# bucket_size rung per tree, and every rung is a fresh static shape for
# grow_tree_on_device — a few extra XLA compiles that amortize over real
# training runs but triple the wall time of every 3-iteration device test
# here. Pin it off for the suite; the controller's own tests opt back in
# with monkeypatch.setenv("LGBM_TPU_ADAPTIVE_WAVE", "1").
os.environ.setdefault("LGBM_TPU_ADAPTIVE_WAVE", "0")

import jax  # noqa: E402

# The hosted-TPU (axon) plugin force-selects itself via
# jax.config.update("jax_platforms", "axon,cpu") in sitecustomize, overriding
# the JAX_PLATFORMS env var. Tests must run on the virtual 8-device CPU mesh,
# so override it back before any backend initializes.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: long-running test")


@pytest.fixture
def rng():
    return np.random.RandomState(42)


@pytest.fixture(autouse=True)
def _log_state_isolated():
    """Log verbosity and callback are process globals (the CLI sets them);
    restore them so a `verbosity=-1` run can't mute a later test's
    warning assertions."""
    from lightgbm_tpu.utils import log as _log

    verbosity, callback = _log._verbosity, _log._callback
    yield
    _log._verbosity, _log._callback = verbosity, callback
