"""R4 fixture spec: one read param, one ghost, one suppressed."""
PARAM_SPEC = [
    ('used_param', 'int', 0, [], [], False),
    ('ghost_param', 'int', 0, [], [], False),
    ('surface_param', 'int', 0, [], [], False),  # graftlint: disable=param-unread -- fixture: reference-surface only
]
