"""R8 fixture: bare write-mode opens in a model-save path."""


def save_bad(path, text):
    with open(path, "w") as fh:  # fires: literal write mode, positional
        fh.write(text)


def save_bad_kw(path, data):
    with open(path, mode="wb") as fh:  # fires: write mode via keyword
        fh.write(data)


def load_ok(path):
    with open(path) as fh:  # clean: default read mode
        return fh.read()


def load_ok_explicit(path):
    with open(path, "rb") as fh:  # clean: read mode
        return fh.read()


def save_dynamic(path, text, mode):
    with open(path, mode) as fh:  # clean: non-literal mode, out of reach
        fh.write(text)


def save_suppressed(path, text):
    with open(path, "w") as fh:  # graftlint: disable=non-atomic-write -- scratch debug dump, not a persistence artifact
        fh.write(text)
