"""R5 fixture: the serving hot path ops/predict.py is in scope_exact —
a >50-line pack helper with no timer reference must fire."""


def big_untimed_pack(trees):
    tables = []
    total_nodes = 0
    total_leaves = 0
    max_depth = 0
    for tree in trees:
        n_leaves = tree["num_leaves"]
        n_internal = n_leaves - 1
        total_nodes += n_internal
        total_leaves += n_leaves
        if tree["depth"] > max_depth:
            max_depth = tree["depth"]
        features = []
        thresholds = []
        lefts = []
        rights = []
        for node in range(n_internal):
            features.append(tree["split_feature"][node])
            thresholds.append(tree["threshold"][node])
            lefts.append(tree["left"][node])
            rights.append(tree["right"][node])
        while len(features) < 31:
            features.append(0)
            thresholds.append(0.0)
            lefts.append(-1)
            rights.append(-1)
        values = []
        for leaf in range(n_leaves):
            values.append(tree["leaf_value"][leaf])
        while len(values) < 32:
            values.append(0.0)
        tables.append({
            "features": features,
            "thresholds": thresholds,
            "lefts": lefts,
            "rights": rights,
            "values": values,
        })
    summary = {
        "n_trees": len(trees),
        "total_nodes": total_nodes,
        "total_leaves": total_leaves,
        "max_depth": max_depth,
    }
    padded = []
    for table in tables:
        row = []
        for key in ("features", "thresholds", "lefts", "rights", "values"):
            row.extend(table[key])
        padded.append(row)
    return summary, padded
