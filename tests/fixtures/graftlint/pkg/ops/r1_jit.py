"""R1 fixture: host syncs inside jit-reachable functions."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def jitted_entry(x):
    n = int(x.sum())  # line 9: VIOLATION jit-host-sync (concretization)
    helper(x)
    return n


def helper(x):
    v = x.item()  # line 15: VIOLATION (reachable from jitted_entry)
    host = np.asarray(x)  # line 16: VIOLATION (numpy escape)
    ok = int(x.shape[0])  # shapes are trace-time static: clean
    # graftlint: disable=jit-host-sync -- fixture: value is host-side by contract
    quiet = float(x.mean())  # suppressed
    return v, host, ok, quiet


def cold(x):
    return int(x)  # not jit-reachable: clean
