"""R1 fixture: per-iteration host syncs on fresh dispatches in loops."""
import jax
import numpy as np


@jax.jit
def traverse(x):
    return x * 2


def predict_block(x):
    return traverse(x)


def stream_loop(xs):
    out = 0.0
    for x in xs:
        out += np.asarray(predict_block(x)).sum()  # line 18: VIOLATION
    return out


def buffered_loop(xs):
    acc = []
    total = 0
    for x in xs:
        acc.append(predict_block(x))
    for y in acc:
        total += np.asarray(y).sum()  # pull of a prior dispatch: clean
    return total


def gated_loop(xs):
    total = 0
    for x in xs:
        # graftlint: disable=jit-host-sync -- fixture: tiny scalar pull each round by contract
        total += int(traverse(x).sum())  # suppressed
    return total
