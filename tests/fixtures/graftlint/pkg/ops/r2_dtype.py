"""R2 fixture: array constructors without an explicit dtype."""
import jax.numpy as jnp


def build(n):
    bad = jnp.zeros(n)  # line 6: VIOLATION implicit-dtype
    bad2 = jnp.arange(n)  # line 7: VIOLATION implicit-dtype
    good = jnp.ones(n, dtype=jnp.float32)  # dtype kwarg: clean
    good2 = jnp.arange(0, n, 1, jnp.int32)  # positional dtype slot: clean
    like = jnp.zeros_like(bad)  # *_like inherits deliberately: clean
    quiet = jnp.asarray(n)  # graftlint: disable=R2 -- fixture: family-code suppression
    return bad, bad2, good, good2, like, quiet
