"""R3 fixture: tile misalignment, index_map arity, host ops in kernels."""
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl

TILE = 100  # deliberately unaligned: trips both tile checks when resolved


def kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2
    np.asarray(x_ref)  # line 11: VIOLATION pallas-host-op
    # graftlint: disable=pallas-host-op -- fixture: suppressed host op
    print("debug")  # suppressed


def run(x):
    return pl.pallas_call(
        kernel,
        grid=(4,),
        # line 21: two tile-shape VIOLATIONS (100 % 8, 100 % 128) + arity
        in_specs=[pl.BlockSpec((TILE, 100), lambda i, j: (i, 0))],
        out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0)),
        out_shape=None,
    )(x)


def run_prefetch(x):
    return pl.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(2,),
        in_specs=[
            # graftlint: disable=R3 -- fixture: family-code suppression
            pl.BlockSpec((8, 128), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda i, s: (i, 0)),
    )
