"""R5 fixture: the fused split-scan ops/scan_pallas.py joined scope_exact —
a >50-line staging helper with no timer reference must fire; the jitted
dispatch stays exempt (the call site owns the scope)."""
import jax


def big_untimed_stage(hist, meta, n_bins):
    columns = []
    totals = []
    gates = []
    penalties = []
    n_features = len(meta)
    for f in range(n_features):
        entry = meta[f]
        missing_pos = entry["default_bin"]
        if entry["missing_type"] == 2:
            missing_pos = entry["nbins"] - 1
        has_missing = entry["missing_type"] != 0
        gate = not entry["is_categorical"]
        row = [missing_pos, 1.0 if has_missing else 0.0, entry["nbins"]]
        columns.append(row)
        gates.append(1.0 if gate else 0.0)
        penalties.append(entry.get("penalty", 0.0))
    for f in range(n_features):
        g_total = 0.0
        h_total = 0.0
        c_total = 0.0
        for b in range(n_bins):
            g_total += hist[f][b][0]
            h_total += hist[f][b][1]
            c_total += hist[f][b][2]
        totals.append([g_total, h_total, c_total])
    f_pad = n_features
    while f_pad % 8 != 0:
        f_pad += 1
    padded = []
    for f in range(f_pad):
        if f < n_features:
            row = list(columns[f])
            row.append(gates[f])
            row.append(penalties[f])
            row.extend(totals[f])
        else:
            row = [0.0] * 8
        while len(row) < 128:
            row.append(0.0)
        padded.append(row)
    lanes = []
    for f in range(f_pad):
        lane0 = []
        lane1 = []
        acc = [0.0, 0.0, 0.0]
        for b in range(n_bins):
            if f < n_features:
                acc[0] += hist[f][b][0]
                acc[1] += hist[f][b][1]
                acc[2] += hist[f][b][2]
            lane0.append(list(acc))
            lane1.append([acc[0], acc[1], acc[2]])
        lanes.append((lane0, lane1))
    return padded, lanes


@jax.jit
def big_jitted_scan(hist):
    left = hist.cumsum(axis=1)
    right = hist.sum(axis=1, keepdims=True) - left
    gain_left = left[..., 0] * left[..., 0] / (left[..., 1] + 1e-15)
    gain_right = right[..., 0] * right[..., 0] / (right[..., 1] + 1e-15)
    gain = gain_left + gain_right
    best = gain.argmax(axis=1)
    stats_a = left[..., 0] - right[..., 0]
    stats_b = left[..., 1] - right[..., 1]
    stats_c = left[..., 2] - right[..., 2]
    mix_a = stats_a * gain_left
    mix_b = stats_b * gain_right
    mix_c = stats_c * gain
    spread = mix_a + mix_b + mix_c
    norm = spread / (gain.max(axis=1, keepdims=True) + 1e-15)
    score = norm.sum(axis=1)
    rank_a = score * 2.0
    rank_b = score * 3.0
    rank_c = score * 5.0
    blend_a = rank_a + rank_b
    blend_b = rank_b + rank_c
    blend_c = rank_c + rank_a
    total_a = blend_a.sum()
    total_b = blend_b.sum()
    total_c = blend_c.sum()
    weight_a = total_a / (total_b + 1e-15)
    weight_b = total_b / (total_c + 1e-15)
    weight_c = total_c / (total_a + 1e-15)
    combo = weight_a + weight_b + weight_c
    scaled = gain * combo
    folded = scaled + spread
    capped = folded.clip(0.0)
    final = capped.max(axis=1)
    tie = final - gain.max(axis=1)
    adjusted = final - tie
    return best, adjusted
