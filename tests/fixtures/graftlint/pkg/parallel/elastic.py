"""Elastic-scope fixture: R1 covers parallel/elastic.py — the heartbeat
must ride an existing sync window, not pull per iteration — and R9 keeps
the watchdog's emit path enabled-guarded."""
import jax

from .. import telemetry


@jax.jit
def heartbeat_token(x: jax.Array):
    return x.sum() * 2.0


def watchdog_fire(rank):
    telemetry.emit("worker_lost", rank=rank)  # line 15: VIOLATION R9


def heartbeat_per_iteration(xs):
    alive = 0
    for x in xs:
        alive += int(heartbeat_token(x))  # line 21: VIOLATION R1 loop sync
    return alive


def heartbeat_windowed(xs, every=16):
    alive = 0
    for i, x in enumerate(xs):
        if i % every == 0:
            # graftlint: disable=R1 -- one pull per health window rides the existing sync slot
            alive = int(heartbeat_token(x))
    return alive


def watchdog_fire_guarded(rank):
    if telemetry.enabled():
        telemetry.emit("worker_lost", rank=rank)  # guarded: clean
