"""R7 fixture: collectives must name an axis bound by a shard_map."""
import jax
from jax.sharding import PartitionSpec as P


def reduce_block(x):
    # reached from `wrapped` below: "data" is bound -> clean
    return jax.lax.psum_scatter(x, "data", scatter_dimension=0, tiled=True)


def wrapped(x):
    y = jax.lax.psum(x, "data")  # bound by the shard_map below: clean
    return reduce_block(y)


def make(mesh):
    return jax.shard_map(wrapped, mesh=mesh, in_specs=(P("data"),),
                         out_specs=P())


def wrong_axis(x):
    return jax.lax.psum(x, "batch")  # line 22: VIOLATION (unbound axis)


def never_wrapped(x):
    return jax.lax.all_gather(x, "data")  # line 26: VIOLATION (no shard_map)


def computed_axis(x, ax):
    return jax.lax.psum(x, ax)  # line 30: VIOLATION (non-literal axis)


def no_axis(x):
    return jax.lax.psum(x)  # line 34: VIOLATION (axis name missing)


def suppressed_gather(x):
    # graftlint: disable=collective-axis -- fixture: axis bound by the caller's shard_map in another module
    return jax.lax.all_gather(x, "model", axis=0, tiled=True)


def outer(mesh):
    def inner(x):
        # reached from the wrapped body below via a call edge: clean
        return jax.lax.psum(x, "rows")

    def body(x):
        return inner(x)

    return jax.shard_map(body, mesh=mesh, in_specs=(P("rows"),),
                         out_specs=P("rows"))
