"""Reads used_param (attribute load counts as a read for R4)."""


def apply(cfg):
    return cfg.used_param
