"""S1 fixture: malformed suppression directives are themselves findings."""
X = 1  # graftlint: disable=implicit-dtype
Y = 2  # graftlint: disable=not-a-rule -- bogus rule id
Z = 3  # graftlint: disabled=implicit-dtype -- misspelled directive
