"""Streaming-scope fixture: R1/R6/R9/R10 fire under streaming/ too."""
from functools import partial

import jax
import numpy as np

from .. import telemetry


@jax.jit  # line 10: VIOLATION jit-donation (array params, nothing donated)
def block_hist(block: jax.Array, gh: jax.Array):
    rows = int(gh.sum())  # line 12: VIOLATION jit-host-sync
    return block.sum() + rows


@partial(jax.jit, donate_argnums=(0,))
def accum(acc: jax.Array, chunk: jax.Array):  # acc donated: clean for R6
    return acc + chunk.sum()


def drive(acc, chunk):
    out = accum(acc, chunk)
    host = np.asarray(acc)  # line 23: VIOLATION use-after-donation
    telemetry.emit("stream_block", n=host.size)  # line 24: VIOLATION R9
    return out


def drive_rebound(acc, chunk):
    acc = accum(acc, chunk)  # rebinding kills the stale name: clean
    if telemetry.enabled():
        telemetry.emit("stream_block", n=0)  # guarded: clean
    return acc


# graftlint: disable=jit-donation -- fixture: cached block reused across leaves
@jax.jit
def suppressed_entry(block: jax.Array):
    return block.sum()


def push_sketch(sketch, block):
    sketch.update(block)
    telemetry.emit("sketch_block", rows=block.shape[0])  # line 43: VIOLATION R9 (hot-path sketch emit)
    return sketch


def push_sketch_guarded(sketch, block):
    sketch.update(block)
    if telemetry.enabled():
        telemetry.emit("sketch_block", rows=block.shape[0])  # guarded: clean
    return sketch
