"""R9 fixture for the scope_exact tracing.py entry: the flight-recorder
append (``note``) is the sanctioned unguarded hot-path emit — a bounded
ring store, no payload formatting, no I/O — but any ``telemetry.emit``
added alongside it must still sit under an enabled-guard."""
from . import telemetry

_RING = [None] * 16
_SEQ = 0


def record_span(name, duration_s):
    telemetry.emit("span", name=name,  # line 12: VIOLATION
                   duration_s=duration_s)


def record_span_guarded(name, duration_s):
    if telemetry.enabled():  # idiomatic guard: clean
        telemetry.emit("span", name=name, duration_s=duration_s)


def note(kind, **fields):
    # the recorder append itself: O(1) ring store, no telemetry.emit,
    # no guard needed — must stay clean
    global _SEQ
    _RING[_SEQ % len(_RING)] = (kind, fields)
    _SEQ += 1


def dump(sink):
    # cold postmortem path writing through a foreign .emit-style sink:
    # not a telemetry object, stays clean
    sink.emit(list(_RING))
