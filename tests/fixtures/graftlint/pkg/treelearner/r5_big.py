"""R5 fixture: long hot-path functions must reference global_timer."""
import jax

from ..utils.timer import global_timer, timed


def big_untimed(a):  # line 7: VIOLATION untimed-hot-func (>50 lines)
    a += 1
    a += 2
    a += 3
    a += 4
    a += 5
    a += 6
    a += 7
    a += 8
    a += 9
    a += 10
    a += 11
    a += 12
    a += 13
    a += 14
    a += 15
    a += 16
    a += 17
    a += 18
    a += 19
    a += 20
    a += 21
    a += 22
    a += 23
    a += 24
    a += 25
    a += 26
    a += 27
    a += 28
    a += 29
    a += 30
    a += 31
    a += 32
    a += 33
    a += 34
    a += 35
    a += 36
    a += 37
    a += 38
    a += 39
    a += 40
    a += 41
    a += 42
    a += 43
    a += 44
    a += 45
    a += 46
    a += 47
    a += 48
    a += 49
    a += 50
    return a


def big_timed(a):
    with global_timer.scope("fixture"):
        a += 1
        a += 2
        a += 3
        a += 4
        a += 5
        a += 6
        a += 7
        a += 8
        a += 9
        a += 10
        a += 11
        a += 12
        a += 13
        a += 14
        a += 15
        a += 16
        a += 17
        a += 18
        a += 19
        a += 20
        a += 21
        a += 22
        a += 23
        a += 24
        a += 25
        a += 26
        a += 27
        a += 28
        a += 29
        a += 30
        a += 31
        a += 32
        a += 33
        a += 34
        a += 35
        a += 36
        a += 37
        a += 38
        a += 39
        a += 40
        a += 41
        a += 42
        a += 43
        a += 44
        a += 45
        a += 46
        a += 47
        a += 48
        a += 49
        a += 50
    return a


@jax.jit
def big_jitted(a):  # jit-decorated: exempt (call site owns the scope)
    a += 1
    a += 2
    a += 3
    a += 4
    a += 5
    a += 6
    a += 7
    a += 8
    a += 9
    a += 10
    a += 11
    a += 12
    a += 13
    a += 14
    a += 15
    a += 16
    a += 17
    a += 18
    a += 19
    a += 20
    a += 21
    a += 22
    a += 23
    a += 24
    a += 25
    a += 26
    a += 27
    a += 28
    a += 29
    a += 30
    a += 31
    a += 32
    a += 33
    a += 34
    a += 35
    a += 36
    a += 37
    a += 38
    a += 39
    a += 40
    a += 41
    a += 42
    a += 43
    a += 44
    a += 45
    a += 46
    a += 47
    a += 48
    a += 49
    a += 50
    return a


# graftlint: disable=untimed-hot-func -- fixture: suppressed long function
def big_suppressed(a):
    a += 1
    a += 2
    a += 3
    a += 4
    a += 5
    a += 6
    a += 7
    a += 8
    a += 9
    a += 10
    a += 11
    a += 12
    a += 13
    a += 14
    a += 15
    a += 16
    a += 17
    a += 18
    a += 19
    a += 20
    a += 21
    a += 22
    a += 23
    a += 24
    a += 25
    a += 26
    a += 27
    a += 28
    a += 29
    a += 30
    a += 31
    a += 32
    a += 33
    a += 34
    a += 35
    a += 36
    a += 37
    a += 38
    a += 39
    a += 40
    a += 41
    a += 42
    a += 43
    a += 44
    a += 45
    a += 46
    a += 47
    a += 48
    a += 49
    a += 50
    return a
