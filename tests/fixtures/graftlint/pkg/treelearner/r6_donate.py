"""R6 fixture: jitted entry points with device-array params must donate."""
from functools import partial

import jax
import jax.numpy as jnp


@jax.jit  # line 8: VIOLATION jit-donation (anchored at the decorator)
def undonated(bins: jax.Array, gh: jax.Array):
    return bins.sum() + gh.sum()


@partial(jax.jit, static_argnames=("n",), donate_argnums=(0,))
def donated(bins: jax.Array, n: int):  # donate_argnums declared: clean
    return bins.sum() + n


# graftlint: disable=jit-donation -- fixture: bins reused across iterations
@jax.jit
def suppressed(bins: "jax.Array"):
    return bins.sum()


@jax.jit
def scalar_only(n: int, scale: float):  # no array params: exempt
    return n * scale


def not_jitted(bins: jax.Array):  # no jit decorator: exempt
    return jnp.sum(bins)
