"""R9 fixture: hot-path telemetry.emit must sit under an enabled-guard."""
from .. import telemetry
from ..utils.timer import global_timer


def unguarded_emit(committed, speculated):
    telemetry.emit("tree_wave", committed=committed,  # line 7: VIOLATION
                   speculated=speculated)


def guarded_emit(committed, speculated):
    if telemetry.enabled():  # idiomatic guard: clean
        telemetry.emit("tree_wave", committed=committed,
                       speculated=speculated)


def guarded_ternary(rows):
    return telemetry.emit("chunk", rows=rows) if telemetry.enabled() else None


def counter_only(committed):
    # always-cheap counter API needs no guard: clean
    global_timer.add_count("wave_splits_committed", committed)


def unrelated_emit(handler, record):
    handler.emit(record)  # bare .emit on a non-telemetry object: clean


def suppressed_emit(path):
    # graftlint: disable=telemetry-hygiene -- fixture: cold error path, runs once
    telemetry.emit("write_fail", path=path)
