"""R14 plants: one pallas_call whose double-buffered blocks blow past the
16 MiB floor, next to a tiled call that fits. Shapes are R3-aligned
(rows % 8 == 0, cols % 128 == 0) so only the VMEM rule fires.
"""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

BIG_ROWS = 16384
BIG_COLS = 4096
TILE_ROWS = 256
TILE_COLS = 128


def _copy_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def oversized_copy(x):
    return pl.pallas_call(  # R14: 2 x 2 x 256 MiB of blocks vs 16 MiB
        _copy_kernel,
        grid=(1,),
        in_specs=[pl.BlockSpec((BIG_ROWS, BIG_COLS), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((BIG_ROWS, BIG_COLS), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((BIG_ROWS, BIG_COLS), jnp.float32),
    )(x)


def tiled_copy(x):
    return pl.pallas_call(
        _copy_kernel,
        grid=(BIG_ROWS // TILE_ROWS, BIG_COLS // TILE_COLS),
        in_specs=[pl.BlockSpec((TILE_ROWS, TILE_COLS), lambda i, j: (i, j))],
        out_specs=pl.BlockSpec((TILE_ROWS, TILE_COLS), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((BIG_ROWS, BIG_COLS), jnp.float32),
    )(x)
