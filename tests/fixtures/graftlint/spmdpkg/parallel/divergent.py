"""R12 plants: rank-gated collective arms and a rank-local-bound loop,
next to the compliant and suppressed shapes. Every psum here is bound to
an axis, so the R7/R11 axis passes are satisfied — only the collective-
SEQUENCE summary sees the divergence.
"""
import jax
import jax.numpy as jnp


def _sync(x):
    return jax.lax.psum(x, "data")


def rank_gated_sum(x):
    if jax.process_index() == 0:  # R12(a): only rank 0 posts the psum
        x = jax.lax.psum(x, "data")
    return x


def early_return_gate(x, rank):
    if rank != 0:  # R12(a): the implicit else (rest of the block) syncs
        return x
    return _sync(x)


def uniform_gate(x):
    if jax.process_index() == 0:  # clean: both arms post the same sequence
        return jax.lax.psum(x, "data")
    return jax.lax.psum(x, "data")


def per_device_reduce(x):
    total = jnp.zeros_like(x)
    for _ in jax.local_devices():  # R12(b): rank-local trip count
        total = total + jax.lax.psum(x, "data")
    return total


def padded_reduce(x, steps):
    total = jnp.zeros_like(x)
    for _ in range(steps):  # clean: trip count is a plain argument
        total = total + jax.lax.psum(x, "data")
    return total


def single_host_fallback(x):
    # graftlint: disable=collective-order -- process_count() is uniform across the gang: every rank takes the same arm together
    if jax.process_count() == 1:
        return x
    return _sync(x)
