"""R12(c) plant: one shard-mapped body entered under two different axis
bindings. R11's union over entry sites is satisfied — 'data' IS bound at
an entry somewhere — but the 'model'-only entry traces a psum over an
axis it never binds.
"""
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from .divergent import _sync


def _body(x):
    return jax.lax.psum(x, "data")


def enter_data(mesh, x):
    return shard_map(_body, mesh=mesh, in_specs=(P("data"),),
                     out_specs=P("data"))(x)


def enter_model(mesh, x):
    return shard_map(_body, mesh=mesh, in_specs=(P("model"),),
                     out_specs=P("model"))(x)  # R12(c): 'data' unbound here


def reuse_helper(x):
    return _sync(x)
