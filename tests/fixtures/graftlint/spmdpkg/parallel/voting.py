"""R7/R11/R12 plants at the PV-Tree voting collective shapes (the round-9
learners): the nomination gather, the elected-slice psum and the overlap
dispatch, next to their compliant shard_map-wrapped forms. Exact-line
assertions live in tests/test_lint_spmd.py (voting section).
"""
import jax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from .divergent import _sync


def _nominate(local_recs):
    # clean: 'data' flows from the shard_map around _vote_body
    return jax.lax.all_gather(local_recs, "data", axis=1, tiled=True)


def _elected_psum(slices):
    return jax.lax.psum(slices, "data")


def _vote_body(hist, recs):
    return _elected_psum(hist), _nominate(recs)


def vote_wave(mesh, hist, recs):
    # clean: the wrap binds 'data' for the whole body chain
    return shard_map(_vote_body, mesh=mesh, in_specs=(P("data"), P("data")),
                     out_specs=(P(), P("data")))(hist, recs)


@jax.jit
def rescan_entry(hist):
    # R11: this second path to the elected-slice psum binds no mesh axis —
    # tracing the jitted rescan without the vote's shard_map fails
    return _elected_psum(hist)


def skewed_gather(nom):
    return jax.lax.all_gather(nom, "vote", axis=1, tiled=True)  # R7: unbound


def overlap_dispatch(small, pool):
    if jax.process_index() == 0:  # R12(a): only rank 0 posts the elected psum
        small = _elected_psum(small)
    return pool - small


def overlap_wave(mesh, small, pool):
    # the dispatch IS bound (so R7/R11 stay quiet): only the collective-
    # SEQUENCE divergence above is the plant
    return shard_map(overlap_dispatch, mesh=mesh,
                     in_specs=(P("data"), P("data")),
                     out_specs=P())(small, pool)


def gathered_commit(best):
    return _sync(best)  # clean: reuses the compliant helper across modules


def commit_wave(mesh, best):
    return shard_map(gathered_commit, mesh=mesh, in_specs=(P("data"),),
                     out_specs=P())(best)
