"""R13 plants: jitted dispatch / file I/O / transitive sleep under a held
lock and an acquisition-order cycle, next to the compliant pending-record
idiom (record under the lock, act after release) and a reasoned
suppression.
"""
import threading
import time

import jax
import numpy as np


@jax.jit
def _dev_double(x):
    return x * 2.0


def _backoff():
    time.sleep(0.01)


class PlantedServer:
    def __init__(self):
        self._lock = threading.Lock()
        self._aux = threading.Lock()
        self._pending = None

    def bad_dispatch(self, x):
        with self._lock:
            return _dev_double(x)  # R13: jitted dispatch under _lock

    def bad_io(self, payload):
        with self._lock:
            with open("/tmp/spmd_flight.json", "w") as f:  # R13: file I/O
                f.write(payload)

    def bad_transitive(self):
        with self._lock:
            _backoff()  # R13: blocks via time.sleep two frames away

    def order_ab(self):
        with self._lock:
            with self._aux:  # R13: cycle edge _lock -> _aux
                return 1

    def order_ba(self):
        with self._aux:
            with self._lock:  # R13: cycle edge _aux -> _lock
                return 2

    def good_pending(self, payload):
        with self._lock:
            self._pending = payload
        if self._pending is not None:
            with open("/tmp/spmd_ok.json", "w") as f:  # clean: lock released
                f.write(self._pending)

    def seeded(self):
        with self._lock:
            # graftlint: disable=lock-discipline -- startup-only seed read: bounded, runs once before serving starts
            return open("/tmp/spmd_seed.json").read()

    def bad_wire_decode(self, stream, n_rows):
        with self._lock:
            return np.frombuffer(stream.read(8 * n_rows), dtype=np.float32)  # R13: decode blocks on the socket under _lock

    def good_pending_decode(self, stream, n_rows):
        payload = stream.read(8 * n_rows)  # clean: socket drained pre-lock
        with self._lock:
            self._pending = payload
        return np.frombuffer(self._pending, dtype=np.float32)
