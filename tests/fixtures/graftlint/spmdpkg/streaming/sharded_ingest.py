"""R12 in streaming/ scope: the gang sketch-merge allgather shapes.

The plant gates the bin-fit sketch merge on rank 0 — every other rank
never posts the all_gather, so the gang's fit deadlocks at the merge
barrier. The compliant merge posts it unconditionally on every rank;
the single-process fallback mirrors the production sharded-ingest
_allgather_bytes and carries the sanctioned uniformity suppression.
"""
import jax


def rank0_sketch_merge(sk):
    merged = sk
    if jax.process_index() == 0:  # R12(a): only rank 0 posts the merge
        merged = jax.lax.all_gather(sk, "data", axis=0, tiled=True)
    return merged


def every_rank_merge(sk):
    return jax.lax.all_gather(sk, "data", axis=0, tiled=True)


def single_process_fit(sk):
    # graftlint: disable=collective-order -- process_count() is uniform across the gang: every rank skips the merge together below the multi-process world size
    if jax.process_count() == 1:
        return sk
    return every_rank_merge(sk)
