"""The training loop: dispatches a jitted kernel per iteration and calls
a telemetry hook from inside the same loop — the hook's host pull is the
hot-dispatch-path shape R1v2's pass B exists for.
"""
from .. import telemetry
from ..ops import kernels


def train(xs, delta):
    out = []
    for x in xs:
        y = kernels.consume(x, delta)
        telemetry.emit_row(y)  # hook called on the dispatch path
        out.append(y)
    return out
