"""Jitted entry points. The module-level import of treelearner.stats —
which itself imports this module back for SCALE — is a deliberate import
cycle: the call graph must terminate and still resolve both directions.
"""
from functools import partial

import jax

from ..treelearner import stats

SCALE = 3.0


@jax.jit
def scale(x):
    # jit seed: the sync hides one module away, inside stats.normalize
    return stats.normalize(x)


@jax.jit
def centered(x):
    # reaches stats.center, whose sync wears a reasoned suppression
    return stats.center(x)


@partial(jax.jit, donate_argnums=(0,))
def consume(buf, delta):
    # partial-wrapped jit decorator: unwrapping must surface the donation
    return buf + delta
