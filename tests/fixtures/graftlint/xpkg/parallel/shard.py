"""The mesh wrapper: binds axis 'data' around treelearner.steps.grow_step
from a DIFFERENT module than the collective that consumes it.
"""
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ..treelearner import steps


def make_sharded_step(mesh):
    return shard_map(steps.grow_step, mesh=mesh,
                     in_specs=(P("data"),), out_specs=P("data"))
