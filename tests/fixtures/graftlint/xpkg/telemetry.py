"""Telemetry hook surface (hook_exact scope of R1v2's pass B)."""
import numpy as np

_ROWS = []


def emit_row(y):
    _ROWS.append(np.asarray(y))  # line 8: host pull on the hot dispatch path
    return len(_ROWS)


def flush():
    # cold path: nothing dispatches through here, so no finding
    total = float(sum(r.sum() for r in _ROWS))
    _ROWS.clear()
    return total
