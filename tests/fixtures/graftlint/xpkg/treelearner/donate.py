"""R10 use-after-donation shapes: every way a donation can reach a call
site (decorator, jit alias, partial shift, method dispatch, interprocedural
summary, pallas literal aliases) with a read-after for each, plus the
compliant idioms that must stay clean.
"""
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..ops.kernels import consume


def direct_bad(buf, delta):
    out = consume(buf, delta)
    total = buf.sum()  # line 17: buf's buffer was donated at line 16
    return out, total


def direct_ok(buf, delta):
    out = consume(jnp.copy(buf), delta)  # fresh temp donated: compliant
    return out, buf.sum()


def rebound_ok(buf, delta):
    buf = consume(buf, delta)  # rebound to the output: the donated
    return buf.sum()           # reference is dead, the read is the result


def loop_bad(bufs, delta):
    acc = 0.0
    for b in bufs:
        out = consume(b, delta)
        acc = acc + b.sum()  # line 34: same-iteration read after donation
    return acc, out


def suppressed_read(buf, delta):
    out = consume(buf, delta)
    # graftlint: disable=R10 -- fixture: pretend a checkpoint pinned a host copy of buf before the dispatch
    return out, buf.sum()


def _impl(a, b):
    return a * b


scaled = jax.jit(_impl, donate_argnums=(1,))


def alias_bad(a, b):
    r = scaled(a, b)
    return r + b  # line 53: 'b' donated through the jit ALIAS


@partial(jax.jit, donate_argnums=(1,))
def axpy(alpha, x):
    return alpha * x


saxpy = partial(axpy, 2.0)  # shifts donate_argnums=(1,) to position 0


def partial_bad(x):
    y = saxpy(x)
    return y + x  # line 66: 'x' donated through the partial shift


class Learner:
    def _dispatch(self, buf, delta):
        # forwards its own param into consume's donated slot: the summary
        # fixpoint must mark _dispatch as donating positional 0
        return consume(buf, delta)

    def run_bad(self, buf, delta):
        out = self._dispatch(buf, delta)
        return out, buf.sum()  # line 77: donated via the method summary


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def pallas_bad(x):
    out = pl.pallas_call(
        _kernel, out_shape=x, input_output_aliases={0: 0})(x)
    return out + x  # line 87: aliased in-place by the pallas kernel
