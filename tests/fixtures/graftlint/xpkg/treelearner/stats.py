"""Host-sync helpers. No jit in THIS module, so the module-local R1 pass
sees nothing jit-reachable here — only the cross-module pass (R1v2) can
prove ops.kernels traces these bodies.
"""
from ..ops import kernels  # import cycle back into ops.kernels


def normalize(x):
    lo = x.min().item()  # line 9: flagged by R1v2 (reachable via kernels)
    return (x - lo) * kernels.SCALE


def center(x):
    # graftlint: disable=R1 -- fixture: pretend the calibration contract requires a host round-trip here
    mid = x.mean().item()
    return x - mid


def offline_summary(x):
    # NOT jit-reachable from anywhere: both passes must stay quiet
    return x.min().item(), x.max().item()
