"""R11 collective-context shapes: one entry whose every collective is
bound by parallel/shard.py's cross-module shard_map, one jitted entry that
reaches the same collective with NO binding on its path (flagged), and a
suppressed twin.
"""
import jax


def grow_step(x):
    return reduce_hist(x)


def reduce_hist(x):
    # graftlint: disable=R7 -- the 'data' axis is bound by parallel/shard.py's shard_map; the module-local pass cannot see across the import — R11 proves the path
    return jax.lax.psum(x, "data")


@jax.jit
def unbound_entry(x):  # line 18: R11 — no shard_map binds 'data' here
    return grow_step(x)


# graftlint: disable=R11 -- fixture: pretend a static flag prunes the collective from this trace
@jax.jit
def pruned_entry(x):
    return grow_step(x)
