"""bench.py smoke test: the benchmark entrypoint must emit its ONE JSON
record with a real throughput number on a small CPU run — catching drift
between the bench harness and the library surface before a capture round
burns a TPU window on it."""
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_bench_smoke_cpu(tmp_path):
    ledger = tmp_path / "ledger.jsonl"
    env = dict(os.environ)
    env.update({
        "BENCH_ROWS": "20000",
        "BENCH_ITERS": "2",
        "BENCH_PLATFORM": "cpu",  # skip the accelerator probe entirely
        "BENCH_QUANTIZED": "0",   # primary metric only: keep the smoke fast
        "JAX_PLATFORMS": "cpu",
        "BENCH_LEDGER": str(ledger),  # don't dirty the repo ledger
    })
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True, text=True, timeout=900, env=env, cwd=_REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    # last stdout line is the structured record
    record = json.loads(out.stdout.strip().splitlines()[-1])
    assert record["metric"] == "train_row_iters_per_sec"
    assert record["platform"] == "cpu"
    assert "error" not in record, record
    assert record["value"] > 0
    assert record["rows"] == 20000
    assert 0.5 <= record["auc"] <= 1.0
    # wave-traffic instrumentation: both fields present on EVERY record
    # (CPU benches run the serial learner, so the row counter may be 0 but
    # the carry estimate still comes from the dataset shape formula)
    assert record["device_hist_rows"] >= 0
    assert record["est_carried_bytes_per_wave"] > 0
    # 28 features -> Gp=32 groups; rows pad to the 1024-row wave unit.
    # uint8 plane: carry = np_rows * (32*1 + 20); the int32 figure would be
    # np_rows * (32*4 + 20) — assert we sit in the narrow-plane regime.
    n_pad = -(-20000 // 1024) * 1024
    assert record["est_carried_bytes_per_wave"] == n_pad * (32 + 20)
    # round-8 kernel instrumentation: both microlatency fields are real
    # timed dispatches (the fused-scan/XLA routing and the device GOSS
    # select both run on any backend); the wave-controller fields are 0 on
    # CPU benches (serial learner — no waves dispatched) but must exist
    assert "scan_kernel_error" not in record, record
    assert "goss_kernel_error" not in record, record
    assert record["scan_kernel_ms"] > 0
    assert record["goss_device_gather_ms"] > 0
    assert 0.0 <= record["wave_commit_rate"] <= 1.0
    assert record["adaptive_k_final"] >= 0
    # inference metric: chunked streaming predict must have run and timed.
    # 20000 rows -> chunk = bucket_size(5000, 1024) = 8192 (3 chunks).
    assert record["predict_rows_per_sec"] > 0
    assert record["predict_chunk_rows"] == 8192
    # robustness-layer cost tracking: a real timed checkpoint write and a
    # measured guardrail train-loop delta (can be negative on noisy hosts)
    assert record["checkpoint_write_ms"] > 0
    assert isinstance(record["guardrail_overhead_pct"], float)
    # elastic-layer cost tracking: the heartbeat train-loop delta is
    # measured every capture (single-device smoke degrades the psum token
    # to the watchdog beat, so the delta is noise around zero — the field
    # must still be a real measurement), and one stub-gang recovery cycle
    # timed the supervisor's detect -> reap -> respawn loop
    assert isinstance(record["heartbeat_overhead_pct"], float)
    assert "gang_error" not in record, record
    assert record["gang_recovery_ms"] > 0
    # telemetry attribution fields: the aggregate-only session counted real
    # compiles; HBM is 0 on CPU (no memory_stats) but the field is present;
    # the overhead delta is measured every capture (noisy hosts -> negative)
    assert record["compile_count"] > 0
    assert record["hbm_high_water_bytes"] >= 0
    assert isinstance(record["telemetry_overhead_pct"], float)
    # serving-layer metrics: the open-loop generator drove the hardened
    # prediction service and every request was micro-batched and answered
    assert record["serve_rows_per_sec"] > 0
    assert record["serve_p50_ms"] > 0
    assert record["serve_p99_ms"] >= record["serve_p50_ms"]
    assert record["serve_batches"] > 0
    # request-path decomposition (tracing stage histograms, fed by the
    # HTTP-driven open loop): the serving gap now has named parts, and the
    # stages a real request must traverse carry real time
    for field in ("serve_parse_ms_p99", "serve_queue_ms_p99",
                  "serve_assembly_ms_p99", "serve_device_ms_p99",
                  "serve_d2h_ms_p99", "serve_serialize_ms_p99"):
        assert record[field] >= 0, field
    assert record["serve_queue_ms_p99"] > 0
    assert record["serve_device_ms_p99"] > 0
    assert record["serve_serialize_ms_p99"] > 0
    # out-of-core streaming capture: chunked ingest + a 2-blocks-of-8
    # budget train must both have run and timed; the starved budget means
    # the resident fraction sits strictly inside (0, 1) and the overlap
    # percentage is a real ratio (prefetch hits can be 0 on tiny runs)
    assert "stream_error" not in record, record
    assert record["stream_ingest_rows_per_sec"] > 0
    assert record["stream_train_rows_per_sec"] > 0
    assert 0.0 < record["hbm_resident_fraction"] < 1.0
    assert 0.0 <= record["stream_h2d_overlap_pct"] <= 100.0
    # gang-sharded streaming capture: the sketch-merged fit and the
    # sharded (tree_learner=data) streamed train both ran and timed; the
    # single-device smoke degenerates to one shard but the merge gauge is
    # a real measurement and the overlap ratio stays a real percentage
    assert "stream_sharded_error" not in record, record
    assert record["stream_sharded_rows_per_sec"] > 0
    assert record["stream_sketch_merge_ms"] >= 0
    assert record["stream_gang_shards"] >= 1
    # drift-layer cost tracking (docs/STREAMING.md "Drift and generation
    # safety"): the sketch+occupancy ingest delta is measured every capture
    # (noisy hosts -> negative is fine), and one forced bin-mapper refresh
    # plus one holdout gate evaluation both ran and timed
    assert isinstance(record["drift_check_overhead_pct"], float)
    assert record["bin_refresh_ms"] > 0
    assert record["gate_eval_ms"] > 0
    # provenance: every record carries the environment fingerprint and the
    # ledger schema version (benchdiff refuses cross-schema comparisons)
    assert record["schema_version"] == 1
    fp = record["fingerprint"]
    assert fp["git_sha"] not in ("", None)
    assert fp["jax_version"] not in ("unknown", "", None)
    assert fp["backend"] == "cpu"
    assert fp["flags"].get("JAX_PLATFORMS") == "cpu"
    # cost-model attribution: per-stage fractions of the training wall must
    # close to ~1 (the ISSUE acceptance bound benchdiff also gates on)
    attr = record["attribution"]
    assert attr["stages"], attr
    assert abs(attr["fractions_sum"] - 1.0) <= 0.05, attr
    assert all(s["wall_s"] >= 0 for s in attr["stages"].values())
    # XLA static cost analysis captured for the instrumented dispatches
    static = attr.get("static") or {}
    assert "scan" in static and "predict" in static, sorted(static)
    assert static["scan"].get("flops", 0) > 0, static["scan"]
    # the same record was appended to the ledger (atomic rewrite path)
    led = [json.loads(ln) for ln in
           ledger.read_text().splitlines() if ln.strip()]
    assert len(led) == 1
    assert led[0]["value"] == record["value"]
