"""DART and RF boosting-mode tests (dart.hpp / rf.hpp parity)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=2000, f=10, seed=11):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def _make_regression(n=2000, f=8, seed=5):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = 2 * X[:, 0] - X[:, 1] + 0.3 * rng.randn(n)
    return X, y


def test_dart_trains_and_score_consistent():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "dart",
                     "num_leaves": 15, "drop_rate": 0.3, "skip_drop": 0.3,
                     "verbosity": -1}, ds, num_boost_round=20)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc
    # DART renormalization must keep the internal train score equal to a
    # fresh prediction over the stored (renormalized) trees
    internal = np.asarray(bst._gbdt.score[0])
    fresh = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, fresh, rtol=1e-3, atol=1e-3)


def test_dart_xgboost_mode():
    X, y = _make_binary(n=1000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "dart",
                     "xgboost_dart_mode": True, "uniform_drop": True,
                     "num_leaves": 7, "verbosity": -1}, ds, num_boost_round=10)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.85, acc


def test_rf_trains_binary():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "rf",
                     "bagging_fraction": 0.6, "bagging_freq": 1,
                     "num_leaves": 31, "verbosity": -1}, ds,
                    num_boost_round=20)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc


def test_rf_average_output_roundtrip(tmp_path):
    X, y = _make_regression()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "boosting": "rf",
                     "bagging_fraction": 0.5, "bagging_freq": 1,
                     "num_leaves": 31, "verbosity": -1}, ds,
                    num_boost_round=15)
    pred = bst.predict(X)
    # averaged output should be in the label range, not the sum of 15 trees
    assert abs(pred.mean() - y.mean()) < 1.0
    r2 = 1 - np.mean((pred - y) ** 2) / np.var(y)
    assert r2 > 0.6, r2
    path = str(tmp_path / "rf.txt")
    bst.save_model(path)
    text = open(path).read()
    assert "average_output" in text
    re_pred = lgb.Booster(model_file=path).predict(X)
    np.testing.assert_allclose(re_pred, pred, rtol=1e-5, atol=1e-5)


def test_rf_score_is_average():
    X, y = _make_binary(n=1200)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "rf",
                     "bagging_fraction": 0.5, "bagging_freq": 1,
                     "num_leaves": 7, "verbosity": -1}, ds, num_boost_round=6)
    internal = np.asarray(bst._gbdt.score[0])
    fresh = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, fresh, rtol=1e-3, atol=1e-3)


def test_rf_requires_bagging():
    X, y = _make_binary(n=500)
    ds = lgb.Dataset(X, label=y)
    with pytest.raises(Exception):
        lgb.train({"objective": "binary", "boosting": "rf",
                   "verbosity": -1}, ds, num_boost_round=2)
