"""C API: build the cffi-embedded shared library and drive it exactly as a
C client would (ctypes stands in for a C program; every call crosses the
real exported LGBM_* symbols). Mirrors the reference's c_api workflow
(include/LightGBM/c_api.h): CreateFromMat -> SetField -> BoosterCreate ->
UpdateOneIter -> PredictForMat -> SaveModel -> CreateFromModelfile."""
import ctypes
import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def capi(tmp_path_factory):
    pytest.importorskip("cffi")
    out = str(tmp_path_factory.mktemp("capi_build"))
    from lightgbm_tpu.capi.build_capi import build

    try:
        so_path = build(out)
    except Exception as e:  # no compiler / headers on this machine
        pytest.skip(f"C API build unavailable: {e}")
    lib = ctypes.CDLL(so_path)
    lib.LGBM_GetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, ret):
    assert ret == 0, lib.LGBM_GetLastError().decode()


def test_capi_end_to_end(capi, tmp_path):
    lib = capi
    rng = np.random.RandomState(0)
    n, f = 600, 6
    X = rng.randn(n, f).astype(np.float64)
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float32)

    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1,
        b"max_bin=63", None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0))

    nd = ctypes.c_int32()
    nf = ctypes.c_int32()
    _check(lib, lib.LGBM_DatasetGetNumData(ds, ctypes.byref(nd)))
    _check(lib, lib.LGBM_DatasetGetNumFeature(ds, ctypes.byref(nf)))
    assert (nd.value, nf.value) == (n, f)

    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=15 verbosity=-1 device_type=cpu",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(10):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))
    it = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterGetCurrentIteration(bst, ctypes.byref(it)))
    assert it.value == 10
    total = ctypes.c_int()
    _check(lib, lib.LGBM_BoosterNumberOfTotalModel(bst, ctypes.byref(total)))
    assert total.value == 10

    out_len = ctypes.c_int64()
    preds = np.zeros(n, dtype=np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1,
        0, 0, 0, b"", ctypes.byref(out_len),
        preds.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n
    acc = ((preds > 0.5) == y).mean()
    assert acc > 0.9

    model_file = str(tmp_path / "capi_model.txt").encode()
    _check(lib, lib.LGBM_BoosterSaveModel(bst, 0, -1, 0, model_file))

    nit = ctypes.c_int()
    bst2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreateFromModelfile(
        model_file, ctypes.byref(nit), ctypes.byref(bst2)))
    assert nit.value == 10
    preds2 = np.zeros(n, dtype=np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1,
        0, 0, 0, b"", ctypes.byref(out_len),
        preds2.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    np.testing.assert_allclose(preds, preds2, rtol=1e-6)

    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_BoosterFree(bst2))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_model_string_roundtrip_and_predict_types(capi):
    lib = capi
    rng = np.random.RandomState(1)
    n, f = 400, 5
    X = rng.randn(n, f).astype(np.float64)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, b"max_bin=63",
        None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1 device_type=cpu",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    # SaveModelToString: first call with a small buffer to learn the size
    out_len = ctypes.c_int64()
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, ctypes.c_int64(8), ctypes.byref(out_len),
        ctypes.create_string_buffer(8)))
    size = out_len.value
    assert size > 100
    buf = ctypes.create_string_buffer(size)
    _check(lib, lib.LGBM_BoosterSaveModelToString(
        bst, 0, -1, 0, ctypes.c_int64(size), ctypes.byref(out_len), buf))
    model_str = buf.value
    assert model_str.startswith(b"tree")

    nit = ctypes.c_int()
    bst2 = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterLoadModelFromString(
        model_str, ctypes.byref(nit), ctypes.byref(bst2)))
    assert nit.value == 5

    # predict types: raw (1), leaf index (2), contrib (3)
    raw = np.zeros(n)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 1, 0, 0, b"",
        ctypes.byref(out_len), raw.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n and np.isfinite(raw).all()
    leaves = np.zeros(n * 5)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 2, 0, 0, b"",
        ctypes.byref(out_len), leaves.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n * 5
    assert leaves.min() >= 0 and leaves.max() < 7
    contrib = np.zeros(n * (f + 1))
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst2, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 3, 0, 0, b"",
        ctypes.byref(out_len), contrib.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n * (f + 1)
    # SHAP contributions sum to the raw score
    np.testing.assert_allclose(contrib.reshape(n, f + 1).sum(axis=1), raw,
                               rtol=1e-4, atol=1e-5)
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_BoosterFree(bst2))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_predict_for_csr_matches_mat(capi):
    lib = capi
    rng = np.random.RandomState(3)
    n, f = 300, 5
    X = rng.randn(n, f)
    X[rng.rand(n, f) < 0.4] = 0.0  # genuinely sparse rows
    X = np.ascontiguousarray(X, dtype=np.float64)
    y = (X[:, 0] > 0).astype(np.float32)
    ds = ctypes.c_void_p()
    _check(lib, lib.LGBM_DatasetCreateFromMat(
        X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, b"max_bin=63",
        None, ctypes.byref(ds)))
    _check(lib, lib.LGBM_DatasetSetField(
        ds, b"label", y.ctypes.data_as(ctypes.c_void_p), n, 0))
    bst = ctypes.c_void_p()
    _check(lib, lib.LGBM_BoosterCreate(
        ds, b"objective=binary num_leaves=7 verbosity=-1 device_type=cpu",
        ctypes.byref(bst)))
    fin = ctypes.c_int()
    for _ in range(5):
        _check(lib, lib.LGBM_BoosterUpdateOneIter(bst, ctypes.byref(fin)))

    out_len = ctypes.c_int64()
    dense = np.zeros(n, dtype=np.float64)
    _check(lib, lib.LGBM_BoosterPredictForMat(
        bst, X.ctypes.data_as(ctypes.c_void_p), 1, n, f, 1, 0, 0, 0, b"",
        ctypes.byref(out_len),
        dense.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))

    rows, cols = np.nonzero(X)
    values = np.ascontiguousarray(X[rows, cols], dtype=np.float64)
    indices = np.ascontiguousarray(cols, dtype=np.int32)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr).astype(np.int64)
    sparse = np.zeros(n, dtype=np.float64)
    _check(lib, lib.LGBM_BoosterPredictForCSR(
        bst, indptr.ctypes.data_as(ctypes.c_void_p), 3,
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        values.ctypes.data_as(ctypes.c_void_p), 1,
        ctypes.c_int64(indptr.size), ctypes.c_int64(values.size),
        ctypes.c_int64(f), 0, 0, 0, b"", ctypes.byref(out_len),
        sparse.ctypes.data_as(ctypes.POINTER(ctypes.c_double))))
    assert out_len.value == n
    np.testing.assert_array_equal(dense, sparse)
    _check(lib, lib.LGBM_BoosterFree(bst))
    _check(lib, lib.LGBM_DatasetFree(ds))


def test_capi_error_reporting(capi):
    lib = capi
    bad = ctypes.c_void_p(999999)
    out = ctypes.c_int32()
    ret = lib.LGBM_DatasetGetNumData(bad, ctypes.byref(out))
    assert ret == -1
    assert b"invalid handle" in lib.LGBM_GetLastError()
