"""C API parity shims at the impl layer (no compiler needed): drive the
Python functions behind LGBM_BoosterPredictForMat / PredictForCSR through
a real cffi FFI — the same buffer/pointer marshalling the embedded build
uses — and assert both surfaces answer bit-identically with the in-process
Booster.predict they route onto."""
import numpy as np
import pytest

cffi = pytest.importorskip("cffi")

import lightgbm_tpu as lgb
from lightgbm_tpu.capi import impl


@pytest.fixture(scope="module")
def ffi():
    return cffi.FFI()


@pytest.fixture(scope="module")
def booster_handle():
    rng = np.random.RandomState(7)
    X = rng.randn(300, 6)
    # zero out a third of the entries so the CSR form is genuinely sparse
    X[rng.rand(300, 6) < 0.33] = 0.0
    y = (X[:, 0] - X[:, 1] > 0).astype(np.float64)
    ds = lgb.Dataset(X, label=y, params={"max_bin": 63})
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=5)
    h = impl._register(bst)
    yield h, X
    impl._free(h)


def _predict_mat(ffi, handle, X, predict_type=0):
    Xc = np.ascontiguousarray(X, dtype=np.float64)
    out = np.zeros(X.shape[0], dtype=np.float64)
    out_len = ffi.new("int64_t*")
    ret = impl.booster_predict_for_mat(
        ffi, handle, ffi.from_buffer("void*", Xc), 1,
        X.shape[0], X.shape[1], 1, predict_type, 0, 0,
        ffi.new("char[]", b""), out_len,
        ffi.from_buffer("double*", out, require_writable=True))
    assert ret == 0
    assert out_len[0] == X.shape[0]
    return out


def _predict_csr(ffi, handle, X, predict_type=0):
    # hand-rolled CSR of X (scipy-free): row pointers + column indices +
    # the non-zero values, exactly the LGBM_BoosterPredictForCSR ABI
    rows, cols = np.nonzero(X)
    values = np.ascontiguousarray(X[rows, cols], dtype=np.float64)
    indices = np.ascontiguousarray(cols, dtype=np.int32)
    indptr = np.zeros(X.shape[0] + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr).astype(np.int64)
    out = np.zeros(X.shape[0], dtype=np.float64)
    out_len = ffi.new("int64_t*")
    ret = impl.booster_predict_for_csr(
        ffi, handle, ffi.from_buffer("void*", indptr), 3,
        ffi.from_buffer("int32_t*", indices),
        ffi.from_buffer("void*", values), 1,
        indptr.size, values.size, X.shape[1], predict_type, 0, 0,
        ffi.new("char[]", b""), out_len,
        ffi.from_buffer("double*", out, require_writable=True))
    assert ret == 0
    assert out_len[0] == X.shape[0]
    return out


def test_csr_matches_mat_normal(ffi, booster_handle):
    h, X = booster_handle
    np.testing.assert_array_equal(_predict_mat(ffi, h, X),
                                  _predict_csr(ffi, h, X))


def test_csr_matches_mat_raw_score(ffi, booster_handle):
    h, X = booster_handle
    np.testing.assert_array_equal(_predict_mat(ffi, h, X, predict_type=1),
                                  _predict_csr(ffi, h, X, predict_type=1))


def test_csr_matches_booster_predict(ffi, booster_handle):
    h, X = booster_handle
    want = impl._get(h).predict(X)
    np.testing.assert_array_equal(_predict_csr(ffi, h, X), want)


def test_csr_rejects_bad_indptr_type(ffi, booster_handle):
    h, X = booster_handle
    out = np.zeros(X.shape[0], dtype=np.float64)
    out_len = ffi.new("int64_t*")
    indptr = np.zeros(X.shape[0] + 1, dtype=np.float64)
    with pytest.raises(ValueError, match="indptr_type"):
        impl.booster_predict_for_csr(
            ffi, h, ffi.from_buffer("void*", indptr), 1,
            ffi.new("int32_t[1]"), ffi.new("double[1]"), 1,
            indptr.size, 0, X.shape[1], 0, 0, 0,
            ffi.new("char[]", b""), out_len,
            ffi.from_buffer("double*", out, require_writable=True))
