"""Categorical split tests (FindBestThresholdCategoricalInner parity,
feature_histogram.cpp:147-241)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _cat_data(n=2000, n_cats=12, seed=9):
    rng = np.random.RandomState(seed)
    cats = rng.randint(0, n_cats, size=n)
    # category effect: a few categories strongly positive
    effect = np.where(np.isin(cats, [2, 5, 7]), 2.0, -1.0)
    X = np.column_stack([cats.astype(np.float64), rng.randn(n)])
    y = (effect + 0.3 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y, cats


def test_categorical_sorted_subset_split():
    X, y, cats = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 20, "verbosity": -1},
                    ds, num_boost_round=15)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.85, acc
    # the model must contain at least one categorical split
    dumped = bst.dump_model()

    def has_cat(node):
        if "split_feature" in node:
            return (node["decision_type"] == "==" or
                    has_cat(node["left_child"]) or has_cat(node["right_child"]))
        return False

    assert any(has_cat(t["tree_structure"]) for t in dumped["tree_info"])


def test_categorical_onehot_split():
    # few categories -> one-hot path (max_cat_to_onehot default 4)
    rng = np.random.RandomState(3)
    cats = rng.randint(0, 3, size=1500)
    y = (cats == 1).astype(np.float64)
    X = np.column_stack([cats.astype(np.float64), rng.randn(1500)])
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 4,
                     "verbosity": -1}, ds, num_boost_round=10)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.99, acc


def test_categorical_model_roundtrip(tmp_path):
    X, y, _ = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=8)
    pred = bst.predict(X)
    path = str(tmp_path / "cat.txt")
    bst.save_model(path)
    re_pred = lgb.Booster(model_file=path).predict(X)
    np.testing.assert_allclose(re_pred, pred, rtol=1e-5, atol=1e-6)


def test_categorical_unseen_category_goes_right():
    X, y, _ = _cat_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=8)
    X_unseen = X[:5].copy()
    X_unseen[:, 0] = 999  # never-seen category
    out = bst.predict(X_unseen)
    assert np.isfinite(out).all()


def test_categorical_score_consistency():
    """Internal train score must equal fresh prediction (partition decisions
    and stored bitsets agree)."""
    X, y, _ = _cat_data(n=1200)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=6)
    internal = np.asarray(bst._gbdt.score[0])
    fresh = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, fresh, rtol=1e-4, atol=1e-4)


def test_categorical_with_numerical_mix():
    rng = np.random.RandomState(5)
    n = 2000
    cats = rng.randint(0, 8, size=n)
    x1 = rng.randn(n)
    y = ((np.isin(cats, [1, 3]) & (x1 > 0)) | (x1 > 1.5)).astype(np.float64)
    X = np.column_stack([x1, cats.astype(np.float64), rng.randn(n)])
    ds = lgb.Dataset(X, label=y, categorical_feature=[1])
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=20)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.93, acc
