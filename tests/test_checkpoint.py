"""Crash-consistent checkpoint/resume: atomic writes, fail-fast loading of
damaged model files, and BIT-IDENTICAL kill-and-resume across the trainer
variants (plain, column-sampled, bagged mid-window, quantized, early-stop,
and the sharded 8-fake-device learner).

Bit-identity contract: train N straight vs. train k, snapshot, build a
FRESH process-equivalent state (new Booster/GBDT), resume to N with the
same command — the full model text (every float printed shortest-roundtrip)
and raw predictions must be byte-equal.
"""
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.checkpoint import (CKPT_MAGIC, SIDECAR_SUFFIX, atomic_open,
                                     atomic_write_text, load_checkpoint,
                                     restore_trainer_state, save_checkpoint)
from lightgbm_tpu.config import Config
from lightgbm_tpu.engine import train
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.models.serialize import GBDTModel
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.utils.log import LightGBMError


def _data(rng, n=500, f=10):
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.5 > 0)
    return X, y.astype(np.float64)


BASE = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}


def _train(params, X, y, rounds, init_model=None, valid=None, cbs=None):
    vs = None
    if valid is not None:
        vs = [lgb.Dataset(valid[0], label=valid[1])]
    return train(dict(params), lgb.Dataset(X, label=y),
                 num_boost_round=rounds, init_model=init_model,
                 valid_sets=vs, callbacks=cbs)


def _resume_case(tmp_path, rng, params, rounds=6, snap_at=3):
    """Train straight vs snapshot-at-k + resume with the same command;
    return both boosters."""
    X, y = _data(np.random.RandomState(7))
    straight = _train(params, X, y, rounds)
    half = _train(params, X, y, snap_at)
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)
    resumed = _train(params, X, y, rounds, init_model=p)
    return straight, resumed, X


def _assert_bit_identical(straight, resumed, X):
    assert straight.current_iteration() == resumed.current_iteration()
    assert (straight.model_to_string(num_iteration=-1)
            == resumed.model_to_string(num_iteration=-1))
    np.testing.assert_array_equal(
        np.asarray(straight.predict(X, raw_score=True)),
        np.asarray(resumed.predict(X, raw_score=True)))


# ----------------------------------------------------------- atomic writes

def test_atomic_write_leaves_no_temp_files(tmp_path):
    p = str(tmp_path / "out.txt")
    atomic_write_text(p, "payload")
    with open(p) as fh:
        assert fh.read() == "payload"
    assert os.listdir(tmp_path) == ["out.txt"]  # temp cleaned up


def test_atomic_open_unlinks_temp_on_failure(tmp_path):
    p = str(tmp_path / "out.txt")
    with pytest.raises(RuntimeError):
        with atomic_open(p, "w") as fh:
            fh.write("partial")
            raise RuntimeError("crash mid-write")
    assert os.listdir(tmp_path) == []  # neither target nor temp remains


def test_save_to_file_is_atomic(tmp_path, rng):
    X, y = _data(rng)
    bst = _train(BASE, X, y, 2)
    p = str(tmp_path / "model.txt")
    bst.save_model(p)
    assert os.listdir(tmp_path) == ["model.txt"]
    assert GBDTModel.from_file(p).num_iterations == 2


# ------------------------------------------------- fail-fast damaged loads

def test_truncated_model_file_fails_fast(tmp_path, rng):
    X, y = _data(rng)
    bst = _train(BASE, X, y, 3)
    p = str(tmp_path / "model.txt")
    bst.save_model(p)
    size = os.path.getsize(p)
    with open(p, "rb+") as fh:
        fh.truncate(size // 2)
    with pytest.raises(LightGBMError) as ei:
        GBDTModel.from_file(p)
    msg = str(ei.value)
    assert "model.txt" in msg and "truncated or corrupt" in msg


def test_garbled_header_names_key_and_file(tmp_path, rng):
    X, y = _data(rng)
    text = _train(BASE, X, y, 1).model_to_string()
    p = str(tmp_path / "bad.txt")
    with open(p, "w") as fh:
        fh.write(text.replace("num_class=1", "num_class=banana"))
    with pytest.raises(LightGBMError) as ei:
        GBDTModel.from_file(p)
    assert "bad.txt" in str(ei.value) and "garbled" in str(ei.value)


def test_missing_header_key_fails_fast():
    with pytest.raises(LightGBMError) as ei:
        GBDTModel.from_string("tree\nversion=v4\n", source="mem.txt")
    assert "num_class" in str(ei.value) and "mem.txt" in str(ei.value)


# ------------------------------------------------------ resume bit-identity

def test_resume_bit_identical_plain_with_col_sampling(tmp_path, rng):
    params = {**BASE, "feature_fraction": 0.7}
    _assert_bit_identical(*_resume_case(tmp_path, rng, params))


def test_resume_bit_identical_bagged_mid_window(tmp_path, rng):
    # snapshot at iteration 3 with bagging_freq=2: the bag in force was
    # sampled at iteration 2 and must survive the resume (iteration 3
    # REUSES it; resampling would diverge)
    params = {**BASE, "bagging_fraction": 0.6, "bagging_freq": 2,
              "feature_fraction": 0.8}
    _assert_bit_identical(*_resume_case(tmp_path, rng, params, snap_at=3))


def test_resume_bit_identical_quantized(tmp_path, rng):
    # the per-tree PRNG split chain of the stochastic-rounding key must
    # continue from the checkpointed key, not restart from the seed
    params = {**BASE, "use_quantized_grad": True,
              "quant_train_renew_leaf": True}
    _assert_bit_identical(*_resume_case(tmp_path, rng, params))


def test_resume_restores_early_stop_state(tmp_path):
    rng = np.random.RandomState(7)
    X, y = _data(rng, n=400)
    Xv, yv = _data(np.random.RandomState(8), n=200)
    params = {**BASE, "metric": "auc", "early_stopping_round": 2,
              "learning_rate": 0.5, "num_leaves": 31, "min_data_in_leaf": 2}
    straight = _train(params, X, y, 30, valid=(Xv, yv))
    # a run long enough to early-stop well before 30
    assert straight.current_iteration() < 30
    snap_at = max(2, straight.best_iteration - 1)
    half = _train(params, X, y, snap_at, valid=(Xv, yv))
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)
    st = load_checkpoint(p)
    assert st is not None and st.es is not None and st.es["enabled"]
    resumed = _train(params, X, y, 30, init_model=p, valid=(Xv, yv))
    assert resumed.best_iteration == straight.best_iteration
    assert resumed.best_score["valid_0"] == straight.best_score["valid_0"]
    assert (straight.model_to_string(num_iteration=-1)
            == resumed.model_to_string(num_iteration=-1))


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_resume_bit_identical_sharded_8_devices(tmp_path):
    """tree_learner=data on the fake 8-device mesh: every device holds a
    shard of the restored state and the resumed run matches the straight
    run bit for bit (trees are committed replicated, so equality of the
    single exported model IS equality on all devices)."""
    import jax

    from lightgbm_tpu.parallel.learners import DeviceDataParallelTreeLearner

    assert len(jax.devices()) == 8
    rng = np.random.RandomState(11)
    X, y = _data(rng, n=900, f=6)
    params = {**BASE, "num_leaves": 7}

    def _gbdt():
        cfg = Config(dict(params))
        ds = CoreDataset.from_matrix(X, label=y, config=cfg)
        bst = GBDT(cfg, ds, create_objective("binary", cfg))
        bst.tree_learner = DeviceDataParallelTreeLearner(cfg, ds)
        return bst

    straight = _gbdt()
    for _ in range(6):
        straight.train_one_iter()

    half = _gbdt()
    for _ in range(3):
        half.train_one_iter()
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)

    resumed = _gbdt()
    st = load_checkpoint(p)
    assert st is not None
    assert st.learner["n_devices"] == 8
    restore_trainer_state(resumed, st)
    assert len(resumed.tree_learner.bins_dev.sharding.device_set) == 8
    for _ in range(3):
        resumed.train_one_iter()

    assert (straight.to_model().to_string(num_iteration=-1)
            == resumed.to_model().to_string(num_iteration=-1))
    np.testing.assert_array_equal(
        np.asarray(straight.predict(X, raw_score=True)),
        np.asarray(resumed.predict(X, raw_score=True)))


# --------------------------------------------------- sidecar invalidation

def test_corrupt_sidecar_falls_back_to_plain_resume(tmp_path, rng, caplog):
    X, y = _data(rng)
    half = _train(BASE, X, y, 3)
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)
    with open(p + SIDECAR_SUFFIX, "rb+") as fh:
        fh.seek(64)
        fh.write(b"\x00" * 16)
    assert load_checkpoint(p) is None  # checksum catches the damage
    # engine falls back to plain continued training: the loaded model seeds
    # init_score and the fresh booster grows N NEW trees of its own
    resumed = _train(BASE, X, y, 3, init_model=p)
    assert resumed.current_iteration() == 3


def test_model_edit_invalidates_sidecar(tmp_path, rng):
    # the sidecar binds to the model text by content hash: touching the
    # model file after the snapshot kills bit-identity claims, so the pair
    # must be rejected
    X, y = _data(rng)
    half = _train(BASE, X, y, 3)
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)
    with open(p, "a") as fh:  # graftlint not in scope: tests
        fh.write("\n")
    assert load_checkpoint(p) is None


def test_missing_sidecar_is_silent_plain_resume(tmp_path, rng):
    X, y = _data(rng)
    half = _train(BASE, X, y, 3)
    p = str(tmp_path / "model.txt")
    half.save_model(p)
    assert not os.path.exists(p + SIDECAR_SUFFIX)
    assert load_checkpoint(p) is None
    resumed = _train(BASE, X, y, 2, init_model=p)
    assert resumed.current_iteration() == 2


def test_manifest_contents(tmp_path, rng):
    X, y = _data(rng)
    half = _train({**BASE, "bagging_fraction": 0.6, "bagging_freq": 2},
                  X, y, 4)
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)
    with open(p + SIDECAR_SUFFIX, "rb") as fh:
        assert fh.read(len(CKPT_MAGIC)) == CKPT_MAGIC
    st = load_checkpoint(p)
    assert st is not None
    man = st.manifest
    assert man["iteration"] == 4
    assert man["boosting"] == "GBDT"
    assert man["num_data"] == len(X)
    assert json.dumps(man)  # manifest is pure JSON
    assert st.score.shape == (1, len(X))
    assert st.bag is not None and len(st.bag) < len(X)
    assert "colsampler_keys" in st.learner
    assert st.learner["colsampler_keys"].shape == (624,)


def test_restore_refuses_dataset_mismatch(tmp_path, rng):
    X, y = _data(rng)
    half = _train(BASE, X, y, 2)
    p = str(tmp_path / "snap.txt")
    save_checkpoint(half, p)
    X2, y2 = _data(np.random.RandomState(9), n=300)
    with pytest.raises(LightGBMError) as ei:
        _train(BASE, X2, y2, 4, init_model=p)
    assert "refusing to resume" in str(ei.value)
