"""If-else C++ codegen tests (GBDT::SaveModelToIfElse / Tree::ToIfElse):
generate, compile with g++, load via ctypes, and assert prediction parity."""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.models.codegen import model_to_cpp
from lightgbm_tpu.models.serialize import GBDTModel


def _compile(src_path, lib_path):
    subprocess.run(["g++", "-O2", "-shared", "-fPIC", "-o", lib_path,
                    src_path], check=True, capture_output=True)
    return ctypes.CDLL(lib_path)


def _predict_native(lib, X, n_out):
    out = np.empty((X.shape[0], n_out))
    row = np.empty(X.shape[1])
    buf = np.empty(n_out)
    for i in range(X.shape[0]):
        row[:] = X[i]
        lib.Predict(row.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    buf.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
        out[i] = buf
    return out


def test_codegen_binary_with_categorical_and_nan(rng, tmp_path):
    n = 1500
    X = rng.randn(n, 5)
    X[:, 3] = rng.randint(0, 8, size=n)
    X[rng.rand(n) < 0.05, 1] = np.nan
    y = ((X[:, 0] > 0) ^ np.isin(X[:, 3], [2, 5])).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1},
                    lgb.Dataset(X, label=y, categorical_feature=[3]),
                    num_boost_round=8)
    src = str(tmp_path / "model.cpp")
    with open(src, "w") as fh:
        fh.write(model_to_cpp(GBDTModel.from_string(bst.model_to_string())))
    lib = _compile(src, str(tmp_path / "model.so"))
    native = _predict_native(lib, X, 1)[:, 0]
    ours = bst.predict(X)
    np.testing.assert_allclose(native, ours, rtol=1e-5, atol=1e-7)


def test_codegen_multiclass(rng, tmp_path):
    n = 1000
    X = rng.randn(n, 4)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    src = str(tmp_path / "mc.cpp")
    with open(src, "w") as fh:
        fh.write(model_to_cpp(GBDTModel.from_string(bst.model_to_string())))
    lib = _compile(src, str(tmp_path / "mc.so"))
    native = _predict_native(lib, X, 3)
    np.testing.assert_allclose(native, bst.predict(X), rtol=1e-5, atol=1e-7)


def test_codegen_linear_tree(rng, tmp_path):
    X = rng.uniform(-2, 2, size=(1200, 3))
    y = np.where(X[:, 0] > 0, 2 * X[:, 1], -X[:, 1]) + rng.randn(1200) * 0.05
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    src = str(tmp_path / "lin.cpp")
    with open(src, "w") as fh:
        fh.write(model_to_cpp(GBDTModel.from_string(bst.model_to_string())))
    lib = _compile(src, str(tmp_path / "lin.so"))
    native = _predict_native(lib, X, 1)[:, 0]
    np.testing.assert_allclose(native, bst.predict(X), rtol=1e-5, atol=1e-6)


def test_cli_convert_model(rng, tmp_path):
    from lightgbm_tpu import cli

    X = rng.randn(600, 3)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=3)
    model_path = str(tmp_path / "m.txt")
    bst.save_model(model_path)
    out = str(tmp_path / "pred.cpp")
    rc = cli.run(["task=convert_model", f"input_model={model_path}",
                  f"convert_model={out}", "device_type=cpu", "verbosity=-1"])
    assert rc == 0
    text = open(out).read()
    assert "PredictTree0" in text and 'extern "C"' in text
