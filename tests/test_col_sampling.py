"""feature_fraction / feature_fraction_bynode / interaction_constraints
(col_sampler.hpp parity)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _xy(n=1500, f=10, seed=21):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] - X[:, 1] + 0.5 * X[:, 2] + rng.randn(n) * 0.3 > 0
         ).astype(np.float64)
    return X, y


def _used_features(bst):
    return set(np.nonzero(bst.feature_importance())[0])


def test_feature_fraction_trains():
    X, y = _xy()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "feature_fraction": 0.5, "verbosity": -1},
                    ds, num_boost_round=20)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc


def test_feature_fraction_changes_trees():
    X, y = _xy()
    ds = lgb.Dataset(X, label=y)
    full = lgb.train({"objective": "binary", "num_leaves": 7,
                      "verbosity": -1}, ds, num_boost_round=5)
    ds2 = lgb.Dataset(X, label=y)
    frac = lgb.train({"objective": "binary", "num_leaves": 7,
                      "feature_fraction": 0.3, "verbosity": -1},
                     ds2, num_boost_round=5)
    assert not np.allclose(full.predict(X), frac.predict(X))


def test_feature_fraction_bynode():
    X, y = _xy()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "feature_fraction_bynode": 0.4, "verbosity": -1},
                    ds, num_boost_round=15)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.88, acc


def test_interaction_constraints_respected():
    X, y = _xy()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "interaction_constraints": "[0,1],[2,3]",
                     "verbosity": -1}, ds, num_boost_round=15)
    # every tree's feature set must be inside one constraint group
    dumped = bst.dump_model()
    for tree in dumped["tree_info"]:
        feats = set()

        def walk(node):
            if "split_feature" in node:
                feats.add(node["split_feature"])
                walk(node["left_child"])
                walk(node["right_child"])

        walk(tree["tree_structure"])
        assert feats <= {0, 1} or feats <= {2, 3}, feats


def test_feature_fraction_distributed():
    X, y = _xy()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "tree_learner": "data", "feature_fraction": 0.5,
                     "verbosity": -1}, ds, num_boost_round=8)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.85, acc
