"""Leaf-contiguous compaction: forward-map helper + Pallas pair kernel
(interpret mode on CPU) vs the argsort-stable partition oracle, bit-exact."""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.compact_pallas import (
    COMPACT_TILE, build_pair_tables, compact_rows, max_pairs_bound,
    range_partition_dst)


def _np_dst(go_left, ranges, n):
    """Stable 2-way partition forward map, built from the argsort oracle:
    within each range, rows ordered by (right-flag, original position)."""
    dst = np.arange(n)
    for s, c in ranges:
        order = np.argsort(~go_left[s:s + c], kind="stable") + s  # old idx
        dst[order] = np.arange(s, s + c)
    return dst


def _masks(go_left, ranges, n):
    match = np.zeros((n, len(ranges)), dtype=bool)
    for k, (s, c) in enumerate(ranges):
        match[s:s + c, k] = True
    cm = [match[:, k] & go_left for k in range(len(ranges))]
    cm += [match[:, k] & ~go_left for k in range(len(ranges))]
    return match, cm


def _dst(go_left, ranges, n):
    match, cm = _masks(go_left, ranges, n)
    starts = jnp.asarray([s for s, _ in ranges], jnp.int32)
    counts = jnp.asarray([c for _, c in ranges], jnp.int32)
    valid = jnp.ones(len(ranges), bool)
    dst, n_left = range_partition_dst(
        jnp.asarray(go_left), jnp.asarray(match), starts, counts, valid)
    return np.asarray(dst), np.asarray(n_left), cm, match


CASES = [
    ("multi", [(64, 300), (512, 512), (1100, 180), (1280, 250)]),
    ("adjacent_tiny", [(0, 7), (7, 9), (16, 3), (19, 501)]),
    ("tile_aligned", [(0, 512), (1024, 512)]),
    ("full", [(0, 2048)]),
]


@pytest.mark.parametrize("name,ranges", CASES)
def test_range_partition_dst_matches_oracle(rng, name, ranges):
    n = 2048
    go_left = rng.rand(n) < 0.4
    dst, n_left, _, _ = _dst(go_left, ranges, n)
    np.testing.assert_array_equal(dst, _np_dst(go_left, ranges, n))
    for k, (s, c) in enumerate(ranges):
        assert n_left[k] == go_left[s:s + c].sum()


@pytest.mark.parametrize("name,ranges", CASES)
@pytest.mark.parametrize("tile", [256, 512])
def test_compact_pallas_bit_exact(rng, name, ranges, tile):
    n, gp, rc = 2048, 8, 5
    go_left = rng.rand(n) < 0.5
    dst, _, cm, match = _dst(go_left, ranges, n)
    bins = rng.randint(0, 60000, size=(gp, n)).astype(np.int32)
    row = rng.randn(n, rc).astype(np.float32)
    row[:, 3] = np.arange(n)  # a perm-style integer column rides along
    moved = match.any(axis=1)
    ours_b, ours_r = compact_rows(
        jnp.asarray(bins), jnp.asarray(row), jnp.asarray(dst),
        [jnp.asarray(m) for m in cm], jnp.asarray(moved),
        tile=tile, use_pallas=True, interpret=True)
    ref_b = np.zeros_like(bins)
    ref_b[:, dst] = bins
    ref_r = np.zeros_like(row)
    ref_r[dst] = row
    np.testing.assert_array_equal(np.asarray(ours_b), ref_b)
    # bit-exact: limb transport must preserve f32 payloads exactly
    np.testing.assert_array_equal(
        np.asarray(ours_r).view(np.uint32), ref_r.view(np.uint32))


@pytest.mark.parametrize("name,ranges", CASES)
def test_compact_pallas_uint8_plane(rng, name, ranges):
    """8-bit bin plane rides the single-limb path, output stays uint8 and
    matches both the permutation oracle and the int32 2-limb result."""
    n, gp, rc, tile = 2048, 32, 5, 256  # gp % 32 == 0 for the 8-bit tile
    go_left = rng.rand(n) < 0.5
    dst, _, cm, match = _dst(go_left, ranges, n)
    bins8 = rng.randint(0, 256, size=(gp, n)).astype(np.uint8)
    row = rng.randn(n, rc).astype(np.float32)
    moved = match.any(axis=1)
    args = ([jnp.asarray(m) for m in cm], jnp.asarray(moved))
    b8, r8 = compact_rows(
        jnp.asarray(bins8), jnp.asarray(row), jnp.asarray(dst), *args,
        tile=tile, use_pallas=True, interpret=True)
    assert np.asarray(b8).dtype == np.uint8
    ref_b = np.zeros_like(bins8)
    ref_b[:, dst] = bins8
    np.testing.assert_array_equal(np.asarray(b8), ref_b)
    b32, r32 = compact_rows(
        jnp.asarray(bins8.astype(np.int32)), jnp.asarray(row),
        jnp.asarray(dst), *args, tile=tile, use_pallas=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(b8).astype(np.int32),
                                  np.asarray(b32))
    np.testing.assert_array_equal(
        np.asarray(r8).view(np.uint32), np.asarray(r32).view(np.uint32))


def test_compact_xla_fallback_uint8(rng):
    n, gp = 1024, 4
    ranges = [(100, 500)]
    go_left = rng.rand(n) < 0.3
    dst, _, cm, match = _dst(go_left, ranges, n)
    bins = rng.randint(0, 256, size=(gp, n)).astype(np.uint8)
    row = rng.randn(n, 3).astype(np.float32)
    ours_b, _ = compact_rows(
        jnp.asarray(bins), jnp.asarray(row), jnp.asarray(dst),
        [jnp.asarray(m) for m in cm], jnp.asarray(match.any(axis=1)),
        use_pallas=False)
    assert np.asarray(ours_b).dtype == np.uint8
    ref_b = np.zeros_like(bins)
    ref_b[:, dst] = bins
    np.testing.assert_array_equal(np.asarray(ours_b), ref_b)


def test_compact_xla_fallback_exact(rng):
    n, gp, rc = 1024, 3, 5
    ranges = [(100, 500), (700, 300)]
    go_left = rng.rand(n) < 0.3
    dst, _, cm, match = _dst(go_left, ranges, n)
    bins = rng.randint(0, 256, size=(gp, n)).astype(np.int32)
    row = rng.randn(n, rc).astype(np.float32)
    ours_b, ours_r = compact_rows(
        jnp.asarray(bins), jnp.asarray(row), jnp.asarray(dst),
        [jnp.asarray(m) for m in cm], jnp.asarray(match.any(axis=1)),
        use_pallas=False)
    ref_b = np.zeros_like(bins)
    ref_b[:, dst] = bins
    ref_r = np.zeros_like(row)
    ref_r[dst] = row
    np.testing.assert_array_equal(np.asarray(ours_b), ref_b)
    np.testing.assert_array_equal(np.asarray(ours_r), ref_r)


def test_compact_one_sided(rng):
    """Empty-left and empty-right partitions stay identity permutations."""
    n, tile = 1024, 256
    for flag in (True, False):
        go_left = np.full(n, flag)
        ranges = [(0, 600)]
        dst, n_left, cm, match = _dst(go_left, ranges, n)
        np.testing.assert_array_equal(dst, np.arange(n))
        assert n_left[0] == (600 if flag else 0)
        bins = np.arange(2 * n, dtype=np.int32).reshape(2, n) % 256
        bins = np.vstack([bins] * 4)  # gp=8
        row = np.arange(n * 5, dtype=np.float32).reshape(n, 5)
        ob, orr = compact_rows(
            jnp.asarray(bins), jnp.asarray(row), jnp.asarray(dst),
            [jnp.asarray(m) for m in cm], jnp.asarray(match.any(axis=1)),
            tile=tile, use_pallas=True, interpret=True)
        np.testing.assert_array_equal(np.asarray(ob), bins)
        np.testing.assert_array_equal(np.asarray(orr), row)


def test_pair_table_bound_and_coverage(rng):
    """n_pairs respects the static bound; every output tile is produced."""
    n, tile = 4096, 256
    ranges = [(0, 900), (1000, 200), (1200, 64), (1500, 2000)]
    go_left = rng.rand(n) < 0.5
    dst, _, cm, match = _dst(go_left, ranges, n)
    pi, po, copy, npairs = build_pair_tables(
        jnp.asarray(dst), [jnp.asarray(m) for m in cm],
        jnp.asarray(match.any(axis=1)), tile)
    t = n // tile
    mp = max_pairs_bound(t, len(cm))
    assert pi.shape == (mp,)
    assert int(npairs[0]) <= mp
    # all T output tiles covered, pairs sorted by out tile
    live = np.asarray(po)[:int(npairs[0])]
    assert set(live.tolist()) == set(range(t))
    assert (np.diff(live) >= 0).all()
    # pcopy semantics: 1 = raw copy of an untouched identity tile,
    # 2 = duplicate pair demoted to a skip (must repeat its predecessor's
    # blocks and never open an output block), 0 = one-hot permute.
    touched = match.any(axis=1).reshape(t, tile).any(axis=1)
    live_in = np.asarray(pi)[:int(npairs[0])]
    live_copy = np.asarray(copy)[:int(npairs[0])]
    for p in range(int(npairs[0])):
        if live_copy[p] == 1:
            assert live_in[p] == live[p] and not touched[live_in[p]]
        elif live_copy[p] == 2:
            assert p > 0
            assert live_in[p] == live_in[p - 1] and live[p] == live[p - 1]
    # after dropping skip pairs, (in, out) pairs are unique
    keep = live_copy < 2
    pairs = list(zip(live_in[keep].tolist(), live[keep].tolist()))
    assert len(pairs) == len(set(pairs))
