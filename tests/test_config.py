import pytest

from lightgbm_tpu.config import Config, key_alias_transform, kv2map, load_config_file, parse_objective_alias
from lightgbm_tpu.utils.log import LightGBMError


def test_defaults():
    c = Config()
    assert c.num_iterations == 100
    assert c.learning_rate == 0.1
    assert c.num_leaves == 31
    assert c.max_bin == 255
    assert c.min_data_in_leaf == 20
    assert c.boosting == "gbdt"
    assert c.tree_learner == "serial"


def test_alias_resolution():
    out = key_alias_transform({"n_estimators": 50, "eta": 0.3, "num_leaf": 63})
    assert out == {"num_iterations": 50, "learning_rate": 0.3, "num_leaves": 63}


def test_canonical_wins_over_alias():
    c = Config({"num_boost_round": 10, "num_iterations": 20})
    assert c.num_iterations == 20


def test_objective_aliases():
    assert parse_objective_alias("mse") == "regression"
    assert parse_objective_alias("mae") == "regression_l1"
    assert parse_objective_alias("softmax") == "multiclass"
    assert parse_objective_alias("none") == "custom"
    c = Config({"objective": "l2"})
    assert c.objective == "regression"
    assert c.metric == ["l2"]


def test_metric_parsing():
    c = Config({"objective": "binary", "metric": "auc,binary_logloss"})
    assert c.metric == ["auc", "binary_logloss"]
    c2 = Config({"objective": "binary"})
    assert c2.metric == ["binary_logloss"]


def test_type_coercion_and_checks():
    c = Config({"learning_rate": "0.05", "feature_fraction": "0.8", "is_unbalance": "true"})
    assert c.learning_rate == 0.05
    assert c.is_unbalance is True
    with pytest.raises(LightGBMError):
        Config({"feature_fraction": 1.5})


def test_goss_legacy_boosting():
    c = Config({"boosting": "goss"})
    assert c.boosting == "gbdt"
    assert c.data_sample_strategy == "goss"


def test_max_depth_caps_num_leaves():
    c = Config({"max_depth": 3})
    assert c.num_leaves == 8


def test_kv2map_and_config_file(tmp_path):
    assert kv2map(["a=1", "# comment", "b = 2 # trailing"]) == {"a": "1", "b": "2"}
    p = tmp_path / "train.conf"
    p.write_text("task = train\nobjective = binary\nnum_trees = 5\n# c\n")
    kvs = load_config_file(str(p))
    assert kvs["objective"] == "binary"
    c = Config(kvs)
    assert c.num_iterations == 5


def test_reference_train_conf_parses():
    kvs = load_config_file("/root/reference/examples/binary_classification/train.conf")
    c = Config(kvs)
    assert c.objective == "binary"
    assert c.num_trees == 100 if hasattr(c, "num_trees") else True
    assert c.metric == ["binary_logloss", "auc"]


def test_to_string_roundtrip_keys():
    c = Config({"num_leaves": 63})
    s = c.to_string()
    assert "[num_leaves: 63]" in s
    assert "[learning_rate: 0.1]" in s
    # boosting is [no-save] in the reference spec (stored as submodel name)
    assert "[boosting:" not in s


def test_uninitialized_reference_params_present():
    c = Config({"monotone_constraints": "1,-1,0", "eval_at": "1,3,5"})
    assert c.monotone_constraints == [1, -1, 0]
    assert c.eval_at == [1, 3, 5]
    assert not hasattr(Config(), "value")  # no bogus extraction artifacts


def test_no_save_params_excluded_from_to_string():
    s = Config().to_string()
    assert "[config:" not in s
    assert "[output_model:" not in s
    assert "[task:" not in s
    assert "[num_leaves: 31]" in s


def test_explicit_num_leaves_not_clamped():
    c = Config({"num_leaves": 31, "max_depth": 3})
    assert c.num_leaves == 31
    assert Config({"max_depth": 3}).num_leaves == 8


def test_verbosity_duplicate_takes_min():
    assert kv2map(["verbosity=1", "verbosity=-1"]) == {"verbosity": "-1"}
    out = key_alias_transform({"verbosity": 1, "verbose": -1})
    assert out == {"verbosity": -1}


def test_unimplemented_gain_params_warn_loudly(capsys):
    """path_smooth / monotone_penalty must never be silent no-ops: the
    config emits a loud warning naming the ignored parameter."""
    Config({"path_smooth": 0.5, "monotone_penalty": 2.0})
    out = capsys.readouterr().out
    assert "path_smooth" in out and "IGNORED" in out
    assert "monotone_penalty" in out
    # defaults stay quiet
    Config()
    assert "path_smooth" not in capsys.readouterr().out
