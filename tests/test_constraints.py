"""Monotone-constraint and CEGB tests.

References: src/treelearner/monotone_constraints.hpp (BasicLeafConstraints),
src/treelearner/feature_histogram.hpp:788-792 (constrained GetSplitGains),
src/treelearner/cost_effective_gradient_boosting.hpp (DeltaGain).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _mono_data(rng, n=3000):
    X = rng.uniform(-3, 3, size=(n, 3))
    # y increases in x0, decreases in x1, noisy in x2
    y = 2.0 * X[:, 0] - 1.5 * X[:, 1] + 0.3 * np.sin(3 * X[:, 2]) \
        + rng.randn(n) * 0.2
    return X, y


def _sweep_predictions(bst, feature, others, lo=-3, hi=3, k=64):
    grid = np.linspace(lo, hi, k)
    X = np.tile(others, (k, 1))
    X[:, feature] = grid
    return bst.predict(X)


@pytest.mark.parametrize("learner", [
    "serial",
    # the data-parallel leg re-trains on the 8-device mesh: slow tier
    pytest.param("data", marks=pytest.mark.slow),
])
def test_monotone_constraints_enforced(rng, learner):
    X, y = _mono_data(rng)
    params = {"objective": "regression", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20,
              "monotone_constraints": [1, -1, 0],
              "tree_learner": learner, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30)

    # predictions must be monotone along the constrained features for many
    # random slices of the other features
    for _ in range(20):
        others = rng.uniform(-3, 3, size=3)
        up = _sweep_predictions(bst, 0, others)
        assert np.all(np.diff(up) >= -1e-10), "feature 0 not non-decreasing"
        down = _sweep_predictions(bst, 1, others)
        assert np.all(np.diff(down) <= 1e-10), "feature 1 not non-increasing"

    # and the fit should still be useful
    mse = float(np.mean((bst.predict(X) - y) ** 2))
    assert mse < np.var(y) * 0.3


def test_unconstrained_violates_monotonicity(rng):
    """Sanity check on the test itself: without constraints the sweep is
    non-monotone somewhere (otherwise the assertion above proves nothing)."""
    X, y = _mono_data(rng)
    params = {"objective": "regression", "num_leaves": 31,
              "learning_rate": 0.1, "min_data_in_leaf": 20, "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=30)
    violated = False
    for _ in range(20):
        others = rng.uniform(-3, 3, size=3)
        up = _sweep_predictions(bst, 0, others)
        if np.any(np.diff(up) < -1e-10):
            violated = True
            break
    assert violated


def test_monotone_constraints_method_fatal(rng):
    X, y = _mono_data(rng, n=500)
    params = {"objective": "regression", "num_leaves": 7,
              "monotone_constraints": [1, 0, 0],
              "monotone_constraints_method": "advanced", "verbosity": -1}
    with pytest.raises(Exception):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2)


def test_cegb_penalty_split_shrinks_trees(rng):
    X = rng.randn(2000, 5)
    y = X[:, 0] + 0.5 * X[:, 1] ** 2 + rng.randn(2000) * 0.1
    base = {"objective": "regression", "num_leaves": 63,
            "min_data_in_leaf": 20, "verbosity": -1}
    plain = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=5)
    pen = lgb.train({**base, "cegb_penalty_split": 2.0},
                    lgb.Dataset(X, label=y), num_boost_round=5)

    def total_leaves(bst):
        return sum(t["num_leaves"] for t in bst.dump_model()["tree_info"])

    assert total_leaves(pen) < total_leaves(plain)


def test_cegb_coupled_feature_penalty(rng):
    """A huge coupled penalty on every feature but one restricts splits to
    the free feature."""
    X = rng.randn(2000, 4)
    y = X[:, 0] + 0.8 * X[:, 1] + 0.6 * X[:, 2] + rng.randn(2000) * 0.1
    params = {"objective": "regression", "num_leaves": 15,
              "min_data_in_leaf": 20, "verbosity": -1,
              "cegb_penalty_feature_coupled": [1e9, 1e9, 1e9, 0.0]}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=3)
    used = set()

    def walk(node):
        if "split_feature" in node:
            used.add(node["split_feature"])
            walk(node["left_child"])
            walk(node["right_child"])

    for t in bst.dump_model()["tree_info"]:
        walk(t["tree_structure"])
    assert used <= {3}, used


def test_cegb_lazy_feature_penalty(rng):
    """Lazy penalties are charged per not-yet-seen row; once rows are seen
    by a feature, later splits on it at those rows are cheaper. Just check
    training works and penalized features are used less."""
    X = rng.randn(1500, 3)
    y = 1.0 * X[:, 0] + 0.95 * X[:, 1] + rng.randn(1500) * 0.1
    base = {"objective": "regression", "num_leaves": 15,
            "min_data_in_leaf": 20, "verbosity": -1}
    pen = lgb.train({**base, "cegb_penalty_feature_lazy": [10.0, 0.0, 0.0],
                     "cegb_tradeoff": 1.0},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    counts = {0: 0, 1: 0, 2: 0}

    def walk(node):
        if "split_feature" in node:
            counts[node["split_feature"]] += 1
            walk(node["left_child"])
            walk(node["right_child"])

    for t in pen.dump_model()["tree_info"]:
        walk(t["tree_structure"])
    assert counts[1] > counts[0]


def test_cegb_distributed_fatal(rng):
    X = rng.randn(500, 3)
    y = X[:, 0] + rng.randn(500) * 0.1
    params = {"objective": "regression", "num_leaves": 7,
              "cegb_penalty_split": 1.0, "tree_learner": "data",
              "verbosity": -1}
    with pytest.raises(Exception):
        lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2)
