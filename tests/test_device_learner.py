"""Whole-tree-on-device learner: parity with the host-driven serial learner.

The factory only selects DeviceTreeLearner on accelerators (its masked
full-N histograms are MXU-cheap but CPU-slow), so these tests instantiate it
directly on small data.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.treelearner.device import DeviceTreeLearner
from lightgbm_tpu.treelearner.serial import SerialTreeLearner


def _boosters(X, y, params, n_iters):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    out = []
    for cls in (SerialTreeLearner, DeviceTreeLearner):
        obj = create_objective(cfg.objective, cfg)
        bst = GBDT(cfg, ds, obj)
        bst.tree_learner = cls(cfg, ds)
        for _ in range(n_iters):
            if bst.train_one_iter():
                break
        out.append(bst)
    return out


@pytest.mark.parametrize("params", [
    {"objective": "binary", "num_leaves": 15, "verbosity": -1},
    {"objective": "binary", "num_leaves": 7, "max_depth": 3,
     "min_data_in_leaf": 40, "verbosity": -1},
    {"objective": "regression", "num_leaves": 15, "lambda_l1": 0.5,
     "lambda_l2": 2.0, "verbosity": -1},
])
def test_device_matches_serial(rng, params):
    X = rng.randn(1500, 8)
    if params["objective"] == "binary":
        y = (X[:, 0] - 0.7 * X[:, 1] + rng.randn(1500) * 0.3 > 0).astype(float)
    else:
        y = 2 * X[:, 0] - X[:, 1] + 0.2 * rng.randn(1500)
    serial, device = _boosters(X, y, params, n_iters=6)
    np.testing.assert_allclose(serial.predict(X, raw_score=True),
                               device.predict(X, raw_score=True),
                               rtol=1e-4, atol=1e-5)


def test_device_with_bagging(rng):
    X = rng.randn(1200, 8)
    y = (X[:, 0] + rng.randn(1200) * 0.3 > 0).astype(float)
    cfg = Config({"objective": "binary", "num_leaves": 7, "verbosity": -1})
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    obj = create_objective("binary", cfg)
    bst = GBDT(cfg, ds, obj)
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    import jax.numpy as jnp

    grads, hesses = bst._grad_fn(bst.score[0])
    gh = jnp.concatenate([jnp.stack([grads, hesses,
                                     jnp.ones_like(grads)], axis=1),
                          jnp.zeros((1, 3), jnp.float32)])
    bag = np.sort(np.random.RandomState(0).choice(1200, 800, replace=False))
    tree = bst.tree_learner.train(gh, bag)
    assert tree.num_leaves > 1
    part = bst.tree_learner.partition
    total = sum(part.count(i) for i in range(tree.num_leaves))
    assert total == 800
    # out-of-bag rows keep leaf -1
    assert (part.ids_host == -1).sum() == 400


def test_device_stops_on_no_gain(rng):
    # constant labels -> no positive gain -> single-leaf tree
    X = rng.randn(400, 4)
    y = np.ones(400)
    cfg = Config({"objective": "regression", "num_leaves": 31,
                  "boost_from_average": False, "verbosity": -1})
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    obj = create_objective("regression", cfg)
    bst = GBDT(cfg, ds, obj)
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    stop = bst.train_one_iter()
    # first tree fits the mean; second should find nothing
    stop2 = bst.train_one_iter()
    assert stop or stop2


def test_device_hist_rows_counter(rng):
    """Rows histogrammed per tree must be O(rows in selected leaves):
    root N + sum of smaller-child rows <= ~2N for a full leaf-wise tree,
    NOT O(N * waves). Narrow waves force many waves so the old full-N
    formulation would blow far past the bound."""
    from lightgbm_tpu.utils.timer import global_timer

    n = 2000
    X = rng.randn(n, 8)
    y = 2 * X[:, 0] - X[:, 1] + np.sin(3 * X[:, 2]) + 0.1 * rng.randn(n)
    cfg = Config({"objective": "regression", "num_leaves": 31,
                  "min_data_in_leaf": 5, "verbosity": -1})
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    obj = create_objective("regression", cfg)
    bst = GBDT(cfg, ds, obj)
    learner = DeviceTreeLearner(cfg, ds)
    learner.wave = 4  # many waves: the O(N * waves) failure mode is loud
    bst.tree_learner = learner
    global_timer.counters.pop("device_hist_rows", None)
    bst.train_one_iter()
    assert learner.last_hist_rows > 0
    # root pass = N rows; each of the <=30 splits histograms the SMALLER
    # child (<= half its parent), summing to <= N per depth level of work;
    # 4N is a generous ceiling that O(N*waves) (>= 8N here) cannot meet
    assert learner.last_hist_rows <= 4 * n, learner.last_hist_rows
    assert global_timer.counters["device_hist_rows"] == learner.last_hist_rows
    assert "device_hist_rows" in global_timer.report()


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_device_pallas_interpret_matches_serial(rng, monkeypatch):
    """End-to-end coverage of the Pallas ragged-histogram + compaction wave
    path on CPU via interpret mode (on TPU this is the production path)."""
    monkeypatch.setenv("LGBM_TPU_PALLAS_INTERPRET", "1")
    # f32 operands: parity with the serial learner to float tolerance (the
    # TPU-default bf16 operands round gh to 8 mantissa bits by design)
    monkeypatch.setenv("LGBM_TPU_HIST_F32", "1")
    from lightgbm_tpu.treelearner import device as device_mod

    device_mod.grow_tree_on_device.clear_cache()
    try:
        X = rng.randn(1200, 6)
        y = (X[:, 0] - 0.6 * X[:, 1] + rng.randn(1200) * 0.3 > 0).astype(float)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
        serial, device = _boosters(X, y, params, n_iters=2)
        np.testing.assert_allclose(serial.predict(X, raw_score=True),
                                   device.predict(X, raw_score=True),
                                   rtol=1e-4, atol=1e-5)
    finally:
        device_mod.grow_tree_on_device.clear_cache()


def _device_booster(X, y, params, n_iters, probe=None):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    obj = create_objective(cfg.objective, cfg)
    bst = GBDT(cfg, ds, obj)
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    stopped_at = None
    for it in range(n_iters):
        if bst.train_one_iter():
            stopped_at = it
            break
        if probe is not None:
            probe(bst, it)
    bst.to_model()  # flushes any in-flight async tree
    return bst, stopped_at


def _assert_same_models(a, b):
    assert len(a.models) == len(b.models)
    for ta, tb in zip(a.models, b.models):
        for k, va in ta.__dict__.items():
            vb = tb.__dict__[k]
            if isinstance(va, np.ndarray):
                np.testing.assert_array_equal(va, vb, err_msg=k)
            else:
                assert va == vb, k


def test_async_pipeline_bit_identical(rng, monkeypatch):
    """The async per-tree pipeline (device growth of tree t overlapped with
    host replay of t-1, score updated from the device split log) must be
    BIT-identical to the sync path, not merely close."""
    X = rng.randn(900, 8)
    y = (X[:, 0] - 0.7 * X[:, 1] + rng.randn(900) * 0.3 > 0).astype(float)
    # 0.5 is f32-exact, so device f32 (leaf * rate) == host f64-shrink + cast
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.5,
              "min_data_in_leaf": 5, "verbosity": -1}
    monkeypatch.setenv("LGBM_TPU_ASYNC", "0")
    sync, _ = _device_booster(X, y, params, 6)
    monkeypatch.setenv("LGBM_TPU_ASYNC", "1")
    # mid-stream predict forces a flush while a tree is in flight
    asy, _ = _device_booster(
        X, y, params, 6,
        probe=lambda b, it: b.predict(X[:64], raw_score=True) if it == 2 else None)
    _assert_same_models(sync, asy)
    np.testing.assert_array_equal(np.asarray(sync.score[0]),
                                  np.asarray(asy.score[0]))
    np.testing.assert_array_equal(
        np.asarray(sync.predict(X, raw_score=True)),
        np.asarray(asy.predict(X, raw_score=True)))


def test_async_auto_gate(rng, monkeypatch):
    """Without LGBM_TPU_ASYNC the pipeline self-enables only when the
    learning rate is exactly representable in f32 (bit-identity proof
    holds); 0.1 is not f32-exact so it must stay sync."""
    monkeypatch.delenv("LGBM_TPU_ASYNC", raising=False)
    X = rng.randn(200, 4)
    y = (X[:, 0] > 0).astype(float)
    for rate, want in ((0.5, True), (0.1, False)):
        cfg = Config({"objective": "binary", "num_leaves": 7,
                      "learning_rate": rate, "verbosity": -1})
        ds = CoreDataset.from_matrix(X, label=y, config=cfg)
        bst = GBDT(cfg, ds, create_objective("binary", cfg))
        bst.tree_learner = DeviceTreeLearner(cfg, ds)
        assert bst._async_enabled() is want, rate
        monkeypatch.setenv("LGBM_TPU_ASYNC", "0")
        assert bst._async_enabled() is False
        monkeypatch.delenv("LGBM_TPU_ASYNC", raising=False)


def test_async_stops_on_no_gain(rng, monkeypatch):
    """A no-split tree is discovered one iteration late in the pipeline
    (at flush); the stub and its zero-delta duplicate are both unwound so
    the surviving model list matches the sync run exactly."""
    monkeypatch.setenv("LGBM_TPU_ASYNC", "0")
    X = rng.randn(400, 4)
    y = np.ones(400)
    params = {"objective": "regression", "num_leaves": 31,
              "learning_rate": 0.5, "boost_from_average": False,
              "verbosity": -1}
    sync, stop_sync = _device_booster(X, y, params, 6)
    monkeypatch.setenv("LGBM_TPU_ASYNC", "1")
    asy, stop_async = _device_booster(X, y, params, 6)
    assert stop_sync is not None and stop_async is not None
    # the pipeline may report the stop at most one iteration later
    assert stop_async <= stop_sync + 1
    _assert_same_models(sync, asy)
    assert sync.iter_ == asy.iter_


_PLANE_VARIANTS = {
    "plain": {},
    "bagged": {"bagging_fraction": 0.7, "bagging_freq": 1, "seed": 7},
    "quantized": {"use_quantized_grad": True, "quant_train_renew_leaf": True},
}


@pytest.mark.parametrize("variant,interpret", [
    ("plain", False), ("bagged", False), ("quantized", False),
    # interpret-mode legs pay Python per wave: slow tier (budget triage)
    pytest.param("plain", True, marks=pytest.mark.slow),
    pytest.param("quantized", True, marks=pytest.mark.slow),
])
def test_device_uint8_vs_i32_bit_identical(rng, monkeypatch, variant,
                                           interpret):
    """The narrow uint8 bin plane is a pure transport change: forcing the
    int32 escape hatch (LGBM_TPU_BINS_I32=1) must reproduce the same trees,
    predictions and hist-rows counter BIT for bit — on the XLA fallback and
    through the Pallas kernels in interpret mode."""
    import jax.numpy as jnp
    from lightgbm_tpu.treelearner import device as device_mod

    if interpret:
        monkeypatch.setenv("LGBM_TPU_PALLAS_INTERPRET", "1")
    device_mod.grow_tree_on_device.clear_cache()
    try:
        n = 600 if interpret else 1000
        n_iters = 2 if interpret else 4
        X = rng.randn(n, 6)
        y = (X[:, 0] - 0.6 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
        params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
                  **_PLANE_VARIANTS[variant]}
        monkeypatch.delenv("LGBM_TPU_BINS_I32", raising=False)
        b8, _ = _device_booster(X, y, params, n_iters)
        assert b8.tree_learner.bins_dev.dtype == jnp.uint8
        rows8 = b8.tree_learner.last_hist_rows
        monkeypatch.setenv("LGBM_TPU_BINS_I32", "1")
        b32, _ = _device_booster(X, y, params, n_iters)
        assert b32.tree_learner.bins_dev.dtype == jnp.int32
        _assert_same_models(b8, b32)
        np.testing.assert_array_equal(
            np.asarray(b8.predict(X, raw_score=True)),
            np.asarray(b32.predict(X, raw_score=True)))
        assert rows8 == b32.tree_learner.last_hist_rows
    finally:
        device_mod.grow_tree_on_device.clear_cache()


def test_device_learner_quantized_matches_serial_quantized(rng):
    """Quantized int8/int32 path in the fori_loop learner: identical int
    gradients (same PRNG seed + call order) must reproduce the serial
    quantized learner's trees exactly."""
    n = 1500
    X = rng.randn(n, 6)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "use_quantized_grad": True, "quant_train_renew_leaf": True}
    serial_b, device_b = _boosters(X, y, params, 8)
    p_serial = serial_b.predict(X)
    p_device = device_b.predict(X)
    np.testing.assert_allclose(p_device, p_serial, rtol=1e-4, atol=1e-5)
    acc = np.mean((p_device > 0.5) == y)
    assert acc > 0.9, acc


# -- gain-adaptive wave width (round 8) -----------------------------------

def _adaptive_run(X, y, params, n_iters, adaptive, monkeypatch):
    from lightgbm_tpu.utils.timer import global_timer

    monkeypatch.setenv("LGBM_TPU_ADAPTIVE_WAVE", "1" if adaptive else "0")
    global_timer.counters.pop("device_hist_rows", None)
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    learner = DeviceTreeLearner(cfg, ds)
    bst.tree_learner = learner
    ks = []
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
        ks.append(learner.wave_k)
    bst.to_model()
    rows = int(global_timer.counters["device_hist_rows"])
    return bst, learner, ks, rows


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_adaptive_wave_width_byte_identical_and_cheaper(rng, monkeypatch):
    """The wave-width controller only changes how much speculative work a
    wave dispatches, never which splits win: split decisions are replayed
    exact best-first from the same records, so the adaptive run must
    produce byte-identical trees while histogramming measurably fewer
    rows on a low-commit-rate workload (ISSUE round-8 acceptance)."""
    n = 1200
    X = rng.randn(n, 8)
    y = 2 * X[:, 0] - X[:, 1] + np.sin(3 * X[:, 2]) + 0.1 * rng.randn(n)
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 5, "verbosity": -1}
    b_on, l_on, ks_on, rows_on = _adaptive_run(
        X, y, params, 6, True, monkeypatch)
    b_off, l_off, ks_off, rows_off = _adaptive_run(
        X, y, params, 6, False, monkeypatch)
    # the fixed run pins K at the cap; the adaptive run must have shrunk
    assert all(k == l_off._wave_cap for k in ks_off), ks_off
    assert ks_on[-1] < l_on._wave_cap, ks_on
    # every adaptive width is a bucket_size rung (bounds the jit cache)
    from lightgbm_tpu.ops.partition import bucket_size
    assert all(k == l_on._wave_cap or k == bucket_size(k, minimum=1)
               for k in ks_on), ks_on
    # fewer speculative leaves per wave -> fewer rows histogrammed
    assert rows_on < rows_off, (rows_on, rows_off)
    _assert_same_models(b_on, b_off)
    np.testing.assert_array_equal(
        np.asarray(b_on.predict(X, raw_score=True)),
        np.asarray(b_off.predict(X, raw_score=True)))
    # the controller publishes its state as a gauge
    from lightgbm_tpu.utils.timer import global_timer
    assert global_timer.counters.get("wave_k") == l_off.wave_k


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_adaptive_wave_width_bounded_recompiles(rng, monkeypatch):
    """Satellite 2: K moves only along bucket_size power-of-two rungs, so
    the static `batch` arg of grow_tree_on_device takes at most
    log2(K_max)+2 distinct values — the controller must never trigger a
    per-tree recompile cascade."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.treelearner import device as device_mod

    # start cold: an earlier test may have compiled the same K rungs
    device_mod.grow_tree_on_device.clear_cache()
    monkeypatch.setenv("LGBM_TPU_ADAPTIVE_WAVE", "1")
    n = 1200
    X = rng.randn(n, 8)
    y = 2 * X[:, 0] - X[:, 1] + np.sin(3 * X[:, 2]) + 0.1 * rng.randn(n)
    params = {"objective": "regression", "num_leaves": 31,
              "min_data_in_leaf": 5, "verbosity": -1}
    with telemetry.capture(None, label="adaptive-k") as s:
        _, learner, ks, _ = _adaptive_run(X, y, params, 8, True, monkeypatch)
        grow_compiles = sum(
            c for fn, c in s.recompiles.per_fn.items() if "grow_tree" in fn)
    assert len(set(ks)) >= 3, ks  # the controller actually moved
    cap = learner._wave_cap
    bound = int(np.log2(max(cap, 2))) + 2
    assert 0 < grow_compiles <= bound, (grow_compiles, bound, ks)


# -- device-resident GOSS (round 8) ---------------------------------------

_GOSS_PARAMS = {"objective": "binary", "num_leaves": 15,
                "learning_rate": 0.5, "data_sample_strategy": "goss",
                "top_rate": 0.2, "other_rate": 0.1,
                "min_data_in_leaf": 5, "verbosity": -1}


def _goss_booster(X, y, mode, monkeypatch, cls=DeviceTreeLearner,
                  params=None):
    monkeypatch.setenv("LGBM_TPU_GOSS_DEVICE", mode)
    cfg = Config(params or _GOSS_PARAMS)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    bst.tree_learner = cls(cfg, ds)
    for _ in range(8):  # warm-up ends at iter 2 (1/0.5); GOSS active after
        if bst.train_one_iter():
            break
    bst.to_model()
    return bst


@pytest.mark.parametrize("cls", [DeviceTreeLearner, SerialTreeLearner])
def test_goss_device_bit_identical_to_host(rng, monkeypatch, cls):
    """The device-resident GOSS selection consumes the MT19937 stream
    exactly like the host path (both reduce to permutation(n_rest)[:k])
    and scores with the same f32 value chain, so the bags — and therefore
    the trained models — must match BIT for bit on both learners (the
    serial learner exercises DeviceBag's lazy host-index materialization
    and the OOB score path)."""
    n = 900
    X = rng.randn(n, 8)
    y = (X[:, 0] - 0.7 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
    b_dev = _goss_booster(X, y, "1", monkeypatch, cls)
    b_host = _goss_booster(X, y, "0", monkeypatch, cls)
    _assert_same_models(b_dev, b_host)
    np.testing.assert_array_equal(np.asarray(b_dev.score[0]),
                                  np.asarray(b_host.score[0]))
    np.testing.assert_array_equal(
        np.asarray(b_dev.predict(X, raw_score=True)),
        np.asarray(b_host.predict(X, raw_score=True)))


def test_goss_device_multiclass_bit_identical(rng, monkeypatch):
    """Multiclass gradients are [C, N]: the per-class |g·h| terms must be
    added in the same fixed class order on both paths or the f32 sort keys
    — and the bags — drift."""
    n = 900
    X = rng.randn(n, 6)
    y = (rng.rand(n) * 3).astype(int).astype(float)
    params = {**_GOSS_PARAMS, "objective": "multiclass", "num_class": 3}
    b_dev = _goss_booster(X, y, "1", monkeypatch, SerialTreeLearner,
                          params=params)
    b_host = _goss_booster(X, y, "0", monkeypatch, SerialTreeLearner,
                           params=params)
    _assert_same_models(b_dev, b_host)
    np.testing.assert_array_equal(
        np.asarray(b_dev.predict(X, raw_score=True)),
        np.asarray(b_host.predict(X, raw_score=True)))


def test_goss_device_selection_is_sync_free(rng, monkeypatch):
    """ISSUE round-8 acceptance: zero per-iteration host gathers on the
    sampling path. The sanitizer asserts no countable device sync happens
    inside the goss_device_select scope while the bag is drawn on device
    (SyncInScopeError would fail the run)."""
    from lightgbm_tpu.utils import sanitize

    sanitize.enable()
    sanitize.reset()
    try:
        n = 900
        X = rng.randn(n, 8)
        y = (X[:, 0] - 0.7 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
        b = _goss_booster(X, y, "1", monkeypatch)
        assert len(b.models) > 0
        # the device select actually ran (its jit was built) ...
        assert b.sample_strategy._select_jit is not None
        # ... and recorded no syncs under its scope (enforced live by
        # _note_sync, but assert the ledger agrees)
        counts = sanitize.sync_counts()
        assert not counts.get("goss_device_select"), counts
    finally:
        sanitize.clear_override()
        sanitize.reset()
