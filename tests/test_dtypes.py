"""Dtype-discipline lock-in: the explicit dtypes graftlint R2 demanded are
part of the device ABI. These assertions keep a future x64 flip (or a
refactor that drops a dtype=) from silently doubling memory traffic or
changing Mosaic tiling."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDS
from lightgbm_tpu.ops.partition import RowPartition
from lightgbm_tpu.ops.predict import pack_ensemble
from lightgbm_tpu.ops.score import binned_tree_arrays
from lightgbm_tpu.ops.split import make_feature_meta
from tests.test_tree import make_simple_tree


@pytest.fixture(scope="module")
def small_ds():
    rng = np.random.RandomState(3)
    X = rng.normal(size=(400, 3))
    y = rng.normal(size=400).astype(np.float32)
    return CoreDS.from_matrix(X, label=y, config=Config({"verbosity": -1}))


def test_feature_meta_dtypes(small_ds):
    meta = make_feature_meta(small_ds, int(small_ds.group_bin_counts().max()))
    assert meta.gather_index.dtype == jnp.int32
    assert meta.valid_slot.dtype == jnp.bool_
    assert meta.default_bin.dtype == jnp.int32
    assert meta.efb_omitted.dtype == jnp.bool_
    assert meta.missing_type.dtype == jnp.int32
    assert meta.nbins.dtype == jnp.int32
    assert meta.is_categorical.dtype == jnp.bool_
    assert meta.monotone.dtype == jnp.int32


def test_binned_tree_arrays_dtypes(small_ds):
    ta = binned_tree_arrays(make_simple_tree(), small_ds)
    for name in ("group", "threshold", "missing_type", "default_bin",
                 "nbins", "efb_lo", "efb_hi", "left_child", "right_child"):
        assert getattr(ta, name).dtype == jnp.int32, name
    assert ta.default_left.dtype == jnp.bool_
    assert ta.is_efb.dtype == jnp.bool_
    assert ta.leaf_value.dtype == jnp.float32


def test_packed_ensemble_dtypes():
    packed = pack_ensemble([make_simple_tree()])
    for name in ("split_feature", "decision_type", "left_child",
                 "right_child", "cat_offset", "cat_n_words", "num_leaves"):
        assert getattr(packed, name).dtype == jnp.int32, name
    assert packed.cat_words.dtype == jnp.uint32
    assert packed.threshold.dtype == jnp.float32
    assert packed.leaf_value.dtype == jnp.float32


def test_partition_index_dtypes():
    part = RowPartition(1000, min_bucket=256)
    assert part.indices(0).dtype == jnp.int32


# -- the 8-bit bin-plane ABI ------------------------------------------------
# The device learner carries the [G, N] bin plane UNWIDENED through the wave
# loop (4x less HBM traffic than int32); kernels widen per tile in-register.
# These locks keep a stray astype from silently restoring the wide plane.

def test_dataset_bins_host_dtype_uint8(small_ds):
    # max_bin <= 256: one byte per (group, row) on the host side too
    assert small_ds.bins.dtype == np.uint8


def test_device_learner_bins_stay_uint8(small_ds):
    from lightgbm_tpu.treelearner.device import DeviceTreeLearner

    learner = DeviceTreeLearner(Config({"verbosity": -1}), small_ds)
    assert learner.bins_dev.dtype == jnp.uint8


def test_bins_i32_escape_hatch(small_ds, monkeypatch):
    # LGBM_TPU_BINS_I32=1 restores the pre-narrowing int32 plane (debug /
    # backend-regression escape hatch; results stay bit-identical — see
    # test_device_learner.py::test_device_uint8_vs_i32_bit_identical)
    from lightgbm_tpu.treelearner.device import DeviceTreeLearner

    monkeypatch.setenv("LGBM_TPU_BINS_I32", "1")
    learner = DeviceTreeLearner(Config({"verbosity": -1}), small_ds)
    assert learner.bins_dev.dtype == jnp.int32


def test_wide_bins_auto_widen():
    # > 256 bins cannot fit a byte: the host plane is uint16 and the device
    # path widens to int32 at the kernel boundary automatically
    from lightgbm_tpu.treelearner.device import DeviceTreeLearner

    rng = np.random.RandomState(5)
    X = rng.normal(size=(2000, 2))
    y = rng.normal(size=2000).astype(np.float32)
    cfg = Config({"max_bin": 500, "verbosity": -1})
    ds = CoreDS.from_matrix(X, label=y, config=cfg)
    assert ds.bins.dtype == np.uint16
    learner = DeviceTreeLearner(cfg, ds)
    assert learner.bins_dev.dtype.itemsize > 1
