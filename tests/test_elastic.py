"""Elastic multi-process training suite: gang supervision (reap, elastic
restart, liveness deadlines), the collective watchdog / heartbeat runtime,
shrink-to-fit resume bit-identity, and the continuous-training flywheel's
worker-loss rollback.

Gang tests run on STUB subprocess workers (no JAX startup) so detection,
reaping and relaunch policy are tested in milliseconds; the end-to-end
4-process launcher chaos scenario lives in tools/chaos_smoke.py and the
slow-marked test that drives it.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.checkpoint import (checkpoint_callback, load_checkpoint,
                                     read_sidecar_manifest, save_checkpoint)
from lightgbm_tpu.engine import train
from lightgbm_tpu.parallel import elastic
from lightgbm_tpu.parallel.elastic import (EXIT_WORKER_LOST, GangSupervisor,
                                           WorkerLostError, latest_snapshot,
                                           worker_env)
from lightgbm_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}

# the shrink-to-fit contract holds for quantized histograms (integer
# collectives are order-exact); these are the params the chain test uses
QUANT = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
         "tree_learner": "data", "device_type": "cpu",
         "use_quantized_grad": True, "quant_train_renew_leaf": False,
         "seed": 7}


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()
    elastic.clear()


def _data(seed=7, n=500, f=10):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.5 > 0)
    return X, y.astype(np.float64)


# ------------------------------------------------------ fault-token parsing

def test_distributed_fault_tokens_parse():
    p = faults.FaultPlan("worker_kill@1:3")
    assert p.worker_kill == (1, 3)
    p = faults.FaultPlan("worker_hang@0:2")
    assert p.worker_hang == (0, 2)
    p = faults.FaultPlan("coord_loss@4")  # sugar for worker_kill@0:4
    assert p.worker_kill == (0, 4)
    p = faults.FaultPlan("slow_worker@2:5")
    assert p.slow_worker == (2, 0.005)
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError):
        faults.FaultPlan("worker_kill@1")  # malformed rank:iter stays fatal


def test_slow_worker_fires_every_attempt(monkeypatch):
    faults.install("slow_worker@0:30")
    monkeypatch.setenv("LGBM_TPU_GANG_ATTEMPT", "1")  # not attempt 0
    t0 = time.perf_counter()
    faults.check_distributed(3)
    assert time.perf_counter() - t0 >= 0.03


# -------------------------------------------------- checkpoint world fields

def test_sidecar_carries_world_fingerprint(tmp_path):
    X, y = _data(n=300)
    bst = train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=2)
    p = str(tmp_path / "m.txt")
    save_checkpoint(bst, p)
    world = read_sidecar_manifest(p)["world"]
    assert world["process_count"] == 1
    assert world["mesh_shape"] == [1]  # serial learner: no mesh cap
    assert world["device_kinds"] == ["cpu"]
    assert world["jax_version"] not in ("", "unknown")


def test_world_mismatch_restore_warns_not_fatal(tmp_path, monkeypatch, capfd):
    """A checkpoint written under a different world restores fine but names
    both shapes in a structured warning (the named-invariant contract)."""
    import lightgbm_tpu.checkpoint as ckpt_mod

    X, y = _data(n=300)
    bst = train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=2)
    p = str(tmp_path / "m.txt")
    monkeypatch.setattr(
        ckpt_mod, "world_fingerprint",
        lambda: {"process_count": 8, "mesh_shape": [8],
                 "device_kinds": ["TPU v4"], "jax_version": "x",
                 "jaxlib_version": "x"})
    save_checkpoint(bst, p)  # sidecar now claims an 8-process TPU world
    monkeypatch.undo()
    resumed = train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=4,
                    init_model=p)
    cap = capfd.readouterr()
    txt = cap.out + cap.err
    assert "written under world" in txt
    # both shapes are NAMED in the warning (the save-side mesh_shape is
    # always the learner's actual shard count, so the fake world shows
    # through its process/device fields)
    assert "'process_count': 8" in txt
    assert "'device_kinds': ['TPU v4']" in txt
    assert "restored under {'process_count': 1" in txt
    assert resumed.current_iteration() == 4


# ------------------------------------------------------- gang supervision

_STUB = ("import sys, time\n"
         "rank, attempt, mode = sys.argv[1:4]\n"
         "rank, attempt = int(rank), int(attempt)\n"
         "if mode == 'rank1_dies' and rank == 1 and attempt == 0:\n"
         "    sys.exit(7)\n"
         "if mode == 'rank0_sleeps' and rank == 0:\n"
         "    time.sleep(60)\n"
         "if mode == 'beat_then_hang':\n"
         "    import os\n"
         "    d = sys.argv[4]\n"
         "    open(os.path.join(d, f'hb_{rank}'), 'w').write('0')\n"
         "    time.sleep(60)\n"
         "time.sleep(0.05)\n")


def _stub_spawn(mode, gang_dir=""):
    def spawn(world, rank, attempt):
        return subprocess.Popen(
            [sys.executable, "-c", _STUB, str(rank), str(attempt), mode,
             gang_dir])
    return spawn


def test_gang_reaps_siblings_on_first_loss():
    """The pre-elastic launcher bug: one dead worker must not leave the
    rest running (blocked in jax.distributed barriers) while the launcher
    waits forever. rank 1 dies instantly, rank 0 'hangs' for 60s — the
    supervisor must return the failure in well under that, with rank 0
    reaped."""
    procs_seen = []

    def spawn(world, rank, attempt):
        mode = "rank1_dies" if rank == 1 else "rank0_sleeps"
        p = _stub_spawn(mode)(world, rank, attempt)
        procs_seen.append(p)
        return p

    sup = GangSupervisor(spawn, 2, elastic=False, poll_s=0.02,
                         reap_grace_s=2.0)
    t0 = time.perf_counter()
    rc = sup.run()
    took = time.perf_counter() - t0
    assert rc == 7
    assert took < 30.0  # nowhere near rank 0's 60s sleep
    for p in procs_seen:
        assert p.poll() is not None  # nobody left behind


def test_gang_elastic_restart_recovers():
    sup = GangSupervisor(_stub_spawn("rank1_dies"), 4, elastic=True,
                         max_restarts=2, poll_s=0.02)
    assert sup.run() == 0
    assert sup.attempts_used == 1
    assert sup.last_recovery_ms is not None and sup.last_recovery_ms > 0


def test_gang_restart_budget_exhausts():
    # every attempt kills rank 1 -> budget burns down, failure surfaces
    def spawn(world, rank, attempt):
        return subprocess.Popen(
            [sys.executable, "-c",
             "import sys; sys.exit(7 if int(sys.argv[1]) == 1 else 0)",
             str(rank)])
    sup = GangSupervisor(spawn, 2, elastic=True, max_restarts=1, poll_s=0.02)
    assert sup.run() == 7
    assert sup.attempts_used == 1


def test_gang_shrink_drops_world_size():
    worlds = []

    def spawn(world, rank, attempt):
        if rank == 0:
            worlds.append(world)
        return _stub_spawn("rank1_dies")(world, rank, attempt)

    sup = GangSupervisor(spawn, 4, elastic=True, max_restarts=1,
                         allow_shrink=True, poll_s=0.02)
    assert sup.run() == 0
    assert worlds == [4, 3]


def test_gang_liveness_deadline_reaps_hung_worker(tmp_path):
    """A worker that beats once then stops (hung, not dead: exit code never
    arrives) is detected through its stale liveness file and the gang is
    reaped — the hung-not-crashed half of the fault domain."""
    gd = str(tmp_path)
    sup = GangSupervisor(_stub_spawn("beat_then_hang", gd), 2, elastic=False,
                         liveness_timeout_s=0.6, gang_dir=gd, poll_s=0.05,
                         reap_grace_s=2.0)
    t0 = time.perf_counter()
    rc = sup.run()
    assert rc == 1  # liveness loss has no exit code; the supervisor's own
    assert time.perf_counter() - t0 < 30.0


def test_worker_env_builds_gang_block(tmp_path):
    env = worker_env({}, port=12345, world=4, rank=2, attempt=1,
                     gang_dir=str(tmp_path), elastic=True,
                     devices_per_proc=2)
    assert env["JAX_COORDINATOR_ADDRESS"] == "127.0.0.1:12345"
    assert env["JAX_NUM_PROCESSES"] == "4"
    assert env["JAX_PROCESS_ID"] == "2"
    assert env["LGBM_TPU_GANG"] == "1"
    assert env["LGBM_TPU_GANG_ATTEMPT"] == "1"
    assert env["LGBM_TPU_ELASTIC"] == "1"
    assert "host_platform_device_count=2" in env["XLA_FLAGS"]


def test_latest_snapshot_skips_torn_sidecar(tmp_path):
    X, y = _data(n=300)
    out = str(tmp_path / "model.txt")
    train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=4,
          callbacks=[checkpoint_callback(
              lambda it: f"{out}.snapshot_iter_{it}", period=2)])
    assert latest_snapshot(out).endswith(".snapshot_iter_4")
    # tear the newest snapshot's sidecar: resume must fall back to iter 2
    os.unlink(f"{out}.snapshot_iter_4.ckpt")
    assert latest_snapshot(out).endswith(".snapshot_iter_2")


# --------------------------------------------- watchdog / heartbeat runtime

def test_watchdog_converts_hang_to_worker_lost(tmp_path, monkeypatch):
    """A planted worker_hang blocks the training loop; the collective
    watchdog converts the block into a typed WorkerLostError — rank +
    last-good iteration — within the timeout, and dumps a flight
    postmortem."""
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    X, y = _data(n=300)
    elastic.install(timeout_s=2.0)
    faults.install("worker_hang@0:2")
    t0 = time.perf_counter()
    with pytest.raises(WorkerLostError) as ei:
        train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=6)
    took = time.perf_counter() - t0
    assert ei.value.rank == 0
    assert ei.value.last_good_iteration == 2
    assert took < 20.0  # detection bounded by the timeout, not the hang
    dumps = [f for f in os.listdir(str(tmp_path)) if "worker_lost" in f]
    assert dumps, os.listdir(str(tmp_path))
    payload = json.loads(open(os.path.join(str(tmp_path), dumps[0])).read())
    assert payload["extra"]["rank"] == 0
    assert payload["extra"]["last_good_iteration"] == 2


def test_watchdog_disarms_at_train_end():
    X, y = _data(n=300)
    rt = elastic.install(timeout_s=2.0)
    train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=2)
    # post-training silence is legitimate: the watchdog must not fire even
    # after the deadline (plus a poll cycle) has long passed
    assert not rt.watchdog._armed
    time.sleep(2.5)
    assert rt.watchdog.error is None


def test_heartbeat_rides_health_window():
    """With a HealthMonitor armed, the heartbeat token piggybacks on its
    sync slot; the self-windowed path stays quiet (no double sync)."""
    from lightgbm_tpu.utils.timer import global_timer

    X, y = _data(n=300)
    base = int(global_timer.counters.get("elastic_heartbeats", 0))
    elastic.install(timeout_s=None, heartbeat_every=1)
    train({**BASE, "health_check_policy": "warn", "health_check_every": 2},
          lgb.Dataset(X, label=y), num_boost_round=4)
    rode = int(global_timer.counters.get("elastic_heartbeats", 0)) - base
    assert rode == 2  # one per health window (4 iters / check_every 2)


def test_heartbeat_detects_short_token(monkeypatch):
    rt = elastic.install(timeout_s=None, heartbeat_every=1)
    # a completed-but-short psum means the mesh lost cardinality: fake the
    # collective to answer with fewer participants than the world
    rt._hb = (lambda x: x, 6.0, 8)
    monkeypatch.setattr("lightgbm_tpu.parallel.dist.host_value",
                        lambda x: x)
    with pytest.raises(WorkerLostError) as ei:
        rt.heartbeat_sync(iteration=5)
    assert "6/8" in str(ei.value)
    assert ei.value.last_good_iteration == 5


def test_exit_codes_are_distinct():
    # the supervisor's log keys off these; collisions would mislabel losses
    from lightgbm_tpu.utils.faults import EXIT_INJECTED_KILL

    assert EXIT_WORKER_LOST != EXIT_INJECTED_KILL
    assert EXIT_WORKER_LOST not in (0, 1, 2)


# ---------------------------------------------- shrink-to-fit bit-identity

def test_shrink_resume_8_4_1_bit_identical(tmp_path, monkeypatch):
    """THE shrink-to-fit contract: a quantized data-parallel run
    checkpointed on the 8-device mesh, resumed on 4, then resumed again on
    1, produces byte-identical model text to the undisturbed 8-device run.
    Mesh shrinkage is forced via LGBM_TPU_FORCE_MESH_DEVICES (num_machines
    cannot express the 1-device leg and echoes into the model text)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    X, y = _data(seed=42, n=1600, f=10)
    ck = str(tmp_path / "chain.txt")

    undisturbed = train(dict(QUANT), lgb.Dataset(X, label=y),
                        num_boost_round=6)

    def leg(boost_to, devices, resume):
        if devices:
            monkeypatch.setenv("LGBM_TPU_FORCE_MESH_DEVICES", str(devices))
        else:
            monkeypatch.delenv("LGBM_TPU_FORCE_MESH_DEVICES", raising=False)
        bst = train(dict(QUANT), lgb.Dataset(X, label=y),
                    num_boost_round=boost_to,
                    init_model=ck if resume else None,
                    callbacks=[checkpoint_callback(ck, period=2)])
        monkeypatch.delenv("LGBM_TPU_FORCE_MESH_DEVICES", raising=False)
        return bst

    leg(2, devices=0, resume=False)   # 8-device leg writes iter-2 state
    assert load_checkpoint(ck).iteration == 2
    assert read_sidecar_manifest(ck)["world"]["mesh_shape"] == [8]
    leg(4, devices=4, resume=True)    # shrink to 4
    assert read_sidecar_manifest(ck)["world"]["mesh_shape"] == [4]
    chained = leg(6, devices=1, resume=True)  # shrink to 1

    assert (chained.model_to_string(num_iteration=-1)
            == undisturbed.model_to_string(num_iteration=-1))


# -------------------------------------------------- flywheel worker loss

def test_flywheel_worker_loss_rolls_back_and_keeps_serving(tmp_path):
    """A gang peer lost mid-refit: the generation rolls back to its pinned
    checkpoint (no publish, watermark stays pinned), the serving front
    keeps answering from the last published model, and the NEXT refit
    resumes the same row range and publishes."""
    from lightgbm_tpu.serving import ModelRegistry
    from lightgbm_tpu.streaming import ContinuousTrainer, RowBlockStore

    X, y = _data(n=600)
    params = dict(BASE)
    store = RowBlockStore(params=params)
    store.push_rows(X[:400], label=y[:400])
    reg = ModelRegistry()
    tr = ContinuousTrainer(params, store, num_boost_round=4,
                           checkpoint_dir=str(tmp_path), registry=reg,
                           model_name="live")
    first = tr.step()  # generation 0 publishes cleanly
    assert first is not None and tr.generation == 1
    baseline = np.asarray(reg.get("live").predict(X[:32], raw_score=True))

    store.push_rows(X[400:], label=y[400:])
    elastic.install(timeout_s=2.0)
    faults.install("worker_hang@0:2")
    assert tr.step() is None          # worker lost mid-refit: no publish
    faults.clear()
    elastic.clear()
    assert tr.generation == 1         # generation did NOT advance
    assert tr._inflight_rows == 600   # watermark stays pinned
    # serving kept the last published model the whole time
    np.testing.assert_array_equal(
        np.asarray(reg.get("live").predict(X[:32], raw_score=True)),
        baseline)

    second = tr.step()                # resumes the SAME pinned row range
    assert second is not None
    assert tr.generation == 2
    assert tr._inflight_rows is None
    # the new generation is now live
    assert not np.array_equal(
        np.asarray(reg.get("live").predict(X[:32], raw_score=True)),
        baseline)


# ----------------------------------------------------- end-to-end chaos

@pytest.mark.slow
def test_chaos_smoke_end_to_end(tmp_path):
    """Drive tools/chaos_smoke.py: a 4-process --elastic launcher gang with
    a planted worker_kill@1:3 must produce a byte-identical model to the
    undisturbed gang, plus a gang_worker_lost flight dump naming rank 1."""
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "chaos_smoke.py"),
         str(tmp_path / "chaos")],
        capture_output=True, text=True, timeout=900,
        env={**os.environ, "PYTHONPATH": _REPO})
    assert out.returncode == 0, out.stdout[-3000:] + out.stderr[-3000:]
    report = json.loads(out.stdout.strip().splitlines()[-1])
    assert report["byte_equal"] is True
    assert report["flight_rank"] == 1
