"""Fault-injection suite: deterministic kills, NaN-poisoned gradients,
transient write failures, and on-disk artifact damage — asserting the
fault-tolerance layer recovers per policy instead of crashing or silently
training on garbage.

Every plan is armed programmatically via faults.install and disarmed by the
autouse fixture, so no fault leaks into other tests.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.checkpoint import (atomic_write_text, checkpoint_callback,
                                     load_checkpoint, save_checkpoint)
from lightgbm_tpu.engine import train
from lightgbm_tpu.models.serialize import GBDTModel
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.faults import InjectedFault
from lightgbm_tpu.utils.log import LightGBMError

BASE = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _data(seed=7, n=500, f=10):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.5 > 0)
    return X, y.astype(np.float64)


def _train(params, X, y, rounds, init_model=None, cbs=None):
    return train(dict(params), lgb.Dataset(X, label=y),
                 num_boost_round=rounds, init_model=init_model,
                 callbacks=cbs)


# -------------------------------------------------------- kill-and-resume

def test_kill_at_iteration_then_resume_bit_identical(tmp_path):
    """The acceptance scenario end to end: periodic snapshots, an injected
    mid-train kill, then re-running the SAME command with init_model
    pointed at the snapshot reproduces the uninterrupted run bit for bit."""
    X, y = _data()
    params = {**BASE, "bagging_fraction": 0.7, "bagging_freq": 2}
    straight = _train(params, X, y, 6)

    p = str(tmp_path / "snap.txt")
    faults.install("kill@4")
    with pytest.raises(InjectedFault):
        _train(params, X, y, 6, cbs=[checkpoint_callback(p, period=2)])
    faults.clear()
    # iterations 0..3 completed before the kill, so the last durable
    # snapshot is the period-2 one taken after iteration index 3
    assert load_checkpoint(p).iteration == 4

    resumed = _train(params, X, y, 6, init_model=p)
    assert (straight.model_to_string(num_iteration=-1)
            == resumed.model_to_string(num_iteration=-1))
    np.testing.assert_array_equal(
        np.asarray(straight.predict(X, raw_score=True)),
        np.asarray(resumed.predict(X, raw_score=True)))


def test_kill_fires_once_per_plan(tmp_path):
    # the one-shot guard: after the injected kill, the very same iteration
    # index trains through on resume without re-tripping
    X, y = _data()
    p = str(tmp_path / "snap.txt")
    faults.install("kill@2")
    with pytest.raises(InjectedFault):
        _train(BASE, X, y, 4, cbs=[checkpoint_callback(p, period=1)])
    resumed = _train(BASE, X, y, 4, init_model=p)  # plan still armed
    assert resumed.current_iteration() == 4


# ------------------------------------------------ numerical-health policies

def test_nan_poison_fatal_policy_aborts():
    X, y = _data()
    params = {**BASE, "health_check_policy": "fatal", "health_check_every": 1}
    faults.install("nan_gh@2:0.05", seed=3)
    with pytest.raises(LightGBMError) as ei:
        _train(params, X, y, 5)
    assert "health check failed" in str(ei.value)


def test_nan_poison_warn_policy_keeps_training():
    X, y = _data()
    params = {**BASE, "health_check_policy": "warn", "health_check_every": 1}
    faults.install("nan_gh@2:0.05", seed=3)
    bst = _train(params, X, y, 5)  # must not raise
    assert bst.current_iteration() >= 2


def test_nan_poison_rollback_policy_recovers():
    X, y = _data()
    params = {**BASE, "health_check_policy": "rollback",
              "health_check_every": 1}
    faults.install("nan_gh@2:0.05", seed=3)
    bst = _train(params, X, y, 6)
    # the poisoned iteration was rolled back to the last healthy sync and
    # re-trained on recomputed (clean) gradients: the model keeps growing
    # and stays finite end to end
    assert bst.current_iteration() >= 5
    preds = np.asarray(bst.predict(X, raw_score=True))
    assert np.isfinite(preds).all()


def test_unpoisoned_run_ignores_policy():
    # guardrails on, nothing injected: result identical to guardrails off
    X, y = _data()
    plain = _train(BASE, X, y, 4)
    guarded = _train({**BASE, "health_check_policy": "rollback",
                      "health_check_every": 2}, X, y, 4)
    # the parameters echo legitimately differs (it records the health
    # params); every tree must be byte-equal
    strip = lambda b: b.model_to_string(num_iteration=-1).split("\nparameters")[0]
    assert strip(plain) == strip(guarded)
    np.testing.assert_array_equal(
        np.asarray(plain.predict(X, raw_score=True)),
        np.asarray(guarded.predict(X, raw_score=True)))


def test_unknown_health_policy_is_fatal():
    X, y = _data(n=100)
    with pytest.raises(LightGBMError):
        _train({**BASE, "health_check_policy": "retry"}, X, y, 1)


# ------------------------------------------------- transient write failures

def test_transient_write_failures_absorbed_by_retries(tmp_path):
    p = str(tmp_path / "out.txt")
    faults.install("ckpt_write_fail:2")
    atomic_write_text(p, "survived")  # retries=3 > 2 injected failures
    with open(p) as fh:
        assert fh.read() == "survived"


def test_write_failures_beyond_retries_raise(tmp_path):
    p = str(tmp_path / "out.txt")
    faults.install("ckpt_write_fail:5")
    with pytest.raises(OSError):
        atomic_write_text(p, "doomed")
    assert not os.path.exists(p)  # nothing partial left behind


# ----------------------------------------------------- damaged artifacts

def test_corrupted_sidecar_is_rejected_on_load(tmp_path):
    X, y = _data()
    half = _train(BASE, X, y, 3)
    p = str(tmp_path / "snap.txt")
    faults.install("ckpt_corrupt")
    save_checkpoint(half, p)  # sidecar damaged after the durable write
    assert load_checkpoint(p) is None
    # ...and the model text itself is untouched, so plain resume works
    resumed = _train(BASE, X, y, 2, init_model=p)
    assert resumed.current_iteration() == 2


def test_truncated_model_fails_fast_with_filename(tmp_path):
    X, y = _data()
    bst = _train(BASE, X, y, 3)
    p = str(tmp_path / "model.txt")
    faults.install("ckpt_truncate")
    bst.save_model(p)  # truncated to half after the durable write
    with pytest.raises(LightGBMError) as ei:
        GBDTModel.from_file(p)
    assert "model.txt" in str(ei.value)
    assert "truncated or corrupt" in str(ei.value)


def test_unknown_fault_token_is_fatal():
    with pytest.raises(LightGBMError):
        faults.install("explode@3")


def test_serving_fault_tokens_parse():
    p = faults.FaultPlan("slow_predict@3")
    assert p.slow_predict_at == 3 and p.slow_predict_s == 0.05
    p = faults.FaultPlan("slow_predict@2:0.5")
    assert p.slow_predict_at == 2 and p.slow_predict_s == 0.5
    p = faults.FaultPlan("predict_fail@4")
    assert p.fail_predict_at == 4 and p.fail_predict_count == 3
    p = faults.FaultPlan("predict_fail@1:7,model_corrupt_upload")
    assert p.fail_predict_at == 1 and p.fail_predict_count == 7
    assert p.corrupt_upload
    with pytest.raises(LightGBMError):
        faults.FaultPlan("predict_slow@1")  # unknown token stays fatal


def test_on_serve_dispatch_window():
    faults.install("predict_fail@2:2")
    faults.on_serve_dispatch()  # dispatch 1: before the window
    for _ in range(2):  # dispatches 2-3: inside
        with pytest.raises(InjectedFault):
            faults.on_serve_dispatch()
    faults.on_serve_dispatch()  # dispatch 4: window passed
    faults.clear()
    # disarmed plans must not count dispatches at all
    faults.on_serve_dispatch()
    assert faults._get()._dispatch_no == 0


def test_corrupt_upload_fires_once():
    faults.install("model_corrupt_upload")
    text = "x" * 4096
    first = faults.maybe_corrupt_upload(text)
    assert first != text and len(first) == len(text)
    assert faults.maybe_corrupt_upload(text) == text  # one-shot
