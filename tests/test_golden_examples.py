"""Golden end-to-end CLI tests on the reference's own example configs.

SURVEY §4 takeaway (a): run the untouched reference train.conf files
(/root/reference/examples/*) through lightgbm_tpu.cli and assert metric
thresholds derived from the reference CLI's results at the same iteration
count (captured with the reference binary built from /root/reference,
round 3): binary valid AUC 0.8015 / logloss 0.5514; regression valid
l2 0.2736; multiclass valid multi_logloss 1.4663 — all at num_trees=20.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import cli

EXAMPLES = "/root/reference/examples"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(EXAMPLES), reason="reference examples not available")


def _run_cli(example, conf, tmp_path, extra=()):
    cwd = os.getcwd()
    model_path = str(tmp_path / "model.txt")
    try:
        os.chdir(os.path.join(EXAMPLES, example))
        rc = cli.run([f"config={conf}", "num_trees=20",
                      f"output_model={model_path}", "device_type=cpu",
                      "verbosity=-1", *extra])
    finally:
        os.chdir(cwd)
    assert rc == 0
    return lgb.Booster(model_file=model_path)


def _load(example, name):
    data = np.loadtxt(os.path.join(EXAMPLES, example, name), delimiter="\t")
    return data[:, 1:], data[:, 0]


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    n_pos = y.sum()
    n_neg = len(y) - n_pos
    return (ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_binary_classification_example(tmp_path):
    bst = _run_cli("binary_classification", "train.conf", tmp_path)
    X, y = _load("binary_classification", "binary.test")
    p = bst.predict(X)
    auc = _auc(y, p)
    logloss = -np.mean(y * np.log(p) + (1 - y) * np.log(1 - p))
    # reference binary at 20 trees: valid auc 0.8015, logloss 0.5514
    assert auc >= 0.79, auc
    assert logloss <= 0.57, logloss


def test_regression_example_with_goss(tmp_path):
    bst = _run_cli("regression", "train.conf", tmp_path,
                   extra=("data_sample_strategy=goss",))
    X, y = _load("regression", "regression.test")
    l2 = float(np.mean((bst.predict(X) - y) ** 2))
    # reference at 20 trees (plain bagging): valid l2 0.2736
    assert l2 <= 0.30, l2


def test_multiclass_classification_example(tmp_path):
    bst = _run_cli("multiclass_classification", "train.conf", tmp_path)
    X, y = _load("multiclass_classification", "multiclass.test")
    p = bst.predict(X)
    eps = 1e-15
    logloss = -np.mean(np.log(np.clip(
        p[np.arange(len(y)), y.astype(int)], eps, 1)))
    # reference at 20 trees: valid multi_logloss 1.4663
    assert logloss <= 1.55, logloss
