"""Pallas histogram kernel correctness (interpret mode on CPU) vs the XLA
path and the numpy reference."""
import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.ops.hist_pallas import pallas_histogram
from lightgbm_tpu.ops.histogram import build_histogram


def _ref_hist(bins, gh, num_bins):
    G, N = bins.shape
    out = np.zeros((G, num_bins, gh.shape[1]))
    for g in range(G):
        for b in range(num_bins):
            out[g, b] = gh[bins[g] == b].sum(axis=0)
    return out


@pytest.mark.parametrize("n,tile", [(500, 128), (4096, 2048), (3000, 2048)])
def test_pallas_histogram_float(rng, n, tile):
    G, B = 5, 16
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    gh = rng.randn(n, 3).astype(np.float32)
    ours = np.asarray(pallas_histogram(
        jnp.asarray(bins), jnp.asarray(gh), B, tile_rows=tile, f32=True,
        interpret=True))
    np.testing.assert_allclose(ours, _ref_hist(bins, gh, B), rtol=1e-5,
                               atol=1e-4)
    xla = np.asarray(build_histogram(jnp.asarray(bins), jnp.asarray(gh), B))
    np.testing.assert_allclose(ours, xla, rtol=1e-5, atol=1e-4)


def test_pallas_histogram_bf16_default(rng):
    """The TPU default path: bf16 operands, f32 accumulation — sums must
    track the exact histogram to bf16 operand-rounding tolerance."""
    G, B, n = 4, 32, 20_000
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    gh = rng.randn(n, 3).astype(np.float32)
    ours = np.asarray(pallas_histogram(
        jnp.asarray(bins), jnp.asarray(gh), B, interpret=True))
    assert ours.dtype == np.float32
    ref = _ref_hist(bins, gh, B)
    np.testing.assert_allclose(ours, ref, rtol=2e-2, atol=2e-1)


def test_pallas_histogram_slots(rng):
    """Slot-expanded wave histogram == per-slot masked histograms."""
    from lightgbm_tpu.ops.hist_pallas import pallas_histogram_slots

    G, B, n, S = 3, 16, 3000, 4
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    gh = rng.randn(n, 3).astype(np.float32)
    slot = rng.randint(0, S + 2, size=n).astype(np.int32)  # S+ = dump
    ours = np.asarray(pallas_histogram_slots(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot), B, S,
        f32=True, interpret=True))
    assert ours.shape == (G, B, S * 3)
    for s in range(S):
        ref = _ref_hist(bins, np.where((slot == s)[:, None], gh, 0.0), B)
        np.testing.assert_allclose(ours[..., s * 3:(s + 1) * 3], ref,
                                   rtol=1e-5, atol=1e-4)


def test_pallas_histogram_slots_bf16_default(rng):
    """The default TPU wave path: bf16 operands, f32 accumulation."""
    from lightgbm_tpu.ops.hist_pallas import pallas_histogram_slots

    G, B, n, S = 3, 16, 4000, 4
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    gh = rng.randn(n, 3).astype(np.float32)
    slot = rng.randint(0, S + 2, size=n).astype(np.int32)
    ours = np.asarray(pallas_histogram_slots(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot), B, S,
        interpret=True))
    assert ours.dtype == np.float32
    for s in range(S):
        ref = _ref_hist(bins, np.where((slot == s)[:, None], gh, 0.0), B)
        np.testing.assert_allclose(ours[..., s * 3:(s + 1) * 3], ref,
                                   rtol=2e-2, atol=2e-1)


def test_pallas_histogram_slots_quantized_exact(rng):
    """Quantized wave path: int32 in-kernel build, int8 matmul operands,
    exact int32 accumulation."""
    from lightgbm_tpu.ops.hist_pallas import pallas_histogram_slots

    G, B, n, S = 3, 16, 4000, 4
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    gh = np.stack([rng.randint(-4, 5, n), rng.randint(0, 6, n),
                   np.ones(n)], axis=1).astype(np.int8)
    slot = rng.randint(0, S + 2, size=n).astype(np.int32)
    ours = np.asarray(pallas_histogram_slots(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot), B, S,
        quantized=True, interpret=True))
    assert ours.dtype == np.int32
    for s in range(S):
        ref = _ref_hist(bins, np.where((slot == s)[:, None],
                                       gh.astype(np.int64), 0), B)
        np.testing.assert_array_equal(ours[..., s * 3:(s + 1) * 3],
                                      ref.astype(np.int64))


def _ragged_setup(rng, n, tile, ranges, S, quantized=False):
    """Leaf-contiguous layout: slot < S only inside the given ranges."""
    import jax.numpy as jnp
    from lightgbm_tpu.ops.hist_pallas import active_tile_table

    G, B = 3, 16
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    if quantized:
        gh = np.stack([rng.randint(-4, 5, n), rng.randint(0, 6, n),
                       np.ones(n)], axis=1).astype(np.float32)
    else:
        gh = rng.randn(n, 3).astype(np.float32)
    slot = np.full(n, S, dtype=np.int32)  # dump by default
    for k, (s, e) in enumerate(ranges):
        slot[s:e] = k % S
    starts = jnp.asarray([s for s, _ in ranges], jnp.int32)
    ends = jnp.asarray([e for _, e in ranges], jnp.int32)
    tiles, n_act = active_tile_table(starts, ends,
                                     jnp.ones(len(ranges), bool),
                                     n // tile, tile)
    return G, B, bins, gh, slot, tiles, n_act


@pytest.mark.parametrize("ranges", [
    [(0, 700), (1024, 1100), (2000, 3000)],
    [(512, 1024)],                      # tile-aligned single range
    [(100, 101), (3500, 4096)],         # tiny + tail
])
def test_pallas_histogram_slots_ragged(rng, ranges):
    from lightgbm_tpu.ops.hist_pallas import pallas_histogram_slots_ragged

    n, tile, S = 4096, 512, 4
    G, B, bins, gh, slot, tiles, n_act = _ragged_setup(rng, n, tile, ranges,
                                                       S)
    ours = np.asarray(pallas_histogram_slots_ragged(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot), tiles, n_act,
        B, S, tile_rows=tile, f32=True, interpret=True))
    assert ours.shape == (G, B, S * 3)
    covered = int(np.asarray(n_act)[0]) * tile
    assert covered <= n  # ragged grid walks only overlapping tiles
    for s in range(S):
        ref = _ref_hist(bins, np.where((slot == s)[:, None], gh, 0.0), B)
        np.testing.assert_allclose(ours[..., s * 3:(s + 1) * 3], ref,
                                   rtol=1e-5, atol=1e-4)


def test_pallas_histogram_slots_ragged_quantized_exact(rng):
    """Quantized ragged path: f32 gh holding small ints, bf16 operands,
    int32 accumulation — must match the dense int8 path bit-for-bit."""
    from lightgbm_tpu.ops.hist_pallas import (pallas_histogram_slots,
                                              pallas_histogram_slots_ragged)

    n, tile, S = 4096, 512, 3
    ranges = [(0, 900), (1500, 2600), (3000, 4000)]
    G, B, bins, gh, slot, tiles, n_act = _ragged_setup(
        rng, n, tile, ranges, S, quantized=True)
    ours = np.asarray(pallas_histogram_slots_ragged(
        jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot), tiles, n_act,
        B, S, tile_rows=tile, quantized=True, interpret=True))
    assert ours.dtype == np.int32
    dense = np.asarray(pallas_histogram_slots(
        jnp.asarray(bins), jnp.asarray(gh.astype(np.int8)),
        jnp.asarray(slot), B, S, quantized=True, interpret=True))
    np.testing.assert_array_equal(ours, dense)


def test_active_tile_table():
    import jax.numpy as jnp
    from lightgbm_tpu.ops.hist_pallas import active_tile_table

    tiles, n_act = active_tile_table(
        jnp.asarray([0, 1024, 4000], jnp.int32),
        jnp.asarray([512, 1536, 4096], jnp.int32),
        jnp.asarray([True, True, False]), 8, 512)
    # [0,512) -> tile 0; [1024,1536) -> tile 2; third range invalid
    assert int(n_act[0]) == 2
    np.testing.assert_array_equal(np.asarray(tiles)[:3], [0, 2, 2])
    # boundary straddle: [500, 1030) touches tiles 0, 1, 2
    tiles, n_act = active_tile_table(
        jnp.asarray([500], jnp.int32), jnp.asarray([1030], jnp.int32),
        jnp.asarray([True]), 4, 512)
    assert int(n_act[0]) == 3
    np.testing.assert_array_equal(np.asarray(tiles), [0, 1, 2, 2])


def test_pallas_histogram_uint8_bins_bit_identical(rng):
    """The 8-bit plane path (uint8 bins pass through unwidened, kernel
    widens the group row in-register) is bit-identical to int32 bins."""
    G, B, n = 5, 256, 3000
    bins8 = rng.randint(0, B, size=(G, n)).astype(np.uint8)
    gh = rng.randn(n, 3).astype(np.float32)
    for f32 in (True, False):
        ours8 = np.asarray(pallas_histogram(
            jnp.asarray(bins8), jnp.asarray(gh), B, f32=f32, interpret=True))
        ours32 = np.asarray(pallas_histogram(
            jnp.asarray(bins8.astype(np.int32)), jnp.asarray(gh), B,
            f32=f32, interpret=True))
        np.testing.assert_array_equal(ours8.view(np.uint32),
                                      ours32.view(np.uint32))


def test_pallas_histogram_slots_ragged_uint8_bit_identical(rng):
    """Wave (ragged) kernel: uint8 bins bit-identical to int32 bins, float
    and quantized variants."""
    from lightgbm_tpu.ops.hist_pallas import pallas_histogram_slots_ragged

    n, tile, S = 4096, 512, 3
    ranges = [(0, 900), (1500, 2600), (3000, 4000)]
    for quant in (False, True):
        G, B, bins, gh, slot, tiles, n_act = _ragged_setup(
            rng, n, tile, ranges, S, quantized=quant)
        bins8 = bins.astype(np.uint8)
        a = np.asarray(pallas_histogram_slots_ragged(
            jnp.asarray(bins8), jnp.asarray(gh), jnp.asarray(slot), tiles,
            n_act, B, S, tile_rows=tile, quantized=quant, interpret=True))
        b = np.asarray(pallas_histogram_slots_ragged(
            jnp.asarray(bins), jnp.asarray(gh), jnp.asarray(slot), tiles,
            n_act, B, S, tile_rows=tile, quantized=quant, interpret=True))
        np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))


def test_pallas_histogram_slots_uint8_bit_identical(rng):
    from lightgbm_tpu.ops.hist_pallas import pallas_histogram_slots

    G, B, n, S = 3, 16, 3000, 4
    bins8 = rng.randint(0, B, size=(G, n)).astype(np.uint8)
    gh = rng.randn(n, 3).astype(np.float32)
    slot = rng.randint(0, S + 2, size=n).astype(np.int32)
    a = np.asarray(pallas_histogram_slots(
        jnp.asarray(bins8), jnp.asarray(gh), jnp.asarray(slot), B, S,
        interpret=True))
    b = np.asarray(pallas_histogram_slots(
        jnp.asarray(bins8.astype(np.int32)), jnp.asarray(gh),
        jnp.asarray(slot), B, S, interpret=True))
    np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))


def test_pallas_histogram_quantized_exact(rng):
    G, B, n = 4, 32, 5000
    bins = rng.randint(0, B, size=(G, n)).astype(np.int32)
    gh = np.stack([rng.randint(-2, 3, n), rng.randint(0, 5, n),
                   np.ones(n)], axis=1).astype(np.int8)
    ours = np.asarray(pallas_histogram(
        jnp.asarray(bins), jnp.asarray(gh), B, tile_rows=1024,
        quantized=True, interpret=True))
    assert ours.dtype == np.int32
    ref = _ref_hist(bins, gh.astype(np.int64), B)
    np.testing.assert_array_equal(ours, ref.astype(np.int64))
