"""Cross-implementation model interchange tests.

The fixtures under tests/fixtures/ were produced by the REFERENCE LightGBM
CLI (built from /root/reference at round 3): `interchange.model.txt` is a
reference-saved model (12 trees, numerical + categorical splits, NaN
missing values) and `interchange.pred.txt` the reference's own predictions
on the training file. Loading the reference's model and reproducing its
predictions proves the model text format (gbdt_model_text.cpp:314-666,
tree.cpp:349-410) and the decision semantics (NumericalDecision /
CategoricalDecision, include/LightGBM/tree.h:338-420) interchange both ways.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _load_fixture_data():
    data = np.loadtxt(os.path.join(FIXTURES, "interchange.train"),
                      delimiter="\t")
    return data[:, 1:], data[:, 0]


def test_load_reference_model_and_predict():
    X, _ = _load_fixture_data()
    ref_pred = np.loadtxt(os.path.join(FIXTURES, "interchange.pred.txt"))
    bst = lgb.Booster(model_file=os.path.join(FIXTURES,
                                              "interchange.model.txt"))
    pred = bst.predict(X)
    # reference predicts in double; our packed traversal/accumulation is f32
    np.testing.assert_allclose(pred, ref_pred, rtol=2e-5, atol=2e-6)


def test_reference_model_raw_score():
    X, _ = _load_fixture_data()
    bst = lgb.Booster(model_file=os.path.join(FIXTURES,
                                              "interchange.model.txt"))
    raw = bst.predict(X, raw_score=True)
    prob = bst.predict(X)
    np.testing.assert_allclose(prob, 1.0 / (1.0 + np.exp(-raw)), rtol=1e-6)


def test_reference_model_roundtrip_resave(tmp_path):
    """Re-saving the loaded reference model must not change predictions
    (the %.17g round-trip requirement)."""
    X, _ = _load_fixture_data()
    path_in = os.path.join(FIXTURES, "interchange.model.txt")
    bst = lgb.Booster(model_file=path_in)
    pred = bst.predict(X)
    path_out = str(tmp_path / "resaved.txt")
    bst.save_model(path_out)
    re_pred = lgb.Booster(model_file=path_out).predict(X)
    np.testing.assert_allclose(re_pred, pred, rtol=0, atol=0)


def test_our_model_keeps_reference_fields(tmp_path):
    """Models we save carry every header/tree field the reference's parser
    requires (gbdt_model_text.cpp LoadModelFromString)."""
    X, y = _load_fixture_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[3])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=4)
    path = str(tmp_path / "ours.txt")
    bst.save_model(path)
    text = open(path).read()
    for field in ("tree\nversion=v4", "num_class=", "num_tree_per_iteration=",
                  "max_feature_idx=", "objective=binary",
                  "feature_names=", "feature_infos=", "tree_sizes=",
                  "Tree=0", "num_leaves=", "split_feature=", "threshold=",
                  "decision_type=", "left_child=", "right_child=",
                  "leaf_value=", "cat_boundaries=", "cat_threshold=",
                  "shrinkage=", "end of trees"):
        assert field in text, f"missing reference model field: {field}"
