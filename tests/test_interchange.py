"""Cross-implementation model interchange tests.

The fixtures under tests/fixtures/ were produced by the REFERENCE LightGBM
CLI (built from /root/reference at round 3): `interchange.model.txt` is a
reference-saved model (12 trees, numerical + categorical splits, NaN
missing values) and `interchange.pred.txt` the reference's own predictions
on the training file. Loading the reference's model and reproducing its
predictions proves the model text format (gbdt_model_text.cpp:314-666,
tree.cpp:349-410) and the decision semantics (NumericalDecision /
CategoricalDecision, include/LightGBM/tree.h:338-420) interchange both ways.
"""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _load_fixture_data():
    data = np.loadtxt(os.path.join(FIXTURES, "interchange.train"),
                      delimiter="\t")
    return data[:, 1:], data[:, 0]


def test_load_reference_model_and_predict():
    X, _ = _load_fixture_data()
    ref_pred = np.loadtxt(os.path.join(FIXTURES, "interchange.pred.txt"))
    bst = lgb.Booster(model_file=os.path.join(FIXTURES,
                                              "interchange.model.txt"))
    pred = bst.predict(X)
    # reference predicts in double; our packed traversal/accumulation is f32
    np.testing.assert_allclose(pred, ref_pred, rtol=2e-5, atol=2e-6)


def test_reference_model_raw_score():
    X, _ = _load_fixture_data()
    bst = lgb.Booster(model_file=os.path.join(FIXTURES,
                                              "interchange.model.txt"))
    raw = bst.predict(X, raw_score=True)
    prob = bst.predict(X)
    np.testing.assert_allclose(prob, 1.0 / (1.0 + np.exp(-raw)), rtol=1e-6)


def test_reference_model_roundtrip_resave(tmp_path):
    """Re-saving the loaded reference model must not change predictions
    (the %.17g round-trip requirement)."""
    X, _ = _load_fixture_data()
    path_in = os.path.join(FIXTURES, "interchange.model.txt")
    bst = lgb.Booster(model_file=path_in)
    pred = bst.predict(X)
    path_out = str(tmp_path / "resaved.txt")
    bst.save_model(path_out)
    re_pred = lgb.Booster(model_file=path_out).predict(X)
    np.testing.assert_allclose(re_pred, pred, rtol=0, atol=0)


def test_our_model_keeps_reference_fields(tmp_path):
    """Models we save carry every header/tree field the reference's parser
    requires (gbdt_model_text.cpp LoadModelFromString)."""
    X, y = _load_fixture_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[3])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=4)
    path = str(tmp_path / "ours.txt")
    bst.save_model(path)
    text = open(path).read()
    for field in ("tree\nversion=v4", "num_class=", "num_tree_per_iteration=",
                  "max_feature_idx=", "objective=binary",
                  "feature_names=", "feature_infos=", "tree_sizes=",
                  "Tree=0", "num_leaves=", "split_feature=", "threshold=",
                  "decision_type=", "left_child=", "right_child=",
                  "leaf_value=", "cat_boundaries=", "cat_threshold=",
                  "shrinkage=", "end of trees"):
        assert field in text, f"missing reference model field: {field}"


@pytest.mark.skipif(not os.path.isdir("/root/reference/examples"),
                    reason="reference examples not available")
def test_load_reference_lambdarank_model():
    """Ranking-model interchange: the reference-trained lambdarank model
    (8 trees on examples/lambdarank) loads and reproduces the reference's
    own raw predictions on rank.test."""
    from lightgbm_tpu.io.parser import parse_file

    X, _, _ = parse_file("/root/reference/examples/lambdarank/rank.test")
    ref_pred = np.loadtxt(os.path.join(FIXTURES, "rank.pred.txt"))
    bst = lgb.Booster(model_file=os.path.join(FIXTURES, "rank.model.txt"))
    pred = bst.predict(X)
    np.testing.assert_allclose(pred, ref_pred, rtol=2e-5, atol=2e-6)


@pytest.mark.skipif(not os.path.isdir("/root/reference/examples"),
                    reason="reference examples not available")
def test_load_reference_multiclass_model():
    """Multiclass interchange (num_tree_per_iteration=5 softmax packing)."""
    from lightgbm_tpu.io.parser import parse_file

    X, _, _ = parse_file(
        "/root/reference/examples/multiclass_classification/multiclass.test")
    ref_pred = np.loadtxt(os.path.join(FIXTURES, "multiclass.pred.txt"))
    bst = lgb.Booster(model_file=os.path.join(FIXTURES,
                                              "multiclass.model.txt"))
    pred = bst.predict(X)
    assert pred.shape == ref_pred.shape
    # a handful of rows sit exactly on split thresholds where f32 device
    # inference and the reference's f64 traversal legitimately disagree;
    # demand near-total elementwise agreement instead of exactness
    close = np.isclose(pred, ref_pred, rtol=5e-5, atol=5e-6)
    assert close.mean() > 0.995, close.mean()
    assert np.abs(pred - ref_pred).mean() < 1e-4


REF_BIN = "/tmp/refsrc/lightgbm"


@pytest.mark.skipif(not os.path.exists(REF_BIN),
                    reason="reference binary not built (see memory notes: "
                           "cp -r /root/reference /tmp/refsrc + stubs)")
def test_reference_binary_loads_our_model(tmp_path):
    """Reverse interchange: the REFERENCE LightGBM binary loads a model we
    saved and reproduces our predictions."""
    import subprocess

    X, y = _load_fixture_data()
    ds = lgb.Dataset(X, label=y, categorical_feature=[3])
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=6)
    model_path = str(tmp_path / "ours.txt")
    bst.save_model(model_path)
    conf = tmp_path / "pred.conf"
    out_path = str(tmp_path / "ref_pred.txt")
    conf.write_text(
        "task = predict\n"
        f"data = {os.path.join(FIXTURES, 'interchange.train')}\n"
        f"input_model = {model_path}\n"
        f"output_result = {out_path}\n")
    subprocess.run([REF_BIN, f"config={conf}"], check=True,
                   capture_output=True, timeout=300)
    ref_on_ours = np.loadtxt(out_path)
    np.testing.assert_allclose(ref_on_ours, bst.predict(X), rtol=2e-5,
                               atol=2e-6)
