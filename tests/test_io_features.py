"""Binary dataset cache, forced bins, and forced splits tests
(Dataset::SaveBinaryFile / DatasetLoader::GetForcedBins /
SerialTreeLearner::ForceSplits)."""
import json
import os

import numpy as np

import lightgbm_tpu as lgb


def _data(rng, n=1200):
    X = rng.randn(n, 4)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def test_binary_cache_roundtrip(rng, tmp_path):
    X, y = _data(rng)
    ds = lgb.Dataset(X, label=y)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    bst = lgb.train(params, ds, num_boost_round=5)
    pred = bst.predict(X)

    cache = str(tmp_path / "train.bin")
    ds.save_binary(cache)
    ds2 = lgb.Dataset(cache)
    ds2.construct()
    np.testing.assert_array_equal(ds2._handle.bins, ds._handle.bins)
    bst2 = lgb.train(params, ds2, num_boost_round=5)
    np.testing.assert_allclose(bst2.predict(X), pred, rtol=1e-6)


def test_forced_bins(rng, tmp_path):
    X, y = _data(rng)
    fb = str(tmp_path / "forced_bins.json")
    bounds = [-0.5, 0.0, 0.5]
    with open(fb, "w") as fh:
        json.dump([{"feature": 0, "bin_upper_bound": bounds}], fh)
    ds = lgb.Dataset(X, label=y, params={"forcedbins_filename": fb,
                                         "max_bin": 16})
    ds.construct()
    ub = ds._handle.mappers[0].bin_upper_bound
    for b in bounds:
        assert any(abs(u - b) < 1e-9 for u in ub), (b, ub)


def test_forced_splits(rng, tmp_path):
    X, y = _data(rng)
    fs = str(tmp_path / "forced_splits.json")
    with open(fs, "w") as fh:
        json.dump({"feature": 2, "threshold": 0.25,
                   "left": {"feature": 3, "threshold": -0.1}}, fh)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "forcedsplits_filename": fs, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    for t in bst.dump_model()["tree_info"]:
        root = t["tree_structure"]
        assert root["split_feature"] == 2
        assert abs(float(root["threshold"]) - 0.25) < 0.3  # binned threshold
        assert root["left_child"].get("split_feature") == 3
    assert np.isfinite(bst.predict(X)).all()


def test_histogram_pool_cap_exact(rng):
    """histogram_pool_size LRU eviction + recompute must not change the
    model (feature_histogram.hpp HistogramPool semantics)."""
    X, y = _data(rng, n=1500)
    base = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 10,
            "verbosity": -1}
    p_full = lgb.train(base, lgb.Dataset(X, label=y),
                       num_boost_round=5).predict(X)
    p_cap = lgb.train({**base, "histogram_pool_size": 0.001},
                      lgb.Dataset(X, label=y), num_boost_round=5).predict(X)
    np.testing.assert_allclose(p_cap, p_full, rtol=1e-6)

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.treelearner.serial import SerialTreeLearner

    cfg = Config({**base, "histogram_pool_size": 0.001})
    core = CoreDataset.from_matrix(X, label=y, config=cfg)
    learner = SerialTreeLearner(cfg, core)
    assert learner._pool_cap >= 2


def test_cli_save_binary_cache(rng, tmp_path):
    """is_save_binary_file writes a loadable cache next to the data file
    (application.cpp LoadData -> SaveBinaryFile)."""
    from lightgbm_tpu import cli

    X, y = _data(rng, n=400)
    train_path = str(tmp_path / "sb.train")
    np.savetxt(train_path, np.column_stack([y, X]), delimiter="\t",
               fmt="%.8g")
    rc = cli.run([f"data={train_path}", "objective=binary", "num_trees=2",
                  "num_leaves=7", "is_save_binary_file=true",
                  f"output_model={tmp_path}/m.txt", "device_type=cpu",
                  "verbosity=-1"])
    assert rc == 0
    cache = train_path + ".bin"
    assert os.path.exists(cache)
    ds = lgb.Dataset(cache)
    ds.construct()
    assert ds._handle.num_data == 400


def test_profiler_trace_capture(rng, tmp_path, monkeypatch):
    """LGBM_TPU_PROFILE=<dir> wraps training in a jax.profiler trace and
    leaves a TensorBoard-loadable profile behind (utils/profile.py)."""
    import os

    import lightgbm_tpu as lgb

    trace_dir = str(tmp_path / "prof")
    monkeypatch.setenv("LGBM_TPU_PROFILE", trace_dir)
    X = rng.randn(300, 5)
    y = (X[:, 0] > 0).astype(float)
    ds = lgb.Dataset(X, label=y)
    lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
              ds, num_boost_round=2)
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found.extend(f for f in files if "xplane" in f or f.endswith(".json.gz"))
    assert found, f"no profile artifacts under {trace_dir}"
