"""Linear-tree tests (LinearTreeLearner, src/treelearner/linear_tree_learner.cpp)."""
import numpy as np

import lightgbm_tpu as lgb


def _piecewise_linear(rng, n=3000):
    X = rng.uniform(-2, 2, size=(n, 3))
    # piecewise-linear target: different slope per region of x0
    y = np.where(X[:, 0] > 0, 2.0 * X[:, 1] + 1.0, -1.5 * X[:, 1]) \
        + 0.5 * X[:, 2] + rng.randn(n) * 0.05
    return X, y


def test_linear_tree_beats_constant_leaves(rng):
    X, y = _piecewise_linear(rng)
    base = {"objective": "regression", "num_leaves": 7, "learning_rate": 0.2,
            "min_data_in_leaf": 40, "verbosity": -1}
    plain = lgb.train(base, lgb.Dataset(X, label=y), num_boost_round=20)
    lin = lgb.train({**base, "linear_tree": True, "linear_lambda": 0.01},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    mse_plain = float(np.mean((plain.predict(X) - y) ** 2))
    mse_lin = float(np.mean((lin.predict(X) - y) ** 2))
    assert mse_lin < mse_plain * 0.8, (mse_lin, mse_plain)


def test_linear_tree_model_roundtrip(rng, tmp_path):
    X, y = _piecewise_linear(rng, n=1500)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    pred = bst.predict(X)
    path = str(tmp_path / "linear.txt")
    bst.save_model(path)
    assert "is_linear=1" in open(path).read()
    re_pred = lgb.Booster(model_file=path).predict(X)
    np.testing.assert_allclose(re_pred, pred, rtol=1e-5, atol=1e-7)


def test_linear_tree_nan_fallback(rng):
    """Rows with NaN in a leaf-model feature fall back to the constant
    leaf value (tree.cpp linear prediction path)."""
    X, y = _piecewise_linear(rng, n=1500)
    X[::50, 1] = np.nan  # some NaNs in a model feature
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    pred = bst.predict(X)
    assert np.isfinite(pred).all()


def test_linear_tree_forces_serial(rng):
    X, y = _piecewise_linear(rng, n=800)
    params = {"objective": "regression", "num_leaves": 7,
              "linear_tree": True, "tree_learner": "data", "verbosity": -1}
    bst = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=2)
    assert np.isfinite(bst.predict(X)).all()


def test_linear_tree_l1_fatal(rng):
    import pytest

    X, y = _piecewise_linear(rng, n=500)
    with pytest.raises(Exception):
        lgb.train({"objective": "regression_l1", "linear_tree": True,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=1)


def test_linear_tree_shap_unsupported(rng):
    X, y = _piecewise_linear(rng, n=500)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=2)
    import pytest

    with pytest.raises(ValueError, match="linear"):
        bst.predict(X, pred_contrib=True)
