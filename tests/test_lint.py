"""graftlint self-tests: every rule fires on its fixture, suppressions with
reasons are honored, malformed directives are findings, and the real
package is clean.

The fixture tree under tests/fixtures/graftlint/pkg mimics the package
layout (ops/, treelearner/) so path-scoped rules apply to it unchanged.
"""
import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import run_lint, rule_codes

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "fixtures" / "graftlint" / "pkg"
PACKAGE = REPO / "lightgbm_tpu"


@pytest.fixture(scope="module")
def fixture_result():
    return run_lint(FIXTURES)


def _hits(result, rule, path=None, suppressed=False):
    pool = result.suppressed if suppressed else result.violations
    return [v for v in pool
            if v.rule == rule and (path is None or v.path == path)]


# -- R1 jit-boundary hygiene ---------------------------------------------

def test_r1_detects_host_syncs(fixture_result):
    lines = {v.line for v in _hits(fixture_result, "jit-host-sync",
                                   "ops/r1_jit.py")}
    assert lines == {9, 15, 16}  # int(tracer), .item(), np.asarray


def test_r1_static_and_unreachable_are_clean(fixture_result):
    # int(x.shape[0]) (line 17) and the non-jit-reachable int(x) (line 24)
    # must not fire
    lines = {v.line for v in _hits(fixture_result, "jit-host-sync")}
    assert 17 not in lines and 24 not in lines


def test_r1_suppression_honored(fixture_result):
    sup = _hits(fixture_result, "jit-host-sync", "ops/r1_jit.py",
                suppressed=True)
    assert [v.line for v in sup] == [19]
    assert "host-side by contract" in sup[0].reason


def test_r1_loop_sync_on_fresh_dispatch(fixture_result):
    # np.asarray(predict_block(x)) per loop iteration — the pre-rewrite
    # predict_raw_early_stop shape — must fire with the pipeline message
    bad = _hits(fixture_result, "jit-host-sync", "ops/r1_stream.py")
    assert [v.line for v in bad] == [18]
    assert "serializes the dispatch pipeline" in bad[0].message


def test_r1_loop_sync_buffered_and_suppressed(fixture_result):
    # pulling a PREVIOUSLY dispatched value (bare name, double-buffer
    # drain) is clean; the reasoned suppression is honored
    sup = _hits(fixture_result, "jit-host-sync", "ops/r1_stream.py",
                suppressed=True)
    assert [v.line for v in sup] == [36]
    assert "tiny scalar pull" in sup[0].reason


# -- R2 dtype discipline --------------------------------------------------

def test_r2_detects_implicit_dtype(fixture_result):
    lines = {v.line for v in _hits(fixture_result, "implicit-dtype",
                                   "ops/r2_dtype.py")}
    assert lines == {6, 7}  # bare zeros + arange


def test_r2_explicit_and_like_are_clean(fixture_result):
    # dtype kwarg (8), positional dtype slot (9), zeros_like (10)
    lines = {v.line for v in _hits(fixture_result, "implicit-dtype")}
    assert not lines & {8, 9, 10}


def test_r2_family_code_suppression(fixture_result):
    sup = _hits(fixture_result, "implicit-dtype", "ops/r2_dtype.py",
                suppressed=True)
    assert [v.line for v in sup] == [11]  # disable=R2 covers the rule


# -- R3 Pallas kernel rules -----------------------------------------------

def test_r3_tile_shape_resolves_module_constants(fixture_result):
    msgs = [v.message for v in _hits(fixture_result, "pallas-tile-shape",
                                     "ops/r3_pallas.py")]
    # TILE = 100 resolved symbolically -> both sublane and lane misaligned
    assert len(msgs) == 2
    assert any("multiple of 128" in m for m in msgs)
    assert any("multiple of 8" in m for m in msgs)


def test_r3_prefetch_arity(fixture_result):
    bad = _hits(fixture_result, "pallas-prefetch-arity", "ops/r3_pallas.py")
    assert len(bad) == 1 and "takes 2 args, expected 1" in bad[0].message
    sup = _hits(fixture_result, "pallas-prefetch-arity", "ops/r3_pallas.py",
                suppressed=True)
    # num_scalar_prefetch=1 shifts the expected arity; disable=R3 covers it
    assert len(sup) == 1 and "expected 2" in sup[0].message


def test_r3_host_op_in_kernel(fixture_result):
    bad = _hits(fixture_result, "pallas-host-op", "ops/r3_pallas.py")
    assert [v.line for v in bad] == [11]  # np.asarray in kernel body
    sup = _hits(fixture_result, "pallas-host-op", "ops/r3_pallas.py",
                suppressed=True)
    assert [v.line for v in sup] == [13]  # suppressed print()


# -- R4 param-spec consistency --------------------------------------------

def test_r4_unread_param_detected(fixture_result):
    bad = _hits(fixture_result, "param-unread", "_param_spec.py")
    assert len(bad) == 1 and "'ghost_param'" in bad[0].message


def test_r4_read_param_clean_and_suppression_honored(fixture_result):
    all_msgs = [v.message for v in
                fixture_result.violations + fixture_result.suppressed]
    assert not any("'used_param'" in m for m in all_msgs)
    sup = _hits(fixture_result, "param-unread", suppressed=True)
    assert len(sup) == 1 and "'surface_param'" in sup[0].message


# -- R5 timer discipline --------------------------------------------------

def test_r5_untimed_long_function(fixture_result):
    bad = _hits(fixture_result, "untimed-hot-func", "treelearner/r5_big.py")
    assert len(bad) == 1 and "'big_untimed'" in bad[0].message


def test_r5_timed_and_jitted_exempt(fixture_result):
    msgs = [v.message for v in
            fixture_result.violations + fixture_result.suppressed]
    assert not any("'big_timed'" in m for m in msgs)
    assert not any("'big_jitted'" in m for m in msgs)


def test_r5_scope_covers_serving_hot_path(fixture_result):
    # ops/predict.py joined the R5 scope (scope_exact): the untimed pack
    # helper fixture must fire there too
    bad = _hits(fixture_result, "untimed-hot-func", "ops/predict.py")
    assert len(bad) == 1 and "'big_untimed_pack'" in bad[0].message


def test_r5_scope_covers_fused_scan(fixture_result):
    # ops/scan_pallas.py joined the R5 scope (scope_exact, round 8): the
    # untimed staging helper fires at its def line; the jitted dispatch
    # stays exempt (the call site owns the scope, device.py's
    # "tree_device")
    bad = _hits(fixture_result, "untimed-hot-func", "ops/scan_pallas.py")
    assert len(bad) == 1 and "'big_untimed_stage'" in bad[0].message
    assert bad[0].line == 7
    msgs = [v.message for v in
            fixture_result.violations + fixture_result.suppressed]
    assert not any("'big_jitted_scan'" in m for m in msgs)


def test_r5_suppression_honored(fixture_result):
    sup = _hits(fixture_result, "untimed-hot-func", suppressed=True)
    assert len(sup) == 1 and "'big_suppressed'" in sup[0].message


# -- R6 donation discipline -----------------------------------------------

def test_r6_undonated_jit_entry_detected(fixture_result):
    bad = _hits(fixture_result, "jit-donation", "treelearner/r6_donate.py")
    assert len(bad) == 1 and "'undonated'" in bad[0].message
    assert bad[0].line == 8  # anchored at the decorator, not the def


def test_r6_donated_scalar_and_unjitted_are_clean(fixture_result):
    msgs = [v.message for v in
            fixture_result.violations + fixture_result.suppressed]
    for name in ("'donated'", "'scalar_only'", "'not_jitted'"):
        assert not any(name in m and "donate" in m for m in msgs), name


def test_r6_suppression_honored(fixture_result):
    sup = _hits(fixture_result, "jit-donation", "treelearner/r6_donate.py",
                suppressed=True)
    assert len(sup) == 1 and "'suppressed'" in sup[0].message
    assert "reused across iterations" in sup[0].reason


# -- R7 collective axis binding -------------------------------------------

def test_r7_unbound_collectives_detected(fixture_result):
    bad = _hits(fixture_result, "collective-axis", "parallel/r7_axis.py")
    msgs = {v.line: v.message for v in bad}
    assert set(msgs) == {22, 26, 30, 34}
    assert "'batch'" in msgs[22]       # axis not bound anywhere
    assert "no shard_map" in msgs[26]  # function never wrapped
    assert "not a string literal" in msgs[30]
    assert "without an axis name" in msgs[34]


def test_r7_wrapped_chain_and_nested_are_clean(fixture_result):
    # psum/psum_scatter reached from shard_map-wrapped fns (directly, via a
    # module call edge, and from a nested def) must not fire
    lines = {v.line for v in
             _hits(fixture_result, "collective-axis", "parallel/r7_axis.py")}
    assert not lines & {8, 12, 44}


def test_r7_suppression_honored(fixture_result):
    sup = _hits(fixture_result, "collective-axis", suppressed=True)
    assert len(sup) == 1 and "bound by the caller's shard_map" in sup[0].reason


# -- R8 atomic-write discipline -------------------------------------------

def test_r8_bare_write_opens_detected(fixture_result):
    bad = _hits(fixture_result, "non-atomic-write", "models/r8_write.py")
    assert [v.line for v in bad] == [5, 10]  # positional + mode= keyword
    assert all("atomic" in v.message for v in bad)


def test_r8_reads_and_dynamic_modes_are_clean(fixture_result):
    lines = {v.line for v in
             _hits(fixture_result, "non-atomic-write", "models/r8_write.py")
             + _hits(fixture_result, "non-atomic-write", "models/r8_write.py",
                     suppressed=True)}
    assert not lines & {15, 20, 25}


def test_r8_suppression_honored(fixture_result):
    sup = _hits(fixture_result, "non-atomic-write", suppressed=True)
    assert len(sup) == 1 and "scratch debug dump" in sup[0].reason


# -- R9 telemetry hygiene -------------------------------------------------

def test_r9_unguarded_emit_detected(fixture_result):
    bad = _hits(fixture_result, "telemetry-hygiene",
                "treelearner/r9_telemetry.py")
    assert [v.line for v in bad] == [7]
    assert "enabled" in bad[0].message


def test_r9_guards_counters_and_foreign_emit_are_clean(fixture_result):
    lines = {v.line for v in
             _hits(fixture_result, "telemetry-hygiene")
             + _hits(fixture_result, "telemetry-hygiene", suppressed=True)}
    # if-guard (13), ternary guard (18), counter API (23), handler.emit (27)
    assert not lines & {13, 18, 23, 27}


def test_r9_suppression_honored(fixture_result):
    sup = _hits(fixture_result, "telemetry-hygiene", suppressed=True)
    assert len(sup) == 1 and "cold error path" in sup[0].reason


def test_r9_tracing_scope_exact(fixture_result):
    # tracing.py is in scope_exact: an unguarded telemetry.emit there
    # fires even though the file sits outside the scoped directories
    bad = _hits(fixture_result, "telemetry-hygiene", "tracing.py")
    assert [v.line for v in bad] == [12]


def test_r9_recorder_append_is_sanctioned(fixture_result):
    # the flight-recorder ring append (note()) and the cold dump path's
    # foreign sink.emit must NOT trip R9 — only telemetry.emit needs a
    # guard; the guarded emit (line 18) is clean too
    lines = {v.line for v in
             _hits(fixture_result, "telemetry-hygiene", "tracing.py")
             + _hits(fixture_result, "telemetry-hygiene", "tracing.py",
                     suppressed=True)}
    assert not lines & {18, 25, 26, 32}


# -- streaming/ scope (R1/R6/R9/R10 cover the out-of-core engine) ---------

def test_streaming_scope_r1_and_r6(fixture_result):
    r6 = _hits(fixture_result, "jit-donation", "streaming/r_stream.py")
    assert [v.line for v in r6] == [10]
    assert "'block_hist'" in r6[0].message
    r1 = _hits(fixture_result, "jit-host-sync", "streaming/r_stream.py")
    assert [v.line for v in r1] == [12]


def test_streaming_scope_r9_and_r10(fixture_result):
    r10 = _hits(fixture_result, "use-after-donation",
                "streaming/r_stream.py")
    assert [v.line for v in r10] == [23]
    assert "'acc'" in r10[0].message
    r9 = _hits(fixture_result, "telemetry-hygiene", "streaming/r_stream.py")
    assert [v.line for v in r9] == [24, 43]


def test_streaming_clean_and_suppressed(fixture_result):
    # donated accum (17), rebound-name read (29), guarded emits (31, 50): clean
    lines = {v.line for v in
             fixture_result.violations + fixture_result.suppressed
             if v.path == "streaming/r_stream.py"}
    assert not lines & {17, 29, 31, 50}
    sup = _hits(fixture_result, "jit-donation", "streaming/r_stream.py",
                suppressed=True)
    assert len(sup) == 1 and "reused across leaves" in sup[0].reason


# -- parallel/elastic.py scope (R1 beat path + R9 watchdog emits) ---------

def test_elastic_scope_r9_watchdog_emit(fixture_result):
    # the watchdog fire path builds a worker_lost payload: unguarded emit
    # fires, the enabled-guarded twin stays clean
    r9 = _hits(fixture_result, "telemetry-hygiene", "parallel/elastic.py")
    assert [v.line for v in r9] == [15]


def test_elastic_scope_r1_per_iteration_heartbeat(fixture_result):
    # a heartbeat that pulls the token every iteration is exactly the
    # hot-path host sync the elastic runtime must NOT reintroduce
    r1 = _hits(fixture_result, "jit-host-sync", "parallel/elastic.py")
    assert [v.line for v in r1] == [21]
    assert "serializes the dispatch pipeline" in r1[0].message


def test_elastic_scope_windowed_pull_suppressed(fixture_result):
    # the sanctioned shape — one pull per health window — carries its
    # reasoned escape hatch; nothing else in the file may be suppressed
    sup = [v for v in fixture_result.suppressed
           if v.path == "parallel/elastic.py"]
    assert [(v.rule, v.line) for v in sup] == [("jit-host-sync", 30)]
    assert "health window" in sup[0].reason


# -- S1 directive hygiene -------------------------------------------------

def test_s1_bad_directives_are_findings(fixture_result):
    bad = _hits(fixture_result, "bad-suppression", "s1_bad.py")
    msgs = {v.line: v.message for v in bad}
    assert "without a reason" in msgs[2]
    assert "not-a-rule" in msgs[3]
    assert "unparseable" in msgs[4]


def test_s1_is_never_suppressible():
    # a reasoned disable=S1 on the same line must NOT silence the finding
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = Path(d) / "x.py"
        p.write_text("A = 1  # graftlint: disable=implicit-dtype\n")
        res = run_lint(p)
        assert [v.rule for v in res.violations] == ["bad-suppression"]


# -- driver behavior ------------------------------------------------------

def test_select_filters_rules(fixture_result):
    res = run_lint(FIXTURES, select=["R2"])
    rules = {v.rule for v in res.violations}
    # directive errors always surface; otherwise only the selected rule
    assert rules <= {"implicit-dtype", "bad-suppression"}
    assert "implicit-dtype" in rules


def test_ignore_filters_rules():
    res = run_lint(FIXTURES, ignore=["param-unread"])
    assert not any(v.rule == "param-unread" for v in res.violations)


def test_rule_codes_cover_names_and_codes():
    table = rule_codes()
    for ident in ("R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8", "R9",
                  "R10", "R11",
                  "jit-donation", "jit-host-sync", "jit-host-sync-xmod",
                  "implicit-dtype", "pallas-tile-shape",
                  "pallas-prefetch-arity", "pallas-host-op",
                  "param-unread", "untimed-hot-func", "collective-axis",
                  "non-atomic-write", "telemetry-hygiene",
                  "use-after-donation", "collective-context"):
        assert ident in table
    # two rules share the R1 code; the code must keep resolving to the
    # ORIGINAL local rule, with the family expansion covering both
    assert table["R1"] == "jit-host-sync"


def test_code_family_expansion_covers_both_r1_rules():
    from tools.graftlint.rules import code_families

    fams = code_families()
    assert {"jit-host-sync", "jit-host-sync-xmod"} <= set(fams["R1"])
    # selecting by code runs the whole family; ignoring by code drops it
    both = run_lint(FIXTURES, select=["R1"])
    assert any(v.rule == "jit-host-sync" for v in both.violations)
    none = run_lint(FIXTURES, ignore=["R1"])
    assert not any(v.rule.startswith("jit-host-sync")
                   for v in none.violations)


# -- the gate: the real package is clean ----------------------------------

def test_package_has_zero_unsuppressed_violations():
    res = run_lint(PACKAGE)
    assert res.ok, "\n" + res.render()


def test_every_package_suppression_carries_a_reason():
    res = run_lint(PACKAGE)
    assert all(v.reason for v in res.suppressed)


def test_cli_exit_codes():
    clean = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "lightgbm_tpu"],
        cwd=REPO, capture_output=True, text=True)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    dirty = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", str(FIXTURES)],
        cwd=REPO, capture_output=True, text=True)
    assert dirty.returncode == 1
    usage = subprocess.run(
        [sys.executable, "-m", "tools.graftlint", "--select", "no-such-rule",
         "lightgbm_tpu"],
        cwd=REPO, capture_output=True, text=True)
    assert usage.returncode == 2
