"""graftlint v3 (R12/R13/R14) against the planted SPMD fixture package
(tests/fixtures/graftlint/spmdpkg): every planted defect — divergent
collective arms, a rank-local-bound loop, an inconsistent axis entry, a
lock-order cycle, dispatch/IO under a lock, a VMEM-overflowing
pallas_call — is caught at its exact line, the adjacent compliant shapes
stay quiet, and the reasoned suppressions are honored. Plus the
--changed-only scoping mode and the hardened cache config key.
"""
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import run_lint
from tools.graftlint.cache import CacheStore
from tools.graftlint.core import collect
from tools.graftlint.rules import RULES

REPO = Path(__file__).resolve().parent.parent
SPMD = REPO / "tests" / "fixtures" / "graftlint" / "spmdpkg"
ACTIVE = sorted(r.name for r in RULES)


@pytest.fixture(scope="module")
def result():
    return run_lint(SPMD)


def _hits(result, rule, path=None, suppressed=False):
    pool = result.suppressed if suppressed else result.violations
    return [v for v in pool
            if v.rule == rule and (path is None or v.path == path)]


# -- R12(a) rank-dependent branch divergence ------------------------------

def test_r12_rank_gated_arms_flagged(result):
    bad = _hits(result, "collective-order", "parallel/divergent.py")
    assert [v.line for v in bad] == [15, 21]
    assert "[psum@data] vs []" in bad[0].message
    assert "deadlock the mesh" in bad[0].message
    # early_return_gate: the implicit else is the rest of the block
    assert "[] vs [psum@data]" in bad[1].message


def test_r12_uniform_arms_stay_quiet(result):
    # uniform_gate posts the same sequence on both arms (line 26)
    lines = {v.line for v in _hits(result, "collective-order")}
    assert 26 not in lines


def test_r12_sanctioned_suppression_honored(result):
    sup = _hits(result, "collective-order", "parallel/divergent.py",
                suppressed=True)
    assert [v.line for v in sup] == [48]
    assert "uniform across the gang" in sup[0].reason


# -- R12 over the streaming/ scope (the sharded-ingest sketch merge) ------

def test_r12_streaming_sketch_merge_plant_flagged(result):
    bad = _hits(result, "collective-order", "streaming/sharded_ingest.py")
    assert [v.line for v in bad] == [14]
    assert "[all_gather@data] vs []" in bad[0].message


def test_r12_streaming_uniform_merge_quiet_and_fallback_suppressed(result):
    # every_rank_merge posts the merge unconditionally (line 20): quiet —
    # the plant at 14 is the module's only live finding
    lines = {v.line for v in _hits(result, "collective-order",
                                   "streaming/sharded_ingest.py")}
    assert lines == {14}
    sup = _hits(result, "collective-order", "streaming/sharded_ingest.py",
                suppressed=True)
    assert [v.line for v in sup] == [25]
    assert "uniform across the gang" in sup[0].reason


# -- R12(b) rank-local loop trip counts -----------------------------------

def test_r12_rank_local_loop_flagged(result):
    bad = _hits(result, "collective-rank-loop", "parallel/divergent.py")
    assert [v.line for v in bad] == [34]
    assert "psum@data" in bad[0].message
    assert "rank-local data" in bad[0].message


def test_r12_global_trip_count_stays_quiet(result):
    # padded_reduce loops over a plain argument (line 41)
    assert not [v for v in _hits(result, "collective-rank-loop")
                if v.line == 41]


# -- R12(c) inconsistent axis bindings across entries ---------------------

def test_r12_axis_entry_divergence_flagged(result):
    bad = _hits(result, "collective-axis-entry", "parallel/entries.py")
    assert [v.line for v in bad] == [23]
    assert "binding only ['model']" in bad[0].message
    assert "uses axis ['data']" in bad[0].message


def test_r12_covering_entry_stays_quiet(result):
    # enter_data binds 'data' (lines 18-19): not an entry finding
    lines = {v.line for v in _hits(result, "collective-axis-entry")}
    assert not lines & {18, 19}


# -- the round-9 voting-learner collective shapes (R7/R11/R12) ------------

def test_voting_unbound_nomination_gather_flagged(result):
    # skewed_gather posts the nomination all_gather over an axis no
    # shard_map in the module binds
    bad = _hits(result, "collective-axis", "parallel/voting.py")
    assert [v.line for v in bad] == [40]
    assert "all_gather over axis 'vote'" in bad[0].message


def test_voting_unbound_context_paths_flagged(result):
    # two R11 paths to the elected-slice collectives: the jitted rescan
    # (no mesh context at its jit boundary) and the skewed gather root
    bad = _hits(result, "collective-context", "parallel/voting.py")
    assert sorted(v.line for v in bad) == [32, 39]
    by_line = {v.line: v.message for v in bad}
    assert "jit boundary" in by_line[32]
    assert "axis 'data'" in by_line[32]
    assert "entry point" in by_line[39]
    assert "axis 'vote'" in by_line[39]


def test_voting_overlap_dispatch_divergence_flagged(result):
    # overlap_dispatch posts the elected psum on rank 0 only
    bad = _hits(result, "collective-order", "parallel/voting.py")
    assert [v.line for v in bad] == [44]
    assert "[psum@data] vs []" in bad[0].message


def test_voting_wrapped_waves_stay_quiet(result):
    # vote_wave / overlap_wave / commit_wave bind 'data' via shard_map:
    # nothing beyond the three planted shapes fires in the module
    lines = {(v.rule, v.line) for v in result.violations
             if v.path == "parallel/voting.py"}
    assert lines == {("collective-axis", 40), ("collective-context", 32),
                     ("collective-context", 39), ("collective-order", 44)}


# -- R13 blocking work under a held lock ----------------------------------

def test_r13_blocking_under_lock_flagged(result):
    bad = _hits(result, "lock-discipline", "serving/locks.py")
    assert [v.line for v in bad] == [30, 34, 39, 65]
    assert "jitted dispatch _dev_double" in bad[0].message
    assert "file I/O (open)" in bad[1].message
    # the sleep lives two frames away: the finding names the chain
    assert "time.sleep at serving/locks.py:19" in bad[2].message
    # wire-protocol plant: np.frombuffer over a blocking stream read holds
    # the batcher lock for the peer's send pace
    assert "np.frombuffer decodes a blocking stream read" in bad[3].message
    assert ".read" in bad[3].message


def test_r13_pending_record_idiom_stays_quiet(result):
    # good_pending writes its file AFTER releasing the lock (line 54);
    # good_pending_decode drains the stream pre-lock and decodes after
    # release (line 71)
    lines = {v.line for v in _hits(result, "lock-discipline")}
    assert 54 not in lines
    assert 71 not in lines and 68 not in lines


def test_r13_suppression_honored(result):
    sup = _hits(result, "lock-discipline", "serving/locks.py",
                suppressed=True)
    assert [v.line for v in sup] == [61]
    assert "startup-only" in sup[0].reason


# -- R13 acquisition-order cycles -----------------------------------------

def test_r13_lock_order_cycle_both_directions(result):
    bad = _hits(result, "lock-order-cycle", "serving/locks.py")
    assert sorted(v.line for v in bad) == [43, 48]
    assert all("acquisition-order cycle" in v.message for v in bad)
    assert all("PlantedServer._lock" in v.message
               and "PlantedServer._aux" in v.message for v in bad)


# -- R14 Pallas VMEM budget -----------------------------------------------

def test_r14_oversized_blocks_flagged(result):
    bad = _hits(result, "pallas-vmem", "ops/vmem_kernels.py")
    assert [v.line for v in bad] == [20]
    assert "1024.0 MiB" in bad[0].message
    assert "16.0 MiB" in bad[0].message


def test_r14_tiled_kernel_fits(result):
    # tiled_copy (line 30) stays under the floor
    assert len(_hits(result, "pallas-vmem")) == 1


def test_r14_perfmodel_budget_is_read_from_the_linted_root(tmp_path):
    root = tmp_path / "spmdpkg"
    shutil.copytree(SPMD, root)
    (root / "perfmodel.py").write_text(
        "PALLAS_VMEM_DEFAULT_BYTES = 2 * 1024 * 1024 * 1024\n")
    relaxed = run_lint(root)
    assert not _hits(relaxed, "pallas-vmem")


# -- the production tree stays clean --------------------------------------

def test_product_package_clean_under_v3():
    res = run_lint(REPO / "lightgbm_tpu",
                   select=["collective-order", "collective-rank-loop",
                           "collective-axis-entry", "lock-discipline",
                           "lock-order-cycle", "pallas-vmem"])
    assert res.violations == []
    # the sanctioned R12 suppression (elastic heartbeat) is present
    assert any(v.path == "parallel/elastic.py"
               and v.rule == "collective-order"
               for v in res.suppressed)


# -- changed-only scoping -------------------------------------------------

def test_changed_only_restricts_local_rules():
    # pallas-vmem is file-local: changing only parallel/ must drop it,
    # while the whole-program R12 findings still run (affected non-empty)
    res = run_lint(SPMD, changed_only=["parallel/divergent.py"])
    assert not _hits(res, "pallas-vmem")
    assert [v.line for v in _hits(res, "collective-order",
                                  "parallel/divergent.py")] == [15, 21]


def test_changed_only_follows_reverse_imports():
    # entries.py imports divergent.py: changing divergent affects entries,
    # so entries' file-local findings reappear — but serving/ stays out
    res = run_lint(SPMD, changed_only=["parallel/divergent.py"])
    full = run_lint(SPMD)
    wanted = {(v.rule, v.path, v.line) for v in full.violations
              if v.path.startswith("parallel/")}
    got = {(v.rule, v.path, v.line) for v in res.violations
           if v.path.startswith("parallel/")}
    assert wanted == got


def test_changed_only_empty_set_runs_nothing():
    res = run_lint(SPMD, changed_only=[])
    assert res.violations == [] and res.suppressed == []


def test_changed_only_cli_against_git(tmp_path):
    shutil.copytree(SPMD, tmp_path / "spmdpkg")
    env = {"PYTHONPATH": str(REPO), "HOME": str(tmp_path),
           "PATH": "/usr/bin:/bin:/usr/local/bin"}

    def git(*args):
        subprocess.run(("git", "-c", "user.email=t@t", "-c", "user.name=t")
                       + args, cwd=tmp_path, check=True,
                       capture_output=True, env=env)

    git("init", "-q")
    git("add", ".")
    git("commit", "-q", "-m", "seed")

    cmd = [sys.executable, "-m", "tools.graftlint", "spmdpkg",
           "--changed-only"]
    clean = subprocess.run(cmd, cwd=tmp_path, capture_output=True,
                           text=True, env=env)
    assert clean.returncode == 0  # nothing changed -> nothing linted
    assert "0 violation(s)" in clean.stdout

    kernels = tmp_path / "spmdpkg" / "ops" / "vmem_kernels.py"
    kernels.write_text(kernels.read_text() + "\n# touched\n")
    touched = subprocess.run(cmd, cwd=tmp_path, capture_output=True,
                             text=True, env=env)
    assert touched.returncode == 1
    assert "ops/vmem_kernels.py:20" in touched.stdout
    # file-local findings from untouched files are excluded...
    assert "[collective-axis]" not in touched.stdout
    # ...but whole-program rules still run over the full package
    assert "[collective-order]" in touched.stdout


# -- cache config key -----------------------------------------------------

def test_cache_key_includes_format_component(tmp_path):
    root = tmp_path / "spmdpkg"
    shutil.copytree(SPMD, root)
    cache_dir = tmp_path / "cache"
    run_lint(root, cache=CacheStore(root, cache_dir=cache_dir),
             cache_key_extra="fmt=text")
    store = CacheStore(root, cache_dir=cache_dir)
    hit = store.plan(collect(root), ACTIVE, "fmt=text")
    assert hit[2] is not None  # whole-program served
    miss = store.plan(collect(root), ACTIVE, "fmt=sarif")
    assert miss[2] is None and len(miss[1]) == len(collect(root).files)


def test_cache_key_uses_canonical_rule_set(tmp_path):
    # --select R12 and --select by-name spell the same active set: the
    # canonical key makes them share one cache entry
    root = tmp_path / "spmdpkg"
    shutil.copytree(SPMD, root)
    cache_dir = tmp_path / "cache"
    by_code = run_lint(root, select=["R12"],
                       cache=CacheStore(root, cache_dir=cache_dir))
    by_name = run_lint(root,
                       select=["collective-order", "collective-rank-loop",
                               "collective-axis-entry"],
                       cache=CacheStore(root, cache_dir=cache_dir))
    assert [v.render() for v in by_name.violations] == \
           [v.render() for v in by_code.violations]
    store = CacheStore(root, cache_dir=cache_dir)
    active = sorted(["collective-order"])
    hit = store.plan(collect(root), active)
    assert hit[2] is not None


def test_cache_invalidated_by_perfmodel_edit(tmp_path):
    root = tmp_path / "spmdpkg"
    shutil.copytree(SPMD, root)
    (root / "perfmodel.py").write_text("PALLAS_VMEM_DEFAULT_BYTES = 2**24\n")
    cache_dir = tmp_path / "cache"
    run_lint(root, cache=CacheStore(root, cache_dir=cache_dir))
    # editing the R14 config tables must invalidate everything, even
    # though perfmodel.py is outside the linter's own source tree
    (root / "perfmodel.py").write_text("PALLAS_VMEM_DEFAULT_BYTES = 2**25\n")
    store = CacheStore(root, cache_dir=cache_dir)
    cached, invalid, wp = store.plan(collect(root), ACTIVE)
    assert wp is None
