"""graftlint v2 whole-program analysis: the interprocedural fixture
package (tests/fixtures/graftlint/xpkg) exercises the call graph —
import cycles, partial-wrapped jit, method dispatch — and the three
cross-module rules; plus the incremental cache and SARIF export.

The headline property fixtures assert: every v2 finding is INVISIBLE to
the module-local v1 pass (run with select=jit-host-sync the package is
clean) and caught by the whole-program pass at an exact line.
"""
import json
import shutil
import subprocess
import sys
from pathlib import Path

import pytest

from tools.graftlint import run_lint
from tools.graftlint.cache import CacheStore
from tools.graftlint.callgraph import get_callgraph, import_deps
from tools.graftlint.core import collect
from tools.graftlint.rules import RULES
from tools.graftlint.sarif import to_sarif

REPO = Path(__file__).resolve().parent.parent
XPKG = REPO / "tests" / "fixtures" / "graftlint" / "xpkg"
# the cache key holds the canonical ACTIVE rule set; a default run_lint
# activates every registered rule
ACTIVE = sorted(r.name for r in RULES)


@pytest.fixture(scope="module")
def result():
    return run_lint(XPKG)


def _hits(result, rule, path=None, suppressed=False):
    pool = result.suppressed if suppressed else result.violations
    return [v for v in pool
            if v.rule == rule and (path is None or v.path == path)]


# -- R1v2 cross-module sync escape ---------------------------------------

def test_v1_alone_is_blind_to_every_xpkg_finding():
    # the entire fixture package is CLEAN under the module-local pass:
    # every defect needs the call graph to see
    v1 = run_lint(XPKG, select=["jit-host-sync"])
    assert v1.violations == []


def test_xmod_sync_through_import_cycle(result):
    bad = _hits(result, "jit-host-sync-xmod", "treelearner/stats.py")
    assert [v.line for v in bad] == [9]
    assert "jit-reachable via ops/kernels.py:17" in bad[0].message


def test_xmod_suppression_honored(result):
    sup = _hits(result, "jit-host-sync-xmod", "treelearner/stats.py",
                suppressed=True)
    assert [v.line for v in sup] == [15]
    assert "calibration contract" in sup[0].reason


def test_unreachable_helper_stays_quiet(result):
    # offline_summary's syncs are not jit-reachable from anywhere
    lines = {v.line for v in _hits(result, "jit-host-sync-xmod",
                                   "treelearner/stats.py")}
    assert 21 not in lines


def test_hot_dispatch_hook_flagged(result):
    bad = _hits(result, "jit-host-sync-xmod", "telemetry.py")
    assert [v.line for v in bad] == [8]
    assert "hot dispatch path" in bad[0].message
    assert "models/driver.py:11" in bad[0].message  # the loop that reaches it


# -- R10 use-after-donation ----------------------------------------------

def test_r10_flags_every_donation_shape(result):
    lines = {v.line for v in _hits(result, "use-after-donation",
                                   "treelearner/donate.py")}
    # direct, loop-carried, jit alias, partial shift, method summary,
    # pallas literal input_output_aliases
    assert lines == {17, 35, 54, 67, 78, 88}


def test_r10_compliant_idioms_clean(result):
    lines = {v.line for v in _hits(result, "use-after-donation",
                                   "treelearner/donate.py")}
    # direct_ok (fresh jnp.copy donated) and rebound_ok (donate-and-
    # replace: `buf = consume(buf, ...)`) must not fire
    assert not lines & set(range(21, 29))


def test_r10_suppression_honored(result):
    sup = _hits(result, "use-after-donation", "treelearner/donate.py",
                suppressed=True)
    assert [v.line for v in sup] == [42]
    assert "pinned a host copy" in sup[0].reason


# -- R11 collective-context ----------------------------------------------

def test_r11_unbound_jit_entry_flagged(result):
    bad = _hits(result, "collective-context", "treelearner/steps.py")
    assert [v.line for v in bad] == [18]
    assert "axis 'data'" in bad[0].message
    assert "treelearner/steps.py:15" in bad[0].message  # witness collective


def test_r11_cross_module_shard_map_binds(result):
    # grow_step itself is never flagged: parallel/shard.py's wrap binds
    # 'data' on that path, and the R7 suppression carries the rationale
    assert len(_hits(result, "collective-context")) == 1
    sup = _hits(result, "collective-axis", "treelearner/steps.py",
                suppressed=True)
    assert [v.line for v in sup] == [15]


def test_r11_suppression_honored(result):
    sup = _hits(result, "collective-context", "treelearner/steps.py",
                suppressed=True)
    assert [v.line for v in sup] == [24]


# -- the call graph itself -----------------------------------------------

def test_import_cycle_resolves_both_directions():
    pkg = collect(XPKG)
    deps = import_deps(pkg)
    assert "treelearner/stats.py" in deps["ops/kernels.py"]
    assert "ops/kernels.py" in deps["treelearner/stats.py"]


def test_partial_wrapped_jit_donation_survives_unwrap():
    pkg = collect(XPKG)
    g = get_callgraph(pkg)
    # decorator form: @partial(jax.jit, donate_argnums=(0,))
    consume = g.nodes["ops.kernels:consume"]
    assert consume.jitted and consume.donate == (0,)
    # alias form shifted through functools.partial: the call edge from
    # partial_bad carries offset 1 into axpy's donate_argnums=(1,)
    edges = [e for e in g.nodes["treelearner.donate:partial_bad"].edges
             if e.target == "treelearner.donate:axpy"]
    assert edges and edges[0].offset == 1


def test_method_dispatch_resolved():
    pkg = collect(XPKG)
    g = get_callgraph(pkg)
    edges = g.nodes["treelearner.donate:Learner.run_bad"].edges
    assert any(e.target == "treelearner.donate:Learner._dispatch"
               for e in edges)


# -- incremental cache ----------------------------------------------------

def _copy_xpkg(tmp_path):
    root = tmp_path / "xpkg"
    shutil.copytree(XPKG, root)
    return root


def test_cache_full_hit_reproduces_results(tmp_path):
    root = _copy_xpkg(tmp_path)
    cache_dir = tmp_path / "cache"
    cold = run_lint(root, cache=CacheStore(root, cache_dir=cache_dir))
    warm = run_lint(root, cache=CacheStore(root, cache_dir=cache_dir))
    assert [v.render() for v in warm.violations] == \
           [v.render() for v in cold.violations]
    assert [v.render() for v in warm.suppressed] == \
           [v.render() for v in cold.suppressed]
    # an unchanged tree is a full hit: nothing invalid, whole-program
    # findings served from cache
    cached, invalid, wp = CacheStore(root, cache_dir=cache_dir).plan(
        collect(root), ACTIVE)
    assert not invalid
    assert wp is not None


def test_cache_cross_file_invalidation(tmp_path):
    """Editing ops/kernels.py must invalidate treelearner/stats.py's
    entry (stats imports kernels) AND rerun the whole-program pass: the
    stats.py finding exists only because kernels jits the call path."""
    root = _copy_xpkg(tmp_path)
    cache_dir = tmp_path / "cache"
    cold = run_lint(root, cache=CacheStore(root, cache_dir=cache_dir))
    assert any(v.path == "treelearner/stats.py" and v.line == 9
               for v in cold.violations)
    kernels = root / "ops" / "kernels.py"
    kernels.write_text(kernels.read_text().replace(
        "@jax.jit\ndef scale", "def scale"))
    cached, invalid, wp = CacheStore(root, cache_dir=cache_dir).plan(
        collect(root), ACTIVE)
    assert wp is None  # a changed tree can't serve whole-program findings
    assert "ops/kernels.py" in invalid
    assert "treelearner/stats.py" in invalid  # reverse dependency
    assert "parallel/shard.py" not in invalid  # doesn't import kernels
    after = run_lint(root, cache=CacheStore(root, cache_dir=cache_dir))
    # scale() is no longer a jit seed, so normalize's sync is unreachable
    assert not any(v.path == "treelearner/stats.py" and v.line == 9
                   for v in after.violations)


def test_cache_invalidated_by_rules_digest(tmp_path, monkeypatch):
    root = _copy_xpkg(tmp_path)
    cache_dir = tmp_path / "cache"
    run_lint(root, cache=CacheStore(root, cache_dir=cache_dir))
    store = CacheStore(root, cache_dir=cache_dir)
    monkeypatch.setattr(store, "_rules_digest", "different")
    cached, invalid, wp = store.plan(collect(root), ACTIVE)
    assert wp is None and len(invalid) == len(collect(root).files)


# -- SARIF ---------------------------------------------------------------

def test_sarif_document_shape(result):
    doc = to_sarif(result.violations, result.suppressed)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert {"jit-host-sync-xmod", "use-after-donation",
            "collective-context", "jit-host-sync"} <= ids
    results = run["results"]
    assert len(results) == len(result.violations) + len(result.suppressed)
    sup = [r for r in results if r.get("suppressions")]
    assert len(sup) == len(result.suppressed)
    for s in sup:
        assert s["suppressions"][0]["kind"] == "inSource"
        assert s["suppressions"][0]["justification"]
        assert s["level"] == "note"


def test_sarif_columns_are_one_based(result):
    v = next(v for v in result.violations
             if v.path == "treelearner/stats.py" and v.line == 9)
    doc = to_sarif([v])
    region = doc["runs"][0]["results"][0]["locations"][0][
        "physicalLocation"]["region"]
    assert region["startLine"] == 9
    assert region["startColumn"] == v.col + 1


def test_cli_sarif_output():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.graftlint",
         "tests/fixtures/graftlint/xpkg", "--format", "sarif",
         "--no-cache"],
        cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 1  # fixtures have violations by design
    doc = json.loads(proc.stdout)
    uris = {r["locations"][0]["physicalLocation"]["artifactLocation"]["uri"]
            for r in doc["runs"][0]["results"]}
    # re-rooted at the linted directory so paths resolve from the repo root
    assert "tests/fixtures/graftlint/xpkg/treelearner/stats.py" in uris


def test_cli_caches_by_default(tmp_path):
    env = {"PYTHONPATH": str(REPO), "PATH": "/usr/bin:/bin:/usr/local/bin"}
    cmd = [sys.executable, "-m", "tools.graftlint", str(XPKG)]
    first = subprocess.run(cmd, cwd=tmp_path, capture_output=True,
                           text=True, env=env)
    assert first.returncode == 1
    cache_files = list((tmp_path / ".graftlint_cache").glob("*.json"))
    assert len(cache_files) == 1
    second = subprocess.run(cmd, cwd=tmp_path, capture_output=True,
                            text=True, env=env)
    assert second.stdout == first.stdout
