import numpy as np
import pytest

import lightgbm_tpu as lgb


def make_blobs(n=1500, k=4, f=6, seed=5):
    rng = np.random.RandomState(seed)
    centers = rng.normal(scale=3.0, size=(k, f))
    y = rng.randint(0, k, n)
    X = centers[y] + rng.normal(size=(n, f))
    return X, y.astype(np.float64)


def test_multiclass_softmax():
    X, y = make_blobs()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "multiclass", "num_class": 4, "num_leaves": 15,
                     "verbosity": -1}, ds, num_boost_round=15)
    pred = bst.predict(X)
    assert pred.shape == (len(X), 4)
    np.testing.assert_allclose(pred.sum(axis=1), 1.0, rtol=1e-4)
    acc = (pred.argmax(axis=1) == y).mean()
    assert acc > 0.95, f"accuracy {acc}"
    assert bst.num_trees() == 15 * 4


def test_multiclass_ova():
    X, y = make_blobs(800, 3)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "multiclassova", "num_class": 3, "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=10)
    pred = bst.predict(X)
    acc = (pred.argmax(axis=1) == y).mean()
    assert acc > 0.92, f"accuracy {acc}"


def test_multiclass_metrics_and_model_roundtrip(tmp_path):
    X, y = make_blobs(900, 3)
    ds = lgb.Dataset(X, label=y)
    rec = {}
    dv = lgb.Dataset(X[:200], label=y[:200], reference=ds)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "metric": "multi_logloss,multi_error", "num_leaves": 7,
                     "verbosity": -1}, ds, num_boost_round=8,
                    valid_sets=[dv], callbacks=[lgb.record_evaluation(rec)])
    assert rec["valid_0"]["multi_logloss"][-1] < rec["valid_0"]["multi_logloss"][0]
    assert rec["valid_0"]["multi_error"][-1] < 0.2
    path = str(tmp_path / "mc.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    p1, p2 = bst.predict(X[:50]), bst2.predict(X[:50])
    np.testing.assert_allclose(p1, p2, rtol=1e-5)


def test_xentropy():
    rng = np.random.RandomState(0)
    X = rng.normal(size=(1200, 5))
    p_true = 1.0 / (1.0 + np.exp(-(X[:, 0] - X[:, 1])))
    ds = lgb.Dataset(X, label=p_true)
    bst = lgb.train({"objective": "cross_entropy", "num_leaves": 15, "verbosity": -1},
                    ds, num_boost_round=30)
    pred = bst.predict(X)
    assert np.corrcoef(pred, p_true)[0, 1] > 0.97
