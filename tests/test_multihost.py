"""Two-process jax.distributed test of the multi-host tree-learner path.

The reference validates its socket/MPI linkers with multi-machine mockups
(tests/distributed/_test_distributed.py); here two REAL `jax.distributed`
processes (4 virtual CPU devices each -> one 8-device global mesh) train a
data-parallel tree each and must produce the identical model as the
single-process serial learner — proving the shard_map collectives compute
the same histograms/splits when they cross a process (DCN) boundary.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nproc, process_id=pid)
assert jax.process_count() == nproc
assert len(jax.devices()) == 4 * nproc

import numpy as np
import jax.numpy as jnp
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.parallel.learners import DataParallelTreeLearner

rng = np.random.RandomState(11)
n = 512
X = rng.randn(n, 6)
y = (X[:, 0] - X[:, 1] + 0.2 * rng.randn(n) > 0).astype(np.float64)
grad = (1.0 / (1.0 + np.exp(-0.0)) - y).astype(np.float32)
hess = np.full(n, 0.25, dtype=np.float32)

config = Config(dict(objective="binary", num_leaves=7, min_data_in_leaf=10,
                     tree_learner="data", verbosity=-1))
ds = CoreDataset.from_matrix(X, label=y, config=config)
learner = DataParallelTreeLearner(config, ds)
gh = np.stack([grad, hess, np.ones(n, np.float32)], axis=1)
gh_ext = jnp.asarray(np.concatenate([gh, np.zeros((1, 3), np.float32)]))
tree = learner.train(gh_ext)
if pid == 0:
    out = sys.argv[4]
    with open(out, "w") as f:
        f.write(tree.to_string())
print(f"proc {pid} done, leaves={tree.num_leaves}")
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_two_process_data_parallel_matches_serial(tmp_path):
    port = _free_port()
    out = str(tmp_path / "dist_tree.txt")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(pid), "2", str(port), out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outputs = [p.communicate(timeout=600)[0] for p in procs]
    for p, o in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"
    dist_tree = open(out).read()

    # single-process serial reference on the same data/gradients
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.treelearner.serial import SerialTreeLearner

    rng = np.random.RandomState(11)
    n = 512
    X = rng.randn(n, 6)
    y = (X[:, 0] - X[:, 1] + 0.2 * rng.randn(n) > 0).astype(np.float64)
    grad = (1.0 / (1.0 + np.exp(-0.0)) - y).astype(np.float32)
    hess = np.full(n, 0.25, dtype=np.float32)
    config = Config(dict(objective="binary", num_leaves=7, min_data_in_leaf=10,
                         verbosity=-1))
    ds = CoreDataset.from_matrix(X, label=y, config=config)
    learner = SerialTreeLearner(config, ds)
    gh = np.stack([grad, hess, np.ones(n, np.float32)], axis=1)
    gh_ext = jnp.asarray(np.concatenate([gh, np.zeros((1, 3), np.float32)]))
    serial_tree = learner.train(gh_ext)

    def fields(text, names=("split_feature", "threshold", "num_leaves")):
        return {ln.split("=")[0]: ln.split("=", 1)[1]
                for ln in text.splitlines() if ln.split("=")[0] in names}

    assert fields(dist_tree) == fields(serial_tree.to_string())


@pytest.mark.slow
def test_launcher_two_process_cli(tmp_path):
    """python -m lightgbm_tpu.launch spawns a jax.distributed worker group
    running the reference-style CLI end to end."""
    rng = np.random.RandomState(3)
    X = rng.randn(600, 4)
    y = (X[:, 0] - X[:, 1] + 0.2 * rng.randn(600) > 0).astype(np.float64)
    train_path = str(tmp_path / "launch.train")
    np.savetxt(train_path, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")
    model_path = str(tmp_path / "launch_model.txt")

    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.launch", "-n", "2",
         "--devices-per-proc", "2", "--",
         f"data={train_path}", "objective=binary", "num_trees=3",
         "num_leaves=7", "tree_learner=data", "min_data_in_leaf=10",
         f"output_model={model_path}", "device_type=cpu", "verbosity=-1"],
        env=env, capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout[-2000:] + proc.stderr[-2000:]
    assert os.path.exists(model_path)

    import lightgbm_tpu as lgb

    pred = lgb.Booster(model_file=model_path).predict(X)
    assert np.mean((pred > 0.5) == y) > 0.85
