"""Native C++ parser tests: parity with the pure-python path and a build
sanity check (lightgbm_tpu/native/parser.cpp)."""
import os

import numpy as np
import pytest

from lightgbm_tpu.native import get_parser
from lightgbm_tpu.io.parser import parse_file


def test_native_parser_builds():
    assert get_parser() is not None, "native parser failed to build"


def _parity(path, header=False, label_column="0"):
    Xn, yn, nn = parse_file(path, header=header, label_column=label_column)
    os.environ["LIGHTGBM_TPU_NO_NATIVE"] = "1"
    try:
        import lightgbm_tpu.native as nat
        saved, nat._cached = nat._cached, False
        Xp, yp, np_names = parse_file(path, header=header,
                                      label_column=label_column)
        nat._cached = saved
    finally:
        del os.environ["LIGHTGBM_TPU_NO_NATIVE"]
    np.testing.assert_array_equal(np.isnan(Xn), np.isnan(Xp))
    np.testing.assert_allclose(np.nan_to_num(Xn), np.nan_to_num(Xp))
    np.testing.assert_allclose(yn, yp)
    assert nn == np_names


def test_tsv_parity(rng, tmp_path):
    X = rng.randn(200, 4)
    X[5, 1] = np.nan
    y = rng.randint(0, 2, 200)
    p = str(tmp_path / "d.tsv")
    with open(p, "w") as fh:
        for i in range(200):
            row = [str(y[i])] + ["nan" if np.isnan(v) else repr(v)
                                 for v in X[i]]
            fh.write("\t".join(row) + "\n")
    _parity(p)


def test_csv_with_header_parity(rng, tmp_path):
    X = rng.randn(100, 3)
    y = rng.randint(0, 2, 100)
    p = str(tmp_path / "d.csv")
    with open(p, "w") as fh:
        fh.write("target,a,b,c\n")
        for i in range(100):
            fh.write(",".join([str(y[i])] + [repr(v) for v in X[i]]) + "\n")
    _parity(p, header=True, label_column="name:target")


def test_libsvm_parity():
    path = "/root/reference/examples/lambdarank/rank.train"
    _parity(path)


def test_reference_example_parses_identically():
    path = "/root/reference/examples/binary_classification/binary.train"
    _parity(path)


def test_native_parse_dense_multithreaded(tmp_path):
    """Files past the shard threshold take the pipelined multi-shard path;
    results must be byte-identical to the single-shard/numpy parse."""
    native = pytest.importorskip("lightgbm_tpu.native").get_parser()
    if native is None:
        pytest.skip("native parser unavailable")
    rng = np.random.RandomState(3)
    rows, cols = 70_000, 10  # ~5.5 MB > the 4 MB sharding threshold
    M = rng.randn(rows, cols).round(6)
    path = tmp_path / "big.csv"
    np.savetxt(path, M, delimiter=",", fmt="%.6f")
    assert path.stat().st_size > (4 << 20)
    buf, nr, nc = native.parse_dense(str(path), ord(","), 0)
    assert (nr, nc) == (rows, cols)
    out = np.frombuffer(buf, dtype=np.float64).reshape(rows, cols)
    np.testing.assert_allclose(out, M, atol=1e-9)
