"""Device-op oracle tests: histogram/split/partition vs numpy references."""
import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDS
from lightgbm_tpu.ops.histogram import (build_histogram, build_histogram_rows,
                                        subtract_histogram)
from lightgbm_tpu.ops.partition import RowPartition, pad_indices
from lightgbm_tpu.ops.split import (SplitInfo, find_best_split,
                                    gather_feature_hist, make_feature_meta)


@pytest.fixture(scope="module")
def setup():
    rng = np.random.RandomState(11)
    N, F = 3000, 5
    X = rng.normal(size=(N, F))
    X[:, 2] = rng.binomial(1, 0.3, N) * rng.normal(size=N)  # zeros -> sparse
    grad = rng.normal(size=N).astype(np.float32)
    hess = np.abs(rng.normal(size=N)).astype(np.float32) + 0.1
    ds = CoreDS.from_matrix(X, label=grad, config=Config({"verbosity": -1}))
    gh = np.concatenate([np.stack([grad, hess, np.ones(N, np.float32)], 1),
                         np.zeros((1, 3), np.float32)])
    return ds, jnp.asarray(ds.bins), jnp.asarray(gh), grad, hess, N


def test_full_histogram_matches_numpy(setup):
    ds, bins_dev, gh_dev, grad, hess, N = setup
    B = int(ds.group_bin_counts().max())
    hist = np.asarray(build_histogram(bins_dev, gh_dev[:N], B))
    for g in range(ds.num_groups):
        ref = ds.construct_histogram_np(g, grad.astype(np.float64), hess.astype(np.float64))
        np.testing.assert_allclose(hist[g][: ds.groups[g].num_total_bin], ref,
                                   rtol=1e-4, atol=1e-3)


def test_row_histogram_with_padding(setup):
    ds, bins_dev, gh_dev, grad, hess, N = setup
    B = int(ds.group_bin_counts().max())
    rows = np.arange(0, N, 3, dtype=np.int32)
    idx = jnp.asarray(pad_indices(rows, N))
    hist = np.asarray(build_histogram_rows(bins_dev, gh_dev, idx, B))
    for g in range(ds.num_groups):
        ref = ds.construct_histogram_np(g, grad.astype(np.float64),
                                        hess.astype(np.float64), rows)
        np.testing.assert_allclose(hist[g][: ds.groups[g].num_total_bin], ref,
                                   rtol=1e-4, atol=1e-3)


def test_subtraction_trick(setup):
    ds, bins_dev, gh_dev, grad, hess, N = setup
    B = int(ds.group_bin_counts().max())
    left = np.arange(0, N // 2, dtype=np.int32)
    right = np.arange(N // 2, N, dtype=np.int32)
    h_all = build_histogram(bins_dev, gh_dev[:N], B)
    h_left = build_histogram_rows(bins_dev, gh_dev, jnp.asarray(pad_indices(left, N)), B)
    h_right_sub = np.asarray(subtract_histogram(h_all, h_left))
    h_right = np.asarray(build_histogram_rows(bins_dev, gh_dev,
                                              jnp.asarray(pad_indices(right, N)), B))
    np.testing.assert_allclose(h_right_sub, h_right, rtol=1e-3, atol=1e-2)


def test_split_partition_consistency(setup):
    """The invariant whose violation broke training: the partition's left
    count must equal the split record's left count for every leaf."""
    ds, bins_dev, gh_dev, grad, hess, N = setup
    B = int(ds.group_bin_counts().max())
    meta = make_feature_meta(ds, B)
    params = jnp.asarray([0, 0, 20, 1e-3, 0, 0], dtype=jnp.float32)
    part = RowPartition(N, min_bucket=256)
    hist = build_histogram_rows(bins_dev, gh_dev, part.indices(0), B)
    totals = hist[0].sum(axis=0)
    frontier = {0: (hist, totals)}
    next_leaf = 1
    for step in range(6):
        # split every leaf currently in the frontier once
        leaf = max(frontier, key=lambda l: float(frontier[l][1][2]))
        hist_l, totals_l = frontier.pop(leaf)
        rec = SplitInfo.from_packed(np.asarray(
            find_best_split(hist_l, totals_l.astype(jnp.float32), meta, params)))
        if not rec.valid:
            break
        real_f = meta.real_feature[rec.feature]
        mapper = ds.mappers[real_f]
        gi, mi = ds.feature_to_group[real_f]
        fg = ds.groups[gi]
        lo, hi, dbin = fg.feature_bin_range(mi)
        decision = jnp.asarray([
            float(rec.threshold_bin), 1.0 if rec.default_left else 0.0,
            float(mapper.missing_type), float(mapper.default_bin),
            float(mapper.num_bin), float(lo), float(hi),
            1.0 if fg.is_multi else 0.0], dtype=jnp.float32)
        lc, rc = part.split(leaf, next_leaf, bins_dev[gi], decision)
        assert lc == rec.left_count, f"step {step}: {lc} != {rec.left_count}"
        assert rc == rec.right_count, f"step {step}: {rc} != {rec.right_count}"
        h_small_leaf = leaf if lc <= rc else next_leaf
        h_small = build_histogram_rows(bins_dev, gh_dev,
                                       part.indices(h_small_leaf), B)
        h_big = subtract_histogram(hist_l, h_small)
        lt = jnp.asarray([rec.left_sum_g, rec.left_sum_h, lc], dtype=jnp.float32)
        rt = jnp.asarray([rec.right_sum_g, rec.right_sum_h, rc], dtype=jnp.float32)
        if h_small_leaf == leaf:
            frontier[leaf] = (h_small, lt)
            frontier[next_leaf] = (h_big, rt)
        else:
            frontier[leaf] = (h_big, lt)
            frontier[next_leaf] = (h_small, rt)
        # cross-check: rebuilt hist for the big child matches subtraction
        h_big_direct = np.asarray(build_histogram_rows(
            bins_dev, gh_dev, part.indices(leaf if h_small_leaf != leaf else next_leaf), B))
        np.testing.assert_allclose(np.asarray(h_big), h_big_direct, rtol=1e-3, atol=5e-2)
        next_leaf += 1


def test_efb_bundled_feature_histogram():
    """Two mutually exclusive sparse features bundle into one group; the
    reconstructed per-feature histograms must match the unbundled oracle."""
    rng = np.random.RandomState(5)
    N = 4000
    mask = rng.binomial(1, 0.5, N).astype(bool)
    X = np.zeros((N, 2))
    X[mask, 0] = rng.uniform(1, 2, mask.sum())
    X[~mask, 1] = rng.uniform(1, 2, (~mask).sum())
    cfg = Config({"verbosity": -1, "enable_bundle": True, "min_data_in_bin": 1})
    ds = CoreDS.from_matrix(X, label=np.zeros(N), config=cfg)
    grad = rng.normal(size=N).astype(np.float32)
    hess = np.ones(N, np.float32)
    if ds.num_groups == 1:
        assert ds.groups[0].is_multi  # bundled
    B = int(ds.group_bin_counts().max())
    gh = np.concatenate([np.stack([grad, hess, np.ones(N, np.float32)], 1),
                         np.zeros((1, 3), np.float32)])
    hist = build_histogram(jnp.asarray(ds.bins), jnp.asarray(gh[:N]), B)
    meta = make_feature_meta(ds, B)
    totals = hist[0].sum(axis=0)
    fh = np.asarray(gather_feature_hist(hist, meta, totals.astype(jnp.float32)))
    for k, f in enumerate(ds.used_features):
        m = ds.mappers[f]
        raw_bins = m.values_to_bins(X[:, f])
        ref = np.zeros((m.num_bin, 3))
        np.add.at(ref[:, 0], raw_bins, grad)
        np.add.at(ref[:, 1], raw_bins, hess)
        np.add.at(ref[:, 2], raw_bins, 1.0)
        np.testing.assert_allclose(fh[k][: m.num_bin], ref, rtol=1e-3, atol=1e-2)
