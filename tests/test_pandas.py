"""Pandas DataFrame handling: category dtype auto-detection, the
pandas_categorical training->predict mapping, and model-file persistence
(python-package _data_from_pandas protocol)."""
import numpy as np
import pytest

pd = pytest.importorskip("pandas")

import lightgbm_tpu as lgb


def _frame(rng, n=800):
    df = pd.DataFrame({
        "a": rng.randn(n),
        "b": pd.Categorical(rng.choice(["x", "y", "z"], n)),
        "c": rng.randn(n),
    })
    y = ((df["a"] + (df["b"] == "x") * 2.0 + rng.randn(n) * 0.3) > 0
         ).astype(float)
    return df, y


def test_pandas_categorical_training(rng):
    df, y = _frame(rng)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=10)
    acc = ((bst.predict(df) > 0.5) == y).mean()
    assert acc > 0.85, acc
    # the category column must actually be used as categorical
    dumped = bst.dump_model()

    def has_cat(node):
        if "split_feature" in node:
            return (node["decision_type"] == "==" or has_cat(node["left_child"])
                    or has_cat(node["right_child"]))
        return False

    assert any(has_cat(t["tree_structure"]) for t in dumped["tree_info"])


def test_pandas_categorical_mapping_roundtrip(rng, tmp_path):
    df, y = _frame(rng)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=5)
    pred = bst.predict(df)
    # reordered/unseen categories at predict time map through TRAINING codes
    df2 = df.copy()
    df2["b"] = pd.Categorical(df["b"].astype(str),
                              categories=["z", "x", "y", "new"])
    np.testing.assert_allclose(bst.predict(df2), pred, rtol=1e-6)

    path = str(tmp_path / "pd.txt")
    bst.save_model(path)
    assert "pandas_categorical:" in open(path).read()
    re = lgb.Booster(model_file=path)
    np.testing.assert_allclose(re.predict(df2), pred, rtol=1e-6)


def test_pandas_plain_numeric_frame(rng):
    df = pd.DataFrame({"a": rng.randn(300), "b": rng.randn(300)})
    y = (df["a"] > 0).astype(float)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=3)
    assert np.isfinite(bst.predict(df)).all()


def test_sklearn_with_pandas_categorical(rng):
    df, y = _frame(rng, n=600)
    clf = lgb.LGBMClassifier(n_estimators=8, num_leaves=7, verbosity=-1)
    clf.fit(df, (y > 0).astype(int))
    acc = (clf.predict(df) == (y > 0)).mean()
    assert acc > 0.85, acc


def test_valid_set_maps_through_training_categories(rng):
    """A validation frame whose pandas categories are ordered differently
    must still encode through the TRAINING category lists."""
    df, y = _frame(rng, n=600)
    df_v = df.iloc[:200].copy()
    y_v = y.iloc[:200]
    # same values, different category order + an extra unseen category
    df_v["b"] = pd.Categorical(df_v["b"].astype(str),
                               categories=["z", "y", "x", "extra"])
    ds = lgb.Dataset(df, label=y)
    dv = lgb.Dataset(df_v, label=y_v, reference=ds)
    rec = {}
    lgb.train({"objective": "binary", "num_leaves": 7, "metric": "binary_logloss",
               "verbosity": -1}, ds, num_boost_round=8, valid_sets=[dv],
              callbacks=[lgb.record_evaluation(rec)])
    vloss = rec["valid_0"]["binary_logloss"][-1]
    # with correct mapping the valid loss tracks training (same rows)
    assert vloss < 0.5, vloss


def test_categorical_count_mismatch_raises(rng):
    df, y = _frame(rng, n=300)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(df, label=y),
                    num_boost_round=2)
    bad = df.copy()
    bad["c"] = pd.Categorical(rng.choice(["u", "v"], len(df)))  # extra cat col
    with pytest.raises(ValueError, match="categorical_feature"):
        bst.predict(bad)
