"""Distributed learner tests on the 8-device virtual CPU mesh.

Counterpart of the reference's DistributedMockup (tests/distributed/
_test_distributed.py) and test_dask.py: exercise the real collective code
paths (psum_scatter / all_gather / psum inside shard_map) without a cluster,
and check the distributed learners agree with the serial learner.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(rng, n=2000, f=10):
    X = rng.randn(n, f)
    logit = X[:, 0] * 2.0 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def _train(X, y, learner, num_rounds=10, **extra):
    params = dict(objective="binary", num_leaves=15, learning_rate=0.2,
                  min_data_in_leaf=20, tree_learner=learner, verbosity=-1,
                  **extra)
    ds = lgb.Dataset(X, label=y)
    return lgb.train(params, ds, num_boost_round=num_rounds)


@pytest.mark.parametrize("learner", ["data", "feature", "voting"])
def test_parallel_matches_serial_predictions(rng, learner):
    X, y = _make_binary(rng)
    p_serial = _train(X, y, "serial").predict(X)
    p_dist = _train(X, y, learner).predict(X)
    # data/feature parallel are exact re-shardings of the same algorithm;
    # voting may diverge when the vote misses the global best feature
    if learner in ("data", "feature"):
        np.testing.assert_allclose(p_dist, p_serial, rtol=1e-4, atol=1e-5)
    else:
        acc_s = np.mean((p_serial > 0.5) == y)
        acc_v = np.mean((p_dist > 0.5) == y)
        assert acc_v >= acc_s - 0.02


@pytest.mark.parametrize("learner", ["data", "feature", "voting"])
def test_parallel_accuracy(rng, learner):
    X, y = _make_binary(rng)
    pred = _train(X, y, learner, num_rounds=20).predict(X)
    acc = np.mean((pred > 0.5) == y)
    assert acc > 0.9, f"{learner} learner accuracy {acc}"


def test_data_parallel_sharding_active(rng):
    """The data-parallel learner really shards rows over the mesh."""
    import jax

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.parallel.learners import DataParallelTreeLearner

    X, y = _make_binary(rng, n=1024)
    config = Config(dict(objective="binary", num_leaves=7,
                         tree_learner="data", verbosity=-1))
    ds = CoreDataset.from_matrix(X, label=y, config=config)
    learner = DataParallelTreeLearner(config, ds)
    assert learner.D == len(jax.devices())
    shards = learner.bins_dev.addressable_shards
    assert len(shards) == learner.D
    assert shards[0].data.shape[1] == learner.n_pad // learner.D


def test_data_parallel_with_bagging_indices(rng):
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.parallel.learners import DataParallelTreeLearner

    X, y = _make_binary(rng, n=1000)
    config = Config(dict(objective="binary", num_leaves=7,
                         tree_learner="data", verbosity=-1))
    ds = CoreDataset.from_matrix(X, label=y, config=config)
    learner = DataParallelTreeLearner(config, ds)
    n = 1000
    resid = y - 0.5
    gh = jnp.concatenate([
        jnp.stack([jnp.asarray(-resid, jnp.float32),
                   jnp.full(n, 0.25, jnp.float32),
                   jnp.ones(n, jnp.float32)], axis=1),
        jnp.zeros((1, 3), jnp.float32)])
    bag = np.sort(np.random.RandomState(0).choice(n, 700, replace=False))
    tree = learner.train(gh, bag)
    assert tree.num_leaves > 1
    part = learner.partition
    total = sum(part.count(i) for i in range(tree.num_leaves))
    assert total == 700


@pytest.mark.parametrize("learner", ["data", "feature", "voting"])
def test_parallel_categorical_splits(rng, learner):
    """Distributed learners handle categorical features (the reference's
    distributed learners do, data_parallel_tree_learner.cpp); data/feature
    parallel must agree with serial exactly."""
    n = 2000
    cats = rng.randint(0, 12, size=n)
    effect = np.where(np.isin(cats, [2, 5, 7]), 2.0, -1.0)
    X = np.column_stack([cats.astype(np.float64), rng.randn(n)])
    y = (effect + 0.3 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(np.float64)

    def train(ltype):
        params = dict(objective="binary", num_leaves=7, learning_rate=0.2,
                      min_data_in_leaf=20, tree_learner=ltype, verbosity=-1)
        ds = lgb.Dataset(X, label=y, categorical_feature=[0])
        return lgb.train(params, ds, num_boost_round=10)

    bst = train(learner)
    pred = bst.predict(X)
    acc = np.mean((pred > 0.5) == y)
    assert acc > 0.85, f"{learner} accuracy {acc}"

    dumped = bst.dump_model()

    def has_cat(node):
        if "split_feature" in node:
            return (node["decision_type"] == "==" or
                    has_cat(node["left_child"]) or has_cat(node["right_child"]))
        return False

    assert any(has_cat(t["tree_structure"]) for t in dumped["tree_info"])

    if learner in ("data", "feature"):
        p_serial = train("serial").predict(X)
        np.testing.assert_allclose(pred, p_serial, rtol=1e-4, atol=1e-5)
