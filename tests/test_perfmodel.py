"""Performance-observatory suite: perfmodel formulas, dispatch capture +
XLA static cost analysis, attribution structure, the environment
fingerprint + bench ledger, benchdiff direction/threshold gating, and the
Prometheus exposition (render, parse, /metrics endpoint, snapshot file).
"""
import json
import os
import subprocess
import sys
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import exposition, fingerprint, perfmodel, telemetry
from lightgbm_tpu.engine import train
from lightgbm_tpu.utils.timer import global_timer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCHDIFF = os.path.join(_REPO, "tools", "benchdiff.py")

BASE = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}


def _data(n=400, f=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.5 > 0)
    return X, y.astype(np.float64)


@pytest.fixture(autouse=True)
def _clean_capture_state():
    perfmodel.reset_dispatches()
    yield
    perfmodel.reset_dispatches()
    assert telemetry.session() is None, "test leaked a telemetry session"


# -- analytic formulas ----------------------------------------------------

def test_carry_formula_matches_bench_expectation():
    # the bench smoke's locked figure: 28 features -> Gp=32 uint8 groups,
    # 20000 rows pad to the 1024-row wave unit, payload 5 cols x 4 B
    n_pad = -(-20000 // 1024) * 1024
    assert perfmodel.carry_bytes_per_wave(20000, 28, 1, 1024) \
        == n_pad * (32 * 1 + 5 * 4)
    # int32 planes pad the group dim to 8: ceil(28/8)*8 = 32 groups still
    assert perfmodel.carry_bytes_per_wave(20000, 28, 4, 1024) \
        == n_pad * (32 * 4 + 5 * 4)
    assert perfmodel.plane_groups_padded(17, 4) == 24


def test_ici_formula_matches_parallel_learner():
    # parallel/learners.py _record_ici_bytes: K*F_pad*Bmax*CH*pool_bytes
    # + 2K*F_pad*REC*4 — perfmodel is the single source of truth now
    k, f_pad, bmax = 21, 32, 256
    expected = k * f_pad * bmax * 3 * 4 + 2 * k * f_pad * 14 * 4
    assert perfmodel.ici_bytes_per_wave(k, f_pad, bmax) == expected
    # narrow (int16) histogram pool halves the first term only
    narrow = k * f_pad * bmax * 3 * 2 + 2 * k * f_pad * 14 * 4
    assert perfmodel.ici_bytes_per_wave(k, f_pad, bmax,
                                        pool_bytes=2) == narrow


def test_peak_bandwidth_table_and_override(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_PEAK_BW_GBPS", raising=False)
    assert perfmodel.peak_bandwidth_bytes_per_s("TPU v5 lite") == 819e9
    assert perfmodel.peak_bandwidth_bytes_per_s("cpu") is None
    monkeypatch.setenv("LGBM_TPU_PEAK_BW_GBPS", "100")
    assert perfmodel.peak_bandwidth_bytes_per_s("cpu") == 100e9


# -- dispatch capture + static cost analysis ------------------------------

def test_capture_and_cost_analysis_keys_for_instrumented_fns(tmp_path):
    """A telemetry-on CPU train + predict must capture the serial-learner
    scan and histogram dispatches and the fused predict, and XLA's
    cost_analysis must report flops/bytes for each."""
    X, y = _data()
    with telemetry.capture(None, label="perfmodel-test"):
        bst = train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=3)
        bst.predict(X[:64], raw_score=True)
        captured = perfmodel.captured_stages()
        assert "scan" in captured, captured
        assert "histogram" in captured, captured
        assert "predict" in captured, captured
        static = perfmodel.static_costs()
    for stage in ("scan", "histogram", "predict"):
        entry = static[stage]
        assert "error" not in entry, (stage, entry)
        assert entry["flops"] > 0, (stage, entry)
        assert entry["bytes_accessed"] > 0, (stage, entry)
        assert entry["argument_bytes"] > 0, (stage, entry)
    # repeat lowering hits the cache, not a recompute
    assert perfmodel.static_costs() == static


def test_capture_is_noop_without_session():
    X, y = _data(n=120)
    train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=1)
    assert perfmodel.captured_stages() == []


# -- attribution ----------------------------------------------------------

def test_attribution_fractions_sum_to_one_on_real_train():
    X, y = _data()
    with telemetry.capture(None, label="attr-test"):
        train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=3)
        report = perfmodel.attribution(dict(global_timer.totals),
                                       dict(global_timer.counters))
    assert report["stages"], report
    assert abs(report["fractions_sum"] - 1.0) <= 0.05, report
    for st in report["stages"].values():
        assert 0.0 <= st["fraction"] <= 1.0
        assert st["wall_s"] >= 0.0


def test_attribution_model_and_roofline(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_PEAK_BW_GBPS", "1")  # 1e9 B/s
    totals = {"boosting": 2.0, "tree_device": 1.0, "update_score": 0.4}
    counters = {"device_waves": 10,
                "device_carry_bytes_per_wave": 10_000_000,
                "device_hist_rows": 1_000_000,
                "device_hist_bytes_per_row": 52,
                "device_scan_bytes_per_wave": 2_000_000,
                "device_ici_bytes_per_wave": 500_000}
    rep = perfmodel.attribution(totals, counters, device_kind="whatever")
    grow = rep["stages"]["grow_fused"]
    comp = grow["model_components_bytes"]
    assert comp["compact"] == 2 * 10_000_000 * 10
    assert comp["histogram"] == 1_000_000 * 52
    assert comp["scan"] == 2_000_000 * 10
    assert comp["ici"] == 500_000 * 10
    assert grow["model_bytes"] == sum(comp.values())
    # model seconds at 1e9 B/s; drift + roofline derived from it
    assert grow["model_s"] == pytest.approx(grow["model_bytes"] / 1e9)
    assert "drift_pct" in grow and "roofline_frac" in grow
    # the uncovered wall shows up as an explicit "other" stage and the
    # fractions still close to 1
    assert "other" in rep["stages"]
    assert abs(rep["fractions_sum"] - 1.0) <= 0.05


# -- fingerprint + ledger -------------------------------------------------

def test_fingerprint_keys():
    fp = fingerprint.fingerprint()
    assert fp["schema_version"] == fingerprint.LEDGER_SCHEMA_VERSION
    assert fp["git_sha"] and fp["git_sha"] != "unknown"
    assert fp["jax_version"] != "unknown"
    assert fp["device_count"] >= 1
    assert isinstance(fp["flags"], dict)


def test_ledger_append_and_disable(tmp_path, monkeypatch):
    path = str(tmp_path / "ledger.jsonl")
    assert fingerprint.append_ledger({"value": 1}, path=path) == path
    assert fingerprint.append_ledger({"value": 2}, path=path) == path
    lines = [json.loads(ln) for ln in open(path) if ln.strip()]
    assert [r["value"] for r in lines] == [1, 2]
    monkeypatch.setenv("BENCH_LEDGER", "off")
    assert fingerprint.ledger_path() is None
    assert fingerprint.append_ledger({"value": 3}) is None


# -- benchdiff gating -----------------------------------------------------

def _record(**over):
    rec = {"metric": "train_row_iters_per_sec", "value": 10_000.0,
           "unit": "row_iters/s", "platform": "cpu", "rows": 20000,
           "iters": 2, "auc": 0.85, "est_carried_bytes_per_wave": 1064960,
           "predict_chunk_rows": 8192, "device_hist_rows": 0,
           "serve_p99_ms": 4.0, "schema_version": 1,
           "fingerprint": {"git_sha": "aaa", "schema_version": 1},
           "attribution": {"fractions_sum": 1.0}}
    rec.update(over)
    return rec


def _run_benchdiff(tmp_path, old, new, *extra):
    ledger = tmp_path / "ledger.jsonl"
    ledger.write_text(json.dumps(old) + "\n" + json.dumps(new) + "\n")
    out = subprocess.run(
        [sys.executable, BENCHDIFF, str(ledger), "--gate", *extra],
        capture_output=True, text=True, timeout=60)
    return out


def test_benchdiff_exits_1_on_seeded_throughput_regression(tmp_path):
    out = _run_benchdiff(tmp_path, _record(), _record(value=5_000.0))
    assert out.returncode == 1, out.stdout + out.stderr
    assert "REGRESSION" in out.stdout and "value" in out.stdout


def test_benchdiff_exits_0_on_noise_within_threshold(tmp_path):
    out = _run_benchdiff(tmp_path, _record(), _record(value=10_400.0,
                                                      serve_p99_ms=4.2))
    assert out.returncode == 0, out.stdout + out.stderr


def test_benchdiff_direction_lower_is_better(tmp_path):
    # serve_p99_ms doubling IS a regression; halving is an improvement
    out = _run_benchdiff(tmp_path, _record(), _record(serve_p99_ms=20.0))
    assert out.returncode == 1, out.stdout
    out = _run_benchdiff(tmp_path, _record(), _record(serve_p99_ms=1.0,
                                                      value=20_000.0))
    assert out.returncode == 0, out.stdout
    assert "improved" in out.stdout


def test_benchdiff_exact_metric_change_gates(tmp_path):
    out = _run_benchdiff(tmp_path, _record(),
                         _record(est_carried_bytes_per_wave=999))
    assert out.returncode == 1, out.stdout


def test_benchdiff_deterministic_only_skips_perf(tmp_path):
    out = _run_benchdiff(tmp_path, _record(), _record(value=5_000.0),
                         "--deterministic-only")
    assert out.returncode == 0, out.stdout + out.stderr


def test_benchdiff_bad_attribution_gates(tmp_path):
    bad = _record(attribution={"fractions_sum": 0.5})
    out = _run_benchdiff(tmp_path, _record(), bad)
    assert out.returncode == 1, out.stdout


def test_benchdiff_incomparable_records_skip_not_fail(tmp_path):
    out = _run_benchdiff(tmp_path, _record(rows=40000),
                         _record(value=5_000.0))
    assert out.returncode == 0, out.stdout
    assert "not comparable" in out.stdout
    out = _run_benchdiff(tmp_path, _record(rows=40000),
                         _record(value=5_000.0), "--strict")
    assert out.returncode == 1, out.stdout


def test_benchdiff_gates_stream_sharded_metrics(tmp_path):
    """Pod-streaming SPEC entries: throughput gates as perf (skipped in
    CI's deterministic-only mode); the overlap/merge pair gates
    everywhere inside wide deterministic tolerances."""
    old = _record(stream_sharded_rows_per_sec=1000.0,
                  stream_h2d_overlap_pct=80.0, stream_sketch_merge_ms=10.0)
    # throughput halves: a perf regression ...
    out = _run_benchdiff(tmp_path, old,
                         _record(stream_sharded_rows_per_sec=400.0,
                                 stream_h2d_overlap_pct=80.0,
                                 stream_sketch_merge_ms=10.0))
    assert out.returncode == 1, out.stdout
    assert "stream_sharded_rows_per_sec" in out.stdout
    # ... that deterministic-only CI mode does NOT gate on
    out = _run_benchdiff(tmp_path, old,
                         _record(stream_sharded_rows_per_sec=400.0,
                                 stream_h2d_overlap_pct=80.0,
                                 stream_sketch_merge_ms=10.0),
                         "--deterministic-only")
    assert out.returncode == 0, out.stdout
    # overlap collapsing past the 25-point allowance gates even there
    out = _run_benchdiff(tmp_path, old,
                         _record(stream_sharded_rows_per_sec=1000.0,
                                 stream_h2d_overlap_pct=20.0,
                                 stream_sketch_merge_ms=10.0),
                         "--deterministic-only")
    assert out.returncode == 1, out.stdout
    assert "stream_h2d_overlap_pct" in out.stdout
    # a merge wall blowing through the 250ms allowance gates too
    out = _run_benchdiff(tmp_path, old,
                         _record(stream_sharded_rows_per_sec=1000.0,
                                 stream_h2d_overlap_pct=80.0,
                                 stream_sketch_merge_ms=700.0),
                         "--deterministic-only")
    assert out.returncode == 1, out.stdout
    assert "stream_sketch_merge_ms" in out.stdout


def test_benchdiff_gates_against_committed_baseline():
    """The committed CPU baseline must self-gate clean (the CI invocation)."""
    baseline = os.path.join(_REPO, "BENCH_BASELINE_CPU.json")
    out = subprocess.run(
        [sys.executable, BENCHDIFF, baseline, baseline,
         "--gate", "--deterministic-only"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr


# -- exposition -----------------------------------------------------------

def test_render_metrics_matches_signals_and_parses():
    with telemetry.capture(None, label="expo-test"):
        X, y = _data(n=137, f=11)
        train(dict(BASE), lgb.Dataset(X, label=y), num_boost_round=1)
        sig = telemetry.signals()
        text = exposition.render_metrics(extra={"serve_p50_ms": 1.25})
    parsed = exposition.parse_exposition(text)
    assert parsed[("lgbm_tpu_compiles_total", ())] == float(sig["compiles"])
    assert sig["compiles"] > 0
    assert parsed[("lgbm_tpu_kernel_compiles_total", ())] \
        == float(sig["kernel_compiles"])
    assert parsed[("lgbm_tpu_hbm_high_water_bytes", ())] \
        == float(sig["hbm_high_water_bytes"])
    assert parsed[("lgbm_tpu_telemetry_enabled", ())] == 1.0
    assert parsed[("lgbm_tpu_serve_p50_ms", ())] == 1.25
    # per-stage timer totals carry the stage label
    stage_samples = [k for k in parsed
                     if k[0] == "lgbm_tpu_stage_seconds_total"]
    assert stage_samples, sorted(parsed)
    assert all(dict(labels).get("stage") for _, labels in stage_samples)


def test_parse_exposition_rejects_malformed():
    with pytest.raises(ValueError):
        exposition.parse_exposition("this is { not a metric line\n")


def test_telemetry_dir_gets_metrics_snapshot(tmp_path):
    X, y = _data(n=150)
    train(dict(BASE, telemetry_dir=str(tmp_path)), lgb.Dataset(X, label=y),
          num_boost_round=2)
    snap = tmp_path / exposition.SNAPSHOT_FILE
    assert snap.is_file()
    parsed = exposition.parse_exposition(snap.read_text())
    # the close-time snapshot must carry the SESSION's compile total, not
    # the no-session zeros (stop() detaches the module global before close)
    assert parsed[("lgbm_tpu_compiles_total", ())] > 0
    assert parsed[("lgbm_tpu_telemetry_enabled", ())] == 0.0


def test_metrics_endpoint_prometheus_text():
    from lightgbm_tpu.serving import PredictionService
    from lightgbm_tpu.serving.http import serve

    rng = np.random.RandomState(42)
    X = rng.rand(300, 10)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train(dict(BASE, num_leaves=15), lgb.Dataset(X, label=y),
                    num_boost_round=4)
    svc = PredictionService(max_batch_rows=512, batch_window_s=0.0)
    server = None
    try:
        svc.load_model("m", booster=bst)
        svc.predict("m", X[:32], raw_score=True)
        server, _ = serve(svc, port=0)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/metrics", timeout=10) as r:
            assert r.status == 200
            ctype = r.headers.get("Content-Type", "")
            body = r.read().decode("utf-8")
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        parsed = exposition.parse_exposition(body)
        sig = telemetry.signals()
        assert parsed[("lgbm_tpu_compiles_total", ())] \
            == float(sig["compiles"])
        assert parsed[("lgbm_tpu_hbm_high_water_bytes", ())] \
            == float(sig["hbm_high_water_bytes"])
        # the flattened /statz figures ride along as serve_* gauges
        assert parsed[("lgbm_tpu_serve_batcher_batches", ())] >= 1.0
        assert ("lgbm_tpu_serve_breaker_failures", ()) in parsed \
            or ("lgbm_tpu_serve_swaps", ()) in parsed
    finally:
        if server is not None:
            server.shutdown()
        svc.close()
