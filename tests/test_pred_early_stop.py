"""Prediction early-stop tests (src/boosting/prediction_early_stop.cpp)."""
import numpy as np

import lightgbm_tpu as lgb


def test_binary_early_stop_margin(rng):
    n = 2000
    X = rng.randn(n, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.3, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=40)
    full = bst.predict(X, raw_score=True)
    es = bst.predict(X, raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=5, pred_early_stop_margin=2.0)
    # stopped rows have 2*|raw| past the margin (prediction_early_stop.cpp:65)
    assert np.mean(np.sign(es) == np.sign(full)) > 0.99
    stopped = np.abs(es - full) > 1e-9
    assert stopped.any()  # early stop actually kicked in
    assert np.all(2.0 * np.abs(es[stopped]) > 2.0)
    # huge margin => identical to full prediction
    same = bst.predict(X, raw_score=True, pred_early_stop=True,
                       pred_early_stop_margin=1e9)
    np.testing.assert_allclose(same, full, rtol=1e-6)


def test_multiclass_early_stop(rng):
    n = 1500
    X = rng.randn(n, 4)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "learning_rate": 0.3, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=3.0)
    assert np.mean(es.argmax(axis=1) == full.argmax(axis=1)) > 0.99
