"""Prediction early-stop tests (src/boosting/prediction_early_stop.cpp)."""
import jax.numpy as jnp
import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu.ops.predict import pack_ensemble, predict_raw_early_stop


def _early_stop_reference(trees, X, C, freq, margin):
    """Host sequential early stop: per block of freq*C trees, each active
    row adds tree m's output to class m % C, then stops once its margin
    (2|s| binary, top-2 gap multiclass) clears the threshold."""
    N = X.shape[0]
    out = np.zeros((N, C), dtype=np.float64)
    active = np.ones(N, dtype=bool)
    block = max(freq, 1) * C
    for start in range(0, len(trees), block):
        if not active.any():
            break
        for m in range(start, min(start + block, len(trees))):
            t = trees[m]
            for i in np.nonzero(active)[0]:
                out[i, m % C] += t.predict(X[i])
        for i in np.nonzero(active)[0]:
            if C == 1:
                mg = 2.0 * abs(out[i, 0])
            else:
                top = np.sort(out[i])[-2:]
                mg = top[1] - top[0]
            if mg > margin:
                active[i] = False
    return out


def test_binary_early_stop_margin(rng):
    n = 2000
    X = rng.randn(n, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "learning_rate": 0.3, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=40)
    full = bst.predict(X, raw_score=True)
    es = bst.predict(X, raw_score=True, pred_early_stop=True,
                     pred_early_stop_freq=5, pred_early_stop_margin=2.0)
    # stopped rows have 2*|raw| past the margin (prediction_early_stop.cpp:65)
    assert np.mean(np.sign(es) == np.sign(full)) > 0.99
    stopped = np.abs(es - full) > 1e-9
    assert stopped.any()  # early stop actually kicked in
    assert np.all(2.0 * np.abs(es[stopped]) > 2.0)
    # huge margin => identical to full prediction
    same = bst.predict(X, raw_score=True, pred_early_stop=True,
                       pred_early_stop_margin=1e9)
    np.testing.assert_allclose(same, full, rtol=1e-6)


def test_multiclass_early_stop(rng):
    n = 1500
    X = rng.randn(n, 4)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "learning_rate": 0.3, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=30)
    full = bst.predict(X)
    es = bst.predict(X, pred_early_stop=True, pred_early_stop_freq=5,
                     pred_early_stop_margin=3.0)
    assert np.mean(es.argmax(axis=1) == full.argmax(axis=1)) > 0.99


# ------------------- device path vs host sequential reference equivalence

def test_binary_early_stop_matches_host_reference(rng):
    n = 300
    X = rng.randn(n, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "learning_rate": 0.3, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=20)
    got = bst.predict(X, raw_score=True, pred_early_stop=True,
                      pred_early_stop_freq=4, pred_early_stop_margin=1.5)
    ref = _early_stop_reference(bst._gbdt.models, X, 1, 4, 1.5)[:, 0]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_multiclass_early_stop_matches_host_reference(rng):
    n = 250
    X = rng.randn(n, 4)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "learning_rate": 0.3, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=12)
    got = bst.predict(X, raw_score=True, pred_early_stop=True,
                      pred_early_stop_freq=3, pred_early_stop_margin=1.0)
    ref = _early_stop_reference(bst._gbdt.models, X, 3, 3, 1.0)
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)


def test_early_stop_categorical_nan_ensemble():
    from tests.test_predict_op import _nan_cat_tree

    # the same cat+NaN tree across 6 blocks: block semantics + the device
    # categorical/missing decisions must match the host walk exactly
    trees = [_nan_cat_tree() for _ in range(6)]
    X = np.array([[np.nan, 0.0], [1.0, 1.0], [1.0, 2.0], [1.0, 3.0],
                  [1.0, np.nan], [0.2, 1.5]], dtype=np.float64)
    packed = pack_ensemble(trees)
    got = predict_raw_early_stop(packed, jnp.asarray(X, dtype=jnp.float32),
                                 1, 2, 9.0)
    ref = _early_stop_reference(trees, X, 1, 2, 9.0)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_early_stop_linear_tree_ensemble(rng):
    n = 200
    X = rng.rand(n, 3)
    y = 2.0 * X[:, 0] - X[:, 1] + 0.05 * rng.randn(n)
    bst = lgb.train({"objective": "regression", "num_leaves": 7,
                     "linear_tree": True, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=10)
    got = bst.predict(X, raw_score=True, pred_early_stop=True,
                      pred_early_stop_freq=3, pred_early_stop_margin=2.0)
    ref = _early_stop_reference(bst._gbdt.models, X, 1, 3, 2.0)[:, 0]
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
