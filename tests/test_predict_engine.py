"""Serving-path engine tests: PredictorCache reuse/invalidation, chunked
streaming, and row-sharded predict on the 8 fake CPU devices."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train_binary(rng, n=600, rounds=8):
    X = rng.randn(n, 5)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=rounds)
    return bst, X, y


def test_repeated_predict_does_not_repack(rng, monkeypatch):
    import lightgbm_tpu.ops.predict as pred_mod

    bst, X, _ = _train_binary(rng)
    first = bst.predict(X)  # populates the cache

    calls = {"n": 0}
    real = pred_mod.pack_ensemble

    def counting(*args, **kwargs):
        calls["n"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(pred_mod, "pack_ensemble", counting)
    second = bst.predict(X)
    assert calls["n"] == 0  # device-resident ensemble reused, zero repacks
    np.testing.assert_array_equal(first, second)
    # leaf-index predict shares the same cache entry
    bst.predict(X, pred_leaf=True)
    assert calls["n"] == 0


def test_cache_invalidated_by_training(rng):
    bst, X, _ = _train_binary(rng)
    bst.predict(X)
    cache = bst._gbdt._predictor
    assert len(cache._entries) == 1
    bst.update()  # training an iteration must drop device-resident packs
    assert len(cache._entries) == 0
    p = bst.predict(X)
    assert len(cache._entries) == 1
    # sliced predicts get their own entries, bounded by the LRU capacity
    bst.predict(X, num_iteration=2)
    assert len(cache._entries) == 2
    np.testing.assert_array_equal(p, bst.predict(X))


def test_model_load_predict_matches(rng):
    bst, X, _ = _train_binary(rng)
    p = bst.predict(X)
    loaded = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(loaded.predict(X), p, rtol=1e-6, atol=1e-9)


def test_streamed_predict_bit_identical(rng):
    bst, X, _ = _train_binary(rng, n=3000)
    single = bst.predict(X, raw_score=True)
    chunked = bst.predict(X, raw_score=True, pred_chunk_rows=512)
    np.testing.assert_array_equal(single, chunked)
    # non-power-of-two request rounds up to a bucket; tail chunk included
    chunked2 = bst.predict(X, raw_score=True, pred_chunk_rows=700)
    np.testing.assert_array_equal(single, chunked2)


def test_streamed_predict_env_var(rng, monkeypatch):
    from lightgbm_tpu.utils.timer import global_timer

    bst, X, _ = _train_binary(rng, n=2000)
    single = bst.predict(X, raw_score=True)
    monkeypatch.setenv("LGBM_TPU_PREDICT_CHUNK", "256")
    before = global_timer.counters.get("predict_stream_chunks", 0)
    streamed = bst.predict(X, raw_score=True)
    assert global_timer.counters.get("predict_stream_chunks", 0) > before
    np.testing.assert_array_equal(single, streamed)


def test_stream_chunk_policy():
    from lightgbm_tpu.ops.predict import stream_chunk_rows

    assert stream_chunk_rows(1000) == 0          # small batch: single shot
    assert stream_chunk_rows(1000, 256) == 256   # explicit request wins
    assert stream_chunk_rows(1000, 0) == 0       # 0 disables
    assert stream_chunk_rows(1000, 300) == 512   # rounds up to a bucket
    assert stream_chunk_rows(1 << 20) == 1 << 18  # auto for huge batches


def test_sharded_predict_bit_identical(rng, monkeypatch):
    import jax

    assert jax.device_count() == 8  # conftest forces the fake CPU mesh
    bst, X, _ = _train_binary(rng, n=1000)
    single = bst.predict(X, raw_score=True)
    monkeypatch.setenv("LGBM_TPU_PREDICT_SHARD", "1")
    sharded = bst.predict(X, raw_score=True)
    np.testing.assert_array_equal(single, sharded)
    # transformed output and a row count not divisible by 8 (pads + crops)
    single_p = bst.predict(X[:997])
    sharded_p = bst.predict(X[:997])
    np.testing.assert_array_equal(single_p, sharded_p)


def test_sharded_predict_multiclass_ops_level(rng, monkeypatch):
    import jax.numpy as jnp
    from lightgbm_tpu.ops.predict import predict_raw
    from lightgbm_tpu.parallel.predict import predict_raw_sharded

    X = rng.randn(400, 4)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    packed = bst._gbdt._packed()
    X32 = X.astype(np.float32)
    single = np.asarray(predict_raw(packed, jnp.asarray(X32), 3))
    sharded = predict_raw_sharded(packed, X32, 3)
    np.testing.assert_array_equal(single, sharded)


def test_sharded_predict_env_off(rng, monkeypatch):
    from lightgbm_tpu.parallel.predict import sharded_predict_enabled

    monkeypatch.setenv("LGBM_TPU_PREDICT_SHARD", "0")
    assert not sharded_predict_enabled(1 << 20)
    monkeypatch.setenv("LGBM_TPU_PREDICT_SHARD", "1")
    assert sharded_predict_enabled(16)
    monkeypatch.delenv("LGBM_TPU_PREDICT_SHARD")
    assert not sharded_predict_enabled(100)      # small: auto stays off
    assert sharded_predict_enabled(1 << 16)      # auto for big batches


def test_pred_chunk_rows_param_accepted(rng):
    # pred_chunk_rows through params (not kwargs), the CLI-config route
    X = rng.randn(500, 5)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1, "pred_chunk_rows": 128},
                    lgb.Dataset(X, label=y), num_boost_round=3)
    single = bst._gbdt.predict(X.astype(np.float32), raw_score=True)
    via_params = bst.predict(X, raw_score=True)
    np.testing.assert_array_equal(np.asarray(single)[:, 0]
                                  if np.asarray(single).ndim > 1
                                  else np.asarray(single), via_params)

def test_predictor_cache_thread_safety_under_invalidate(rng):
    """Regression: PredictorCache's OrderedDict was mutated without a lock;
    concurrent predicts racing an invalidate() could corrupt the LRU or
    serve a stale-version pack. Hammer predict from many threads across
    repeated invalidations and assert every output stays bit-identical."""
    import threading

    bst, X, _ = _train_binary(rng)
    cache = bst._gbdt._predictor
    expected = bst.predict(X)
    expected_sliced = bst.predict(X, num_iteration=2)
    errors = []
    stop = threading.Event()

    def hammer():
        try:
            while not stop.is_set():
                np.testing.assert_array_equal(bst.predict(X), expected)
                np.testing.assert_array_equal(
                    bst.predict(X, num_iteration=2), expected_sliced)
        except Exception as exc:
            errors.append(exc)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(30):
        cache.invalidate()
    stop.set()
    for t in threads:
        t.join()
    assert not errors, errors[0]
    # the cache is still a consistent LRU afterwards: bounded and reusable
    bst.predict(X)
    assert len(cache._entries) <= cache.capacity
    np.testing.assert_array_equal(bst.predict(X), expected)
