import numpy as np
import jax.numpy as jnp
import pytest

from lightgbm_tpu.models.tree import Tree, MISSING_NONE, MISSING_NAN
from lightgbm_tpu.ops.predict import pack_ensemble, predict_raw, predict_leaf_indices
from tests.test_tree import make_simple_tree


def test_packed_matches_host_predict(rng):
    trees = [make_simple_tree() for _ in range(3)]
    trees[1].shrink(0.5)
    packed = pack_ensemble(trees)
    X = rng.uniform(-1, 5, size=(64, 2)).astype(np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X)))
    expected = np.array([[sum(t.predict(row) for t in trees)] for row in X])
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_packed_handles_nan_and_categorical(rng):
    t = Tree(max_leaves=3)
    right = t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                    threshold_double=0.5, default_left=True, missing_type=MISSING_NAN,
                    gain=1.0, left_value=-1.0, right_value=1.0, left_count=1, right_count=1,
                    left_weight=1.0, right_weight=1.0, parent_value=0.0)
    t.split_categorical(leaf=right, feature_inner=1, real_feature=1,
                        bin_bitset=[0b110], value_bitset=[0b110],
                        missing_type=MISSING_NONE, gain=1.0,
                        left_value=5.0, right_value=7.0, left_count=1, right_count=1,
                        left_weight=1.0, right_weight=1.0, parent_value=1.0)
    packed = pack_ensemble([t])
    X = np.array([
        [np.nan, 0.0],   # nan -> default left -> -1
        [1.0, 1.0],      # right, cat 1 in {1,2} -> 5
        [1.0, 2.0],      # -> 5
        [1.0, 3.0],      # -> 7
        [1.0, np.nan],   # cat nan -> right -> 7
    ], dtype=np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X)))[:, 0]
    np.testing.assert_allclose(out, [-1.0, 5.0, 5.0, 7.0, 7.0])
    host = np.array([t.predict(row) for row in X])
    np.testing.assert_allclose(out, host)


def test_multiclass_grouping(rng):
    # 2 iterations x 2 classes = 4 trees; class k sums trees k, k+2
    trees = []
    for v in (1.0, 10.0, 100.0, 1000.0):
        t = Tree(max_leaves=2)
        t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                threshold_double=0.5, default_left=False, missing_type=MISSING_NONE,
                gain=1.0, left_value=v, right_value=-v, left_count=1, right_count=1,
                left_weight=1.0, right_weight=1.0, parent_value=0.0)
        trees.append(t)
    packed = pack_ensemble(trees)
    X = np.array([[0.0], [1.0]], dtype=np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X), num_tree_per_iteration=2))
    np.testing.assert_allclose(out, [[101.0, 1010.0], [-101.0, -1010.0]])


def test_leaf_indices(rng):
    trees = [make_simple_tree()]
    packed = pack_ensemble(trees)
    X = np.array([[0.0, 0.0], [1.0, 2.0], [1.0, 3.0]], dtype=np.float32)
    leaves = np.asarray(predict_leaf_indices(packed, jnp.asarray(X)))
    assert leaves[:, 0].tolist() == [0, 1, 2]


def test_stump_only_model():
    t = Tree(max_leaves=2)
    t.as_constant_tree(0.25)
    packed = pack_ensemble([t])
    X = np.zeros((4, 1), dtype=np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X)))
    np.testing.assert_allclose(out, 0.25)


def test_threshold_downcast_preserves_f32_decisions():
    import math
    # threshold not representable in f32, just above a representable value
    x = np.float32(1.0000001)
    t64 = float(x) + 1e-12  # x <= t64 in f64
    tree = Tree(max_leaves=2)
    tree.split(0, 0, 0, 1, t64, False, MISSING_NONE, 1.0, -1.0, 1.0, 1, 1, 1.0, 1.0, 0.0)
    packed = pack_ensemble([tree])
    X = jnp.asarray(np.array([[x], [np.nextafter(x, np.float32(2.0))]], dtype=np.float32))
    out = np.asarray(predict_raw(packed, X))[:, 0]
    assert out[0] == -1.0  # x <= t64 -> left, preserved after downcast
    assert out[1] == 1.0
