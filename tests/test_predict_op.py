import jax
import numpy as np
import jax.numpy as jnp
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.common import MISSING_ZERO, K_ZERO_THRESHOLD
from lightgbm_tpu.models.tree import Tree, MISSING_NONE, MISSING_NAN
from lightgbm_tpu.ops.predict import pack_ensemble, predict_raw, predict_leaf_indices
from lightgbm_tpu.utils.log import LightGBMError
from tests.test_tree import make_simple_tree


# --------------------------------------------------------------- reference
# Verbatim copy of the pre-fusion per-tree traversal (one vmap lane per
# tree, one X gather per tree per level): the bit-identity oracle for the
# fused level-synchronous path.

def _ref_tree_leaf_index(packed, tree_idx, X, max_depth):
    sf = packed.split_feature[tree_idx]
    th = packed.threshold[tree_idx]
    dt = packed.decision_type[tree_idx]
    lc = packed.left_child[tree_idx]
    rc = packed.right_child[tree_idx]
    co = packed.cat_offset[tree_idx]
    cn = packed.cat_n_words[tree_idx]
    n = X.shape[0]
    single_leaf = packed.num_leaves[tree_idx] <= 1

    def body(_, node):
        active = node >= 0
        nd = jnp.maximum(node, 0)
        feat = sf[nd]
        fval = jnp.take_along_axis(X, feat[:, None], axis=1)[:, 0]
        d = dt[nd]
        is_cat = (d & 1) > 0
        default_left = (d & 2) > 0
        missing_type = (d >> 2) & 3
        is_nan = jnp.isnan(fval)
        fval_num = jnp.where(is_nan & (missing_type != MISSING_NAN), 0.0, fval)
        is_missing = ((missing_type == MISSING_ZERO)
                      & (jnp.abs(fval_num) <= K_ZERO_THRESHOLD)) | (
            (missing_type == MISSING_NAN) & jnp.isnan(fval_num))
        go_left_num = jnp.where(is_missing, default_left, fval_num <= th[nd])
        int_fval = jnp.where(is_nan, -1, fval.astype(jnp.int32))
        word_idx = jnp.clip(int_fval, 0, None) // 32
        bit_idx = jnp.clip(int_fval, 0, None) % 32
        in_range = (int_fval >= 0) & (word_idx < cn[nd])
        word = packed.cat_words[jnp.clip(co[nd] + word_idx, 0,
                                         packed.cat_words.shape[0] - 1)]
        go_left_cat = in_range & (((word >> bit_idx.astype(jnp.uint32)) & 1) > 0)
        go_left = jnp.where(is_cat, go_left_cat, go_left_num)
        nxt = jnp.where(go_left, lc[nd], rc[nd])
        return jnp.where(active, nxt, node)

    node0 = jnp.zeros(n, dtype=jnp.int32)
    node = jax.lax.fori_loop(0, max_depth, body, node0)
    return jnp.where(single_leaf, 0, ~node)


def _ref_predict_raw(packed, X, num_tree_per_iteration=1):
    T = packed.num_trees
    if T == 0:
        return np.zeros((X.shape[0], num_tree_per_iteration), dtype=X.dtype)

    def tree_score(k):
        leaf = _ref_tree_leaf_index(packed, k, X, packed.max_depth)
        base = packed.leaf_value[k][leaf]
        if not packed.linear:
            return base
        feats = packed.lin_feat[k][leaf]
        used = feats >= 0
        fv = jnp.take_along_axis(X, jnp.clip(feats, 0, X.shape[1] - 1), axis=1)
        bad = (used & ~jnp.isfinite(fv)).any(axis=1)
        fv = jnp.where(used, fv, 0.0)
        lin = packed.lin_const[k][leaf] + jnp.where(
            used, packed.lin_coeff[k][leaf] * fv, 0.0).sum(axis=1)
        return jnp.where(bad, base, lin)

    scores = jax.vmap(tree_score)(jnp.arange(T, dtype=jnp.int32))
    scores = scores.reshape(T // num_tree_per_iteration,
                            num_tree_per_iteration, X.shape[0])
    return np.asarray(scores.sum(axis=0).T)


def _nan_cat_tree():
    t = Tree(max_leaves=3)
    right = t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                    threshold_double=0.5, default_left=True,
                    missing_type=MISSING_NAN, gain=1.0, left_value=-1.0,
                    right_value=1.0, left_count=1, right_count=1,
                    left_weight=1.0, right_weight=1.0, parent_value=0.0)
    t.split_categorical(leaf=right, feature_inner=1, real_feature=1,
                        bin_bitset=[0b110], value_bitset=[0b110],
                        missing_type=MISSING_NONE, gain=1.0,
                        left_value=5.0, right_value=7.0, left_count=1,
                        right_count=1, left_weight=1.0, right_weight=1.0,
                        parent_value=1.0)
    return t


def test_packed_matches_host_predict(rng):
    trees = [make_simple_tree() for _ in range(3)]
    trees[1].shrink(0.5)
    packed = pack_ensemble(trees)
    X = rng.uniform(-1, 5, size=(64, 2)).astype(np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X)))
    expected = np.array([[sum(t.predict(row) for t in trees)] for row in X])
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_packed_handles_nan_and_categorical(rng):
    t = Tree(max_leaves=3)
    right = t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                    threshold_double=0.5, default_left=True, missing_type=MISSING_NAN,
                    gain=1.0, left_value=-1.0, right_value=1.0, left_count=1, right_count=1,
                    left_weight=1.0, right_weight=1.0, parent_value=0.0)
    t.split_categorical(leaf=right, feature_inner=1, real_feature=1,
                        bin_bitset=[0b110], value_bitset=[0b110],
                        missing_type=MISSING_NONE, gain=1.0,
                        left_value=5.0, right_value=7.0, left_count=1, right_count=1,
                        left_weight=1.0, right_weight=1.0, parent_value=1.0)
    packed = pack_ensemble([t])
    X = np.array([
        [np.nan, 0.0],   # nan -> default left -> -1
        [1.0, 1.0],      # right, cat 1 in {1,2} -> 5
        [1.0, 2.0],      # -> 5
        [1.0, 3.0],      # -> 7
        [1.0, np.nan],   # cat nan -> right -> 7
    ], dtype=np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X)))[:, 0]
    np.testing.assert_allclose(out, [-1.0, 5.0, 5.0, 7.0, 7.0])
    host = np.array([t.predict(row) for row in X])
    np.testing.assert_allclose(out, host)


def test_multiclass_grouping(rng):
    # 2 iterations x 2 classes = 4 trees; class k sums trees k, k+2
    trees = []
    for v in (1.0, 10.0, 100.0, 1000.0):
        t = Tree(max_leaves=2)
        t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                threshold_double=0.5, default_left=False, missing_type=MISSING_NONE,
                gain=1.0, left_value=v, right_value=-v, left_count=1, right_count=1,
                left_weight=1.0, right_weight=1.0, parent_value=0.0)
        trees.append(t)
    packed = pack_ensemble(trees)
    X = np.array([[0.0], [1.0]], dtype=np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X), num_tree_per_iteration=2))
    np.testing.assert_allclose(out, [[101.0, 1010.0], [-101.0, -1010.0]])


def test_leaf_indices(rng):
    trees = [make_simple_tree()]
    packed = pack_ensemble(trees)
    X = np.array([[0.0, 0.0], [1.0, 2.0], [1.0, 3.0]], dtype=np.float32)
    leaves = np.asarray(predict_leaf_indices(packed, jnp.asarray(X)))
    assert leaves[:, 0].tolist() == [0, 1, 2]


def test_stump_only_model():
    t = Tree(max_leaves=2)
    t.as_constant_tree(0.25)
    packed = pack_ensemble([t])
    X = np.zeros((4, 1), dtype=np.float32)
    out = np.asarray(predict_raw(packed, jnp.asarray(X)))
    np.testing.assert_allclose(out, 0.25)


# ------------------------------------- fused traversal bit-identity locks

def _trained_ensembles(rng):
    """(name, packed, X, C) across ensemble types: trained numerical with
    NaNs, hand-built categorical + NaN, trained multiclass, linear trees."""
    out = []
    Xn = rng.randn(400, 5).astype(np.float64)
    Xn[rng.rand(400, 5) < 0.1] = np.nan
    yn = (np.nan_to_num(Xn[:, 0]) + 0.5 * np.nan_to_num(Xn[:, 1]) > 0)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1, "use_missing": True},
                    lgb.Dataset(Xn, label=yn.astype(float)),
                    num_boost_round=8)
    out.append(("numerical_nan", bst._gbdt._packed(),
                Xn.astype(np.float32), 1))

    cat_trees = [_nan_cat_tree(), make_simple_tree()]
    Xc = np.array([[np.nan, 0.0], [1.0, 1.0], [1.0, 2.0], [1.0, 3.0],
                   [1.0, np.nan], [0.2, 1.5], [0.9, 2.5]], dtype=np.float32)
    out.append(("categorical_nan", pack_ensemble(cat_trees), Xc, 1))

    Xm = rng.randn(300, 4).astype(np.float64)
    ym = ((Xm[:, 0] > 0).astype(int) + (Xm[:, 1] > 0).astype(int)).astype(float)
    bm = lgb.train({"objective": "multiclass", "num_class": 3,
                    "num_leaves": 7, "verbosity": -1},
                   lgb.Dataset(Xm, label=ym), num_boost_round=5)
    out.append(("multiclass", bm._gbdt._packed(), Xm.astype(np.float32), 3))

    Xl = rng.rand(300, 3).astype(np.float64)
    yl = 2.0 * Xl[:, 0] - Xl[:, 1] + 0.1 * rng.randn(300)
    bl = lgb.train({"objective": "regression", "num_leaves": 7,
                    "linear_tree": True, "verbosity": -1},
                   lgb.Dataset(Xl, label=yl), num_boost_round=5)
    Xl32 = Xl.astype(np.float32).copy()
    Xl32[0, 1] = np.nan  # linear fallback-to-constant path
    out.append(("linear", bl._gbdt._packed(), Xl32, 1))
    return out


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_fused_bit_identical_to_per_tree_reference(rng):
    for name, packed, X, C in _trained_ensembles(rng):
        got = np.asarray(predict_raw(packed, jnp.asarray(X), C))
        ref = _ref_predict_raw(packed, jnp.asarray(X), C)
        np.testing.assert_array_equal(got, ref, err_msg=name)


def test_fused_leaf_indices_bit_identical(rng):
    for name, packed, X, C in _trained_ensembles(rng):
        got = np.asarray(predict_leaf_indices(packed, jnp.asarray(X)))
        ref = np.stack([np.asarray(_ref_tree_leaf_index(
            packed, k, jnp.asarray(X), packed.max_depth))
            for k in range(packed.num_trees)], axis=1)
        np.testing.assert_array_equal(got, ref, err_msg=name)


def test_pallas_interpret_bit_identical(rng):
    from lightgbm_tpu.ops.predict_pallas import pallas_predict_raw

    for name, packed, X, C in _trained_ensembles(rng):
        if packed.linear:
            continue  # linear ensembles keep the XLA path
        got = np.asarray(pallas_predict_raw(packed, jnp.asarray(X), C,
                                            tile_rows=128, interpret=True))
        ref = np.asarray(predict_raw(packed, jnp.asarray(X), C))
        np.testing.assert_array_equal(got, ref, err_msg=name)


def test_pallas_env_flag_auto_interprets_off_tpu(rng, monkeypatch):
    # LGBM_TPU_PREDICT_PALLAS=1 must work end to end on CPU: predict_raw
    # has to pass interpret=True itself (Mosaic only compiles on TPU)
    monkeypatch.delenv("LGBM_TPU_PREDICT_PALLAS", raising=False)
    name, packed, X, C = _trained_ensembles(rng)[0]
    ref = np.asarray(predict_raw(packed, jnp.asarray(X), C))
    monkeypatch.setenv("LGBM_TPU_PREDICT_PALLAS", "1")
    got = np.asarray(predict_raw(packed, jnp.asarray(X), C))
    np.testing.assert_array_equal(got, ref, err_msg=name)


def test_ragged_tree_count_is_fatal():
    trees = [make_simple_tree() for _ in range(5)]
    packed = pack_ensemble(trees)
    X = jnp.zeros((3, 2), dtype=jnp.float32)
    with pytest.raises(LightGBMError, match="whole iterations"):
        predict_raw(packed, X, num_tree_per_iteration=2)


def test_multiclass_partial_iteration_predict(rng):
    # num_iteration slicing on a multiclass booster: T = 2 iters * 3
    # classes; the slice must stay a whole-iteration multiple and match
    # the host sum over trees[:2*C]
    X = rng.randn(200, 4)
    y = ((X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)).astype(float)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 7, "verbosity": -1},
                    lgb.Dataset(X, label=y), num_boost_round=4)
    C = 3
    out = bst.predict(X, raw_score=True, num_iteration=2)
    trees = bst._gbdt.models[: 2 * C]
    host = np.zeros((X.shape[0], C))
    for m, t in enumerate(trees):
        host[:, m % C] += [t.predict(row) for row in X]
    np.testing.assert_allclose(out, host, rtol=1e-5, atol=1e-6)


def test_predict_routes_f64_when_x64_enabled():
    # a threshold whose decision differs between f32 and f64 inputs: the
    # old forced-f32 upload sent both rows left; x64 callers must keep
    # their f64 values end to end
    x32 = np.float64(np.float32(1.0000001))
    t64 = x32 + 1e-12
    tree = Tree(max_leaves=2)
    tree.split(0, 0, 0, 1, t64, False, MISSING_NONE, 1.0, -1.0, 1.0,
               1, 1, 1.0, 1.0, 0.0)
    jax.config.update("jax_enable_x64", True)
    try:
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.models.gbdt import GBDT

        g = GBDT(Config({}), None, None)
        g.models = [tree]
        X = np.array([[x32], [t64 + 1e-12]], dtype=np.float64)
        out = g.predict(X, raw_score=True)
        assert out[0] == -1.0  # x32 <= t64 in f64
        assert out[1] == 1.0   # t64 + eps > t64: right — lost under f32
    finally:
        jax.config.update("jax_enable_x64", False)


def test_threshold_downcast_preserves_f32_decisions():
    import math
    # threshold not representable in f32, just above a representable value
    x = np.float32(1.0000001)
    t64 = float(x) + 1e-12  # x <= t64 in f64
    tree = Tree(max_leaves=2)
    tree.split(0, 0, 0, 1, t64, False, MISSING_NONE, 1.0, -1.0, 1.0, 1, 1, 1.0, 1.0, 0.0)
    packed = pack_ensemble([tree])
    X = jnp.asarray(np.array([[x], [np.nextafter(x, np.float32(2.0))]], dtype=np.float32))
    out = np.asarray(predict_raw(packed, X))[:, 0]
    assert out[0] == -1.0  # x <= t64 -> left, preserved after downcast
    assert out[1] == 1.0
