"""Quantized-gradient training tests (GradientDiscretizer,
src/treelearner/gradient_discretizer.{hpp,cpp})."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _binary(rng, n=3000, f=10):
    X = rng.randn(n, f)
    logit = X[:, 0] * 2.0 - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def _auc(y, p):
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    n_pos = y.sum()
    n_neg = len(y) - n_pos
    return (ranks[y == 1].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def _train(X, y, extra=None, rounds=30):
    params = dict(objective="binary", num_leaves=15, learning_rate=0.1,
                  min_data_in_leaf=20, verbosity=-1, **(extra or {}))
    return lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=rounds)


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_quantized_auc_parity(rng):
    X, y = _binary(rng)
    auc_float = _auc(y, _train(X, y).predict(X))
    auc_quant = _auc(y, _train(X, y, {
        "use_quantized_grad": True, "num_grad_quant_bins": 4,
        "quant_train_renew_leaf": True}).predict(X))
    assert auc_quant > auc_float - 0.003, (auc_quant, auc_float)


def test_quantized_more_bins_closer(rng):
    X, y = _binary(rng)
    auc_float = _auc(y, _train(X, y, rounds=15).predict(X))
    auc16 = _auc(y, _train(X, y, {
        "use_quantized_grad": True, "num_grad_quant_bins": 16,
        "quant_train_renew_leaf": True}, rounds=15).predict(X))
    assert auc16 > auc_float - 0.005


def test_quantized_nearest_rounding(rng):
    X, y = _binary(rng, n=1500)
    bst = _train(X, y, {"use_quantized_grad": True,
                        "stochastic_rounding": False}, rounds=10)
    assert _auc(y, bst.predict(X)) > 0.85


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_quantized_data_parallel_matches_serial(rng):
    """Same seed -> identical int gradients -> the data-parallel integer
    psum_scatter (int16-narrowed here: 2000 rows x 4 bins < 32000) must
    reproduce the serial quantized learner exactly."""
    X, y = _binary(rng, n=2000)
    q = {"use_quantized_grad": True, "quant_train_renew_leaf": True}
    p_serial = _train(X, y, q, rounds=10).predict(X)
    p_dp = _train(X, y, {**q, "tree_learner": "data"}, rounds=10).predict(X)
    np.testing.assert_allclose(p_dp, p_serial, rtol=1e-4, atol=1e-5)


def test_quantized_feature_parallel(rng):
    X, y = _binary(rng, n=2000)
    q = {"use_quantized_grad": True}
    p_serial = _train(X, y, q, rounds=10).predict(X)
    p_fp = _train(X, y, {**q, "tree_learner": "feature"}, rounds=10).predict(X)
    np.testing.assert_allclose(p_fp, p_serial, rtol=1e-4, atol=1e-5)


def test_quantized_voting_fatal(rng):
    X, y = _binary(rng, n=500)
    with pytest.raises(Exception):
        _train(X, y, {"use_quantized_grad": True, "tree_learner": "voting"},
               rounds=1)


def test_quantized_multiclass(rng):
    n = 1500
    X = rng.randn(n, 6)
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0).astype(int)
    bst = lgb.train({"objective": "multiclass", "num_class": 3,
                     "num_leaves": 15, "use_quantized_grad": True,
                     "quant_train_renew_leaf": True, "verbosity": -1},
                    lgb.Dataset(X, label=y.astype(np.float64)),
                    num_boost_round=10)
    acc = np.mean(bst.predict(X).argmax(axis=1) == y)
    assert acc > 0.85, acc
