"""Ranking tests on the reference's examples/lambdarank data."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

RANK_TRAIN = "/root/reference/examples/lambdarank/rank.train"
RANK_TEST = "/root/reference/examples/lambdarank/rank.test"


def test_lambdarank_reference_example():
    ds = lgb.Dataset(RANK_TRAIN)
    dv = lgb.Dataset(RANK_TEST, reference=ds)
    rec = {}
    bst = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                     "eval_at": "1,3,5", "num_leaves": 31, "learning_rate": 0.1,
                     "verbosity": -1, "min_data_in_leaf": 50, "min_sum_hessian_in_leaf": 5.0},
                    ds, num_boost_round=30, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(rec)])
    ndcg5 = rec["valid_0"]["ndcg@5"]
    assert ndcg5[-1] > 0.55, f"ndcg@5 too low: {ndcg5[-1]}"
    assert ndcg5[-1] > ndcg5[0] - 0.02  # learning, not diverging


def test_rank_xendcg():
    ds = lgb.Dataset(RANK_TRAIN)
    rec = {}
    dv = lgb.Dataset(RANK_TEST, reference=ds)
    bst = lgb.train({"objective": "rank_xendcg", "metric": "ndcg", "eval_at": "5",
                     "num_leaves": 31, "verbosity": -1, "min_data_in_leaf": 1,
                     "min_sum_hessian_in_leaf": 1e-3},
                    ds, num_boost_round=20, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(rec)])
    assert rec["valid_0"]["ndcg@5"][-1] > 0.5


def test_ndcg_metric_values():
    # hand-computable case: one query, 4 docs
    from lightgbm_tpu.metrics import create_metric
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.metadata import Metadata
    import jax.numpy as jnp

    md = Metadata(4)
    md.set_label(np.array([3.0, 2.0, 1.0, 0.0]))
    md.set_query(np.array([4]))
    cfg = Config({"eval_at": "2,4"})
    m = create_metric("ndcg", cfg)
    m.init(md, 4)
    # perfect ranking
    perfect = m.eval(jnp.asarray([4.0, 3.0, 2.0, 1.0]), None)
    assert perfect[0] == pytest.approx(1.0, abs=1e-6)
    assert perfect[1] == pytest.approx(1.0, abs=1e-6)
    # reversed ranking
    rev = m.eval(jnp.asarray([1.0, 2.0, 3.0, 4.0]), None)
    assert rev[0] < 0.3
    g = [0, 1, 3, 7]
    disc = 1.0 / np.log2(np.arange(4) + 2.0)
    dcg_rev = np.sum(np.array([g[0], g[1], g[2], g[3]]) * disc)
    max_dcg = np.sum(np.array([g[3], g[2], g[1], g[0]]) * disc)
    assert rev[1] == pytest.approx(dcg_rev / max_dcg, abs=1e-5)


def test_map_metric():
    from lightgbm_tpu.metrics import create_metric
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.metadata import Metadata
    import jax.numpy as jnp

    md = Metadata(4)
    md.set_label(np.array([1.0, 0.0, 1.0, 0.0]))
    md.set_query(np.array([4]))
    m = create_metric("map", Config({"eval_at": "4"}))
    m.init(md, 4)
    # ranking: rel, not, rel, not -> AP = (1/1 + 2/3)/2
    val = m.eval(jnp.asarray([4.0, 3.0, 2.0, 1.0]), None)
    assert val[0] == pytest.approx((1.0 + 2.0 / 3.0) / 2.0, abs=1e-6)


def test_lambdarank_position_debias():
    """Position-debiased lambdarank (rank_objective.hpp:43-90,296-340):
    positions accepted via Dataset, bias factors iteratively estimated,
    NDCG no worse on unbiased data."""
    rng = np.random.RandomState(5)

    def load(path):
        ds = lgb.Dataset(path)
        ds.construct()
        return ds

    def ndcg(params, position=None):
        ds = lgb.Dataset(RANK_TRAIN, position=position)
        dv = lgb.Dataset(RANK_TEST, reference=ds)
        rec = {}
        lgb.train(params, ds, num_boost_round=20, valid_sets=[dv],
                  callbacks=[lgb.record_evaluation(rec)])
        return rec["valid_0"]["ndcg@5"][-1]

    params = {"objective": "lambdarank", "metric": "ndcg", "eval_at": "5",
              "num_leaves": 31, "learning_rate": 0.1, "verbosity": -1,
              "min_data_in_leaf": 50, "min_sum_hessian_in_leaf": 5.0,
              "lambdarank_position_bias_regularization": 0.1}
    base = ndcg(params)
    # unbiased data with random positions: debias must not hurt
    n = lgb.Dataset(RANK_TRAIN)
    n.construct()
    num_rows = n._handle.num_data
    positions = rng.randint(0, 10, size=num_rows)
    debiased = ndcg(params, position=positions)
    assert debiased > base - 0.02, (debiased, base)


def test_position_bias_factors_move():
    """The per-position bias factors are actually updated during training."""
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.objectives import create_objective

    rng = np.random.RandomState(3)
    ds = lgb.Dataset(RANK_TRAIN)
    ds.construct()
    core = ds._handle
    positions = rng.randint(0, 6, size=core.num_data)
    core.metadata.set_positions(positions)
    cfg = Config({"objective": "lambdarank", "verbosity": -1})
    obj = create_objective("lambdarank", cfg)
    obj.init(core.metadata, core.num_data)
    import jax.numpy as jnp

    score = jnp.zeros(core.num_data, dtype=jnp.float32)
    obj.get_gradients(score)
    b1 = np.asarray(obj._pos_biases).copy()
    obj.get_gradients(score)
    b2 = np.asarray(obj._pos_biases)
    assert np.any(b1 != 0.0) or np.any(b2 != b1)
