"""Refit + snapshot tests (GBDT::RefitTree gbdt.cpp:266-305, snapshot_freq
gbdt.cpp:258-262)."""
import os

import numpy as np

import lightgbm_tpu as lgb
from lightgbm_tpu import cli


def _data(rng, n=1200, shift=0.0):
    X = rng.randn(n, 5)
    y = (X[:, 0] * 2.0 - X[:, 1] + shift + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def test_booster_refit_improves_on_new_data(rng):
    X, y = _data(rng)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=10)
    X2, y2 = _data(rng, shift=0.7)  # shifted distribution
    acc_old = np.mean((bst.predict(X2) > 0.5) == y2)
    refitted = bst.refit(X2, y2, decay_rate=0.5)
    acc_new = np.mean((refitted.predict(X2) > 0.5) == y2)
    assert acc_new >= acc_old - 1e-9, (acc_new, acc_old)
    # structure must be identical, only leaf values change
    t_old = bst.dump_model()["tree_info"]
    t_new = refitted.dump_model()["tree_info"]
    assert len(t_old) == len(t_new)

    def structure(node):
        if "split_feature" not in node:
            return None
        return (node["split_feature"], round(float(node["threshold"]), 6)
                if not isinstance(node["threshold"], str) else node["threshold"],
                structure(node["left_child"]), structure(node["right_child"]))

    for a, b in zip(t_old, t_new):
        assert structure(a["tree_structure"]) == structure(b["tree_structure"])


def test_cli_refit_and_snapshots(rng, tmp_path):
    X, y = _data(rng, n=800)
    train_path = str(tmp_path / "refit.train")
    np.savetxt(train_path, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")
    model_path = str(tmp_path / "model.txt")
    rc = cli.run([f"data={train_path}", "objective=binary", "num_trees=6",
                  "num_leaves=7", f"output_model={model_path}",
                  "snapshot_freq=2", "device_type=cpu", "verbosity=-1"])
    assert rc == 0
    assert os.path.exists(model_path + ".snapshot_iter_2")
    assert os.path.exists(model_path + ".snapshot_iter_4")

    refit_out = str(tmp_path / "refit_model.txt")
    rc = cli.run(["task=refit", f"data={train_path}",
                  f"input_model={model_path}", "objective=binary",
                  f"output_model={refit_out}", "device_type=cpu",
                  "verbosity=-1"])
    assert rc == 0
    pred = lgb.Booster(model_file=refit_out).predict(X)
    assert np.mean((pred > 0.5) == y) > 0.8
