"""Bagging and GOSS sample-strategy tests (bagging.hpp / goss.hpp parity)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=3000, f=10, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def test_bagging_trains_and_predicts():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "bagging_fraction": 0.5, "bagging_freq": 1,
                     "verbosity": -1}, ds, num_boost_round=25)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc


def test_bagging_score_consistency():
    """Out-of-bag rows must get score updates: the internal train score must
    equal a fresh full prediction."""
    X, y = _make_binary(n=1200)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "bagging_fraction": 0.4, "bagging_freq": 2,
                     "verbosity": -1}, ds, num_boost_round=8)
    internal = np.asarray(bst._gbdt.score[0])
    fresh = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, fresh, rtol=1e-4, atol=1e-4)


def test_bagging_bag_sizes():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.sample_strategy import create_sample_strategy

    cfg = Config({"bagging_fraction": 0.3, "bagging_freq": 5,
                  "objective": "binary"})
    strat = create_sample_strategy(cfg, 1000, None, 1)
    bag0, _, _ = strat.bagging(0, None, None)
    assert len(bag0) == 300
    bag1, _, _ = strat.bagging(1, None, None)
    assert bag1 is bag0  # reused until the next resample boundary
    bag5, _, _ = strat.bagging(5, None, None)
    assert not np.array_equal(bag5, bag0)


def test_pos_neg_bagging():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.metadata import Metadata
    from lightgbm_tpu.models.sample_strategy import create_sample_strategy

    y = np.concatenate([np.ones(200), np.zeros(800)])
    md = Metadata(1000)
    md.set_label(y)
    cfg = Config({"pos_bagging_fraction": 1.0, "neg_bagging_fraction": 0.25,
                  "bagging_freq": 1, "objective": "binary"})
    strat = create_sample_strategy(cfg, 1000, md, 1)
    bag, _, _ = strat.bagging(0, None, None)
    n_pos = (y[bag] > 0).sum()
    n_neg = (y[bag] == 0).sum()
    assert n_pos == 200  # all positives kept
    assert 120 < n_neg < 280  # ~25% of negatives


def test_goss_trains_and_predicts():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "data_sample_strategy": "goss", "learning_rate": 0.2,
                     "top_rate": 0.2, "other_rate": 0.1,
                     "verbosity": -1}, ds, num_boost_round=25)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc


def test_goss_warmup_and_bag_size():
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.sample_strategy import GOSSStrategy

    cfg = Config({"data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.2, "other_rate": 0.1, "objective": "binary"})
    strat = GOSSStrategy(cfg, 1000, None, 1)
    g = jnp.asarray(np.random.RandomState(0).randn(1000).astype(np.float32))
    h = jnp.ones(1000, dtype=jnp.float32)
    bag, _, _ = strat.bagging(0, g, h)  # warm-up: 0 < 1/0.5
    assert bag is None
    bag, g2, h2 = strat.bagging(2, g, h)
    assert len(bag) == 300  # 20% top + 10% sampled
    # sampled small-grad rows were rescaled by (1-0.2)/0.1 = 8
    ratio = np.asarray(h2)
    assert np.isclose(sorted(np.unique(ratio.round(4)))[-1], 8.0)


def test_goss_legacy_boosting_alias():
    X, y = _make_binary(n=800)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "goss",
                     "num_leaves": 7, "verbosity": -1}, ds, num_boost_round=5)
    assert bst.predict(X).shape == (800,)
