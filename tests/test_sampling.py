"""Bagging and GOSS sample-strategy tests (bagging.hpp / goss.hpp parity)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _make_binary(n=3000, f=10, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    logit = 1.5 * X[:, 0] - X[:, 1] + 0.5 * X[:, 2] * X[:, 3]
    y = (logit + rng.randn(n) * 0.3 > 0).astype(np.float64)
    return X, y


def test_bagging_trains_and_predicts():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "bagging_fraction": 0.5, "bagging_freq": 1,
                     "verbosity": -1}, ds, num_boost_round=25)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc


def test_bagging_score_consistency():
    """Out-of-bag rows must get score updates: the internal train score must
    equal a fresh full prediction."""
    X, y = _make_binary(n=1200)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "bagging_fraction": 0.4, "bagging_freq": 2,
                     "verbosity": -1}, ds, num_boost_round=8)
    internal = np.asarray(bst._gbdt.score[0])
    fresh = bst.predict(X, raw_score=True)
    np.testing.assert_allclose(internal, fresh, rtol=1e-4, atol=1e-4)


def test_bagging_bag_sizes():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.sample_strategy import create_sample_strategy

    cfg = Config({"bagging_fraction": 0.3, "bagging_freq": 5,
                  "objective": "binary"})
    strat = create_sample_strategy(cfg, 1000, None, 1)
    bag0, _, _ = strat.bagging(0, None, None)
    assert len(bag0) == 300
    bag1, _, _ = strat.bagging(1, None, None)
    assert bag1 is bag0  # reused until the next resample boundary
    bag5, _, _ = strat.bagging(5, None, None)
    assert not np.array_equal(bag5, bag0)


def test_pos_neg_bagging():
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.metadata import Metadata
    from lightgbm_tpu.models.sample_strategy import create_sample_strategy

    y = np.concatenate([np.ones(200), np.zeros(800)])
    md = Metadata(1000)
    md.set_label(y)
    cfg = Config({"pos_bagging_fraction": 1.0, "neg_bagging_fraction": 0.25,
                  "bagging_freq": 1, "objective": "binary"})
    strat = create_sample_strategy(cfg, 1000, md, 1)
    bag, _, _ = strat.bagging(0, None, None)
    n_pos = (y[bag] > 0).sum()
    n_neg = (y[bag] == 0).sum()
    assert n_pos == 200  # all positives kept
    assert 120 < n_neg < 280  # ~25% of negatives


def test_goss_trains_and_predicts():
    X, y = _make_binary()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15,
                     "data_sample_strategy": "goss", "learning_rate": 0.2,
                     "top_rate": 0.2, "other_rate": 0.1,
                     "verbosity": -1}, ds, num_boost_round=25)
    acc = np.mean((bst.predict(X) > 0.5) == y)
    assert acc > 0.9, acc


def test_goss_warmup_and_bag_size():
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.sample_strategy import GOSSStrategy

    cfg = Config({"data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.2, "other_rate": 0.1, "objective": "binary"})
    strat = GOSSStrategy(cfg, 1000, None, 1)
    g = jnp.asarray(np.random.RandomState(0).randn(1000).astype(np.float32))
    h = jnp.ones(1000, dtype=jnp.float32)
    bag, _, _ = strat.bagging(0, g, h)  # warm-up: 0 < 1/0.5
    assert bag is None
    bag, g2, h2 = strat.bagging(2, g, h)
    assert len(bag) == 300  # 20% top + 10% sampled
    # sampled small-grad rows were rescaled by (1-0.2)/0.1 = 8
    ratio = np.asarray(h2)
    assert np.isclose(sorted(np.unique(ratio.round(4)))[-1], 8.0)


def test_goss_legacy_boosting_alias():
    X, y = _make_binary(n=800)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "boosting": "goss",
                     "num_leaves": 7, "verbosity": -1}, ds, num_boost_round=5)
    assert bst.predict(X).shape == (800,)


@pytest.mark.parametrize("shape", [(1000,), (3, 1000)], ids=["binary", "multiclass"])
def test_goss_device_bag_matches_host_bag(monkeypatch, shape):
    """Round 8: the device-resident GOSS select must pick the SAME bag and
    produce the SAME rescaled gradients as the host path, bit for bit —
    both consume the MT19937 stream identically (choice(n, k) and
    choice(rest, k) are both permutation(n)[:k]) and score with the same
    f32 per-class value chain (stable argsort: equal keys keep order)."""
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.sample_strategy import DeviceBag, GOSSStrategy

    rng = np.random.RandomState(5)
    g = jnp.asarray(rng.randn(*shape).astype(np.float32))
    h = jnp.asarray((np.abs(rng.randn(*shape)) + 0.1).astype(np.float32))
    cfg = Config({"data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.2, "other_rate": 0.1, "objective": "binary"})

    monkeypatch.setenv("LGBM_TPU_GOSS_DEVICE", "0")
    bag_h, g_h, h_h = GOSSStrategy(cfg, 1000, None, 1).bagging(3, g, h)
    monkeypatch.setenv("LGBM_TPU_GOSS_DEVICE", "1")
    bag_d, g_d, h_d = GOSSStrategy(cfg, 1000, None, 1).bagging(3, g, h)

    assert isinstance(bag_d, DeviceBag) and not isinstance(bag_h, DeviceBag)
    assert len(bag_d) == len(bag_h) == 300  # 20% top + 10% sampled
    # both paths emit ascending row ids: host sorts its concat, the device
    # mask materializes via nonzero
    np.testing.assert_array_equal(bag_d.indices, np.asarray(bag_h))
    # rescaled gradient planes are bit-identical (multiplier applied to
    # the same rows through the same f32 multiply)
    np.testing.assert_array_equal(np.asarray(g_d), np.asarray(g_h))
    np.testing.assert_array_equal(np.asarray(h_d), np.asarray(h_h))
    # mask bookkeeping is consistent with the materialized indices
    assert int(np.asarray(bag_d.mask).sum()) == bag_d.n_bag


def test_goss_device_warmup_and_auto_gate(monkeypatch):
    """Warm-up iterations return the full bag on both paths, and the auto
    mode resolves to host on the CPU test backend."""
    import jax.numpy as jnp

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.sample_strategy import (GOSSStrategy,
                                                     use_device_goss)

    for val, want in (("0", False), ("off", False), ("host", False),
                      ("1", True), ("on", True), ("device", True)):
        monkeypatch.setenv("LGBM_TPU_GOSS_DEVICE", val)
        assert use_device_goss() is want, val
    monkeypatch.setenv("LGBM_TPU_GOSS_DEVICE", "auto")
    assert use_device_goss() is False  # CPU backend: host path

    monkeypatch.setenv("LGBM_TPU_GOSS_DEVICE", "1")
    cfg = Config({"data_sample_strategy": "goss", "learning_rate": 0.5,
                  "top_rate": 0.2, "other_rate": 0.1, "objective": "binary"})
    strat = GOSSStrategy(cfg, 1000, None, 1)
    g = jnp.ones(1000, jnp.float32)
    bag, _, _ = strat.bagging(0, g, g)  # warm-up: 0 < 1/0.5
    assert bag is None
