"""Runtime donation/sync sanitizer (lightgbm_tpu/utils/sanitize.py).

Unit level: the poison registry raises on any host access to a donated
reference (naming the donation site), sync counters attribute to the
innermost timer scope, and sync-free scopes reject counted syncs.
Integration level: a full device-learner train under the sanitizer is
BIT-identical to one without it — the sanitizer observes, never perturbs.
"""
from functools import partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.treelearner.device import DeviceTreeLearner
from lightgbm_tpu.utils import sanitize
from lightgbm_tpu.utils.timer import global_timer


@pytest.fixture(autouse=True)
def _sanitizer_state():
    yield
    sanitize.clear_override()
    sanitize.reset()


def test_guard_is_identity_when_disabled():
    sanitize.disable()

    def fn(x):
        return x

    assert sanitize.guard(fn, (0,), "site") is fn


def test_env_var_drives_enabled(monkeypatch):
    sanitize.clear_override()
    monkeypatch.delenv("LGBM_TPU_SANITIZE", raising=False)
    assert not sanitize.enabled()
    monkeypatch.setenv("LGBM_TPU_SANITIZE", "1")
    assert sanitize.enabled()
    monkeypatch.setenv("LGBM_TPU_SANITIZE", "0")
    assert not sanitize.enabled()


def test_planted_use_after_donation_names_site():
    """The seeded defect: read a reference whose buffer was donated. The
    error must name the DONATION SITE, not just fail generically."""
    sanitize.enable()

    @partial(jax.jit, donate_argnums=(0,))
    def step(buf, delta):
        return buf + delta

    guarded = sanitize.guard(step, (0,), "step (deliberate plant)")
    buf = jnp.ones(8, jnp.float32)
    out = guarded(buf, jnp.float32(1.0))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full(8, 2.0, np.float32))
    with pytest.raises(sanitize.UseAfterDonationError,
                       match=r"step \(deliberate plant\)"):
        _ = buf + 0


def test_poison_covers_np_asarray():
    # np.asarray bypasses every patchable sync method (the documented
    # counter gap) but still trips _check_if_deleted on a poisoned array
    sanitize.enable()

    @partial(jax.jit, donate_argnums=(0,))
    def step(buf):
        return buf * 2

    buf = jnp.ones(4, jnp.float32)
    sanitize.guard(step, (0,), "step")(buf)
    with pytest.raises(sanitize.UseAfterDonationError):
        np.asarray(buf)


def test_undonated_args_and_outputs_stay_live():
    sanitize.enable()

    @partial(jax.jit, donate_argnums=(0,))
    def step(buf, keep):
        return buf + keep

    buf = jnp.ones(4, jnp.float32)
    keep = jnp.full(4, 3.0, jnp.float32)
    out = sanitize.guard(step, (0,), "step")(buf, keep)
    # only position 0 was poisoned
    np.testing.assert_array_equal(np.asarray(keep),
                                  np.full(4, 3.0, np.float32))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full(4, 4.0, np.float32))


def test_sync_counts_attribute_to_innermost_scope():
    sanitize.enable()
    sanitize.reset()
    x = jnp.ones(3, jnp.float32)
    with global_timer.scope("tree_replay"):
        x.block_until_ready()
        float(x[0])
    counts = sanitize.sync_counts()["tree_replay"]
    assert counts["block_until_ready"] == 1
    assert counts["__float__"] == 1


def test_sync_free_scope_raises():
    sanitize.enable()
    sanitize.reset()
    x = jnp.ones(3, jnp.float32)
    with pytest.raises(sanitize.SyncInScopeError, match="tree_device"):
        with global_timer.scope("tree_device"):
            x[0].item()
    # ... and only inside the declared scope
    sanitize.reset()
    with global_timer.scope("tree_replay"):
        assert x[0].item() == 1.0


def _device_booster(X, y, params, n_iters):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    bst.to_model()  # flushes any in-flight async tree
    return bst


def test_device_train_bit_identical_under_sanitizer(rng, monkeypatch):
    """The sanitizer must be a pure observer: the async device pipeline —
    the path whose donations it poisons — produces bit-identical models
    with it on and off."""
    X = rng.randn(600, 6)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.randn(600) * 0.3 > 0).astype(float)
    # 0.5 is f32-exact: the async score path stays bit-identical
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.5,
              "min_data_in_leaf": 5, "verbosity": -1}
    monkeypatch.setenv("LGBM_TPU_ASYNC", "1")
    sanitize.disable()
    plain = _device_booster(X, y, params, 5)
    sanitize.enable()
    sanitize.reset()
    guarded = _device_booster(X, y, params, 5)
    sanitize.disable()
    assert len(plain.models) == len(guarded.models)
    for ta, tb in zip(plain.models, guarded.models):
        for k, va in ta.__dict__.items():
            vb = tb.__dict__[k]
            if isinstance(va, np.ndarray):
                np.testing.assert_array_equal(va, vb, err_msg=k)
            else:
                assert va == vb, k
    np.testing.assert_array_equal(
        np.asarray(plain.predict(X, raw_score=True)),
        np.asarray(guarded.predict(X, raw_score=True)))
    # the asserted-sync-free dispatch scope really saw zero counted syncs
    assert "tree_device" not in sanitize.sync_counts()
