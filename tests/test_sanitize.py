"""Runtime donation/sync sanitizer (lightgbm_tpu/utils/sanitize.py).

Unit level: the poison registry raises on any host access to a donated
reference (naming the donation site), sync counters attribute to the
innermost timer scope, sync-free scopes reject counted syncs, and the
collective-order probe records traced collectives and raises a typed
CollectiveOrderError naming the first divergent op (graftlint R12's
dynamic oracle). Integration level: a full device-learner train under
the sanitizer is BIT-identical to one without it — the sanitizer
observes, never perturbs — and a real two-process gloo gang with a
planted rank-divergent psum is caught at the cross-check.
"""
import os
import socket
import subprocess
import sys
from functools import lru_cache, partial

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.treelearner.device import DeviceTreeLearner
from lightgbm_tpu.utils import sanitize
from lightgbm_tpu.utils.timer import global_timer


@pytest.fixture(autouse=True)
def _sanitizer_state():
    yield
    sanitize.clear_override()
    sanitize.reset()


def test_guard_is_identity_when_disabled():
    sanitize.disable()

    def fn(x):
        return x

    assert sanitize.guard(fn, (0,), "site") is fn


def test_env_var_drives_enabled(monkeypatch):
    sanitize.clear_override()
    monkeypatch.delenv("LGBM_TPU_SANITIZE", raising=False)
    assert not sanitize.enabled()
    monkeypatch.setenv("LGBM_TPU_SANITIZE", "1")
    assert sanitize.enabled()
    monkeypatch.setenv("LGBM_TPU_SANITIZE", "0")
    assert not sanitize.enabled()


def test_planted_use_after_donation_names_site():
    """The seeded defect: read a reference whose buffer was donated. The
    error must name the DONATION SITE, not just fail generically."""
    sanitize.enable()

    @partial(jax.jit, donate_argnums=(0,))
    def step(buf, delta):
        return buf + delta

    guarded = sanitize.guard(step, (0,), "step (deliberate plant)")
    buf = jnp.ones(8, jnp.float32)
    out = guarded(buf, jnp.float32(1.0))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full(8, 2.0, np.float32))
    with pytest.raises(sanitize.UseAfterDonationError,
                       match=r"step \(deliberate plant\)"):
        _ = buf + 0


def test_poison_covers_np_asarray():
    # np.asarray bypasses every patchable sync method (the documented
    # counter gap) but still trips _check_if_deleted on a poisoned array
    sanitize.enable()

    @partial(jax.jit, donate_argnums=(0,))
    def step(buf):
        return buf * 2

    buf = jnp.ones(4, jnp.float32)
    sanitize.guard(step, (0,), "step")(buf)
    with pytest.raises(sanitize.UseAfterDonationError):
        np.asarray(buf)


def test_undonated_args_and_outputs_stay_live():
    sanitize.enable()

    @partial(jax.jit, donate_argnums=(0,))
    def step(buf, keep):
        return buf + keep

    buf = jnp.ones(4, jnp.float32)
    keep = jnp.full(4, 3.0, jnp.float32)
    out = sanitize.guard(step, (0,), "step")(buf, keep)
    # only position 0 was poisoned
    np.testing.assert_array_equal(np.asarray(keep),
                                  np.full(4, 3.0, np.float32))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.full(4, 4.0, np.float32))


def test_sync_counts_attribute_to_innermost_scope():
    sanitize.enable()
    sanitize.reset()
    x = jnp.ones(3, jnp.float32)
    with global_timer.scope("tree_replay"):
        x.block_until_ready()
        float(x[0])
    counts = sanitize.sync_counts()["tree_replay"]
    assert counts["block_until_ready"] == 1
    assert counts["__float__"] == 1


def test_sync_free_scope_raises():
    sanitize.enable()
    sanitize.reset()
    x = jnp.ones(3, jnp.float32)
    with pytest.raises(sanitize.SyncInScopeError, match="tree_device"):
        with global_timer.scope("tree_device"):
            x[0].item()
    # ... and only inside the declared scope
    sanitize.reset()
    with global_timer.scope("tree_replay"):
        assert x[0].item() == 1.0


@lru_cache(maxsize=None)
def _psum_fn(axis):
    @jax.jit
    def f(x):
        return jax.vmap(lambda v: jax.lax.psum(v, axis), axis_name=axis)(x)

    return f


def _traced_psum(axis):
    return _psum_fn(axis)(jnp.ones((4, 2), jnp.float32))


def test_collective_probe_records_traced_sequence():
    sanitize.enable()
    sanitize.reset()
    _traced_psum("batch")
    assert sanitize.collective_sequence() == [("psum", "'batch'")]
    count, crc = sanitize.collective_fingerprint()
    assert count == 1 and crc != 0
    # a cached jit re-dispatches without re-tracing: the sequence is a
    # per-traced-program property and must not grow (documented caveat)
    _traced_psum("batch")
    assert sanitize.collective_sequence() == [("psum", "'batch'")]


def test_collective_probe_inert_when_disabled():
    sanitize.enable()  # installs the patches...
    sanitize.disable()  # ...which must now pass through silently
    sanitize.reset()
    _traced_psum("quiet")
    assert sanitize.collective_sequence() == []
    sanitize.check_collective_order(gather_fn=lambda vec: 1 / 0)  # no-op


def test_collective_order_check_names_first_divergent_op():
    sanitize.enable()
    sanitize.reset()
    _traced_psum("a")
    _traced_psum("b")

    def matching(vec):
        return np.stack([vec, vec])

    sanitize.check_collective_order(gather_fn=matching)  # agreement: quiet

    def divergent(vec):
        other = np.array(vec, copy=True)
        other[2] ^= 0x5A5A  # the fake peer's SECOND op differs
        return np.stack([vec, other])

    with pytest.raises(sanitize.CollectiveOrderError) as exc:
        sanitize.check_collective_order(gather_fn=divergent)
    assert exc.value.first_divergent_op == "psum@'b'"
    assert exc.value.rank == 0
    assert "op #1" in str(exc.value)


def test_collective_order_check_flags_count_mismatch():
    sanitize.enable()
    sanitize.reset()
    _traced_psum("only")

    def longer_peer(vec):
        other = np.array(vec, copy=True)
        other[0] += 1  # the peer traced one extra collective...
        other[2] = 12345  # ...so its second prefix slot is non-zero
        return np.stack([vec, other])

    with pytest.raises(sanitize.CollectiveOrderError) as exc:
        sanitize.check_collective_order(gather_fn=longer_peer)
    assert exc.value.first_divergent_op.startswith("<none:")
    assert "traced 1 collective(s)" in exc.value.first_divergent_op


_ORDER_WORKER = r"""
import os, sys
pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4").strip()
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=nproc, process_id=pid)
import jax.numpy as jnp
from lightgbm_tpu.utils import sanitize
sanitize.enable()

def traced(axis):
    @jax.jit
    def f(x):
        return jax.vmap(lambda v: jax.lax.psum(v, axis), axis_name=axis)(x)
    return f(jnp.ones((4, 2), jnp.float32))

traced("data")            # every rank posts this one
if pid == 1:
    traced("extra")       # the planted defect: rank 1 traces a stray psum
try:
    sanitize.check_collective_order()
except sanitize.CollectiveOrderError as e:
    print("CAUGHT CollectiveOrderError rank=%d op=%s"
          % (e.rank, e.first_divergent_op))
    sys.exit(0)
print("NO DIVERGENCE DETECTED")
sys.exit(1)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.mark.slow
def test_collective_order_divergence_across_gloo_gang():
    """Two real jax.distributed processes; rank 1 traces a psum the gang
    never posts. The heartbeat-slot cross-check must catch it on BOTH
    ranks: rank 1 names the stray op, rank 0 reports the count gap."""
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, "-c", _ORDER_WORKER, str(pid), "2", str(port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outputs = [p.communicate(timeout=600)[0] for p in procs]
    for p, o in zip(procs, outputs):
        assert p.returncode == 0, f"worker failed:\n{o[-3000:]}"
    assert "CAUGHT CollectiveOrderError rank=1 op=psum@'extra'" in outputs[1]
    assert "CAUGHT CollectiveOrderError rank=0 op=<none:" in outputs[0]


def _device_booster(X, y, params, n_iters):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    bst.to_model()  # flushes any in-flight async tree
    return bst


def test_device_train_bit_identical_under_sanitizer(rng, monkeypatch):
    """The sanitizer must be a pure observer: the async device pipeline —
    the path whose donations it poisons — produces bit-identical models
    with it on and off."""
    X = rng.randn(600, 6)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.randn(600) * 0.3 > 0).astype(float)
    # 0.5 is f32-exact: the async score path stays bit-identical
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.5,
              "min_data_in_leaf": 5, "verbosity": -1}
    monkeypatch.setenv("LGBM_TPU_ASYNC", "1")
    sanitize.disable()
    plain = _device_booster(X, y, params, 5)
    sanitize.enable()
    sanitize.reset()
    guarded = _device_booster(X, y, params, 5)
    sanitize.disable()
    assert len(plain.models) == len(guarded.models)
    for ta, tb in zip(plain.models, guarded.models):
        for k, va in ta.__dict__.items():
            vb = tb.__dict__[k]
            if isinstance(va, np.ndarray):
                np.testing.assert_array_equal(va, vb, err_msg=k)
            else:
                assert va == vb, k
    np.testing.assert_array_equal(
        np.asarray(plain.predict(X, raw_score=True)),
        np.asarray(guarded.predict(X, raw_score=True)))
    # the asserted-sync-free dispatch scope really saw zero counted syncs
    assert "tree_device" not in sanitize.sync_counts()
