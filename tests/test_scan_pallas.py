"""Fused split-scan kernel oracle: ops/scan_pallas.py vs the XLA body.

The contract is JIT-vs-JIT bit identity (ISSUE round 8): the fused kernel
in interpret mode must reproduce the jitted XLA `per_feature_best` BIT for
bit — same gains, same thresholds, same lane picks, same -inf/-0.0
patterns — across plain, regularized, masked/penalized and missing-heavy
histograms, and end-to-end through the device learner on the plain,
bagged and quantized planes. `LGBM_TPU_SCAN_PALLAS=0` must restore the
XLA path byte-for-byte (the escape-hatch acceptance criterion).

Eager XLA is NOT the oracle: outside jit the gain expression fuses
differently and drifts 1 ULP, so every comparison here jits both sides
(fresh `jax.jit` wrappers re-read the env gate at trace time; the public
`find_best_split` entry is cleared between env flips instead).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDS
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.ops import scan_pallas
from lightgbm_tpu.ops import split as split_mod
from lightgbm_tpu.ops.histogram import build_histogram
from lightgbm_tpu.ops.split import (SPLIT_FIELDS, find_best_split,
                                    gather_feature_hist, make_feature_meta,
                                    per_feature_best)
from lightgbm_tpu.treelearner.device import DeviceTreeLearner


def _clear_dispatch_caches():
    """The SCAN_PALLAS gate is read at trace time; jitted entries that
    captured one routing must be re-traced after an env flip."""
    from lightgbm_tpu.treelearner import device as device_mod

    find_best_split.clear_cache()
    device_mod.grow_tree_on_device.clear_cache()


@pytest.fixture(autouse=True)
def _interpret_and_clean(monkeypatch):
    """Every test in this file runs the kernel in interpret mode (CPU) and
    leaves no routing decision cached behind for other test files."""
    monkeypatch.setenv("LGBM_TPU_PALLAS_INTERPRET", "1")
    _clear_dispatch_caches()
    yield
    _clear_dispatch_caches()


@pytest.fixture(scope="module")
def leaf():
    """One leaf's split-scan inputs over a feature set that exercises all
    scan lanes: dense numerics, a zero-sparse feature (MissingType::Zero,
    missing bin == default bin) and a NaN feature (MissingType::NaN,
    missing bin == last)."""
    rng = np.random.RandomState(31)
    N, F = 4000, 7
    X = rng.normal(size=(N, F))
    X[:, 2] = rng.binomial(1, 0.25, N) * rng.normal(size=N)  # zero-sparse
    X[rng.rand(N) < 0.15, 4] = np.nan                        # NaN-missing
    X[:, 5] = rng.randint(0, 3, N).astype(float)             # few bins
    grad = rng.normal(size=N).astype(np.float32)
    hess = (np.abs(rng.normal(size=N)) + 0.1).astype(np.float32)
    ds = CoreDS.from_matrix(X, label=grad, config=Config({"verbosity": -1}))
    B = int(ds.group_bin_counts().max())
    gh = np.stack([grad, hess, np.ones(N, np.float32)], 1)
    hist = build_histogram(jnp.asarray(ds.bins), jnp.asarray(gh), B)
    meta = make_feature_meta(ds, B)
    totals = hist[0].sum(axis=0).astype(jnp.float32)
    return hist, totals, meta


def _run_per_feature(monkeypatch, scan_env, hist, totals, meta, params,
                     mask=None, penalty=None, constraint=None):
    """Jitted [F, len(SPLIT_FIELDS)] scan under one SCAN_PALLAS setting.
    A fresh jax.jit wrapper per call re-reads the env gate at trace time."""
    monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", scan_env)

    @jax.jit
    def run(h, t, p):
        fh = gather_feature_hist(h, meta, t)
        return per_feature_best(fh, t, meta, p, mask, constraint, penalty)

    return np.asarray(run(hist, totals, jnp.asarray(params, jnp.float32)))


# params vector layout: [lambda_l1, lambda_l2, min_data_in_leaf,
#                        min_sum_hessian_in_leaf, min_gain_to_split,
#                        max_delta_step]
_PARAM_CASES = {
    "plain": [0, 0, 20, 1e-3, 0, 0],
    "l1_l2": [0.5, 1.0, 20, 1e-3, 0, 0],
    "max_delta": [0, 0, 20, 1e-3, 0, 0.3],
    "min_gain": [0, 0, 20, 1e-3, 0.05, 0],
    "tight_floors": [0, 0, 600, 5.0, 0, 0],
    "everything": [0.2, 0.7, 50, 0.5, 0.02, 0.4],
}


@pytest.mark.parametrize("case", sorted(_PARAM_CASES))
def test_fused_bit_identical_per_feature(leaf, monkeypatch, case):
    """Kernel (interpret) vs XLA on the full per-feature record tensor —
    exact equality, including -inf rows for gated-off candidates."""
    hist, totals, meta = leaf
    params = _PARAM_CASES[case]
    fused = _run_per_feature(monkeypatch, "1", hist, totals, meta, params)
    xla = _run_per_feature(monkeypatch, "0", hist, totals, meta, params)
    np.testing.assert_array_equal(fused, xla, err_msg=case)
    # the scan found at least one real split (the test isn't vacuous)
    if case in ("plain", "l1_l2"):
        assert np.isfinite(fused[:, 0]).any(), case


def test_fused_bit_identical_mask_and_penalty(leaf, monkeypatch):
    """ColSampler mask + CEGB penalty lanes flow through the meta columns."""
    hist, totals, meta = leaf
    F = int(meta.gather_index.shape[0])
    mask = jnp.asarray(np.arange(F) % 2 == 0)
    penalty = jnp.asarray(np.linspace(0.0, 0.5, F), jnp.float32)
    params = _PARAM_CASES["plain"]
    fused = _run_per_feature(monkeypatch, "1", hist, totals, meta, params,
                             mask=mask, penalty=penalty)
    xla = _run_per_feature(monkeypatch, "0", hist, totals, meta, params,
                           mask=mask, penalty=penalty)
    np.testing.assert_array_equal(fused, xla)
    # masked-off features must be invalid in both
    assert (fused[1::2, 1] == -1.0).all()


def test_monotone_constraint_stays_on_xla(leaf, monkeypatch):
    """Constrained scans never route to the kernel: flipping the env flag
    must be a no-op byte-for-byte when a constraint vector is present."""
    hist, totals, meta = leaf
    params = _PARAM_CASES["plain"]
    con = jnp.asarray([-0.2, 0.2], jnp.float32)
    on = _run_per_feature(monkeypatch, "1", hist, totals, meta, params,
                          constraint=con)
    off = _run_per_feature(monkeypatch, "0", hist, totals, meta, params,
                           constraint=con)
    np.testing.assert_array_equal(on, off)


def test_find_best_split_escape_hatch(leaf, monkeypatch):
    """The public jitted entry: LGBM_TPU_SCAN_PALLAS=0 restores the XLA
    reduction byte-for-byte (acceptance criterion), cache-cleared between
    flips because the routing is baked in at trace time."""
    hist, totals, meta = leaf
    params = jnp.asarray(_PARAM_CASES["everything"], jnp.float32)
    monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", "1")
    find_best_split.clear_cache()
    fused = np.asarray(find_best_split(hist, totals, meta, params))
    monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", "0")
    find_best_split.clear_cache()
    xla = np.asarray(find_best_split(hist, totals, meta, params))
    np.testing.assert_array_equal(fused, xla)
    assert np.isfinite(fused[0])  # a real split was picked


def test_constants_pinned_to_split_module():
    """The kernel re-states two contracts from ops/split.py; drift between
    the twins would silently break bit identity."""
    assert scan_pallas.K_EPSILON == split_mod.K_EPSILON
    assert scan_pallas.N_REC == len(SPLIT_FIELDS)
    assert scan_pallas.REC_PAD >= scan_pallas.N_REC
    # tile width must stay a power of two (BlockSpec grid arithmetic)
    t = scan_pallas.SCAN_TILE_FEATURES
    assert t > 0 and (t & (t - 1)) == 0


def test_use_scan_pallas_env_gate(monkeypatch):
    for val, want in (("0", False), ("off", False), ("false", False),
                      ("xla", False), ("1", True), ("on", True),
                      ("true", True), ("pallas", True)):
        monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", val)
        assert scan_pallas.use_scan_pallas() is want, val
    monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", "auto")
    # CPU test harness: auto means off (kernel is a TPU win, not a CPU one)
    assert scan_pallas.use_scan_pallas() is False


def _train_device(X, y, params, n_iters):
    cfg = Config(params)
    ds = CoreDS.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    for _ in range(n_iters):
        if bst.train_one_iter():
            break
    bst.to_model()  # flush any in-flight async tree
    return bst


def _assert_same_models(a, b):
    """Byte-equality on every tree field except the stored `split_gain`
    metadata, which may drift by one upstream rounding between the fused
    and XLA paths when the scan is embedded in the big grow_tree_on_device
    jit: XLA refuses a fixed op order for its OWN body across fusion
    contexts (the big-jit XLA gain drifts from its standalone-jit self,
    which is the value the kernel reproduces), and the final
    `best_gain - gain_shift` cancellation amplifies that single rounding
    to a few ULP of the result. Decisions, thresholds, counts and leaf
    outputs — everything that feeds predictions — must match bit for
    bit."""
    assert len(a.models) == len(b.models)
    for ta, tb in zip(a.models, b.models):
        for k, va in ta.__dict__.items():
            vb = tb.__dict__[k]
            if k == "split_gain":
                np.testing.assert_allclose(np.asarray(va), np.asarray(vb),
                                           rtol=1e-4, atol=1e-5, err_msg=k)
            elif isinstance(va, np.ndarray):
                np.testing.assert_array_equal(va, vb, err_msg=k)
            else:
                assert va == vb, k


_VARIANTS = {
    "plain": {},
    "bagged": {"bagging_fraction": 0.7, "bagging_freq": 1, "seed": 7},
    "quantized": {"use_quantized_grad": True, "quant_train_renew_leaf": True},
}


@pytest.mark.slow  # ~2 min/variant: interpret mode pays Python per wave.
@pytest.mark.parametrize("variant", sorted(_VARIANTS))
def test_train_bit_identical_fused_vs_xla(rng, monkeypatch, variant):
    """End-to-end through the device learner: the fused scan (interpret)
    grows trees identical to the XLA scan on every training plane — same
    structure, thresholds, counts and leaf values bit for bit; the stored
    split_gain metadata is allowed the 1-ULP big-jit context drift (see
    _assert_same_models). (Quantized histograms are int32, so that variant
    exercises the dtype gate: the kernel must step aside without
    perturbing anything.)"""
    n = 900
    X = rng.randn(n, 6)
    y = (X[:, 0] - 0.6 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, **_VARIANTS[variant]}
    monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", "1")
    _clear_dispatch_caches()
    fused = _train_device(X, y, params, 3)
    monkeypatch.setenv("LGBM_TPU_SCAN_PALLAS", "0")
    _clear_dispatch_caches()
    xla = _train_device(X, y, params, 3)
    _assert_same_models(fused, xla)
    np.testing.assert_array_equal(
        np.asarray(fused.predict(X, raw_score=True)),
        np.asarray(xla.predict(X, raw_score=True)))
