"""Hardened-serving suite: bit-identity with direct Booster.predict on both
the device and host-fallback paths, checksum-verified hot-swap that a
corrupt upload can never win, breaker trip -> host fallback -> half-open
recovery, deadline shedding before dispatch, bounded admission, and the
end-to-end fault-injected acceptance scenario.
"""
import hashlib
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import checkpoint
from lightgbm_tpu.serving import (CircuitBreaker, DeadlineExceeded,
                                  InvalidRequest, ModelLoadError,
                                  ModelNotFound, Overloaded,
                                  PredictionService)
from lightgbm_tpu.serving.breaker import CLOSED, DEGRADED, HALF_OPEN, OPEN
from lightgbm_tpu.utils import faults

PARAMS = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
          "verbosity": -1, "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _train(rng, n=500, rounds=8, params=None):
    X = rng.rand(n, 10)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    return lgb.train(params or PARAMS, ds, num_boost_round=rounds), X, y


def _service(**kw):
    kw.setdefault("batch_window_s", 0.0)
    kw.setdefault("max_batch_rows", 1024)
    return PredictionService(**kw)


# ------------------------------------------------------------ bit-identity


def test_served_predictions_bit_identical_to_direct(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        svc.load_model("m", booster=bst)
        for n in (1, 37, 300):
            Q = rng.rand(n, 10)
            assert np.array_equal(svc.predict("m", Q), bst.predict(Q))
            assert np.array_equal(svc.predict("m", Q, raw_score=True),
                                  bst.predict(Q, raw_score=True))
    finally:
        svc.close()


def test_host_fallback_bit_identical(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        svc.load_model("m", booster=bst)
        entry = svc.registry.get("m")
        Q = np.ascontiguousarray(rng.rand(64, 10), dtype=np.float32)
        for raw in (False, True):
            assert np.array_equal(entry.predict_host(Q, raw),
                                  entry.predict_device(Q, raw))
    finally:
        svc.close()


def test_multiclass_and_regression_served(rng):
    X = rng.rand(400, 8)
    y_mc = (X[:, 0] * 3).astype(int).clip(0, 2).astype(np.float64)
    mc = lgb.train({"objective": "multiclass", "num_class": 3,
                    "num_leaves": 7, "verbosity": -1},
                   lgb.Dataset(X, label=y_mc), num_boost_round=5)
    reg = lgb.train({"objective": "regression", "num_leaves": 7,
                     "verbosity": -1},
                    lgb.Dataset(X, label=X[:, 0]), num_boost_round=5)
    svc = _service()
    try:
        svc.load_model("mc", booster=mc)
        svc.load_model("reg", booster=reg)
        Q = rng.rand(33, 8)
        assert np.array_equal(svc.predict("mc", Q), mc.predict(Q))
        assert np.array_equal(svc.predict("reg", Q), reg.predict(Q))
    finally:
        svc.close()


def test_concurrent_mixed_size_requests_bit_identical(rng):
    bst, _, _ = _train(rng)
    svc = PredictionService(batch_window_s=0.002, max_batch_rows=1024)
    try:
        svc.load_model("m", booster=bst)
        queries = [rng.rand(int(n), 10) for n in
                   rng.randint(1, 200, size=24)]
        expected = [bst.predict(q) for q in queries]
        results = [None] * len(queries)
        errors = []

        def worker(i):
            try:
                results[i] = svc.predict("m", queries[i])
            except Exception as exc:  # pragma: no cover - diagnostic
                errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(len(queries))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        for got, want in zip(results, expected):
            assert np.array_equal(got, want)
    finally:
        svc.close()


# ----------------------------------------------------------- admission


def test_validation_rejects_before_dispatch(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        svc.load_model("m", booster=bst)
        with pytest.raises(InvalidRequest, match="9 features"):
            svc.predict("m", rng.rand(3, 9))
        with pytest.raises(InvalidRequest, match="numeric"):
            svc.predict("m", [[1.0, 2.0], [3.0]])
        with pytest.raises(InvalidRequest, match="no rows"):
            svc.predict("m", np.zeros((0, 10)))
        with pytest.raises(InvalidRequest, match="per-request limit"):
            svc.predict("m", np.zeros((svc.max_request_rows + 1, 10)))
        with pytest.raises(ModelNotFound):
            svc.predict("nope", rng.rand(1, 10))
    finally:
        svc.close()


def test_nonfinite_rejection_is_opt_in(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        svc.load_model("nan_ok", booster=bst)
        svc.load_model("strict", booster=bst, reject_nonfinite=True)
        Q = rng.rand(5, 10)
        Q[2, 7] = np.nan
        # NaN is a legitimate missing value by default (LightGBM semantics)
        direct = bst.predict(Q)
        assert np.array_equal(svc.predict("nan_ok", Q), direct)
        with pytest.raises(InvalidRequest, match="column 7"):
            svc.predict("strict", Q)
    finally:
        svc.close()


def test_overload_rejects_without_enqueuing(rng):
    bst, _, _ = _train(rng)
    svc = _service(max_queue_rows=256)
    try:
        svc.load_model("m", booster=bst)
        faults.install("slow_predict@1:0.2")  # hold the worker busy
        Q = rng.rand(100, 10)
        svc_errors = []
        done = []

        def worker():
            try:
                done.append(svc.predict("m", Q))
            except Overloaded as exc:
                svc_errors.append(exc)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
            time.sleep(0.005)  # let earlier submits claim queue slots
        for t in threads:
            t.join()
        assert svc_errors, "saturation never produced an Overloaded"
        # bounded admission: queued rows never exceeded the limit
        assert svc.batcher.stats()["queue_rows"] == 0
        assert svc.batcher.n_overloaded == len(svc_errors)
        # accepted requests still answered correctly
        for out in done:
            assert np.array_equal(out, bst.predict(Q))
    finally:
        svc.close()


# ------------------------------------------------------------- deadlines


def test_deadline_expired_request_is_shed_before_dispatch(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        svc.load_model("m", booster=bst)
        faults.install("slow_predict@1:0.25")
        Q = rng.rand(32, 10)
        dispatches_before = svc.batcher.n_batches

        slow_ok = []
        t_slow = threading.Thread(
            target=lambda: slow_ok.append(svc.predict("m", Q)))
        t_slow.start()
        time.sleep(0.02)  # slow batch is now holding the worker
        with pytest.raises(DeadlineExceeded):
            svc.predict("m", Q, timeout_s=0.05)
        t_slow.join()
        # the expired request was shed at assembly time: the worker ran the
        # slow batch and nothing else ever reached a dispatch
        deadline = time.monotonic() + 2.0
        while (svc.batcher.n_deadline_shed == 0
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert svc.batcher.n_deadline_shed >= 1
        assert svc.batcher.n_batches == dispatches_before + 1
        assert slow_ok and np.array_equal(slow_ok[0], bst.predict(Q))
    finally:
        svc.close()


def test_expired_inflight_wait_does_not_block_batch(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        svc.load_model("m", booster=bst)
        faults.install("slow_predict@1:0.2")
        Q = rng.rand(16, 10)
        t0 = time.monotonic()
        with pytest.raises(DeadlineExceeded):
            svc.predict("m", Q, timeout_s=0.05)
        # the caller came back at its deadline, not after the slow batch
        assert time.monotonic() - t0 < 0.15
    finally:
        svc.close()


# --------------------------------------------------------------- hot-swap


def test_hot_swap_and_idempotent_reload(rng):
    bst1, X, y = _train(rng)
    bst2 = lgb.train({**PARAMS, "num_leaves": 7},
                     lgb.Dataset(X, label=y), num_boost_round=4)
    svc = _service()
    try:
        v1 = svc.load_model("m", booster=bst1)
        assert v1["version"] == 1
        # idempotent retry: same bytes, same version
        assert svc.load_model("m", booster=bst1)["version"] == 1
        Q = rng.rand(20, 10)
        assert np.array_equal(svc.predict("m", Q), bst1.predict(Q))
        v2 = svc.load_model("m", booster=bst2)
        assert v2["version"] == 2
        assert np.array_equal(svc.predict("m", Q), bst2.predict(Q))
    finally:
        svc.close()


def test_corrupt_upload_never_replaces_serving_model(rng, tmp_path):
    bst1, X, y = _train(rng)
    bst2 = lgb.train({**PARAMS, "num_leaves": 7},
                     lgb.Dataset(X, label=y), num_boost_round=4)
    path = str(tmp_path / "model.txt")
    checkpoint.save_checkpoint(bst2, path)  # model text + .ckpt sidecar
    svc = _service()
    try:
        svc.load_model("m", booster=bst1)
        Q = rng.rand(20, 10)
        faults.install("model_corrupt_upload")
        with pytest.raises(ModelLoadError):
            svc.load_model("m", path=path)
        # prior version still serving, bit-identical
        assert svc.registry.get("m").version == 1
        assert np.array_equal(svc.predict("m", Q), bst1.predict(Q))
        assert svc.registry.rejected_uploads == 1
        faults.clear()
        # the same path loads fine once the transit corruption is gone
        info = svc.load_model("m", path=path)
        assert info["version"] == 2 and info["verified"]
        assert np.array_equal(svc.predict("m", Q), bst2.predict(Q))
    finally:
        svc.close()


def test_expected_sha256_mismatch_rejected(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    try:
        text = bst.model_to_string()
        good = hashlib.sha256(text.encode()).hexdigest()
        with pytest.raises(ModelLoadError, match="does not match"):
            svc.load_model("m", model_str=text, expected_sha256="0" * 64)
        assert svc.registry.names() == []
        info = svc.load_model("m", model_str=text, expected_sha256=good)
        assert info["verified"]
    finally:
        svc.close()


def test_unparseable_model_text_rejected(rng):
    svc = _service()
    try:
        with pytest.raises(ModelLoadError, match="unparseable"):
            svc.load_model("m", model_str="this is not a model\n")
        assert svc.registry.names() == []
    finally:
        svc.close()


def test_damaged_sidecar_rejected_for_serving(rng, tmp_path):
    bst, _, _ = _train(rng)
    path = str(tmp_path / "model.txt")
    checkpoint.save_checkpoint(bst, path)
    sidecar = path + checkpoint.SIDECAR_SUFFIX
    blob = bytearray(open(sidecar, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(sidecar, "wb").write(bytes(blob))
    svc = _service()
    try:
        with pytest.raises(ModelLoadError, match="sidecar"):
            svc.load_model("m", path=path)
    finally:
        svc.close()


# ---------------------------------------------------------------- breaker


def test_breaker_trips_to_host_and_recovers(rng):
    bst, _, _ = _train(rng)
    breaker = CircuitBreaker(fail_threshold=3, probe_successes=2,
                             cooldown_s=0.1)
    svc = _service(breaker=breaker)
    try:
        svc.load_model("m", booster=bst)
        Q = rng.rand(25, 10)
        expected = bst.predict(Q)
        faults.install("predict_fail@1:3")
        # every response stays correct through the failure window (host
        # retry in place), and the third failure opens the breaker
        for _ in range(3):
            assert np.array_equal(svc.predict("m", Q), expected)
        assert breaker.state == OPEN
        faults.clear()
        # OPEN: served from the host path, still bit-identical
        assert np.array_equal(svc.predict("m", Q), expected)
        assert svc.batcher.n_host_chunks >= 4
        time.sleep(0.15)  # cooldown -> HALF_OPEN probe on next dispatch
        for _ in range(3):
            assert np.array_equal(svc.predict("m", Q), expected)
        assert breaker.state == CLOSED
        assert breaker.transitions >= 3  # closed->open->half_open->closed
    finally:
        svc.close()


def test_breaker_probe_failure_reopens():
    clock = [0.0]
    b = CircuitBreaker(fail_threshold=1, cooldown_s=5.0,
                       clock=lambda: clock[0])
    b.on_failure(RuntimeError("boom"))
    assert b.state == OPEN
    assert b.decide().use_host
    clock[0] = 6.0
    d = b.decide()
    assert b.state == HALF_OPEN and d.probe and not d.use_host
    b.on_failure(RuntimeError("still broken"))
    assert b.state == OPEN
    # reopened: cooldown restarts from the probe failure
    clock[0] = 7.0
    assert b.decide().use_host


def test_breaker_degrades_on_compile_churn_and_recovers():
    b = CircuitBreaker(compile_churn_limit=4, recovery_successes=2)
    b.note_signals({"compiles": 10})
    assert b.state == CLOSED
    b.note_signals({"compiles": 20})  # +10 >= limit
    assert b.state == DEGRADED
    assert b.decide().max_rows == b.degraded_rows
    b.on_success()
    b.on_success()
    assert b.state == CLOSED


def test_breaker_degrades_on_hbm_pressure():
    b = CircuitBreaker(hbm_limit_bytes=1000)
    b.note_signals({"compiles": 0, "hbm_high_water_bytes": 500})
    assert b.state == CLOSED
    b.note_signals({"compiles": 0, "hbm_high_water_bytes": 2000})
    assert b.state == DEGRADED


# ------------------------------------------------- acceptance (end-to-end)


def test_fault_injected_serving_scenario(rng, tmp_path):
    """ISSUE acceptance: slow chunk + corrupt upload + expired deadline +
    dispatch failures in one serving run — no crash, corrupt model
    rejected while the prior version serves, breaker trips to host
    fallback and recovers, every completed response bit-identical."""
    bst, X, y = _train(rng)
    breaker = CircuitBreaker(fail_threshold=2, probe_successes=1,
                             cooldown_s=0.05)
    svc = _service(breaker=breaker)
    try:
        svc.load_model("m", booster=bst)
        Q = rng.rand(40, 10)
        expected = bst.predict(Q)

        # slow chunk + an expired deadline riding behind it
        faults.install("slow_predict@1:0.15")
        t = threading.Thread(target=lambda: svc.predict("m", Q))
        t.start()
        time.sleep(0.02)
        with pytest.raises(DeadlineExceeded):
            svc.predict("m", Q, timeout_s=0.03)
        t.join()
        faults.clear()

        # corrupt upload rejected mid-flight; v1 keeps serving
        faults.install("model_corrupt_upload")
        with pytest.raises(ModelLoadError):
            svc.load_model("m", model_str=bst.model_to_string(),
                           expected_sha256=hashlib.sha256(
                               bst.model_to_string().encode()).hexdigest())
        faults.clear()
        assert svc.registry.get("m").version == 1
        assert np.array_equal(svc.predict("m", Q), expected)

        # sustained dispatch failures: breaker opens, host path serves
        faults.install("predict_fail@1:2")
        for _ in range(2):
            assert np.array_equal(svc.predict("m", Q), expected)
        assert breaker.state == OPEN
        faults.clear()
        assert np.array_equal(svc.predict("m", Q), expected)
        time.sleep(0.1)
        assert np.array_equal(svc.predict("m", Q), expected)
        assert breaker.state == CLOSED

        stats = svc.stats()
        assert stats["batcher"]["device_failures"] == 2
        assert stats["batcher"]["host_chunks"] >= 3
        assert stats["rejected_uploads"] == 1
        assert svc.healthz()["status"] == "ok"
    finally:
        svc.close()


def test_close_fails_pending_and_new_requests(rng):
    bst, _, _ = _train(rng)
    svc = _service()
    svc.load_model("m", booster=bst)
    svc.close()
    from lightgbm_tpu.serving import ServiceClosed

    with pytest.raises(ServiceClosed):
        svc.predict("m", rng.rand(2, 10))
