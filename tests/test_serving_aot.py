"""AOT warm-start suite: export_aot -> fresh replica loads the sidecar
and serves bit-identically without recompiling; every refusal path
(stale environment fingerprint, wrong model hash, damaged sidecar,
missing sidecar) warns and falls back to fresh compiles — a bad bundle
can cost a compile, never a wrong answer.
"""
import os
import pickle

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import checkpoint
from lightgbm_tpu.serving import PredictionService
from lightgbm_tpu.utils.timer import global_timer

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """A trained model saved to disk with an AOT sidecar exported next
    to it by a warm service, plus reference predictions."""
    rng = np.random.RandomState(7)
    X = rng.rand(400, 10)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=8)
    mpath = str(tmp_path_factory.mktemp("aot") / "model.txt")
    bst.save_model(mpath)
    warm = PredictionService(max_batch_rows=256, batch_window_s=0.0)
    warm.load_model("m", path=mpath)
    sidecar = warm.export_aot("m")
    warm.close()
    assert sidecar == mpath + checkpoint.AOT_SUFFIX
    assert os.path.exists(sidecar)
    Q = np.ascontiguousarray(rng.rand(64, 10), dtype=np.float32)
    want_raw = bst.predict(Q, raw_score=True).astype(np.float32)
    want = bst.predict(Q).astype(np.float32)
    return mpath, sidecar, Q, want_raw, want


def _fresh_service():
    return PredictionService(max_batch_rows=256, batch_window_s=0.0)


def test_cold_replica_installs_bundle_and_matches(exported):
    mpath, _, Q, want_raw, want = exported
    svc = _fresh_service()
    try:
        before = global_timer.counters["predict_aot_hits"]
        info = svc.load_model("cold", path=mpath)
        assert info["aot_buckets"] > 0
        # warmup already dispatched the AOT-covered buckets
        assert global_timer.counters["predict_aot_hits"] > before
        hits = global_timer.counters["predict_aot_hits"]
        # the block pads up to an exported bucket -> AOT dispatch
        got_raw = svc.predict("cold", Q, raw_score=True)
        assert np.array_equal(got_raw, want_raw)
        assert global_timer.counters["predict_aot_hits"] > hits
        # transformed output rides the same executable + convert_output
        assert np.array_equal(svc.predict("cold", Q), want)
    finally:
        svc.close()


def test_stale_environment_fingerprint_falls_back(exported, capsys):
    mpath, sidecar, Q, want_raw, _ = exported
    obj = pickle.loads(checkpoint.read_aot_sidecar(mpath))
    obj["environment"]["jax"] = "0.0.0-stale"
    stale = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    svc = _fresh_service()
    try:
        checkpoint.write_aot_sidecar(mpath, stale)
        info = svc.load_model("stale", path=mpath)
        assert info["aot_buckets"] == 0
        assert "fingerprint mismatch" in capsys.readouterr().out
        # fallback recompiles and still answers bit-identically
        assert np.array_equal(svc.predict("stale", Q, raw_score=True),
                              want_raw)
    finally:
        svc.close()
        # restore the good sidecar for tests that follow
        svc2 = _fresh_service()
        svc2.load_model("m", path=mpath)
        svc2.export_aot("m")
        svc2.close()


def test_wrong_model_hash_refused(exported, tmp_path):
    mpath, _, Q, want_raw, _ = exported
    obj = pickle.loads(checkpoint.read_aot_sidecar(mpath))
    obj["model_sha256"] = "0" * 64
    other = str(tmp_path / "model.txt")
    with open(mpath) as fh:
        text = fh.read()
    with open(other, "w") as fh:
        fh.write(text)
    checkpoint.write_aot_sidecar(
        other, pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
    svc = _fresh_service()
    try:
        info = svc.load_model("wrong", path=other)
        assert info["aot_buckets"] == 0
        assert np.array_equal(svc.predict("wrong", Q, raw_score=True),
                              want_raw)
    finally:
        svc.close()


def test_damaged_sidecar_falls_back(exported, tmp_path, capsys):
    mpath, _, Q, want_raw, _ = exported
    other = str(tmp_path / "model.txt")
    with open(mpath) as fh:
        text = fh.read()
    with open(other, "w") as fh:
        fh.write(text)
    good = checkpoint.read_aot_sidecar(mpath)
    # zero the stored digest so read_aot_sidecar rejects the sidecar
    with open(other + checkpoint.AOT_SUFFIX, "wb") as fh:
        fh.write(checkpoint.AOT_MAGIC + b"\x00" * 32 + good)
    svc = _fresh_service()
    try:
        info = svc.load_model("dmg", path=other)
        assert info["aot_buckets"] == 0
        assert "damaged AOT sidecar" in capsys.readouterr().out
        assert np.array_equal(svc.predict("dmg", Q, raw_score=True),
                              want_raw)
    finally:
        svc.close()


def test_missing_sidecar_is_silent_zero(exported, tmp_path):
    mpath, _, Q, want_raw, _ = exported
    other = str(tmp_path / "model.txt")
    with open(mpath) as fh:
        text = fh.read()
    with open(other, "w") as fh:
        fh.write(text)
    svc = _fresh_service()
    try:
        info = svc.load_model("nosc", path=other)
        assert info["aot_buckets"] == 0
        assert np.array_equal(svc.predict("nosc", Q, raw_score=True),
                              want_raw)
    finally:
        svc.close()


def test_export_requires_a_path_for_in_process_boosters(exported):
    mpath, _, _, _, _ = exported
    rng = np.random.RandomState(8)
    X = rng.rand(200, 10)
    y = (X[:, 0] > 0.5).astype(np.float64)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=3)
    svc = _fresh_service()
    try:
        svc.load_model("mem", booster=bst)
        with pytest.raises(ValueError, match="explicit path"):
            svc.export_aot("mem")
    finally:
        svc.close()


def test_sidecar_io_roundtrip(tmp_path):
    path = str(tmp_path / "anything.txt")
    assert checkpoint.read_aot_sidecar(path) is None
    blob = b"\x01\x02payload" * 9
    sc = checkpoint.write_aot_sidecar(path, blob)
    assert checkpoint.read_aot_sidecar(path) == blob
    with open(sc, "r+b") as fh:
        fh.seek(len(checkpoint.AOT_MAGIC) + 32 + 2)
        fh.write(b"\xff")
    with pytest.raises(checkpoint.CheckpointError, match="checksum"):
        checkpoint.read_aot_sidecar(path)
    with open(sc, "wb") as fh:
        fh.write(b"NOTMAGIC" + blob)
    with pytest.raises(checkpoint.CheckpointError, match="magic"):
        checkpoint.read_aot_sidecar(path)
