"""Fleet dispatch suite: replica placement + aggregate stats, per-entry
breaker shards (one faulting model cannot shed its neighbours),
pred_shard_rows routing through a model entry, and the batcher's
zero-copy exact-bucket-fit pad path.
"""
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import CircuitBreaker, PredictionService
from lightgbm_tpu.serving import batcher as batcher_mod
from lightgbm_tpu.serving.batcher import MicroBatcher
from lightgbm_tpu.serving.breaker import CLOSED, OPEN
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.timer import global_timer

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _train(rng, n=400, seed_col=0):
    X = rng.rand(n, 10)
    y = (X[:, seed_col] + X[:, 1] > 1.0).astype(np.float64)
    return lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=8)


# ----------------------------------------------------------- pad zero-copy


def test_pad_exact_bucket_fit_is_zero_copy():
    b = MicroBatcher(max_batch_rows=1024, min_bucket=256,
                     batch_window_s=0.0)
    try:
        chunk = np.zeros((256, 6), dtype=np.float32)
        assert b._pad(chunk, 1024) is chunk
        full = np.zeros((1024, 6), dtype=np.float32)
        assert b._pad(full, 1024) is full
    finally:
        b.close()


def test_pad_exact_fit_never_allocates():
    b = MicroBatcher(max_batch_rows=1024, min_bucket=256,
                     batch_window_s=0.0)

    calls = []
    real_zeros = np.zeros

    class _SpyNp:
        def __getattr__(self, name):
            if name == "zeros":
                def spy(*a, **kw):
                    calls.append(a)
                    return real_zeros(*a, **kw)
                return spy
            return getattr(np, name)

    try:
        batcher_mod.np = _SpyNp()
        chunk = np.ones((512, 4), dtype=np.float32)
        out = b._pad(chunk, 1024)
        assert out is chunk and not calls
        # a ragged tail still pays exactly one pad allocation
        ragged = np.ones((300, 4), dtype=np.float32)
        padded = b._pad(ragged, 1024)
        assert padded.shape == (512, 4) and len(calls) == 1
        assert np.array_equal(padded[:300], ragged)
        assert not padded[300:].any()
    finally:
        batcher_mod.np = np
        b.close()


def test_pad_exact_fit_noncontiguous_still_copies():
    b = MicroBatcher(max_batch_rows=1024, min_bucket=256,
                     batch_window_s=0.0)
    try:
        base = np.zeros((256, 12), dtype=np.float32)
        view = base[:, ::2]                     # not C-contiguous
        out = b._pad(view, 1024)
        assert out is not view
        assert out.flags["C_CONTIGUOUS"] and out.dtype == np.float32
        f64 = np.zeros((256, 6), dtype=np.float64)
        out64 = b._pad(f64, 1024)
        assert out64 is not f64 and out64.dtype == np.float32
    finally:
        b.close()


# ------------------------------------------------------ per-entry breaker


def test_breaker_shards_isolate_entries(rng):
    breaker = CircuitBreaker(fail_threshold=2, probe_successes=1,
                             cooldown_s=60.0)
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0,
                            breaker=breaker)
    try:
        svc.load_model("a", booster=_train(rng, seed_col=0))
        svc.load_model("b", booster=_train(rng, seed_col=2))
        Q = np.ascontiguousarray(rng.rand(17, 10), dtype=np.float32)
        want_a = svc.predict("a", Q)
        want_b = svc.predict("b", Q)
        # fail the next two device dispatches — both aimed at entry 'a'
        faults.install("predict_fail@1:2")
        for _ in range(2):
            assert np.array_equal(svc.predict("a", Q), want_a)  # host retry
        info = svc.breaker.info()
        assert info["entries"]["a"]["state"] == OPEN
        assert info["entries"]["b"]["state"] == CLOSED
        assert info["state"] == OPEN            # aggregate = worst shard
        # 'b' still serves on the DEVICE: its dispatch succeeds and its
        # shard stays closed while 'a' is host-pinned
        assert np.array_equal(svc.predict("b", Q), want_b)
        info = svc.breaker.info()
        assert info["entries"]["b"]["state"] == CLOSED
        assert info["entries"]["a"]["state"] == OPEN
        # 'a' keeps answering bit-identically through the host path
        host_chunks = svc.batcher.n_host_chunks
        assert np.array_equal(svc.predict("a", Q), want_a)
        assert svc.batcher.n_host_chunks > host_chunks
    finally:
        svc.close()


def test_unload_forgets_breaker_shard(rng):
    breaker = CircuitBreaker(fail_threshold=1, probe_successes=1,
                             cooldown_s=60.0)
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0,
                            breaker=breaker)
    try:
        svc.load_model("a", booster=_train(rng))
        Q = np.ascontiguousarray(rng.rand(9, 10), dtype=np.float32)
        faults.install("predict_fail@1:1")
        svc.predict("a", Q)
        assert svc.breaker.info()["state"] == OPEN
        svc.unload_model("a")
        # the tripped shard leaves with its entry: aggregate recovers
        assert svc.breaker.info()["state"] == CLOSED
        assert "entries" not in svc.breaker.info()
    finally:
        svc.close()


# ------------------------------------------------------- replica dispatch


def test_replica_placement_and_aggregate_stats(rng):
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0,
                            replicas=2)
    try:
        bst0, bst1 = _train(rng, seed_col=0), _train(rng, seed_col=3)
        svc.load_model("m0", booster=bst0)
        svc.load_model("m1", booster=bst1)
        stats = svc.stats()
        assert stats["replicas"]["count"] == 2
        placement = stats["replicas"]["placement"]
        assert placement["m0"] != placement["m1"]
        Q = np.ascontiguousarray(rng.rand(25, 10), dtype=np.float32)
        got0 = svc.predict("m0", Q, raw_score=True)
        got1 = svc.predict("m1", Q, raw_score=True)
        assert np.array_equal(
            got0, bst0.predict(Q, raw_score=True).astype(np.float32))
        assert np.array_equal(
            got1, bst1.predict(Q, raw_score=True).astype(np.float32))
        # aggregate batcher stats sum the per-replica counters
        agg = svc.stats()["batcher"]
        assert agg["requests"] == sum(b.n_requests for b in svc._batchers)
        assert agg["rows"] >= 2 * 25
        assert svc.healthz()["status"] == "ok"
    finally:
        svc.close()


def test_replica_placement_is_sticky_and_forgotten_on_unload(rng):
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0,
                            replicas=3)
    try:
        svc.load_model("m0", booster=_train(rng))
        first = svc.stats()["replicas"]["placement"]["m0"]
        Q = np.ascontiguousarray(rng.rand(5, 10), dtype=np.float32)
        for _ in range(4):
            svc.predict("m0", Q)
        assert svc.stats()["replicas"]["placement"]["m0"] == first
        svc.unload_model("m0")
        assert "m0" not in svc.stats()["replicas"]["placement"]
    finally:
        svc.close()


def test_replica_concurrent_models_bit_exact(rng):
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0,
                            replicas=2)
    try:
        boosters = [_train(rng, seed_col=i) for i in range(4)]
        for i, bst in enumerate(boosters):
            svc.load_model(f"m{i}", booster=bst)
        Q = np.ascontiguousarray(rng.rand(16, 10), dtype=np.float32)
        want = [b.predict(Q).astype(np.float32) for b in boosters]
        got = [None] * 4
        errs = []

        def fire(i):
            try:
                for _ in range(5):
                    got[i] = svc.predict(f"m{i}", Q)
            except Exception as exc:  # noqa: BLE001 - surface in main thread
                errs.append(exc)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        for i in range(4):
            assert np.array_equal(got[i], want[i])
    finally:
        svc.close()


# ------------------------------------------------------- row-sharded path


def test_entry_shard_rows_routes_sharded_predict(rng):
    import jax

    if jax.device_count() <= 1:
        pytest.skip("needs the multi-device test harness")
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0)
    try:
        bst = _train(rng)
        svc.load_model("s", booster=bst, shard_rows=1)
        entry_info = {e["name"]: e for e in svc.stats()["models"]}
        assert entry_info["s"]["shard_rows"] == 1
        Q = np.ascontiguousarray(rng.rand(64, 10), dtype=np.float32)
        before = global_timer.counters["predict_sharded_rows"]
        got = svc.predict("s", Q, raw_score=True)
        assert global_timer.counters["predict_sharded_rows"] > before
        # bit-identical to the single-chip answer
        assert np.array_equal(
            got, bst.predict(Q, raw_score=True).astype(np.float32))
    finally:
        svc.close()


def test_pred_shard_rows_kwarg_bit_identical(rng):
    import jax

    if jax.device_count() <= 1:
        pytest.skip("needs the multi-device test harness")
    bst = _train(rng)
    X = rng.rand(333, 10)              # pads + crops across 8 devices
    single = bst.predict(X, raw_score=True)
    before = global_timer.counters["predict_sharded_rows"]
    sharded = bst.predict(X, raw_score=True, pred_shard_rows=1)
    assert global_timer.counters["predict_sharded_rows"] >= before + 333
    np.testing.assert_array_equal(single, sharded)
