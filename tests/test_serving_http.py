"""HTTP front suite: every endpoint, typed error statuses, concurrent
clients, and hot-swap over the wire — all against an ephemeral-port server
with the stdlib urllib client (no new dependencies on either side).
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import PredictionService
from lightgbm_tpu.serving.http import serve

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(scope="module")
def served():
    rng = np.random.RandomState(42)
    X = rng.rand(500, 10)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=8)
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0)
    svc.load_model("m", booster=bst)
    server, thread = serve(svc, port=0)
    yield server.port, bst, svc
    server.shutdown()
    svc.close()


def _call(port, path, payload=None, method=None):
    url = f"http://127.0.0.1:{port}{path}"
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def _call_err(port, path, payload=None, method=None):
    try:
        return _call(port, path, payload, method)
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_predict_endpoint_bit_identical(served):
    port, bst, _ = served
    rng = np.random.RandomState(0)
    Q = rng.rand(17, 10)
    status, body = _call(port, "/predict",
                         {"model": "m", "rows": Q.tolist()})
    assert status == 200
    assert body["model"] == "m" and body["version"] == 1
    assert "latency_ms" in body
    got = np.asarray(body["predictions"], dtype=np.float32)
    assert np.array_equal(got, bst.predict(Q).astype(np.float32))
    status, body = _call(port, "/predict",
                         {"model": "m", "rows": Q.tolist(),
                          "raw_score": True})
    want = bst.predict(Q, raw_score=True).astype(np.float32)
    assert np.array_equal(
        np.asarray(body["predictions"], dtype=np.float32), want)


def test_error_statuses(served):
    port, _, _ = served
    code, body = _call_err(port, "/predict",
                           {"model": "nope", "rows": [[0.0] * 10]})
    assert code == 404 and body["error"] == "model_not_found"
    code, body = _call_err(port, "/predict",
                           {"model": "m", "rows": [[0.0] * 9]})
    assert code == 400 and body["error"] == "invalid_request"
    assert "9 features" in body["detail"]
    code, body = _call_err(port, "/predict", {"rows": [[0.0] * 10]})
    assert code == 400 and body["error"] == "invalid_request"
    code, body = _call_err(port, "/predict", {"model": "m"})
    assert code == 400 and "rows" in body["detail"]
    code, body = _call_err(port, "/nowhere")
    assert code == 404
    # malformed JSON body
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=b"{not json",
        method="POST")
    try:
        urllib.request.urlopen(req, timeout=10)
        assert False, "expected 400"
    except urllib.error.HTTPError as exc:
        assert exc.code == 400


def test_health_ready_stats_models(served):
    port, _, _ = served
    status, body = _call(port, "/healthz")
    assert status == 200 and body["status"] == "ok"
    assert body["breaker"]["state"] == "closed"
    status, body = _call(port, "/readyz")
    assert status == 200 and body["ready"]
    status, body = _call(port, "/statz")
    assert status == 200 and "batcher" in body
    status, body = _call(port, "/models")
    assert status == 200
    assert [m["name"] for m in body["models"]] == ["m"]
    assert body["models"][0]["n_features"] == 10


def test_model_upload_swap_and_unload_over_http(served):
    port, bst, svc = served
    rng = np.random.RandomState(1)
    X = rng.rand(300, 10)
    y = (X[:, 0] > 0.5).astype(np.float64)
    other = lgb.train({**PARAMS, "num_leaves": 7},
                      lgb.Dataset(X, label=y), num_boost_round=4)
    status, info = _call(port, "/models",
                         {"name": "other", "model_str":
                          other.model_to_string()})
    assert status == 200 and info["version"] == 1
    Q = rng.rand(9, 10)
    _, body = _call(port, "/predict", {"model": "other", "rows": Q.tolist()})
    assert np.array_equal(
        np.asarray(body["predictions"], np.float32),
        other.predict(Q).astype(np.float32))
    # corrupt text never lands; "other" keeps serving v1
    code, body = _call_err(port, "/models",
                           {"name": "other", "model_str": "garbage"})
    assert code == 400 and body["error"] == "model_load_error"
    assert svc.registry.get("other").version == 1
    status, body = _call(port, "/models/other", method="DELETE")
    assert status == 200 and body["unloaded"] == "other"
    code, body = _call_err(port, "/predict",
                           {"model": "other", "rows": Q.tolist()})
    assert code == 404


def test_concurrent_http_clients(served):
    port, bst, _ = served
    rng = np.random.RandomState(2)
    queries = [rng.rand(int(n), 10) for n in rng.randint(1, 64, size=12)]
    expected = [bst.predict(q).astype(np.float32) for q in queries]
    results = [None] * len(queries)
    errors = []

    def worker(i):
        try:
            _, body = _call(port, "/predict",
                            {"model": "m", "rows": queries[i].tolist()})
            results[i] = np.asarray(body["predictions"], np.float32)
        except Exception as exc:  # pragma: no cover - diagnostic
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(queries))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for got, want in zip(results, expected):
        assert np.array_equal(got, want)
