"""Open-loop load test (slow-marked): a fixed-rate arrival process that
does NOT slow down when the service does — the arrival generator keeps
firing while an injected slow_predict throttles the worker, so the queue
genuinely saturates. Asserts the three hardening contracts under
saturation: queue depth stays bounded (Overloaded/429 instead of growth),
expired requests are shed without ever reaching a device dispatch
(telemetry serve_batch row accounting), and every completed response is
bit-identical to the direct predict.
"""
import json
import threading
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry, tracing
from lightgbm_tpu.serving import (DeadlineExceeded, Overloaded,
                                  PredictionService)
from lightgbm_tpu.utils import faults

pytestmark = pytest.mark.slow

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def test_open_loop_saturation_bounded_and_correct(rng, tmp_path):
    bst = lgb.train(PARAMS, lgb.Dataset(
        rng.rand(500, 10),
        label=(rng.rand(500) > 0.5).astype(np.float64)), num_boost_round=8)
    max_queue_rows = 512
    rows_per_req = 64
    # batch = 2 requests, queue = 4 batches deep: a tail-of-queue request
    # waits several 50ms dispatches, far past its 60ms budget -> shed
    svc = PredictionService(max_batch_rows=128,
                            max_queue_rows=max_queue_rows,
                            batch_window_s=0.0)
    tracing.reset()  # clean recorder: stage accounting asserted below
    telemetry.start(str(tmp_path / "tele"), label="serve_load")
    try:
        svc.load_model("m", booster=bst)
        # every dispatch takes >= 50ms while arrivals land every ~2ms with
        # a 60ms deadline: the service MUST reject and shed to stay bounded
        faults.install("slow_predict@1:0.05")

        n_requests = 120
        queries = [rng.rand(rows_per_req, 10) for _ in range(3)]
        expected = [bst.predict(q) for q in queries]
        ok, overloaded, deadline = [], [], []
        peak_queue = [0]
        lock = threading.Lock()

        def fire(i):
            q = i % len(queries)
            try:
                out = svc.predict("m", queries[q], timeout_s=0.06)
                with lock:
                    ok.append((q, out))
            except Overloaded:
                with lock:
                    overloaded.append(i)
            except DeadlineExceeded:
                with lock:
                    deadline.append(i)

        threads = []
        for i in range(n_requests):
            t = threading.Thread(target=fire, args=(i,))
            t.start()
            threads.append(t)
            with lock:
                peak_queue[0] = max(peak_queue[0],
                                    svc.batcher.stats()["queue_rows"])
            time.sleep(0.002)  # open loop: fixed arrival rate
        for t in threads:
            t.join()
        faults.clear()
        # drain: abandoned (caller-timed-out) requests still sitting in the
        # queue are shed by the worker's next assembly passes
        t_end = time.monotonic() + 5.0
        while (svc.batcher.stats()["queue_rows"] > 0
               and time.monotonic() < t_end):
            time.sleep(0.02)
        stats = svc.batcher.stats()

        # 1. bounded admission: depth never exceeded the cap, and the
        #    saturation produced real Overloaded rejections
        assert peak_queue[0] <= max_queue_rows
        assert overloaded, "open-loop saturation never produced a 429"
        assert stats["queue_rows"] == 0
        # 2. every arrival accounted for exactly once
        assert len(ok) + len(overloaded) + len(deadline) == n_requests
        assert deadline, "60ms deadlines behind 50ms batches never expired"
        assert stats["deadline_shed"] >= 1
        # 3. completed responses bit-identical to the direct predict
        for q, out in ok:
            assert np.array_equal(out, expected[q])
    finally:
        faults.clear()
        telemetry.stop()
        svc.close()

    # 4. expired requests never reached the device: every ADMITTED request
    #    was either dispatched in exactly one serve_batch or shed exactly
    #    once — so telemetry batch rows + shed rows == admitted rows
    events_file = None
    for p in (tmp_path / "tele").rglob("events.jsonl"):
        events_file = p
    assert events_file is not None
    batch_rows = 0
    batch_requests = 0
    for line in events_file.read_text().splitlines():
        ev = json.loads(line)
        if ev.get("ev") == "serve_batch":
            batch_rows += int(ev["rows"])
            batch_requests += int(ev["requests"])
    admitted = len(ok) + len(deadline)
    assert batch_rows + stats["deadline_shed"] * rows_per_req \
        == admitted * rows_per_req
    assert batch_requests + stats["deadline_shed"] == admitted
    # shedding really suppressed dispatches: strictly fewer rows hit the
    # device than were admitted
    assert batch_rows < admitted * rows_per_req

    # 5. request-path tracing accounts for the wall: for every COMPLETED
    #    request span the stage marks are disjoint sections of the span
    #    (sum <= wall), and on the median span the decomposition explains
    #    most of it — queue_wait + the batch walls dominate under
    #    saturation (thread wake-up latency is the untracked remainder)
    spans = [r for r in tracing.recorder().snapshot()
             if r["kind"] == "span" and r["name"] == "serve_request"]
    done = [s for s in spans if "terminal" not in s]
    shed = [s for s in spans if s.get("terminal") == "shed"]
    assert len(done) == len(ok)
    coverages = []
    for s in done:
        wall_ms = (s["t1"] - s["t0"]) * 1000.0
        total_ms = sum(s["stages_ms"].values())
        assert total_ms <= wall_ms * 1.05 + 1.0, s
        assert {"queue_wait", "device"} <= set(s["stages_ms"]), s
        coverages.append(total_ms / max(wall_ms, 1e-9))
    if coverages:  # full saturation may complete zero requests in-deadline
        coverages.sort()
        assert coverages[len(coverages) // 2] >= 0.5, coverages
    # 6. every shed/expired request carries the terminal `shed` stage —
    #    the postmortem can tell a shed from a request that simply vanished
    assert shed, "saturation produced no shed spans"
    assert all("shed" in s["stages_ms"] for s in shed)
