"""Binary wire protocol suite (serving/wire.py + the HTTP fast path):
codec round-trips and zero-copy decode, every frame-fault -> typed
InvalidRequest, bit-identity with the JSON path over a live server —
including through the breaker's host-fallback path — and traceparent
propagation from inside the frame.
"""
import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (CircuitBreaker, InvalidRequest,
                                  PredictionService)
from lightgbm_tpu.serving import wire
from lightgbm_tpu.serving.http import serve
from lightgbm_tpu.utils import faults

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


# ---------------------------------------------------------------- codec


def test_request_roundtrip_f32():
    rng = np.random.RandomState(0)
    X = np.ascontiguousarray(rng.rand(13, 7), dtype=np.float32)
    frame = wire.encode_request("m", X, raw_score=True, timeout_ms=250,
                                traceparent="00-" + "ab" * 16 + "-"
                                + "cd" * 8 + "-01")
    dec = wire.decode_request(frame)
    assert dec.model == "m"
    assert dec.raw_score is True
    assert dec.timeout_ms == 250
    assert dec.traceparent.startswith("00-")
    assert dec.rows.dtype == np.float32
    assert np.array_equal(dec.rows, X)


def test_request_roundtrip_f64_and_defaults():
    X = np.arange(12, dtype=np.float64).reshape(3, 4)
    dec = wire.decode_request(wire.encode_request("model-x", X))
    assert dec.rows.dtype == np.float64
    assert np.array_equal(dec.rows, X)
    assert dec.raw_score is False
    assert dec.timeout_ms is None
    assert dec.traceparent is None


def test_decode_is_zero_copy():
    X = np.ascontiguousarray(np.random.rand(8, 5), dtype=np.float32)
    frame = wire.encode_request("m", X)
    dec = wire.decode_request(frame)
    # a view into the frame, not a copy: base chains back to the buffer
    assert dec.rows.base is not None
    assert not dec.rows.flags["OWNDATA"]


def test_response_roundtrip():
    preds = np.linspace(0, 1, 9, dtype=np.float32)
    buf = wire.encode_response(preds, model_version=3, latency_ms=1.5)
    got, version, latency = wire.decode_response(buf)
    assert np.array_equal(got, preds)
    assert version == 3
    assert latency == pytest.approx(1.5, abs=1e-3)


def test_response_multiclass_keeps_2d():
    preds = np.random.rand(6, 3).astype(np.float32)
    got, _, _ = wire.decode_response(
        wire.encode_response(preds, model_version=1, latency_ms=0.0))
    assert got.shape == (6, 3)
    assert np.array_equal(got, preds)


@pytest.mark.parametrize("mangle, needle", [
    (lambda f: b"", "shorter than"),
    (lambda f: f[:20], "shorter than"),
    (lambda f: b"XXXX" + f[4:], "bad wire magic"),
    (lambda f: f[:4] + b"\x09" + f[5:], "unsupported wire version"),
    (lambda f: f[:5] + b"\x07" + f[6:], "unexpected frame kind"),
    (lambda f: f[:6] + b"\x09" + f[7:], "unknown row-block dtype"),
    (lambda f: f[:-4], "does not match"),
    (lambda f: f + b"\x00" * 8, "does not match"),
])
def test_frame_faults_are_typed(mangle, needle):
    X = np.zeros((2, 3), dtype=np.float32)
    frame = wire.encode_request("m", X)
    with pytest.raises(InvalidRequest, match=needle):
        wire.decode_request(mangle(frame))


def test_empty_block_and_missing_name_rejected():
    hdr = wire._REQ.pack(wire.MAGIC, wire.VERSION, wire.KIND_PREDICT,
                         wire.DTYPE_F32, 0, 0, 3, 1, 0, 0)
    with pytest.raises(InvalidRequest, match="empty request"):
        wire.decode_request(hdr + b"m")
    X = np.zeros((1, 2), dtype=np.float32)
    frame = wire.encode_request("", X)
    with pytest.raises(InvalidRequest, match="missing model name"):
        wire.decode_request(frame)


# ------------------------------------------------------------- HTTP path


@pytest.fixture(scope="module")
def served():
    rng = np.random.RandomState(42)
    X = rng.rand(500, 10)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=8)
    breaker = CircuitBreaker(fail_threshold=2, probe_successes=1,
                             cooldown_s=30.0)
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0,
                            breaker=breaker)
    svc.load_model("m", booster=bst)
    server, thread = serve(svc, port=0)
    yield server.port, bst, svc
    server.shutdown()
    svc.close()


def _post_wire(port, body, traceparent=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=body,
        headers={"Content-Type": wire.CONTENT_TYPE})
    if traceparent:
        req.add_header("traceparent", traceparent)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return (resp.status, resp.read(),
                dict((k.lower(), v) for k, v in resp.headers.items()))


def test_wire_predict_bit_identical_to_json(served):
    port, bst, _ = served
    rng = np.random.RandomState(1)
    Q = np.ascontiguousarray(rng.rand(33, 10), dtype=np.float32)
    status, body, headers = _post_wire(
        port, wire.encode_request("m", Q, raw_score=True))
    assert status == 200
    assert headers["content-type"] == wire.CONTENT_TYPE
    preds, version, latency = wire.decode_response(body)
    assert version == 1 and latency >= 0.0
    # JSON path answer for the SAME rows
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps({"model": "m", "rows": Q.tolist(),
                         "raw_score": True}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=10) as resp:
        via_json = np.asarray(json.loads(resp.read())["predictions"],
                              dtype=np.float32)
    assert np.array_equal(preds, via_json)
    # and both equal the direct engine answer
    assert np.array_equal(
        preds, bst.predict(Q, raw_score=True).astype(np.float32))


def test_wire_f64_request_matches_json(served):
    port, bst, _ = served
    rng = np.random.RandomState(2)
    Q = rng.rand(9, 10)  # float64 block on the wire
    status, body, _ = _post_wire(port, wire.encode_request("m", Q))
    assert status == 200
    preds, _, _ = wire.decode_response(body)
    assert np.array_equal(preds, bst.predict(Q).astype(np.float32))


def test_wire_errors_are_json_bodies(served):
    port, _, _ = served
    # corrupt frame -> typed 400 with a JSON error body the client can
    # branch on via Content-Type
    frame = wire.encode_request("m", np.zeros((2, 10), dtype=np.float32))
    for bad, status, err in (
            (b"XXXX" + frame[4:], 400, "invalid_request"),
            (frame[:-8], 400, "invalid_request"),
            (wire.encode_request("ghost",
                                 np.zeros((1, 10), dtype=np.float32)),
             404, "model_not_found")):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict", data=bad,
            headers={"Content-Type": wire.CONTENT_TYPE})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == status
        ctype = ei.value.headers.get("Content-Type", "")
        assert ctype.startswith("application/json")
        assert json.loads(ei.value.read())["error"] == err


def test_wire_traceparent_in_frame_wins(served):
    port, _, _ = served
    frame_trace = "00-" + "1a" * 16 + "-" + "2b" * 8 + "-01"
    header_trace = "00-" + "3c" * 16 + "-" + "4d" * 8 + "-01"
    body = wire.encode_request("m", np.zeros((1, 10), dtype=np.float32),
                               traceparent=frame_trace)
    status, _, headers = _post_wire(port, body, traceparent=header_trace)
    assert status == 200
    # the response's traceparent continues the FRAME's trace id
    assert headers["traceparent"].split("-")[1] == "1a" * 16


def test_wire_bit_identical_on_host_fallback(served):
    port, bst, svc = served
    rng = np.random.RandomState(3)
    Q = np.ascontiguousarray(rng.rand(21, 10), dtype=np.float32)
    want = bst.predict(Q).astype(np.float32)
    # trip the per-entry breaker: two failed device dispatches open it
    faults.install("predict_fail@1:10")
    for _ in range(3):
        status, body, _ = _post_wire(port, wire.encode_request("m", Q))
        assert status == 200
        preds, _, _ = wire.decode_response(body)
        assert np.array_equal(preds, want)
    assert svc.breaker.info()["state"] == "open"
    # breaker OPEN -> host-pinned path; still bit-identical on the wire
    status, body, _ = _post_wire(port, wire.encode_request("m", Q))
    assert status == 200
    preds, _, _ = wire.decode_response(body)
    assert np.array_equal(preds, want)
    faults.clear()
    # reset the tripped shard so later tests see a closed breaker
    svc.breaker.forget_entry("m")
    svc.breaker.register_entry("m")
    assert svc.breaker.info()["state"] == "closed"


def test_wire_concurrent_clients_bit_exact(served):
    port, bst, _ = served
    rng = np.random.RandomState(4)
    blocks = [np.ascontiguousarray(rng.rand(16, 10), dtype=np.float32)
              for _ in range(10)]
    want = [bst.predict(b, raw_score=True).astype(np.float32)
            for b in blocks]
    got = [None] * len(blocks)

    def fire(i):
        _, body, _ = _post_wire(
            port, wire.encode_request("m", blocks[i], raw_score=True))
        got[i] = wire.decode_response(body)[0]

    threads = [threading.Thread(target=fire, args=(i,))
               for i in range(len(blocks))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(len(blocks)):
        assert np.array_equal(got[i], want[i])
