"""ICI-sharded whole-tree learner: bit-identity with the single-chip wave
learner on the 8 fake CPU devices conftest forces.

Bit-identity strategy per variant:

* plain / bagged — gh is GRID-SNAPPED (multiples of 2^-10, |v| <= 1, ~1k
  rows), so every f32 partial sum is exact in ANY summation order: the
  per-shard-then-psum reduction produces the same bits as the single-device
  full-N reduction, and the whole split log must match exactly.
* quantized — gradients are int8 and the histogram pool int32; integer
  addition commutes exactly, so the FULL GBDT driver (same PRNG stream,
  renewal densified to one device) is bit-identical end to end.

The only tolerance anywhere is on pure DIAGNOSTIC scalars: the recorded
split gain (XLA fuses its arithmetic differently in the two compiled
programs) and the tree's hessian-weight display fields (f32 sums whose
row order differs across shards). Thresholds, chosen features, child
sums/counts, leaf outputs and predictions are compared bit for bit.

Plus the ICI gauge: `device_ici_bytes_per_wave` is O(K*F_pad*Bmax*CH) —
independent of the row count — which is the whole point of data-parallel
sharding (docs/PERF_NOTES.md round-6 comm model).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel.learners import DeviceDataParallelTreeLearner
from lightgbm_tpu.treelearner.device import DeviceTreeLearner
from lightgbm_tpu.utils.timer import global_timer


def _snap(v):
    """Snap to the 2^-10 grid: f32 sums of ~1k such values are exact in
    any association order (integers < 2^24 in units of 2^-10)."""
    return np.round(np.clip(v, -1.0, 1.0) * 1024.0) / 1024.0


def _snapped_gh(rng, n):
    g = _snap(rng.uniform(-1.0, 1.0, n)).astype(np.float32)
    h = _snap(rng.uniform(0.25, 1.0, n)).astype(np.float32)
    gh = np.stack([g, h, np.ones(n, np.float32)], axis=1)
    return jnp.asarray(np.concatenate([gh, np.zeros((1, 3), np.float32)]))


def _learner(cls, X, y, params):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    return cls(cfg, ds)


# Diagnostic scalars that ride on f32 rounding, not on the split decision:
# split_gain picks up XLA fusion differences between the two compiled
# programs, and the *_weight fields are per-leaf f32 hessian sums whose
# row order differs across shards. Everything else must match bit for bit.
_ULP_FIELDS = {"split_gain", "internal_weight", "leaf_weight"}


def _assert_same_trees(a, b):
    assert len(a) == len(b)
    for ta, tb in zip(a, b):
        for k, va in ta.__dict__.items():
            vb = tb.__dict__[k]
            if k in _ULP_FIELDS:
                np.testing.assert_allclose(va, vb, rtol=1e-6, err_msg=k)
            elif isinstance(va, np.ndarray):
                np.testing.assert_array_equal(va, vb, err_msg=k)
            else:
                assert va == vb, k


@pytest.mark.parametrize("bagged", [False, True])
def test_sharded_split_log_bit_identical(rng, bagged):
    """One tree, grid-snapped gh: the device split log (rec_store) and the
    final per-row leaf ids of the sharded learner must match the
    single-device wave learner bit for bit."""
    n = 1100
    X = rng.randn(n, 7)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(float)
    gh_ext = _snapped_gh(rng, n)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbosity": -1}
    bag = (np.sort(np.random.RandomState(3).choice(n, 800, replace=False))
           .astype(np.int32) if bagged else None)

    logs, trees, ids = [], [], []
    for cls in (DeviceTreeLearner, DeviceDataParallelTreeLearner):
        learner = _learner(cls, X, y, params)
        pending = learner.train_async(gh_ext, bag)
        logs.append(np.asarray(pending.rec_store))
        trees.append(learner.finalize(pending))
        ids.append(np.asarray(learner.partition.ids_host))
    # col 4 is the packed SplitInfo gain scalar: its arithmetic picks up
    # XLA fusion differences between the two programs (1-ulp wobble); every
    # decision-bearing column — feature, threshold, sums, counts, outputs —
    # must be exact.
    gain_col = 4
    np.testing.assert_allclose(logs[0][:, gain_col], logs[1][:, gain_col],
                               rtol=1e-6)
    mask = np.ones(logs[0].shape[1], bool)
    mask[gain_col] = False
    np.testing.assert_array_equal(logs[0][:, mask], logs[1][:, mask])
    np.testing.assert_array_equal(ids[0], ids[1])
    _assert_same_trees(trees[:1], trees[1:])
    assert trees[0].num_leaves > 2  # the comparison saw a real tree


def test_sharded_quantized_driver_bit_identical(rng):
    """Quantized path through the FULL driver: int32 histogram reduction is
    exact under any order, the PRNG rounding stream is shared, and leaf
    renewal densifies — tree decisions, leaf values and predictions match
    exactly (weight diagnostics to 1 ulp, see module docstring)."""
    n = 1200
    X = rng.randn(n, 6)
    y = (X[:, 0] - 0.6 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "use_quantized_grad": True, "quant_train_renew_leaf": True}
    out = []
    for cls in (DeviceTreeLearner, DeviceDataParallelTreeLearner):
        cfg = Config(params)
        ds = CoreDataset.from_matrix(X, label=y, config=cfg)
        bst = GBDT(cfg, ds, create_objective("binary", cfg))
        bst.tree_learner = cls(cfg, ds)
        for _ in range(4):
            if bst.train_one_iter():
                break
        bst.to_model()
        out.append(bst)
    single, sharded = out
    _assert_same_trees(single.models, sharded.models)
    np.testing.assert_array_equal(
        np.asarray(single.predict(X, raw_score=True)),
        np.asarray(sharded.predict(X, raw_score=True)))


def test_sharded_learner_is_actually_sharded(rng):
    """The carry really spans the mesh: the bin plane and the returned
    leaf ids are laid out over all 8 fake devices, the split log is
    replicated, and growth commits the same tree everywhere."""
    n = 900
    X = rng.randn(n, 6)
    y = (X[:, 0] > 0).astype(float)
    learner = _learner(DeviceDataParallelTreeLearner, X, y,
                       {"objective": "binary", "num_leaves": 7,
                        "verbosity": -1})
    assert learner.D == 8
    assert len(learner.bins_dev.sharding.device_set) == 8
    pending = learner.train_async(_snapped_gh(rng, n))
    assert len(pending.leaf_id.sharding.device_set) == 8
    tree = learner.finalize(pending)
    assert tree.num_leaves > 1
    assert learner.partition.ids_host.shape == (n,)


def test_ici_bytes_gauge_independent_of_rows(rng):
    """The comm-volume claim the docs make: per-wave ICI traffic is
    O(K * F_pad * Bmax * CH) and does NOT scale with N. max_bin=16 so both
    datasets saturate the bin budget and differ ONLY in row count."""
    params = {"objective": "binary", "num_leaves": 7, "max_bin": 16,
              "verbosity": -1}
    gauges = []
    for n in (600, 2400):
        X = rng.randn(n, 6)
        y = (X[:, 0] > 0).astype(float)
        learner = _learner(DeviceDataParallelTreeLearner, X, y, params)
        global_timer.counters.pop("device_ici_bytes_per_wave", None)
        learner.finalize(learner.train_async(_snapped_gh(rng, n)))
        gauges.append(global_timer.counters["device_ici_bytes_per_wave"])
    assert gauges[0] == gauges[1], gauges
    assert gauges[0] > 0


def test_gh_bf16_payload_opt_in(rng, monkeypatch):
    """LGBM_TPU_GH_BF16=1 narrows the wave-carry payload (2 packed gh
    columns instead of 3) and still grows a sane tree; default stays f32
    with full payload width."""
    from lightgbm_tpu.treelearner import device as device_mod

    n = 700
    X = rng.randn(n, 6)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}

    monkeypatch.delenv("LGBM_TPU_GH_BF16", raising=False)
    base = _learner(DeviceTreeLearner, X, y, params)
    assert base._payload_cols() == 5
    tree_f32 = base.train(_snapped_gh(rng, n))

    monkeypatch.setenv("LGBM_TPU_GH_BF16", "1")
    device_mod.grow_tree_on_device.clear_cache()
    try:
        narrow = _learner(DeviceTreeLearner, X, y, params)
        assert narrow._payload_cols() == 4
        tree_bf16 = narrow.train(_snapped_gh(rng, n))
        # bit-identity is NOT guaranteed (bf16 keeps 8 mantissa bits, the
        # snapped grid needs 10) — it must simply grow a real tree
        assert tree_bf16.num_leaves > 1
        assert tree_f32.num_leaves > 1
    finally:
        device_mod.grow_tree_on_device.clear_cache()


def test_factory_routes_data_to_host_learner_on_cpu(rng):
    """On the CPU backend device growth never applies, so tree_learner=data
    keeps selecting the host-driven data-parallel learner (the fallback
    path the sharded learner is documented to leave intact)."""
    from lightgbm_tpu.parallel.learners import (DataParallelTreeLearner,
                                                create_parallel_learner)

    X = rng.randn(300, 5)
    y = (X[:, 0] > 0).astype(float)
    cfg = Config({"objective": "binary", "num_leaves": 7,
                  "num_machines": 8, "verbosity": -1})
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    learner = create_parallel_learner("data", cfg, ds)
    assert isinstance(learner, DataParallelTreeLearner)
    assert not isinstance(learner, DeviceDataParallelTreeLearner)
