"""sklearn-API tests (subset of the reference's test_sklearn.py surface)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _xy_binary(n=1500, f=8, seed=1):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(int)
    return X, y


def test_classifier_binary():
    X, y = _xy_binary()
    clf = lgb.LGBMClassifier(n_estimators=20, num_leaves=15)
    clf.fit(X, y)
    pred = clf.predict(X)
    assert set(np.unique(pred)) <= {0, 1}
    assert (pred == y).mean() > 0.9
    proba = clf.predict_proba(X)
    assert proba.shape == (len(y), 2)
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, atol=1e-5)
    assert clf.n_classes_ == 2
    assert list(clf.classes_) == [0, 1]
    assert clf.n_features_ == 8
    assert clf.feature_importances_.shape == (8,)


def test_classifier_multiclass_string_labels():
    rng = np.random.RandomState(0)
    X = rng.randn(900, 6)
    y_int = np.argmax(X[:, :3] + rng.randn(900, 3) * 0.3, axis=1)
    y = np.array(["a", "b", "c"])[y_int]
    clf = lgb.LGBMClassifier(n_estimators=15, num_leaves=7)
    clf.fit(X, y)
    pred = clf.predict(X)
    assert set(np.unique(pred)) <= {"a", "b", "c"}
    assert (pred == y).mean() > 0.8
    proba = clf.predict_proba(X)
    assert proba.shape == (900, 3)


def test_regressor_with_eval_set_early_stopping():
    rng = np.random.RandomState(2)
    X = rng.randn(2000, 8)
    y = 2 * X[:, 0] - X[:, 1] + 0.2 * rng.randn(2000)
    Xt, yt, Xv, yv = X[:1500], y[:1500], X[1500:], y[1500:]
    reg = lgb.LGBMRegressor(n_estimators=100, num_leaves=15)
    reg.fit(Xt, yt, eval_set=[(Xv, yv)],
            callbacks=[lgb.early_stopping(5, verbose=False)])
    assert reg.best_iteration_ > 0
    pred = reg.predict(Xv)
    r2 = 1 - np.mean((pred - yv) ** 2) / np.var(yv)
    assert r2 > 0.9
    assert "valid_0" in reg.evals_result_


def test_regressor_sklearn_clone_and_params():
    from sklearn.base import clone

    reg = lgb.LGBMRegressor(n_estimators=5, num_leaves=7, reg_alpha=0.1)
    reg2 = clone(reg)
    assert reg2.get_params()["reg_alpha"] == 0.1
    X, y = _xy_binary(300)
    reg2.fit(X, y.astype(float))
    assert reg2.predict(X).shape == (300,)


def test_ranker():
    rng = np.random.RandomState(3)
    n_q, per_q = 40, 20
    X = rng.randn(n_q * per_q, 6)
    rel = (X[:, 0] + rng.randn(n_q * per_q) * 0.5)
    y = np.clip((rel * 2).astype(int) - rel.astype(int).min(), 0, 4)
    group = np.full(n_q, per_q)
    rk = lgb.LGBMRanker(n_estimators=10, num_leaves=7)
    rk.fit(X, y, group=group)
    scores = rk.predict(X)
    assert scores.shape == (n_q * per_q,)
    # ranking scores should correlate with relevance
    assert np.corrcoef(scores, y)[0, 1] > 0.5


def test_ranker_requires_group():
    X, y = _xy_binary(100)
    with pytest.raises(ValueError):
        lgb.LGBMRanker().fit(X, y)


def test_plotting_smoke(tmp_path):
    import matplotlib

    matplotlib.use("Agg")
    X, y = _xy_binary(500)
    record = {}
    ds = lgb.Dataset(X, label=y.astype(float))
    dv = lgb.Dataset(X[:100], label=y[:100].astype(float), reference=ds)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "metric": "binary_logloss", "verbosity": -1},
                    ds, num_boost_round=5, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(record)])
    ax = lgb.plot_importance(bst)
    assert ax is not None
    ax = lgb.plot_metric(record)
    assert ax is not None
    ax = lgb.plot_tree(bst, tree_index=0)
    assert ax is not None
    used = int(np.argmax(bst.feature_importance()))
    ax = lgb.plot_split_value_histogram(bst, used)
    assert ax is not None
