"""Sparse (scipy CSR/CSC) ingestion: identical bins and models to the
dense equivalent, without densifying the full matrix (io/dataset.py
column-at-a-time construction; c_api.cpp LGBM_DatasetCreateFromCSR is the
reference analog)."""
import numpy as np
import pytest

sp = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb


def _sparse_data(rng, n=1200, f=12, density=0.15):
    M = sp.random(n, f, density=density, random_state=rng, format="csr")
    Xd = M.toarray()
    y = (Xd[:, 0] - Xd[:, 1] + 0.05 * rng.randn(n) > 0).astype(float)
    return M, Xd, y


def test_sparse_matches_dense(rng):
    M, Xd, y = _sparse_data(rng)
    params = {"objective": "binary", "num_leaves": 15, "min_data_in_leaf": 20,
              "verbosity": -1}
    bst_s = lgb.train(params, lgb.Dataset(M, label=y), num_boost_round=8)
    bst_d = lgb.train(params, lgb.Dataset(Xd, label=y), num_boost_round=8)
    np.testing.assert_allclose(bst_s.predict(Xd), bst_d.predict(Xd),
                               rtol=1e-6)


def test_sparse_core_bins_identical(rng):
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset

    M, Xd, y = _sparse_data(rng, n=600, f=8)
    ds_s = CoreDataset.from_matrix(M.tocsc(), label=y)
    ds_d = CoreDataset.from_matrix(Xd, label=y)
    np.testing.assert_array_equal(ds_s.bins, ds_d.bins)


def test_sparse_valid_set_alignment(rng):
    M, Xd, y = _sparse_data(rng)
    train = lgb.Dataset(M[:900], label=y[:900])
    valid = lgb.Dataset(M[900:], label=y[900:], reference=train)
    evals = {}
    lgb.train({"objective": "binary", "num_leaves": 7, "metric": "auc",
               "verbosity": -1}, train, num_boost_round=5,
              valid_sets=[valid],
              callbacks=[lgb.record_evaluation(evals)])
    assert evals["valid_0"]["auc"][-1] > 0.7


def test_sparse_linear_tree_rejected(rng):
    M, _Xd, y = _sparse_data(rng, n=300, f=5)
    with pytest.raises(Exception):
        lgb.train({"objective": "regression", "linear_tree": True,
                   "verbosity": -1}, lgb.Dataset(M, label=y),
                  num_boost_round=2)


def test_sparse_cv(rng):
    M, _Xd, y = _sparse_data(rng, n=800, f=8)
    res = lgb.cv({"objective": "binary", "num_leaves": 7, "metric": "auc",
                  "verbosity": -1}, lgb.Dataset(M, label=y),
                 num_boost_round=4, nfold=3)
    key = [k for k in res if "auc" in k][0]
    assert len(res[key]) == 4


def test_sparse_continued_training(rng):
    M, Xd, y = _sparse_data(rng)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1}
    first = lgb.train(params, lgb.Dataset(M, label=y), num_boost_round=3)
    # reference python semantics: the predictor seeds init_score; the new
    # booster holds only the continuation trees (engine.py:233-244)
    cont = lgb.train(params, lgb.Dataset(M, label=y), num_boost_round=3,
                     init_model=first)
    assert cont.current_iteration() == 3
    assert np.isfinite(cont.predict(Xd)).all()
