"""Out-of-core streaming engine: the ISSUE acceptance suite.

Locks the four contracts of docs/STREAMING.md on the CPU tier:

  * streamed-vs-resident bit-identity — the StreamedTreeLearner under a
    budget 4x smaller than the bin plane (real evictions) and under a
    budget that fits everything (pin-all) trains byte-identical models to
    the resident SerialTreeLearner, across plain / bagged / quantized;
  * push-vs-one-shot equivalence — chunked RowBlockStore ingest (dense,
    CSR, iterator) finalizes into the same plane/metadata and trains the
    same model as one-shot construction, including on the 8-virtual-device
    data-parallel learner;
  * continuous-training crash consistency — an injected mid-refit kill
    resumes from the generation checkpoint bit-identically even while new
    pushes keep landing (the row-watermark contract);
  * zero-downtime hot-swap — refit generations publish into a live
    PredictionService under concurrent predict load with zero failures.
"""
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.engine import train
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.streaming import (ContinuousTrainer, RowBlockStore,
                                    StreamedTreeLearner, wrap_dataset)
from lightgbm_tpu.streaming.learner import (BLOCK_ROWS_ENV, BUDGET_ENV,
                                            parse_budget_bytes)
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.faults import InjectedFault

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _data(seed=3, n=2048, f=12):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.3 > 0)
    return X, y.astype(np.float64)


def _model(params, X, y, rounds=5):
    return train(dict(params), lgb.Dataset(X, label=y),
                 num_boost_round=rounds)


def _plane_bytes(params, X, y):
    core = CoreDataset.from_matrix(X, label=y, config=Config(dict(params)))
    return core.bins.size * core.bins.dtype.itemsize, core.bins.shape[0]


# ------------------------------------------------ streamed-vs-resident

@pytest.mark.parametrize("extra", [
    {},
    {"bagging_fraction": 0.7, "bagging_freq": 1},
    {"feature_fraction": 0.8},
    {"use_quantized_grad": True},
], ids=["plain", "bagged", "featfrac", "quantized"])
def test_streamed_bit_identical_starved_budget(monkeypatch, extra):
    """Budget = 2 blocks of 8 (plane is exactly 4x the budget): the
    acceptance bound — eviction + prefetch churn must not move a bit."""
    X, y = _data()
    params = {**BASE, **extra}
    resident = _model(params, X, y)

    plane, groups = _plane_bytes(params, X, y)
    block_bytes = groups * 256  # uint8 plane
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(BUDGET_ENV, str(2 * block_bytes))
    assert plane >= 4 * (2 * block_bytes)
    streamed = _model(params, X, y)

    assert resident.model_to_string() == streamed.model_to_string()
    np.testing.assert_array_equal(
        np.asarray(resident.predict(X, raw_score=True)),
        np.asarray(streamed.predict(X, raw_score=True)))


def test_streamed_bit_identical_when_plane_fits(monkeypatch):
    """A budget covering the whole plane pins every block — same code
    path, zero evictions, still bit-identical."""
    X, y = _data(n=1024)
    resident = _model(BASE, X, y)
    monkeypatch.setenv(BUDGET_ENV, "1g")
    streamed = _model(BASE, X, y)
    assert resident.model_to_string() == streamed.model_to_string()


def test_streaming_factory_routing(monkeypatch):
    X, y = _data(n=512)
    monkeypatch.setenv(BUDGET_ENV, "64k")
    bst = lgb.Booster(params=dict(BASE), train_set=lgb.Dataset(X, label=y))
    learner = bst._gbdt.tree_learner
    assert isinstance(learner, StreamedTreeLearner)
    assert learner.bins_dev is None  # the plane never uploads whole


def test_parse_budget_bytes():
    assert parse_budget_bytes("64k") == 64 << 10
    assert parse_budget_bytes("1.5m") == int(1.5 * (1 << 20))
    assert parse_budget_bytes("2g") == 2 << 30
    assert parse_budget_bytes("12345") == 12345
    assert parse_budget_bytes("") is None
    assert parse_budget_bytes(None) is None
    assert parse_budget_bytes("0") is None
    assert parse_budget_bytes("junk") is None


# ------------------------------------------------- push-vs-one-shot

def test_push_rows_matches_one_shot():
    X, y = _data(n=900)
    params = dict(BASE)
    store = RowBlockStore(params=params)
    for lo in range(0, 900, 256):
        hi = min(900, lo + 256)
        store.push_rows(X[lo:hi], label=y[lo:hi])
    core = store.finalize()
    oneshot = CoreDataset.from_matrix(X, label=y, config=Config(params))
    assert np.array_equal(core.bins, oneshot.bins)
    assert core.num_data == oneshot.num_data
    assert len(core.groups) == len(oneshot.groups)
    np.testing.assert_array_equal(np.asarray(core.metadata.label),
                                  np.asarray(oneshot.metadata.label))

    pushed = train(dict(params), store.to_basic_dataset(params=params),
                   num_boost_round=5)
    direct = _model(params, X, y)
    assert pushed.model_to_string() == direct.model_to_string()


def _dense_to_csr(M):
    indptr, indices, values = [0], [], []
    for row in M:
        nz = np.flatnonzero(row)
        indices.extend(nz.tolist())
        values.extend(row[nz].tolist())
        indptr.append(len(indices))
    return (np.asarray(indptr, np.int64), np.asarray(indices, np.int32),
            np.asarray(values, np.float64))


def test_push_csr_and_iterator_match_dense():
    X, y = _data(n=600, f=8)
    dense = RowBlockStore(params=dict(BASE))
    csr = RowBlockStore(params=dict(BASE))
    it = RowBlockStore(params=dict(BASE))
    chunks = [(X[lo:lo + 200], y[lo:lo + 200]) for lo in range(0, 600, 200)]
    for cx, cy in chunks:
        dense.push_rows(cx, label=cy)
        ip, ix, vals = _dense_to_csr(cx.astype(np.float64))
        csr.push_csr(ip, ix, vals, X.shape[1], label=cy)
    it.push_from_iterator(iter(chunks))
    a, b, c = dense.finalize(), csr.finalize(), it.finalize()
    assert np.array_equal(a.bins, b.bins)
    assert np.array_equal(a.bins, c.bins)
    np.testing.assert_array_equal(np.asarray(a.metadata.label),
                                  np.asarray(b.metadata.label))


def test_pushed_dataset_trains_on_sharded_learner():
    """The finalized streamed dataset drops into the 8-virtual-device
    data-parallel learner and reproduces the one-shot model exactly."""
    X, y = _data(n=1024)
    params = {**BASE, "tree_learner": "data"}
    store = RowBlockStore(params=params)
    for lo in range(0, 1024, 300):
        hi = min(1024, lo + 300)
        store.push_rows(X[lo:hi], label=y[lo:hi])
    pushed = train(dict(params), store.to_basic_dataset(params=params),
                   num_boost_round=4)
    direct = _model(params, X, y, rounds=4)
    assert pushed.model_to_string() == direct.model_to_string()


def test_push_errors():
    store = RowBlockStore()
    store.push_rows(np.zeros((4, 3), np.float32))
    with pytest.raises(ValueError, match="features"):
        store.push_rows(np.zeros((4, 5), np.float32))
    with pytest.raises(ValueError, match="label length"):
        store.push_rows(np.zeros((4, 3), np.float32), label=np.zeros(3))
    with pytest.raises(ValueError, match="exceeds pushed rows"):
        store.finalize(99)
    empty = RowBlockStore()
    with pytest.raises(ValueError, match="empty"):
        empty.finalize()


# --------------------------------------------- continuous: crash resume

def test_refit_kill_and_resume_bit_identical(tmp_path):
    """The flywheel acceptance scenario: a kill mid-refit, new rows still
    landing, then a retried step() trains the pinned watermark rows from
    the generation checkpoint — byte-identical to the uninterrupted run."""
    X, y = _data(n=800)
    params = dict(BASE)

    def _filled_store():
        s = RowBlockStore(params=params)
        for lo in range(0, 600, 200):
            s.push_rows(X[lo:lo + 200], label=y[lo:lo + 200])
        return s

    clean = ContinuousTrainer(params, _filled_store(), num_boost_round=6,
                              checkpoint_dir=str(tmp_path / "clean"))
    straight = clean.refit()

    crashy_store = _filled_store()
    crashy = ContinuousTrainer(params, crashy_store, num_boost_round=6,
                               checkpoint_dir=str(tmp_path / "crashy"))
    faults.install("kill@3")
    with pytest.raises(InjectedFault):
        crashy.step()
    faults.clear()
    assert crashy.generation == 0
    # pushes keep landing while the refit is down — the watermark must
    # keep the retried generation's dataset pinned to the pre-crash rows
    crashy_store.push_rows(X[600:800], label=y[600:800])
    resumed = crashy.step()
    assert resumed.model_to_string() == straight.model_to_string()
    assert crashy.generation == 1

    # the NEXT generation picks up the post-crash rows
    second = crashy.step()
    assert second is not None
    assert crashy.generation == 2
    assert second.model_to_string() != straight.model_to_string()


def test_step_noops_below_threshold():
    X, y = _data(n=400)
    store = RowBlockStore(params=dict(BASE))
    store.push_rows(X, label=y)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=2,
                           min_new_rows=100)
    assert tr.step() is not None  # first call always fits
    assert tr.step() is None     # no fresh rows
    store.push_rows(X[:50], label=y[:50])
    assert tr.step() is None     # below min_new_rows
    store.push_rows(X[50:150], label=y[50:150])
    assert tr.step() is not None


# ------------------------------------------------ continuous: hot-swap

def test_refit_hot_swap_zero_failed_predicts():
    from lightgbm_tpu.serving import PredictionService

    X, y = _data(n=700, f=6)
    store = RowBlockStore(params=dict(BASE))
    store.push_rows(X[:300], label=y[:300])
    svc = PredictionService(max_batch_rows=512, batch_window_s=0.0005)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=3,
                           service=svc, model_name="live")
    try:
        tr.refit()  # publish generation 1 before load starts
        failures, done = [], threading.Event()

        def hammer():
            while not done.is_set():
                try:
                    out = svc.predict("live", X[:16], raw_score=True)
                    assert out.shape[0] == 16
                except Exception as e:  # noqa: BLE001 - the assertion target
                    failures.append(repr(e))

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        for lo in (300, 500):
            store.push_rows(X[lo:lo + 200], label=y[lo:lo + 200])
            tr.step()
        done.set()
        for t in threads:
            t.join()
    finally:
        svc.close()
    assert failures == []
    assert tr.generation == 3
    assert svc.registry.get("live").version == 3


# ------------------------------------------------------- C-API shims

class _FakeFfi:
    """Just enough of cffi's ffi for capi/impl: zero-copy buffer views
    over numpy arrays and pass-through byte strings."""

    def buffer(self, obj, size=None):
        mv = memoryview(obj).cast("B")
        return mv if size is None else mv[:size]

    def string(self, s):
        return s if isinstance(s, bytes) else str(s).encode()


def test_capi_push_rows_shims():
    from lightgbm_tpu.capi import impl

    ffi = _FakeFfi()
    X, y = _data(n=600, f=8)
    Xd = np.ascontiguousarray(X, dtype=np.float64)

    out = [0]
    assert impl.dataset_create_streaming(ffi, 0, b"verbosity=-1", out) == 0
    handle = out[0]
    try:
        # dense push (float64 = C_API_DTYPE 1), then a CSR push
        assert impl.dataset_push_rows(ffi, handle, Xd[:400], 1,
                                      400, 8, 0) == 0
        ip, ix, vals = _dense_to_csr(Xd[400:])
        assert impl.dataset_push_rows_by_csr(
            ffi, handle, ip, 3, ix, vals, 1, len(ip), len(vals), 8, 400) == 0
        with pytest.raises(ValueError, match="non-sequential"):
            impl.dataset_push_rows(ffi, handle, Xd[:400], 1, 400, 8, 0)

        yf = np.asarray(y, dtype=np.float32)
        assert impl.dataset_set_field(ffi, handle, b"label", yf,
                                      len(yf), 0) == 0
        nd, nf = [0], [0]
        impl.dataset_get_num_data(ffi, handle, nd)
        impl.dataset_get_num_feature(ffi, handle, nf)
        assert (nd[0], nf[0]) == (600, 8)

        bout = [0]
        assert impl.booster_create(
            ffi, handle,
            b"objective=binary num_leaves=15 verbosity=-1 num_iterations=3",
            bout) == 0
        try:
            fin = [0]
            for _ in range(3):
                impl.booster_update_one_iter(ffi, bout[0], fin)
            capi_bst = impl._get(bout[0])
            assert capi_bst.current_iteration() == 3

            # the shim route trains the same bits as the python route
            store = RowBlockStore(params={"verbosity": -1})
            store.push_rows(Xd[:400]).push_rows(Xd[400:])
            store.set_label(yf)
            direct = train({"objective": "binary", "num_leaves": "15",
                            "verbosity": "-1", "num_iterations": "3"},
                           store.to_basic_dataset(), num_boost_round=3)
            assert capi_bst.model_to_string() == direct.model_to_string()
        finally:
            impl.booster_free(ffi, bout[0])
    finally:
        impl.dataset_free(ffi, handle)


def test_capi_non_streaming_handle_rejected():
    from lightgbm_tpu.capi import impl

    ffi = _FakeFfi()
    h = impl._register(object())
    try:
        with pytest.raises(TypeError, match="streaming"):
            impl.dataset_push_rows(ffi, h, np.zeros((1, 2)), 1, 1, 2, 0)
    finally:
        impl._free(h)
