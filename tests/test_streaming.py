"""Out-of-core streaming engine: the ISSUE acceptance suite.

Locks the four contracts of docs/STREAMING.md on the CPU tier:

  * streamed-vs-resident bit-identity — the StreamedTreeLearner under a
    budget 4x smaller than the bin plane (real evictions) and under a
    budget that fits everything (pin-all) trains byte-identical models to
    the resident SerialTreeLearner, across plain / bagged / quantized;
  * push-vs-one-shot equivalence — chunked RowBlockStore ingest (dense,
    CSR, iterator) finalizes into the same plane/metadata and trains the
    same model as one-shot construction, including on the 8-virtual-device
    data-parallel learner;
  * continuous-training crash consistency — an injected mid-refit kill
    resumes from the generation checkpoint bit-identically even while new
    pushes keep landing (the row-watermark contract);
  * zero-downtime hot-swap — refit generations publish into a live
    PredictionService under concurrent predict load with zero failures.
"""
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.engine import train
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.streaming import (ContinuousTrainer, RowBlockStore,
                                    StreamedTreeLearner, wrap_dataset)
from lightgbm_tpu.streaming.learner import (BLOCK_ROWS_ENV, BUDGET_ENV,
                                            parse_budget_bytes)
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.faults import InjectedFault

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faults.clear()


def _data(seed=3, n=2048, f=12):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.3 > 0)
    return X, y.astype(np.float64)


def _model(params, X, y, rounds=5):
    return train(dict(params), lgb.Dataset(X, label=y),
                 num_boost_round=rounds)


def _plane_bytes(params, X, y):
    core = CoreDataset.from_matrix(X, label=y, config=Config(dict(params)))
    return core.bins.size * core.bins.dtype.itemsize, core.bins.shape[0]


# ------------------------------------------------ streamed-vs-resident

@pytest.mark.parametrize("extra", [
    # plain/bagged legs are the heaviest: slow tier (tier-1 budget
    # triage); featfrac + quantized keep the bound in every tier-1 run
    pytest.param({}, id="plain", marks=pytest.mark.slow),
    pytest.param({"bagging_fraction": 0.7, "bagging_freq": 1}, id="bagged",
                 marks=pytest.mark.slow),
    pytest.param({"feature_fraction": 0.8}, id="featfrac"),
    pytest.param({"use_quantized_grad": True}, id="quantized"),
])
def test_streamed_bit_identical_starved_budget(monkeypatch, extra):
    """Budget = 2 blocks of 8 (plane is exactly 4x the budget): the
    acceptance bound — eviction + prefetch churn must not move a bit."""
    X, y = _data()
    params = {**BASE, **extra}
    resident = _model(params, X, y)

    plane, groups = _plane_bytes(params, X, y)
    block_bytes = groups * 256  # uint8 plane
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(BUDGET_ENV, str(2 * block_bytes))
    assert plane >= 4 * (2 * block_bytes)
    streamed = _model(params, X, y)

    assert resident.model_to_string() == streamed.model_to_string()
    np.testing.assert_array_equal(
        np.asarray(resident.predict(X, raw_score=True)),
        np.asarray(streamed.predict(X, raw_score=True)))


def test_streamed_bit_identical_when_plane_fits(monkeypatch):
    """A budget covering the whole plane pins every block — same code
    path, zero evictions, still bit-identical."""
    X, y = _data(n=1024)
    resident = _model(BASE, X, y)
    monkeypatch.setenv(BUDGET_ENV, "1g")
    streamed = _model(BASE, X, y)
    assert resident.model_to_string() == streamed.model_to_string()


def test_streaming_factory_routing(monkeypatch):
    X, y = _data(n=512)
    monkeypatch.setenv(BUDGET_ENV, "64k")
    bst = lgb.Booster(params=dict(BASE), train_set=lgb.Dataset(X, label=y))
    learner = bst._gbdt.tree_learner
    assert isinstance(learner, StreamedTreeLearner)
    assert learner.bins_dev is None  # the plane never uploads whole


def test_parse_budget_bytes():
    assert parse_budget_bytes("64k") == 64 << 10
    assert parse_budget_bytes("1.5m") == int(1.5 * (1 << 20))
    assert parse_budget_bytes("2g") == 2 << 30
    assert parse_budget_bytes("12345") == 12345
    assert parse_budget_bytes("") is None
    assert parse_budget_bytes(None) is None
    assert parse_budget_bytes("0") is None
    assert parse_budget_bytes("junk") is None


# ------------------------------------------------- push-vs-one-shot

def test_push_rows_matches_one_shot():
    X, y = _data(n=900)
    params = dict(BASE)
    store = RowBlockStore(params=params)
    for lo in range(0, 900, 256):
        hi = min(900, lo + 256)
        store.push_rows(X[lo:hi], label=y[lo:hi])
    core = store.finalize()
    oneshot = CoreDataset.from_matrix(X, label=y, config=Config(params))
    assert np.array_equal(core.bins, oneshot.bins)
    assert core.num_data == oneshot.num_data
    assert len(core.groups) == len(oneshot.groups)
    np.testing.assert_array_equal(np.asarray(core.metadata.label),
                                  np.asarray(oneshot.metadata.label))

    pushed = train(dict(params), store.to_basic_dataset(params=params),
                   num_boost_round=5)
    direct = _model(params, X, y)
    assert pushed.model_to_string() == direct.model_to_string()


def _dense_to_csr(M):
    indptr, indices, values = [0], [], []
    for row in M:
        nz = np.flatnonzero(row)
        indices.extend(nz.tolist())
        values.extend(row[nz].tolist())
        indptr.append(len(indices))
    return (np.asarray(indptr, np.int64), np.asarray(indices, np.int32),
            np.asarray(values, np.float64))


def test_push_csr_and_iterator_match_dense():
    X, y = _data(n=600, f=8)
    dense = RowBlockStore(params=dict(BASE))
    csr = RowBlockStore(params=dict(BASE))
    it = RowBlockStore(params=dict(BASE))
    chunks = [(X[lo:lo + 200], y[lo:lo + 200]) for lo in range(0, 600, 200)]
    for cx, cy in chunks:
        dense.push_rows(cx, label=cy)
        ip, ix, vals = _dense_to_csr(cx.astype(np.float64))
        csr.push_csr(ip, ix, vals, X.shape[1], label=cy)
    it.push_from_iterator(iter(chunks))
    a, b, c = dense.finalize(), csr.finalize(), it.finalize()
    assert np.array_equal(a.bins, b.bins)
    assert np.array_equal(a.bins, c.bins)
    np.testing.assert_array_equal(np.asarray(a.metadata.label),
                                  np.asarray(b.metadata.label))


def test_pushed_dataset_trains_on_sharded_learner():
    """The finalized streamed dataset drops into the 8-virtual-device
    data-parallel learner and reproduces the one-shot model exactly."""
    X, y = _data(n=1024)
    params = {**BASE, "tree_learner": "data"}
    store = RowBlockStore(params=params)
    for lo in range(0, 1024, 300):
        hi = min(1024, lo + 300)
        store.push_rows(X[lo:hi], label=y[lo:hi])
    pushed = train(dict(params), store.to_basic_dataset(params=params),
                   num_boost_round=4)
    direct = _model(params, X, y, rounds=4)
    assert pushed.model_to_string() == direct.model_to_string()


def test_push_errors():
    store = RowBlockStore()
    store.push_rows(np.zeros((4, 3), np.float32))
    with pytest.raises(ValueError, match="features"):
        store.push_rows(np.zeros((4, 5), np.float32))
    with pytest.raises(ValueError, match="label length"):
        store.push_rows(np.zeros((4, 3), np.float32), label=np.zeros(3))
    with pytest.raises(ValueError, match="exceeds pushed rows"):
        store.finalize(99)
    empty = RowBlockStore()
    with pytest.raises(ValueError, match="empty"):
        empty.finalize()


# --------------------------------------------- continuous: crash resume

def test_refit_kill_and_resume_bit_identical(tmp_path):
    """The flywheel acceptance scenario: a kill mid-refit, new rows still
    landing, then a retried step() trains the pinned watermark rows from
    the generation checkpoint — byte-identical to the uninterrupted run."""
    X, y = _data(n=800)
    params = dict(BASE)

    def _filled_store():
        s = RowBlockStore(params=params)
        for lo in range(0, 600, 200):
            s.push_rows(X[lo:lo + 200], label=y[lo:lo + 200])
        return s

    clean = ContinuousTrainer(params, _filled_store(), num_boost_round=6,
                              checkpoint_dir=str(tmp_path / "clean"))
    straight = clean.refit()

    crashy_store = _filled_store()
    crashy = ContinuousTrainer(params, crashy_store, num_boost_round=6,
                               checkpoint_dir=str(tmp_path / "crashy"))
    faults.install("kill@3")
    with pytest.raises(InjectedFault):
        crashy.step()
    faults.clear()
    assert crashy.generation == 0
    # pushes keep landing while the refit is down — the watermark must
    # keep the retried generation's dataset pinned to the pre-crash rows
    crashy_store.push_rows(X[600:800], label=y[600:800])
    resumed = crashy.step()
    assert resumed.model_to_string() == straight.model_to_string()
    assert crashy.generation == 1

    # the NEXT generation picks up the post-crash rows
    second = crashy.step()
    assert second is not None
    assert crashy.generation == 2
    assert second.model_to_string() != straight.model_to_string()


def test_step_noops_below_threshold():
    X, y = _data(n=400)
    store = RowBlockStore(params=dict(BASE))
    store.push_rows(X, label=y)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=2,
                           min_new_rows=100)
    assert tr.step() is not None  # first call always fits
    assert tr.step() is None     # no fresh rows
    store.push_rows(X[:50], label=y[:50])
    assert tr.step() is None     # below min_new_rows
    store.push_rows(X[50:150], label=y[50:150])
    assert tr.step() is not None


# ------------------------------------------------ continuous: hot-swap

def test_refit_hot_swap_zero_failed_predicts():
    from lightgbm_tpu.serving import PredictionService

    X, y = _data(n=700, f=6)
    store = RowBlockStore(params=dict(BASE))
    store.push_rows(X[:300], label=y[:300])
    svc = PredictionService(max_batch_rows=512, batch_window_s=0.0005)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=3,
                           service=svc, model_name="live")
    try:
        tr.refit()  # publish generation 1 before load starts
        failures, done = [], threading.Event()

        def hammer():
            while not done.is_set():
                try:
                    out = svc.predict("live", X[:16], raw_score=True)
                    assert out.shape[0] == 16
                except Exception as e:  # noqa: BLE001 - the assertion target
                    failures.append(repr(e))

        threads = [threading.Thread(target=hammer) for _ in range(3)]
        for t in threads:
            t.start()
        for lo in (300, 500):
            store.push_rows(X[lo:lo + 200], label=y[lo:lo + 200])
            tr.step()
        done.set()
        for t in threads:
            t.join()
    finally:
        svc.close()
    assert failures == []
    assert tr.generation == 3
    assert svc.registry.get("live").version == 3


# --------------------------------------- drift, refresh, publish gate

def _binary_chunks(seed=3, n=2048, f=8, shift_from=None, shift_feature=0):
    """Deterministic labelled chunks; rows >= shift_from get the feature
    pushed out of the fitted bin support (the drift scenario)."""
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f))
    y = (X[:, 1] + 0.3 * X[:, 2] > 0).astype(np.float64)
    if shift_from is not None:
        X[shift_from:, shift_feature] = \
            X[shift_from:, shift_feature] * 3.0 + 10.0
    return X, y


def test_refit_event_generation_matches_sidecar(tmp_path):
    """Satellite regression: the stream_refit event and stream_generation
    gauge must name the generation the model was checkpointed and
    published as (emit first, bump after)."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.checkpoint import read_sidecar_manifest
    from lightgbm_tpu.utils.timer import global_timer

    X, y = _data(n=512)
    store = RowBlockStore(params=dict(BASE))
    store.push_rows(X, label=y)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=2,
                           checkpoint_dir=str(tmp_path))
    with telemetry.capture(None, label="gen-test",
                           watch_compiles=False) as sess:
        assert tr.step() is not None
    events = [e for e in sess.events if e.get("ev") == "stream_refit"]
    assert len(events) == 1
    manifest = read_sidecar_manifest(tr.checkpoint_path(0))
    assert manifest is not None
    assert events[0]["generation"] == manifest["stream_generation"] == 0
    assert global_timer.counters["stream_generation"] == 0
    assert tr.generation == 1  # the attribute still counts completed refits


@pytest.mark.parametrize("source", ["dense64", "dense256", "dense_whole",
                                    "csr", "iterator"])
def test_layout_prefix_deterministic_across_chunkings(source):
    """Identical pushed rows must fit identical cut points no matter how
    callers chunk them — the fit prefix is clipped to exactly
    bin_sample_rows, never the last block's overshoot."""
    X, y = _data(n=1500, f=6)
    Xd = X.astype(np.float64)
    Xd[Xd < -2.2] = 0.0  # sparse tail so CSR roundtrips exercise zeros

    def _store():
        return RowBlockStore(params=dict(BASE), bin_sample_rows=1000)

    baseline = _store()
    baseline.push_rows(Xd, label=y)
    base_cuts = [tuple(m.bin_upper_bound) for m in baseline._layout.mappers]
    assert baseline._layout is not None  # 1500 pushed > 1000 budget

    store = _store()
    if source == "dense64":
        for lo in range(0, 1500, 64):
            store.push_rows(Xd[lo:lo + 64], label=y[lo:lo + 64])
    elif source == "dense256":
        for lo in range(0, 1500, 256):
            store.push_rows(Xd[lo:lo + 256], label=y[lo:lo + 256])
    elif source == "dense_whole":
        store.push_rows(Xd, label=y)
    elif source == "csr":
        for lo in range(0, 1500, 300):
            ip, ix, vals = _dense_to_csr(Xd[lo:lo + 300])
            store.push_csr(ip, ix, vals, 6, label=y[lo:lo + 300])
    else:
        store.push_from_iterator(
            (Xd[lo:lo + 200], y[lo:lo + 200]) for lo in range(0, 1500, 200))
    cuts = [tuple(m.bin_upper_bound) for m in store._layout.mappers]
    assert cuts == base_cuts
    assert np.array_equal(store.finalize().bins, baseline.finalize().bins)


def test_drift_alarm_refresh_restores_resolution(monkeypatch, tmp_path):
    """Chaos acceptance (detection + refresh): a planted drift_shift must
    trip the PSI alarm with a flight dump, and the sketch-driven bin
    refresh must measurably restore bin resolution on the shifted feature
    while previously published models stay byte-identical."""
    from lightgbm_tpu.streaming import drift
    from lightgbm_tpu.utils.timer import global_timer

    monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
    monkeypatch.setenv("LGBM_TPU_DRIFT_CHECK_ROWS", "512")
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    faults.install("drift_shift@1024:0")
    X, y = _binary_chunks(n=3072)
    store = RowBlockStore(params=dict(BASE), bin_sample_rows=1024)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=3)
    store.push_rows(X[:1024], label=y[:1024])
    published = tr.step()
    old_text = published.model_to_string()
    old_preds = np.asarray(published.predict(X[:256], raw_score=True))

    alarms_before = global_timer.counters.get("drift_alarms", 0)
    for lo in range(1024, 3072, 256):
        store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
    mon = store._drift
    assert mon is not None and mon.alarmed
    assert mon.alarm_feature == 0
    assert global_timer.counters["drift_alarms"] == alarms_before + 1
    assert (tmp_path / "flight-drift_alarm.json").exists()
    assert drift.latest()["max_psi"] >= mon.threshold

    # resolution on the shifted regime before vs after the refresh: the
    # shifted values crowd the top edge bin under the old cut points
    shifted = X[1024:2048, 0] * 3.0 + 10.0  # what the fault made of them
    old_bins = store._layout.mappers[0].values_to_bins(shifted)
    distinct_before = len(np.unique(old_bins))
    assert store.maybe_refresh_bins() is True
    assert store.layout_generation == 1
    assert not mon.alarmed  # refresh re-anchors the baseline
    new_bins = store._layout.mappers[0].values_to_bins(shifted)
    distinct_after = len(np.unique(new_bins))
    assert distinct_after > 4 * max(distinct_before, 1)
    top = store._layout.mappers[0].num_bin - 1
    assert (new_bins >= top - 1).mean() < 0.2

    # the published model is untouched by the refresh: thresholds are
    # real-valued at the model surface, so bits AND predictions hold
    assert published.model_to_string() == old_text
    np.testing.assert_array_equal(
        np.asarray(published.predict(X[:256], raw_score=True)), old_preds)

    # the next generation trains against the refreshed mapper cleanly
    assert tr.step() is not None
    assert tr.generation == 2


def test_drift_shift_chaos_end_to_end_trainer_refresh(monkeypatch, tmp_path):
    """The scheduled-refresh path: LGBM_TPU_BIN_REFRESH_EVERY drives
    maybe_refresh_bins at a fresh generation boundary inside step(), and
    the post-refresh generation checkpoint records the mapper generation."""
    from lightgbm_tpu.checkpoint import read_sidecar_manifest

    monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
    X, y = _binary_chunks(n=2048)
    store = RowBlockStore(params=dict(BASE), bin_sample_rows=512)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=2,
                           checkpoint_dir=str(tmp_path), refresh_every=1)
    store.push_rows(X[:1024], label=y[:1024])
    assert tr.step() is not None
    man0 = read_sidecar_manifest(tr.checkpoint_path(0))
    assert man0["bin_mapper_generation"] == 0
    store.push_rows(X[1024:], label=y[1024:])
    assert tr.step() is not None  # gen 1: refresh forced at the boundary
    assert store.layout_generation == 1
    man1 = read_sidecar_manifest(tr.checkpoint_path(1))
    assert man1["stream_generation"] == 1
    assert man1["bin_mapper_generation"] == 1


def test_refresh_then_kill_resume_bit_identical(monkeypatch, tmp_path):
    """Acceptance: a kill mid-refit AFTER a bin refresh resumes
    bit-identically against the refreshed mapper (the sidecar carries the
    mapper generation; refreshes are fenced to generation boundaries)."""
    from lightgbm_tpu.checkpoint import read_sidecar_manifest

    monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
    monkeypatch.setenv("LGBM_TPU_DRIFT_CHECK_ROWS", "256")
    params = dict(BASE)

    def run(kill):
        X, y = _binary_chunks(n=2560, shift_from=1024)
        store = RowBlockStore(params=params, bin_sample_rows=1024)
        tr = ContinuousTrainer(
            params, store, num_boost_round=6,
            checkpoint_dir=str(tmp_path / ("crashy" if kill else "clean")))
        store.push_rows(X[:1024], label=y[:1024])
        assert tr.step() is not None
        for lo in range(1024, 2560, 256):
            store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
        assert store._drift.alarmed
        if kill:
            faults.install("kill@3")
            with pytest.raises(InjectedFault):
                tr.step()  # refresh + pin happened, then train died
            faults.clear()
            assert store.layout_generation == 1
        booster = tr.step()
        manifest = read_sidecar_manifest(tr.checkpoint_path(1))
        return booster.model_to_string(), store.layout_generation, manifest

    clean_text, clean_gen, _ = run(kill=False)
    crash_text, crash_gen, manifest = run(kill=True)
    assert clean_gen == crash_gen == 1
    assert manifest["bin_mapper_generation"] == 1
    assert crash_text == clean_text


def test_bad_generation_rejected_and_never_serves(tmp_path):
    """Chaos acceptance (gate): a poisoned generation is rejected with the
    full rollback paper trail and never answers a single predict — every
    response during the window is byte-identical to the prior model's."""
    from lightgbm_tpu import telemetry
    from lightgbm_tpu.serving import PredictionService
    from lightgbm_tpu.utils.timer import global_timer

    X, y = _binary_chunks(n=2048, f=6)
    store = RowBlockStore(params=dict(BASE))
    store.push_rows(X[:1024], label=y[:1024])
    svc = PredictionService(max_batch_rows=512, batch_window_s=0.0005)
    tr = ContinuousTrainer(dict(BASE), store, num_boost_round=3,
                           service=svc, model_name="live",
                           checkpoint_dir=str(tmp_path),
                           holdout_rows=256, gate_tolerance=0.1)
    try:
        with telemetry.capture(None, label="gate-test",
                               watch_compiles=False) as sess:
            tr.step()
            assert svc.registry.get("live").version == 1
            expected = svc.predict("live", X[:16], raw_score=True)

            faults.install("bad_generation@1")
            store.push_rows(X[1024:2048], label=y[1024:2048])
            failures, done = [], threading.Event()

            def hammer():
                while not done.is_set():
                    try:
                        out = svc.predict("live", X[:16], raw_score=True)
                        if not np.array_equal(out, expected):
                            failures.append("served non-prior-model bytes")
                    except Exception as e:  # noqa: BLE001
                        failures.append(repr(e))

            threads = [threading.Thread(target=hammer) for _ in range(3)]
            for t in threads:
                t.start()
            rejected = tr.step()
            done.set()
            for t in threads:
                t.join()
            assert rejected is None
            assert failures == []
            assert tr.generation == 1  # not advanced
            assert svc.registry.get("live").version == 1  # serving untouched
            assert global_timer.counters["stream_generation_rejected"] >= 1
            events = [e for e in sess.events
                      if e.get("ev") == "generation_rejected"]
            assert events and events[-1]["generation"] == 1
            assert events[-1]["candidate_loss"] > events[-1]["serving_loss"]

            # the retry resumes the checkpointed (clean) model and passes
            retried = tr.step()
            assert retried is not None
            assert tr.generation == 2
            assert svc.registry.get("live").version == 2
    finally:
        svc.close()


def test_drift_off_is_default_and_bit_identical(monkeypatch):
    """LGBM_TPU_DRIFT unset => no monitor object exists at all (the push
    path pays one is-None check) and models are bit-identical to a
    drift-enabled run that never refreshes."""
    X, y = _binary_chunks(n=1536, f=6)

    def run():
        store = RowBlockStore(params=dict(BASE), bin_sample_rows=512)
        for lo in range(0, 1536, 256):
            store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
        return store, train(dict(BASE), store.to_basic_dataset(
            params=dict(BASE)), num_boost_round=3)

    monkeypatch.delenv("LGBM_TPU_DRIFT", raising=False)
    store_off, model_off = run()
    assert store_off._drift is None
    monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
    store_on, model_on = run()
    assert store_on._drift is not None
    assert model_off.model_to_string() == model_on.model_to_string()


def test_sketch_corrupt_discards_sketch_keeps_cut_points(monkeypatch):
    """Chaos: planted sketch corruption must be caught by the health check
    at refresh time — the feature keeps its current cut points instead of
    refitting them from garbage, and the discard is counted."""
    from lightgbm_tpu.utils.timer import global_timer

    monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
    monkeypatch.setenv("LGBM_TPU_DRIFT_CHECK_ROWS", "256")
    faults.install("sketch_corrupt@2")
    X, y = _binary_chunks(n=2048, f=6)
    store = RowBlockStore(params=dict(BASE), bin_sample_rows=512)
    for lo in range(0, 2048, 256):
        store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
    assert not store._drift.sketches[2].healthy()
    discarded_before = global_timer.counters.get("drift_sketch_discarded", 0)
    old_mapper = store._layout.mappers[2]
    assert store.maybe_refresh_bins(force=True) is True
    assert store._layout.mappers[2] is old_mapper  # kept, not refitted
    assert global_timer.counters["drift_sketch_discarded"] \
        == discarded_before + 1
    # the discarded sketch was replaced fresh and is healthy again
    assert store._drift.sketches[2].healthy()


def test_canary_promote_and_rollback():
    """Canary lifecycle on the serving facade: a clean candidate promotes
    after its window; a failing candidate rolls back mid-request with the
    caller still answered from the primary."""
    from lightgbm_tpu.serving import PredictionService

    X, y = _data(n=512, f=6)
    b1 = _model(BASE, X, y, rounds=2)
    b2 = _model(BASE, X, y, rounds=5)

    svc = PredictionService(max_batch_rows=256, batch_window_s=0.0005)
    try:
        svc.load_model("m", booster=b1)
        svc.start_canary("m", booster=b2, fraction=0.5, promote_after=3)
        assert svc.canary_info()["active"]
        for _ in range(12):
            out = svc.predict("m", X[:8], raw_score=True)
            assert out.shape[0] == 8
        info = svc.canary_info()
        assert not info["active"] and info["promoted"] == 1
        assert svc.registry.get("m").version == 2
        np.testing.assert_allclose(
            np.asarray(svc.predict("m", X[:8], raw_score=True)),
            np.asarray(b2.predict(X[:8], raw_score=True)), rtol=1e-5)
    finally:
        svc.close()

    svc = PredictionService(max_batch_rows=256, batch_window_s=0.0005)
    try:
        svc.load_model("m", booster=b1)
        svc.start_canary("m", booster=b2, fraction=1.0, promote_after=50)
        # 3 straight dispatch failures open the breaker (the batcher keeps
        # answering via its host-path retry, so no caller ever fails);
        # the next canary routing decision sees the pressure and rolls back
        faults.install("predict_fail@1")
        for _ in range(5):
            out = svc.predict("m", X[:8], raw_score=True)
            assert out.shape[0] == 8  # every request still answered
        info = svc.canary_info()
        assert not info["active"] and info["rolled_back"] == 1
        assert svc.registry.get("m").version == 1  # primary untouched
        from lightgbm_tpu.serving.errors import ModelNotFound
        with pytest.raises(ModelNotFound):
            svc.registry.get("m!canary")
    finally:
        svc.close()


@pytest.mark.slow
def test_drift_overhead_under_two_percent(monkeypatch):
    """Acceptance bound: sketches + occupancy + gate cost < 2% of the
    ingest+refit wall (median of repeated runs to beat host noise)."""
    import time

    X, y = _binary_chunks(n=40000, f=12)

    def wall(drift_on):
        if drift_on:
            monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
        else:
            monkeypatch.delenv("LGBM_TPU_DRIFT", raising=False)
        t0 = time.perf_counter()
        store = RowBlockStore(params=dict(BASE), bin_sample_rows=8192)
        for lo in range(0, 40000, 2048):
            store.push_rows(X[lo:lo + 2048], label=y[lo:lo + 2048])
        tr = ContinuousTrainer(dict(BASE), store, num_boost_round=5,
                               holdout_rows=2048 if drift_on else 0)
        assert tr.step() is not None
        return time.perf_counter() - t0

    wall(False)  # warm jit caches out of the measurement
    base = min(wall(False) for _ in range(3))
    on = min(wall(True) for _ in range(3))
    assert on <= base * 1.02 + 0.25, (on, base)


# ------------------------------------------------------- C-API shims

class _FakeFfi:
    """Just enough of cffi's ffi for capi/impl: zero-copy buffer views
    over numpy arrays and pass-through byte strings."""

    def buffer(self, obj, size=None):
        mv = memoryview(obj).cast("B")
        return mv if size is None else mv[:size]

    def string(self, s):
        return s if isinstance(s, bytes) else str(s).encode()


def test_capi_push_rows_shims():
    from lightgbm_tpu.capi import impl

    ffi = _FakeFfi()
    X, y = _data(n=600, f=8)
    Xd = np.ascontiguousarray(X, dtype=np.float64)

    out = [0]
    assert impl.dataset_create_streaming(ffi, 0, b"verbosity=-1", out) == 0
    handle = out[0]
    try:
        # dense push (float64 = C_API_DTYPE 1), then a CSR push
        assert impl.dataset_push_rows(ffi, handle, Xd[:400], 1,
                                      400, 8, 0) == 0
        ip, ix, vals = _dense_to_csr(Xd[400:])
        assert impl.dataset_push_rows_by_csr(
            ffi, handle, ip, 3, ix, vals, 1, len(ip), len(vals), 8, 400) == 0
        with pytest.raises(ValueError, match="non-sequential"):
            impl.dataset_push_rows(ffi, handle, Xd[:400], 1, 400, 8, 0)

        yf = np.asarray(y, dtype=np.float32)
        assert impl.dataset_set_field(ffi, handle, b"label", yf,
                                      len(yf), 0) == 0
        nd, nf = [0], [0]
        impl.dataset_get_num_data(ffi, handle, nd)
        impl.dataset_get_num_feature(ffi, handle, nf)
        assert (nd[0], nf[0]) == (600, 8)

        bout = [0]
        assert impl.booster_create(
            ffi, handle,
            b"objective=binary num_leaves=15 verbosity=-1 num_iterations=3",
            bout) == 0
        try:
            fin = [0]
            for _ in range(3):
                impl.booster_update_one_iter(ffi, bout[0], fin)
            capi_bst = impl._get(bout[0])
            assert capi_bst.current_iteration() == 3

            # the shim route trains the same bits as the python route
            store = RowBlockStore(params={"verbosity": -1})
            store.push_rows(Xd[:400]).push_rows(Xd[400:])
            store.set_label(yf)
            direct = train({"objective": "binary", "num_leaves": "15",
                            "verbosity": "-1", "num_iterations": "3"},
                           store.to_basic_dataset(), num_boost_round=3)
            assert capi_bst.model_to_string() == direct.model_to_string()
        finally:
            impl.booster_free(ffi, bout[0])
    finally:
        impl.dataset_free(ffi, handle)


def test_capi_non_streaming_handle_rejected():
    from lightgbm_tpu.capi import impl

    ffi = _FakeFfi()
    h = impl._register(object())
    try:
        with pytest.raises(TypeError, match="streaming"):
            impl.dataset_push_rows(ffi, h, np.zeros((1, 2)), 1, 1, 2, 0)
    finally:
        impl._free(h)
