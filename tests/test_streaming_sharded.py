"""Pod-scale sharded streaming: the ISSUE acceptance suite.

Locks the gang-sharded composition of the out-of-core stack
(streaming/sharded.py, docs/STREAMING.md "Pod-scale streaming"):

  * sharded-vs-single bit-identity — tree_learner=data + a budget 4x
    smaller than the plane on the 8-virtual-device mesh trains byte-
    identical models to the single-shard streamed learner, across
    plain / bagged / quantized (the quantized leg exercises the real
    psum merge; float legs exercise the canonical-fold fallback);
  * global-sketch binning — the rank-merged sketch fit reproduces the
    raw-prefix fit (cut points, EFB groups, the whole plane) byte-for-
    byte independent of shard count / block placement;
  * elastic survival — a worker lost mid-refit surfaces the typed
    WorkerLostError, and an 8-shard flywheel resumed over 4 surviving
    shards trains byte-identical to the undisturbed run;
  * ragged kernel equality — the per-block ragged Pallas histogram in
    interpret mode matches the XLA scatter fold (bit-exact end-to-end
    for quantized; bit-exact at the histogram level for float when the
    gh values are snapped to an exactly-summable grid);
  * the two rider regressions — the _BlockCache eviction race under
    threads, and merge_ranked's arrival-order invariance.
"""
import threading

import jax
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.engine import train
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.parallel import elastic
from lightgbm_tpu.parallel.elastic import WorkerLostError
from lightgbm_tpu.streaming import (ContinuousTrainer, PodDriftMonitor,
                                    RowBlockStore, ShardedRowBlockStore,
                                    ShardedStreamedTreeLearner, merge_ranked)
from lightgbm_tpu.streaming.drift import QuantileSketch
from lightgbm_tpu.streaming.learner import (BLOCK_ROWS_ENV, BUDGET_ENV,
                                            RAGGED_ENV, _BlockCache,
                                            StreamedTreeLearner)
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.log import LightGBMError
from lightgbm_tpu.utils.timer import global_timer

BASE = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}
MESH_ENV = "LGBM_TPU_FORCE_MESH_DEVICES"


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()
    elastic.clear()


def _data(seed=3, n=2048, f=12):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.3 > 0)
    return X, y.astype(np.float64)


def _model(params, X, y, rounds=5):
    return train(dict(params), lgb.Dataset(X, label=y),
                 num_boost_round=rounds)


def _plane_bytes(params, X, y):
    core = CoreDataset.from_matrix(X, label=y, config=Config(dict(params)))
    return core.bins.size * core.bins.dtype.itemsize, core.bins.shape[0]


def _need_mesh():
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")


# ------------------------------------------- sharded-vs-single bit-identity

@pytest.mark.parametrize("extra", [
    pytest.param({}, id="plain", marks=pytest.mark.slow),
    pytest.param({"bagging_fraction": 0.7, "bagging_freq": 1}, id="bagged",
                 marks=pytest.mark.slow),
    pytest.param({"use_quantized_grad": True}, id="quantized"),
])
def test_sharded_streamed_bit_identical_starved_budget(monkeypatch, extra):
    """THE tentpole bound: the gang-sharded streamed learner at a budget
    4x smaller than the plane trains byte-identical to the single-shard
    streamed learner (which is itself bit-identical to resident)."""
    _need_mesh()
    X, y = _data()
    params = {**BASE, "tree_learner": "data", **extra}
    plane, groups = _plane_bytes(params, X, y)
    block_bytes = groups * 256  # uint8 plane
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(BUDGET_ENV, str(2 * block_bytes))
    assert plane >= 4 * (2 * block_bytes)

    # a forced 1-device mesh makes the sharded learner the parent
    # streamed learner exactly (no cache wrap, canonical fold)
    monkeypatch.setenv(MESH_ENV, "1")
    single = _model(params, X, y)
    monkeypatch.setenv(MESH_ENV, "8")
    sharded = _model(params, X, y)

    assert global_timer.counters["stream_shards"] == 8
    assert single.model_to_string() == sharded.model_to_string()
    np.testing.assert_array_equal(
        np.asarray(single.predict(X, raw_score=True)),
        np.asarray(sharded.predict(X, raw_score=True)))


def test_sharded_wire_cost_is_n_independent(monkeypatch):
    """Quantized gang merge moves one [G, B, 3] int32 histogram per rank
    per wave — the gauge must equal that closed form and not move with
    the row count."""
    _need_mesh()
    params = {**BASE, "tree_learner": "data", "use_quantized_grad": True}
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(BUDGET_ENV, "64k")
    monkeypatch.setenv(MESH_ENV, "8")

    def wire(n):
        X, y = _data(n=n)
        bst = _model(params, X, y, rounds=2)
        learner = bst._gbdt.tree_learner
        assert isinstance(learner, ShardedStreamedTreeLearner)
        expect = (len(learner.dataset.groups)
                  * learner.group_bin_padded * 3 * 4)
        got = global_timer.counters["stream_ici_bytes_per_wave"]
        assert got == expect
        assert global_timer.counters["device_ici_bytes_per_wave"] == expect
        return got

    assert wire(1024) == wire(2048)


def test_streaming_factory_routes_data_to_sharded(monkeypatch):
    X, y = _data(n=512)
    monkeypatch.setenv(BUDGET_ENV, "64k")
    bst = lgb.Booster(params={**BASE, "tree_learner": "data"},
                      train_set=lgb.Dataset(X, label=y))
    learner = bst._gbdt.tree_learner
    assert isinstance(learner, ShardedStreamedTreeLearner)
    assert isinstance(learner, StreamedTreeLearner)
    assert learner.bins_dev is None  # the plane never uploads whole


@pytest.mark.parametrize("kind", ["feature", "voting"])
def test_streaming_rejects_plane_resident_learners(monkeypatch, kind):
    X, y = _data(n=512)
    monkeypatch.setenv(BUDGET_ENV, "64k")
    with pytest.raises(LightGBMError, match="serial or data only"):
        train({**BASE, "tree_learner": kind}, lgb.Dataset(X, label=y),
              num_boost_round=1)


# --------------------------------------------- global-sketch binning fit

def _sparse_chunks(seed=11, n=1500, f=8):
    """float64 rows with a sparse tail (EFB-eligible zeros) and planted
    NaNs so the surrogate's NaN-tail scatter is exercised."""
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f))
    X[X < -1.2] = 0.0
    nan_pos = rng.rand(n, f) < 0.01
    X[nan_pos] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.3 * np.nan_to_num(X[:, 1]) > 0)
    return X, y.astype(np.float64)


@pytest.mark.parametrize("shards", [4, 7])
def test_sharded_fit_matches_raw_prefix_fit(shards):
    """The rank-merged sketch fit must reproduce the raw-prefix one-shot
    fit byte-for-byte — cut points, EFB group lists, the whole binned
    plane — for ANY shard count / block placement."""
    X, y = _sparse_chunks()
    params = dict(BASE)

    def fill(store):
        for lo in range(0, 1500, 256):
            hi = min(1500, lo + 256)
            store.push_rows(X[lo:hi], label=y[lo:hi])
        return store

    base = fill(RowBlockStore(params=params, bin_sample_rows=1024))
    sh = fill(ShardedRowBlockStore(params=params, bin_sample_rows=1024,
                                   num_shards=shards))
    assert base._layout is not None and sh._layout is not None
    assert len(sh._layout.mappers) == len(base._layout.mappers)
    for ma, mb in zip(base._layout.mappers, sh._layout.mappers):
        assert ma.num_bin == mb.num_bin
        assert np.array_equal(np.asarray(ma.bin_upper_bound, dtype=float),
                              np.asarray(mb.bin_upper_bound, dtype=float),
                              equal_nan=True)
    assert sh._group_lists == base._group_lists  # EFB bundles byte-equal
    a, b = base.finalize(), sh.finalize()
    assert np.array_equal(a.bins, b.bins)
    np.testing.assert_array_equal(np.asarray(a.metadata.label),
                                  np.asarray(b.metadata.label))

    # the sketch merge actually ran (and was timed)
    assert global_timer.counters["stream_sketch_merges"] >= 1
    assert "stream_sketch_merge_us" in global_timer.counters

    pushed = train(dict(params), sh.to_basic_dataset(params=params),
                   num_boost_round=4)
    direct = train(dict(params), base.to_basic_dataset(params=params),
                   num_boost_round=4)
    assert pushed.model_to_string() == direct.model_to_string()


def test_shard_watermarks_pin_round_robin_placement():
    X, y = _data(n=900, f=6)
    store = ShardedRowBlockStore(params=dict(BASE), num_shards=4)
    sizes = [256, 256, 256, 132]
    lo = 0
    for sz in sizes:
        store.push_rows(X[lo:lo + sz], label=y[lo:lo + sz])
        lo += sz
    # placement pinned at push: block i -> shard i % 4
    assert store._block_owner == [0, 1, 2, 3]
    assert [store.shard_rows(r) for r in range(4)] == sizes
    assert sum(store.shard_rows(r) for r in range(4)) == 900
    # reshard re-takes placements round-robin over the surviving world
    store.reshard(2)
    assert store.num_shards == 2
    assert store._block_owner == [0, 1, 0, 1]
    assert store.shard_rows(0) == 256 + 256
    assert store.shard_rows(1) == 256 + 132


def test_pod_drift_alarm_refresh_deterministic(monkeypatch):
    """Gang-merged drift: the planted shift trips the pod alarm, the
    sketch-driven refresh lands, and both — plus the refreshed cut
    points — replay byte-identically (the merged state is a pure
    function of the pushed stream)."""
    monkeypatch.setenv("LGBM_TPU_DRIFT", "1")
    monkeypatch.setenv("LGBM_TPU_DRIFT_CHECK_ROWS", "512")

    def run():
        faults.clear()
        faults.install("drift_shift@1024:0")
        rng = np.random.RandomState(3)
        X = rng.standard_normal((3072, 8))
        y = (X[:, 1] + 0.3 * X[:, 2] > 0).astype(np.float64)
        store = ShardedRowBlockStore(params=dict(BASE),
                                     bin_sample_rows=1024, num_shards=4)
        for lo in range(0, 3072, 256):
            store.push_rows(X[lo:lo + 256], label=y[lo:lo + 256])
        mon = store._drift
        assert isinstance(mon, PodDriftMonitor)
        assert mon.alarmed and mon.alarm_feature == 0
        assert store.maybe_refresh_bins() is True
        assert store.layout_generation == 1
        cuts = [tuple(m.bin_upper_bound) for m in store._layout.mappers]
        return cuts, store.finalize().bins

    cuts1, bins1 = run()
    cuts2, bins2 = run()
    assert cuts1 == cuts2
    assert np.array_equal(bins1, bins2)


# ------------------------------------------------------ elastic survival

def test_sharded_stream_worker_lost_is_typed(monkeypatch):
    """A gang peer lost mid-train under the sharded streamed learner
    surfaces the typed WorkerLostError — rank + last-good iteration —
    within the watchdog timeout."""
    _need_mesh()
    monkeypatch.setenv(BUDGET_ENV, "64k")
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(MESH_ENV, "8")
    X, y = _data(n=600)
    params = {**BASE, "tree_learner": "data", "use_quantized_grad": True}
    # warm the jit caches: the watchdog deadline must measure the planted
    # hang, not the first iteration's compile stall
    train(dict(params), lgb.Dataset(X, label=y), num_boost_round=1)
    elastic.install(timeout_s=2.0)
    faults.install("worker_hang@0:2")
    with pytest.raises(WorkerLostError) as ei:
        train(dict(params), lgb.Dataset(X, label=y), num_boost_round=6)
    assert ei.value.rank == 0
    assert ei.value.last_good_iteration == 2


@pytest.mark.slow  # heavy full-training driver: tier-1 keeps the quantized starved-budget bound
def test_worker_lost_mid_refit_shrinks_8_to_4_bit_identical(tmp_path,
                                                            monkeypatch):
    """THE shrink-to-fit contract at pod scale: a worker lost mid-refit
    on the 8-shard flywheel rolls the generation back (watermark stays
    pinned), the store re-shards over the 4 survivors, and the resumed
    refit is byte-identical to the undisturbed 8-shard run."""
    _need_mesh()
    monkeypatch.setenv(BUDGET_ENV, "64k")
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    X, y = _data(seed=42, n=1200, f=10)
    params = {**BASE, "tree_learner": "data", "use_quantized_grad": True}

    def filled():
        s = ShardedRowBlockStore(params=params)
        for lo in range(0, 1200, 300):
            s.push_rows(X[lo:lo + 300], label=y[lo:lo + 300])
        return s

    monkeypatch.setenv(MESH_ENV, "8")
    clean = ContinuousTrainer(params, filled(), num_boost_round=4,
                              checkpoint_dir=str(tmp_path / "clean"))
    straight = clean.step()
    assert straight is not None

    store = filled()
    assert store.num_shards == 8
    tr = ContinuousTrainer(params, store, num_boost_round=4,
                           checkpoint_dir=str(tmp_path / "crashy"))
    elastic.install(timeout_s=2.0)
    faults.install("worker_hang@0:2")
    assert tr.step() is None          # worker lost mid-refit: no publish
    faults.clear()
    elastic.clear()
    assert tr.generation == 0         # generation did NOT advance
    assert tr._inflight_rows == 1200  # watermark stays pinned

    # the gang shrank to 4 survivors: re-shard the block store and the
    # mesh, then resume — the plane and merged drift state are
    # placement-independent, so the retry reproduces the 8-shard bits
    store.reshard(4)
    assert store.num_shards == 4
    monkeypatch.setenv(MESH_ENV, "4")
    resumed = tr.step()
    assert resumed is not None
    assert tr.generation == 1
    assert resumed.model_to_string() == straight.model_to_string()


# ------------------------------------------------- ragged kernel equality

@pytest.mark.slow  # heavy full-training driver: tier-1 keeps the quantized starved-budget bound
def test_ragged_interpret_bit_identical_quantized(monkeypatch):
    """End-to-end: the ragged per-block kernel (interpret mode) and the
    XLA scatter fold train byte-identical quantized models — int32
    accumulation is exact under any block order."""
    X, y = _data(n=1024)
    params = {**BASE, "use_quantized_grad": True}
    plane, groups = _plane_bytes(params, X, y)
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(BUDGET_ENV, str(2 * groups * 256))

    monkeypatch.setenv(RAGGED_ENV, "0")
    scatter = _model(params, X, y)
    before = global_timer.counters.get("stream_ragged_leaves", 0)
    monkeypatch.setenv(RAGGED_ENV, "interpret")
    ragged = _model(params, X, y)
    assert global_timer.counters["stream_ragged_leaves"] > before
    assert scatter.model_to_string() == ragged.model_to_string()


@pytest.mark.slow  # heavy full-training driver: tier-1 keeps the quantized starved-budget bound
def test_ragged_interpret_matches_scatter_float_snapped(monkeypatch):
    """Histogram-level float equality: with gh snapped to the 2^-10 grid
    (partial sums exact in f32 under ANY association) and f32 kernel
    operands forced, the ragged kernel must reproduce the scatter fold
    bit-for-bit over every index-set shape."""
    monkeypatch.setenv(BLOCK_ROWS_ENV, "256")
    monkeypatch.setenv(BUDGET_ENV, "64k")
    X, y = _data(n=1500, f=6)
    bst = _model(BASE, X, y, rounds=1)
    learner = bst._gbdt.tree_learner
    assert isinstance(learner, StreamedTreeLearner)

    import jax.numpy as jnp
    gh = np.asarray(learner._gh)
    snapped = np.round(np.clip(gh, -1.0, 1.0) * 1024.0) / 1024.0
    learner._gh = jnp.asarray(snapped.astype(np.float32))
    monkeypatch.setenv("LGBM_TPU_HIST_F32", "1")

    n = learner.num_data
    for idx in (np.arange(0, n, 2),            # strided across all blocks
                np.arange(300, 520),           # straddles a block boundary
                np.asarray([7, 263, 519, 1033, 1499])):  # sparse tiles
        a = np.asarray(learner._hist_over_indices(idx.astype(np.int64)))
        b = np.asarray(learner._ragged_over_indices(idx.astype(np.int64),
                                                    interpret=True))
        np.testing.assert_array_equal(a, b)


# ------------------------------------------------------ rider regressions

def test_block_cache_concurrent_get_prefetch_evict():
    """The LRU race regression: concurrent get/prefetch across threads
    with a 2-slot cache (eviction on almost every access) must neither
    corrupt the maps nor serve wrong block contents."""
    rng = np.random.RandomState(0)
    plane = rng.randint(0, 255, size=(4, 4096)).astype(np.uint8)
    cache = _BlockCache(plane, 256, capacity=2, upload_dtype=None)
    errors = []

    def worker(seed):
        r = np.random.RandomState(seed)
        try:
            for _ in range(300):
                b = int(r.randint(cache.n_blocks))
                if r.rand() < 0.5:
                    cache.prefetch((b + 1) % cache.n_blocks)
                lo, hi = cache.block_range(b)
                if not np.array_equal(np.asarray(cache.get(b)),
                                      plane[:, lo:hi]):
                    errors.append(("wrong-bytes", b))
        except Exception as e:  # noqa: BLE001 - the assertion target
            errors.append(("raised", repr(e)))

    threads = [threading.Thread(target=worker, args=(s,)) for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    assert len(cache._resident) <= cache.capacity


def test_merge_ranked_is_arrival_order_invariant():
    """The sketch-merge canonicalization regression: merging the same
    shard sketches in ANY arrival order yields byte-identical merged
    state (rank order is the merge order, not arrival)."""
    rng = np.random.RandomState(1)
    shards = []
    for _ in range(5):
        sk = QuantileSketch(64)
        for _ in range(6):
            sk.update(rng.standard_normal(200))  # forces compaction
        shards.append(sk)

    ref = merge_ranked([(r, sk.copy()) for r, sk in enumerate(shards)])
    ref_sample = ref.quantile_sample(256)
    assert ref.nonzero_n == sum(sk.nonzero_n for sk in shards)

    for seed in range(5):
        order = np.random.RandomState(seed).permutation(5)
        merged = merge_ranked([(int(r), shards[int(r)].copy())
                               for r in order])
        np.testing.assert_array_equal(merged.quantile_sample(256),
                                      ref_sample)

    with pytest.raises(ValueError, match="distinct ranks"):
        merge_ranked([(0, shards[0].copy()), (0, shards[1].copy())])
