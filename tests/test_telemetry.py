"""Telemetry stack: JSONL event stream round-trip, Chrome-trace validity,
recompile watcher, HBM gauge, counter semantics, and the two contract
claims — bit-identical model output with telemetry on, and a disabled
path cheap enough for the <1% overhead budget.
"""
import json
import logging
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry
from lightgbm_tpu.checkpoint import save_checkpoint
from lightgbm_tpu.engine import train
from lightgbm_tpu.telemetry import EVENTS_FILE, TRACE_FILE, build_chrome_trace
from lightgbm_tpu.utils.timer import global_timer

BASE = {"objective": "binary", "num_leaves": 7, "learning_rate": 0.1,
        "verbosity": -1, "min_data_in_leaf": 5}


def _data(n=400, f=8, seed=3):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.5 > 0)
    return X, y.astype(np.float64)


def _train(params, X, y, rounds=4, **kw):
    return train(dict(BASE, **params), lgb.Dataset(X, label=y),
                 num_boost_round=rounds, **kw)


def _read_events(run_dir):
    with open(os.path.join(run_dir, EVENTS_FILE)) as fh:
        return [json.loads(line) for line in fh if line.strip()]


@pytest.fixture(autouse=True)
def _no_leaked_session():
    assert telemetry.session() is None
    yield
    # a test that leaks a session would silently disturb every later test
    assert telemetry.session() is None, "test leaked a telemetry session"


# -- end-to-end: enabled training run ------------------------------------

def test_enabled_run_writes_event_stream_and_trace(tmp_path):
    X, y = _data()
    rounds = 4
    _train({"telemetry_dir": str(tmp_path)}, X, y, rounds=rounds)

    events = _read_events(tmp_path)
    by_type = {}
    for e in events:
        assert isinstance(e["t"], (int, float)) and e["t"] >= 0
        by_type.setdefault(e["ev"], []).append(e)
    # one record per iteration plus the session/loop framing events
    assert by_type["session_start"][0]["label"] == "train"
    assert len(by_type["iteration"]) == rounds
    assert len(by_type["session_end"]) == 1
    assert by_type["train_begin"][0]["end_iteration"] == rounds
    assert len(by_type["compile"]) > 0  # the watcher saw jit cache misses
    for i, rec in enumerate(by_type["iteration"]):
        assert rec["iteration"] == i
        assert rec["wall_s"] > 0
        assert rec["num_trees"] == i + 1
        assert rec["tree_leaves"] > 0
    end = by_type["session_end"][0]
    assert end["compile_count"] == len(by_type["compile"])
    assert end["events"]["iteration"] == rounds
    assert end["n_spans"] > 0

    # the trace must be loadable and structurally valid Perfetto input
    trace = json.load(open(os.path.join(tmp_path, TRACE_FILE)))
    evs = trace["traceEvents"]
    assert isinstance(evs, list) and evs
    last_ts = 0
    depth = {}
    for ev in evs:
        if ev["ph"] == "M":
            continue
        assert isinstance(ev["ts"], int) and ev["ts"] >= last_ts
        last_ts = ev["ts"]
        if ev["ph"] in "BE":
            key = (ev["pid"], ev["tid"])
            depth[key] = depth.get(key, 0) + (1 if ev["ph"] == "B" else -1)
            assert depth[key] >= 0, "E without matching B on track %s" % (key,)
    assert all(d == 0 for d in depth.values()), "unclosed spans: %s" % depth
    # the thread-name metadata names the timer phases feeding the tracks
    names = {ev["args"]["name"] for ev in evs
             if ev["ph"] == "M" and ev["name"] == "thread_name"}
    assert "tree_train" in names


def test_checkpoint_events_ride_the_stream(tmp_path):
    X, y = _data()
    run_dir = tmp_path / "tel"
    with telemetry.capture(str(run_dir)):
        bst = _train({}, X, y, rounds=2)
        save_checkpoint(bst, str(tmp_path / "snap.txt"))
    events = _read_events(run_dir)
    ck = [e for e in events if e["ev"] == "checkpoint"]
    assert len(ck) == 1 and ck[0]["iteration"] == 2
    assert ck[0]["model_only"] is False and ck[0]["sidecar_bytes"] > 0


def test_env_var_enables_telemetry(tmp_path, monkeypatch):
    monkeypatch.setenv(telemetry.ENV_VAR, str(tmp_path))
    X, y = _data(n=200)
    _train({}, X, y, rounds=2)
    assert {e["ev"] for e in _read_events(tmp_path)} >= {
        "session_start", "iteration", "session_end"}


def test_telemetry_on_is_bit_identical_to_off(tmp_path):
    X, y = _data()
    base = _train({}, X, y, rounds=4)
    with telemetry.capture(str(tmp_path)):
        instrumented = _train({}, X, y, rounds=4)
    assert base.model_to_string() == instrumented.model_to_string()
    np.testing.assert_array_equal(base.predict(X, raw_score=True),
                                  instrumented.predict(X, raw_score=True))


def test_device_learner_emits_tree_wave_events(tmp_path):
    # the factory only picks DeviceTreeLearner on accelerators; instantiate
    # directly (the test_device_learner.py pattern) to cover the wave-
    # efficiency event off-TPU
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import Dataset as CoreDataset
    from lightgbm_tpu.models.gbdt import GBDT
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.treelearner.device import DeviceTreeLearner

    X, y = _data(n=800)
    cfg = Config(dict(BASE))
    ds = CoreDataset.from_matrix(np.asarray(X, np.float64), label=y,
                                 config=cfg)
    bst = GBDT(cfg, ds, create_objective(cfg.objective, cfg))
    bst.tree_learner = DeviceTreeLearner(cfg, ds)
    with telemetry.capture(str(tmp_path), watch_compiles=False) as s:
        for _ in range(3):
            bst.train_one_iter()
        deltas = s.counter_deltas()
    waves = [e for e in _read_events(tmp_path) if e["ev"] == "tree_wave"]
    assert waves, "device learner finalize emitted no tree_wave events"
    for w in waves:
        assert w["waves"] >= 1
        assert 0 < w["committed"] <= w["speculated"]
        assert w["speculated"] == w["waves"] * w["wave_width"]
        assert 0 < w["efficiency"] <= 1.0
    assert deltas["device_waves"] >= len(waves)
    assert deltas["wave_splits_committed"] == sum(
        w["committed"] for w in waves)


# -- watchers -------------------------------------------------------------

def test_recompile_watcher_counts_forced_shape_changes():
    @jax.jit
    def poly(v):
        return (v * 2.0).sum()

    with telemetry.capture(None, label="shapes") as s:
        before = s.recompiles.total
        for n in (8, 16, 32):  # three distinct shapes -> three cache misses
            poly(jnp.ones((n,), jnp.float32)).block_until_ready()
        fn_counts = {fn: c for fn, c in s.recompiles.per_fn.items()
                     if "poly" in fn}
        assert sum(fn_counts.values()) == 3
        assert s.recompiles.total >= before + 3
        compiles = [e for e in s.events if e["ev"] == "compile"
                    and "poly" in e["fn"]]
        assert len(compiles) == 3
        shapes = {e["shapes"] for e in compiles}
        assert len(shapes) == 3  # distinct input shapes recorded
    summary = s.close()
    assert summary["compile_count"] >= 3


def test_recompile_watcher_warns_on_churn(capsys):
    @jax.jit
    def churny(v):
        return v + 1.0

    with telemetry.capture(None, label="churn", recompile_warn=2):
        for n in (3, 5):
            churny(jnp.ones((n,), jnp.float32)).block_until_ready()
    out = capsys.readouterr()
    assert "Recompile churn: 'churny' compiled 2 times" in out.out + out.err


def test_recompile_watcher_restores_logging_state():
    pxla = logging.getLogger("jax._src.interpreters.pxla")
    prev_propagate = pxla.propagate
    prev_flag = bool(jax.config.jax_log_compiles)
    with telemetry.capture(None, label="restore"):
        assert pxla.propagate is False
        assert bool(jax.config.jax_log_compiles) is True
    assert pxla.propagate == prev_propagate
    assert bool(jax.config.jax_log_compiles) == prev_flag


def test_kernel_fn_registry_and_markers():
    telemetry.register_kernel_fn("my_custom_kernel_entry")
    assert telemetry.is_kernel_fn("my_custom_kernel_entry")
    # the pallas wrappers register at import; substring markers back them up
    assert telemetry.is_kernel_fn("_pallas_compact_call")
    assert telemetry.is_kernel_fn("some_mosaic_lowered_fn")
    assert not telemetry.is_kernel_fn("find_best_split")


def test_recompile_watcher_splits_kernel_compiles():
    pxla = logging.getLogger("jax._src.interpreters.pxla")
    with telemetry.capture(None, label="kernel") as s:
        base = telemetry.signals()
        # synthetic compile-log lines in jax's exact format: one Pallas
        # kernel wrapper, one ordinary jit function
        pxla.warning("Compiling pallas_histogram with global shapes and "
                     "types (f32[128,8],). Argument mapping: ().")
        pxla.warning("Compiling update_score with global shapes and "
                     "types (f32[128],). Argument mapping: ().")
        sig = telemetry.signals()
        assert sig["compiles"] == base["compiles"] + 2
        assert sig["kernel_compiles"] == base["kernel_compiles"] + 1
        flags = {e["fn"]: e["kernel"] for e in s.events
                 if e["ev"] == "compile"
                 and e["fn"] in ("pallas_histogram", "update_score")}
        assert flags == {"pallas_histogram": True, "update_score": False}
    summary = s.close()
    assert summary["kernel_compile_count"] == 1
    assert summary["compile_count"] >= 2


class _FakeDevice:
    def __init__(self, name, peak):
        self._name, self._peak = name, peak

    def memory_stats(self):
        return {"peak_bytes_in_use": self._peak, "bytes_in_use": 1}

    def __str__(self):
        return self._name


def test_hbm_gauge_tracks_high_water_and_counter_track(tmp_path):
    devs = [_FakeDevice("tpu:0", 1000), _FakeDevice("tpu:1", 3000)]
    with telemetry.capture(str(tmp_path), label="hbm", devices=devs,
                           watch_compiles=False) as s:
        s.hbm.sample()
        devs[0]._peak = 5000  # later sample raises the high-water
        summary_peak = telemetry.sample_hbm()
    assert summary_peak == 5000
    assert s.close()["hbm_high_water_bytes"] == 5000
    assert global_timer.counters["hbm_high_water_bytes"] == 5000
    assert "hbm_high_water_bytes" in global_timer.gauges
    trace = json.load(open(os.path.join(tmp_path, TRACE_FILE)))
    counters = [e for e in trace["traceEvents"] if e["ph"] == "C"]
    assert {e["name"] for e in counters} == {"hbm:tpu:0", "hbm:tpu:1"}
    assert max(e["args"]["bytes"] for e in counters) == 5000


# -- session mechanics ----------------------------------------------------

def test_counter_deltas_scope_accumulators_to_the_session():
    global_timer.add_count("test_accum", 10)  # pre-session noise
    with telemetry.capture(None, label="deltas",
                           watch_compiles=False) as s:
        global_timer.add_count("test_accum", 7)
        global_timer.set_count("test_gauge", 42)
        deltas = s.counter_deltas()
    assert deltas["test_accum"] == 7     # delta, not the cumulative 17
    assert deltas["test_gauge"] == 42    # gauges read absolute


def test_second_start_keeps_first_session(tmp_path):
    s1 = telemetry.start(None, label="first", watch_compiles=False)
    try:
        s2 = telemetry.start(str(tmp_path), label="second")
        assert s2 is s1
    finally:
        assert telemetry.stop()["label"] == "first"
    assert telemetry.stop() is None  # idempotent when nothing is active


def test_capture_closes_on_exception(tmp_path):
    with pytest.raises(RuntimeError):
        with telemetry.capture(str(tmp_path), watch_compiles=False):
            telemetry.emit("custom", detail="before the failure")
            raise RuntimeError("boom")
    assert telemetry.session() is None
    evs = _read_events(tmp_path)
    assert [e["ev"] for e in evs][0] == "session_start"
    assert any(e["ev"] == "custom" for e in evs)
    assert evs[-1]["ev"] == "session_end"  # close flushed despite the raise


def test_session_restores_timer_hooks():
    prev_enabled = global_timer.enabled
    prev_hook = global_timer.span_hook
    with telemetry.capture(None, watch_compiles=False):
        assert global_timer.enabled is True
        assert global_timer.span_hook is not None
    assert global_timer.enabled == prev_enabled
    assert global_timer.span_hook == prev_hook


def test_jsonl_flush_cadence(tmp_path):
    with telemetry.capture(str(tmp_path), flush_every=4,
                           watch_compiles=False):
        for i in range(6):
            telemetry.emit("tick", i=i)
        # 7 events so far (session_start + 6) -> one mid-run flush at 4
        assert len(_read_events(tmp_path)) == 4
    assert len(_read_events(tmp_path)) == 8  # close flushes the rest


def test_event_payloads_jsonable_for_device_scalars(tmp_path):
    with telemetry.capture(str(tmp_path), watch_compiles=False):
        telemetry.emit("device_vals", scalar=jnp.float32(1.5),
                       vec=jnp.arange(3), np_int=np.int64(7))
    ev = [e for e in _read_events(tmp_path) if e["ev"] == "device_vals"][0]
    assert ev["scalar"] == 1.5 and ev["vec"] == [0, 1, 2] and ev["np_int"] == 7


# -- trace builder unit ---------------------------------------------------

def test_chrome_trace_orders_ties_and_nests_containment():
    # outer contains inner; a zero-length span and an exact tie stress the
    # E-before-B ordering the Perfetto importer requires
    spans = [("outer", 0.0, 0.010), ("inner", 0.002, 0.004),
             ("inner", 0.004, 0.004), ("outer", 0.010, 0.020)]
    trace = build_chrome_trace(spans, [("hbm:dev", 0.001, 5)], label="unit")
    evs = [e for e in trace["traceEvents"] if e["ph"] != "M"]
    assert all(b["ts"] <= a["ts"] for b, a in zip(evs, evs[1:]))
    at_10ms = [(e["ph"], e["name"]) for e in evs if e["ts"] == 10000]
    assert at_10ms.index(("E", "outer")) < at_10ms.index(("B", "outer"))
    c = [e for e in evs if e["ph"] == "C"]
    assert len(c) == 1 and c[0]["args"]["bytes"] == 5


# -- the overhead budget --------------------------------------------------

# generous stand-in for the real count of enabled()/emit() call sites hit
# per boosting iteration (engine loop + per-wave + per-chunk guards)
_CALL_SITES_PER_ITER = 2000


@pytest.mark.slow
def test_disabled_overhead_under_one_percent():
    assert not telemetry.enabled()
    n = 200_000
    t0 = time.perf_counter()
    for _ in range(n):
        telemetry.emit("hot", a=1)
    emit_cost = (time.perf_counter() - t0) / n
    t0 = time.perf_counter()
    for _ in range(n):
        telemetry.enabled()
    guard_cost = (time.perf_counter() - t0) / n

    # per-iteration wall from a real (telemetry-off) training run
    X, y = _data(n=2000, f=20)
    _train({}, X, y, rounds=2)  # warm the jit caches out of the measurement
    rounds = 10
    t0 = time.perf_counter()
    _train({}, X, y, rounds=rounds)
    iter_wall = (time.perf_counter() - t0) / rounds

    worst_site = max(emit_cost, guard_cost)
    modeled_pct = 100.0 * _CALL_SITES_PER_ITER * worst_site / iter_wall
    assert modeled_pct < 1.0, (
        "disabled telemetry path too hot: %.3f%% modeled overhead "
        "(%.0f ns/site x %d sites vs %.1f ms/iter)" % (
            modeled_pct, worst_site * 1e9, _CALL_SITES_PER_ITER,
            iter_wall * 1e3))
