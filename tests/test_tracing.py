"""Tracing + flight-recorder suite: W3C traceparent handling, the
log-bucketed stage histograms, ring-buffer eviction determinism under
concurrent writers, end-to-end header propagation over the HTTP front,
breaker transitions recorded with telemetry OFF, the dump format round
trip through flightview and teldiff --self-check, and bit-identical
numerics with the recorder on vs compiled out.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import telemetry, tracing
from lightgbm_tpu.serving import CircuitBreaker, PredictionService
from lightgbm_tpu.serving.http import serve
from lightgbm_tpu.utils import faults

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PARAMS = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
          "min_data_in_leaf": 5}


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """Each test starts from an empty ring + stats and leaves the module
    enabled (the process default) for the next suite."""
    tracing.reset()
    tracing.set_enabled(True)
    yield
    faults.clear()
    tracing.reset()
    tracing.set_enabled(True)


def _train_small(rng, rounds=4):
    X = rng.rand(400, 10)
    y = (X[:, 0] + X[:, 1] > 1.0).astype(np.float64)
    bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=rounds)
    return bst, X


# -- W3C trace context ----------------------------------------------------

def test_traceparent_roundtrip():
    tid, sid = tracing.new_trace_id(), tracing.new_span_id()
    header = tracing.format_traceparent(tid, sid)
    assert tracing.parse_traceparent(header) == (tid, sid)
    # case-insensitive with surrounding whitespace, per spec
    assert tracing.parse_traceparent("  " + header.upper() + " ") \
        == (tid, sid)


@pytest.mark.parametrize("header", [
    None, "", "garbage", "00-short-beef-01",
    "00-" + "g" * 32 + "-" + "a" * 16 + "-01",          # non-hex
    "ff-" + "a" * 32 + "-" + "b" * 16 + "-01",          # forbidden version
    "00-" + "0" * 32 + "-" + "b" * 16 + "-01",          # zero trace id
    "00-" + "a" * 32 + "-" + "0" * 16 + "-01",          # zero parent id
    "00-" + "a" * 32 + "-" + "b" * 16 + "-01-extra",    # trailing junk
])
def test_traceparent_malformed_restarts_trace(header):
    # malformed context restarts the trace (W3C behaviour), never raises
    assert tracing.parse_traceparent(header) is None
    span = tracing.start_span("t", traceparent=header)
    assert span.parent_id is None and len(span.trace_id) == 32


def test_span_ancestry_and_stage_accumulation():
    parent = tracing.start_span("outer")
    child = tracing.start_span("inner", parent=parent)
    assert child.trace_id == parent.trace_id
    assert child.parent_id == parent.span_id
    child.add_stage("device", 0.010)
    child.add_stage("device", 0.005)  # chunked dispatch accumulates
    child.finish()
    assert child.stages["device"] == pytest.approx(0.015)
    # finish is idempotent and freezes the stage map
    child.add_stage("device", 1.0)
    child.finish(terminal="late")
    assert child.stages["device"] == pytest.approx(0.015)
    assert child.terminal is None
    parent.finish()


# -- stage histograms -----------------------------------------------------

def test_stage_histogram_quantiles_conservative():
    h = tracing.StageHistogram()
    for ms in (1.0, 2.0, 4.0, 8.0, 100.0):
        h.record(ms / 1000.0)
    h.record(-1.0)  # clock skew clamps to bucket 0, never raises
    assert h.n == 6
    # bucket upper bound: reported quantile >= true value, within one
    # geometric bucket width (25%)
    p99 = h.quantile_s(0.99)
    assert 0.100 <= p99 <= 0.100 * 1.25
    assert h.quantile_s(0.50) >= 0.002


def test_stage_summary_and_gauges_from_finished_spans():
    for _ in range(3):
        s = tracing.start_span("serve_request")
        s.add_stage("device", 0.004)
        s.add_stage("queue_wait", 0.001)
        s.finish()
    summary = tracing.stage_summary("serve_request")
    assert summary["device"]["count"] == 3
    assert summary["device"]["p99_ms"] >= 4.0
    assert summary["device"]["total_ms"] == pytest.approx(12.0, rel=0.01)
    gauges = tracing.quantile_gauges()
    assert gauges["serve_request_stage_device_p99_ms"] >= 4.0
    assert "serve_request_stage_queue_wait_p50_ms" in gauges


def test_quantile_gauges_round_trip_through_exposition():
    from lightgbm_tpu import exposition

    s = tracing.start_span("serve_request")
    s.add_stage("device", 0.002)
    s.finish()
    parsed = exposition.parse_exposition(exposition.render_metrics())
    key = ("lgbm_tpu_serve_request_stage_device_p99_ms", ())
    assert key in parsed and parsed[key] >= 2.0


# -- flight recorder ring -------------------------------------------------

def test_ring_eviction_deterministic_under_concurrent_writers():
    rec = tracing.FlightRecorder(capacity=64)
    n_threads, per_thread = 8, 100

    def writer(tid):
        for i in range(per_thread):
            rec.note("w", {"tid": tid, "i": i})

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    assert rec.total == total
    assert rec.dropped == total - 64
    snap = rec.snapshot()
    # exactly the newest `capacity` records survive, in sequence order,
    # with no gaps and no duplicates — eviction is deterministic
    assert [r["seq"] for r in snap] == list(range(total - 64, total))
    ts = [r["t"] for r in snap]
    assert all(b <= a for b, a in zip(ts, ts[1:]))


def test_recorder_disabled_drops_everything():
    tracing.set_enabled(False)
    tracing.note("never", x=1)
    s = tracing.start_span("serve_request")
    s.add_stage("device", 0.001)
    s.finish()
    assert tracing.recorder().total == 0
    assert tracing.stage_summary("serve_request") == {}
    assert tracing.dump_flight("unit") is None and tracing.last_dump() is None


def test_dump_rate_limited_per_reason(tmp_path, monkeypatch):
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    tracing.note("hello", n=1)
    p1 = tracing.dump_flight("storm")
    assert p1 and os.path.isfile(p1)
    # a second firing inside the interval is swallowed...
    assert tracing.dump_flight("storm") is None
    # ...but a different reason and a forced dump still write
    assert tracing.dump_flight("other") is not None
    assert tracing.dump_flight("storm", force=True) == p1  # same file: bounded


# -- dump format round trip ----------------------------------------------

def test_dump_flightview_teldiff_round_trip(tmp_path, monkeypatch):
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    for i in range(5):
        tracing.note("tick", i=i)
    s = tracing.start_span("serve_request")
    s.add_stage("device", 0.003)
    s.finish()
    path = tracing.dump_flight("unit_test", extra={"k": "v"})
    assert path == str(tmp_path / "flight-unit_test.json")
    dump = json.loads((tmp_path / "flight-unit_test.json").read_text())
    assert dump["format"] == "lgbm-flight" and dump["version"] == 1
    assert dump["reason"] == "unit_test" and dump["extra"] == {"k": "v"}
    assert [e["kind"] for e in dump["events"][:5]] == ["tick"] * 5
    assert dump["stage_summary"]["serve_request"]["device"]["count"] == 1

    # flightview renders + emits a loadable Chrome trace
    trace_out = tmp_path / "trace.json"
    fv = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "flightview.py"),
         path, "--trace", str(trace_out)],
        capture_output=True, text=True, timeout=60)
    assert fv.returncode == 0, fv.stderr
    assert "unit_test" in fv.stdout
    trace = json.loads(trace_out.read_text())
    names = {e["name"] for e in trace["traceEvents"]}
    assert "serve_request.device" in names

    # teldiff --self-check accepts the dump format
    td = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "teldiff.py"),
         "--self-check", path], capture_output=True, text=True, timeout=60)
    assert td.returncode == 0, td.stdout + td.stderr


# -- HTTP propagation -----------------------------------------------------

@pytest.fixture()
def served(rng):
    bst, X = _train_small(rng)
    svc = PredictionService(max_batch_rows=1024, batch_window_s=0.0)
    svc.load_model("m", booster=bst)
    server, _ = serve(svc, port=0)
    yield server.port, bst, svc
    server.shutdown()
    svc.close()


def _post_predict(port, payload, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(payload).encode(), method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return (resp.status, json.loads(resp.read()),
                resp.headers.get("traceparent"))


def _wait_for_request_spans(pred, timeout_s=5.0):
    """The handler finishes (and records) the span AFTER the response bytes
    are on the wire, so poll briefly instead of racing the handler thread."""
    deadline = time.perf_counter() + timeout_s
    while True:
        spans = [r for r in tracing.recorder().snapshot()
                 if r["kind"] == "span" and r["name"] == "serve_request"
                 and pred(r)]
        if spans or time.perf_counter() >= deadline:
            return spans
        time.sleep(0.01)


def test_inbound_traceparent_honored_and_echoed(served, rng):
    port, _, _ = served
    rows = rng.rand(4, 10).tolist()
    inbound_trace = "c" * 32
    header = f"00-{inbound_trace}-{'b' * 16}-01"
    status, body, echoed = _post_predict(
        port, {"model": "m", "rows": rows}, {"traceparent": header})
    assert status == 200
    # same trace id end to end; the echoed span id is the SERVER's span
    assert body["trace_id"] == inbound_trace
    parsed = tracing.parse_traceparent(echoed)
    assert parsed is not None and parsed[0] == inbound_trace
    assert parsed[1] != "b" * 16
    # the finished request span landed in the recorder with ancestry
    mine = _wait_for_request_spans(
        lambda s: s["trace_id"] == inbound_trace)
    assert mine and mine[-1]["parent_id"] == "b" * 16


def test_missing_or_malformed_traceparent_generates_fresh(served, rng):
    port, _, _ = served
    rows = rng.rand(2, 10).tolist()
    _, body1, tp1 = _post_predict(port, {"model": "m", "rows": rows})
    _, body2, tp2 = _post_predict(port, {"model": "m", "rows": rows},
                                  {"traceparent": "not-a-traceparent"})
    for body, tp in ((body1, tp1), (body2, tp2)):
        assert len(body["trace_id"]) == 32
        assert tracing.parse_traceparent(tp)[0] == body["trace_id"]
    assert body1["trace_id"] != body2["trace_id"]


def test_request_span_stages_cover_the_wall(served, rng):
    port, _, _ = served
    rows = rng.rand(32, 10).tolist()
    t0 = time.perf_counter()
    status, _, _ = _post_predict(port, {"model": "m", "rows": rows})
    wall_ms = (time.perf_counter() - t0) * 1000.0
    assert status == 200
    spans = _wait_for_request_spans(lambda s: "serialize" in s["stages_ms"])
    assert spans
    stages = spans[-1]["stages_ms"]
    # the full decomposition is present...
    for name in ("parse", "queue_wait", "assembly", "device", "d2h",
                 "serialize"):
        assert name in stages, sorted(stages)
    # ...and sums to no more than the observed client wall (stages are
    # disjoint sections of one request; client wall adds socket overhead)
    assert 0.0 < sum(stages.values()) <= wall_ms
    # /statz surfaces the same figures as quantiles
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/statz", timeout=10) as resp:
        stz = json.loads(resp.read())
    assert stz["stages"]["device"]["count"] >= 1
    assert stz["flight"]["enabled"] and stz["flight"]["records"] > 0


def test_debug_flight_endpoint(served):
    port, _, _ = served
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/flight", timeout=10) as resp:
        dump = json.loads(resp.read())
    assert dump["format"] == "lgbm-flight"
    assert dump["reason"] == "debug_endpoint"


# -- breaker postmortems (telemetry OFF throughout) -----------------------

def test_breaker_transitions_recorded_without_telemetry(tmp_path,
                                                        monkeypatch, rng):
    assert not telemetry.enabled()
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    bst, X = _train_small(rng)
    svc = PredictionService(max_batch_rows=512, batch_window_s=0.0,
                            breaker=CircuitBreaker(cooldown_s=30.0))
    try:
        svc.load_model("m", booster=bst)
        expected = bst.predict(X[:16])
        faults.install("predict_fail@1:10")
        for _ in range(4):
            out = svc.predict("m", X[:16])
            # host fallback keeps answers bit-identical through the flap
            assert np.array_equal(out, expected)
            if svc.breaker.state == "open":
                break
        faults.clear()
        assert svc.breaker.state == "open"
        # satellite (a): the transition history exists with telemetry off
        info = svc.breaker.info()
        opens = [t for t in info["last_transitions"] if t["new"] == "open"]
        assert opens and "failure" in opens[0]["reason"]
        # ...mirrored into the recorder...
        recorded = [r for r in tracing.recorder().snapshot()
                    if r["kind"] == "breaker_transition"
                    and r["new"] == "open"]
        assert recorded
        # ...and the auto-dump fired with the breaker context attached
        dump_path = tmp_path / "flight-breaker_open.json"
        assert dump_path.is_file()
        dump = json.loads(dump_path.read_text())
        assert dump["telemetry_enabled"] is False
        assert dump["extra"]["breaker"]["state"] == "open"
        assert any(e.get("kind") == "fault" for e in dump["events"])
    finally:
        faults.clear()
        svc.close()


# -- numerics: recorder on == recorder off --------------------------------

def test_bit_identical_numerics_with_recorder_on_and_off(rng):
    X = rng.rand(500, 12)
    y = (X[:, 0] - 0.3 * X[:, 1] > 0.2).astype(np.float64)
    Q = rng.rand(64, 12)

    def run():
        bst = lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=6)
        return bst.model_to_string(), bst.predict(Q)

    tracing.set_enabled(True)
    model_on, preds_on = run()
    assert tracing.recorder().total > 0  # the run really was recorded
    tracing.reset()
    tracing.set_enabled(False)
    model_off, preds_off = run()
    assert tracing.recorder().total == 0
    assert model_on == model_off
    assert np.array_equal(preds_on, preds_off)


# -- overhead budget ------------------------------------------------------

# per-iteration recorder call sites: iteration span finish + a handful of
# note() sites (waves, faults); generous stand-in like telemetry's model
_NOTE_SITES_PER_ITER = 500


@pytest.mark.slow
def test_recorder_overhead_under_one_percent(rng):
    n = 100_000
    tracing.set_enabled(True)
    t0 = time.perf_counter()
    for _ in range(n):
        tracing.note("hot", a=1, b=2)
    on_cost = (time.perf_counter() - t0) / n
    tracing.set_enabled(False)
    t0 = time.perf_counter()
    for _ in range(n):
        tracing.note("hot", a=1, b=2)
    off_cost = (time.perf_counter() - t0) / n
    tracing.set_enabled(True)

    X = rng.rand(2000, 20)
    y = (X[:, 0] > 0.5).astype(np.float64)
    ds = lgb.Dataset(X, label=y)
    lgb.train(PARAMS, ds, num_boost_round=2)  # warm jit caches
    rounds = 10
    t0 = time.perf_counter()
    lgb.train(PARAMS, ds, num_boost_round=rounds)
    iter_wall = (time.perf_counter() - t0) / rounds

    # the enabled-vs-compiled-out DELTA, modeled at a generous call-site
    # count, must stay under the 1% budget
    delta = max(0.0, on_cost - off_cost)
    modeled_pct = 100.0 * _NOTE_SITES_PER_ITER * delta / iter_wall
    assert modeled_pct < 1.0, (
        "recorder append too hot: %.3f%% modeled overhead "
        "(%.0f ns/site on, %.0f ns/site off, %.1f ms/iter)" % (
            modeled_pct, on_cost * 1e9, off_cost * 1e9, iter_wall * 1e3))


# -- training spans -------------------------------------------------------

def test_train_iteration_spans_recorded(rng):
    X = rng.rand(300, 8)
    y = (X[:, 0] > 0.5).astype(np.float64)
    lgb.train(PARAMS, lgb.Dataset(X, label=y), num_boost_round=3)
    spans = [r for r in tracing.recorder().snapshot()
             if r["kind"] == "span" and r["name"] == "train_iteration"]
    assert len(spans) == 3
    assert [s["attrs"]["iteration"] for s in spans] == [0, 1, 2]
    assert all("boost" in s["stages_ms"] for s in spans)
    summary = tracing.stage_summary("train_iteration")
    assert summary["boost"]["count"] == 3
