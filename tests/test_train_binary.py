"""End-to-end training tests: the v0 demo slice.

Mirrors the reference's golden-threshold strategy (tests/distributed/
_test_distributed.py asserts accuracy >= thresholds on known data; the
examples/ configs are the fixtures)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb

BINARY_TRAIN = "/root/reference/examples/binary_classification/binary.train"
BINARY_TEST = "/root/reference/examples/binary_classification/binary.test"


def make_synthetic(n=2000, f=10, seed=7):
    rng = np.random.RandomState(seed)
    X = rng.normal(size=(n, f))
    logit = 1.5 * X[:, 0] - 2.0 * X[:, 1] + X[:, 2] * X[:, 3]
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    return X, y


def auc_np(y, p):
    order = np.argsort(p)
    y = y[order]
    n_pos = y.sum()
    n_neg = len(y) - n_pos
    ranks = np.arange(1, len(y) + 1)
    return (ranks[y > 0].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def test_binary_synthetic_train_auc():
    X, y = make_synthetic()
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 15, "verbosity": -1},
                    ds, num_boost_round=30)
    pred = bst.predict(X)
    assert pred.min() >= 0 and pred.max() <= 1
    auc = auc_np(y, pred)
    assert auc > 0.97, f"train AUC too low: {auc}"


def test_binary_valid_and_early_stopping():
    X, y = make_synthetic(3000)
    Xtr, ytr, Xv, yv = X[:2000], y[:2000], X[2000:], y[2000:]
    ds = lgb.Dataset(Xtr, label=ytr)
    dv = lgb.Dataset(Xv, label=yv, reference=ds)
    record = {}
    bst = lgb.train({"objective": "binary", "metric": "auc,binary_logloss",
                     "num_leaves": 15, "verbosity": -1},
                    ds, num_boost_round=40, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(record)])
    assert "valid_0" in record
    assert len(record["valid_0"]["auc"]) == 40
    assert record["valid_0"]["auc"][-1] > 0.9
    # logloss should improve over training
    assert record["valid_0"]["binary_logloss"][-1] < record["valid_0"]["binary_logloss"][0]


def test_model_save_load_predict_consistency(tmp_path):
    X, y = make_synthetic(1000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "binary", "num_leaves": 7, "verbosity": -1},
                    ds, num_boost_round=10)
    path = str(tmp_path / "model.txt")
    bst.save_model(path)
    bst2 = lgb.Booster(model_file=path)
    p1 = bst.predict(X[:100])
    p2 = bst2.predict(X[:100])
    np.testing.assert_allclose(p1, p2, rtol=1e-5)
    # host-side tree predict agrees with device path
    model = lgb.GBDTModel.from_file(path)
    import math
    for i in range(5):
        raw_host = sum(t.predict(X[i]) for t in model.trees)
        p_host = 1.0 / (1.0 + math.exp(-raw_host))
        assert abs(p_host - p1[i]) < 1e-4


def test_reference_example_binary_auc():
    """Train on the reference's example data; AUC threshold mirrors the
    distributed-test accuracy gates."""
    ds = lgb.Dataset(BINARY_TRAIN, params={"header": False})
    dv = lgb.Dataset(BINARY_TEST, reference=ds)
    rec = {}
    bst = lgb.train({"objective": "binary", "metric": "auc", "num_leaves": 31,
                     "learning_rate": 0.1, "verbosity": -1},
                    ds, num_boost_round=50, valid_sets=[dv],
                    callbacks=[lgb.record_evaluation(rec)])
    auc = rec["valid_0"]["auc"][-1]
    # binary.train is a 7k-row HIGGS subset; HIGGS AUC tops out ~0.845
    # (docs/Experiments.rst:134). 0.80 at 50 rounds gates real learning.
    assert auc > 0.80, f"reference-example AUC too low: {auc}"


def test_regression_l2():
    rng = np.random.RandomState(3)
    X = rng.uniform(-3, 3, size=(2000, 5))
    y = X[:, 0] ** 2 + 2 * np.sin(X[:, 1]) + rng.normal(scale=0.1, size=2000)
    ds = lgb.Dataset(X, label=y)
    bst = lgb.train({"objective": "regression", "num_leaves": 31, "verbosity": -1},
                    ds, num_boost_round=50)
    pred = bst.predict(X)
    mse = float(np.mean((pred - y) ** 2))
    var = float(np.var(y))
    assert mse < 0.1 * var, f"mse {mse} vs var {var}"


def test_custom_objective_fobj():
    X, y = make_synthetic(1000)
    ds = lgb.Dataset(X, label=y)

    def logloss_obj(preds, train_data):
        labels = train_data.get_label()
        p = 1.0 / (1.0 + np.exp(-preds))
        return p - labels, p * (1 - p)

    # objective 'none' without fobj must fail like the reference
    with pytest.raises(lgb.LightGBMError):
        lgb.train({"objective": "none", "num_leaves": 7, "verbosity": -1},
                  ds, num_boost_round=2)
    # custom objective through params callable
    ds2 = lgb.Dataset(X, label=y)
    bst2 = lgb.train({"objective": logloss_obj, "num_leaves": 7, "verbosity": -1},
                     ds2, num_boost_round=20)
    raw = bst2.predict(X, raw_score=True)
    auc = auc_np(y, raw)
    assert auc > 0.95


def test_predict_start_iteration(rng):
    """start_iteration slices the ensemble (Booster.predict parity with
    python-package predict(start_iteration=...))."""
    X = rng.randn(800, 4)
    y = (X[:, 0] > 0).astype(np.float64)
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "verbosity": -1}, lgb.Dataset(X, label=y),
                    num_boost_round=6)
    full = bst.predict(X, raw_score=True)
    head = bst.predict(X, raw_score=True, num_iteration=2)
    tail = bst.predict(X, raw_score=True, start_iteration=2)
    np.testing.assert_allclose(head + tail, full, rtol=1e-5, atol=1e-6)
    mid = bst.predict(X, raw_score=True, start_iteration=2, num_iteration=2)
    last = bst.predict(X, raw_score=True, start_iteration=4)
    np.testing.assert_allclose(head + mid + last, full, rtol=1e-5, atol=1e-6)
