import math

import numpy as np
import pytest

from lightgbm_tpu.models.tree import Tree, MISSING_NONE, MISSING_NAN, MISSING_ZERO
from lightgbm_tpu.models.serialize import GBDTModel


def make_simple_tree():
    """f0 <= 0.5 -> leaf0(-1.0); else f1 <= 2.5 -> leaf1(2.0) else leaf2(3.0)."""
    t = Tree(max_leaves=4)
    right = t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
                    threshold_double=0.5, default_left=False, missing_type=MISSING_NONE,
                    gain=10.0, left_value=-1.0, right_value=1.5, left_count=5, right_count=5,
                    left_weight=5.0, right_weight=5.0, parent_value=0.0)
    t.split(leaf=right, feature_inner=1, real_feature=1, threshold_bin=2,
            threshold_double=2.5, default_left=False, missing_type=MISSING_NONE,
            gain=4.0, left_value=2.0, right_value=3.0, left_count=3, right_count=2,
            left_weight=3.0, right_weight=2.0, parent_value=1.5)
    return t


def test_tree_predict():
    t = make_simple_tree()
    assert t.num_leaves == 3
    assert t.predict(np.array([0.0, 0.0])) == -1.0
    assert t.predict(np.array([1.0, 2.0])) == 2.0
    assert t.predict(np.array([1.0, 3.0])) == 3.0


def test_missing_nan_default_direction():
    t = Tree(max_leaves=2)
    t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
            threshold_double=0.5, default_left=True, missing_type=MISSING_NAN,
            gain=1.0, left_value=-1.0, right_value=1.0, left_count=1, right_count=1,
            left_weight=1.0, right_weight=1.0, parent_value=0.0)
    assert t.predict(np.array([float("nan")])) == -1.0
    assert t.predict(np.array([0.7])) == 1.0
    # NaN with missing_type None is treated as 0.0 (tree.h:339-341)
    t2 = Tree(max_leaves=2)
    t2.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
             threshold_double=0.5, default_left=False, missing_type=MISSING_NONE,
             gain=1.0, left_value=-1.0, right_value=1.0, left_count=1, right_count=1,
             left_weight=1.0, right_weight=1.0, parent_value=0.0)
    assert t2.predict(np.array([float("nan")])) == -1.0


def test_zero_as_missing():
    t = Tree(max_leaves=2)
    t.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
            threshold_double=-5.0, default_left=False, missing_type=MISSING_ZERO,
            gain=1.0, left_value=-1.0, right_value=1.0, left_count=1, right_count=1,
            left_weight=1.0, right_weight=1.0, parent_value=0.0)
    # zero goes to default (right) even though 0 > -5 would anyway; use default_left
    t2 = Tree(max_leaves=2)
    t2.split(leaf=0, feature_inner=0, real_feature=0, threshold_bin=1,
             threshold_double=5.0, default_left=False, missing_type=MISSING_ZERO,
             gain=1.0, left_value=-1.0, right_value=1.0, left_count=1, right_count=1,
             left_weight=1.0, right_weight=1.0, parent_value=0.0)
    assert t2.predict(np.array([0.0])) == 1.0  # zero -> default right despite 0 <= 5
    assert t2.predict(np.array([1.0])) == -1.0


def test_categorical_split():
    t = Tree(max_leaves=2)
    bitset = [0b1010]  # categories {1, 3} go left
    t.split_categorical(leaf=0, feature_inner=0, real_feature=0,
                        bin_bitset=bitset, value_bitset=bitset,
                        missing_type=MISSING_NONE, gain=1.0,
                        left_value=-2.0, right_value=2.0, left_count=1, right_count=1,
                        left_weight=1.0, right_weight=1.0, parent_value=0.0)
    assert t.predict(np.array([1.0])) == -2.0
    assert t.predict(np.array([3.0])) == -2.0
    assert t.predict(np.array([2.0])) == 2.0
    assert t.predict(np.array([float("nan")])) == 2.0
    assert t.predict(np.array([-1.0])) == 2.0
    assert t.predict(np.array([64.0])) == 2.0  # out of bitset range -> right


def test_shrinkage():
    t = make_simple_tree()
    t.shrink(0.1)
    assert t.predict(np.array([0.0, 0.0])) == pytest.approx(-0.1)
    assert t.shrinkage == pytest.approx(0.1)


def test_text_roundtrip():
    t = make_simple_tree()
    t.shrink(0.1)
    s = t.to_string()
    assert s.startswith("num_leaves=3")
    kv = {}
    for line in s.split("\n"):
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k] = v
    t2 = Tree.from_key_values(kv)
    assert t2.num_leaves == 3
    for row in ([0.0, 0.0], [1.0, 2.0], [1.0, 3.0], [0.5, 2.5]):
        assert t2.predict(np.array(row)) == pytest.approx(t.predict(np.array(row)))


def test_model_roundtrip():
    model = GBDTModel()
    model.num_class = 1
    model.num_tree_per_iteration = 1
    model.max_feature_idx = 1
    model.objective_str = "binary sigmoid:1"
    model.feature_names = ["Column_0", "Column_1"]
    model.feature_infos = ["[0:1]", "[0:5]"]
    model.trees = [make_simple_tree(), make_simple_tree()]
    model.trees[1].shrink(0.1)
    text = model.to_string()
    assert text.startswith("tree\nversion=v4\n")
    assert "end of trees" in text

    model2 = GBDTModel.from_string(text)
    assert model2.num_class == 1
    assert model2.max_feature_idx == 1
    assert model2.objective_str == "binary sigmoid:1"
    assert len(model2.trees) == 2
    row = np.array([1.0, 2.0])
    expected = model.trees[0].predict(row) + model.trees[1].predict(row)
    got = model2.trees[0].predict(row) + model2.trees[1].predict(row)
    assert got == pytest.approx(expected)
    # re-serialize identical
    assert model2.to_string() == text


def test_feature_importance():
    model = GBDTModel()
    model.max_feature_idx = 1
    model.feature_names = ["a", "b"]
    model.feature_infos = ["[0:1]", "[0:5]"]
    model.trees = [make_simple_tree()]
    imp = model.feature_importance("split")
    assert imp.tolist() == [1.0, 1.0]
    gain = model.feature_importance("gain")
    assert gain[0] == pytest.approx(10.0)


def test_json_dump():
    import json

    model = GBDTModel()
    model.max_feature_idx = 1
    model.feature_names = ["a", "b"]
    model.feature_infos = ["[0:1]", "[0:5]"]
    model.trees = [make_simple_tree()]
    d = json.loads(model.dump_json())
    assert d["num_class"] == 1
    assert d["tree_info"][0]["num_leaves"] == 3
    assert d["tree_info"][0]["tree_structure"]["split_feature"] == 0
