"""Pod-scale learners on the 8 fake CPU devices conftest forces: the
PV-Tree voting data-parallel learner and the feature-parallel learner
(ISSUE 18, arxiv 1611.01276 semantics).

Correctness strategy mirrors tests/test_sharded_device.py:

* top_k >= F elects EVERY feature (the sorted election index equals
  arange(F_pad)), so the voting rescan degenerates to the exact
  data-parallel reduction and the whole split log must be bit-identical
  to the single-device wave learner. Feature-parallel is exact by
  construction (disjoint blocks + tie-break toward the lowest device =
  lowest feature range), so it joins the same bit-identity matrix.
* small top_k is a DOCUMENTED approximation: quality is pinned against
  the exact learner (AUC within 1e-3 / L2 within 2%), and
  LGBM_TPU_VOTING_EXACT_CHECK=1 runs the full reduction alongside and
  counts committed-split disagreements (voting_miss_total).

Plus the comm-model gauges (voting ICI independent of F, feature ICI
independent of N, voting <= 1/4 of data-parallel at F=256/top_k=20), the
satellite int16-packing bugfix (decision keyed off the psum'd GLOBAL bag
count, never a shard-local view), the elastic-gang story (kill mid-train
surfaces WorkerLostError; shrink-to-fit resume re-shards the vote
bit-identically), and the vote_skew fault token (typed error, not a
hang, with and without the exact check).
"""
import time

import jax.numpy as jnp
import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.engine import train
from lightgbm_tpu.io.dataset import Dataset as CoreDataset
from lightgbm_tpu.models.gbdt import GBDT
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.parallel import elastic
from lightgbm_tpu.parallel.elastic import WorkerLostError
from lightgbm_tpu.parallel.learners import (DeviceDataParallelTreeLearner,
                                            DeviceFeatureParallelTreeLearner,
                                            VotingDataParallelTreeLearner)
from lightgbm_tpu.treelearner.device import DeviceTreeLearner
from lightgbm_tpu.utils import faults
from lightgbm_tpu.utils.faults import VotingDivergenceError
from lightgbm_tpu.utils.timer import global_timer


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.clear()
    elastic.clear()


def _snap(v):
    """Snap to the 2^-10 grid: f32 sums of ~1k such values are exact in
    any association order (see test_sharded_device.py)."""
    return np.round(np.clip(v, -1.0, 1.0) * 1024.0) / 1024.0


def _snapped_gh(rng, n):
    g = _snap(rng.uniform(-1.0, 1.0, n)).astype(np.float32)
    h = _snap(rng.uniform(0.25, 1.0, n)).astype(np.float32)
    gh = np.stack([g, h, np.ones(n, np.float32)], axis=1)
    return jnp.asarray(np.concatenate([gh, np.zeros((1, 3), np.float32)]))


def _learner(cls, X, y, params):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    return cls(cfg, ds)


def _auc(y, score):
    order = np.argsort(np.asarray(score))
    ranks = np.empty(len(y))
    ranks[order] = np.arange(1, len(y) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def _split_log(cls, X, y, params, gh, bag=None):
    learner = _learner(cls, X, y, params)
    pending = learner.train_async(gh, bag)
    log = np.asarray(pending.rec_store)
    learner.finalize(pending)
    return log, np.asarray(learner.partition.ids_host)


def _assert_same_log(a, b):
    # col 4 is the packed gain scalar — 1-ulp XLA fusion wobble between
    # the two compiled programs; every decision-bearing column is exact
    gain_col = 4
    np.testing.assert_allclose(a[0][:, gain_col], b[0][:, gain_col],
                               rtol=1e-6)
    mask = np.ones(a[0].shape[1], bool)
    mask[gain_col] = False
    np.testing.assert_array_equal(a[0][:, mask], b[0][:, mask])
    np.testing.assert_array_equal(a[1], b[1])


# ------------------------------------------------- top_k >= F bit-identity

@pytest.mark.parametrize("bagged", [False, True])
@pytest.mark.parametrize("cls", [VotingDataParallelTreeLearner,
                                 DeviceFeatureParallelTreeLearner])
def test_topk_ge_f_bit_identical_to_single_device(rng, cls, bagged):
    """top_k=64 >= F_pad: the election keeps every feature, so the voting
    learner must reproduce the single-device wave learner's split log and
    row->leaf map bit for bit (and feature-parallel always must)."""
    n = 1100
    X = rng.randn(n, 7)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(float)
    gh = _snapped_gh(rng, n)
    params = {"objective": "binary", "num_leaves": 15, "top_k": 64,
              "min_data_in_leaf": 5, "verbosity": -1}
    bag = (np.sort(np.random.RandomState(3).choice(n, 800, replace=False))
           .astype(np.int32) if bagged else None)
    base = _split_log(DeviceTreeLearner, X, y, params, gh, bag)
    _assert_same_log(base, _split_log(cls, X, y, params, gh, bag))


@pytest.mark.slow
def test_voting_quantized_driver_bit_identical(rng):
    """Quantized regime through the FULL driver: int32 slice reduction is
    order-exact, so with top_k >= F the voting booster matches the exact
    data-parallel booster's predictions bit for bit."""
    n = 1200
    X = rng.randn(n, 6)
    y = (X[:, 0] - 0.6 * X[:, 1] + rng.randn(n) * 0.3 > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "top_k": 64, "use_quantized_grad": True,
              "quant_train_renew_leaf": True}
    preds = []
    for cls in (DeviceDataParallelTreeLearner, VotingDataParallelTreeLearner):
        cfg = Config(params)
        ds = CoreDataset.from_matrix(X, label=y, config=cfg)
        bst = GBDT(cfg, ds, create_objective("binary", cfg))
        bst.tree_learner = cls(cfg, ds)
        for _ in range(4):
            if bst.train_one_iter():
                break
        bst.to_model()
        preds.append(np.asarray(bst.predict(X, raw_score=True)))
    np.testing.assert_array_equal(preds[0], preds[1])


# ------------------------------------------------------- comm-model gauges

@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_voting_ici_gauge_independent_of_f(rng):
    """THE voting claim (perfmodel.voting_ici_bytes_per_wave): per-wave
    ICI volume depends on top_k, never on F. max_bin=16 so both widths
    saturate the bin budget; F_pad >= 2*top_k at both widths so the
    election caps identically."""
    params = {"objective": "binary", "num_leaves": 7, "max_bin": 16,
              "top_k": 20, "verbosity": -1}
    gauges, data_gauges = [], []
    for f in (64, 256):
        X = rng.randn(600, f)
        y = (X[:, 0] > 0).astype(float)
        for sink, cls in ((gauges, VotingDataParallelTreeLearner),
                          (data_gauges, DeviceDataParallelTreeLearner)):
            learner = _learner(cls, X, y, params)
            global_timer.counters.pop("device_ici_bytes_per_wave", None)
            learner.finalize(learner.train_async(_snapped_gh(rng, 600)))
            sink.append(global_timer.counters["device_ici_bytes_per_wave"])
    assert gauges[0] == gauges[1] > 0, gauges
    # contrast: the full reduction DOES scale with F (4x the features)
    assert data_gauges[1] == 4 * data_gauges[0], data_gauges


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_voting_ici_at_most_quarter_of_data_at_f256(rng):
    """Acceptance: at F=256, top_k=20 the voting learner moves <= 1/4 of
    the data-parallel learner's per-wave ICI bytes."""
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 16,
              "top_k": 20, "verbosity": -1}
    n = 600
    X = rng.randn(n, 256)
    y = (X[:, 0] > 0).astype(float)
    gauges = {}
    for cls in (DeviceDataParallelTreeLearner, VotingDataParallelTreeLearner):
        learner = _learner(cls, X, y, params)
        global_timer.counters.pop("device_ici_bytes_per_wave", None)
        learner.finalize(learner.train_async(_snapped_gh(rng, n)))
        gauges[cls.__name__] = global_timer.counters[
            "device_ici_bytes_per_wave"]
    assert (gauges["VotingDataParallelTreeLearner"]
            <= gauges["DeviceDataParallelTreeLearner"] / 4), gauges


def test_feature_ici_gauge_independent_of_rows(rng):
    """Feature-parallel moves ONLY the [2K, D, REC] best-record gather:
    the gauge must not scale with N (and it is the cheapest of the three
    learners by orders of magnitude)."""
    params = {"objective": "binary", "num_leaves": 7, "max_bin": 16,
              "verbosity": -1}
    gauges = []
    for n in (600, 2400):
        X = rng.randn(n, 6)
        y = (X[:, 0] > 0).astype(float)
        learner = _learner(DeviceFeatureParallelTreeLearner, X, y, params)
        global_timer.counters.pop("feature_ici_bytes_per_wave", None)
        learner.finalize(learner.train_async(_snapped_gh(rng, n)))
        gauges.append(global_timer.counters["feature_ici_bytes_per_wave"])
    assert gauges[0] == gauges[1] > 0, gauges


def test_voting_overlap_gauge_published(rng):
    """The double-buffered dispatch hides the smaller-child slice psum
    behind the larger-child subtraction: half the wave's ICI bytes by
    construction, published as device_ici_overlap_pct."""
    n = 600
    X = rng.randn(n, 6)
    y = (X[:, 0] > 0).astype(float)
    learner = _learner(VotingDataParallelTreeLearner, X, y,
                       {"objective": "binary", "num_leaves": 7,
                        "verbosity": -1})
    global_timer.counters.pop("device_ici_overlap_pct", None)
    learner.finalize(learner.train_async(_snapped_gh(rng, n)))
    assert global_timer.counters["device_ici_overlap_pct"] == 50


# ------------------------------------------------- small-top_k quality pin

def _driver_scores(cls, X, y, params, objective, rounds=5):
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective(objective, cfg))
    bst.tree_learner = cls(cfg, ds)
    for _ in range(rounds):
        if bst.train_one_iter():
            break
    bst.to_model()
    return np.asarray(bst.predict(X, raw_score=True))


@pytest.mark.slow  # tier-1 budget triage: heavy full-training driver, runs in the slow tier
def test_voting_auc_within_1e3_of_exact(rng):
    n = 2000
    X = rng.randn(n, 40)
    y = (X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2]
         + rng.randn(n) * 0.3 > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "top_k": 5,
              "learning_rate": 0.1, "verbosity": -1}
    exact = _auc(y, _driver_scores(DeviceDataParallelTreeLearner,
                                   X, y, params, "binary"))
    voted = _auc(y, _driver_scores(VotingDataParallelTreeLearner,
                                   X, y, params, "binary"))
    assert exact > 0.75  # the comparison saw real learning
    assert abs(exact - voted) < 1e-3, (exact, voted)


@pytest.mark.slow
def test_voting_l2_within_tolerance_of_exact(rng):
    n = 2000
    X = rng.randn(n, 40)
    y = X[:, 0] - 0.5 * X[:, 1] + 0.25 * X[:, 2] + rng.randn(n) * 0.1
    params = {"objective": "regression", "num_leaves": 15, "top_k": 5,
              "learning_rate": 0.1, "verbosity": -1}
    l2 = []
    for cls in (DeviceDataParallelTreeLearner, VotingDataParallelTreeLearner):
        score = _driver_scores(cls, X, y, params, "regression")
        l2.append(float(np.mean((score - y) ** 2)))
    exact, voted = l2
    assert voted <= exact * 1.02, l2


# --------------------------------------------------- exact-check counting

@pytest.mark.slow
def test_exact_check_counts_disagreements(rng, monkeypatch):
    """LGBM_TPU_VOTING_EXACT_CHECK=1 runs the full reduction alongside
    the election: a deliberately starved top_k=2 at F=40 must record
    committed splits where the un-nominated global best won, while
    top_k >= F must record exactly zero."""
    monkeypatch.setenv("LGBM_TPU_VOTING_EXACT_CHECK", "1")
    n = 1500
    X = rng.randn(n, 40)
    y = (X[:, :8].sum(axis=1) + rng.randn(n) * 2.0 > 0).astype(float)
    gh = _snapped_gh(rng, n)
    miss = {}
    for top_k in (2, 64):
        learner = _learner(VotingDataParallelTreeLearner, X, y,
                           {"objective": "binary", "num_leaves": 31,
                            "min_data_in_leaf": 5, "top_k": top_k,
                            "verbosity": -1})
        assert learner._exact_check
        global_timer.counters.pop("voting_miss_total", None)
        learner.finalize(learner.train_async(gh))
        miss[top_k] = int(global_timer.counters["voting_miss_total"])
    assert miss[64] == 0, miss
    assert miss[2] > 0, miss


# ------------------------------------------ int16 packing satellite bugfix

def test_int16_packing_keyed_off_global_bag_count(rng, monkeypatch):
    """The satellite bugfix: with a bag that is int16-safe on EVERY
    shard-local view (each shard holds <= n/8 rows) but unsafe globally,
    the packing decision must see the psum'd global count — shards
    disagreeing on the reduction dtype deadlock or garble the wire. Also
    pins the quantized+bagged regime bit-identical to the single-device
    learner under the same skewed bag."""
    import lightgbm_tpu.parallel.learners as learners_mod
    from lightgbm_tpu.ops.quantize import int16_reduction_safe

    n = 9216  # 8 shards x 1152 rows
    X = rng.randn(n, 6)
    y = (X[:, 0] - 0.4 * X[:, 1] > 0).astype(float)
    bag = np.sort(np.random.RandomState(5).choice(
        n, 8200, replace=False)).astype(np.int32)
    params = {"objective": "binary", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5, "use_quantized_grad": True}
    bins = Config(params).num_grad_quant_bins
    # the skew the bug keyed on: every local view fits int16, the global
    # reduction does not
    assert (n // 8) * bins < 32000 <= len(bag) * bins

    seen = []

    def spy(count, b):
        seen.append((count, b))
        return int16_reduction_safe(count, b)

    monkeypatch.setattr(learners_mod, "int16_reduction_safe", spy)
    gh = _snapped_gh(rng, n)
    sharded = _split_log(DeviceDataParallelTreeLearner, X, y, params, gh, bag)
    assert seen and seen[0] == (len(bag), bins), seen  # GLOBAL, not local
    _assert_same_log(_split_log(DeviceTreeLearner, X, y, params, gh, bag),
                     sharded)


# ------------------------------------------------ elastic gang + vote_skew

QUANT_VOTING = {"objective": "binary", "num_leaves": 15, "verbosity": -1,
                "tree_learner": "voting", "top_k": 64, "device_type": "cpu",
                "use_quantized_grad": True, "quant_train_renew_leaf": False,
                "seed": 7}


def _force_device_growth(monkeypatch):
    """The engine factory only picks the device learners on accelerators;
    route it onto the fake-device mesh the way the TPU path would."""
    import lightgbm_tpu.parallel.learners as learners_mod

    monkeypatch.setattr(learners_mod, "device_growth_applies",
                        lambda *a, **k: True)


def _data(seed=7, n=1600, f=10):
    rng = np.random.RandomState(seed)
    X = rng.standard_normal((n, f)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] + rng.standard_normal(n) * 0.5 > 0)
    return X, y.astype(np.float64)


@pytest.mark.slow
def test_voting_gang_kill_surfaces_worker_lost(rng, monkeypatch):
    """A gang peer hung mid-train under the elastic runtime: the
    collective watchdog converts the block into a typed WorkerLostError
    with the last-good iteration — the voting learner rides the same
    PR 14 contract as the data-parallel learner."""
    import lightgbm_tpu as lgb

    _force_device_growth(monkeypatch)
    X, y = _data(n=800)
    # the device voting learner's first-iteration compile is ~9s on a CPU
    # host; a deadline inside that window fires the watchdog before the
    # hang and async-raises the bare (iteration-less) error. 30s clears
    # the compile with margin while keeping detection bounded
    elastic.install(timeout_s=30.0)
    faults.install("worker_hang@0:2")
    t0 = time.perf_counter()
    with pytest.raises(WorkerLostError) as ei:
        train(dict(QUANT_VOTING), lgb.Dataset(X, label=y), num_boost_round=6)
    assert ei.value.last_good_iteration == 2
    assert time.perf_counter() - t0 < 120.0


@pytest.mark.slow
def test_voting_shrink_resume_8_4_1_bit_identical(rng, tmp_path, monkeypatch):
    """Shrink-to-fit for a voting gang: a quantized run checkpointed on
    the 8-device mesh, resumed on 4, then on 1, re-shards the vote each
    leg (top_k >= F keeps the election exact, so the integer reduction
    stays mesh-independent) and must match the undisturbed 8-device model
    text byte for byte."""
    import jax

    import lightgbm_tpu as lgb
    from lightgbm_tpu.checkpoint import checkpoint_callback

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual mesh")
    _force_device_growth(monkeypatch)
    X, y = _data(seed=42)
    ck = str(tmp_path / "chain.txt")

    undisturbed = train(dict(QUANT_VOTING), lgb.Dataset(X, label=y),
                        num_boost_round=6)

    def leg(boost_to, devices, resume):
        if devices:
            monkeypatch.setenv("LGBM_TPU_FORCE_MESH_DEVICES", str(devices))
        else:
            monkeypatch.delenv("LGBM_TPU_FORCE_MESH_DEVICES", raising=False)
        bst = train(dict(QUANT_VOTING), lgb.Dataset(X, label=y),
                    num_boost_round=boost_to,
                    init_model=ck if resume else None,
                    callbacks=[checkpoint_callback(ck, period=2)])
        monkeypatch.delenv("LGBM_TPU_FORCE_MESH_DEVICES", raising=False)
        return bst

    leg(2, devices=0, resume=False)
    leg(4, devices=4, resume=True)
    chained = leg(6, devices=1, resume=True)
    assert (chained.model_to_string(num_iteration=-1)
            == undisturbed.model_to_string(num_iteration=-1))


def test_vote_skew_exact_check_raises_typed_error(rng, monkeypatch):
    """faults token vote_skew@R:K + exact check: a corrupted ballot must
    abort with VotingDivergenceError naming the injection — never train
    on silently."""
    monkeypatch.setenv("LGBM_TPU_VOTING_EXACT_CHECK", "1")
    faults.install("vote_skew@2:1")
    n = 1100
    X = rng.randn(n, 20)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(float)
    learner = _learner(VotingDataParallelTreeLearner, X, y,
                       {"objective": "binary", "num_leaves": 15,
                        "min_data_in_leaf": 5, "top_k": 3,
                        "verbosity": -1})
    with pytest.raises(VotingDivergenceError, match="vote_skew@2:1"):
        learner.finalize(learner.train_async(_snapped_gh(rng, n)))


def test_vote_skew_elastic_surfaces_worker_lost(rng, monkeypatch):
    """Without the exact check, under an elastic gang, the detecting
    worker parks in the interruptible watchdog spin and the deadline
    converts the injection into WorkerLostError — a typed error, not a
    hang."""
    monkeypatch.delenv("LGBM_TPU_VOTING_EXACT_CHECK", raising=False)
    n = 1100
    X = rng.randn(n, 20)
    y = (X[:, 0] - 0.5 * X[:, 1] > 0).astype(float)
    params = {"objective": "binary", "num_leaves": 15, "top_k": 3,
              "min_data_in_leaf": 5, "verbosity": -1}
    cfg = Config(params)
    ds = CoreDataset.from_matrix(X, label=y, config=cfg)
    bst = GBDT(cfg, ds, create_objective("binary", cfg))
    bst.tree_learner = VotingDataParallelTreeLearner(cfg, ds)
    elastic.install(timeout_s=1.0)
    faults.install("vote_skew@1:0")
    t0 = time.perf_counter()
    with pytest.raises(WorkerLostError):
        for _ in range(3):
            bst.train_one_iter()
    assert time.perf_counter() - t0 < 60.0
