#!/usr/bin/env python3
"""benchdiff: diff bench records / gate a PR on the bench ledger.

bench.py appends every capture to BENCH_LEDGER.jsonl (one fingerprinted
JSON record per line — see lightgbm_tpu/fingerprint.py and the schema
section of docs/OBSERVABILITY.md). This tool makes a regression visible
at PR time:

    python tools/benchdiff.py OLD.json NEW.json        # two record files
    python tools/benchdiff.py BENCH_LEDGER.jsonl       # newest vs previous
    python tools/benchdiff.py LEDGER --gate            # exit 1 on regression
    python tools/benchdiff.py LEDGER --gate --baseline BENCH_BASELINE_CPU.json
    python tools/benchdiff.py LEDGER --gate --deterministic-only   # CI mode

Per-metric DIRECTION and threshold live in the SPEC table: a 10% drop in
row-iters/s is a regression, a 10% drop in serve_p99_ms is an
improvement — symmetric gating (tools/teldiff.py's old behaviour) cannot
express that. Metrics are split into two classes:

  * deterministic — structure the code fully determines (auc on the fixed
    bench seed, est_carried_bytes_per_wave, predict_chunk_rows,
    device_hist_rows, attribution sanity). Gated everywhere, including CI
    runners whose absolute speed means nothing.
  * perf — wall-clock-derived (throughputs, latencies, compile counts).
    Gated by default, skipped under --deterministic-only (CI compares a
    GitHub runner against a committed baseline from a different machine:
    timing comparisons there are noise, not signal).

Records are only comparable when rows/iters/platform and the ledger
schema version match; non-comparable pairs skip the affected metrics
with a note (or fail under --strict). stdlib only — runs anywhere.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, NamedTuple, Optional, Tuple


class Metric(NamedTuple):
    direction: str       # "higher" | "lower" | "exact"
    rel_tol: float       # allowed regression as a fraction (0.10 = 10%)
    cls: str             # "deterministic" | "perf"
    abs_tol: float = 0.0  # absolute slack, for near-zero metrics (auc)


SPEC: Dict[str, Metric] = {
    # --- perf: wall-clock-derived, generous thresholds over host noise ----
    "value": Metric("higher", 0.10, "perf"),
    "quantized_row_iters_per_sec": Metric("higher", 0.15, "perf"),
    "predict_rows_per_sec": Metric("higher", 0.15, "perf"),
    "serve_rows_per_sec": Metric("higher", 0.25, "perf"),
    "stream_sharded_rows_per_sec": Metric("higher", 0.25, "perf"),
    "serve_wire_binary_rows_per_sec": Metric("higher", 0.25, "perf"),
    "serve_cold_start_ms": Metric("lower", 1.00, "perf"),
    "serve_replica_scaling_efficiency": Metric("higher", 0.50, "perf"),
    "serve_p50_ms": Metric("lower", 0.50, "perf"),
    "serve_p99_ms": Metric("lower", 1.00, "perf"),
    "checkpoint_write_ms": Metric("lower", 1.00, "perf"),
    # compile counts vary with micro-batch bucket warming order, so they
    # gate as perf despite not being wall-clock
    "compile_count": Metric("lower", 0.25, "perf"),
    "hbm_high_water_bytes": Metric("lower", 0.10, "perf"),
    # scaling efficiency is rows/s against D x the single-device learner:
    # a throughput ratio, so it gates as perf (host noise on both sides)
    "scaling_efficiency_data": Metric("higher", 0.50, "perf"),
    "scaling_efficiency_voting": Metric("higher", 0.50, "perf"),
    "scaling_efficiency_feature": Metric("higher", 0.50, "perf"),
    # --- deterministic: the code fully determines these on the bench seed -
    "auc": Metric("higher", 0.0, "deterministic", abs_tol=0.02),
    "quantized_auc": Metric("higher", 0.0, "deterministic", abs_tol=0.02),
    "est_carried_bytes_per_wave": Metric("exact", 0.0, "deterministic"),
    "predict_chunk_rows": Metric("exact", 0.0, "deterministic"),
    "device_hist_rows": Metric("exact", 0.0, "deterministic"),
    # round-9 comm model: the analytic per-wave ICI volumes are pure
    # functions of (wave width, top_k, Bmax, shard count) on the fixed
    # bench shapes, and the overlap gauge is set by the dispatch
    # structure, not the clock
    "voting_ici_bytes_per_wave": Metric("exact", 0.0, "deterministic"),
    "feature_ici_bytes_per_wave": Metric("exact", 0.0, "deterministic"),
    "device_ici_overlap_pct": Metric("exact", 0.0, "deterministic"),
    # exact-check disagreements on the bench seed: deterministic, but a
    # couple of election flips from unrelated numeric churn are tolerated
    "voting_miss_total": Metric("lower", 0.0, "deterministic", abs_tol=2.0),
    # pod streaming: the prefetch/cold split is set by the dispatch
    # structure (not the clock) but small runs leave few blocks to
    # overlap, and the rank-merge wall is a host-side numpy fold bounded
    # by a generous absolute allowance — both gate everywhere with
    # wide deterministic tolerances rather than as cross-host perf noise
    "stream_h2d_overlap_pct": Metric("higher", 0.0, "deterministic",
                                     abs_tol=25.0),
    "stream_sketch_merge_ms": Metric("lower", 0.0, "deterministic",
                                     abs_tol=250.0),
}

# fields that must MATCH for two records to be comparable at all
COMPARABILITY_KEYS = ("rows", "iters", "platform")

# attribution sanity gate: ISSUE acceptance — fractions sum to 1 +/- this
FRACTIONS_TOL = 0.05


class Finding(NamedTuple):
    metric: str
    kind: str           # "regression" | "improvement" | "note" | "skip"
    detail: str


def load_records(path: str) -> List[Dict[str, Any]]:
    """A .jsonl ledger (all lines) or a single-record .json file."""
    recs: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        text = fh.read().strip()
    if not text:
        return recs
    if path.endswith(".jsonl"):
        for i, line in enumerate(text.splitlines()):
            line = line.strip()
            if not line:
                continue
            try:
                recs.append(json.loads(line))
            except ValueError as e:
                raise SystemExit(f"{path}:{i + 1}: bad ledger line: {e}")
        return recs
    obj = json.loads(text)
    if isinstance(obj, list):
        recs.extend(obj)
    else:
        recs.append(obj)
    return recs


def _schema_of(rec: Dict[str, Any]) -> int:
    v = rec.get("schema_version",
                (rec.get("fingerprint") or {}).get("schema_version", 0))
    try:
        return int(v)
    except (TypeError, ValueError):
        return 0


def comparable(old: Dict[str, Any], new: Dict[str, Any]
               ) -> Tuple[bool, List[str]]:
    problems: List[str] = []
    so, sn = _schema_of(old), _schema_of(new)
    if so != sn:
        problems.append(f"schema_version {so} vs {sn}")
    for key in COMPARABILITY_KEYS:
        if old.get(key) != new.get(key):
            problems.append(f"{key} {old.get(key)!r} vs {new.get(key)!r}")
    return not problems, problems


def diff(old: Dict[str, Any], new: Dict[str, Any],
         deterministic_only: bool = False,
         threshold_scale: float = 1.0) -> List[Finding]:
    """Compare two records metric by metric under SPEC. threshold_scale
    multiplies every relative tolerance (--threshold 2 doubles the slack
    on a known-noisy host)."""
    findings: List[Finding] = []
    ok, problems = comparable(old, new)
    if not ok:
        findings.append(Finding("comparability", "skip",
                                "records not comparable: "
                                + "; ".join(problems)))
        return findings
    for name, spec in SPEC.items():
        if deterministic_only and spec.cls != "deterministic":
            continue
        if name not in old or name not in new:
            continue
        try:
            ov, nv = float(old[name]), float(new[name])
        except (TypeError, ValueError):
            continue
        findings.extend(_judge(name, spec, ov, nv, threshold_scale))
    findings.extend(_attribution_checks(new))
    return findings


def _judge(name: str, spec: Metric, ov: float, nv: float,
           scale: float) -> List[Finding]:
    rel = spec.rel_tol * scale
    if spec.direction == "exact":
        if nv != ov:
            return [Finding(name, "regression",
                            f"{ov:g} -> {nv:g} (exact-match metric changed)")]
        return []
    # signed change in the GOOD direction (positive = better)
    good = (nv - ov) if spec.direction == "higher" else (ov - nv)
    base = abs(ov) if ov else 1.0
    slack = base * rel + spec.abs_tol
    pct = 100.0 * (nv - ov) / base if base else 0.0
    detail = f"{ov:g} -> {nv:g} ({pct:+.1f}%, {spec.direction}-is-better)"
    if good < -slack:
        return [Finding(name, "regression", detail)]
    if good > slack:
        return [Finding(name, "improvement", detail)]
    return [Finding(name, "note", detail + " within threshold")]


def _attribution_checks(new: Dict[str, Any]) -> List[Finding]:
    """Structural sanity of the new record's attribution block (present
    since schema v1): stage fractions must sum to ~1."""
    attr = new.get("attribution")
    if not isinstance(attr, dict):
        return []
    fsum = attr.get("fractions_sum")
    if fsum is None:
        return [Finding("attribution", "regression",
                        "attribution block has no fractions_sum")]
    if abs(float(fsum) - 1.0) > FRACTIONS_TOL:
        return [Finding("attribution", "regression",
                        f"stage fractions sum to {fsum} "
                        f"(expected 1 +/- {FRACTIONS_TOL})")]
    return [Finding("attribution", "note",
                    f"fractions_sum {fsum} within 1 +/- {FRACTIONS_TOL}")]


def render(old: Dict[str, Any], new: Dict[str, Any],
           findings: List[Finding]) -> str:
    lines = []
    ofp = old.get("fingerprint") or {}
    nfp = new.get("fingerprint") or {}
    lines.append(f"benchdiff: {ofp.get('git_sha', '?')} -> "
                 f"{nfp.get('git_sha', '?')}  "
                 f"(platform {new.get('platform', '?')}, "
                 f"rows {new.get('rows', '?')}, iters {new.get('iters', '?')})")
    order = {"regression": 0, "improvement": 1, "skip": 2, "note": 3}
    for f in sorted(findings, key=lambda f: (order.get(f.kind, 9), f.metric)):
        tag = {"regression": "REGRESSION", "improvement": "improved",
               "skip": "skipped", "note": "ok"}.get(f.kind, f.kind)
        lines.append(f"  [{tag:>10}] {f.metric}: {f.detail}")
    n_reg = sum(1 for f in findings if f.kind == "regression")
    lines.append(f"benchdiff: {n_reg} regression(s), "
                 f"{sum(1 for f in findings if f.kind == 'improvement')} "
                 f"improvement(s)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Diff bench records / gate on the bench ledger")
    ap.add_argument("paths", nargs="+",
                    help="LEDGER.jsonl, or two record files OLD NEW")
    ap.add_argument("--gate", action="store_true",
                    help="exit 1 when any regression is found")
    ap.add_argument("--baseline",
                    help="record file to diff the ledger head against "
                         "(default: the ledger's previous record)")
    ap.add_argument("--deterministic-only", action="store_true",
                    help="gate only code-determined metrics (CI mode: "
                         "skip wall-clock metrics across hosts)")
    ap.add_argument("--threshold", type=float, default=1.0,
                    help="scale every relative tolerance (2 = double slack)")
    ap.add_argument("--strict", action="store_true",
                    help="treat non-comparable records as a gate failure")
    args = ap.parse_args(argv)

    if len(args.paths) == 2 and args.baseline is None:
        old = load_records(args.paths[0])[-1]
        new = load_records(args.paths[1])[-1]
    elif len(args.paths) == 1:
        ledger = load_records(args.paths[0])
        if not ledger:
            print(f"benchdiff: {args.paths[0]} is empty", file=sys.stderr)
            return 1 if args.gate else 0
        new = ledger[-1]
        if args.baseline:
            old = load_records(args.baseline)[-1]
        elif len(ledger) >= 2:
            old = ledger[-2]
        else:
            print("benchdiff: single record and no --baseline; "
                  "nothing to diff")
            return 0
    else:
        ap.error("pass LEDGER.jsonl, or OLD NEW record files")
        return 2  # unreachable; argparse exits

    if "error" in new:
        print(f"benchdiff: newest record is a failure record: "
              f"{new['error']}", file=sys.stderr)
        return 1 if args.gate else 0

    findings = diff(old, new, deterministic_only=args.deterministic_only,
                    threshold_scale=args.threshold)
    print(render(old, new, findings))
    regressed = any(f.kind == "regression" for f in findings)
    skipped = any(f.kind == "skip" for f in findings)
    if args.gate and (regressed or (args.strict and skipped)):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
