#!/usr/bin/env python
"""Chaos smoke: kill a worker mid-train and prove elastic recovery.

Runs the same 4-process gang twice through ``lightgbm_tpu.launch``:

1. undisturbed -- the reference model;
2. with ``LGBM_TPU_FAULT=worker_kill@1:3`` under ``--elastic`` -- rank 1
   hard-exits at iteration 3, the supervisor reaps the gang, dumps a
   ``flight-gang_worker_lost.json`` postmortem, and relaunches from the
   latest crash-consistent snapshot.

The smoke passes when the recovered model is BYTE-identical to the
undisturbed one and the flight dump names the lost rank. The last stdout
line is a JSON report (CI uploads it as an artifact):

    {"byte_equal": true, "flight_rank": 1, ...}

Usage: python tools/chaos_smoke.py <workdir>
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NPROC = 4
DEVICES_PER_PROC = 2
KILL_TOKEN = "worker_kill@1:3"


def _write_dataset(path: str) -> None:
    import numpy as np

    rng = np.random.RandomState(3)
    X = rng.randn(600, 4)
    y = (X[:, 0] - X[:, 1] + 0.2 * rng.randn(600) > 0).astype(np.float64)
    np.savetxt(path, np.column_stack([y, X]), delimiter="\t", fmt="%.8g")


def _gang(train_path: str, model_path: str, *, elastic: bool,
          env_extra: dict) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO
    env.pop("XLA_FLAGS", None)  # worker_env re-derives the device count
    env.update(env_extra)
    cmd = [sys.executable, "-m", "lightgbm_tpu.launch",
           "-n", str(NPROC), "--devices-per-proc", str(DEVICES_PER_PROC)]
    if elastic:
        cmd += ["--elastic", "--max-restarts", "2"]
    cmd += ["--",
            f"data={train_path}", "objective=binary", "num_trees=6",
            "num_leaves=7", "tree_learner=data", "min_data_in_leaf=10",
            "snapshot_freq=1", f"output_model={model_path}",
            "device_type=cpu", "verbosity=-1"]
    return subprocess.run(cmd, env=env, cwd=_REPO, capture_output=True,
                          text=True, timeout=540)


def main(argv) -> int:
    if len(argv) < 2:
        print("usage: chaos_smoke.py <workdir>", file=sys.stderr)
        return 2
    workdir = os.path.abspath(argv[1])
    os.makedirs(workdir, exist_ok=True)
    flight_dir = os.path.join(workdir, "flight")
    train_path = os.path.join(workdir, "chaos.train")
    base_model = os.path.join(workdir, "base_model.txt")
    chaos_model = os.path.join(workdir, "chaos_model.txt")
    _write_dataset(train_path)

    report = {"nproc": NPROC, "fault": KILL_TOKEN}
    t0 = time.monotonic()
    base = _gang(train_path, base_model, elastic=False, env_extra={})
    report["base_s"] = round(time.monotonic() - t0, 2)
    if base.returncode != 0:
        report["error"] = ("undisturbed gang rc=%d\n%s" % (
            base.returncode, (base.stdout + base.stderr)[-2000:]))
        print(json.dumps(report))
        return 1

    t0 = time.monotonic()
    chaos = _gang(train_path, chaos_model, elastic=True, env_extra={
        "LGBM_TPU_FAULT": KILL_TOKEN,
        "LGBM_TPU_FLIGHT_DIR": flight_dir,
    })
    report["chaos_s"] = round(time.monotonic() - t0, 2)
    if chaos.returncode != 0:
        report["error"] = ("chaos gang rc=%d\n%s" % (
            chaos.returncode, (chaos.stdout + chaos.stderr)[-2000:]))
        print(json.dumps(report))
        return 1

    with open(base_model, "rb") as f:
        base_bytes = f.read()
    with open(chaos_model, "rb") as f:
        chaos_bytes = f.read()
    report["byte_equal"] = base_bytes == chaos_bytes

    # the supervisor's postmortem must name the lost rank
    dumps = sorted(glob.glob(
        os.path.join(flight_dir, "flight-gang_worker_lost*.json")))
    if dumps:
        with open(dumps[-1]) as f:
            payload = json.load(f)
        extra = payload.get("extra") or {}
        report["flight_rank"] = extra.get("rank")
        report["flight_attempt"] = extra.get("attempt")
        report["flight_path"] = dumps[-1]
    else:
        report["flight_rank"] = None
        report["error"] = f"no gang_worker_lost flight dump in {flight_dir}"

    ok = report.get("byte_equal") is True and report.get("flight_rank") == 1
    print(json.dumps(report))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv))
