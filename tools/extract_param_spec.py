"""Extract the LightGBM parameter spec (names, types, defaults, aliases,
checks, no-save markers) from the reference's config.h doc-comments into a
Python literal.

This mirrors what the reference's own .ci/parameter-generator.py does for
config_auto.cpp: the doc-comments in include/LightGBM/config.h are the single
source of truth for the parameter API surface. We emit
lightgbm_tpu/_param_spec.py.
"""
import re

src = open('/root/reference/include/LightGBM/config.h').read()
lines = src.split('\n')

params = []
comments = []
in_params = False
depth = 0
for line in lines:
    s = line.strip()
    if s.startswith('#pragma region'):
        depth += 1
        if 'Parameters' in s and depth == 1:
            in_params = True
        continue
    if s.startswith('#pragma endregion'):
        depth -= 1
        if depth == 0:
            in_params = False
        continue
    if not in_params:
        continue
    if s.startswith('//'):
        comments.append(s[2:].strip())
        continue
    m = re.match(
        r'(std::string|std::vector<std::string>|std::vector<double>|std::vector<int>|'
        r'std::vector<int8_t>|std::vector<int32_t>|double|float|int|int64_t|size_t|bool|data_size_t)\s+(\w+)\s*(?:=\s*(.*?))?;\s*$',
        s)
    if m:
        ctype, name, default = m.groups()
        meta = {'name': name, 'ctype': ctype, 'default': default,
                'aliases': [], 'checks': [], 'no_save': False}
        for c in comments:
            if c.startswith('alias'):
                meta['aliases'] = [a.strip() for a in c.split('=', 1)[1].split(',')]
            elif c.startswith('check'):
                meta['checks'].append(c.split('=', 1)[1].strip())
            elif c == '[no-save]':
                meta['no_save'] = True
        params.append(meta)
        comments = []
    elif s:
        comments = []

PYTYPE = {'std::string': 'str', 'std::vector<std::string>': 'list_str',
          'std::vector<double>': 'list_float', 'std::vector<int>': 'list_int',
          'std::vector<int8_t>': 'list_int',
          'std::vector<int32_t>': 'list_int', 'double': 'float', 'float': 'float',
          'int': 'int', 'int64_t': 'int', 'size_t': 'int', 'bool': 'bool',
          'data_size_t': 'int'}
SYMBOLIC = {'kDefaultNumLeaves': 31, 'size_t(10) * 1024 * 1024 * 1024': 10737418240}


def pydefault(p):
    d = p['default']
    t = PYTYPE[p['ctype']]
    if d is None:
        return '' if t == 'str' else ([] if t.startswith('list') else (False if t == 'bool' else 0))
    if d in SYMBOLIC:
        return SYMBOLIC[d]
    if t == 'str':
        return d.strip('"')
    if t.startswith('list'):
        return []
    if t == 'bool':
        return d == 'true'
    if t == 'int':
        return int(float(d.rstrip('f')))
    if t == 'float':
        return float(d.rstrip('f'))
    return d


out = ['# Parameter spec extracted from the reference config doc-comments',
       '# (include/LightGBM/config.h) by tools/extract_param_spec.py.',
       '# Fields: (name, pytype, default, aliases, checks, no_save)',
       'PARAM_SPEC = [']
for p in params:
    out.append('    (%r, %r, %r, %r, %r, %r),' % (
        p['name'], PYTYPE[p['ctype']], pydefault(p), p['aliases'], p['checks'], p['no_save']))
out.append(']')
open('/root/repo/lightgbm_tpu/_param_spec.py', 'w').write('\n'.join(out) + '\n')
print('extracted', len(params), 'params;', sum(p['no_save'] for p in params), 'no-save')
