"""Render a flight-recorder dump (lightgbm_tpu/tracing.py) for humans.

    python tools/flightview.py DUMP.json [--trace OUT.json] [--events N]
    python tools/flightview.py --url http://127.0.0.1:8080 [--out DUMP.json]

Prints the postmortem header (reason, drop accounting), the breaker
transition history captured in the ring, the per-stage latency quantile
table, top counters, and the tail of the event ring. `--trace` exports
the dump's span records as a Chrome trace (chrome://tracing /
ui.perfetto.dev) — stages laid out contiguously from each span's start,
one track per span family. `--url` fetches a live dump from a running
server's /debug/flight endpoint.

Stdlib only — usable on a box with nothing but the dump file.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any, Dict, List

FORMAT = "lgbm-flight"


def load_dump(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        dump = json.load(fh)
    return _validate(dump, path)


def fetch_dump(url: str) -> Dict[str, Any]:
    target = url.rstrip("/") + "/debug/flight"
    with urllib.request.urlopen(target, timeout=30) as resp:
        dump = json.loads(resp.read())
    return _validate(dump, target)


def _validate(dump: Any, origin: str) -> Dict[str, Any]:
    if not isinstance(dump, dict) or dump.get("format") != FORMAT:
        raise SystemExit(
            f"flightview: {origin} is not a {FORMAT} dump "
            f"(format={dump.get('format') if isinstance(dump, dict) else '?'})")
    return dump


def render(dump: Dict[str, Any], events_tail: int = 20) -> str:
    lines: List[str] = []
    lines.append(f"flight dump · reason={dump.get('reason')} "
                 f"pid={dump.get('pid')} "
                 f"telemetry={'on' if dump.get('telemetry_enabled') else 'off'}")
    lines.append(f"  ring: {len(dump.get('events', []))} records held, "
                 f"{dump.get('total_records', 0)} total, "
                 f"{dump.get('dropped', 0)} dropped "
                 f"(capacity {dump.get('capacity', '?')})")

    transitions = [e for e in dump.get("events", [])
                   if e.get("kind") == "breaker_transition"]
    if transitions:
        lines.append("breaker transitions (in ring):")
        for t in transitions:
            lines.append(f"  seq={t['seq']:>6}  {t.get('old')} -> "
                         f"{t.get('new')}  ({t.get('reason')})")

    summary = dump.get("stage_summary", {})
    if summary:
        lines.append("stage latency quantiles:")
        lines.append(f"  {'span':<16} {'stage':<12} {'count':>8} "
                     f"{'p50 ms':>10} {'p99 ms':>10} {'total ms':>11}")
        for span_name in sorted(summary):
            for stage, q in summary[span_name].items():
                lines.append(
                    f"  {span_name:<16} {stage:<12} {q['count']:>8} "
                    f"{q['p50_ms']:>10.3f} {q['p99_ms']:>10.3f} "
                    f"{q['total_ms']:>11.1f}")

    counters = dump.get("counters", {})
    if counters:
        lines.append("counters:")
        for key in sorted(counters):
            lines.append(f"  {key}: {counters[key]}")

    events = dump.get("events", [])
    if events:
        tail = events[-events_tail:]
        lines.append(f"last {len(tail)} records:")
        for ev in tail:
            fields = {k: v for k, v in ev.items()
                      if k not in ("seq", "t", "kind")}
            lines.append(f"  seq={ev['seq']:>6} t={ev['t']:>14.6f} "
                         f"{ev['kind']:<20} {json.dumps(fields)[:120]}")
    return "\n".join(lines)


def build_trace(dump: Dict[str, Any]) -> Dict[str, Any]:
    """Chrome-trace JSON from the dump's span records: B/E pairs per
    stage, contiguous from each span's start; non-span records become
    instant events on their own track."""
    events = dump.get("events", [])
    spans = [e for e in events if e.get("kind") == "span"]
    others = [e for e in events if e.get("kind") != "span"]
    t_base = min([s.get("t0", s["t"]) for s in spans]
                 + [e["t"] for e in others], default=0.0)
    tids = {}

    def tid(name: str) -> int:
        if name not in tids:
            tids[name] = len(tids) + 1
        return tids[name]

    trace: List[Dict[str, Any]] = []
    for s in spans:
        name = s.get("name", "span")
        t = float(s.get("t0", s["t"]))
        for stage, dur_ms in (s.get("stages_ms") or {}).items():
            dur = float(dur_ms) / 1000.0
            trace.append({"name": f"{name}.{stage}", "ph": "B", "pid": 1,
                          "tid": tid(name),
                          "ts": round((t - t_base) * 1e6, 3),
                          "args": {"trace_id": s.get("trace_id"),
                                   "span_id": s.get("span_id")}})
            trace.append({"name": f"{name}.{stage}", "ph": "E", "pid": 1,
                          "tid": tid(name),
                          "ts": round((t + dur - t_base) * 1e6, 3)})
            t += dur
    for e in others:
        trace.append({"name": e["kind"], "ph": "i", "pid": 1,
                      "tid": tid("events"), "s": "g",
                      "ts": round((e["t"] - t_base) * 1e6, 3),
                      "args": {k: v for k, v in e.items()
                               if k not in ("t", "kind")}})
    trace.sort(key=lambda ev: (ev["ts"], 0 if ev["ph"] == "E" else 1))
    meta = [{"name": "thread_name", "ph": "M", "pid": 1, "tid": t,
             "args": {"name": label}} for label, t in sorted(tids.items())]
    return {"traceEvents": meta + trace,
            "displayTimeUnit": "ms",
            "otherData": {"reason": dump.get("reason"),
                          "source": "flightview"}}


def main(argv: List[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="flightview", description=__doc__.splitlines()[0])
    ap.add_argument("dump", nargs="?", help="path to a flight-*.json dump")
    ap.add_argument("--url", help="fetch a live dump from this server's "
                                  "/debug/flight instead of a file")
    ap.add_argument("--out", help="with --url: also save the fetched dump")
    ap.add_argument("--trace", help="write a Chrome trace JSON here")
    ap.add_argument("--events", type=int, default=20,
                    help="event-ring tail length to print (default 20)")
    args = ap.parse_args(argv)
    if bool(args.dump) == bool(args.url):
        ap.error("pass exactly one of DUMP.json or --url")
    dump = fetch_dump(args.url) if args.url else load_dump(args.dump)
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            json.dump(dump, fh, indent=1, sort_keys=True)
        print(f"flightview: saved dump -> {args.out}")
    print(render(dump, events_tail=args.events))
    if args.trace:
        with open(args.trace, "w", encoding="utf-8") as fh:
            json.dump(build_trace(dump), fh)
        print(f"flightview: wrote Chrome trace -> {args.trace}")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
