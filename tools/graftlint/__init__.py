"""graftlint: AST-based JAX/Pallas invariant checker for the TPU hot path.

XLA enforces none of the invariants this codebase's correctness and
performance rest on: a host sync inside a jitted tree-growing loop
compiles fine and silently serializes every wave; a bare `jnp.asarray`
picks its dtype from ambient x64 state; a Pallas block shape off the
(8, 128) Mosaic tile lowers on CPU interpret mode and explodes on real
hardware; a config parameter nobody reads trains a silently different
model than the reference (the `path_smooth` defect class, fixed by hand
in PR 1). graftlint checks all of these mechanically on every commit.

Rules (see docs/LINTING.md for rationale and examples):

  R1 jit-host-sync        host syncs / numpy escapes in jit-reachable code
  R2 implicit-dtype       array constructors without an explicit dtype
  R3 pallas-tile-shape    literal BlockSpec dims off the (8, 128) tile
     pallas-prefetch-arity index_map arity vs grid + scalar-prefetch count
     pallas-host-op        host-only ops inside Pallas kernel bodies
  R4 param-unread         spec parameters accepted but never read
  R5 untimed-hot-func     >50-line hot-path functions without timer scopes
  S1 bad-suppression      malformed / reason-less suppression comments

Suppression syntax (reason REQUIRED; an empty reason is itself an S1):

    x = jnp.asarray(v)  # graftlint: disable=implicit-dtype -- host literal

Run as `python -m tools.graftlint lightgbm_tpu`. Pure stdlib — importing
this package must never import jax (CI lints before deps install).
"""
from .core import LintResult, Violation, run_lint  # noqa: F401
from .rules import RULES, rule_codes  # noqa: F401

__all__ = ["run_lint", "LintResult", "Violation", "RULES", "rule_codes"]
