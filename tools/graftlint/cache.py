"""Content-hash incremental cache for graftlint.

A lint of the full package parses every file regardless (the whole-program
rules need the complete call graph), so what the cache actually saves is
RULE EXECUTION:

* file-local rules rerun only on files whose content digest — or the
  digest of anything in their transitive in-package import closure —
  changed since the last run (the call graph's `import_deps` is what makes
  this cross-file-aware: touching `treelearner/device.py` invalidates
  `parallel/learners.py`, which imports it);
* whole-program rules (call-graph passes) rerun whenever ANY file changed,
  and are served from cache only on a fully-unchanged tree.

Every entry is keyed on a digest of the linter's own source tree
(`rules_digest`) plus a config key: the CANONICAL active rule set (after
R-code family expansion — `--select R1` and `--select jit-sync,jit-sync-xmod`
hash identically), the CLI's output format, and a digest of the linted
root's `perfmodel.py` (the R14 VMEM capacity/bound tables live there, so
editing a budget must invalidate cached Pallas findings even though the
file is outside the linter's own tree). Raw select/ignore tokens are NOT
part of the key any more — keying on unexpanded aliases let two spellings
of the same rule set miss each other's entries.

Cache location: `.graftlint_cache/<sha16-of-root>.json` under the working
directory (one file per linted root). Writes are atomic (tmp + rename);
a corrupt or unreadable cache degrades to a full run, never to an error.
The library-level `run_lint` does NOT cache by default; the CLI opts in
(disable with `--no-cache`).
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile
from dataclasses import asdict
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Package, Violation

_VERSION = 2


def _sha(data: str) -> str:
    return hashlib.sha256(data.encode("utf-8", "surrogateescape")).hexdigest()


def file_digest(source: str) -> str:
    return _sha(source)[:32]


def rules_digest() -> str:
    """Digest of the graftlint source tree itself: any edit to a rule, the
    call graph, or this module invalidates every cache entry."""
    tree = Path(__file__).parent
    h = hashlib.sha256()
    for path in sorted(tree.rglob("*.py")):
        if "__pycache__" in path.parts:
            continue
        h.update(path.relative_to(tree).as_posix().encode())
        h.update(b"\0")
        h.update(path.read_bytes())
        h.update(b"\0")
    return h.hexdigest()[:32]


def config_key(root: Path, active: Sequence[str], extra: str = "") -> list:
    """The run-configuration component of the cache key.

    `active` is the canonical post-expansion rule-name set actually run
    (run_lint computes it), `extra` carries CLI-level knobs that shape the
    recorded findings or their rendering (currently the output format),
    and the trailing element digests `<root>/perfmodel.py` when present —
    rule configuration sourced from the linted tree rather than the
    linter's own tree."""
    perf_digest = ""
    try:
        perf = Path(root) / "perfmodel.py"
        if perf.is_file():
            perf_digest = file_digest(
                perf.read_text(encoding="utf-8", errors="surrogateescape"))
    except OSError:
        pass
    return [sorted(active), extra, perf_digest]


class CacheStore:
    """One linted root's cache file, plus the plan/save protocol run_lint
    drives: `plan()` splits the package into served-from-cache and must-
    rerun sets, `save()` records this run's raw (pre-suppression) findings
    for the next one."""

    def __init__(self, root: Path, cache_dir: Optional[Path] = None) -> None:
        self.root = Path(root)
        base = Path(cache_dir) if cache_dir is not None \
            else Path.cwd() / ".graftlint_cache"
        key = _sha(str(self.root.resolve()))[:16]
        self.path = base / ("%s.json" % key)
        self._rules_digest = rules_digest()

    # -- load / validate ---------------------------------------------------
    def _load(self, config: list) -> Optional[dict]:
        try:
            data = json.loads(self.path.read_text())
        except (OSError, ValueError):
            return None
        if not isinstance(data, dict) or data.get("version") != _VERSION:
            return None
        if data.get("rules_digest") != self._rules_digest:
            return None
        if data.get("config") != config:
            return None
        return data

    def plan(self, pkg: Package,
             active: Sequence[str] = (),
             extra: str = "",
             ) -> Tuple[Dict[str, List[Violation]], Set[str],
                        Optional[List[Violation]]]:
        """Returns (cached_local_findings_by_relpath, invalid_relpaths,
        cached_whole_program_findings_or_None). `active` is the canonical
        rule-name set being run; `extra` is the CLI's format component."""
        digests = {ctx.relpath: file_digest(ctx.source) for ctx in pkg.files}
        data = self._load(config_key(pkg.root, active, extra))
        if data is None:
            return {}, set(digests), None
        entries = data.get("files", {})
        cached: Dict[str, List[Violation]] = {}
        invalid: Set[str] = set()
        for rel, digest in digests.items():
            ent = entries.get(rel)
            ok = (isinstance(ent, dict) and ent.get("digest") == digest
                  and all(digests.get(dep) == dep_digest
                          for dep, dep_digest in ent.get("deps", {}).items()))
            if not ok:
                invalid.add(rel)
                continue
            cached[rel] = [Violation(**f) for f in ent.get("findings", [])]
        # whole-program findings survive only a fully-unchanged tree: same
        # relpath set, every digest equal
        wp: Optional[List[Violation]] = None
        if not invalid and set(entries) == set(digests):
            wp_raw = data.get("whole_program")
            if isinstance(wp_raw, list):
                wp = [Violation(**f) for f in wp_raw]
        return cached, invalid, wp

    # -- save --------------------------------------------------------------
    def save(self, pkg: Package,
             local_by_file: Dict[str, List[Violation]],
             whole_program: List[Violation],
             active: Sequence[str] = (),
             extra: str = "") -> None:
        from .callgraph import import_deps

        digests = {ctx.relpath: file_digest(ctx.source) for ctx in pkg.files}
        deps = import_deps(pkg)
        data = {
            "version": _VERSION,
            "rules_digest": self._rules_digest,
            "config": config_key(pkg.root, active, extra),
            "files": {
                rel: {
                    "digest": digests[rel],
                    "deps": {d: digests[d] for d in sorted(deps.get(rel, ()))
                             if d in digests},
                    "findings": [asdict(v)
                                 for v in local_by_file.get(rel, [])],
                }
                for rel in digests
            },
            "whole_program": [asdict(v) for v in whole_program],
        }
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=str(self.path.parent),
                                       suffix=".tmp")
            with os.fdopen(fd, "w") as f:
                json.dump(data, f, separators=(",", ":"))
            os.replace(tmp, self.path)
        except OSError:
            pass  # a cache that can't be written is just a slow lint
