"""Whole-program symbol table + call graph for the interprocedural passes.

The v1 rules are module-local by design (R1's docstring used to say
"cross-module reachability is intentionally out of scope"). PRs 3-7 made
exactly the code shape that scoping cannot protect: donated buffers and
collectives flowing through treelearner/device.py, parallel/learners.py
and models/gbdt.py, with telemetry/health hooks called from the engine
loop. This module gives rules a package-wide view:

* one `Node` per function/method at ANY nesting depth (plus a pseudo-node
  per module for top-level statements), addressed as `module:Qual.path`;
* resolved call edges: plain names through local scope -> module scope ->
  `from .x import f` imports; `mod.func(...)` through module aliases;
  `self.method(...)` through the in-package class hierarchy (bases
  resolved transitively, cycles tolerated); `obj.method(...)` when `obj`
  can be typed from a `name = ClassName(...)` / factory-return assignment;
* `functools.partial` / `jax.jit(fn, ...)` / `shard_map(fn, ...)` call
  chains unwrapped, accumulating the donation positions, bound mesh axes
  and positional-argument offset the wrappers introduce — including
  factories that RETURN a wrapped callable (make_sharded_grow_fn) and are
  dispatched as `self._grow_fn(...)(args)`;
* bare function references passed as arguments (`while_loop(cond, body,
  ..)`, `json.dumps(default=_jsonable)`) become `ref` edges: the callee
  may run, so reachability-style passes must follow them;
* anything unresolvable degrades to a conservative may-call edge with
  `target=None` — passes treat it as an opaque callee, never as proof of
  absence.

Import cycles are a non-issue (modules are parsed independently; every
traversal carries a visited set) and recursion terminates the same way.
The graph is built once per lint run and cached on the Package object.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, Package, dotted_name, keyword_arg

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_name(relpath: str) -> str:
    """'treelearner/device.py' -> 'treelearner.device'; '__init__.py' -> ''."""
    rel = relpath
    if rel.startswith("lightgbm_tpu/"):
        rel = rel[len("lightgbm_tpu/"):]
    if rel.endswith(".py"):
        rel = rel[:-3]
    parts = [p for p in rel.split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass(frozen=True)
class CallableRef:
    """What a callable expression resolved to, after unwrapping wrappers.

    `target` is a node qual or None (may-call). `donate` holds donated
    positional indices of the UNDERLYING function, `offset` the number of
    positionals already bound by partial(), `axes` the mesh axis names a
    shard_map wrapper binds around the target.
    """

    target: Optional[str]
    donate: Tuple[int, ...] = ()
    axes: FrozenSet[str] = frozenset()
    offset: int = 0
    jit_wrapped: bool = False


@dataclass
class Edge:
    """One call (or callable reference) site."""

    src: str
    target: Optional[str]          # node qual, or None = may-call unknown
    call: Optional[ast.Call]       # the Call node (None for bare refs)
    kind: str                      # "call" | "ref" | "wrap"
    axes: FrozenSet[str] = frozenset()   # axes bound by wrappers at this site
    donate: Tuple[int, ...] = ()
    offset: int = 0


@dataclass
class Node:
    qual: str                      # "module:Class.method" / "module:<module>"
    module: str
    ctx: FileContext
    node: Optional[ast.AST]        # def node; None for the module pseudo-node
    cls: Optional[str] = None      # enclosing class name for methods
    lexical_parent: Optional[str] = None
    children: Dict[str, str] = field(default_factory=dict)  # name -> qual
    jitted: bool = False
    donate: Tuple[int, ...] = ()   # donated positions when called directly
    returns_callable: Optional[CallableRef] = None
    returns_classes: Set[str] = field(default_factory=set)  # "module:Class"
    edges: List[Edge] = field(default_factory=list)


class _ModuleEnv:
    """Per-module name environment: imports, top-level defs, classes."""

    def __init__(self) -> None:
        self.mod_aliases: Dict[str, str] = {}    # local name -> module name
        self.sym_imports: Dict[str, Tuple[str, str]] = {}  # name -> (mod, sym)
        self.top_defs: Dict[str, str] = {}       # name -> node qual
        self.classes: Dict[str, "_ClassInfo"] = {}
        self.assigns: Dict[str, ast.AST] = {}    # module-level name = expr


@dataclass
class _ClassInfo:
    qual: str                       # "module:Class"
    bases: List[ast.AST]
    methods: Dict[str, str]         # method name -> node qual


def _literal_ints(node: Optional[ast.AST]) -> Tuple[int, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return ()  # non-literal member: degrade to "unknown positions"
        return tuple(out)
    return ()


def _literal_strs(node: Optional[ast.AST]) -> Tuple[str, ...]:
    if node is None:
        return ()
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant) and isinstance(e.value, str))
    return ()


def _string_literals(node: ast.AST) -> FrozenSet[str]:
    return frozenset(sub.value for sub in ast.walk(node)
                     if isinstance(sub, ast.Constant)
                     and isinstance(sub.value, str))


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in list(a.posonlyargs) + list(a.args)]


def _jit_decorator_info(fn: ast.AST) -> Tuple[bool, Tuple[int, ...]]:
    """(is_jitted, donated positions) from the decorator list. Handles
    @jax.jit, @jit, @partial(jax.jit, donate_argnums=...), donate_argnames
    mapped onto positional indices."""
    jitted = False
    donate: Tuple[int, ...] = ()
    params = _param_names(fn)
    for dec in getattr(fn, "decorator_list", []):
        mentions_jit = any(
            (isinstance(n, ast.Attribute) and n.attr == "jit")
            or (isinstance(n, ast.Name) and n.id == "jit")
            for n in ast.walk(dec))
        if not mentions_jit:
            continue
        jitted = True
        if isinstance(dec, ast.Call):
            donate = donate + _literal_ints(keyword_arg(dec, "donate_argnums"))
            for nm in _literal_strs(keyword_arg(dec, "donate_argnames")):
                if nm in params:
                    donate = donate + (params.index(nm),)
    return jitted, tuple(sorted(set(donate)))


class CallGraph:
    """Package-wide call graph. Build with CallGraph.build(pkg)."""

    def __init__(self) -> None:
        self.nodes: Dict[str, Node] = {}
        self.envs: Dict[str, _ModuleEnv] = {}
        # "module:Class" -> _ClassInfo
        self.class_table: Dict[str, _ClassInfo] = {}
        # instance typing: var key -> set of "module:Class".  Keys are
        # "module:name" for plain names and "module:Class.attr" for
        # self-attribute assignments.
        self.instance_types: Dict[str, Set[str]] = {}
        # functions that become jit boundaries WITHOUT a jit decorator:
        # `g = jax.jit(f)` aliases, factories returning jit(...) products
        self.extra_jit_targets: Set[str] = set()
        self._callers: Optional[Dict[str, List[Edge]]] = None

    # ---------------------------------------------------------------- build

    @classmethod
    def build(cls, pkg: Package) -> "CallGraph":
        g = cls()
        root_pkg = pkg.root.name  # absolute self-imports strip this prefix
        for ctx in pkg.files:
            if ctx.tree is None:
                continue
            g._index_module(ctx)
        for ctx in pkg.files:
            if ctx.tree is None:
                continue
            g._scan_imports(ctx, root_pkg)
        g._type_instances()
        g._resolve_factory_returns()
        # module-level `g = jax.jit(f, ...)` aliases make f a jit boundary
        for mod, env in g.envs.items():
            for val in env.assigns.values():
                ref = g._unwrap_callable(val, mod, None, None, set())
                if ref is not None and ref.target and ref.jit_wrapped:
                    g.extra_jit_targets.update(ref.target.split("|"))
        for ctx in pkg.files:
            if ctx.tree is None:
                continue
            g._build_edges(ctx)
        return g

    def jit_seeds(self) -> Set[str]:
        """Every node that is a jit boundary: decorator-jitted defs plus
        functions wrapped by an explicit jax.jit(...) call anywhere."""
        seeds = {q for q, n in self.nodes.items() if n.jitted}
        seeds |= {q for q in self.extra_jit_targets if q in self.nodes}
        return seeds

    def _index_module(self, ctx: FileContext) -> None:
        mod = module_name(ctx.relpath)
        env = self.envs.setdefault(mod, _ModuleEnv())
        mod_node = Node(qual="%s:<module>" % mod, module=mod, ctx=ctx,
                        node=None)
        self.nodes[mod_node.qual] = mod_node

        def add_def(fn: ast.AST, prefix: str, cls_name: Optional[str],
                    parent: Optional[str]) -> str:
            qual = "%s:%s%s" % (mod, prefix, fn.name)
            jitted, donate = _jit_decorator_info(fn)
            node = Node(qual=qual, module=mod, ctx=ctx, node=fn,
                        cls=cls_name, lexical_parent=parent, jitted=jitted,
                        donate=donate)
            self.nodes[qual] = node
            if parent is not None:
                self.nodes[parent].children[fn.name] = qual
            for sub in ast.iter_child_nodes(fn):
                _walk_nested(sub, qual, prefix + fn.name + ".", cls_name)
            return qual

        def _walk_nested(node: ast.AST, parent: str, prefix: str,
                         cls_name: Optional[str]) -> None:
            if isinstance(node, _DEFS):
                add_def(node, prefix, cls_name, parent)
                return
            for sub in ast.iter_child_nodes(node):
                _walk_nested(sub, parent, prefix, cls_name)

        for stmt in ctx.tree.body:
            if isinstance(stmt, _DEFS):
                qual = add_def(stmt, "", None, None)
                env.top_defs[stmt.name] = qual
            elif isinstance(stmt, ast.ClassDef):
                info = _ClassInfo(qual="%s:%s" % (mod, stmt.name),
                                  bases=list(stmt.bases), methods={})
                env.classes[stmt.name] = info
                self.class_table[info.qual] = info
                for sub in stmt.body:
                    if isinstance(sub, _DEFS):
                        q = add_def(sub, stmt.name + ".", stmt.name, None)
                        info.methods[sub.name] = q
                    else:
                        for n in ast.walk(sub):
                            if isinstance(n, _DEFS):
                                break
            elif isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        env.assigns[tgt.id] = stmt.value

    def _scan_imports(self, ctx: FileContext, root_pkg: str) -> None:
        mod = module_name(ctx.relpath)
        env = self.envs[mod]
        # level=1 resolves to the CONTAINING package: for a plain module
        # that is mod minus its last segment, for a package __init__ it is
        # the package itself
        is_pkg = ctx.relpath.endswith("__init__.py")
        base0 = mod.split(".") if mod else []
        if not is_pkg and base0:
            base0 = base0[:-1]

        def canon(dotted: str) -> str:
            """Strip the package's own top name from absolute imports."""
            parts = dotted.split(".")
            if parts and parts[0] == root_pkg:
                parts = parts[1:]
            return ".".join(parts)

        for stmt in ast.walk(ctx.tree):
            if isinstance(stmt, ast.Import):
                for alias in stmt.names:
                    tgt = canon(alias.name)
                    if tgt in self.envs:
                        env.mod_aliases[alias.asname or
                                        alias.name.split(".")[0]] = tgt
            elif isinstance(stmt, ast.ImportFrom):
                if stmt.level:
                    up = stmt.level - 1
                    if up > len(base0):
                        continue
                    base = base0[:len(base0) - up] if up else list(base0)
                    src = ".".join(base + (stmt.module or "").split("."))
                    src = src.strip(".")
                else:
                    src = canon(stmt.module or "")
                for alias in stmt.names:
                    name = alias.asname or alias.name
                    as_mod = (src + "." + alias.name).strip(".") \
                        if src else alias.name
                    if as_mod in self.envs:
                        # `from . import telemetry` — a module import
                        env.mod_aliases[name] = as_mod
                    elif src in self.envs:
                        env.sym_imports[name] = (src, alias.name)

    # ------------------------------------------------------ symbol lookup

    def _module_symbol(self, mod: str, name: str,
                       seen: Optional[Set[str]] = None) -> Optional[str]:
        """Resolve `name` in module `mod` to a node/class qual, following
        re-export chains (from .x import f) with a cycle guard."""
        seen = set() if seen is None else seen
        key = "sym:%s:%s" % (mod, name)
        if key in seen:
            return None
        seen.add(key)
        env = self.envs.get(mod)
        if env is None:
            return None
        if name in env.top_defs:
            return env.top_defs[name]
        if name in env.classes:
            return env.classes[name].qual
        if name in env.sym_imports:
            src, sym = env.sym_imports[name]
            return self._module_symbol(src, sym, seen)
        if name in env.assigns:
            ref = self._unwrap_callable(env.assigns[name], mod, None, None,
                                        seen)
            if ref is not None and ref.target is not None:
                return ref.target
        return None

    def _class_info(self, qual: str) -> Optional[_ClassInfo]:
        return self.class_table.get(qual)

    def _resolve_base(self, base: ast.AST, mod: str) -> Optional[str]:
        name = dotted_name(base)
        if not name:
            return None
        parts = name.split(".")
        env = self.envs.get(mod)
        if env is None:
            return None
        if len(parts) == 1:
            sym = self._module_symbol(mod, parts[0])
            return sym if sym in self.class_table else None
        if parts[0] in env.mod_aliases and len(parts) == 2:
            sym = self._module_symbol(env.mod_aliases[parts[0]], parts[1])
            return sym if sym in self.class_table else None
        return None

    def mro(self, class_qual: str) -> List[str]:
        """Linearized in-package ancestry (order: class, then bases,
        breadth-first). Unresolvable bases simply drop out — callers must
        treat a miss as may-call, not absence."""
        out: List[str] = []
        frontier = [class_qual]
        seen: Set[str] = set()
        while frontier:
            q = frontier.pop(0)
            if q in seen:
                continue
            seen.add(q)
            info = self.class_table.get(q)
            if info is None:
                continue
            out.append(q)
            mod = q.split(":", 1)[0]
            for b in info.bases:
                rb = self._resolve_base(b, mod)
                if rb is not None:
                    frontier.append(rb)
        return out

    def method_on(self, class_qual: str, name: str) -> Optional[str]:
        for q in self.mro(class_qual):
            info = self.class_table.get(q)
            if info and name in info.methods:
                return info.methods[name]
        return None

    # --------------------------------------------------- instance typing

    def _type_instances(self) -> None:
        """`x = ClassName(...)` / `self.attr = factory(...)` assignments
        give `x.method()` / `self.attr.method()` a resolvable receiver."""
        for qual, node in list(self.nodes.items()):
            tree = node.node if node.node is not None else node.ctx.tree
            if tree is None:
                continue
            mod = node.module
            for sub in ast.walk(tree):
                if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                    continue
                value = sub.value
                if value is None or not isinstance(value, ast.Call):
                    continue
                classes = self._classes_of_call(value, mod)
                if not classes:
                    continue
                targets = sub.targets if isinstance(sub, ast.Assign) \
                    else [sub.target]
                for tgt in targets:
                    key = self._var_key(tgt, node)
                    if key is not None:
                        self.instance_types.setdefault(key, set()) \
                            .update(classes)

    def _var_key(self, tgt: ast.AST, node: Node) -> Optional[str]:
        if isinstance(tgt, ast.Name):
            return "%s:%s" % (node.module, tgt.id)
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self" and node.cls):
            return "%s:%s.%s" % (node.module, node.cls, tgt.attr)
        return None

    def _classes_of_call(self, call: ast.Call, mod: str) -> Set[str]:
        name = dotted_name(call.func)
        if not name:
            return set()
        parts = name.split(".")
        sym: Optional[str] = None
        env = self.envs.get(mod)
        if len(parts) == 1:
            sym = self._module_symbol(mod, parts[0])
        elif env and parts[0] in env.mod_aliases and len(parts) == 2:
            sym = self._module_symbol(env.mod_aliases[parts[0]], parts[1])
        if sym is None:
            return set()
        if sym in self.class_table:
            return {sym}
        target = self.nodes.get(sym)
        if target is not None and target.returns_classes:
            return set(target.returns_classes)
        return set()

    def _resolve_factory_returns(self) -> None:
        """Factories returning `ClassName(...)` type their call sites; run
        to a fixpoint so factory-of-factory chains resolve too."""
        changed = True
        guard = 0
        while changed and guard < 10:
            changed = False
            guard += 1
            for node in self.nodes.values():
                if node.node is None:
                    continue
                for sub in _own_statements(node.node):
                    if not isinstance(sub, ast.Return) or sub.value is None:
                        continue
                    if isinstance(sub.value, ast.Call):
                        cl = self._classes_of_call(sub.value, node.module)
                        if cl and not cl <= node.returns_classes:
                            node.returns_classes |= cl
                            changed = True
                ref = self._returned_callable(node)
                if ref is not None and node.returns_callable is None:
                    node.returns_callable = ref
                    changed = True
            # re-type instances once factory returns are known
            self._type_instances()

    def _returned_callable(self, node: Node) -> Optional[CallableRef]:
        """Detect factories that return a wrapped callable: a `return`
        whose value unwraps to a function (jit/shard_map/partial chains),
        or a name/subscript assigned from one inside the same function
        (the `self._grow_fns[key] = make_...(); return self._grow_fns[key]`
        memoization shape — matched structurally by AST dump)."""
        if node.node is None:
            return None
        assigns: Dict[str, CallableRef] = {}
        for sub in ast.walk(node.node):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                ref = self._unwrap_callable(sub.value, node.module, node,
                                            node.cls, set())
                if ref is None or ref.target is None:
                    continue
                if not (ref.jit_wrapped or ref.axes or ref.donate
                        or ref.offset):
                    continue  # plain `x = fn(...)` calls fn, not aliases it
                for tgt in sub.targets:
                    # unparse, not dump: Store vs Load ctx must not break
                    # the `self._cache[k] = make(...); return self._cache[k]`
                    # memoization match
                    assigns[ast.unparse(tgt)] = ref
        for sub in _own_statements(node.node):
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            val = sub.value
            if isinstance(val, ast.Call):
                ref = self._unwrap_callable(val, node.module, node, node.cls,
                                            set())
                if ref is not None and ref.target is not None:
                    if ref.jit_wrapped or ref.axes or ref.donate \
                            or ref.offset:
                        if ref.jit_wrapped:
                            self.extra_jit_targets.update(
                                ref.target.split("|"))
                        return ref
                    # a factory returning another factory's product
                    inner = self.nodes.get(ref.target)
                    if inner is not None \
                            and inner.returns_callable is not None:
                        return inner.returns_callable
                    # plain `return fn(...)` is a call, not a factory
                    continue
            key = ast.unparse(val)
            if key in assigns:
                ref = assigns[key]
                if ref.jit_wrapped and ref.target:
                    self.extra_jit_targets.update(ref.target.split("|"))
                return ref
        return None

    # ----------------------------------------------------- callable exprs

    def _unwrap_callable(self, expr: ast.AST, mod: str, node: Optional[Node],
                         cls: Optional[str],
                         seen: Set[str]) -> Optional[CallableRef]:
        """Resolve an EXPRESSION to the function it denotes (not a call of
        it): unwraps jit()/shard_map()/partial() wrapper calls and factory
        returns, accumulating donation/axes/offset."""
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return self._resolve_name(expr, mod, node, cls, seen)
        if not isinstance(expr, ast.Call):
            return None
        last = dotted_name(expr.func).rsplit(".", 1)[-1]
        if last == "jit":
            if not expr.args:
                return None
            inner = self._unwrap_callable(expr.args[0], mod, node, cls, seen)
            if inner is None:
                return CallableRef(target=None, jit_wrapped=True)
            donate = _literal_ints(keyword_arg(expr, "donate_argnums"))
            return CallableRef(inner.target,
                               tuple(sorted(set(inner.donate + donate))),
                               inner.axes, inner.offset, True)
        if last == "shard_map":
            if not expr.args:
                return None
            inner = self._unwrap_callable(expr.args[0], mod, node, cls, seen)
            axes = _string_literals(expr)
            if inner is None:
                return CallableRef(target=None, axes=axes)
            return CallableRef(inner.target, inner.donate, inner.axes | axes,
                               inner.offset, inner.jit_wrapped)
        if last == "partial":
            if not expr.args:
                return None
            inner = self._unwrap_callable(expr.args[0], mod, node, cls, seen)
            if inner is None:
                return None
            return CallableRef(inner.target, inner.donate, inner.axes,
                               inner.offset + len(expr.args) - 1,
                               inner.jit_wrapped)
        if last == "guard":
            # utils.sanitize.guard(fn, donate, site) dispatches fn unchanged
            # and only poisons the donated args afterwards — analysis sees
            # straight through it, merging the guard's literal donate tuple
            # (so R10 still tracks donation even when the product code
            # routes the dispatch through the runtime sanitizer).
            if not expr.args:
                return None
            inner = self._unwrap_callable(expr.args[0], mod, node, cls, seen)
            donate = (_literal_ints(expr.args[1])
                      if len(expr.args) > 1 else ())
            if inner is None:
                return None
            return CallableRef(inner.target,
                               tuple(sorted(set(inner.donate + donate))),
                               inner.axes, inner.offset, inner.jit_wrapped)
        # a CALL whose target is a factory returning a callable
        fref = self._unwrap_callable(expr.func, mod, node, cls, seen)
        if fref is not None and fref.target is not None \
                and fref.target not in seen:
            seen.add(fref.target)
            target = self.nodes.get(fref.target)
            if target is not None and target.returns_callable is not None:
                return target.returns_callable
        return None

    def _resolve_name(self, expr: ast.AST, mod: str, node: Optional[Node],
                      cls: Optional[str],
                      seen: Optional[Set[str]] = None) -> Optional[CallableRef]:
        """Resolve a Name/Attribute expression to a node or class."""
        env = self.envs.get(mod)
        if env is None:
            return None
        seen = set() if seen is None else seen
        if isinstance(expr, ast.Name):
            # lexically nested defs win over module scope
            cur = node
            while cur is not None:
                if expr.id in cur.children:
                    return CallableRef(cur.children[expr.id])
                cur = self.nodes.get(cur.lexical_parent) \
                    if cur.lexical_parent else None
            # `g = jax.jit(f, donate_argnums=...)`-style aliases: keep the
            # wrapper's donation/axes instead of collapsing to a bare qual
            ref = self._alias_ref(expr.id, mod, node, cls, seen)
            if ref is not None:
                return ref
            sym = self._module_symbol(mod, expr.id, seen)
            if sym is not None:
                return self._as_callable(sym)
            return None
        if not isinstance(expr, ast.Attribute):
            return None
        base, attr = expr.value, expr.attr
        # self.method / cls.method — class-hierarchy dispatch
        if isinstance(base, ast.Name) and base.id in ("self", "cls") and cls:
            cq = "%s:%s" % (mod, cls)
            m = self.method_on(cq, attr)
            if m is not None:
                return CallableRef(m)
            # typed self-attribute: self.attr resolved elsewhere
            return None
        # super().method()
        if (isinstance(base, ast.Call)
                and dotted_name(base.func) == "super" and cls):
            info = self.envs[mod].classes.get(cls)
            if info:
                for bq in self.mro(info.qual)[1:]:
                    i2 = self.class_table.get(bq)
                    if i2 and attr in i2.methods:
                        return CallableRef(i2.methods[attr])
            return None
        # module alias: telemetry.emit(...)
        name = dotted_name(base)
        if name in env.mod_aliases:
            sym = self._module_symbol(env.mod_aliases[name], attr)
            if sym is not None:
                return self._as_callable(sym)
            return None
        # typed variable / typed self-attribute receiver
        key: Optional[str] = None
        if isinstance(base, ast.Name):
            key = "%s:%s" % (mod, base.id)
        elif (isinstance(base, ast.Attribute)
              and isinstance(base.value, ast.Name)
              and base.value.id == "self" and cls):
            key = "%s:%s.%s" % (mod, cls, base.attr)
        if key is not None:
            hits: Set[str] = set()
            for cq in self.instance_types.get(key, ()):  # all candidates
                m = self.method_on(cq, attr)
                if m is not None:
                    hits.add(m)
            if len(hits) == 1:
                return CallableRef(hits.pop())
            if hits:
                # several candidate receivers: the passes get every edge
                return CallableRef("|".join(sorted(hits)))
        return None

    def _as_callable(self, sym: str) -> CallableRef:
        """Calling a class constructs it: route to __init__ when known."""
        if sym in self.class_table:
            init = self.method_on(sym, "__init__")
            if init is not None:
                return CallableRef(init)
        return CallableRef(sym)

    def _alias_ref(self, name: str, mod: str, node: Optional[Node],
                   cls: Optional[str],
                   seen: Set[str]) -> Optional[CallableRef]:
        """Wrapper-preserving resolution of `name = jit/shard_map/partial
        (...)` assignments, nearest scope first. Returns None unless the
        assignment actually carries wrapper info (plain calls stay calls)."""
        def from_value(value: ast.AST, key: str) -> Optional[CallableRef]:
            if key in seen:
                return None
            seen.add(key)
            ref = self._unwrap_callable(value, mod, node, cls, seen)
            if ref is not None and ref.target is not None and (
                    ref.donate or ref.axes or ref.offset or ref.jit_wrapped):
                if ref.jit_wrapped:
                    self.extra_jit_targets.update(ref.target.split("|"))
                return ref
            return None

        if node is not None and node.node is not None:
            for sub in _own_statements(node.node):
                if isinstance(sub, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in sub.targets):
                    ref = from_value(sub.value,
                                     "lassign:%s:%s" % (node.qual, name))
                    if ref is not None:
                        return ref
        env = self.envs.get(mod)
        if env is not None and name not in env.top_defs \
                and name in env.assigns:
            return from_value(env.assigns[name], "assign:%s:%s" % (mod, name))
        return None

    # ------------------------------------------------------------- edges

    def _build_edges(self, ctx: FileContext) -> None:
        mod = module_name(ctx.relpath)
        for node in self.nodes.values():
            if node.module != mod or node.ctx is not ctx:
                continue
            body = node.node if node.node is not None else ctx.tree
            for call in _own_calls(body):
                self._edges_for_call(node, call)

    def _edges_for_call(self, node: Node, call: ast.Call) -> None:
        if isinstance(call.func, (ast.Name, ast.Attribute)):
            ref = self._resolve_name(call.func, node.module, node, node.cls)
        else:
            # direct call of a wrapped expression: jit(shard_map(body))(x)
            ref = self._unwrap_callable(call.func, node.module, node,
                                        node.cls, set())
        last = dotted_name(call.func).rsplit(".", 1)[-1]
        # shard_map(fn, ...) used as an expression wraps fn: record a wrap
        # edge so axis-binding passes see the mapping context
        if last == "shard_map" and call.args:
            inner = self._unwrap_callable(call.args[0], node.module, node,
                                          node.cls, set())
            if inner is not None and inner.target is not None:
                node.edges.append(Edge(node.qual, inner.target, call, "wrap",
                                       axes=_string_literals(call)))
        if last == "jit" and call.args:
            inner = self._unwrap_callable(call.args[0], node.module, node,
                                          node.cls, set())
            if inner is not None and inner.target is not None:
                self.extra_jit_targets.update(inner.target.split("|"))
        if ref is None:
            node.edges.append(Edge(node.qual, None, call, "call"))
        else:
            if ref.jit_wrapped and ref.target:
                self.extra_jit_targets.update(ref.target.split("|"))
            for tq in (ref.target.split("|") if ref.target else [None]):
                target = self.nodes.get(tq) if tq else None
                if target is not None \
                        and target.returns_callable is not None \
                        and isinstance(call.func, ast.Call):
                    # `self._grow_fn(a, b)(args)`: the outer call
                    # dispatches the factory PRODUCT, not the factory
                    rc = target.returns_callable
                    if rc.jit_wrapped and rc.target:
                        self.extra_jit_targets.update(rc.target.split("|"))
                    for pq in (rc.target.split("|") if rc.target else [None]):
                        node.edges.append(Edge(node.qual, pq, call, "call",
                                               axes=rc.axes,
                                               donate=rc.donate,
                                               offset=rc.offset))
                    continue
                node.edges.append(Edge(node.qual, tq, call, "call",
                                       axes=ref.axes, donate=ref.donate,
                                       offset=ref.offset))
        # bare function references in arguments: may-run callbacks.  The
        # first arg of jit/shard_map/partial wrappers is NOT a callback —
        # it is handled by the wrapper logic above.
        args = list(call.args)
        if last in ("jit", "shard_map") and args:
            args = args[1:]
        for arg in args + [kw.value for kw in call.keywords]:
            if isinstance(arg, (ast.Name, ast.Attribute)):
                r = self._resolve_name(arg, node.module, node, node.cls)
                if r is not None and r.target is not None:
                    for tq in r.target.split("|"):
                        node.edges.append(Edge(node.qual, tq, call, "ref",
                                               axes=r.axes, donate=r.donate))
            elif isinstance(arg, ast.Call):
                # partial(fn, ...) or factory(...) passed as an argument
                r = self._unwrap_callable(arg, node.module, node, node.cls,
                                          set())
                if r is not None and r.target is not None:
                    if r.jit_wrapped:
                        self.extra_jit_targets.update(r.target.split("|"))
                    for tq in r.target.split("|"):
                        node.edges.append(Edge(node.qual, tq, arg, "ref",
                                               axes=r.axes, donate=r.donate,
                                               offset=r.offset))

    # ---------------------------------------------------------- queries

    def callers(self) -> Dict[str, List[Edge]]:
        if self._callers is None:
            table: Dict[str, List[Edge]] = {}
            for node in self.nodes.values():
                for e in node.edges:
                    if e.target is not None:
                        table.setdefault(e.target, []).append(e)
            self._callers = table
        return self._callers

    def reachable_from(self, seeds: Iterable[str],
                       kinds: Sequence[str] = ("call", "ref", "wrap"),
                       ) -> Set[str]:
        """Forward closure over resolved edges; may-call edges (target None)
        contribute nothing — conservatively, the unknown callee's body is
        invisible rather than assumed-safe AND assumed-reaching."""
        seen: Set[str] = set()
        frontier = [q for q in seeds if q in self.nodes]
        while frontier:
            q = frontier.pop()
            if q in seen or q not in self.nodes:
                continue
            seen.add(q)
            for e in self.nodes[q].edges:
                if e.kind in kinds and e.target is not None \
                        and e.target not in seen:
                    frontier.append(e.target)
        return seen

    def resolve_call(self, node: Node, call: ast.Call) -> List[CallableRef]:
        """Public resolution for one call site: every candidate callee with
        its accumulated wrapper info (donation positions, axes, offset).
        Unknown -> [CallableRef(target=None)]."""
        if isinstance(call.func, (ast.Name, ast.Attribute)):
            ref = self._resolve_name(call.func, node.module, node, node.cls)
        else:
            ref = self._unwrap_callable(call.func, node.module, node,
                                        node.cls, set())
        if ref is None:
            return [CallableRef(None)]
        out: List[CallableRef] = []
        for tq in (ref.target.split("|") if ref.target else [None]):
            if tq is None:
                out.append(CallableRef(None))
                continue
            target = self.nodes.get(tq)
            donate, axes, offset = ref.donate, ref.axes, ref.offset
            if target is not None:
                if isinstance(call.func, (ast.Name, ast.Attribute)) \
                        and target.jitted:
                    donate = tuple(sorted(set(donate + target.donate)))
                if isinstance(call.func, ast.Call) \
                        and target.returns_callable is not None:
                    # self._grow_fn(...)(args): the OUTER call dispatches
                    # the factory product
                    rc = target.returns_callable
                    out.append(rc)
                    continue
            out.append(CallableRef(tq, donate, axes, offset,
                                   ref.jit_wrapped))
        return out


def _own_calls(root: ast.AST):
    """Call nodes whose innermost enclosing def is `root` (no descent into
    nested defs — they are their own graph nodes)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _DEFS):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def _own_statements(root: ast.AST):
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _DEFS):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def get_callgraph(pkg: Package) -> CallGraph:
    """Build-once accessor: the graph is shared by every interprocedural
    rule in a run (and by the cache's dependency computation)."""
    g = getattr(pkg, "_callgraph", None)
    if g is None:
        g = CallGraph.build(pkg)
        pkg._callgraph = g  # type: ignore[attr-defined]
    return g


def import_deps(pkg: Package) -> Dict[str, Set[str]]:
    """relpath -> set of relpaths it (transitively) depends on through
    in-package imports. This is what makes the cache cross-file-aware: a
    changed module invalidates every file whose closure contains it."""
    g = get_callgraph(pkg)
    mod_to_rel = {module_name(c.relpath): c.relpath for c in pkg.files}
    direct: Dict[str, Set[str]] = {}
    for ctx in pkg.files:
        mod = module_name(ctx.relpath)
        env = g.envs.get(mod)
        deps: Set[str] = set()
        if env is not None:
            for tgt in env.mod_aliases.values():
                if tgt in mod_to_rel:
                    deps.add(mod_to_rel[tgt])
            for src, _sym in env.sym_imports.values():
                if src in mod_to_rel:
                    deps.add(mod_to_rel[src])
        deps.discard(ctx.relpath)
        direct[ctx.relpath] = deps
    # transitive closure (iterative; cycles fine)
    closed: Dict[str, Set[str]] = {}
    for rel in direct:
        seen: Set[str] = set()
        frontier = list(direct[rel])
        while frontier:
            d = frontier.pop()
            if d in seen:
                continue
            seen.add(d)
            frontier.extend(direct.get(d, ()))
        seen.discard(rel)
        closed[rel] = seen
    return closed
