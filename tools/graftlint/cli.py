"""graftlint CLI: `python -m tools.graftlint lightgbm_tpu`.

Exit status 0 = clean (suppressed findings allowed), 1 = unsuppressed
violations, 2 = usage error. Stdlib-only by design: the CI lint job runs
before any heavyweight dependency installs.
"""
from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional

from .core import run_lint
from .rules import RULES, EXTRA_IDS, rule_codes


def _changed_paths(base: str):
    """Absolute paths of files changed vs `base` (plus untracked files),
    or None when git is unavailable / not a repository."""
    import subprocess

    def git(*cmd: str) -> Optional[str]:
        try:
            proc = subprocess.run(("git",) + cmd, capture_output=True,
                                  text=True, timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        return proc.stdout if proc.returncode == 0 else None

    top = git("rev-parse", "--show-toplevel")
    diff = git("diff", "--name-only", base, "--")
    if top is None or diff is None:
        return None
    untracked = git("ls-files", "--others", "--exclude-standard") or ""
    root = Path(top.strip())
    return {(root / line.strip()).resolve()
            for line in diff.splitlines() + untracked.splitlines()
            if line.strip()}


def _list_rules() -> str:
    lines = ["graftlint rules:"]
    for rule in RULES:
        lines.append("  %-4s %-22s %s" % (rule.code, rule.name,
                                          rule.description))
    for name, code in sorted(EXTRA_IDS.items(), key=lambda kv: kv[1]):
        if any(r.name == name for r in RULES):
            continue
        lines.append("  %-4s %-22s (sub-rule / driver-level finding)"
                     % (code, name))
    lines.append("")
    lines.append("suppress a line:  # graftlint: disable=<rule>[,<rule>]"
                 " -- <reason>")
    lines.append("(a reason is mandatory; a bare disable is itself an S1"
                 " violation)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-based JAX/Pallas invariant checker for the TPU "
                    "hot path (see docs/LINTING.md)")
    parser.add_argument("paths", nargs="*",
                        help="package directories or files to lint "
                             "(typically: lightgbm_tpu)")
    parser.add_argument("--select", default=None,
                        help="comma-separated rule names/codes to run "
                             "(default: all)")
    parser.add_argument("--ignore", default=None,
                        help="comma-separated rule names/codes to skip")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings with reasons")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--format", choices=("text", "sarif"),
                        default="text", dest="fmt",
                        help="output format: human text (default) or a "
                             "SARIF 2.1.0 document on stdout")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the .graftlint_cache/ incremental "
                             "cache (the CLI caches by default; the "
                             "run_lint library API never does)")
    parser.add_argument("--changed-only", action="store_true",
                        help="lint only files changed vs --base (plus "
                             "everything that transitively imports them); "
                             "whole-program rules still run when any "
                             "affected file exists. Implies --no-cache.")
    parser.add_argument("--base", default="HEAD",
                        help="git ref --changed-only diffs against "
                             "(default: HEAD; untracked files always "
                             "count as changed)")
    args = parser.parse_args(argv)

    if args.list_rules:
        print(_list_rules())
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print("error: no paths given (try: python -m tools.graftlint "
              "lightgbm_tpu)", file=sys.stderr)
        return 2

    known = rule_codes()
    for opt in (args.select, args.ignore):
        for tok in (opt.split(",") if opt else []):
            if tok.strip() and tok.strip() not in known:
                print("error: unknown rule %r (see --list-rules)"
                      % tok.strip(), file=sys.stderr)
                return 2

    select = [t.strip() for t in args.select.split(",")] if args.select \
        else None
    ignore = [t.strip() for t in args.ignore.split(",")] if args.ignore \
        else None

    changed_abs = None
    if args.changed_only:
        changed_abs = _changed_paths(args.base)
        if changed_abs is None:
            print("error: --changed-only needs a git checkout (git diff "
                  "--name-only %s failed)" % args.base, file=sys.stderr)
            return 2

    failed = False
    all_violations = []
    all_suppressed = []
    for path in args.paths:
        p = Path(path)
        if not p.exists():
            print("error: no such path: %s" % path, file=sys.stderr)
            return 2
        changed_rel = None
        if changed_abs is not None:
            rp = p.resolve()
            if p.is_file():
                changed_rel = [p.name] if rp in changed_abs else []
            else:
                changed_rel = []
                for c in changed_abs:
                    try:
                        changed_rel.append(c.relative_to(rp).as_posix())
                    except ValueError:
                        continue
        store = None
        if not args.no_cache and changed_rel is None:
            from .cache import CacheStore

            store = CacheStore(p)
        result = run_lint(p, select=select, ignore=ignore, cache=store,
                          cache_key_extra="fmt=%s" % args.fmt,
                          changed_only=changed_rel)
        if args.fmt == "sarif":
            prefix = path.rstrip("/") if p.is_dir() else ""
            for v in result.violations:
                all_violations.append((v, prefix))
            for v in result.suppressed:
                all_suppressed.append((v, prefix))
        else:
            print(result.render(show_suppressed=args.show_suppressed))
        failed |= not result.ok
    if args.fmt == "sarif":
        from dataclasses import replace

        from .sarif import render_sarif

        # re-root each finding at its linted directory so one document can
        # cover several roots; paths then resolve from the repo root
        def reroot(pairs):
            return [replace(v, path="%s/%s" % (pre, v.path)) if pre else v
                    for v, pre in pairs]

        print(render_sarif(reroot(all_violations), reroot(all_suppressed)))
    return 1 if failed else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
