"""graftlint core: file contexts, suppression directives, the lint driver.

Everything here is pure stdlib (ast + tokenize). The driver walks a
package root, parses every .py file once, hands the parsed set to each
rule (rules may be file-local or whole-package, like R4's param
cross-reference), then filters the raw findings through the suppression
table and reports what survives.
"""
from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field, replace
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple


@dataclass(frozen=True)
class Violation:
    """One finding. `rule` is the stable name, `code` the R-number."""

    rule: str
    code: str
    path: str  # package-relative, '/'-separated
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def render(self) -> str:
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return "%s:%d:%d: %s [%s] %s%s" % (
            self.path, self.line, self.col, self.code, self.rule,
            self.message, tag)


@dataclass(frozen=True)
class Suppression:
    """A `# graftlint: disable=...` directive.

    `standalone` directives (comment-only line) cover the NEXT source
    line; trailing directives cover their own line. `tokens` holds the
    raw identifiers: a rule name suppresses that rule, an R-code
    suppresses its whole family (disable=R3 covers pallas-tile-shape,
    pallas-prefetch-arity AND pallas-host-op), 'all' suppresses
    everything on the line.
    """

    line: int
    tokens: Tuple[str, ...]
    reason: str
    standalone: bool

    def covers(self, line: int) -> bool:
        target = self.line + 1 if self.standalone else self.line
        return line == target

    def matches(self, rule: str, code: str) -> bool:
        return any(t in (rule, code, "all") for t in self.tokens)


# reason separator is ' -- ' (double dash): single '-' appears inside
# prose too often to delimit reliably.
_DIRECTIVE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\-]*)(?:\s*--\s*(.*))?$")


class FileContext:
    """One parsed source file plus its suppression table."""

    def __init__(self, abspath: Path, relpath: str) -> None:
        self.abspath = abspath
        self.relpath = relpath
        self.source = abspath.read_text()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=str(abspath))
        except SyntaxError as exc:  # surfaced as an E0 finding by the driver
            self.parse_error = "%s (line %s)" % (exc.msg, exc.lineno)
        self.suppressions: List[Suppression] = []
        self.directive_errors: List[Violation] = []
        self._scan_directives()

    # -- suppression directives ------------------------------------------
    def _scan_directives(self) -> None:
        from .rules import rule_codes  # local import: rules import core

        known = rule_codes()  # name -> code, plus code -> name
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.source).readline)
            comments = [(t.start[0], t.start[1], t.string)
                        for t in tokens if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, SyntaxError):
            return
        for line, col, text in comments:
            m = _DIRECTIVE.search(text)
            if m is None:
                if "graftlint" in text and "disable" in text:
                    self.directive_errors.append(Violation(
                        "bad-suppression", "S1", self.relpath, line, col,
                        "unparseable graftlint directive: %r" % text))
                continue
            names = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
            reason = (m.group(2) or "").strip()
            bad = [n for n in names if n != "all" and n not in known]
            if not names or bad:
                self.directive_errors.append(Violation(
                    "bad-suppression", "S1", self.relpath, line, col,
                    "unknown rule(s) in disable=: %s" % (", ".join(bad) or "<none>")))
                continue
            if not reason:
                # the defect class R4 exists for — unexplained exceptions —
                # applies to the linter itself: every escape hatch carries
                # its justification next to the code it excuses.
                self.directive_errors.append(Violation(
                    "bad-suppression", "S1", self.relpath, line, col,
                    "suppression without a reason (use `disable=%s -- <why>`)"
                    % ",".join(names)))
                continue
            standalone = self.source.splitlines()[line - 1][:col].strip() == ""
            self.suppressions.append(Suppression(line, names, reason, standalone))

    def suppression_for(self, rule: str, code: str,
                        line: int) -> Optional[Suppression]:
        for s in self.suppressions:
            if s.covers(line) and s.matches(rule, code):
                return s
        return None


@dataclass
class Package:
    """The unit rules operate on: every parsed file under one root."""

    root: Path
    files: List[FileContext] = field(default_factory=list)

    def by_relpath(self, relpath: str) -> Optional[FileContext]:
        for ctx in self.files:
            if ctx.relpath == relpath:
                return ctx
        return None


@dataclass
class LintResult:
    violations: List[Violation]  # unsuppressed — these fail the build
    suppressed: List[Violation]

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self, show_suppressed: bool = False) -> str:
        lines = [v.render() for v in self.violations]
        if show_suppressed:
            lines += [v.render() for v in self.suppressed]
        lines.append("graftlint: %d violation(s), %d suppressed"
                     % (len(self.violations), len(self.suppressed)))
        return "\n".join(lines)


_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache"}


def collect(root: Path) -> Package:
    pkg = Package(root=root)
    if root.is_file():
        pkg.files.append(FileContext(root, root.name))
        return pkg
    for path in sorted(root.rglob("*.py")):
        if any(part in _SKIP_DIRS for part in path.parts):
            continue
        rel = path.relative_to(root).as_posix()
        pkg.files.append(FileContext(path, rel))
    return pkg


def run_lint(root, select: Optional[Sequence[str]] = None,
             ignore: Optional[Sequence[str]] = None,
             cache=None, cache_key_extra: str = "",
             changed_only: Optional[Sequence[str]] = None) -> LintResult:
    """Lint every .py under `root` (a package directory or single file).

    select/ignore take rule names or R-codes. Suppression directives are
    honored per line; directives that are malformed or reason-less become
    S1 findings themselves (never filtered by select).

    `cache` is an optional `cache.CacheStore`: file-local rules then skip
    files whose content (and import closure) is unchanged, and the
    whole-program rules are served from cache on a fully-unchanged tree.
    The library default is no cache — only the CLI opts in.
    `cache_key_extra` folds CLI-level configuration (output format) into
    the cache key.

    `changed_only` (a collection of relpaths under `root`) restricts the
    run to the AFFECTED set: the changed files plus every file whose
    transitive in-package import closure intersects them. File-local
    rules, parse/directive seeding, and suppression accounting cover only
    affected files; whole-program rules run — over the full package, they
    need the complete call graph — iff the affected set is non-empty.
    Changed-only runs never read or write the cache: their findings are a
    subset and would poison full-run entries.
    """
    from .rules import RULES, code_families, rule_codes

    codes = rule_codes()
    families = code_families()

    def _canon(names: Iterable[str]) -> Set[str]:
        # an R-code expands to its whole family (R1 means BOTH the local
        # and the cross-module jit-sync rules); names pass through
        out: Set[str] = set()
        for n in names:
            if n in families:
                out.update(families[n])
            else:
                out.add(codes.get(n, n))
        return out

    selected = _canon(select) if select else None
    ignored = _canon(ignore) if ignore else set()

    pkg = collect(Path(root))

    # changed-only mode: affected = changed files + their reverse import
    # closure (anything whose transitive deps include a changed file)
    affected: Optional[Set[str]] = None
    if changed_only is not None:
        from .callgraph import import_deps

        changed = set(changed_only)
        deps = import_deps(pkg)
        affected = {ctx.relpath for ctx in pkg.files
                    if ctx.relpath in changed
                    or changed & deps.get(ctx.relpath, set())}
        cache = None  # a partial run must never feed the full-run cache

    raw: List[Violation] = []
    for ctx in pkg.files:
        if affected is not None and ctx.relpath not in affected:
            continue
        if ctx.parse_error is not None:
            raw.append(Violation("parse-error", "E0", ctx.relpath, 1, 0,
                                 ctx.parse_error))
        raw.extend(ctx.directive_errors)

    active = [r for r in RULES
              if (selected is None or r.name in selected)
              and r.name not in ignored]
    active_names = sorted(r.name for r in active)
    local_rules = [r for r in active if not r.whole_program]
    wp_rules = [r for r in active if r.whole_program]
    if affected is not None and not affected:
        wp_rules = []  # nothing changed reaches the call graph

    if cache is not None:
        cached_local, invalid, cached_wp = \
            cache.plan(pkg, active_names, cache_key_extra)
    else:
        cached_local, invalid, cached_wp = \
            {}, {ctx.relpath for ctx in pkg.files}, None
    if affected is not None:
        invalid &= affected

    # file-local rules: cached findings for unchanged files, a sub-package
    # run over just the invalidated ones
    local_by_file: Dict[str, List[Violation]] = \
        {ctx.relpath: [] for ctx in pkg.files}
    for rel, cached in cached_local.items():
        local_by_file[rel] = list(cached)
    if invalid:
        sub = Package(root=pkg.root,
                      files=[c for c in pkg.files if c.relpath in invalid])
        for rule in local_rules:
            for v in rule.check(sub):
                local_by_file.setdefault(v.path, []).append(v)

    # whole-program rules see the full package whenever anything changed
    if cached_wp is not None:
        wp_findings = list(cached_wp)
    else:
        wp_findings = []
        for rule in wp_rules:
            wp_findings.extend(rule.check(pkg))

    for rel, findings in local_by_file.items():
        if affected is not None and rel not in affected:
            continue
        raw.extend(findings)
    raw.extend(wp_findings)
    # a full hit (no invalid files, whole-program served) leaves the cache
    # file already current — skip the save and its call-graph rebuild
    if cache is not None and (invalid or cached_wp is None):
        cache.save(pkg, local_by_file, wp_findings, active_names,
                   cache_key_extra)

    kept: List[Violation] = []
    suppressed: List[Violation] = []
    for v in sorted(raw, key=lambda v: (v.path, v.line, v.col, v.rule)):
        ctx = pkg.by_relpath(v.path)
        sup = ctx.suppression_for(v.rule, v.code, v.line) if ctx else None
        if sup is not None and v.rule not in ("bad-suppression", "parse-error"):
            suppressed.append(replace(v, suppressed=True, reason=sup.reason))
        else:
            kept.append(v)
    return LintResult(kept, suppressed)


# ---------------------------------------------------------------------------
# shared AST helpers used by several rules

def dotted_name(node: ast.AST) -> str:
    """'a.b.c' for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def literal_int(node: ast.AST) -> Optional[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = literal_int(node.operand)
        return -inner if inner is not None else None
    return None


def keyword_arg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def in_scope(ctx: FileContext, prefixes: Sequence[str],
             exact: Sequence[str] = ()) -> bool:
    """Path scoping for rules. Tolerates being handed the repo root
    instead of the package root by stripping one leading 'lightgbm_tpu/'."""
    rel = ctx.relpath
    if rel.startswith("lightgbm_tpu/"):
        rel = rel[len("lightgbm_tpu/"):]
    if rel in exact:
        return True
    return any(rel.startswith(p) for p in prefixes)


def functions_with_parents(tree: ast.AST):
    """Yield (funcdef, parent_chain) for every function in the module."""
    def walk(node: ast.AST, chain: Tuple[ast.AST, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, chain
                yield from walk(child, chain + (child,))
            else:
                yield from walk(child, chain + (child,))
    yield from walk(tree, ())
