"""Rule registry. Adding a rule = write a module with a Rule subclass,
instantiate it here, document it in docs/LINTING.md, give it a fixture in
tests/fixtures/graftlint/. Sub-ids (e.g. R3's pallas-prefetch-arity) are
declared in EXTRA_IDS so suppressions and --select resolve them."""
from __future__ import annotations

from typing import Dict, List

from .atomic_io import AtomicWriteRule
from .base import Rule
from .collective_axis import CollectiveAxisRule
from .collective_context import CollectiveContextRule
from .donation import DonationRule
from .donation_flow import DonationFlowRule
from .dtype_discipline import DtypeDisciplineRule
from .collective_order import CollectiveOrderRule
from .jit_boundary import JitBoundaryRule
from .jit_boundary_xmod import JitBoundaryXModRule
from .lock_discipline import LockDisciplineRule
from .pallas_rules import PallasRule
from .pallas_vmem import PallasVmemRule
from .param_consistency import ParamConsistencyRule
from .telemetry_hygiene import TelemetryHygieneRule
from .timer_discipline import TimerDisciplineRule

RULES: List[Rule] = [
    JitBoundaryRule(),
    DtypeDisciplineRule(),
    PallasRule(),
    ParamConsistencyRule(),
    TimerDisciplineRule(),
    DonationRule(),
    CollectiveAxisRule(),
    AtomicWriteRule(),
    TelemetryHygieneRule(),
    # interprocedural passes (call-graph driven; see ../callgraph.py)
    JitBoundaryXModRule(),
    DonationFlowRule(),
    CollectiveContextRule(),
    CollectiveOrderRule(),
    LockDisciplineRule(),
    PallasVmemRule(),
]

# rule name -> R-code for ids emitted by rules beyond their primary name
EXTRA_IDS: Dict[str, str] = {
    "pallas-prefetch-arity": "R3",
    "pallas-host-op": "R3",
    "collective-rank-loop": "R12",
    "collective-axis-entry": "R12",
    "lock-order-cycle": "R13",
    "bad-suppression": "S1",
    "parse-error": "E0",
}


def rule_codes() -> Dict[str, str]:
    """Map every accepted identifier (name or code) to the canonical rule
    NAME — used by suppression parsing and --select. Codes shared by
    several sub-rules (R3, R1) map to the FIRST registered name; selecting
    or suppressing by code covers the whole family (see code_families)."""
    table: Dict[str, str] = {}
    for rule in RULES:
        table[rule.name] = rule.name
        table.setdefault(rule.code, rule.name)
    for name, code in EXTRA_IDS.items():
        table[name] = name
        table.setdefault(code, name)
    return table


def code_families() -> Dict[str, List[str]]:
    """R-code -> every rule NAME sharing it (R1 covers jit-host-sync AND
    jit-host-sync-xmod; R3 covers the pallas sub-ids). --select/--ignore
    by code must expand to the full family."""
    fams: Dict[str, List[str]] = {}
    for rule in RULES:
        fams.setdefault(rule.code, []).append(rule.name)
    for name, code in EXTRA_IDS.items():
        fams.setdefault(code, []).append(name)
    return fams
