"""R8 non-atomic-write: model/checkpoint artifacts must go through the
atomic writer.

The defect class this PR's checkpoint work exists to kill: a bare
``open(path, "w")`` in a save path means a crash (or preemption — the TPU
fleet's steady state) mid-write leaves a truncated model file that the next
run trips over. ``lightgbm_tpu/checkpoint.py`` provides the one correct
write primitive (temp file in the target directory + fsync + ``os.replace``
+ directory fsync, with bounded retry): ``atomic_open`` for streaming
writers, ``atomic_write_text``/``atomic_write_bytes`` for whole-content
writes. This rule flags any literal write-mode ``open()`` call in the
modules that persist models, checkpoints, datasets, or converted artifacts
— read-mode opens and non-literal modes pass.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..core import Package, Violation, dotted_name, keyword_arg
from .base import Rule

_WRITE_CHARS = set("wax")


def _literal_write_mode(call: ast.Call) -> Optional[str]:
    """The call's literal mode string when it opens for writing, else None
    (no mode = read; non-literal modes are out of static reach)."""
    mode_node: Optional[ast.AST] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    kw = keyword_arg(call, "mode")
    if kw is not None:
        mode_node = kw
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        if _WRITE_CHARS & set(mode_node.value):
            return mode_node.value
    return None


class AtomicWriteRule(Rule):
    name = "non-atomic-write"
    code = "R8"
    description = ("bare write-mode open() in a model/checkpoint/dataset "
                   "save path — a crash mid-write leaves a truncated "
                   "artifact; route it through checkpoint.atomic_open / "
                   "atomic_write_text / atomic_write_bytes")
    scope_prefixes = ("models/",)
    scope_exact = ("checkpoint.py", "cli.py", "basic.py", "engine.py")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                if dotted_name(node.func) != "open":
                    continue
                mode = _literal_write_mode(node)
                if mode is None:
                    continue
                out.append(self.violation(
                    ctx, node,
                    "open(..., %r) writes a persistence artifact "
                    "non-atomically — a crash here leaves a truncated "
                    "file; use checkpoint.atomic_open (streaming) or "
                    "checkpoint.atomic_write_text/bytes (whole content), "
                    "which add temp+fsync+os.replace and bounded retry"
                    % mode))
        return out
