"""Rule base class. A rule sees the whole Package (R4 needs cross-file
state); file-local rules iterate `self.scoped(pkg)` and keep their scope
predicate in one place."""
from __future__ import annotations

from typing import Iterable, Iterator, List, Sequence, Tuple

import ast

from ..core import FileContext, Package, Violation, in_scope


class Rule:
    name: str = ""
    code: str = ""
    description: str = ""
    # path prefixes (package-relative) + exact files this rule applies to;
    # empty scope_prefixes + empty scope_exact = every file.
    scope_prefixes: Sequence[str] = ()
    scope_exact: Sequence[str] = ()
    # whole-program rules see cross-file state (call graph, R4's param
    # table): the incremental cache must rerun them whenever ANY file
    # changed, while file-local rules rerun only on changed files.
    whole_program: bool = False

    def check(self, pkg: Package) -> Iterable[Violation]:
        raise NotImplementedError

    def scoped(self, pkg: Package) -> Iterator[FileContext]:
        for ctx in pkg.files:
            if ctx.tree is None:
                continue
            if not self.scope_prefixes and not self.scope_exact:
                yield ctx
            elif in_scope(ctx, self.scope_prefixes, self.scope_exact):
                yield ctx

    def violation(self, ctx: FileContext, node: ast.AST, message: str,
                  rule: str = "", code: str = "") -> Violation:
        return Violation(rule or self.name, code or self.code, ctx.relpath,
                         getattr(node, "lineno", 1),
                         getattr(node, "col_offset", 0), message)


def module_functions(tree: ast.Module) -> List[Tuple[str, ast.AST]]:
    """(name, def) for module-level functions and class methods — the
    granularity at which 'one function, one responsibility' rules apply.
    Nested defs belong to their enclosing function's subtree."""
    out: List[Tuple[str, ast.AST]] = []
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append((node.name, node))
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.append(("%s.%s" % (node.name, sub.name), sub))
    return out
