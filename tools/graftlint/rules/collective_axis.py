"""R7 collective-axis: collectives must name an axis bound by a shard_map.

`jax.lax.psum` / `psum_scatter` / `all_gather` resolve their axis name
against the innermost surrounding `shard_map` (or pmap) binding it. A
collective whose axis name is a typo, computed at runtime, or simply not
bound by the shard_map that ultimately traces the function fails at TRACE
time at best — and at worst traces fine under one call path and explodes
when a refactor moves the function out from under its mapping wrapper.
The sharded device learner's collectives all ride the ``data`` mesh axis
through functions several call levels below the `jax.shard_map` call, so
the binding is invisible at the call site; this rule makes it checkable.

The check is module-local and conservative:

* every `shard_map(fn, ...)` call in the module contributes its STRING
  literals (the axis names in axis_names and the PartitionSpecs of
  in_specs/out_specs) to the bound-axis set of the wrapped function
  `fn` (first positional argument, plain name);
* bound axes flow to lexically nested defs (they trace inside the
  wrapper) and — to a fixpoint — through plain-name calls to other
  functions in the module (the sharded learner's
  `body -> _grow_impl -> raw_blocks` chain);
* a collective call anywhere else in the module, or naming an axis not
  in its bound set, or passing a non-literal axis name, is a violation.

Functions the module never routes through a shard_map are still checked:
a bare collective in a module with no shard_map at all is exactly the
refactor hazard above. Modules outside the accelerator surface
(parallel/, treelearner/, models/, ops/) are not scanned.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from ..core import (Package, Violation, dotted_name, functions_with_parents,
                    keyword_arg)
from .base import Rule

_COLLECTIVES = {"psum", "psum_scatter", "all_gather"}
_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)


def _last_segment(node: ast.AST) -> str:
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


def _string_literals(node: ast.AST) -> Set[str]:
    return {sub.value for sub in ast.walk(node)
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str)}


def _axis_arg(call: ast.Call) -> Optional[ast.AST]:
    """The axis-name argument of a collective call: every jax.lax
    collective takes it as the second positional or `axis_name=`."""
    kw = keyword_arg(call, "axis_name")
    if kw is not None:
        return kw
    if len(call.args) >= 2:
        return call.args[1]
    return None


def _own_calls(root: ast.AST) -> Iterator[ast.Call]:
    """Call nodes whose innermost enclosing def is `root` (does not
    descend into nested defs; lambdas are not a binding scope here)."""
    stack = list(ast.iter_child_nodes(root))
    while stack:
        node = stack.pop()
        if isinstance(node, _DEFS):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


class CollectiveAxisRule(Rule):
    name = "collective-axis"
    code = "R7"
    description = ("psum/psum_scatter/all_gather whose axis name is not a "
                   "literal bound by a shard_map in the same module")
    scope_prefixes = ("parallel/", "treelearner/", "models/", "ops/")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            out.extend(self._check_module(ctx))
        return out

    def _check_module(self, ctx) -> List[Violation]:
        tree = ctx.tree
        all_defs: List[Tuple[ast.AST, Tuple[ast.AST, ...]]] = [
            (fn, tuple(a for a in chain if isinstance(a, _DEFS)))
            for fn, chain in functions_with_parents(tree)]
        by_name: Dict[str, List[ast.AST]] = {}
        for fn, _ in all_defs:
            by_name.setdefault(fn.name, []).append(fn)

        # 1. axes bound directly: shard_map(fn, ...) seeds fn with every
        #    string literal in the call (axis tuple + PartitionSpecs)
        direct: Dict[ast.AST, Set[str]] = {}
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            if _last_segment(call.func) != "shard_map":
                continue
            if not call.args or not isinstance(call.args[0], ast.Name):
                continue
            axes = _string_literals(call)
            for target in by_name.get(call.args[0].id, []):
                direct.setdefault(target, set()).update(axes)

        def effective(fn: ast.AST, ancestors: Tuple[ast.AST, ...]) -> Set[str]:
            eff = set(direct.get(fn, ()))
            for anc in ancestors:
                eff |= direct.get(anc, set())
            return eff

        # 2. fixpoint: a wrapped function's axes flow through plain-name
        #    call edges to same-module functions (body -> _grow_impl ->
        #    nested helpers); lexical nesting flows via effective() above
        changed = True
        while changed:
            changed = False
            for fn, ancestors in all_defs:
                eff = effective(fn, ancestors)
                if not eff:
                    continue
                for call in _own_calls(fn):
                    if not isinstance(call.func, ast.Name):
                        continue
                    for target in by_name.get(call.func.id, []):
                        have = direct.setdefault(target, set())
                        if not eff <= have:
                            have |= eff
                            changed = True

        # 3. every collective checks against its innermost def's effective
        #    axes; module-level calls have nothing bound
        out: List[Violation] = []
        for fn, ancestors in all_defs:
            axes = effective(fn, ancestors)
            for call in _own_calls(fn):
                out.extend(self._check_call(ctx, call, axes))
        for call in _own_calls(tree):
            out.extend(self._check_call(ctx, call, set()))
        return out

    def _check_call(self, ctx, call: ast.Call,
                    axes: Set[str]) -> List[Violation]:
        op = _last_segment(call.func)
        if op not in _COLLECTIVES:
            return []
        axis = _axis_arg(call)
        if axis is None:
            return [self.violation(
                ctx, call,
                "%s without an axis name — collectives must name the "
                "shard_map axis they reduce over" % op)]
        if not (isinstance(axis, ast.Constant)
                and isinstance(axis.value, str)):
            return [self.violation(
                ctx, call,
                "%s axis name is not a string literal — the binding to an "
                "enclosing shard_map cannot be checked" % op)]
        if axis.value not in axes:
            if axes:
                detail = "the enclosing shard_map binds only %s" % (
                    ", ".join(repr(a) for a in sorted(axes)))
            else:
                detail = ("no shard_map in this module wraps a function "
                          "reaching this call")
            return [self.violation(
                ctx, call,
                "%s over axis %r which is not bound here — %s"
                % (op, axis.value, detail))]
        return []
