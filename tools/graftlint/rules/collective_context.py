"""R11 collective-context: every call-graph path to a collective must bind
its axis.

R7 proves a collective's axis is bound by a shard_map *in the same
module*. That leaves exactly one hole, and the sharded device learner
sits in it: a helper whose `psum` is correctly wrapped when reached
through `parallel/learners.py` can ALSO be reachable from an unsharded
jitted entry in another module — that trace has no mesh context and
fails the moment somebody exercises the second path.

This pass propagates axis REQUIREMENTS bottom-up over the package call
graph: a function requires the axes of its own literal-axis collectives
plus whatever its callees require, minus the axes an edge's wrapper
binds (`shard_map(fn, ...)` wrap edges and factory products like
`jax.jit(shard_map(body), ...)` both carry their bound axes on the
edge). Propagation stops at jit boundaries: `jit(f)` with an unbound
collective inside is broken no matter who calls it, so the finding
anchors there and does not flood every transitive caller.

A finding is one (origin, axis) pair where the origin is a jit boundary
whose residual requirement is non-empty, or a root function (no
in-package callers) with a residual requirement. Non-literal axis names
and axisless collectives stay R7's findings — this pass only reasons
about axes it can name. Anchoring follows R6: the def / first decorator
line, so a standalone suppression sits directly above the entry point
whose trace is the hazard.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set, Tuple

from ..callgraph import CallGraph, Node, _own_calls, get_callgraph
from ..core import Package, Violation, dotted_name
from .base import Rule
from .collective_axis import _COLLECTIVES, _axis_arg

# witness: where the requirement was born, for the message
_Witness = Tuple[str, int]  # (relpath, line)


class CollectiveContextRule(Rule):
    name = "collective-context"
    code = "R11"
    description = ("collective reachable from a jit boundary or root with "
                   "no shard_map binding its axis on that call path")
    scope_prefixes = ("parallel/", "treelearner/", "models/", "ops/")
    whole_program = True

    def check(self, pkg: Package) -> Iterable[Violation]:
        graph = get_callgraph(pkg)
        scoped = {id(c) for c in self.scoped(pkg)}
        jit_boundary = graph.jit_seeds()

        # own requirements: literal-axis collectives in each node
        req: Dict[str, Dict[str, _Witness]] = {}
        for q, node in graph.nodes.items():
            body = node.node if node.node is not None else node.ctx.tree
            if body is None or id(node.ctx) not in scoped:
                continue
            for call in _own_calls(body):
                op = dotted_name(call.func).rsplit(".", 1)[-1]
                if op not in _COLLECTIVES:
                    continue
                axis = _axis_arg(call)
                if isinstance(axis, ast.Constant) \
                        and isinstance(axis.value, str):
                    req.setdefault(q, {}).setdefault(
                        axis.value, (node.ctx.relpath, call.lineno))

        # bottom-up fixpoint over call/ref edges; wrapper-bound axes are
        # subtracted per edge; jit boundaries absorb (they report locally)
        changed = True
        guard = 0
        while changed and guard < 200:
            changed = False
            guard += 1
            for q, node in graph.nodes.items():
                for e in node.edges:
                    if e.target is None or e.kind == "wrap":
                        continue
                    if e.target in jit_boundary:
                        continue  # reported at the boundary itself
                    for axis, wit in req.get(e.target, {}).items():
                        if axis in e.axes:
                            continue
                        mine = req.setdefault(q, {})
                        if axis not in mine:
                            mine[axis] = wit
                            changed = True

        # a jitted/wrapped node's OWN binding context: axes bound by wrap
        # edges pointing at it (shard_map(body) inside its factory)
        bound_at: Dict[str, Set[str]] = {}
        for node in graph.nodes.values():
            for e in node.edges:
                if e.kind == "wrap" and e.target is not None:
                    bound_at.setdefault(e.target, set()).update(e.axes)
                elif e.kind == "call" and e.target is not None and e.axes:
                    # factory-product dispatch: the call's wrapper binds
                    # these axes around the target
                    bound_at.setdefault(e.target, set()).update(e.axes)

        callers = graph.callers()
        out: List[Violation] = []
        reported: Set[Tuple[str, str]] = set()
        for q in sorted(req):
            node = graph.nodes[q]
            if node.node is None or id(node.ctx) not in scoped:
                continue
            residual = {a: w for a, w in req[q].items()
                        if a not in bound_at.get(q, set())}
            if not residual:
                continue
            is_boundary = q in jit_boundary
            is_root = not any(e.kind in ("call", "ref")
                              for e in callers.get(q, ()))
            if not is_boundary and not is_root:
                continue
            for axis, wit in sorted(residual.items()):
                if (q, axis) in reported:
                    continue
                reported.add((q, axis))
                kind = "jit boundary" if is_boundary else "entry point"
                anchor = node.node.decorator_list[0] \
                    if node.node.decorator_list else node.node
                out.append(self.violation(
                    node.ctx, anchor,
                    "%s %r reaches a collective over axis %r (%s:%d) with "
                    "no shard_map binding it on this path — tracing this "
                    "entry without a mesh context fails; wrap the dispatch "
                    "or prove the collective is statically pruned here"
                    % (kind, q, axis, wit[0], wit[1])))
        return out
