"""R12 collective-consistency: every rank must issue the same collectives
in the same order.

R7/R11 prove each collective is *bound to an axis*; nothing proves the
*sequence* of collectives is rank-invariant — and a rank-divergent
sequence is the canonical way to hang a pod: one rank enters a psum the
others never post, the mesh deadlocks until the elastic watchdog fires
(if it is armed at all). This pass computes an ordered per-function
collective-sequence summary — (op, axis) pairs, spliced through resolved
call edges and through shard_map/jit factory wrap sites — as an
interprocedural fixpoint, then flags three divergence shapes:

* **collective-order** (a): an ``if`` whose test depends on
  ``jax.process_index()`` / ``jax.process_count()`` / a rank-named value
  and whose arms yield different collective sequences. A body that
  terminates (return/raise/break/continue) is compared against the rest
  of the enclosing block — the early-return gate is the common disguise.
* **collective-rank-loop** (b): a collective inside a for/while whose
  iterable or condition derives from rank-local data (process_index,
  local/addressable device or shard queries, or names assigned from
  them): the trip count — and so the number of collectives posted —
  differs per rank.
* **collective-axis-entry** (c): the same function entered through two
  wrapper sites with *different* axis bindings where one binding does not
  cover the axes its collective sequence uses. R11's union over entry
  sites cannot see this: each axis is bound *somewhere*, just not on
  every path.

Conservatism notes: process_count-gated single-process fallbacks are
uniform across a gang in practice but statically indistinguishable from
rank divergence — such sites carry reasoned suppressions (the elastic
heartbeat's windowed pull is the sanctioned one). Factory wrap sites
(``jax.jit(shard_map(body, ...))``) contribute the wrapped body's
sequence at the wrap line: the build-then-call pattern means the
collective runs on whichever rank executes the surrounding code path.
The dynamic oracle for this pass is sanitize.py's collective-order
cross-check (docs/ROBUSTNESS.md).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..callgraph import CallGraph, Edge, Node, get_callgraph
from ..core import Package, Violation, dotted_name
from .base import Rule
from .collective_axis import _COLLECTIVES, _axis_arg

_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef)
_CAP = 64  # summary length cap: divergence shows up long before this

# names whose value differs per process: the (a) branch-test markers
_RANK_CALLS = {"process_index", "process_count"}
_RANK_NAMES = {"rank", "process_id", "pid"}
# additionally rank-LOCAL data sources for the (b) loop-bound taint
_LOCAL_CALLS = {"local_devices", "local_device_count", "addressable_devices"}
_LOCAL_ATTRS = {"addressable_shards", "addressable_data"}

Seq = Tuple[Tuple[str, str], ...]


def _calls_in_order(node: ast.AST):
    """Pre-order Call nodes in source order; nested defs/lambdas are their
    own graph nodes and do not run inline, so they are skipped."""
    if isinstance(node, _DEFS) or isinstance(node, ast.Lambda):
        return
    if isinstance(node, ast.Call):
        yield node
    for child in ast.iter_child_nodes(node):
        yield from _calls_in_order(child)


def _collective_at(call: ast.Call) -> Optional[Tuple[str, str]]:
    op = dotted_name(call.func).rsplit(".", 1)[-1]
    if op not in _COLLECTIVES:
        return None
    axis = _axis_arg(call)
    if isinstance(axis, ast.Constant) and isinstance(axis.value, str):
        return (op, axis.value)
    return (op, "?")


class _Summaries:
    """Memoized ordered collective sequences per call-graph node."""

    def __init__(self, graph: CallGraph) -> None:
        self.graph = graph
        self.memo: Dict[str, Seq] = {}
        # per-node: id(Call) -> edges at that site
        self._by_call: Dict[str, Dict[int, List[Edge]]] = {}

    def edges_at(self, node: Node) -> Dict[int, List[Edge]]:
        table = self._by_call.get(node.qual)
        if table is None:
            table = {}
            for e in node.edges:
                if e.call is not None:
                    table.setdefault(id(e.call), []).append(e)
            self._by_call[node.qual] = table
        return table

    def of_node(self, qual: str, visiting: Optional[Set[str]] = None) -> Seq:
        if qual in self.memo:
            return self.memo[qual]
        visiting = visiting if visiting is not None else set()
        if qual in visiting:
            return ()  # recursion: the cycle contributes nothing extra
        node = self.graph.nodes.get(qual)
        if node is None:
            return ()
        visiting.add(qual)
        if node.node is not None:
            stmts: Sequence[ast.AST] = node.node.body
        elif node.ctx.tree is not None:
            stmts = node.ctx.tree.body
        else:
            stmts = ()
        seq = self.of_stmts(node, stmts, visiting)
        visiting.discard(qual)
        self.memo[qual] = seq
        return seq

    def of_stmts(self, node: Node, stmts: Sequence[ast.AST],
                 visiting: Set[str]) -> Seq:
        out: List[Tuple[str, str]] = []
        by_call = self.edges_at(node)
        wrapped_once: Set[str] = set()
        for stmt in stmts:
            for call in _calls_in_order(stmt):
                if len(out) >= _CAP:
                    return tuple(out)
                own = _collective_at(call)
                if own is not None:
                    out.append(own)
                    continue
                for e in by_call.get(id(call), ()):
                    if e.target is None:
                        continue
                    if e.kind == "wrap":
                        # jit(shard_map(body)) factory: body's sequence
                        # runs where the product is dispatched — splice
                        # once per wrapped target
                        if e.target in wrapped_once:
                            continue
                        wrapped_once.add(e.target)
                    out.extend(self.of_node(e.target, visiting))
                    break  # first resolved candidate keeps it deterministic
        return tuple(out[:_CAP])


def _expr_tainted(expr: ast.AST, tainted: Set[str], local: bool) -> bool:
    """Does `expr` mention a rank marker (or a name assigned from one)?
    With local=True the rank-LOCAL data sources count too (loop bounds)."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            last = dotted_name(sub.func).rsplit(".", 1)[-1]
            if last in _RANK_CALLS or (local and last in _LOCAL_CALLS):
                return True
        elif isinstance(sub, ast.Attribute):
            if sub.attr in _RANK_NAMES or (local and sub.attr in _LOCAL_ATTRS):
                return True
        elif isinstance(sub, ast.Name):
            if sub.id in _RANK_NAMES or sub.id in tainted:
                return True
    return False


def _tainted_names(fn: ast.AST, local: bool) -> Set[str]:
    """Names assigned (transitively, two passes) from rank markers inside
    one function body."""
    tainted: Set[str] = set()
    for _ in range(2):
        for stmt in ast.walk(fn):
            if isinstance(stmt, _DEFS) and stmt is not fn:
                continue
            value = None
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                value, targets = stmt.value, stmt.targets
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                value, targets = stmt.value, [stmt.target]
            elif isinstance(stmt, ast.AugAssign):
                value, targets = stmt.value, [stmt.target]
            if value is None or not _expr_tainted(value, tainted, local):
                continue
            for tgt in targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        tainted.add(n.id)
    return tainted


def _terminates(stmts: Sequence[ast.AST]) -> bool:
    return any(isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue))
               for s in stmts)


def _fmt(seq: Seq) -> str:
    if not seq:
        return "[]"
    return "[" + ", ".join("%s@%s" % (op, ax) for op, ax in seq[:6]) + (
        ", ..." if len(seq) > 6 else "") + "]"


class CollectiveOrderRule(Rule):
    name = "collective-order"
    code = "R12"
    description = ("rank-divergent collective sequence: collectives under "
                   "process_index/rank-dependent branches, inside "
                   "rank-local-bound loops, or behind inconsistent axis "
                   "bindings")
    scope_prefixes = ("parallel/", "treelearner/", "models/", "ops/",
                      "streaming/")
    whole_program = True

    def check(self, pkg: Package) -> Iterable[Violation]:
        graph = get_callgraph(pkg)
        sums = _Summaries(graph)
        scoped = {id(c) for c in self.scoped(pkg)}
        out: List[Violation] = []
        for qual in sorted(graph.nodes):
            node = graph.nodes[qual]
            if node.node is None or id(node.ctx) not in scoped:
                continue
            out.extend(self._check_branches(node, sums))
            out.extend(self._check_loops(node, sums))
        out.extend(self._check_entries(graph, sums, scoped))
        return out

    # -- (a) rank-dependent branches with divergent sequences ------------
    def _check_branches(self, node: Node, sums: _Summaries
                        ) -> List[Violation]:
        out: List[Violation] = []
        tainted = _tainted_names(node.node, local=False)
        visiting: Set[str] = {node.qual}

        def walk_block(stmts: Sequence[ast.AST]) -> None:
            for i, st in enumerate(stmts):
                if isinstance(st, _DEFS):
                    continue
                if isinstance(st, ast.If) \
                        and _expr_tainted(st.test, tainted, local=False):
                    body_seq = sums.of_stmts(node, st.body, visiting)
                    if st.orelse:
                        other: Sequence[ast.AST] = st.orelse
                    elif _terminates(st.body):
                        # early-return gate: the implicit else is the rest
                        # of the enclosing block
                        other = stmts[i + 1:]
                    else:
                        other = ()
                    else_seq = sums.of_stmts(node, other, visiting)
                    if body_seq != else_seq:
                        out.append(self.violation(
                            node.ctx, st,
                            "rank-dependent branch: the arms of this "
                            "process_index/process_count/rank test post "
                            "different collective sequences (%s vs %s) — "
                            "ranks taking different arms deadlock the "
                            "mesh; restructure so every rank posts the "
                            "same collectives, or suppress with the "
                            "uniformity argument"
                            % (_fmt(body_seq), _fmt(else_seq))))
                for sub in (getattr(st, "body", ()), getattr(st, "orelse", ()),
                            getattr(st, "finalbody", ())):
                    if sub:
                        walk_block(sub)
                for h in getattr(st, "handlers", ()):
                    walk_block(h.body)

        walk_block(node.node.body)
        return out

    # -- (b) collectives inside rank-local-bound loops -------------------
    def _check_loops(self, node: Node, sums: _Summaries) -> List[Violation]:
        out: List[Violation] = []
        tainted = _tainted_names(node.node, local=True)
        visiting: Set[str] = {node.qual}

        def walk_block(stmts: Sequence[ast.AST]) -> None:
            for st in stmts:
                if isinstance(st, _DEFS):
                    continue
                bound = None
                if isinstance(st, ast.For):
                    bound = st.iter
                elif isinstance(st, ast.While):
                    bound = st.test
                if bound is not None \
                        and _expr_tainted(bound, tainted, local=True):
                    seq = sums.of_stmts(node, st.body, visiting)
                    if seq:
                        out.append(self.violation(
                            node.ctx, st,
                            "collective %s@%s inside a loop whose trip "
                            "count derives from rank-local data: each "
                            "rank posts a different number of "
                            "collectives — hoist the collective out of "
                            "the loop or pad to a global trip count"
                            % seq[0], rule="collective-rank-loop"))
                        continue  # one finding per loop is enough
                for sub in (getattr(st, "body", ()), getattr(st, "orelse", ()),
                            getattr(st, "finalbody", ())):
                    if sub:
                        walk_block(sub)
                for h in getattr(st, "handlers", ()):
                    walk_block(h.body)

        walk_block(node.node.body)
        return out

    # -- (c) inconsistent axis bindings across entry sites ---------------
    def _check_entries(self, graph: CallGraph, sums: _Summaries,
                       scoped: Set[int]) -> List[Violation]:
        # target qual -> list of (caller node, edge) with a wrapper binding
        entries: Dict[str, List[Tuple[Node, Edge]]] = {}
        for node in graph.nodes.values():
            for e in node.edges:
                if e.target is not None and e.axes and e.call is not None:
                    entries.setdefault(e.target, []).append((node, e))
        out: List[Violation] = []
        seen: Set[Tuple[str, int]] = set()
        for target in sorted(entries):
            sites = entries[target]
            bindings = {frozenset(e.axes) for _, e in sites}
            if len(bindings) < 2:
                continue
            used = {ax for _, ax in sums.of_node(target) if ax != "?"}
            if not used:
                continue
            for caller, e in sites:
                missing = used - e.axes
                if not missing or id(caller.ctx) not in scoped:
                    continue
                key = (caller.ctx.relpath, e.call.lineno)
                if key in seen:
                    continue
                seen.add(key)
                out.append(self.violation(
                    caller.ctx, e.call,
                    "%r is entered here binding only %s, but its "
                    "collective sequence uses axis %s (bound at other "
                    "entry sites): the trace through this entry posts a "
                    "different collective sequence than the others"
                    % (target, sorted(e.axes), sorted(missing)),
                    rule="collective-axis-entry"))
        return out
