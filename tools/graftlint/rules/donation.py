"""R6 jit-donation: jitted entry points that take device arrays must donate.

A `jax.jit` boundary in the training loop that accepts large device arrays
without `donate_argnums` forces XLA to keep the caller's buffers alive
across the call — the [G, N] bin plane and [N, CH] gh payload get DOUBLE
buffered in HBM every tree. Donation lets XLA reuse the input allocations
for outputs/loop carries; on a 10.5M-row HIGGS-shape dataset that is
hundreds of MB of working set per dispatch (docs/PERF_NOTES.md).

Scope: treelearner/ and models/ — the per-iteration training surface where
the arrays are big and the calls are hot. ops/ kernels are exempt: they are
called from already-jitted code (donation only applies at the outermost jit
boundary). The rule is annotation-driven: a decorator-jitted function with
at least one parameter annotated `jax.Array` / `jnp.ndarray` must either
declare `donate_argnums`/`donate_argnames` or carry a reasoned suppression
explaining why its inputs must outlive the call (e.g. a buffer reused
across iterations on the caller's side).
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Package, Violation, dotted_name
from .base import Rule, module_functions
from .jit_boundary import _is_jitted

_ARRAY_ANNOTATIONS = {"jax.Array", "jnp.ndarray", "jax.numpy.ndarray",
                      "np.ndarray", "numpy.ndarray"}


def _annotation_names(node: ast.AST) -> Iterable[str]:
    """Dotted names mentioned anywhere in an annotation expression,
    including inside string ('jax.Array') and Optional[...] forms."""
    for sub in ast.walk(node):
        name = dotted_name(sub)
        if name:
            yield name
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            # deferred/string annotation: parse its text best-effort
            try:
                inner = ast.parse(sub.value, mode="eval")
            except SyntaxError:
                continue
            for s in ast.walk(inner):
                n = dotted_name(s)
                if n:
                    yield n


def _has_array_param(fn: ast.AST) -> bool:
    args = fn.args
    params = list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
    for p in params:
        if p.annotation is None:
            continue
        if any(n in _ARRAY_ANNOTATIONS for n in _annotation_names(p.annotation)):
            return True
    return False


def _declares_donation(fn: ast.AST) -> bool:
    for dec in fn.decorator_list:
        for node in ast.walk(dec):
            if isinstance(node, ast.keyword) and node.arg in (
                    "donate_argnums", "donate_argnames"):
                return True
    return False


class DonationRule(Rule):
    name = "jit-donation"
    code = "R6"
    description = ("decorator-jitted function with jax.Array parameters "
                   "declares no donate_argnums (inputs get double buffered)")
    scope_prefixes = ("treelearner/", "models/", "streaming/")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            for qual, fn in module_functions(ctx.tree):
                if not _is_jitted(fn):
                    continue
                if not _has_array_param(fn):
                    continue
                if _declares_donation(fn):
                    continue
                # anchor at the first decorator so a standalone suppression
                # directly above @jax.jit covers the finding
                anchor = fn.decorator_list[0] if fn.decorator_list else fn
                out.append(self.violation(
                    ctx, anchor,
                    "jitted %r takes device-array args but declares no "
                    "donate_argnums — caller buffers stay live across the "
                    "call (double buffering); donate, or suppress with the "
                    "reason the inputs must survive" % qual))
        return out
