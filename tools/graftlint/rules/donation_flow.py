"""R10 use-after-donation: a read of a binding after its buffer was donated.

`donate_argnums` / `donate_argnames` / pallas `input_output_aliases` hand
the input buffer to XLA for reuse: after the dispatch returns, the
caller's reference points at memory the output may already occupy. On CPU
donation is silently ignored, so the bug ships green and detonates on the
TPU — the exact trap the `donate_argnums=(0,1,2)` device learner and the
`LGBM_TPU_COMPACT_ALIAS=1` pallas path can grow.

The pass finds donating call sites through the package call graph, so
every dispatch shape the codebase actually uses is covered:

* decorator donation (`@partial(jax.jit, donate_argnums=(0,))`) on a
  directly-called function, cross-module included;
* `g = jax.jit(f, donate_argnums=...)` assignment aliases (module-level
  or local);
* factory products: `self._grow_fn(key)(bins, gh, ...)` where the factory
  returns `jax.jit(shard_map(body), donate_argnums=(0,1,2))` — partial()
  offsets shift the donated positions;
* `pallas_call(kernel, ..., input_output_aliases={4: 0})(args)` with a
  literal dict (a dynamically-built dict degrades to no-check, not to a
  false positive);
* interprocedural flow: a function that forwards its own parameter into a
  donated position donates that parameter, so ITS callers are checked at
  their own call sites (fixpoint over the graph, cycles safe).

Tracked bindings are bare names and `self.attr` chains. Subscripts
(`self.score[0]`) are deliberately untracked: indexing a jax array makes
a fresh buffer, which is the package's compliant donation idiom — the
caller keeps the container, donates the temp. A read is flagged when it
follows the donating call in source order with no intervening rebinding
(inside a loop, any read in the loop body counts unless the binding is
reassigned somewhere in the loop — the donated object is dead on the
next iteration too).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..callgraph import (CallGraph, Node, _own_calls, _own_statements,
                         get_callgraph)
from ..core import Package, Violation, dotted_name, keyword_arg
from .base import Rule

_LOOPS = (ast.For, ast.AsyncFor, ast.While)
# fresh-buffer constructors: donating their result donates a temp
_FRESH_CALLS = {"copy", "asarray", "array", "zeros", "ones", "full",
                "empty", "zeros_like", "ones_like"}


def _binding_key(expr: ast.AST) -> Optional[str]:
    """'name' for bare names, 'self.attr[.attr...]' for attribute chains
    rooted at a name. Anything else (subscripts, calls) is not a binding
    this pass tracks."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = _binding_key(expr.value)
        return base + "." + expr.attr if base else None
    return None


def _pallas_donated(call: ast.Call) -> Tuple[int, ...]:
    """Donated positions of a `pallas_call(...)(args)` dispatch via a
    LITERAL input_output_aliases dict. Non-literal forms return ()."""
    inner = call.func
    if not isinstance(inner, ast.Call):
        return ()
    if dotted_name(inner.func).rsplit(".", 1)[-1] != "pallas_call":
        return ()
    aliases = keyword_arg(inner, "input_output_aliases")
    if not isinstance(aliases, ast.Dict):
        return ()
    out: List[int] = []
    for k in aliases.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, int):
            out.append(k.value)
        else:
            return ()
    return tuple(sorted(out))


class DonationFlowRule(Rule):
    name = "use-after-donation"
    code = "R10"
    description = ("binding read after its buffer was donated to a jit/"
                   "pallas dispatch (donate_argnums / input_output_aliases)")
    scope_prefixes = ("treelearner/", "models/", "parallel/", "ops/",
                      "streaming/")
    whole_program = True

    def check(self, pkg: Package) -> Iterable[Violation]:
        graph = get_callgraph(pkg)
        summaries = self._param_summaries(graph)
        out: List[Violation] = []
        for node in graph.nodes.values():
            if node.node is None:
                continue
            if not any(node.ctx is c for c in self.scoped(pkg)):
                continue
            out.extend(self._check_function(graph, node, summaries))
        return out

    # -------------------------------------------------- donation sites

    def _donated_positions(self, graph: CallGraph, node: Node,
                           call: ast.Call,
                           summaries: Dict[str, Set[int]]) -> Tuple[int, ...]:
        """Positional indices of `call`'s own args whose buffers the call
        donates (wrapper offsets already applied)."""
        positions: Set[int] = set()
        pallas = _pallas_donated(call)
        positions.update(pallas)
        for ref in graph.resolve_call(node, call):
            if ref.target is None:
                continue
            donate = set(ref.donate)
            for tq in ref.target.split("|"):
                donate |= summaries.get(tq, set())
            for pos in donate:
                arg_idx = pos - ref.offset
                if 0 <= arg_idx < len(call.args):
                    positions.add(arg_idx)
        return tuple(sorted(positions))

    def _param_summaries(self, graph: CallGraph) -> Dict[str, Set[int]]:
        """qual -> parameter positions the function (transitively) passes
        into a donated slot. Fixpoint; cycles converge because the sets
        only grow."""
        summaries: Dict[str, Set[int]] = {}
        params: Dict[str, List[str]] = {}
        for q, node in graph.nodes.items():
            if node.node is None:
                continue
            a = node.node.args
            names = [p.arg for p in list(a.posonlyargs) + list(a.args)]
            if node.cls is not None and names and names[0] in ("self", "cls"):
                names = names[1:]  # callers don't pass the receiver
            params[q] = names
        for _ in range(20):
            changed = False
            for q, node in graph.nodes.items():
                if node.node is None:
                    continue
                my_params = params.get(q, [])
                if not my_params:
                    continue
                for call in _own_calls(node.node):
                    donated = self._donated_positions(graph, node, call,
                                                      summaries)
                    for idx in donated:
                        arg = call.args[idx]
                        if isinstance(arg, ast.Name) \
                                and arg.id in my_params:
                            p = my_params.index(arg.id)
                            if p not in summaries.setdefault(q, set()):
                                summaries[q].add(p)
                                changed = True
            if not changed:
                break
        return summaries

    # ---------------------------------------------------------- checking

    def _check_function(self, graph: CallGraph, node: Node,
                        summaries: Dict[str, Set[int]]) -> List[Violation]:
        out: List[Violation] = []
        body = node.node
        loops = [s for s in _own_statements(body) if isinstance(s, _LOOPS)]

        def enclosing_loops(stmt: ast.AST) -> List[ast.AST]:
            return [lp for lp in loops
                    if any(sub is stmt for sub in ast.walk(lp))]

        for call in _own_calls(body):
            donated = self._donated_positions(graph, node, call, summaries)
            if not donated:
                continue
            call_loops = enclosing_loops(call)
            for idx in donated:
                arg = call.args[idx]
                if isinstance(arg, ast.Call):
                    last = dotted_name(arg.func).rsplit(".", 1)[-1]
                    if last in _FRESH_CALLS:
                        continue  # jnp.copy(...) temp: the compliant idiom
                key = _binding_key(arg)
                if key is None:
                    continue  # subscript / expression: fresh buffer
                out.extend(self._reads_after(node, body, call, call_loops,
                                             key, idx))
        return out

    def _reads_after(self, node: Node, body: ast.AST, call: ast.Call,
                     call_loops: Sequence[ast.AST], key: str,
                     idx: int) -> List[Violation]:
        rebind_lines = self._rebind_lines(body, key)
        call_end = getattr(call, "end_lineno", call.lineno)
        out: List[Violation] = []
        for expr in _own_statements(body):
            if not isinstance(expr, (ast.Name, ast.Attribute)):
                continue
            if not isinstance(getattr(expr, "ctx", None), ast.Load):
                continue
            if _binding_key(expr) != key:
                continue
            line = expr.lineno
            in_call = call.lineno <= line <= call_end
            after = line > call_end
            same_loop = any(any(sub is expr for sub in ast.walk(lp))
                            for lp in call_loops)
            if in_call:
                continue
            if not after and not same_loop:
                continue
            if same_loop and not after:
                # earlier in the loop body: dead on the NEXT iteration
                # unless something rebinds the name within the loop
                lp_lines = [r for r in rebind_lines
                            if any(self._line_in(lp, r)
                                   for lp in call_loops)]
                if lp_lines:
                    continue
            elif any(call.lineno <= r <= line for r in rebind_lines):
                # rebound between donation and read — including by the
                # assignment consuming the call itself (`buf = f(buf)`,
                # the donate-and-replace idiom): the old binding is dead
                # once that statement completes
                continue
            out.append(self.violation(
                node.ctx, expr,
                "%r is read here but its buffer was donated at line %d "
                "(arg %d of the dispatch) — on TPU the memory may already "
                "hold the output; copy before donating or rebind first"
                % (key, call.lineno, idx)))
        return out

    @staticmethod
    def _line_in(stmt: ast.AST, line: int) -> bool:
        return stmt.lineno <= line <= getattr(stmt, "end_lineno",
                                              stmt.lineno)

    @staticmethod
    def _rebind_lines(body: ast.AST, key: str) -> List[int]:
        lines: List[int] = []
        for stmt in _own_statements(body):
            targets: List[ast.AST] = []
            if isinstance(stmt, ast.Assign):
                targets = list(stmt.targets)
            elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
                targets = [stmt.target]
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                targets = [stmt.target]
            for tgt in targets:
                for sub in ast.walk(tgt):
                    if _binding_key(sub) == key:
                        lines.append(stmt.lineno)
        return lines
