"""R2 implicit-dtype: array constructors must name their dtype.

`jnp.asarray(x)` takes its dtype from x's host dtype — which is float64 /
int64 for plain Python floats and numpy defaults. Under JAX's default
x64-disabled mode that silently narrows; with x64 enabled (or when a
future config flips it) the SAME call site doubles its memory traffic and
breaks kernels whose Mosaic tiling is dtype-dependent (int8 tiles are
(32, 128), f32 tiles (8, 128)). The hot path never leaves dtype to
ambient state: every constructor names it, either as the documented
positional slot or as dtype=.

`*_like` constructors inherit deliberately and are exempt.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Package, Violation, dotted_name, keyword_arg
from .base import Rule

# constructor -> index of the positional dtype slot in its signature
_CONSTRUCTORS = {
    "asarray": 1,   # jnp.asarray(a, dtype)
    "array": 1,     # jnp.array(object, dtype)
    "zeros": 1,     # jnp.zeros(shape, dtype)
    "ones": 1,      # jnp.ones(shape, dtype)
    "empty": 1,     # jnp.empty(shape, dtype)
    "full": 2,      # jnp.full(shape, fill_value, dtype)
    "arange": 3,    # jnp.arange(start, stop, step, dtype)
    "eye": 3,       # jnp.eye(N, M, k, dtype)
    "identity": 1,  # jnp.identity(n, dtype)
    "linspace": 5,  # dtype is effectively kwarg-only
}


class DtypeDisciplineRule(Rule):
    name = "implicit-dtype"
    code = "R2"
    description = ("jnp array constructor without an explicit dtype "
                   "(positional slot or dtype=)")
    scope_prefixes = ("ops/", "treelearner/")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if not fname.startswith("jnp."):
                    continue
                ctor = fname[len("jnp."):]
                slot = _CONSTRUCTORS.get(ctor)
                if slot is None:
                    continue
                if keyword_arg(node, "dtype") is not None:
                    continue
                if len(node.args) > slot and not any(
                        isinstance(a, ast.Starred) for a in node.args):
                    continue  # dtype passed positionally
                out.append(self.violation(
                    ctx, node,
                    "jnp.%s without an explicit dtype — result dtype "
                    "depends on ambient x64 state" % ctor))
        return out
