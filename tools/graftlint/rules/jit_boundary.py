"""R1 jit-host-sync: no host syncs or numpy escapes in jit-reachable code.

A traced value hitting `int()`/`float()`/`bool()`/`.item()`/`np.asarray()`
inside a jitted function either raises a TracerError at trace time (best
case) or — when it sneaks in through a shape-dependent branch that only
some configs reach — forces a device→host transfer that serializes the
dispatch pipeline. On a remote-attached TPU one stray `.item()` in the
tree-growing wave loop costs more than the histogram kernel it gates.

Reachability here is intra-module: functions decorated with `jax.jit`
(bare or via `partial(jax.jit, ...)`) seed the set, which closes over
same-module calls by name (including `self.method` calls) and nested
defs. Cross-module reachability is R1v2's job (jit_boundary_xmod.py):
the same sink catalogue walked over the package call graph, reporting
only what this rule cannot see. Both share the R1 code, so disable=R1
covers the family (docs/LINTING.md#r1 for the escape hatch).

The rule also covers the driver side of the boundary: a host loop that
pulls each dispatched result straight back (`np.asarray(jitted_fn(x))`
per iteration — the shape of the pre-rewrite predict_raw_early_stop)
serializes the dispatch pipeline just as surely. Loop bodies in
NON-jit-reachable functions are scanned for host-sync calls whose
argument dispatches a same-module jit-reachable function; pulls of a
previously-dispatched value (a bare name, e.g. double-buffered
copy_to_host_async drains) stay clean.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from ..core import Package, Violation, dotted_name
from .base import Rule, module_functions

# Call(Name) builtins that force concretization of a traced argument.
_HOST_BUILTINS = {"int", "float", "bool", "complex"}
# method calls that block on / transfer from device
_HOST_METHODS = {"item", "tolist", "block_until_ready"}
# numpy entry points that pull a traced array to host (np.asarray(tracer)
# calls __array__, a silent transfer+sync)
_NP_CALLS = {"asarray", "array", "copy", "save", "frombuffer"}
_JAX_HOST = {"jax.device_get", "jax.device_put"}


def _is_jitted(fn: ast.AST) -> bool:
    """Decorator contains a reference to `jit` — covers @jax.jit, @jit,
    @partial(jax.jit, ...), @functools.partial(jax.jit, static_argnames=...)."""
    for dec in getattr(fn, "decorator_list", []):
        for node in ast.walk(dec):
            if isinstance(node, ast.Attribute) and node.attr == "jit":
                return True
            if isinstance(node, ast.Name) and node.id == "jit":
                return True
    return False


def _static_under_jit(node: ast.AST) -> bool:
    """Conservatively true when `int(x)`-style concretization is safe at
    trace time: literals, len(), shape/ndim accesses, arithmetic thereof.
    Anything unrecognized counts as traced (rule fires; suppress if the
    value is genuinely host-side)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _static_under_jit(node.operand)
    if isinstance(node, ast.BinOp):
        return _static_under_jit(node.left) and _static_under_jit(node.right)
    if isinstance(node, ast.Call):
        fname = dotted_name(node.func)
        if fname in ("len", "min", "max") and all(
                _static_under_jit(a) for a in node.args):
            return True
        return False
    if isinstance(node, ast.Attribute) and node.attr in ("ndim", "size"):
        return True  # static under jit: shapes are trace-time constants
    if isinstance(node, ast.Subscript):
        # x.shape[0] — static under jit
        return (isinstance(node.value, ast.Attribute)
                and node.value.attr == "shape")
    return False


class JitBoundaryRule(Rule):
    name = "jit-host-sync"
    code = "R1"
    description = ("host sync / numpy escape (int(), .item(), np.asarray, "
                   "...) inside a jax.jit-reachable function")
    scope_prefixes = ("ops/", "treelearner/", "streaming/")
    # elastic.py sits on the per-iteration beat path: a host pull added
    # there (heartbeat token, watchdog state) costs every training wave
    scope_exact = ("models/gbdt.py", "parallel/elastic.py")

    def check(self, pkg: Package) -> Iterable[Violation]:
        out: List[Violation] = []
        for ctx in self.scoped(pkg):
            funcs = dict(module_functions(ctx.tree))
            # short name -> qualified keys (self.foo calls resolve by attr)
            short: Dict[str, List[str]] = {}
            for qual in funcs:
                short.setdefault(qual.rsplit(".", 1)[-1], []).append(qual)

            def callees(fn: ast.AST) -> Set[str]:
                found: Set[str] = set()
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    f = node.func
                    if isinstance(f, ast.Name) and f.id in short:
                        found.update(short[f.id])
                    elif isinstance(f, ast.Attribute) and f.attr in short:
                        found.update(short[f.attr])
                return found

            reachable: Set[str] = {q for q, fn in funcs.items()
                                   if _is_jitted(fn)}
            frontier = set(reachable)
            while frontier:
                nxt: Set[str] = set()
                for qual in frontier:
                    nxt |= callees(funcs[qual]) - reachable
                reachable |= nxt
                frontier = nxt
            for qual in sorted(reachable):
                out.extend(self._check_function(ctx, qual, funcs[qual]))
            # driver-side: functions that (transitively) CALL jit-reachable
            # code are dispatch points; a host sync on a fresh dispatch
            # inside a loop serializes the pipeline per iteration
            dispatch = set(reachable)
            grew = True
            while grew:
                grew = False
                for qual, fn in funcs.items():
                    if qual in dispatch:
                        continue
                    if callees(fn) & dispatch:
                        dispatch.add(qual)
                        grew = True
            dispatch_short = {q.rsplit(".", 1)[-1] for q in dispatch}
            for qual, fn in funcs.items():
                if qual in reachable:
                    continue  # already fully checked above
                out.extend(self._check_loop_syncs(ctx, qual, fn,
                                                  dispatch_short))
        return out

    def _check_loop_syncs(self, ctx, qual: str, fn: ast.AST,
                          dispatch_short: Set[str]) -> List[Violation]:
        def dispatches(node: ast.AST) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    f = sub.func
                    if isinstance(f, ast.Name) and f.id in dispatch_short:
                        return True
                    if isinstance(f, ast.Attribute) and f.attr in dispatch_short:
                        return True
            return False

        seen: Set[tuple] = set()
        out: List[Violation] = []
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While, ast.AsyncFor)):
                continue
            for node in ast.walk(loop):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                fname = dotted_name(f)
                is_sync = (
                    (isinstance(f, ast.Name) and f.id in _HOST_BUILTINS)
                    or (isinstance(f, ast.Attribute)
                        and f.attr in _HOST_METHODS)
                    or (fname.startswith("np.") and fname[3:] in _NP_CALLS)
                    or fname in _JAX_HOST)
                if not is_sync:
                    continue
                roots = list(node.args)
                if isinstance(f, ast.Attribute):
                    roots.append(f.value)  # jitted_fn(x).item()
                if not any(dispatches(r) for r in roots):
                    continue
                key = (node.lineno, node.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                out.append(self.violation(
                    ctx, node,
                    "per-iteration host sync on a fresh dispatch inside a "
                    "loop in %r serializes the dispatch pipeline (the old "
                    "predict_raw_early_stop pattern) — hoist the pull out "
                    "of the loop or double-buffer with copy_to_host_async"
                    % qual))
        return out

    def _check_function(self, ctx, qual: str, fn: ast.AST) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            fname = dotted_name(f)
            if isinstance(f, ast.Name) and f.id in _HOST_BUILTINS:
                if node.args and not all(_static_under_jit(a)
                                         for a in node.args):
                    out.append(self.violation(
                        ctx, node,
                        "%s() concretizes a traced value inside "
                        "jit-reachable %r" % (f.id, qual)))
            elif isinstance(f, ast.Attribute) and f.attr in _HOST_METHODS:
                out.append(self.violation(
                    ctx, node, ".%s() is a device->host sync inside "
                    "jit-reachable %r" % (f.attr, qual)))
            elif fname.startswith("np.") and fname[3:] in _NP_CALLS:
                out.append(self.violation(
                    ctx, node, "%s() pulls traced data to host inside "
                    "jit-reachable %r" % (fname, qual)))
            elif fname in _JAX_HOST:
                out.append(self.violation(
                    ctx, node, "%s() inside jit-reachable %r"
                    % (fname, qual)))
        return out
